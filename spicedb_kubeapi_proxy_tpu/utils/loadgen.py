"""Open-loop workload generator (ISSUE 20).

Every load number the repo produced before this module was closed-loop:
the next request waited for the previous one to finish, so whenever the
system stalled the generator politely stopped offering load and the
stall's victims were never measured — the coordinated-omission bug that
flatters p99 exactly when p99 matters.  This generator is **open-loop**:
`WorkloadSpec.schedule()` lays out a fixed request schedule up front
(Poisson arrivals at the offered rate), and `OpenLoopRunner` fires each
request at its intended time whether or not earlier ones came back,
recording every latency against the INTENDED send time.  A stall now
shows up twice, as it should: queued requests measure the stall they
sat through, and the generator's own inability to keep to the schedule
is exported as `authz_loadgen_lag_seconds` so an overdriven generator
cannot silently flatter the tail either.

The mix models the reference proxy's three rule types over a
million-user id space with zipfian per-user fan-in (a few hot service
accounts dominate, the long tail is cold):

- ``filter`` — filtered LIST (prefilter/LookupResources rule path);
- ``check``  — single-object read (Check rule path);
- ``update`` — dual-write create (Update rule path, write fan-out);
- ``watch``  — watch-churn touches feeding open watch streams;
- ``grant``/``revoke`` — PAuth-style short-TTL ephemeral grants
  (arXiv:2603.17170): each grant event schedules its own revoke at
  t+TTL, so the fleet serves permission churn, not a frozen ACL set.

The schedule is a pure function of the spec (`random.Random(seed)`, no
wall clock): same seed → byte-identical `schedule_lines()`, which is
what tests/test_topology.py pins.
"""

from __future__ import annotations

import asyncio
import bisect
import json
import time
from array import array
from dataclasses import dataclass
from typing import Callable

from .metrics import REGISTRY

# scheduler lag: how far behind the intended schedule the generator
# fired its most recent request.  A sustained non-zero value means the
# offered rate exceeds what this generator process can issue — the
# measured latencies are then a lower bound, not a measurement.
LAG_GAUGE = REGISTRY.gauge(
    "authz_loadgen_lag_seconds",
    "Open-loop load generator scheduler lag (actual fire time minus "
    "intended send time) of the most recently fired request")


@dataclass(frozen=True)
class WorkloadSpec:
    """Deterministic open-loop workload description.

    `verb_mix` are relative weights (normalized internally) over the
    filter/check/update rule paths; watch churn and grant bursts ride
    on top at their own rates so the read:write mix stays interpretable.
    """
    seed: int = 20
    duration_s: float = 10.0
    rate_per_s: float = 50.0
    users: int = 1_000_000
    zipf_s: float = 1.2
    verb_mix: tuple = (("filter", 0.6), ("check", 0.25), ("update", 0.15))
    watch_churn_per_s: float = 0.0
    grant_burst_per_s: float = 0.0   # burst arrivals per second
    grant_burst_n: int = 4           # grants per burst
    grant_ttl_s: float = 2.0         # each grant's revoke lands t+TTL
    namespaces: int = 4

    def schedule(self) -> list:
        """The full fixed schedule: a list of event dicts sorted by
        intended send offset `t` (seconds from window start).  Pure
        function of the spec — no wall clock, no global state."""
        import random

        rng = random.Random(self.seed)
        zipf = _ZipfSampler(self.users, self.zipf_s)
        verbs = [v for v, _ in self.verb_mix]
        weights = [w for _, w in self.verb_mix]
        events = []
        seq = 0

        def emit(t, verb, **kw):
            nonlocal seq
            ev = {"t": round(t, 6), "verb": verb,
                  "user": f"u{zipf.sample(rng)}",
                  "ns": f"ns{rng.randrange(self.namespaces)}",
                  "seq": seq}
            ev.update(kw)
            events.append(ev)
            seq += 1

        # main verb stream: Poisson arrivals at the offered rate
        t = 0.0
        while True:
            t += rng.expovariate(self.rate_per_s)
            if t >= self.duration_s:
                break
            verb = rng.choices(verbs, weights)[0]
            if verb == "update":
                emit(t, "update", name=f"obj-{seq}")
            else:
                emit(t, verb)
        # watch churn: touches that feed open watch streams
        if self.watch_churn_per_s > 0:
            t = 0.0
            while True:
                t += rng.expovariate(self.watch_churn_per_s)
                if t >= self.duration_s:
                    break
                emit(t, "watch", name=f"watch-{seq}")
        # short-TTL grant bursts: every grant schedules its own revoke
        if self.grant_burst_per_s > 0:
            t = 0.0
            while True:
                t += rng.expovariate(self.grant_burst_per_s)
                if t >= self.duration_s:
                    break
                for _ in range(self.grant_burst_n):
                    name = f"grant-{seq}"
                    emit(t, "grant", name=name, ttl_s=self.grant_ttl_s)
                    emit(t + self.grant_ttl_s, "revoke", name=name)
        events.sort(key=lambda e: (e["t"], e["seq"]))
        return events

    def schedule_lines(self) -> bytes:
        """Canonical byte encoding of the schedule (sorted keys, no
        whitespace): the determinism contract `same seed → byte-
        identical` is asserted against exactly these bytes."""
        return b"\n".join(
            json.dumps(e, sort_keys=True,
                       separators=(",", ":")).encode()
            for e in self.schedule())


class _ZipfSampler:
    """Bounded zipf(s) over ranks 1..n via inverse-CDF + bisect.

    The CDF is built once per (n, s) — O(n) floats in a C array — so a
    million-user id space costs ~8 MB and sub-second setup, and every
    sample after that is one rng draw + one binary search.  Rank r has
    probability proportional to r^-s, so rank 1 is sampled ~2^s times
    more often than rank 2 — the shape tests pin."""

    _cache: dict = {}

    def __init__(self, n: int, s: float):
        key = (n, round(s, 6))
        cdf = self._cache.get(key)
        if cdf is None:
            cdf = array("d")
            total = 0.0
            for r in range(1, n + 1):
                total += r ** -s
                cdf.append(total)
            self._cache[key] = cdf
        self.cdf = cdf
        self.total = cdf[-1]

    def sample(self, rng) -> int:
        """Rank in 1..n (1 = hottest user)."""
        return bisect.bisect_left(self.cdf, rng.random() * self.total) + 1


def percentile(values, q: float) -> float:
    """Nearest-rank percentile over a sequence (0 on empty)."""
    if not values:
        return 0.0
    vs = sorted(values)
    idx = min(len(vs) - 1, max(0, int(round(q * (len(vs) - 1)))))
    return vs[idx]


class OpenLoopRunner:
    """Drive a fixed schedule through an async `issue(event)` callable,
    coordinated-omission-free.

    Each event fires at `window_start + event.t` regardless of whether
    earlier requests completed (their tasks run concurrently and are
    all awaited before `run()` returns), and its latency is recorded as
    `completion − intended_send` — a request that sat in a stall's
    queue is charged the full queue wait.  Scheduler lag (actual fire −
    intended fire) is tracked per event and exported through
    `authz_loadgen_lag_seconds`."""

    def __init__(self, issue: Callable, *,
                 max_inflight: int = 256,
                 clock: Callable[[], float] = time.monotonic):
        self.issue = issue
        self.clock = clock
        self.max_inflight = max_inflight
        self.samples: dict = {}     # verb -> [latency_s]
        self.errors: dict = {}      # verb -> count
        self.max_lag_s = 0.0
        self.offered = 0
        self.achieved = 0
        self.window_s = 0.0

    async def _one(self, ev: dict, intended: float) -> None:
        verb = ev["verb"]
        try:
            await self.issue(ev)
        except Exception:
            self.errors[verb] = self.errors.get(verb, 0) + 1
            return
        self.achieved += 1
        self.samples.setdefault(verb, []).append(
            self.clock() - intended)

    async def run(self, schedule: list) -> dict:
        t0 = self.clock()
        tasks = []
        for ev in schedule:
            intended = t0 + ev["t"]
            delay = intended - self.clock()
            if delay > 0:
                await asyncio.sleep(delay)
            lag = max(0.0, self.clock() - intended)
            if lag > self.max_lag_s:
                self.max_lag_s = lag
            LAG_GAUGE.set(lag)
            self.offered += 1
            # open loop: do NOT await the request here — but keep the
            # in-flight population bounded so an unresponsive system
            # degrades into measured queueing, not task exhaustion
            while len(tasks) >= self.max_inflight:
                done, pending = await asyncio.wait(
                    tasks, return_when=asyncio.FIRST_COMPLETED)
                tasks = list(pending)
            tasks.append(asyncio.create_task(self._one(ev, intended)))
        if tasks:
            await asyncio.gather(*tasks)
        self.window_s = self.clock() - t0
        return self.report()

    def report(self) -> dict:
        per_verb = {}
        for verb, lats in sorted(self.samples.items()):
            per_verb[verb] = {
                "count": len(lats),
                "p50_ms": round(percentile(lats, 0.50) * 1e3, 3),
                "p99_ms": round(percentile(lats, 0.99) * 1e3, 3),
                "errors": self.errors.get(verb, 0),
            }
        all_lats = [x for ls in self.samples.values() for x in ls]
        return {
            "open_loop": True,
            # makespan: schedule start -> last completion.  Under
            # saturation the schedule drains late, so achieved /
            # window_s is the honest capacity measure (never clipped
            # by the generator politely slowing down)
            "window_s": round(self.window_s, 3),
            "offered": self.offered,
            "achieved": self.achieved,
            "errors": sum(self.errors.values()),
            "offered_rate_per_s": round(
                self.offered / self.window_s, 2) if self.window_s else 0.0,
            "p50_ms": round(percentile(all_lats, 0.50) * 1e3, 3),
            "p99_ms": round(percentile(all_lats, 0.99) * 1e3, 3),
            "max_sched_lag_ms": round(self.max_lag_s * 1e3, 3),
            "per_verb": per_verb,
        }
