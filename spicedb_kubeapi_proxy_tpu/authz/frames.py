"""Watch frame capture (reference pkg/authz/frames.go).

Kube JSON watch streams are newline-delimited; each complete line is one
frame whose raw bytes must be preserved for byte-exact replay.  This
generator re-chunks an arbitrary byte stream into raw frame lines,
buffering partial lines across chunks (the mutex-guarded capture window in
the reference becomes plain sequential buffering here).
"""

from __future__ import annotations

from typing import AsyncIterator


async def frame_lines(stream: AsyncIterator[bytes]) -> AsyncIterator[bytes]:
    buf = bytearray()
    async for chunk in stream:
        buf.extend(chunk)
        while True:
            idx = buf.find(b"\n")
            if idx < 0:
                break
            frame = bytes(buf[: idx + 1])
            del buf[: idx + 1]
            yield frame
    if buf:
        yield bytes(buf)
