"""Fleet aggregation plane: cross-process trace assembly + roll-ups.

The proxy fleet is a stateless router in front of N shard leaders with
fan-out follower trees; every per-process observability surface
(/debug/traces, /debug/flight, /metrics) stops at its own process
boundary.  This module is the merge half of the fleet tracing tentpole
(docs/observability.md "Fleet tracing"):

- `collect_fleet()` fans a /debug/fleet request out to each member's
  /debug/traces + /debug/flight + /metrics and normalizes the answers
  into member dicts (errors are per-member, never fatal — a dead
  follower still leaves the rest of the fleet explorable).
- `merge_fleet()` is PURE (no HTTP, unit-testable): it assembles the
  per-process traces into cross-process traces keyed by trace id,
  aligns each child trace inside its parent's hop span (by the
  PARENT's clock — never the remote wall clock, so cross-process clock
  skew cannot reorder the merged timeline), renders one
  Perfetto-loadable chrome-trace with one track per (tier, process),
  attributes per-tier self time + per-hop network time so the tier sums
  reconcile against the root (client-observed) latency by construction,
  and rolls up per-tier p50/p99 and the members' SLO burn lists.

Alignment model: every outbound internal hop records a client-side span
carrying a `span_id` attr (tracing.hop_span); the downstream trace
carries that id as its `parent_span` attr.  A child's offset on the
merged timeline is therefore `offset(parent) + hop_span.start_ms` —
two processes' wall clocks are never subtracted from each other.  The
residual `hop_ms - child_duration_ms` is the hop's network share,
attributed to the pseudo-tier `network`.
"""

from __future__ import annotations

import asyncio
import re
from typing import Iterable, Optional

# /metrics lines worth lifting into the merged view (full scrape text is
# deliberately NOT echoed back — the merge is a roll-up, not a mirror)
_SKEW_RE = re.compile(
    r"^authz_clock_skew_seconds(?:\{[^}]*\})?\s+(-?[0-9.eE+-]+)\s*$",
    re.MULTILINE)
_LAG_RE = re.compile(
    r"^authz_replica_lag_seconds(?:\{[^}]*\})?\s+(-?[0-9.eE+-]+)\s*$",
    re.MULTILINE)

# paths the fan-out scrapes per member
MEMBER_PATHS = ("/debug/traces", "/debug/flight", "/debug/workload",
                "/metrics")


def parse_metric(text: str, pattern: re.Pattern) -> Optional[float]:
    m = pattern.search(text or "")
    if m is None:
        return None
    try:
        return float(m.group(1))
    except ValueError:
        return None


async def fetch_member(url: str, headers: Iterable = (),
                       transport=None, timeout_s: float = 5.0) -> dict:
    """Scrape one fleet member's observability surfaces into a member
    dict; any failure lands in `error` (one member down must not take
    the merged view down)."""
    from ..proxy.httpcore import H11Transport, Headers, Request
    from . import tracing
    member = {"url": url, "error": None, "traces": [], "flight": {},
              "workload": {}, "skew_s": None, "lag_s": None}
    t = transport if transport is not None else H11Transport(url)
    for path in MEMBER_PATHS:
        h = Headers(list(headers))
        h.set("Accept", "application/json")
        # the fan-out is itself a fleet-internal hop: it carries the
        # propagation headers (tier path provenance; empty gate-off)
        for hk, hv in tracing.propagation_headers().items():
            h.set(hk, hv)
        try:
            resp = await asyncio.wait_for(
                t.round_trip(Request(method="GET", target=path,
                                     headers=h)),
                timeout_s)
        except asyncio.CancelledError:
            raise
        except Exception as e:
            member["error"] = f"GET {path}: {e}"
            break
        if resp.status != 200:
            member["error"] = f"GET {path}: HTTP {resp.status}"
            break
        body = resp.body or b""
        if path == "/metrics":
            text = body.decode("utf-8", "replace")
            member["skew_s"] = parse_metric(text, _SKEW_RE)
            member["lag_s"] = parse_metric(text, _LAG_RE)
            continue
        import json
        try:
            payload = json.loads(body or b"{}")
        except ValueError as e:
            member["error"] = f"GET {path}: bad JSON: {e}"
            break
        if path == "/debug/traces":
            member["traces"] = list(payload.get("traces") or [])
        elif path == "/debug/workload":
            member["workload"] = payload
        else:
            member["flight"] = payload
    return member


async def collect_fleet(urls: Iterable[str], headers: Iterable = (),
                        transports: Optional[dict] = None,
                        timeout_s: float = 5.0) -> list:
    """Fan out to every member concurrently; order follows `urls`.
    `transports` (url -> Transport) is the test seam, mirroring
    Options.peer_transports."""
    transports = transports or {}
    return list(await asyncio.gather(*(
        fetch_member(u, headers=headers, transport=transports.get(u),
                     timeout_s=timeout_s)
        for u in urls)))


# -- pure merge ---------------------------------------------------------------


def _percentile(values: list, q: float) -> float:
    if not values:
        return 0.0
    vs = sorted(values)
    idx = min(len(vs) - 1, max(0, int(round(q * (len(vs) - 1)))))
    return vs[idx]


def _segments_by_trace(members: list) -> dict:
    """trace_id -> list of (member, trace_dict) segments.

    Deduped by segment fingerprint: when a node aggregates itself AND
    appears in its own peer list (or several in-process members share
    one trace recorder, as the tests do), the same segment arrives
    twice; keying on (start, duration, tier, span count) keeps one copy
    without ever collapsing two genuinely distinct segments."""
    out: dict = {}
    seen: set = set()
    for member in members:
        for trd in member.get("traces") or []:
            tid = trd.get("trace_id")
            if not tid:
                continue
            attrs = trd.get("attrs") or {}
            fp = (tid,
                  round(float(trd.get("start_unix") or 0.0), 4),
                  round(float(trd.get("duration_ms") or 0.0), 4),
                  str(attrs.get("tier") or ""),
                  len(trd.get("spans") or []))
            if fp in seen:
                continue
            seen.add(fp)
            out.setdefault(tid, []).append((member, trd))
    return out


def _hop_spans(trace: dict) -> list:
    """The client-side hop spans (tracing.hop_span) of one segment."""
    return [s for s in trace.get("spans") or []
            if (s.get("attrs") or {}).get("span_id")]


def assemble_trace(segments: list) -> dict:
    """Merge one trace id's per-process segments into a single aligned
    timeline.  `segments` is [(member, trace_dict), ...]."""
    # root: the segment that did not join anyone else's trace.  Fall
    # back to earliest wall start (skew-prone, flagged) when the root
    # segment was evicted from its recorder.
    root_ix = None
    for i, (_m, trd) in enumerate(segments):
        if not (trd.get("attrs") or {}).get("parent_span"):
            root_ix = i
            break
    aligned_by_wall = root_ix is None
    if root_ix is None:
        root_ix = min(range(len(segments)),
                      key=lambda i: segments[i][1].get("start_unix", 0.0))
    # span_id -> (segment index, hop span) across all segments
    hop_index: dict = {}
    for i, (_m, trd) in enumerate(segments):
        for sp in _hop_spans(trd):
            hop_index[(sp.get("attrs") or {}).get("span_id")] = (i, sp)
    # child offset = parent offset + hop start (parent's clock).  The
    # parent chain is at most the tier depth; iterate to fixpoint.
    offsets = {root_ix: 0.0}
    wall_fallbacks = 0
    root_trd = segments[root_ix][1]
    for _round in range(len(segments) + 1):
        progressed = False
        for i, (_m, trd) in enumerate(segments):
            if i in offsets:
                continue
            parent = (trd.get("attrs") or {}).get("parent_span")
            hit = hop_index.get(parent)
            if hit is not None and hit[0] in offsets:
                offsets[i] = offsets[hit[0]] + hit[1].get("start_ms", 0.0)
                progressed = True
        if not progressed:
            break
    for i, (_m, trd) in enumerate(segments):
        if i not in offsets:
            # orphan (its parent's segment is missing): wall-clock
            # fallback, counted so readers know the alignment is soft
            offsets[i] = max(0.0, (trd.get("start_unix", 0.0)
                                   - root_trd.get("start_unix", 0.0)) * 1e3)
            wall_fallbacks += 1
    # per-tier attribution: self time = segment duration minus the hop
    # spans that have a matching child segment; the residual
    # hop - child duration is that hop's network share.  Tier sums then
    # reconcile against the root duration by construction — PROVIDED
    # each child segment fits inside its parent's hop span.  Under CPU
    # starvation a child finalizes its segment after flushing the
    # response, so its recorded duration can overrun the hop that
    # carried it; that overrun is finalization delay, not serving work
    # (the parent already had the response), and left unclamped it
    # double-counts and compounds down a deep tier chain.  Cap each
    # non-root segment at its parent hop span; genuinely parallel
    # fan-out (several hops concurrent inside one segment) still sums
    # past the root duration, as it physically should.
    parent_hop_cap: dict = {}
    for i, (_m, trd) in enumerate(segments):
        hit = hop_index.get((trd.get("attrs") or {}).get("parent_span"))
        if hit is not None and hit[0] != i:
            parent_hop_cap[i] = float(hit[1].get("duration_ms") or 0.0)
    tiers: dict = {}
    stages: dict = {}
    network_ms = 0.0
    for i, (_m, trd) in enumerate(segments):
        attrs = trd.get("attrs") or {}
        tier = str(attrs.get("tier") or "unknown")
        dur = float(trd.get("duration_ms") or 0.0)
        if i in parent_hop_cap:
            dur = min(dur, parent_hop_cap[i])
        child_hops_ms = 0.0
        for sp in _hop_spans(trd):
            sid = (sp.get("attrs") or {}).get("span_id")
            child = next((j for j, (_m2, t2) in enumerate(segments)
                          if (t2.get("attrs") or {}).get("parent_span")
                          == sid), None)
            if child is None:
                continue
            hop_ms = float(sp.get("duration_ms") or 0.0)
            child_ms = float(
                segments[child][1].get("duration_ms") or 0.0)
            child_hops_ms += hop_ms
            network_ms += max(0.0, hop_ms - child_ms)
        ti = tiers.setdefault(tier, {"self_ms": 0.0, "segments": 0})
        ti["self_ms"] += max(0.0, dur - child_hops_ms)
        ti["segments"] += 1
        for sp in trd.get("spans") or []:
            name = sp.get("name") or ""
            if name.startswith("serving."):
                st = stages.setdefault(tier, {})
                st[name[len("serving."):]] = round(
                    st.get(name[len("serving."):], 0.0)
                    + float(sp.get("duration_ms") or 0.0), 3)
    root_ms = float(root_trd.get("duration_ms") or 0.0)
    attributed = sum(t["self_ms"] for t in tiers.values()) + network_ms
    return {
        "trace_id": root_trd.get("trace_id"),
        "start_unix": root_trd.get("start_unix"),
        "duration_ms": root_ms,
        "root_attrs": root_trd.get("attrs") or {},
        "tier_count": len(tiers),
        "tiers": {k: {"self_ms": round(v["self_ms"], 3),
                      "segments": v["segments"]}
                  for k, v in sorted(tiers.items())},
        "serving_stages_ms": stages,
        "network_ms": round(network_ms, 3),
        "attributed_ms": round(attributed, 3),
        "aligned_by_wall": aligned_by_wall,
        "wall_fallbacks": wall_fallbacks,
        "segments": [
            {"tier": (trd.get("attrs") or {}).get("tier", "unknown"),
             "url": m.get("url", ""),
             "offset_ms": round(offsets[i], 3),
             "duration_ms": trd.get("duration_ms"),
             "spans": trd.get("spans") or []}
            for i, (m, trd) in enumerate(segments)],
    }


def merged_chrome_trace(assembled: list) -> dict:
    """ONE Perfetto-loadable chrome-trace over every assembled trace:
    one track (pid/tid pair) per (tier, process), slices placed at the
    skew-immune merged offsets (µs since the earliest root's wall
    start)."""
    events = []
    tracks: dict = {}  # (tier, url) -> (pid, tid)
    if not assembled:
        return {"traceEvents": [], "displayTimeUnit": "ms",
                "otherData": {"reason": "no multi-process traces"}}
    anchor = min(a.get("start_unix") or 0.0 for a in assembled)

    def track(tier: str, url: str):
        key = (tier, url)
        if key not in tracks:
            pid = len(tracks) + 1
            tracks[key] = (pid, 1)
            events.append({
                "name": "process_name", "ph": "M", "pid": pid, "tid": 1,
                "args": {"name": f"{tier} @ {url or 'local'}"}})
        return tracks[key]

    for a in assembled:
        base_us = ((a.get("start_unix") or 0.0) - anchor) * 1e6
        for seg in a["segments"]:
            pid, tid = track(str(seg.get("tier") or "unknown"),
                             str(seg.get("url") or ""))
            seg_us = base_us + seg["offset_ms"] * 1e3
            events.append({
                "name": f"request {a['trace_id']}", "ph": "X",
                "pid": pid, "tid": tid, "ts": seg_us,
                "dur": float(seg.get("duration_ms") or 0.0) * 1e3,
                "cat": "request",
                "args": {"trace_id": a["trace_id"]}})
            for sp in seg["spans"]:
                events.append({
                    "name": sp.get("name", "?"), "ph": "X",
                    "pid": pid, "tid": tid,
                    "ts": seg_us + float(sp.get("start_ms") or 0.0) * 1e3,
                    "dur": float(sp.get("duration_ms") or 0.0) * 1e3,
                    "cat": "span",
                    "args": sp.get("attrs") or {}})
    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"anchor_unix": anchor,
                          "traces": len(assembled),
                          "tracks": len(tracks)}}


def merge_workload(members: list) -> dict:
    """Fleet-wide workload roll-up (pure): per-(type, permission) rows
    summed across members, Leopard candidates deduped keeping each
    pair's deepest observation (tagged with the member that saw it)."""
    rows: dict = {}
    candidates: dict = {}
    total = attributed = 0.0
    reporting = 0
    for m in members:
        wl = m.get("workload") or {}
        if not wl or wl.get("enabled") is False or "rows" not in wl:
            continue
        reporting += 1
        total += float(wl.get("total_device_s") or 0.0)
        attributed += float(wl.get("attributed_device_s") or 0.0)
        for r in wl.get("rows") or []:
            key = (str(r.get("resource_type")), str(r.get("permission")))
            agg = rows.setdefault(key, {
                "device_s": 0.0, "kernel_rows": 0, "oracle_rows": 0,
                "cache_hits": 0, "cache_misses": 0})
            agg["device_s"] += float(r.get("device_s") or 0.0)
            for f in ("kernel_rows", "oracle_rows", "cache_hits",
                      "cache_misses"):
                agg[f] += int(r.get(f) or 0)
        for c in wl.get("leopard_candidates") or []:
            key = (str(c.get("resource_type")), str(c.get("permission")))
            cur = candidates.get(key)
            if (cur is None or (c.get("mean_sweep_depth") or 0)
                    > (cur.get("mean_sweep_depth") or 0)):
                candidates[key] = dict(c, url=m.get("url", ""))
    out_rows = []
    for (t, p), agg in rows.items():
        row = {"resource_type": t, "permission": p}
        row.update(agg)
        row["device_s"] = round(agg["device_s"], 6)
        out_rows.append(row)
    out_rows.sort(key=lambda r: -r["device_s"])
    return {
        "members_reporting": reporting,
        "rows": out_rows,
        "total_device_s": round(total, 6),
        "attributed_device_s": round(attributed, 6),
        "leopard_candidates": sorted(
            candidates.values(),
            key=lambda c: -(c.get("mean_sweep_depth") or 0)),
    }


def merge_fleet(members: list) -> dict:
    """The /debug/fleet payload: assembled cross-process traces (multi-
    process trace ids only), ONE merged chrome-trace, per-tier p50/p99
    attribution, fleet workload roll-up, SLO burn roll-up, and
    per-member skew/lag/errors."""
    by_trace = _segments_by_trace(members)
    assembled = [assemble_trace(segs)
                 for _tid, segs in sorted(by_trace.items())
                 if len(segs) > 1]
    assembled.sort(key=lambda a: a.get("duration_ms") or 0.0,
                   reverse=True)
    tier_samples: dict = {}
    for a in assembled:
        for tier, ti in a["tiers"].items():
            tier_samples.setdefault(tier, []).append(ti["self_ms"])
        if a["network_ms"] > 0:
            tier_samples.setdefault("network", []).append(a["network_ms"])
    tier_stats = {
        tier: {"count": len(vals),
               "p50_ms": round(_percentile(vals, 0.50), 3),
               "p99_ms": round(_percentile(vals, 0.99), 3)}
        for tier, vals in sorted(tier_samples.items())}
    burning = []
    for m in members:
        for slo in (m.get("flight") or {}).get("burning") or []:
            burning.append({"url": m.get("url", ""), "slo": slo})
    return {
        "members": [{"url": m.get("url", ""),
                     "error": m.get("error"),
                     "traces": len(m.get("traces") or []),
                     "skew_s": m.get("skew_s"),
                     "lag_s": m.get("lag_s")}
                    for m in members],
        "traces": assembled,
        "chrome_trace": merged_chrome_trace(assembled),
        "tiers": tier_stats,
        "workload": merge_workload(members),
        "slo_burning": burning,
    }
