"""Delta-stream minimizer + self-contained repro artifacts.

A failing (case, gate-combo, role) cell shrinks in three passes, each
validated by a fresh full replay (`driver.run_case` in single-query
probe mode — the probe applies a candidate stream and evaluates ONLY
the diverging query at the end state):

1. **prefix truncation** — the stream is cut at the burst the
   divergence was first seen after (the divergence may heal later:
   later state is irrelevant);
2. **burst atomization** — multi-op write bursts split into one-op
   bursts so elimination works at single-delta granularity;
3. **backward elimination** — drop one burst at a time (then one
   bulk/init relationship at a time), keeping any removal that still
   reproduces, looping to a fixpoint under a probe budget.

The artifact a failing seed writes is a plain JSON file carrying the
schema text, the minimized init set + delta stream, the diverging
query, both answers, the revision, and the exact (gates, role, kernel)
cell — everything `replay_artifact` needs to reproduce the divergence
from nothing.
"""

from __future__ import annotations

import json
import os

from . import metrics as fuzz_metrics
from .driver import Divergence, FuzzCase, run_case

ARTIFACT_VERSION = 1

DEFAULT_PROBE_BUDGET = 120


def _probe(case: FuzzCase, d: Divergence) -> bool:
    """Does this candidate stream still reproduce the divergence?"""
    fuzz_metrics.note_shrink_probe()
    got = run_case(case, gates=d.gates, role=d.role,
                   check_only=d.query, final_only=True,
                   record_metrics=False)
    return bool(got)


def _with(case: FuzzCase, init_rels=None, bursts=None) -> FuzzCase:
    return FuzzCase(seed=case.seed, schema_text=case.schema_text,
                    init_rels=case.init_rels if init_rels is None
                    else init_rels,
                    bursts=case.bursts if bursts is None else bursts,
                    targets=case.targets, subjects=case.subjects,
                    kernel=case.kernel, schema=case.schema)


def _atomize(bursts: list) -> list:
    out = []
    for b in bursts:
        if b["kind"] == "write" and len(b["ops"]) > 1:
            out.extend({"kind": "write", "ops": [op]} for op in b["ops"])
        else:
            out.append(b)
    return out


def delta_count(case: FuzzCase) -> int:
    """Store-mutating deltas in the case: init rels + write ops + bulk
    rels + one per delete_by_filter (clock advances are free)."""
    n = len(case.init_rels)
    for b in case.bursts:
        if b["kind"] == "write":
            n += len(b["ops"])
        elif b["kind"] == "bulk":
            n += len(b["rels"])
        elif b["kind"] == "dbf":
            n += 1
    return n


def shrink_case(case: FuzzCase, d: Divergence,
                probe_budget: int = DEFAULT_PROBE_BUDGET) -> FuzzCase:
    """Smallest-reproducing case for divergence `d` (best-effort under
    `probe_budget` replays; the input case is returned unshrunk if the
    budget can't even confirm reproduction)."""
    probes = 0

    def probe(c: FuzzCase) -> bool:
        nonlocal probes
        probes += 1
        return _probe(c, d)

    # the divergence was observed after burst d.step: later bursts are
    # irrelevant by construction
    cur = _with(case, bursts=_atomize(case.bursts[: d.step + 1]))
    if not probe(cur):
        # atomization changed write-batch ordering semantics for this
        # stream (intra-batch delete-after-touch collapses); fall back
        # to the unatomized prefix
        cur = _with(case, bursts=case.bursts[: d.step + 1])
        if not probe(cur):
            return case  # not reproducible in probe mode; keep as-is

    changed = True
    while changed and probes < probe_budget:
        changed = False
        # drop whole bursts, newest first (older bursts are likelier to
        # be load-bearing seed state)
        i = len(cur.bursts) - 1
        while i >= 0 and probes < probe_budget:
            cand = _with(cur, bursts=cur.bursts[:i] + cur.bursts[i + 1:])
            if probe(cand):
                cur = cand
                changed = True
            i -= 1
        # thin bulk bursts one relationship at a time
        for bi, b in enumerate(cur.bursts):
            if b["kind"] != "bulk":
                continue
            ri = len(b["rels"]) - 1
            while ri >= 0 and probes < probe_budget:
                rels = b["rels"][:ri] + b["rels"][ri + 1:]
                nb = dict(b, rels=rels)
                cand = _with(cur, bursts=(cur.bursts[:bi] + [nb]
                                          + cur.bursts[bi + 1:]))
                if probe(cand):
                    cur = cand
                    b = nb
                    changed = True
                ri -= 1
        # thin the init set one relationship at a time
        ri = len(cur.init_rels) - 1
        while ri >= 0 and probes < probe_budget:
            cand = _with(cur, init_rels=(cur.init_rels[:ri]
                                         + cur.init_rels[ri + 1:]))
            if probe(cand):
                cur = cand
                changed = True
            ri -= 1
    return cur


# -- artifacts ----------------------------------------------------------------


def write_artifact(path: str, case: FuzzCase, d: Divergence) -> str:
    """Self-contained repro artifact (docs/fuzzing.md 'artifact
    anatomy'); returns the path written."""
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    payload = {
        "version": ARTIFACT_VERSION,
        "seed": case.seed,
        "gates": d.gates,
        "role": d.role,
        "kernel": case.kernel,
        "schema": case.schema_text,
        "init_rels": case.init_rels,
        "deltas": case.bursts,
        "delta_count": delta_count(case),
        "query": d.query,
        "jax_answer": d.got,
        "oracle_answer": d.want,
        "revision": d.revision,
        "targets": case.targets,
        "subjects": case.subjects,
        "repro": ("python scripts/fuzz_smoke.py --replay "
                  + os.path.abspath(path)),
    }
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f, indent=1, sort_keys=True)
    os.replace(tmp, path)
    return path


def load_artifact(path: str):
    """-> (FuzzCase, Divergence) reconstructed from an artifact file."""
    with open(path) as f:
        a = json.load(f)
    case = FuzzCase(seed=a["seed"], schema_text=a["schema"],
                    init_rels=a["init_rels"], bursts=a["deltas"],
                    targets=[tuple(t) for t in a["targets"]],
                    subjects=a["subjects"], kernel=a["kernel"])
    d = Divergence(seed=a["seed"], gates=a["gates"], role=a["role"],
                   kernel=a["kernel"], step=len(a["deltas"]) - 1,
                   query=a["query"], got=a["jax_answer"],
                   want=a["oracle_answer"], revision=a["revision"])
    return case, d


def replay_artifact(path: str) -> list:
    """Re-run an artifact's cell; returns the divergences seen NOW
    (empty = the underlying bug is fixed)."""
    case, d = load_artifact(path)
    return run_case(case, gates=d.gates, role=d.role,
                    check_only=d.query, final_only=True)
