"""Deterministic workflow runtime over the journal.

A focused equivalent of the go-workflows engine the reference embeds
(reference client.go:18-77): sequential workflows execute activities through
`WorkflowContext.execute_activity`, every completion is journaled, and on
crash (FailPointPanic or process restart) the instance re-runs from the top
with completed activities replayed from the journal — activities are
at-least-once, which is why the SpiceDB write activity carries idempotency
keys (reference activity.go:47-102).
"""

from __future__ import annotations

import asyncio
import json
import traceback
from typing import Any, Awaitable, Callable, Optional

from ...utils.audit import (
    AuditEvent,
    NULL_SINK,
    OUTCOME_ALLOWED,
    OUTCOME_DENIED,
    OUTCOME_ERROR,
)
from ...utils.failpoints import FailPointPanic
from ...utils.tracing import span
from . import journal as journal_mod
from .journal import Journal

DEFAULT_WORKFLOW_TIMEOUT = 30.0


class WorkflowError(Exception):
    pass


class ActivityError(Exception):
    """A journaled activity failure, replayed deterministically."""


class WorkflowContext:
    def __init__(self, instance_id: str, journal: Journal, activities: dict):
        self.instance_id = instance_id
        self._journal = journal
        self._activities = activities
        self._replay = journal.events(instance_id)
        self._seq = 0
        # out-of-band run annotations (NOT journaled): workflows record
        # rollback reasons here so the completion audit event can report
        # the rollback outcome; replayed (already-journaled) activities
        # re-record their notes because the workflow body re-runs
        self.notes: dict = {}

    async def execute_activity(self, name: str, *args: Any) -> Any:
        """Run (or replay) the next activity in the deterministic sequence."""
        seq = self._seq
        self._seq += 1
        if seq < len(self._replay):
            _, recorded_name, result, error = self._replay[seq]
            if recorded_name != name:
                raise WorkflowError(
                    f"non-deterministic replay: journal has {recorded_name!r}"
                    f" at seq {seq}, workflow asked for {name!r}")
            if error:
                raise ActivityError(error)
            return result
        fn = self._activities.get(name)
        if fn is None:
            raise WorkflowError(f"unknown activity {name!r}")
        try:
            # workflow step span: replayed completions above return
            # without one (they did no work this run)
            with span("workflow." + name):
                result = fn(*args)
                if asyncio.iscoroutine(result) or isinstance(result, Awaitable):
                    result = await result
        except FailPointPanic:
            # simulated crash: do NOT journal; replay will re-execute
            raise
        except asyncio.CancelledError:
            raise
        except Exception as e:
            # journaled failure: deterministic on replay
            self._journal.record_event(self.instance_id, seq, name, None,
                                       error=str(e) or type(e).__name__)
            self._replay = self._journal.events(self.instance_id)
            raise ActivityError(str(e) or type(e).__name__) from e
        # results must round-trip through JSON (journal durability)
        result = json.loads(json.dumps(result))
        self._journal.record_event(self.instance_id, seq, name, result)
        self._replay = self._journal.events(self.instance_id)
        return result

    async def sleep(self, seconds: float) -> None:
        # journaled as a no-op activity so replay doesn't re-sleep
        seq = self._seq
        self._seq += 1
        if seq < len(self._replay):
            return
        await asyncio.sleep(seconds)
        self._journal.record_event(self.instance_id, seq, "__sleep__", None)
        self._replay = self._journal.events(self.instance_id)


Workflow = Callable[[WorkflowContext, dict], Awaitable[Optional[dict]]]


class WorkflowEngine:
    """Client + monoprocess worker (reference client.go:32-77)."""

    def __init__(self, journal: Journal, max_crash_replays: int = 50,
                 audit=NULL_SINK):
        self.journal = journal
        self._workflows: dict[str, Workflow] = {}
        self._activities: dict[str, Callable] = {}
        self._wakeup = asyncio.Event()
        self._task: Optional[asyncio.Task] = None
        self._done_events: dict[str, asyncio.Event] = {}
        self.max_crash_replays = max_crash_replays
        self.audit = audit
        # strong refs to eagerly-launched instance tasks: the event loop
        # holds tasks only weakly, so a fire-and-forget ensure_future is
        # collectable by the cyclic gc MID-FLIGHT — the instance then
        # hangs forever and its waiter times out ("Task was destroyed
        # but it is pending").  Latent since the eager path existed; it
        # surfaces whenever allocation churn lands a gen-2 collection
        # inside the workflow window.
        self._eager_tasks: set = set()

    # -- registration --------------------------------------------------------

    def register_workflow(self, name: str, fn: Workflow) -> None:
        self._workflows[name] = fn

    def register_activity(self, name: str, fn: Callable) -> None:
        self._activities[name] = fn

    # -- client --------------------------------------------------------------

    def create_instance(self, instance_id: str, workflow: str, input: dict) -> str:
        if workflow not in self._workflows:
            raise WorkflowError(f"unknown workflow {workflow!r}")
        self.journal.create_instance(instance_id, workflow, input)
        self._done_events[instance_id] = asyncio.Event()
        if self._task is None:
            # no polling worker: execute eagerly in this loop (keeping a
            # strong reference — see _eager_tasks)
            task = asyncio.ensure_future(self._run_instance(instance_id))
            self._eager_tasks.add(task)
            task.add_done_callback(self._eager_tasks.discard)
        else:
            self._wakeup.set()
        return instance_id

    async def get_result(self, instance_id: str,
                         timeout: float = DEFAULT_WORKFLOW_TIMEOUT) -> dict:
        event = self._done_events.get(instance_id)
        rec = self.journal.get_instance(instance_id)
        if rec is None:
            raise WorkflowError(f"unknown instance {instance_id!r}")
        if rec.status == journal_mod.STATUS_PENDING:
            if event is None:
                raise WorkflowError(f"instance {instance_id!r} has no waiter")
            try:
                await asyncio.wait_for(event.wait(), timeout)
            except asyncio.TimeoutError:
                raise WorkflowError(
                    f"timed out waiting for workflow {instance_id}") from None
            rec = self.journal.get_instance(instance_id)
        self._done_events.pop(instance_id, None)
        if rec.status == journal_mod.STATUS_FAILED:
            raise WorkflowError(rec.error or "workflow failed")
        return rec.result or {}

    # -- worker --------------------------------------------------------------

    async def start(self) -> None:
        if self._task is None:
            self._task = asyncio.ensure_future(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def run_pending_once(self) -> int:
        """Drain all pending instances (also used for crash-recovery tests
        and at startup to resume in-flight dual writes)."""
        count = 0
        for instance_id in self.journal.pending_instances():
            await self._run_instance(instance_id)
            count += 1
        return count

    async def _run(self) -> None:
        cycles = 0
        while True:
            await self.run_pending_once()
            cycles += 1
            if cycles % 120 == 0:
                self.journal.prune_completed()
            self._wakeup.clear()
            try:
                await asyncio.wait_for(self._wakeup.wait(), 0.5)
            except asyncio.TimeoutError:
                pass

    async def _run_instance(self, instance_id: str) -> None:
        rec = self.journal.get_instance(instance_id)
        if rec is None or rec.status != journal_mod.STATUS_PENDING:
            return
        fn = self._workflows.get(rec.workflow)
        if fn is None:
            self.journal.complete_instance(
                instance_id, None, error=f"unknown workflow {rec.workflow!r}")
            self._signal(instance_id)
            return
        ctx = None
        while True:
            ctx = WorkflowContext(instance_id, self.journal, self._activities)
            try:
                result = await fn(ctx, rec.input)
            except FailPointPanic:
                # simulated crash: replay the instance (journal intact)
                attempts = self.journal.bump_attempts(instance_id)
                if attempts > self.max_crash_replays:
                    self.journal.complete_instance(
                        instance_id, None,
                        error="workflow exceeded crash-replay budget")
                    break
                continue
            except asyncio.CancelledError:
                raise
            except Exception as e:
                self.journal.complete_instance(
                    instance_id, None,
                    error=f"workflow had a panic: {e}\n{traceback.format_exc()}")
                break
            self.journal.complete_instance(instance_id, result)
            break
        self._audit_instance(instance_id, ctx)
        self._signal(instance_id)

    def _audit_instance(self, instance_id: str, ctx) -> None:
        """Dual-write decision audit: one event per completed instance —
        committed / rolled-back (kube 409 etc.) / failed — with any
        rollback reasons the workflow noted."""
        if not self.audit.enabled:
            return
        rec = self.journal.get_instance(instance_id)
        if rec is None:
            return
        input = rec.input or {}
        result = rec.result or {}
        notes = list((getattr(ctx, "notes", None) or {}).get("rollbacks", ()))
        code = result.get("status_code", 0)
        if rec.status == journal_mod.STATUS_FAILED:
            decision = OUTCOME_ERROR
            message = (rec.error or "workflow failed").splitlines()[0]
            if notes:
                message += "; " + "; ".join(notes)
        elif notes or (code and code >= 400):
            # the write did NOT land as requested: SpiceDB conflict
            # surfaced as kube 409, or a kube failure forced a rollback
            decision = OUTCOME_DENIED
            message = "; ".join(notes) if notes else f"status {code}"
        else:
            decision, message = OUTCOME_ALLOWED, ""
        from ...utils import tracing
        tr = tracing.current_trace()
        name = input.get("object_name") or input.get("request_name") or ""
        if message:
            message = f"instance {instance_id}: {message}"
        self.audit.emit(AuditEvent(
            stage="dualwrite", decision=decision,
            user=input.get("user_name", ""),
            verb=input.get("verb", ""),
            api_group=input.get("api_group", ""),
            resource=input.get("resource", ""),
            names=(name,) if name else (), count=1,
            rule=rec.workflow,
            backend=getattr(self.audit, "backend", ""),
            # prefer the journaled originating trace id: crash-recovery
            # replays complete outside any live request context
            trace_id=(input.get("trace_id", "")
                      or getattr(tr, "trace_id", "")),
            message=message))

    def _signal(self, instance_id: str) -> None:
        event = self._done_events.get(instance_id)
        if event is not None:
            event.set()
