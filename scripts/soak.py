"""Write-path soak: sustained mixed workload on the jax:// endpoint —
unique-name pod create/delete cycles (the normal kubernetes lifecycle),
fused lookups, bulk checks, and a live watch — tracking spare-pool
occupancy, rebuilds (now off-loop: sync vs background vs preemptive),
quarantined stale pairs, suppressions, RSS, and p99 drift per window.
Writes SOAK_r06.json by default.

Profiles:
  default        the r05 mix (2 writers, 3 lookers, 1 bulk checker)
  --churn        tail-latency hardening gate (ROADMAP item 4): heavier
                 sustained write churn (4 writers, no inter-op sleeps on
                 the write side) + list-heavy read traffic, sized to
                 drive the spare pool through preemptive background
                 rebuilds.

Pass/fail mode (--assert-slo): per-window p99 must stay within
max(2 x p50, --p99-floor-ms) and NO window may exceed --p99-cap-ms
(default 1000) — the "no rebuild-coincident multi-second spike"
acceptance gate.  The floor exists because at sub-ms p50 a 2x ratio is
noise, not a tail; the cap is absolute.

Run (real TPU):  PYTHONPATH=/root/repo python scripts/soak.py 1800
30-min churn:    python scripts/soak.py 1800 --churn --assert-slo
Quick CPU gate:  JAX_PLATFORMS=cpu python scripts/soak.py 24 --churn \
                     --graph small --window 6 --assert-slo --out /tmp/s.json

Every lookup/check runs inside a request trace (utils/tracing.py) and
each window dumps its slowest traces with per-phase span breakdowns
(queue_wait vs. kernel vs. extraction), so a p99 spike in a window is
attributable from the soak output alone.
"""

import argparse
import asyncio
import json
import os
import resource
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from spicedb_kubeapi_proxy_tpu.models import workloads as wl
from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import Bootstrap, create_endpoint
from spicedb_kubeapi_proxy_tpu.utils import timeline, tracing
from spicedb_kubeapi_proxy_tpu.spicedb.types import (
    CheckRequest,
    ObjectRef,
    RelationshipUpdate,
    SubjectRef,
    UpdateOp,
    parse_relationship,
)


def rss_mb() -> float:
    return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss / 1024.0


def parse_args():
    p = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    p.add_argument("duration", nargs="?", type=float, default=1800.0,
                   help="soak duration in seconds (default 1800)")
    p.add_argument("--churn", action="store_true",
                   help="sustained write churn + list-heavy profile "
                        "(tail-latency hardening gate)")
    p.add_argument("--graph", choices=["1m", "small"], default="1m",
                   help="workload: multitenant-1m (default) or the "
                        "small pods-depth1 graph for the fast CPU gate")
    p.add_argument("--window", type=float,
                   default=float(os.environ.get("SOAK_WINDOW_S", 300.0)),
                   help="reporting window seconds (default 300)")
    p.add_argument("--open-loop", action="store_true",
                   help="coordinated-omission-free read side: lookups "
                        "fire on a FIXED precomputed schedule (same "
                        "average rate as the closed-loop lookers) and "
                        "each latency is charged from the INTENDED "
                        "send time, so a stall's queued victims are "
                        "measured instead of silently delayed; "
                        "scheduler lag is exported as "
                        "authz_loadgen_lag_seconds "
                        "(docs/performance.md \"Fleet topology bench\")")
    p.add_argument("--assert-slo", action="store_true",
                   help="exit 1 unless every window holds p99 <= "
                        "max(2 x p50, --p99-floor-ms) and "
                        "p99 <= --p99-cap-ms, with zero worker errors")
    p.add_argument("--p99-floor-ms", type=float, default=50.0,
                   help="absolute floor under which the 2x-p50 ratio "
                        "check is waived (sub-ms p50s make the ratio "
                        "noise, not a tail)")
    p.add_argument("--p99-cap-ms", type=float, default=1000.0,
                   help="no window may exceed this p99 (ms)")
    p.add_argument("--out", default=os.environ.get("SOAK_OUT",
                                                   "SOAK_r06.json"),
                   help="output artifact path (default SOAK_r06.json)")
    return p.parse_args()


def main():
    args = parse_args()
    w = wl.multitenant_1m() if args.graph == "1m" else wl.pods_depth1()
    t0 = time.time()
    ep = create_endpoint("jax://", Bootstrap(schema_text=w.schema_text))
    ep.store.bulk_load([parse_relationship(r) for r in w.relationships])
    inner = getattr(ep, "inner", ep)
    # warm start BEFORE the workload: the initial graph compile and the
    # pow-2 bucket-ladder jit compiles are startup cost in production
    # (server warm start does exactly this) — without it window 1 just
    # measures compile latency instead of steady-state tails
    t_warm = time.time()
    inner.warm_start(prewarm=True)
    print(f"loaded {len(w.relationships)} tuples in {time.time()-t0:.1f}s "
          f"(warm start {time.time()-t_warm:.1f}s, "
          f"profile={'churn' if args.churn else 'default'} "
          f"graph={args.graph})", flush=True)

    n_writers = 4 if args.churn else 2
    n_lookers = 6 if args.churn else 3
    write_pause = 0.0 if args.churn else 0.05
    look_pause = 0.05 if args.churn else 0.2

    stop = asyncio.Event()
    lookup_lat: list = []      # seconds within current window
    windows: list = []
    counters = {"creates": 0, "deletes": 0, "lookups": 0, "checks": 0,
                "watch_events": 0, "errors": 0}
    min_pool: dict = {}

    def pool_snapshot():
        with inner._lock:
            for t, pool in inner._spare_pool.items():
                free = len(pool)
                if t not in min_pool or free < min_pool[t]:
                    min_pool[t] = free

    async def writer(wid: int):
        k = 0
        while not stop.is_set():
            name = f"soak-{wid}-{k}"
            try:
                await ep.write_relationships([RelationshipUpdate(
                    UpdateOp.TOUCH, parse_relationship(
                        f"pod:ns{k % 2000}/{name}#creator@user:u{wid}"))])
                counters["creates"] += 1
                await asyncio.sleep(0.02)
                await ep.write_relationships([RelationshipUpdate(
                    UpdateOp.DELETE, parse_relationship(
                        f"pod:ns{k % 2000}/{name}#creator@user:u{wid}"))])
                counters["deletes"] += 1
            except Exception as e:
                counters["errors"] += 1
                print(f"writer error: {e!r}", flush=True)
            pool_snapshot()
            k += 1
            await asyncio.sleep(write_pause)

    async def looker(i: int):
        while not stop.is_set():
            sub = SubjectRef("user", w.subjects[(i * 37) % len(w.subjects)])
            t = time.perf_counter()
            try:
                with tracing.request_trace(op="lookup", subject=sub.id) as tr:
                    ids = await ep.lookup_resources("pod", "view", sub)
                tracing.RECORDER.record(tr)
                lookup_lat.append(time.perf_counter() - t)
                counters["lookups"] += 1
                assert not any("\x00" in x for x in ids)
            except Exception as e:
                counters["errors"] += 1
                print(f"looker error: {e!r}", flush=True)
            await asyncio.sleep(look_pause)

    sched_lag = {"max_ms": 0.0, "scheduled": 0}

    async def open_loop_driver():
        """--open-loop replacement for the lookers: the whole read-side
        schedule is laid out up front (loadgen.WorkloadSpec, zipfian
        subject fan-in) and each lookup fires at its intended time
        whether or not earlier ones returned — latency is charged from
        the INTENDED send, the coordinated-omission fix."""
        from spicedb_kubeapi_proxy_tpu.utils import loadgen

        # offered rate matched to the closed-loop lookers' upper bound
        # so the two modes are comparable on the same profile
        rate = n_lookers / max(look_pause, 0.02)
        spec = loadgen.WorkloadSpec(
            seed=7, duration_s=args.duration, rate_per_s=rate,
            users=max(2, len(w.subjects)),
            verb_mix=(("filter", 1.0),))
        schedule = spec.schedule()
        sched_lag["scheduled"] = len(schedule)
        print(f"open-loop: {len(schedule)} lookups scheduled at "
              f"{rate:.1f}/s", flush=True)

        async def one(ev, intended):
            sub = SubjectRef("user", w.subjects[
                (int(ev["user"][1:]) - 1) % len(w.subjects)])
            try:
                with tracing.request_trace(op="lookup",
                                           subject=sub.id) as tr:
                    ids = await ep.lookup_resources("pod", "view", sub)
                tracing.RECORDER.record(tr)
                lookup_lat.append(time.perf_counter() - intended)
                counters["lookups"] += 1
                assert not any("\x00" in x for x in ids)
            except Exception as e:
                counters["errors"] += 1
                print(f"looker error: {e!r}", flush=True)

        t0 = time.perf_counter()
        tasks: list = []
        for ev in schedule:
            if stop.is_set():
                break
            delay = t0 + ev["t"] - time.perf_counter()
            if delay > 0:
                await asyncio.sleep(delay)
            intended = t0 + ev["t"]
            lag = max(0.0, time.perf_counter() - intended)
            if lag * 1e3 > sched_lag["max_ms"]:
                sched_lag["max_ms"] = round(lag * 1e3, 3)
            loadgen.LAG_GAUGE.set(lag)
            tasks.append(asyncio.ensure_future(one(ev, intended)))
            tasks = [t for t in tasks if not t.done()]
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)

    async def checker():
        while not stop.is_set():
            try:
                reqs = [CheckRequest(
                    ObjectRef("pod", f"ns{j % 2000}/p{j}"), "view",
                    SubjectRef("user", w.subjects[j % len(w.subjects)]))
                    for j in range(16)]
                with tracing.request_trace(op="check_bulk", batch=16) as tr:
                    await ep.check_bulk_permissions(reqs)
                tracing.RECORDER.record(tr)
                counters["checks"] += 16
            except Exception as e:
                counters["errors"] += 1
                print(f"checker error: {e!r}", flush=True)
            await asyncio.sleep(0.5)

    async def watcher():
        wtc = ep.watch(["pod"])
        try:
            while not stop.is_set():
                upd = await wtc.next(timeout=1.0)
                if upd is not None:
                    counters["watch_events"] += len(upd.updates)
        finally:
            wtc.close()

    async def reporter():
        start = time.time()
        last = start
        window_mark = timeline.now()
        while not stop.is_set():
            await asyncio.sleep(min(5, args.window / 3))
            now = time.time()
            if now - last >= args.window or (stop.is_set() and lookup_lat):
                lat = sorted(lookup_lat)
                lookup_lat.clear()
                last = now
                # per-window dispatch-timeline condensate: overlap
                # fraction, roofline fraction, stall-cause breakdown,
                # worst dispatch — a p99 spike window names its stall
                # (rebuild vs transfer vs compile) from the soak output
                tl_sum = timeline.summary(since=window_mark)
                window_mark = timeline.now()
                st = dict(inner.stats)
                windows.append({
                    "t_s": round(now - start, 1),
                    "lookups": len(lat),
                    "p50_ms": round(lat[len(lat) // 2] * 1e3, 1) if lat else None,
                    "p99_ms": round(lat[int(len(lat) * 0.99)] * 1e3, 1) if lat else None,
                    "rss_mb": round(rss_mb(), 1),
                    "rebuilds": st.get("rebuilds"),
                    "bg_rebuilds": st.get("bg_rebuilds"),
                    "preemptive_rebuilds": st.get("preemptive_rebuilds"),
                    "rebuild_failures": st.get("rebuild_failures"),
                    "stale_pair_marks": st.get("stale_pair_marks"),
                    "stale_routed": st.get("stale_routed"),
                    "spare_assignments": st.get("spare_assignments"),
                    "spare_reclaims": st.get("spare_reclaims"),
                    "placeholder_suppressed": st.get("placeholder_suppressed", 0),
                    "suppression_oracle_fallbacks": st.get(
                        "suppression_oracle_fallbacks", 0),
                    "counters": dict(counters),
                    # the window's slowest op traces, spans included —
                    # a p99 spike names its own phase (queue vs kernel
                    # vs extraction) instead of needing a re-run
                    "slow_traces": tracing.RECORDER.drain()[:3],
                    "timeline": tl_sum,
                })
                print(f"window {len(windows)}: {windows[-1]}", flush=True)

    async def run():
        read_side = ([open_loop_driver()] if args.open_loop
                     else [looker(i) for i in range(n_lookers)])
        tasks = [asyncio.ensure_future(x) for x in (
            *[writer(i) for i in range(n_writers)],
            *read_side,
            checker(), watcher(), reporter())]
        await asyncio.sleep(args.duration)
        stop.set()
        await asyncio.gather(*tasks, return_exceptions=True)

    t_run = time.time()
    asyncio.run(run())
    # quiesce in-flight background rebuilds before the final stats read
    wait = getattr(inner, "wait_rebuilds", None)
    if wait is not None:
        wait(timeout=60)
    st = dict(inner.stats)
    warmup_rebuilds = windows[0]["rebuilds"] if windows else st.get("rebuilds")

    slo_failures = []
    if args.assert_slo:
        for i, win in enumerate(windows):
            p50, p99 = win["p50_ms"], win["p99_ms"]
            if p99 is None:
                slo_failures.append(f"window {i + 1}: no lookups completed")
                continue
            if p99 > args.p99_cap_ms:
                slo_failures.append(
                    f"window {i + 1}: p99 {p99}ms > cap {args.p99_cap_ms}ms")
            if p99 > max(2 * (p50 or 0.0), args.p99_floor_ms):
                slo_failures.append(
                    f"window {i + 1}: p99 {p99}ms > "
                    f"max(2 x p50 {p50}ms, floor {args.p99_floor_ms}ms)")
        if not windows:
            slo_failures.append("no windows recorded (duration too short "
                                "for --window?)")
        if counters["errors"]:
            slo_failures.append(f"{counters['errors']} worker errors")

    final = {
        "duration_s": round(time.time() - t_run, 1),
        "platform": os.environ.get("JAX_PLATFORMS", "tpu(axon)"),
        "profile": "churn" if args.churn else "default",
        "graph": args.graph,
        "window_s": args.window,
        "open_loop": args.open_loop,
        "loadgen": ({"scheduled": sched_lag["scheduled"],
                     "max_sched_lag_ms": sched_lag["max_ms"]}
                    if args.open_loop else None),
        "windows": windows,
        "final_stats": {k: v for k, v in st.items()
                        if isinstance(v, (int, float))},
        "min_spare_pool_free": min_pool,
        "counters": counters,
        "rss_mb_final": round(rss_mb(), 1),
        # whole-run dispatch-timeline condensate (ring-bounded: covers
        # the most recent events; per-window views live in windows[])
        "timeline_summary": timeline.summary(),
        "verdict": {
            "rebuilds_after_warmup": (st.get("rebuilds", 0)
                                      - (warmup_rebuilds or 0)),
            "bg_rebuilds": st.get("bg_rebuilds", 0),
            "preemptive_rebuilds": st.get("preemptive_rebuilds", 0),
            "rebuild_failures": st.get("rebuild_failures", 0),
            "stale_pair_marks": st.get("stale_pair_marks", 0),
            "placeholder_suppressed": st.get("placeholder_suppressed", 0),
            "suppression_oracle_fallbacks": st.get(
                "suppression_oracle_fallbacks", 0),
            "errors": counters["errors"],
            "rss_flat": (len(windows) < 2
                         or windows[-1]["rss_mb"] - windows[1]["rss_mb"]
                         < 256),
            "slo_pass": not slo_failures if args.assert_slo else None,
            "slo_failures": slo_failures,
        },
    }
    with open(args.out, "w") as f:
        json.dump(final, f, indent=1)
    print(json.dumps(final["verdict"]), flush=True)
    print(f"wrote {args.out}", flush=True)
    if args.assert_slo and slo_failures:
        print("soak: SLO GATE FAILED:\n  " + "\n  ".join(slo_failures),
              file=sys.stderr, flush=True)
        sys.exit(1)


if __name__ == "__main__":
    main()
