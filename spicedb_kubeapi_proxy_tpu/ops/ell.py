"""Bit-packed ELL (fixed-fanin gather) reachability kernel.

The high-performance variant of ops/spmv.py.  Two ideas:

1. **No scatter.**  TPUs execute XLA scatter (the lowering of
   `jax.ops.segment_sum`) nearly serially; it dominated the segment-path
   kernel.  Here the adjacency is stored destination-major as fixed-width
   gather tables ("ELL" format): row r of `idx_main` lists the state
   indices whose OR is the one-step closure of state r.  One iteration is
   K row-gathers + bitwise ORs — gather only, which XLA lowers to fast
   dynamic-slices along the minor dimension.

   Destinations with more than K1 in-edges ("hubs": a namespace with
   thousands of pods pointing at it, a group with thousands of members)
   are split into an OR-reduction tree of **aux nodes** appended after the
   real state rows: each aux node ORs up to K2 children, levels stacked
   until ≤K1 roots remain.  Aux nodes are stateless OR gates recomputed
   every iteration; they add tree-depth extra iterations (each ~100x
   cheaper than a segment-path iteration) but keep every row's fanin
   static.  Monotonicity of the fixpoint makes this exactly equivalent to
   the flat edge list (reference semantics: SpiceDB's recursive graph
   walk, pkg/authz/check.go:48, bounded like dispatch depth
   pkg/spicedb/spicedb.go:34).

2. **Bit-packed batch.**  The boolean state for a B-query batch is packed
   into uint32 words: x is [NT, W] with W = B/32.  HBM traffic drops 32x
   vs float32, and the whole userset-rewrite algebra maps onto bitwise
   ops: union=OR, intersection=AND, exclusion=AND-NOT — per-bit exact.

Layout: rows [0, state_size) are the GraphProgram's state (slot ranges
unchanged, so permission-op slices and lookup slices work as before);
rows [state_size, NT) are aux tree nodes.  The program's dead index keeps
its position; padding slots in both tables point at it.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils import devtel, timeline, workload
from ..utils.failpoints import fail_point
from .graph_compile import (
    GraphProgram,
    PExclude,
    PIntersect,
    PRead,
    PUnion,
    PZero,
)

# Main-table fanin: rows with more in-edges are tree-split.  Production
# graphs are extremely fanin-skewed (multitenant-1m: 62% of rows fanin 0,
# 38% fanin 1, 0.2% more), so narrow main rows win: each main slot costs
# a full [NT]-row gather per iteration, while tree-split hubs ride the
# tiny aux table.  K=2 keeps one spare slot on the common fanin-1 row for
# incremental inserts (a full row grows an aux node from the spare pool,
# see _EllGraph.add_rel).  Env-tunable for experiments.
K_MAIN = int(os.environ.get("SPICEDB_TPU_K_MAIN", "2"))
# Aux-node fanin: wider is better for hubs (fewer tree levels).
K_AUX = 32
# Caveat (MAYBE-plane) table fanin; caveated tuples are typically sparse,
# hubs tree-split inside the same table
K_CAV = 8

MAX_ITERATIONS = 50  # matches embedded reference dispatch depth cap


def batch_words(batch: int, minimum: int = 1) -> int:
    """Power-of-two uint32 word count covering `batch` query columns."""
    w = max(minimum, 1)
    need = (max(batch, 1) + 31) // 32
    while w < need:
        w *= 2
    return w


@dataclass
class EllTables:
    """Host-side adjacency in fixed-fanin form (device copies are owned by
    the endpoint so it can do row-wise incremental updates)."""
    idx_main: np.ndarray                 # int32 [state_size, K_MAIN]
    idx_aux: np.ndarray                  # int32 [n_aux, K_AUX]
    tree_depth: int                      # max OR-tree levels over all hubs
    # trailing all-dead aux rows reserved for incremental growth: a delta
    # insert hitting a full main row moves the row's children into one of
    # these and gains an OR-tree level instead of forcing a rebuild
    spare_rows: tuple = ()               # aux-table row numbers


def build_tables(prog: GraphProgram,
                 k_main: Optional[int] = None) -> EllTables:
    """Group the program's (src, dst) edge list destination-major into
    fixed-fanin tables, tree-splitting hubs.

    Vectorized: one stable sort by destination, then per-slot scatter for
    the (overwhelmingly common) small rows; only hub destinations fall to
    a Python loop."""
    km = k_main if k_main is not None else K_MAIN
    n = prog.state_size
    dead = prog.dead_index
    idx_main = np.full((n, km), dead, np.int32)
    aux_rows: list[np.ndarray] = []
    tree_depth = 0
    e = len(prog.edge_src)
    if e:
        order = np.argsort(prog.edge_dst, kind="stable")
        sdst = prog.edge_dst[order]
        ssrc = prog.edge_src[order]
        starts = np.concatenate(
            [[0], np.nonzero(np.diff(sdst))[0] + 1])
        counts = np.diff(np.concatenate([starts, [e]]))
        gdst = sdst[starts]
        # rank of each edge within its destination group
        rank = np.arange(e) - np.repeat(starts, counts)
        small = counts <= km
        small_edges = np.repeat(small, counts)
        idx_main[sdst[small_edges], rank[small_edges]] = ssrc[small_edges]

        def new_aux(children: np.ndarray) -> int:
            row = np.full(K_AUX, dead, np.int32)
            row[: len(children)] = children
            aux_rows.append(row)
            return n + len(aux_rows) - 1

        for g in np.nonzero(~small)[0]:
            lo = int(starts[g])
            children = ssrc[lo: lo + int(counts[g])]
            depth = 0
            while len(children) > km:
                children = np.asarray(
                    [new_aux(children[i: i + K_AUX])
                     for i in range(0, len(children), K_AUX)], np.int32)
                depth += 1
            idx_main[int(gdst[g]), : len(children)] = children
            tree_depth = max(tree_depth, depth)

    if aux_rows:
        idx_aux = np.stack(aux_rows).astype(np.int32)
    else:
        idx_aux = np.full((0, K_AUX), dead, np.int32)
    # spare pool sized to the graph; hub-free graphs keep an empty aux
    # table (no per-iteration aux gather at all) and fall back to the
    # rebuild path on their rare full-row inserts.  The pool scales with
    # BOTH the aux table (hub growth) and the main row count (capped):
    # under sustained write churn full-row inserts land anywhere in the
    # graph, and the pool drying up is what turns churn back into
    # rebuilds — on the 1M-row graph the main-scaled term costs ~0.5MB
    # of aux table for thousands of extra in-place growths
    # (docs/performance.md "Overload & rebuild behavior").
    if aux_rows:
        n_spare = max(64, len(aux_rows) // 4, min(4096, n // 256))
        spare0 = idx_aux.shape[0]
        idx_aux = np.vstack([idx_aux,
                             np.full((n_spare, K_AUX), dead, np.int32)])
        spares = tuple(range(spare0, spare0 + n_spare))
    else:
        spares = ()
    return EllTables(idx_main=idx_main, idx_aux=idx_aux,
                     tree_depth=tree_depth, spare_rows=spares)


@dataclass
class CavTables:
    """MAYBE-plane adjacency: one [NT, K_CAV] gather table whose OR is the
    one-step closure over UNDECIDABLE caveated edges only.  Caveat hubs
    tree-split into aux rows appended after the shared aux rows (their
    children live in this same table); rows the shared tables own are
    dead-padded here and vice versa."""
    idx_cav: np.ndarray   # int32 [NT, K_CAV]
    n_aux_cav: int
    tree_depth: int


def build_cav_tables(prog: GraphProgram, n_aux_shared: int) -> CavTables:
    """Destination-major fixed-fanin table for the program's caveat edges.
    Python-loop build is fine: caveated tuples are sparse by nature."""
    dead = prog.dead_index
    base = prog.state_size + n_aux_shared
    groups: dict[int, list] = {}
    for s, d in zip(prog.cav_src, prog.cav_dst):
        groups.setdefault(int(d), []).append(int(s))
    aux_rows: list[list] = []
    roots: dict[int, list] = {}
    tree_depth = 0
    for dst, children in groups.items():
        depth = 0
        while len(children) > K_CAV:
            nxt = []
            for i in range(0, len(children), K_CAV):
                aux_rows.append(children[i: i + K_CAV])
                nxt.append(base + len(aux_rows) - 1)
            children = nxt
            depth += 1
        roots[dst] = children
        tree_depth = max(tree_depth, depth)
    nt = base + len(aux_rows)
    idx_cav = np.full((nt, K_CAV), dead, np.int32)
    for dst, children in roots.items():
        idx_cav[dst, : len(children)] = children
    for j, children in enumerate(aux_rows):
        idx_cav[base + j, : len(children)] = children
    return CavTables(idx_cav=idx_cav, n_aux_cav=len(aux_rows),
                     tree_depth=tree_depth)


# -- packed expression program ----------------------------------------------

def _apply_perm_expr_packed(expr, x: jnp.ndarray,
                            half: Optional[int] = None,
                            plane_last: bool = False) -> jnp.ndarray:
    """Evaluate a permission expression over packed state.

    Tri-state (definite/maybe bitplane) modes — maybe ⊇ definite always;
    union/intersection act planewise (Kleene: T∨U=T via the def plane,
    T∧U=U via the maybe plane); exclusion mixes planes:
    def(A−B) = def(A) ∧ ¬maybe(B),  maybe(A−B) = maybe(A) ∧ ¬def(B) —
    i.e. `base & ~swap(sub)` with the subtrahend's planes swapped.

    - `half` set: planes side by side on the WORD axis (single-chip
      layout; words [0, half) definite, [half, 2*half) maybe) — swap is a
      word-halves concat.
    - `plane_last`: planes on a trailing size-2 axis (sharded layout, so
      the swap stays device-local under a word-sharded mesh) — swap is a
      flip of the last axis."""
    if isinstance(expr, PRead):
        return jax.lax.dynamic_slice_in_dim(x, expr.offset, expr.length, axis=0)
    if isinstance(expr, PZero):
        return jnp.zeros((expr.length,) + x.shape[1:], dtype=x.dtype)
    if isinstance(expr, PUnion):
        out = _apply_perm_expr_packed(expr.children[0], x, half, plane_last)
        for c in expr.children[1:]:
            out = out | _apply_perm_expr_packed(c, x, half, plane_last)
        return out
    if isinstance(expr, PIntersect):
        out = _apply_perm_expr_packed(expr.children[0], x, half, plane_last)
        for c in expr.children[1:]:
            out = out & _apply_perm_expr_packed(c, x, half, plane_last)
        return out
    if isinstance(expr, PExclude):
        base = _apply_perm_expr_packed(expr.base, x, half, plane_last)
        sub = _apply_perm_expr_packed(expr.subtract, x, half, plane_last)
        if plane_last:
            sub = sub[..., ::-1]
        elif half is not None:
            sub = jnp.concatenate([sub[:, half:], sub[:, :half]], axis=1)
        return base & ~sub
    raise TypeError(f"unknown perm expr {expr!r}")


def compute_stages(prog: GraphProgram) -> tuple:
    """Type-level Gauss-Seidel stages for the staged step: contiguous
    state-row ranges grouped by type-SCC, topologically ordered by the
    COMPILED edge list (type-of(src) -> type-of(dst)).

    Evaluating ranges in this order lets one sweep propagate a whole
    user->group->tenant->namespace->pod chain: the fixpoint trip count
    drops from the type-graph depth to ~the longest in-SCC chain (+1 to
    confirm).  Correctness never depends on the order — the while_loop
    exits at the true fixpoint under ANY update order (monotone OR), so
    delta-added edges that violate the compiled order (or cycles) just
    cost extra sweeps, exactly like the unstaged step.

    Returns a tuple of stage descriptors (ranges, repeat): `ranges` is a
    tuple of (lo, hi) row ranges (SCC members merge when adjacent), and
    `repeat` is 2 when the SCC has internal edges (e.g. group#member
    nesting) so one nesting hop resolves within the sweep instead of
    costing an extra sweep; deeper nests still converge via the outer
    while_loop."""
    # per-type contiguous range from the slot layout
    starts: dict = {}
    for (t, _slot), off in prog.slot_offsets.items():
        starts[t] = min(starts.get(t, off), off)
    if not starts:
        return ()
    types = sorted(starts, key=lambda t: starts[t])
    bounds = [starts[t] for t in types] + [prog.dead_index]
    rng_of = {t: (bounds[i], bounds[i + 1]) for i, t in enumerate(types)}
    # type dependency edges from the compiled edge list
    b = np.asarray(bounds[:-1], np.int64)
    deps: dict = {t: set() for t in types}
    self_dep: set = set()
    if len(prog.edge_src):
        from .graph_compile import SELF_SLOT
        live = prog.edge_dst != prog.dead_index
        esrc = prog.edge_src[live]
        src_t = np.searchsorted(b, esrc, side="right") - 1
        dst_t = np.searchsorted(b, prog.edge_dst[live], side="right") - 1
        # a same-type edge forces a within-sweep repeat only when its
        # source is a DYNAMIC slot; sources in the type's self range are
        # static query seeds and resolve in the first pass regardless
        self_lo = np.asarray(
            [prog.slot_offsets.get((t, SELF_SLOT), -1) for t in types],
            np.int64)
        self_hi = self_lo + np.asarray(
            [prog.num_objects.get(t, 0) for t in types], np.int64)
        in_self = (esrc >= self_lo[src_t]) & (esrc < self_hi[src_t])
        for s, d, st in set(zip(src_t.tolist(), dst_t.tolist(),
                                in_self.tolist())):
            if not (0 <= s < len(types) and 0 <= d < len(types)):
                continue
            if s == d:
                if not st:
                    self_dep.add(types[d])
            else:
                deps[types[d]].add(types[s])  # d depends on s
    # SCC condensation (iterative Tarjan) + topological order
    index: dict = {}
    low: dict = {}
    on_stack: dict = {}
    stack: list = []
    sccs: list = []
    counter = [0]
    for root in types:
        if root in index:
            continue
        work = [(root, iter(sorted(deps[root])))]
        index[root] = low[root] = counter[0]
        counter[0] += 1
        stack.append(root)
        on_stack[root] = True
        while work:
            v, it = work[-1]
            advanced = False
            for nxt in it:
                if nxt not in index:
                    index[nxt] = low[nxt] = counter[0]
                    counter[0] += 1
                    stack.append(nxt)
                    on_stack[nxt] = True
                    work.append((nxt, iter(sorted(deps[nxt]))))
                    advanced = True
                    break
                if on_stack.get(nxt):
                    low[v] = min(low[v], index[nxt])
            if advanced:
                continue
            work.pop()
            if work:
                pv = work[-1][0]
                low[pv] = min(low[pv], low[v])
            if low[v] == index[v]:
                comp = []
                while True:
                    u = stack.pop()
                    on_stack[u] = False
                    comp.append(u)
                    if u == v:
                        break
                sccs.append(comp)
    # Tarjan emits SCCs in reverse topological order of the traversal
    # graph; with edges pointing dependent -> prerequisite, that is
    # exactly prerequisites-first — the evaluation order we want
    stages: list = []
    for comp in sccs:
        ranges = sorted(rng_of[t] for t in comp)
        merged = [list(ranges[0])]
        for lo, hi in ranges[1:]:
            if lo == merged[-1][1]:
                merged[-1][1] = hi
            else:
                merged.append([lo, hi])
        rtuple = tuple((lo, hi) for lo, hi in merged if hi > lo)
        if not rtuple:
            continue
        repeat = 2 if (len(comp) > 1 or any(t in self_dep for t in comp)) \
            else 1
        stages.append((rtuple, repeat, True))
    return tuple(stages)


def annotate_stage_refresh(stages: tuple, host_main: np.ndarray,
                           state_size: int) -> tuple:
    """Set each stage's aux-refresh flag to whether its gather table rows
    actually reference aux nodes (values >= state_size): stages that
    never read the aux table skip the per-stage OR-tree refresh.  The
    flags are a convergence-speed hint computed at build time — deltas
    that later grow a tree into a flag-less stage only cost extra
    sweeps (the while_loop still exits at the true fixpoint)."""
    out = []
    for ranges, repeat, _ in stages:
        refs = any(bool((host_main[lo:hi] >= state_size).any())
                   for lo, hi in ranges)
        out.append((ranges, repeat, refs))
    return tuple(out)


def make_ell_step(prog: GraphProgram, n_aux_rows: int,
                  half: Optional[int] = None, aux_passes: int = 1,
                  stages: Optional[tuple] = None):
    """Per-iteration transition over packed state x: [NT, W] uint32 —
    or [NT, 2*half] when the tri-state (definite/maybe bitplane) path is
    active (`half` = words per plane; an idx_cav table feeds the MAYBE
    half with the undecidable caveated edges).

    `aux_passes` (= the OR-tree height) refreshes the aux nodes
    bottom-up BEFORE the main gather reads them (Gauss-Seidel within the
    iteration), so a hub edge propagates leaf -> tree -> destination in
    one outer iteration instead of one per tree level.  Monotone OR
    fixpoint semantics are unchanged — only the trip count drops.

    `stages` (definite path only) extends the same idea across TYPES:
    state-row ranges are updated in type-topological order within one
    sweep, each range's gather reading the ranges already updated this
    sweep, so a full user->group->...->pod chain propagates in ONE sweep
    instead of one per type hop (measured on multitenant-1m: trips 6->2,
    scripts/probe_staged.py).  MAIN-table gather traffic per sweep is
    unchanged; the aux OR-tree refresh runs once per aux-reading stage
    pass instead of once per sweep (the aux table is orders of magnitude
    smaller than the main table, but aux-hub-heavy schemas pay S-fold
    refresh cost — annotate_stage_refresh bounds S to the stages that
    actually read aux roots).  Per-row gather cost is lowering-bound and
    locality-independent (same probe), so fewer sweeps is the win."""
    n = prog.state_size
    dead = prog.dead_index
    perm_ops = tuple(prog.perm_ops)
    wc_terms = tuple(prog.wildcard_terms)
    wc_masks = []
    for term in prog.wildcard_terms:
        m = np.zeros((n + n_aux_rows, 1), np.uint32)
        m[np.asarray(term.mask_indices, np.int64)] = np.uint32(0xFFFFFFFF)
        wc_masks.append(jnp.asarray(m))

    if stages:
        # perm ops and wildcard masks grouped by the stage whose ranges
        # contain them (slot layout keeps a type's slots contiguous, so
        # containment is exact)
        def _in_stage(ranges, off):
            return any(lo <= off < hi for lo, hi in ranges)

        stage_ops = {s: [op for op in perm_ops
                         if _in_stage(s[0], op.offset)] for s in stages}
        stage_wc = {s: [i for i, term in enumerate(wc_terms)
                        if any(_in_stage(s[0], m)
                               for m in term.mask_indices)]
                    for s in stages}

        def staged_step(x, x0, idx_main, idx_aux, idx_cav=None):
            assert idx_cav is None and half is None, \
                "staged step is definite-plane only"
            cur = x

            def refresh_aux(cur):
                # hub OR-trees recomputed bottom-up from the CURRENT
                # values (pure functions of state, safe to recompute any
                # time); published into the carry so the next gather
                # reads fresh roots
                for _ in range(max(1, aux_passes)):
                    y_aux = cur[idx_aux[:, 0]]
                    for k in range(1, idx_aux.shape[1]):
                        y_aux = y_aux | cur[idx_aux[:, k]]
                    cur = jax.lax.dynamic_update_slice_in_dim(
                        cur, y_aux, n, axis=0)
                return cur

            # wildcard liveness: self slots are static seeds (set at
            # init, never rewritten), so reading x here is exact
            lives = [jax.lax.reduce(
                jax.lax.dynamic_slice_in_dim(
                    x, t.self_offset, t.self_length, axis=0),
                np.uint32(0), jax.lax.bitwise_or, (0,))[None, :]
                for t in wc_terms]
            for s in stages:
                ranges, repeat, wants_aux = s
                for _ in range(repeat):
                    if n_aux_rows and wants_aux:
                        # refresh before every pass of a stage whose
                        # table reads aux roots: hub trees whose
                        # children updated earlier this sweep feed this
                        # stage's gather immediately
                        cur = refresh_aux(cur)
                    for lo, hi in ranges:
                        tbl = idx_main[lo:hi]
                        y = cur[tbl[:, 0]]
                        for k in range(1, tbl.shape[1]):
                            y = y | cur[tbl[:, k]]
                        y = y | jax.lax.dynamic_slice_in_dim(
                            x0, lo, hi - lo, axis=0)
                        for i in stage_wc[s]:
                            y = y | (wc_masks[i][lo:hi] & lives[i])
                        cur = jax.lax.dynamic_update_slice_in_dim(
                            cur, y, lo, axis=0)
                    for op in stage_ops[s]:
                        vec = _apply_perm_expr_packed(op.expr, cur, half)
                        seed = jax.lax.dynamic_slice_in_dim(
                            x0, op.offset, op.length, axis=0)
                        cur = jax.lax.dynamic_update_slice_in_dim(
                            cur, vec | seed, op.offset, axis=0)
            if n_aux_rows:
                # leave aux rows consistent with this sweep's final
                # state so the convergence compare (any(x1 != x)) sees a
                # fixpoint as unchanged aux too
                cur = refresh_aux(cur)
            return cur.at[dead].set(np.uint32(0))

        return staged_step

    def step(x, x0, idx_main, idx_aux, idx_cav=None):
        # one-step closure: K gathers + OR per table, concatenated in row
        # order (main rows first, aux rows after) — no scatter anywhere.
        # Fanin widths come from the table shapes (trace-time constants),
        # so one step fn serves any K layout.
        if n_aux_rows:
            # refresh aux OR-tree nodes bottom-up first; each pass fixes
            # one more tree level (pass 1 = nodes whose children are all
            # state rows), then the main gather reads current roots
            xm = x
            for _ in range(max(1, aux_passes)):
                y_aux = xm[idx_aux[:, 0]]
                for k in range(1, idx_aux.shape[1]):
                    y_aux = y_aux | xm[idx_aux[:, k]]
                xm = jnp.concatenate([x[:n], y_aux], axis=0)
            y_main = xm[idx_main[:, 0]]
            for k in range(1, idx_main.shape[1]):
                y_main = y_main | xm[idx_main[:, k]]
            y = jnp.concatenate([y_main, y_aux], axis=0)
        else:
            y_main = x[idx_main[:, 0]]
            for k in range(1, idx_main.shape[1]):
                y_main = y_main | x[idx_main[:, k]]
            y = y_main
        if idx_cav is not None:
            # caveat edges reach the MAYBE plane only: gather their
            # closure and OR it into the maybe half (definite half is
            # untouched — an undecided caveat can never DEFINITELY grant)
            extra = x[idx_cav[:, 0]]
            for k in range(1, idx_cav.shape[1]):
                extra = extra | x[idx_cav[:, k]]
            y = jnp.concatenate([y[:, :half], y[:, half:] | extra[:, half:]],
                                axis=1)
        for term, mask in zip(wc_terms, wc_masks):
            live = jax.lax.dynamic_slice_in_dim(
                x, term.self_offset, term.self_length, axis=0)
            any_live = jax.lax.reduce(
                live, np.uint32(0), jax.lax.bitwise_or, (0,))[None, :]
            y = y | (mask & any_live)
        x1 = y | x0
        for op in perm_ops:
            vec = _apply_perm_expr_packed(op.expr, x1, half)
            seed = jax.lax.dynamic_slice_in_dim(x0, op.offset, op.length, axis=0)
            x1 = jax.lax.dynamic_update_slice_in_dim(
                x1, vec | seed, op.offset, axis=0)
        # the dead row must stay zero (table padding reads it)
        x1 = x1.at[dead].set(np.uint32(0))
        return x1

    return step


def init_packed_state(prog: GraphProgram, n_aux_rows: int, q_idx,
                      n_words: int, planes: bool = False,
                      like=None) -> jnp.ndarray:
    """Packed one-hot [NT, W] from per-query state indices ([NT, 2W] with
    both planes seeded when the tri-state path is active: the query
    subject itself is definite, hence also maybe).

    Column c of the batch is bit (c % 32) of word (c // 32); columns are
    distinct, so the scatter-add below never carries (each target bit is
    added at most once per (row, word)) — add is exactly OR here.

    `like` (the donated state arena, shape [NT, width]) makes the arena
    an operand of the zero-init: the bitplane PACK — int columns to
    one-hot uint32 bit words — happens on device, seeded into the
    buffer XLA aliases to the previous call's donated output, so the
    sweep state updates in place instead of allocating per call.
    """
    nt = prog.state_size + n_aux_rows
    b = q_idx.shape[0]
    cols = jnp.arange(b)
    word = cols // 32
    bit = (cols % 32).astype(jnp.uint32)
    width = 2 * n_words if planes else n_words
    x0 = (jnp.zeros((nt, width), jnp.uint32) if like is None
          else jnp.zeros_like(like))
    x0 = x0.at[q_idx, word].add(jnp.uint32(1) << bit)
    if planes:
        x0 = x0.at[q_idx, n_words + word].add(jnp.uint32(1) << bit)
    return x0.at[prog.dead_index].set(np.uint32(0))


def make_ell_evaluate(prog: GraphProgram, n_aux_rows: int, n_words: int,
                      num_iters: int, use_while: bool = True,
                      planes: bool = False, aux_passes: int = 1,
                      stages: Optional[tuple] = None, arena: bool = False,
                      introspect: bool = False):
    """fn(q_idx, idx_main, idx_aux[, idx_cav]) -> packed x_final
    [NT, W] uint32 ([NT, 2W] on the tri-state plane path).

    With `arena=True` the signature becomes
    fn(state, q_idx, idx_main, idx_aux[, idx_cav]): `state` is the
    previous call's x_final, donated (jax.jit donate_argnums) so XLA
    aliases its buffer to this call's state output — the persistent
    sweep state updates in place instead of allocating per call.

    With `introspect=True` (KernelIntrospect gate, resolved at jit-build
    time) the return value becomes (x_final, tel): tel is an int32
    [1 + num_iters] sweep trace — tel[0] the executed iteration count,
    tel[1:1+tel[0]] the per-iteration frontier population (bits that
    changed, via popcount of x1 ^ x).  The trace rides the carry and is
    read back with the result D2H, so it adds no device sync; off, the
    carry is byte-identical to the pre-introspection build."""
    step = make_ell_step(prog, n_aux_rows,
                         half=n_words if planes else None,
                         aux_passes=aux_passes,
                         stages=None if planes else stages)

    def fixpoint(x0, idx_main, idx_aux, idx_cav):
        if use_while:
            if introspect:
                def cond(state):
                    x, prev_changed, i, trace = state
                    return jnp.logical_and(prev_changed, i < num_iters)

                def body(state):
                    x, _, i, trace = state
                    x1 = step(x, x0, idx_main, idx_aux, idx_cav)
                    delta = jnp.sum(
                        jax.lax.population_count(x1 ^ x)).astype(jnp.int32)
                    return (x1, delta > jnp.int32(0), i + 1,
                            trace.at[i].set(delta))

                x_final, _, i, trace = jax.lax.while_loop(
                    cond, body, (x0, jnp.bool_(True), jnp.int32(0),
                                 jnp.zeros((num_iters,), jnp.int32)))
                return x_final, jnp.concatenate([i[None], trace])

            def cond(state):
                x, prev_changed, i = state
                return jnp.logical_and(prev_changed, i < num_iters)

            def body(state):
                x, _, i = state
                x1 = step(x, x0, idx_main, idx_aux, idx_cav)
                return (x1, jnp.any(x1 != x), i + 1)

            x_final, _, _ = jax.lax.while_loop(
                cond, body, (x0, jnp.bool_(True), jnp.int32(0)))
            return x_final

        if introspect:
            def body(x, _):
                x1 = step(x, x0, idx_main, idx_aux, idx_cav)
                delta = jnp.sum(
                    jax.lax.population_count(x1 ^ x)).astype(jnp.int32)
                return x1, delta

            x_final, deltas = jax.lax.scan(body, x0, None, length=num_iters)
            return x_final, jnp.concatenate(
                [jnp.full((1,), num_iters, jnp.int32), deltas])

        def body(x, _):
            return step(x, x0, idx_main, idx_aux, idx_cav), None

        x_final, _ = jax.lax.scan(body, x0, None, length=num_iters)
        return x_final

    if arena:
        def evaluate(state, q_idx, idx_main, idx_aux, idx_cav=None):
            x0 = init_packed_state(prog, n_aux_rows, q_idx, n_words, planes,
                                   like=state)
            return fixpoint(x0, idx_main, idx_aux, idx_cav)
    else:
        def evaluate(q_idx, idx_main, idx_aux, idx_cav=None):
            x0 = init_packed_state(prog, n_aux_rows, q_idx, n_words, planes)
            return fixpoint(x0, idx_main, idx_aux, idx_cav)

    return evaluate


class EllKernelCache:
    """Jitted packed check/lookup entry points for one (program, tables)
    pair.  Jit cache keys on (batch-word bucket, table shapes).

    With `planes=True` the state carries definite/maybe bitplanes and the
    call signatures grow an `idx_cav` table: checks return tri-state
    {0,1,2} (NO / CONDITIONAL / HAS), lookups return the DEFINITE plane
    only (LookupResources skips conditional results, reference
    lookups.go:85-88)."""

    # metric label for authz_sweep_iterations / authz_frontier_decay
    kernel_name = "ell"

    def __init__(self, prog: GraphProgram, n_aux_rows: int, tree_depth: int,
                 num_iters: Optional[int] = None, planes: bool = False,
                 shared_tree_depth: Optional[int] = None,
                 host_main: Optional[np.ndarray] = None):
        self.prog = prog
        self.n_aux_rows = n_aux_rows
        self.planes = planes
        # in-step bottom-up aux refresh (Gauss-Seidel) collapses OR-tree
        # levels into their outer iteration.  Passes follow the SHARED
        # table's tree height only — cav trees propagate through idx_cav
        # one level per outer iteration regardless, so their depth must
        # not inflate the sweep count (callers fold it into tree_depth
        # for the cap).  +1 spare pass: incremental growth can add a
        # level beyond the built height.
        std = shared_tree_depth if shared_tree_depth is not None else tree_depth
        self.aux_passes = std + 1
        # generous cap — while_loop exits at the true fixpoint anyway
        base = num_iters or MAX_ITERATIONS
        self.num_iters = base * (1 + tree_depth)
        # type-topological Gauss-Seidel stages (definite path only; the
        # plane path keeps the Jacobi step).  SPICEDB_TPU_STAGED=0
        # disables for A/B experiments.
        self.stages = (compute_stages(prog)
                       if not planes
                       and os.environ.get("SPICEDB_TPU_STAGED", "1") != "0"
                       else None)
        if self.stages and host_main is not None:
            self.stages = annotate_stage_refresh(self.stages, host_main,
                                                 prog.state_size)
        self._jits: dict[int, tuple] = {}
        # donated per-bucket state arenas (device-resident pipeline):
        # the pipelined entry points return their final sweep state, and
        # the next call of the same bucket donates it back so XLA
        # aliases the buffer in place (one persistent [NT, W] allocation
        # per bucket instead of one per call).  Ledger-registered under
        # the owning graph's generation (set by the endpoint's HBM
        # registration) so a rebuild retires them wholesale.
        self._arenas: dict = {}
        self._arena_lock = threading.Lock()
        self.devtel_generation = 0
        # jit-cache accounting: hits/misses/entries per batch bucket,
        # plus recompile-storm detection (utils/devtel.py)
        devtel.KERNELS.track(self)

    def note_main_aux_ref(self, row: int) -> bool:
        """Incremental growth (_EllGraph._grow) pointed main row `row`
        at an OR-tree aux node.  If the stage covering that row was
        annotated aux-free at build time (annotate_stage_refresh), the
        staged step would keep skipping the per-stage aux refresh and
        every query touching the grown hub pays one extra outer sweep —
        silently.  Flip the stage's wants_aux flag and drop the compiled
        entry points so the next call re-jits with the refresh; returns
        True when a flip happened (callers surface it as a stat)."""
        if not self.stages:
            return False
        for i, (ranges, repeat, wants_aux) in enumerate(self.stages):
            if any(lo <= row < hi for lo, hi in ranges):
                if wants_aux:
                    return False
                self.stages = (self.stages[:i]
                               + ((ranges, repeat, True),)
                               + self.stages[i + 1:])
                self._jits = {}
                return True
        return False

    def _fns(self, n_words: int) -> tuple:
        fns = self._jits.get(n_words)
        if fns is not None:
            devtel.KERNELS.note_jit_hit(n_words * 32)
            return fns
        devtel.KERNELS.note_compile(n_words * 32)
        # introspection is resolved at jit-BUILD time: gate off, the
        # functions below are exactly the pre-introspection build (no
        # trace in the carry, scalar return shapes) — the killswitch is
        # byte-identical, not merely quiet
        intro = workload.enabled()
        evaluate = make_ell_evaluate(self.prog, self.n_aux_rows, n_words,
                                     self.num_iters, planes=self.planes,
                                     aux_passes=self.aux_passes,
                                     stages=self.stages, introspect=intro)
        if self.planes:
            def run_checks(q_idx, gather_idx, gather_word, gather_bit,
                           idx_main, idx_aux, idx_cav):
                xe = evaluate(q_idx, idx_main, idx_aux, idx_cav)
                x, tel = xe if intro else (xe, None)
                dw = x[gather_idx, gather_word]
                mw = x[gather_idx, n_words + gather_word]
                d = (dw >> gather_bit) & jnp.uint32(1)
                m = (mw >> gather_bit) & jnp.uint32(1)
                # 2=HAS, 1=CONDITIONAL (maybe without definite), 0=NO
                out = d * 2 + (m & (d ^ jnp.uint32(1)))
                return (out, tel) if intro else out

            def run_lookup(slot_offset, slot_length, q_idx,
                           idx_main, idx_aux, idx_cav):
                xe = evaluate(q_idx, idx_main, idx_aux, idx_cav)
                x, tel = xe if intro else (xe, None)
                out = jax.lax.dynamic_slice(
                    x, (slot_offset, 0), (slot_length, n_words))
                return (out, tel) if intro else out
        else:
            def run_checks(q_idx, gather_idx, gather_word, gather_bit,
                           idx_main, idx_aux):
                xe = evaluate(q_idx, idx_main, idx_aux)
                x, tel = xe if intro else (xe, None)
                words = x[gather_idx, gather_word]
                out = (words >> gather_bit) & jnp.uint32(1)
                return (out, tel) if intro else out

            def run_lookup(slot_offset, slot_length, q_idx, idx_main, idx_aux):
                xe = evaluate(q_idx, idx_main, idx_aux)
                x, tel = xe if intro else (xe, None)
                # return PACKED words: device->host transfer is the dominant
                # cost (32x fewer bytes than a bool bitmap); host unpacks
                out = jax.lax.dynamic_slice_in_dim(
                    x, slot_offset, slot_length, axis=0)       # [L, W] uint32
                return (out, tel) if intro else out

        # XLA compiles lazily inside the first execution; the
        # first-call-per-compile-key wrapper records each such window
        # as a `compile` slice on the dispatch timeline (stall cause
        # the flight recorder links p99 spikes to).  run_lookup's
        # static (slot_offset, slot_length) pair IS part of the jit
        # cache key — every new (type, permission) slot range
        # recompiles, so static_args=2 attributes those too.
        # shape_args: the check gather and the grow-able tables retrace
        # the same jit under novel shapes — attribute those compiles
        # too, not just the first call of the bucket
        fns = (timeline.time_first_call(jax.jit(run_checks),
                                        bucket=n_words * 32,
                                        shape_args=True),
               timeline.time_first_call(
                   jax.jit(run_lookup, static_argnums=(0, 1)),
                   bucket=n_words * 32, static_args=2, shape_args=True),
               intro)
        self._jits[n_words] = fns
        return fns

    # -- pipelined (device-resident) entry points ----------------------------
    # The serial entries above sync at the numpy conversion and hand the
    # host a [L, W] result it must word-transpose; these variants keep
    # the whole per-batch pipeline on device: the bitplane pack seeds a
    # DONATED state arena (in-place iteration state), the word transpose
    # is folded into the jit where XLA fuses it with the final slice, and
    # the un-materialized device array is returned so the caller overlaps
    # the D2H readback with the next batch's dispatch.

    def _pipe_fns(self, n_words: int) -> tuple:
        fns = self._jits.get(("pipe", n_words))
        if fns is not None:
            devtel.KERNELS.note_jit_hit(n_words * 32)
            return fns
        devtel.KERNELS.note_compile(n_words * 32)
        # introspection resolved at jit-build time (see _fns); when on,
        # the pipelined entries return (out, state, tel) and the sweep
        # trace rides the same async D2H the result does
        intro = workload.enabled()
        evaluate = make_ell_evaluate(self.prog, self.n_aux_rows, n_words,
                                     self.num_iters, planes=self.planes,
                                     aux_passes=self.aux_passes,
                                     stages=self.stages, arena=True,
                                     introspect=intro)
        if self.planes:
            def run_checks(q_idx, gather_idx, gather_col, state,
                           idx_main, idx_aux, idx_cav):
                # word/bit split of the raw query columns happens HERE:
                # the host uploads plain int32 column ids
                gw = gather_col // 32
                gb = (gather_col % 32).astype(jnp.uint32)
                xe = evaluate(state, q_idx, idx_main, idx_aux, idx_cav)
                x, tel = xe if intro else (xe, None)
                d = (x[gather_idx, gw] >> gb) & jnp.uint32(1)
                m = (x[gather_idx, n_words + gw] >> gb) & jnp.uint32(1)
                # 2=HAS, 1=CONDITIONAL (maybe without definite), 0=NO
                out = d * 2 + (m & (d ^ jnp.uint32(1)))
                return (out, x, tel) if intro else (out, x)

            def run_lookup(slot_offset, slot_length, q_idx, state,
                           idx_main, idx_aux, idx_cav):
                xe = evaluate(state, q_idx, idx_main, idx_aux, idx_cav)
                x, tel = xe if intro else (xe, None)
                sl = jax.lax.dynamic_slice(
                    x, (slot_offset, 0), (slot_length, n_words))
                # transpose ON DEVICE: the D2H lands [W, L] contiguous
                # per word row, so host extraction is row indexing with
                # no 51MB host transpose copy (DEFINITE plane only)
                return (sl.T, x, tel) if intro else (sl.T, x)
        else:
            def run_checks(q_idx, gather_idx, gather_col, state,
                           idx_main, idx_aux):
                gw = gather_col // 32
                gb = (gather_col % 32).astype(jnp.uint32)
                xe = evaluate(state, q_idx, idx_main, idx_aux)
                x, tel = xe if intro else (xe, None)
                # tri-state encoding ({0, 2}) so every kernel variant
                # hands the endpoint the same value space
                out = ((x[gather_idx, gw] >> gb) & jnp.uint32(1)) * 2
                return (out, x, tel) if intro else (out, x)

            def run_lookup(slot_offset, slot_length, q_idx, state,
                           idx_main, idx_aux):
                xe = evaluate(state, q_idx, idx_main, idx_aux)
                x, tel = xe if intro else (xe, None)
                sl = jax.lax.dynamic_slice_in_dim(
                    x, slot_offset, slot_length, axis=0)
                return (sl.T, x, tel) if intro else (sl.T, x)

        # donate_argnums=3 = the state arena (positions count the full
        # signature, statics included); donation is a no-op on backends
        # without aliasing support (CPU) and an in-place update on TPU
        fns = (timeline.time_first_call(
                   jax.jit(run_checks, donate_argnums=(3,)),
                   bucket=n_words * 32, shape_args=True),
               timeline.time_first_call(
                   jax.jit(run_lookup, static_argnums=(0, 1),
                           donate_argnums=(3,)),
                   bucket=n_words * 32, static_args=2, shape_args=True),
               intro)
        self._jits[("pipe", n_words)] = fns
        return fns

    def arena_key(self, lanes: int) -> int:
        """Pool key for a batch of `lanes` padded query columns."""
        return max(1, lanes // 32)

    def take_arena(self, n_words: int):
        """Pop the bucket's state arena (exclusive: a donated buffer must
        never be shared between two in-flight calls); lazily allocated
        and HBM-ledger-registered on first use.  Donation accounting:
        the registered bytes are constant for the arena's lifetime —
        in-place aliasing neither allocates nor frees."""
        # kill-matrix site (tests/test_faultmatrix.py): a failure at the
        # arena pop must fail the dispatching batch fast without
        # corrupting the pool or the ledger
        fail_point("arenaTake")
        with self._arena_lock:
            a = self._arenas.pop(n_words, None)
        if a is not None:
            return a
        nt = self.prog.state_size + self.n_aux_rows
        width = 2 * n_words if self.planes else n_words
        a = jnp.zeros((nt, width), jnp.uint32)
        devtel.LEDGER.register("state_arena", int(a.nbytes),
                               generation=self.devtel_generation,
                               name=f"arena:{n_words}")
        return a

    def put_arena(self, n_words: int, state) -> None:
        """Return a call's final state as the bucket's next donated
        arena.  If a concurrent call repooled first, this one is simply
        dropped (registration is keyed by bucket name, so the ledger
        keeps counting exactly one arena per bucket)."""
        with self._arena_lock:
            self._arenas.setdefault(n_words, state)

    def discard_arena(self, n_words: int) -> None:
        """Drop a bucket's pooled arena — a failed async computation
        poisons its output array, and donating a poisoned arena would
        fail every later call of the bucket."""
        with self._arena_lock:
            a = self._arenas.pop(n_words, None)
        if a is not None:
            devtel.LEDGER.unregister("state_arena",
                                     generation=self.devtel_generation,
                                     name=f"arena:{n_words}")

    # hotpath: begin device dispatch (per-batch work stays on device —
    # lint M003 flags host numpy materialization / per-item loops here)
    def checks_device(self, q_idx: np.ndarray, n_words: int,
                      gather_idx: np.ndarray, gather_col: np.ndarray,
                      idx_main, idx_aux, idx_cav=None):
        """Dispatch-only tri-state checks ({0,2}, or {0,1,2} with
        planes): returns (out, tel) — the un-materialized device result
        plus the sweep-trace device array (None when KernelIntrospect
        was off at jit build); the caller owns the blocking readback."""
        run_checks, _, intro = self._pipe_fns(n_words)
        state = self.take_arena(n_words)
        args = [jnp.asarray(q_idx), jnp.asarray(gather_idx),
                jnp.asarray(gather_col), state, idx_main, idx_aux]
        if self.planes:
            res = run_checks(*args, idx_cav)
        else:
            res = run_checks(*args)
        out, x, tel = res if intro else (res[0], res[1], None)
        self.put_arena(n_words, x)
        return out, tel

    def lookup_packed_T_device(self, slot_offset: int, slot_length: int,
                               q_idx: np.ndarray, n_words: int,
                               idx_main, idx_aux, idx_cav=None):
        """Dispatch-only packed lookup, word-transposed on device:
        returns (out, tel) — out the un-materialized
        [n_words, slot_length] uint32 device array (bit b of word row w
        = query column w*32+b; DEFINITE plane when planes are active),
        tel the sweep trace (None when KernelIntrospect was off)."""
        _, run_lookup, intro = self._pipe_fns(n_words)
        state = self.take_arena(n_words)
        if self.planes:
            res = run_lookup(slot_offset, slot_length, jnp.asarray(q_idx),
                             state, idx_main, idx_aux, idx_cav)
        else:
            res = run_lookup(slot_offset, slot_length, jnp.asarray(q_idx),
                             state, idx_main, idx_aux)
        out, x, tel = res if intro else (res[0], res[1], None)
        self.put_arena(n_words, x)
        return out, tel
    # hotpath: end

    def iterations(self, q_idx: np.ndarray, n_words: int, idx_main, idx_aux,
                   idx_cav=None) -> int:
        """Executed while_loop trips to the fixpoint for this batch — the
        bench's roofline probe (bytes-per-iteration x iterations =
        modeled HBM traffic).  Jitted separately; same step function."""
        key = ("iters", n_words)
        fn = self._jits.get(key)
        if fn is not None:
            devtel.KERNELS.note_jit_hit(n_words * 32)
        else:
            devtel.KERNELS.note_compile(n_words * 32)
            step = make_ell_step(self.prog, self.n_aux_rows,
                                 half=n_words if self.planes else None,
                                 aux_passes=self.aux_passes,
                                 stages=self.stages)
            num_iters = self.num_iters
            prog, n_aux, planes = self.prog, self.n_aux_rows, self.planes

            def run(q_idx, idx_main, idx_aux, idx_cav=None):
                x0 = init_packed_state(prog, n_aux, q_idx, n_words, planes)

                def cond(state):
                    x, prev_changed, i = state
                    return jnp.logical_and(prev_changed, i < num_iters)

                def body(state):
                    x, _, i = state
                    x1 = step(x, x0, idx_main, idx_aux, idx_cav)
                    return (x1, jnp.any(x1 != x), i + 1)

                _, _, i = jax.lax.while_loop(
                    cond, body, (x0, jnp.bool_(True), jnp.int32(0)))
                return i

            fn = timeline.time_first_call(jax.jit(run),
                                          bucket=n_words * 32)
            self._jits[key] = fn
        if self.planes:
            return int(fn(jnp.asarray(q_idx), idx_main, idx_aux, idx_cav))
        return int(fn(jnp.asarray(q_idx), idx_main, idx_aux))

    # -- host-facing ---------------------------------------------------------

    def checks(self, q_idx: np.ndarray, n_words: int, gather_idx: np.ndarray,
               gather_col: np.ndarray, idx_main, idx_aux,
               idx_cav=None) -> np.ndarray:
        """bool allowed per gather slot — or int {0,1,2} tri-state when the
        plane path is active."""
        run_checks, _, intro = self._fns(n_words)
        gcol = np.asarray(gather_col, np.int64)
        args = [jnp.asarray(q_idx), jnp.asarray(gather_idx),
                jnp.asarray(gcol // 32),
                jnp.asarray((gcol % 32).astype(np.uint32)),
                idx_main, idx_aux]
        out = run_checks(*args, idx_cav) if self.planes else run_checks(*args)
        if intro:
            out, tel = out
            workload.note_sweep("ell", "check", np.asarray(tel))
        if self.planes:
            return np.asarray(out).astype(np.int8)
        return np.asarray(out) != 0

    def lookup_packed(self, slot_offset: int, slot_length: int,
                      q_idx: np.ndarray, n_words: int, idx_main, idx_aux,
                      idx_cav=None) -> np.ndarray:
        """Packed uint32 [slot_length, n_words] allowed words (bit b of
        word w is query column w*32+b; DEFINITE plane when planes are
        active).  The packed form is what the device computes and what the
        host should consume: per-column extraction is a shift/AND/nonzero
        over one word column, 32x less memory traffic than a bool bitmap."""
        _, run_lookup, intro = self._fns(n_words)
        if self.planes:
            out = run_lookup(slot_offset, slot_length,
                             jnp.asarray(q_idx), idx_main, idx_aux, idx_cav)
        else:
            out = run_lookup(slot_offset, slot_length,
                             jnp.asarray(q_idx), idx_main, idx_aux)
        if intro:
            out, tel = out
            workload.note_sweep("ell", "lookup", np.asarray(tel))
        return np.ascontiguousarray(out)

    def lookup(self, slot_offset: int, slot_length: int, q_idx: np.ndarray,
               n_words: int, idx_main, idx_aux, idx_cav=None) -> np.ndarray:
        """bool [slot_length, n_words*32] allowed bitmap (columns beyond the
        real batch are padding; DEFINITE plane when planes are active)."""
        packed = self.lookup_packed(slot_offset, slot_length, q_idx, n_words,
                                    idx_main, idx_aux, idx_cav)
        # uint32 little-endian: bit b of word w lands at column w*32 + b
        return np.unpackbits(packed.view(np.uint8).reshape(slot_length, -1),
                             axis=1, bitorder="little").astype(bool)
