"""Zero-dependency line-coverage runner (reference CI uploads codecov,
/root/reference/.github/workflows/build-test.yaml:70-73; this sandbox has
no coverage/pytest-cov baked in, so the local gate uses CPython 3.12's
sys.monitoring (PEP 669) — near-zero overhead because every (code, line)
location disables itself after its first hit.  CI additionally runs real
pytest-cov, see .github/workflows/build-test.yaml).

Usage:
    python scripts/cov.py [--min-pct N] [pytest args...]  # default: tests/ -q

`--min-pct N` (or env COV_MIN=N) makes the run FAIL when total coverage
drops below N percent — the enforced floor scripts/check.sh gates on
(VERDICT round 5: a coverage reporter nobody gates on regresses
silently).

Writes COVERAGE.json ({"total_pct": ..., "files": {...}}) and prints a
per-package summary.  Lines executed only in subprocesses (the CLI e2e
tests spawn `python -m spicedb_kubeapi_proxy_tpu`) are not counted —
the number is a floor.
"""

import ast
import json
import os
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
PKG = str(REPO / "spicedb_kubeapi_proxy_tpu")

executed: dict = {}   # filename -> set of line numbers


def _on_line(code, line):
    fn = code.co_filename
    if fn.startswith(PKG):
        executed.setdefault(fn, set()).add(line)
    return sys.monitoring.DISABLE  # one hit per location is enough


def install():
    mon = sys.monitoring
    mon.use_tool_id(mon.COVERAGE_ID, "spicedb-tpu-cov")
    mon.register_callback(mon.COVERAGE_ID, mon.events.LINE, _on_line)
    mon.set_events(mon.COVERAGE_ID, mon.events.LINE)


def executable_lines(path: Path) -> set:
    """Approximate executable lines: every statement node's first line
    (matches what the LINE event reports for straight-line code; doc-
    strings and blank/comment lines are excluded by construction)."""
    tree = ast.parse(path.read_text())
    out: set = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.stmt):
            lineno = node.lineno
            # a def/class statement's body counts separately; the header
            # line itself executes (binding), so keep it
            if (isinstance(node, ast.Expr)
                    and isinstance(node.value, ast.Constant)
                    and isinstance(node.value.value, str)):
                continue  # docstring
            out.add(lineno)
    return out


def report() -> dict:
    files = {}
    tot_exec = tot_hit = 0
    for py in sorted(Path(PKG).rglob("*.py")):
        ex = executable_lines(py)
        if not ex:
            continue
        hit = executed.get(str(py), set()) & ex
        rel = str(py.relative_to(REPO))
        files[rel] = {"executable": len(ex), "covered": len(hit),
                      "pct": round(100.0 * len(hit) / len(ex), 1)}
        tot_exec += len(ex)
        tot_hit += len(hit)
    total = round(100.0 * tot_hit / max(1, tot_exec), 1)
    out = {"total_pct": total, "executable_lines": tot_exec,
           "covered_lines": tot_hit, "files": files,
           "note": "sys.monitoring line coverage; subprocess execution "
                   "(CLI e2e) not counted — treat as a floor"}
    (REPO / "COVERAGE.json").write_text(json.dumps(out, indent=1))
    return out


def main():
    os.chdir(REPO)
    # pytest.main() from this script does not put the repo root on
    # sys.path the way `python -m pytest` does
    sys.path.insert(0, str(REPO))
    args = sys.argv[1:]
    try:
        min_pct = float(os.environ.get("COV_MIN", "0") or 0)
    except ValueError:
        print(f"error: COV_MIN={os.environ['COV_MIN']!r} is not numeric",
              file=sys.stderr)
        return 2
    if "--min-pct" in args:
        i = args.index("--min-pct")
        try:
            min_pct = float(args[i + 1])
        except (IndexError, ValueError):
            print("error: --min-pct requires a numeric value",
                  file=sys.stderr)
            return 2
        del args[i: i + 2]
    import pytest
    if not hasattr(sys, "monitoring"):
        # pre-3.12 interpreter (no PEP 669): run the suite without
        # coverage instead of crashing; the floor can't be enforced here
        # (CI runs 3.12+ and does enforce it)
        print("cov.py: sys.monitoring unavailable on "
              f"Python {sys.version_info.major}.{sys.version_info.minor}; "
              "running tests without coverage (gate skipped)",
              file=sys.stderr)
        return pytest.main(args or ["tests/", "-q"])
    install()
    rc = pytest.main(args or ["tests/", "-q"])
    sys.monitoring.set_events(sys.monitoring.COVERAGE_ID, 0)
    out = report()
    worst = sorted(out["files"].items(), key=lambda kv: kv[1]["pct"])[:10]
    print("\n== coverage (sys.monitoring floor; subprocesses uncounted)")
    for rel, st in worst:
        print(f"  {st['pct']:5.1f}%  {rel} "
              f"({st['covered']}/{st['executable']})")
    print(f"TOTAL {out['total_pct']}% "
          f"({out['covered_lines']}/{out['executable_lines']} lines) "
          f"-> COVERAGE.json")
    if min_pct and out["total_pct"] < min_pct:
        print(f"coverage gate: TOTAL {out['total_pct']}% is below the "
              f"enforced minimum {min_pct}%", file=sys.stderr)
        return rc or 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
