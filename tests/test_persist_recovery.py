"""Crash-recovery parity for the durable store (spicedb/persist).

The contract under test (ISSUE 4 acceptance): with persistence enabled,
a crash at ANY injected failpoint followed by a restart yields a store
whose full read-set, revision counter, and jax-backend check/lookup
answers are identical to an uninterrupted host-oracle run of the same
update stream prefix.  Plus: dual-write recovery coordination (WAL
idempotency keys let a replayed activity detect an already-applied
SpiceDB write) and expiring tuples surviving a restart into the
decision-cache expiry heap.
"""

import asyncio
import json
import os
import tempfile

import pytest

from spicedb_kubeapi_proxy_tpu.authz.distributedtx.client import (
    setup_workflow_engine,
)
from spicedb_kubeapi_proxy_tpu.authz.distributedtx.workflow import (
    STRATEGY_PESSIMISTIC,
    _collect_updates,
    _lock_update,
)
from spicedb_kubeapi_proxy_tpu.kubefake.apiserver import FakeKubeApiServer
from spicedb_kubeapi_proxy_tpu.proxy.httpcore import HandlerTransport
from spicedb_kubeapi_proxy_tpu.spicedb import schema as sch
from spicedb_kubeapi_proxy_tpu.spicedb.decision_cache import (
    DecisionCacheEndpoint,
)
from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import (
    Bootstrap,
    EmbeddedEndpoint,
    merge_internal_definitions,
)
from spicedb_kubeapi_proxy_tpu.spicedb.persist import PersistenceManager
from spicedb_kubeapi_proxy_tpu.spicedb.store import TupleStore
from spicedb_kubeapi_proxy_tpu.spicedb.types import (
    CheckRequest,
    ObjectRef,
    RelationshipUpdate,
    SubjectRef,
    UpdateOp,
    parse_relationship,
)
from spicedb_kubeapi_proxy_tpu.utils import failpoints

SCHEMA = """
definition user {}
definition doc {
  relation viewer: user
  relation owner: user
  permission view = viewer + owner
}
"""

BOOT = "\n".join(
    [f"doc:d{i}#viewer@user:u{i % 5}" for i in range(40)]
    + [f"doc:d{i}#owner@user:u{(i + 1) % 5}" for i in range(0, 40, 4)])


@pytest.fixture(autouse=True)
def reset_failpoints():
    failpoints.disable_all()
    yield
    failpoints.disable_all()


@pytest.fixture()
def tmpdir():
    with tempfile.TemporaryDirectory() as d:
        yield d


def stream_batch(i):
    """Deterministic update stream: batch i is a pure function of i."""
    ups = []
    for j in range(4):
        n = (i * 13 + j * 7) % 50
        rel = parse_relationship(f"doc:d{n}#viewer@user:u{(i + j) % 5}")
        op = UpdateOp.DELETE if (i + j) % 3 == 0 else UpdateOp.TOUCH
        ups.append(RelationshipUpdate(op, rel))
    return ups


def oracle_at(revision):
    """Uninterrupted host replay of the stream up to `revision`
    (bootstrap is revision 1; batch i commits revision i + 1)."""
    store = TupleStore()
    store.bulk_load_text(BOOT)
    for i in range(1, revision):
        store.write(stream_batch(i))
    assert store.revision == revision
    return store


def rels_of(store):
    return sorted(r.rel_string() for r in store.read(None))


WAL_FAILPOINTS = ["walBeforeAppend", "walAfterAppend"]
CKPT_FAILPOINTS = ["checkpointBeforeRename", "manifestBeforeRename"]


class TestFailpointCrashParity:
    @pytest.mark.parametrize("failpoint", WAL_FAILPOINTS)
    @pytest.mark.parametrize("arm_at", [3, 9])
    def test_crash_mid_write_stream(self, tmpdir, failpoint, arm_at):
        mgr = PersistenceManager(tmpdir, fsync="always")
        store = mgr.recover()
        mgr.attach(store)
        store.bulk_load_text(BOOT)
        crashed = False
        for i in range(1, 13):
            if i == arm_at:
                failpoints.enable_failpoint(failpoint, 1)
            try:
                store.write(stream_batch(i))
            except failpoints.FailPointPanic:
                crashed = True
                break
        assert crashed
        failpoints.disable_all()
        # restart: whatever revision is recovered must match the
        # uninterrupted oracle replay of exactly that prefix
        s2 = PersistenceManager(tmpdir).recover()
        assert s2.revision in (arm_at, arm_at + 1)
        assert rels_of(s2) == rels_of(oracle_at(s2.revision))

    @pytest.mark.parametrize("failpoint", CKPT_FAILPOINTS)
    def test_crash_mid_checkpoint_loses_nothing(self, tmpdir, failpoint):
        mgr = PersistenceManager(tmpdir, fsync="always")
        store = mgr.recover()
        mgr.attach(store)
        store.bulk_load_text(BOOT)
        for i in range(1, 6):
            store.write(stream_batch(i))
        failpoints.enable_failpoint(failpoint, 1)
        with pytest.raises(failpoints.FailPointPanic):
            mgr.checkpoint()
        failpoints.disable_all()
        s2 = PersistenceManager(tmpdir).recover()
        assert s2.revision == store.revision
        assert rels_of(s2) == rels_of(oracle_at(s2.revision))

    def test_recovered_jax_answers_match_oracle(self, tmpdir):
        """The acceptance bar: after a crash + restart, the jax backend
        on the recovered store answers check AND lookup_resources
        identically to the host oracle over the uninterrupted stream."""
        mgr = PersistenceManager(tmpdir, fsync="always")
        store = mgr.recover()
        mgr.attach(store)
        store.bulk_load_text(BOOT)
        failpoints.enable_failpoint("walAfterAppend", 1)
        for i in range(1, 8):
            try:
                store.write(stream_batch(i))
            except failpoints.FailPointPanic:
                break
        failpoints.disable_all()

        from spicedb_kubeapi_proxy_tpu.ops.jax_endpoint import JaxEndpoint

        s2 = PersistenceManager(tmpdir).recover()
        schema = merge_internal_definitions(sch.parse_schema(SCHEMA))
        jax_ep = JaxEndpoint(schema, store=s2)
        jax_ep.warm_start()
        assert jax_ep.stats["rebuilds"] == 1  # warm: no lazy first-query build
        oracle_ep = EmbeddedEndpoint(
            merge_internal_definitions(sch.parse_schema(SCHEMA)),
            store=oracle_at(s2.revision))

        async def compare():
            subjects = [SubjectRef("user", f"u{k}") for k in range(5)]
            reqs = [CheckRequest(ObjectRef("doc", f"d{n}"), "view", s)
                    for n in range(0, 50, 3) for s in subjects]
            got = await jax_ep.check_bulk_permissions(reqs)
            want = await oracle_ep.check_bulk_permissions(reqs)
            for r, g, w in zip(reqs, got, want):
                assert g.permissionship == w.permissionship, r
                assert g.checked_at == s2.revision
            for s in subjects:
                g = sorted(await jax_ep.lookup_resources("doc", "view", s))
                w = sorted(await oracle_ep.lookup_resources("doc", "view", s))
                assert g == w, s
        asyncio.run(compare())


class TestDualWriteRecoveryCoordination:
    def test_replayed_activity_detects_applied_write(self, tmpdir):
        """Crash mid-dualwrite-commit: the SpiceDB write (and its
        idempotency key) landed and went through the WAL, but the
        workflow instance never journaled the activity completion.
        After restart, the pending instance replays against the
        RECOVERED store: the lock precondition fails, the idempotency
        key proves the write already applied, and the workflow
        converges without double-writing (activity.py:62-74)."""
        kube = FakeKubeApiServer()
        db = os.path.join(tmpdir, "dtx.sqlite")
        data_dir = os.path.join(tmpdir, "store")

        write_input = {
            "verb": "create", "request_uri": "/api/v1/namespaces",
            "request_path": "/api/v1/namespaces", "request_name": "",
            "api_group": "", "resource": "namespaces", "headers": {},
            "user_name": "alice", "object_name": "revived",
            "body": json.dumps({"metadata": {"name": "revived"}}),
            "probe_uri": "/api/v1/namespaces/revived",
            "creates": ["namespace:revived#creator@user:alice"],
            "touches": [], "deletes": [], "preconditions": [],
            "delete_by_filter": [],
        }
        boot = Bootstrap()  # default schema: namespace/lock/workflow defs

        async def crashed_process():
            mgr = PersistenceManager(data_dir, fsync="always")
            store = mgr.recover()
            mgr.attach(store)
            ep = EmbeddedEndpoint.from_bootstrap(boot, store=store)
            engine, _ = setup_workflow_engine(ep, HandlerTransport(kube), db)
            # the instance is journaled, then the process dies INSIDE
            # write_to_spicedb: after the endpoint write committed (and
            # hit the WAL) but before the activity completion journaled
            engine.journal.create_instance("inst-1", STRATEGY_PESSIMISTIC,
                                           write_input)
            lock_rel, lock_pre = _lock_update(write_input, "inst-1")
            handler_fn = engine._activities["write_to_spicedb"]
            failpoints.enable_failpoint("panicSpiceDBWriteResp", 1)
            with pytest.raises(failpoints.FailPointPanic):
                await handler_fn(
                    {"updates": _collect_updates(write_input) + [lock_rel],
                     "preconditions": [lock_pre]}, "inst-1")
            failpoints.disable_all()
            rels = {r.rel_string() for r in store.read(None)}
            assert "namespace:revived#creator@user:alice" in rels
        asyncio.run(crashed_process())

        async def restarted_process():
            mgr = PersistenceManager(data_dir, fsync="always")
            store = mgr.recover()
            assert mgr.recovery_info["idempotency_keys"] == 1
            mgr.attach(store)
            ep = EmbeddedEndpoint.from_bootstrap(boot, store=store)
            engine, _ = setup_workflow_engine(ep, HandlerTransport(kube), db)
            assert await engine.run_pending_once() == 1
            rec = engine.journal.get_instance("inst-1")
            assert rec.status == "completed", rec.error
            assert rec.result["status_code"] == 201
            assert "revived" in kube.objects[("", "v1", "namespaces")][""]
            rels = [r.rel_string() for r in store.read(None)]
            # applied exactly once, lock cleaned up
            assert rels.count("namespace:revived#creator@user:alice") == 1
            assert not any(r.startswith("lock:") for r in rels)
        asyncio.run(restarted_process())


class TestExpirySurvivesRestart:
    def test_pre_crash_expiration_fires_after_recovery(self, tmpdir):
        """A tuple written pre-crash with an expiration must expire (and
        invalidate decision-cache entries) on time post-recovery: the
        recovered store's expiry_schedule() reseeds the cache heap."""
        clk = [1000.0]
        mgr = PersistenceManager(tmpdir, fsync="always",
                                 clock=lambda: clk[0])
        store = mgr.recover()
        mgr.attach(store)
        store.bulk_load_text("doc:keep#viewer@user:u1")
        store.write([RelationshipUpdate(UpdateOp.TOUCH, parse_relationship(
            "doc:fleeting#viewer@user:u1[expiration:1500]"))])
        # crash + restart (same clock source)
        mgr2 = PersistenceManager(tmpdir, clock=lambda: clk[0])
        s2 = mgr2.recover()
        sched = s2.expiry_schedule()
        assert [(e, k) for e, k in sched] == [(1500.0, ("doc", "viewer"))]
        ep = DecisionCacheEndpoint(EmbeddedEndpoint(
            merge_internal_definitions(sch.parse_schema(SCHEMA)), store=s2))

        async def go():
            subject = SubjectRef("user", "u1")
            got = sorted(await ep.lookup_resources("doc", "view", subject))
            assert got == ["fleeting", "keep"]
            # warm hit while the tuple is still live
            assert sorted(await ep.lookup_resources(
                "doc", "view", subject)) == got
            assert ep.cache.stats["hits"] >= 1
            # cross the expiry instant: the heap seeded from the
            # RECOVERED store invalidates the cached frontier
            clk[0] = 1600.0
            got2 = sorted(await ep.lookup_resources("doc", "view", subject))
            assert got2 == ["keep"]
            assert ep.cache.stats["invalidations"] >= 1
        asyncio.run(go())

    def test_expiry_survives_via_checkpoint_too(self, tmpdir):
        clk = [1000.0]
        mgr = PersistenceManager(tmpdir, fsync="never",
                                 clock=lambda: clk[0])
        store = mgr.recover()
        mgr.attach(store)
        store.bulk_load_text("doc:keep#viewer@user:u1\n"
                             "doc:fleeting#viewer@user:u1[expiration:1500]")
        mgr.checkpoint()
        s2 = PersistenceManager(tmpdir, clock=lambda: clk[0]).recover()
        assert s2.expiry_schedule() == [(1500.0, ("doc", "viewer"))]
        clk[0] = 1600.0
        assert rels_of(s2) == ["doc:keep#viewer@user:u1"]
