"""CachingRESTMapper concurrency + cache discipline (reference
pkg/proxy/restmapper_test.go:108-179: the discovery mapper is not
concurrency-safe, so the wrapper must serialize it; GVR->GVK hits are
memoized with a TTL; errors are never cached)."""

import asyncio
import json

import pytest

from spicedb_kubeapi_proxy_tpu.proxy.httpcore import Request, Response, Transport
from spicedb_kubeapi_proxy_tpu.proxy.restmapper import (
    CachingRESTMapper,
    NoKindMatchError,
)


class CountingDiscovery(Transport):
    """Fake discovery endpoint that records concurrency and call counts."""

    def __init__(self, fail_times: int = 0, delay: float = 0.01):
        self.calls = 0
        self.in_flight = 0
        self.max_in_flight = 0
        self.fail_times = fail_times
        self.delay = delay

    async def round_trip(self, req: Request) -> Response:
        self.calls += 1
        self.in_flight += 1
        self.max_in_flight = max(self.max_in_flight, self.in_flight)
        try:
            await asyncio.sleep(self.delay)
            if self.fail_times > 0:
                self.fail_times -= 1
                return Response(status=503)
            return Response(status=200, body=json.dumps({
                "resources": [{"name": "pods", "kind": "Pod"},
                              {"name": "services", "kind": "Service"}],
            }).encode())
        finally:
            self.in_flight -= 1


class TestConcurrency:
    def test_concurrent_lookups_serialize_discovery(self):
        """100 concurrent kind_for calls: the non-concurrency-safe
        discovery transport must never see overlapping requests, and the
        cache must collapse them into one call."""
        disc = CountingDiscovery()
        mapper = CachingRESTMapper(disc)

        async def go():
            out = await asyncio.gather(
                *[mapper.kind_for("", "v1", "pods") for _ in range(100)])
            assert all(g.kind == "Pod" for g in out)
        asyncio.run(go())
        assert disc.max_in_flight == 1  # serialized
        assert disc.calls == 1          # cached after the first

    def test_mixed_keys_under_concurrency(self):
        disc = CountingDiscovery()
        mapper = CachingRESTMapper(disc)

        async def go():
            out = await asyncio.gather(
                *[mapper.kind_for("", "v1",
                                  "pods" if i % 2 else "services")
                  for i in range(50)])
            kinds = {g.kind for g in out}
            assert kinds == {"Pod", "Service"}
        asyncio.run(go())
        assert disc.max_in_flight == 1
        assert disc.calls == 2  # one discovery per distinct GVR


class TestCacheDiscipline:
    def test_errors_never_cached(self):
        """A failed discovery must not poison the cache: the next call
        retries and succeeds (reference restmapper.go 'never cache
        errors')."""
        disc = CountingDiscovery(fail_times=1)
        mapper = CachingRESTMapper(disc)

        async def go():
            with pytest.raises(NoKindMatchError):
                await mapper.kind_for("", "v1", "pods")
            gvk = await mapper.kind_for("", "v1", "pods")
            assert gvk.kind == "Pod"
        asyncio.run(go())
        assert disc.calls == 2

    def test_ttl_expiry_refetches(self):
        now = [0.0]
        disc = CountingDiscovery()
        mapper = CachingRESTMapper(disc, ttl=10.0, clock=lambda: now[0])

        async def go():
            await mapper.kind_for("", "v1", "pods")
            await mapper.kind_for("", "v1", "pods")
            assert disc.calls == 1  # within TTL
            now[0] = 11.0
            await mapper.kind_for("", "v1", "pods")
            assert disc.calls == 2  # expired -> refetched
        asyncio.run(go())

    def test_invalidate_clears(self):
        disc = CountingDiscovery()
        mapper = CachingRESTMapper(disc)

        async def go():
            await mapper.kind_for("", "v1", "pods")
            mapper.invalidate()
            await mapper.kind_for("", "v1", "pods")
        asyncio.run(go())
        assert disc.calls == 2

    def test_unknown_resource_raises(self):
        disc = CountingDiscovery()
        mapper = CachingRESTMapper(disc)

        async def go():
            with pytest.raises(NoKindMatchError):
                await mapper.kind_for("", "v1", "widgets")
        asyncio.run(go())
