"""Cross-request batched dispatch (SURVEY.md §2, parallelism table).

The reference fans each HTTP request's checks into one
`CheckBulkPermissions` RPC (pkg/authz/check.go:23-48) but batches only
*within* a request.  On TPU the batch IS the kernel invocation, so this
wrapper also coalesces across concurrent requests: concurrent
check/LookupResources callers enqueue work, and a drain loop issues fused
calls to the inner endpoint.

Policy ("natural batching"): when no inner call is in flight, the queue
flushes immediately — single-caller latency is one kernel call, same as
direct dispatch.  While a call is in flight, new arrivals accumulate and go
out together on the next drain, so high concurrency (BASELINE config 5: 256
simultaneous list requests) produces device-sized batches without a tuning
knob.  `max_batch` caps one drain's fused size.

Failure isolation: if a fused inner call raises, each member request is
retried individually so one malformed query (e.g. unknown definition, which
the endpoint surfaces as an error like the reference does) cannot poison
unrelated co-batched callers.

Pipelining (`pipeline_depth`, docs/performance.md "Device-resident
pipeline"): when the inner endpoint exposes two-phase start/finish pairs
(jax://), the drain loop keeps up to depth-1 started fused batches in
flight — checks and lookups both — so the host encode + upload + kernel
dispatch of batch N+1 overlap batch N's device execution and async D2H
readback.  The DevicePipeline feature gate is the killswitch.
"""

from __future__ import annotations

import asyncio
import collections
import contextlib
import os
import time
from typing import Iterable, Optional

from ..utils import admission, devtel, timeline, tracing
from ..utils.failpoints import fail_point
from .endpoints import PermissionsEndpoint
from .store import Watcher
from .types import (
    CheckRequest,
    Precondition,
    RelationshipFilter,
    RelationshipUpdate,
    SubjectRef,
)


# regression-sentinel proof hook (scripts/check.sh): a per-drain sleep
# armed via env var injects a deterministic slowdown into the dispatch
# hot loop so the benchdiff gate can be shown to catch one.  Read once
# at import; 0 in any real deployment.
_BENCHDIFF_INJECT_S = (
    float(os.environ.get("SPICEDB_TPU_BENCHDIFF_INJECT_MS", "0") or 0)
    / 1e3)


def _trace_ctx() -> Optional[dict]:
    """Per-caller dispatch trace context, captured at enqueue time; the
    drain loop stamps exec_start/exec_end into it so the caller can
    attribute queue wait separately from fused execution.  None when the
    request is untraced (zero overhead)."""
    trace = tracing.current_trace()
    if trace is None:
        return None
    return {"trace": trace, "enq": time.perf_counter()}


def _record_waiter_spans(tc: Optional[dict]) -> None:
    """queue_wait (enqueue -> drain pickup) and execute (fused inner
    call, kernel included) phase spans for one dispatch caller."""
    if not tc:
        return
    now = time.perf_counter()
    exec_start = tc.get("exec_start", now)
    trace = tc["trace"]
    trace.add_span("queue_wait", tc["enq"], exec_start, phase=True)
    trace.add_span("execute", exec_start, tc.get("exec_end", now), phase=True)


def _mark_exec_start(waiters: list) -> None:
    t0 = time.perf_counter()
    for w in waiters:
        if w[2] is not None:
            w[2].setdefault("exec_start", t0)


def _mark_exec_end(waiters: list) -> None:
    t1 = time.perf_counter()
    for w in waiters:
        if w[2] is not None:
            w[2]["exec_end"] = t1


def _follow(leader: asyncio.Future, loop) -> asyncio.Future:
    """A caller-facing future mirroring an internal singleflight leader.
    The leader is never handed to callers, so one caller's cancellation
    can never poison the co-flighted others; results are shared (the
    fused extract path already hands the SAME id list to every waiter of
    a column, so sharing is the established contract)."""
    fut = loop.create_future()

    def _copy(lf: asyncio.Future) -> None:
        if fut.done():
            return
        if lf.cancelled():
            fut.set_exception(
                RuntimeError("singleflight leader cancelled"))
        elif lf.exception() is not None:
            fut.set_exception(lf.exception())
        else:
            fut.set_result(lf.result())

    leader.add_done_callback(_copy)
    return fut


@contextlib.contextmanager
def _activate_batch_trace(waiters: list):
    """Activate the co-batched callers' traces (fanned out) for the
    duration of a fused inner call, so spans the backend records (e.g.
    jax:// kernel spans) land in EVERY member request's trace.

    Always overrides the contextvar — the drain task was created from
    some caller's _kick() and INHERITED that caller's trace context, so
    an all-untraced batch must actively null the sink or its kernel
    spans would leak into the unrelated kicking request's trace."""
    traces: list = []
    seen: set = set()
    for w in waiters:
        tc = w[2]
        if tc is not None and id(tc["trace"]) not in seen:
            seen.add(id(tc["trace"]))
            traces.append(tc["trace"])
    sink = (None if not traces
            else traces[0] if len(traces) == 1
            else tracing.FanoutTrace(traces))
    token = tracing.activate(sink)
    try:
        yield
    finally:
        tracing.deactivate(token)


class BatchingEndpoint(PermissionsEndpoint):
    # Retry-After hint on queue-bound rejections: one drain cycle is the
    # natural unit of backoff (the queue that rejected will have turned
    # over at least once by then)
    RETRY_AFTER_S = 1.0

    def __init__(self, inner: PermissionsEndpoint, max_batch: int = 4096,
                 pipeline_depth: int = 2, max_queue_depth: int = 0):
        if max_batch < 1:
            raise ValueError(f"max_batch must be >= 1, got {max_batch}")
        if pipeline_depth < 1:
            raise ValueError(
                f"pipeline_depth must be >= 1, got {pipeline_depth}")
        if max_queue_depth < 0:
            raise ValueError(
                f"max_queue_depth must be >= 0, got {max_queue_depth}")
        self.inner = inner
        self.max_batch = max_batch
        # admission control (utils/admission.py, --max-queue-depth):
        # bound on EACH of the check and LR queues; an enqueue that
        # would exceed it raises AdmissionRejectedError instead of
        # queueing (0 = unbounded, the pre-admission behavior).
        # Exempt callers (dual-write authorization, admission.exempt())
        # and singleflight followers (they add no queue entry) always
        # admit.
        self.max_queue_depth = max_queue_depth
        if max_queue_depth:
            # only a configured bound publishes the gauge: endpoints
            # constructed later with the default 0 (bench sweeps, test
            # fixtures) must not reset the serving proxy's exported
            # limit to "unbounded"
            admission.set_queue_limit(max_queue_depth)
        # fused batches allowed in flight at once (device-resident
        # pipeline, --pipeline-depth): depth N keeps N-1 STARTED batches
        # pending, so batch N+1's host encode + H2D upload + kernel
        # dispatch overlap batch N's device execution and async D2H
        # readback.  1 = fully serial; the DevicePipeline feature gate
        # off reproduces the pre-pipeline behavior (single-slot lookup
        # window, serial checks) regardless of depth.
        self.pipeline_depth = pipeline_depth
        # waiters are (item, Future, trace-ctx-or-None) triples
        self._check_queue: list = []   # [(CheckRequest, Future, tc)]
        self._lr_queue: dict = {}      # (type, perm) -> [(SubjectRef, Future, tc)]
        # fair service order across LR keys: every queued (type, perm)
        # key appears exactly once; the drain serves the head and a key
        # with remaining waiters rejoins at the TAIL, so one hot lookup
        # key cannot monopolize the drain while others starve
        self._lr_rotation: collections.deque = collections.deque()
        # live LR queue depth (all keys), maintained incrementally so
        # the admission bound check stays O(1) per enqueue
        self._lr_depth = 0
        # in-flight singleflight index: (type, perm, subject) -> the
        # QUEUED leader future.  Entries are removed at drain pickup, so
        # arrivals during execution start a fresh query (a write may have
        # committed since the executing batch drained deltas, and a later
        # arrival must observe it — full consistency).
        self._lr_pending: dict = {}
        # per-pending-key follower counts: how many duplicate callers a
        # queued leader collapsed, drained into the batch-occupancy
        # histogram (utils/devtel.py) at pickup
        self._sf_counts: dict = {}
        self._inflight: list = []      # waiters of the batch being executed
        self._drain_task: Optional[asyncio.Task] = None
        # explain_bypass pre-seeded so InstrumentedEndpoint's one-shot
        # gauge registration sees the key
        self._stats = {"drains": 0, "fused_checks": 0, "fused_lookups": 0,
                       "max_fused_batch": 0, "explain_bypass": 0,
                       "singleflight_hits": 0, "admission_rejected": 0}

    def queue_depth(self) -> int:
        """Total queued (not in-flight) entries across both queues —
        O(1), allocation-free; the load shedder's door check reads this
        on every read-only request (proxy/server.py)."""
        return len(self._check_queue) + self._lr_depth

    @property
    def stats(self) -> dict:
        """Own dispatch counters merged over the inner backend's stats,
        plus live queue-depth / current-fused-batch gauges (sampled at
        scrape time through InstrumentedEndpoint's stats callbacks)."""
        inner_stats = getattr(self.inner, "stats", None)
        out = dict(inner_stats) if isinstance(inner_stats, dict) else {}
        out.update(self._stats)
        out["check_queue_depth"] = len(self._check_queue)
        out["lr_queue_depth"] = sum(len(v) for v in self._lr_queue.values())
        out["inflight_batch"] = len(self._inflight)
        out["pipeline_depth"] = self.pipeline_depth
        out["queue_limit"] = self.max_queue_depth
        return out

    # -- queue plumbing ------------------------------------------------------

    def _admit(self, queue_depth: int, adding: int, which: str) -> None:
        """Reject an enqueue that would push `which` queue past the
        bound (fail fast instead of queueing unboundedly).  The bound
        limits BACKLOG, not request size: a bulk arriving at an empty
        queue always admits whole — otherwise any batch larger than the
        bound would be rejected forever, idle or not, and retrying
        could never succeed.  Worst-case resident depth is therefore
        bound + one batch.  Exempt callers — dual-write authorization
        runs under admission.exempt() — always pass, as does everything
        when the AdmissionControl gate (killswitch) is off."""
        if not self.max_queue_depth:
            return
        if queue_depth == 0 or queue_depth + adding <= self.max_queue_depth:
            return
        if admission.is_exempt() or not admission.enabled():
            return
        self._stats["admission_rejected"] += 1
        admission.note_rejected("queue_limit")
        raise admission.AdmissionRejectedError(
            f"{which} queue at depth {queue_depth} (bound "
            f"{self.max_queue_depth}); retry after "
            f"{self.RETRY_AFTER_S:.1f}s",
            reason="queue_limit", retry_after_s=self.RETRY_AFTER_S)

    def _kick(self) -> None:
        if self._drain_task is None or self._drain_task.done():
            self._drain_task = asyncio.get_running_loop().create_task(
                self._drain())

    async def _drain(self) -> None:
        # Pipelined dispatch: when the inner endpoint exposes two-phase
        # start/finish pairs (jax://), batch N+1's kernel is DISPATCHED
        # (start) before batch N's readback+extraction (finish) blocks,
        # so the device computes N+1 while N's result streams to the
        # host.  `pending` holds up to (pipeline_depth - 1) started
        # batches — checks and lookups share the window, finished
        # strictly FIFO — bounding snapshot retention to the depth.
        # With the DevicePipeline gate off the loop reproduces the
        # pre-pipeline behavior exactly: lookups keep the single-slot
        # two-phase window, checks run serially.
        from ..utils.features import pipeline_enabled
        pending: collections.deque = collections.deque()
        two_lr = (hasattr(self.inner, "lookup_resources_batch_start")
                  and hasattr(self.inner, "lookup_resources_batch_finish"))
        two_ck = (hasattr(self.inner, "check_bulk_permissions_start")
                  and hasattr(self.inner, "check_bulk_permissions_finish"))
        if pipeline_enabled():
            window = self.pipeline_depth - 1
            two_lr = two_lr and window > 0
            two_ck = two_ck and window > 0
        else:
            window = 1 if two_lr else 0
            two_ck = False
        try:
            while self._check_queue or self._lr_queue or pending:
                fail_point("dispatchDrain")
                if _BENCHDIFF_INJECT_S > 0:
                    await asyncio.sleep(_BENCHDIFF_INJECT_S)
                self._stats["drains"] += 1
                # alternate which queue goes first each iteration so
                # sustained traffic on one verb cannot push the other
                # behind it in every drain cycle (fairness, half of the
                # hot-key rotation below)
                order = (("ck", "lr") if self._stats["drains"] % 2
                         else ("lr", "ck"))
                for side in order:
                    if side == "ck" and self._check_queue:
                        batch = self._check_queue[: self.max_batch]
                        del self._check_queue[: len(batch)]
                        self._inflight = batch
                        if two_ck:
                            started = await self._start_checks(batch)
                            self._inflight = []
                            if started:
                                pending.append(started)
                        else:
                            await self._run_checks(batch)
                            self._inflight = []
                    elif side == "lr" and self._lr_queue:
                        key, waiters = self._next_lr_key()
                        rest = waiters[self.max_batch:]
                        waiters = waiters[: self.max_batch]
                        if rest:
                            # remainder rejoins at the BACK of the
                            # rotation: a hot key yields the drain to
                            # every other queued key between its batches
                            self._lr_queue[key] = rest
                            self._lr_rotation.append(key)
                        self._lr_depth -= len(waiters)
                        self._unregister_pending(key, waiters)
                        self._inflight = waiters
                        if two_lr:
                            # `started` joins `pending` BEFORE any
                            # blocking finish, so a drain death during
                            # that await still knows about every
                            # started batch
                            started = await self._start_lookups(key, waiters)
                            self._inflight = []
                            if started:
                                pending.append(started)
                        else:
                            await self._run_lookups(key, waiters)
                            self._inflight = []
                while pending and (len(pending) > window
                                   or not (self._check_queue
                                           or self._lr_queue)):
                    fail_point("dispatchDrainBeforeFinish")
                    kind, waiters, started = pending.popleft()
                    self._inflight = waiters
                    if kind == "lr":
                        await self._finish_lookups(waiters, started)
                    else:
                        await self._finish_checks(waiters, started)
                    self._inflight = []
        except BaseException as e:
            # A cancelled/dying drain task must FAIL its waiters — queued,
            # in-flight, and started-but-unfinished — or every caller
            # awaiting a future hangs forever (ADVICE round-5 finding).
            failure = (RuntimeError("batch dispatch drain task cancelled")
                       if isinstance(e, asyncio.CancelledError) else e)
            stranded = list(self._inflight)
            self._inflight = []
            for _kind, ws, _started in pending:
                stranded.extend(ws)
            stranded.extend(self._check_queue)
            del self._check_queue[:]
            for ws in self._lr_queue.values():
                stranded.extend(ws)
            self._lr_queue.clear()
            self._lr_rotation.clear()
            self._lr_depth = 0
            self._lr_pending.clear()
            self._sf_counts.clear()
            for w in stranded:
                if not w[1].done():
                    w[1].set_exception(failure)
            raise

    def _next_lr_key(self) -> tuple:
        """Pop the next (type, perm) key in fair rotation order and its
        full waiter list.  Invariant: a key is in the rotation exactly
        once iff it has a queue entry, so the popleft loop's guard is
        defensive only."""
        while self._lr_rotation:
            key = self._lr_rotation.popleft()
            waiters = self._lr_queue.pop(key, None)
            if waiters is not None:
                return key, waiters
        # defensive resync (should be unreachable): serve dict order
        key = next(iter(self._lr_queue))
        return key, self._lr_queue.pop(key)

    def _unregister_pending(self, key: tuple, waiters: list) -> None:
        """Close the singleflight window for a batch being picked up:
        identical queries arriving from now on must start fresh (the
        batch's delta drain happens at pickup, not at their arrival)."""
        resource_type, permission = key
        collapsed = 0
        for w in waiters:
            k = (resource_type, permission, w[0])
            if self._lr_pending.get(k) is w[1]:
                del self._lr_pending[k]
                collapsed += self._sf_counts.pop(k, 0)
        devtel.OCCUPANCY.note_collapsed(collapsed)

    def _enqueue_lookup(self, resource_type: str, permission: str,
                        subject: SubjectRef, tc,
                        pre_admitted: bool = False) -> asyncio.Future:
        """Queue one lookup, singleflight-deduped: an identical query
        already QUEUED shares its waiter (one kernel column, one cache
        fill upstream) through an internal leader future; the returned
        future is always caller-private (see _follow).  `pre_admitted`:
        lookup_resources_batch already admitted the WHOLE batch — a
        second per-leader check here would reject mid-batch (the
        batch's own leaders raise the depth past the bound), stranding
        the already-enqueued members and breaking the admit-whole
        guarantee."""
        loop = asyncio.get_running_loop()
        k = (resource_type, permission, subject)
        leader = self._lr_pending.get(k)
        if leader is None:
            # only a NEW leader adds queue depth; followers below join
            # an existing column for free, so under overload identical
            # queries collapse instead of rejecting
            if not pre_admitted:
                self._admit(self._lr_depth, 1, "lookup")
            leader = loop.create_future()
            self._lr_pending[k] = leader
            qkey = (resource_type, permission)
            q = self._lr_queue.get(qkey)
            if q is None:
                q = self._lr_queue[qkey] = []
                self._lr_rotation.append(qkey)
            q.append((subject, leader, tc))
            self._lr_depth += 1
        else:
            self._stats["singleflight_hits"] += 1
            self._sf_counts[k] = self._sf_counts.get(k, 0) + 1
        return _follow(leader, loop)

    async def _retry_individually(self, waiters: list, single_call) -> None:
        """Per-member fallback after a fused call failed (concurrently —
        a poison request must not serialize the drain loop) so one
        malformed query can't fail unrelated co-batched callers."""
        async def retry_one(w):
            item, fut, tc = w
            if fut.done():
                return
            # each retry is ONE member's work: activate that member's
            # trace (or none), never the fused batch fanout — gather's
            # tasks copy the ambient context, so this reset is needed
            # even when called inside _activate_batch_trace
            token = tracing.activate(tc["trace"] if tc else None)
            try:
                res = await single_call(item)
            except Exception as e:
                if not fut.done():  # caller may cancel during the await
                    fut.set_exception(e)
            else:
                if not fut.done():
                    fut.set_result(res)
            finally:
                tracing.deactivate(token)

        await asyncio.gather(*[retry_one(w) for w in waiters])

    @staticmethod
    def _resolve(waiters: list, results: list) -> None:
        for w, res in zip(waiters, results):
            if not w[1].done():
                w[1].set_result(res)

    async def _run_fused(self, waiters: list, stat: str, fused_call,
                         single_call) -> None:
        """One fused inner call for `waiters` ([(item, Future, tc)]); on
        failure, retry members individually."""
        items = [w[0] for w in waiters]
        self._stats[stat] += 1
        self._stats["max_fused_batch"] = max(self._stats["max_fused_batch"],
                                            len(items))
        _mark_exec_start(waiters)
        t0 = timeline.now()
        try:
            with _activate_batch_trace(waiters):
                try:
                    results = await fused_call(items)
                except Exception:
                    await self._retry_individually(waiters, single_call)
                    return
            self._resolve(waiters, results)
        finally:
            _mark_exec_end(waiters)
            # dispatcher-track slice: how long this fused call occupied
            # the drain loop (overlaps the device track's kernel slices
            # in the /debug/timeline view)
            timeline.record("fused", "dispatcher", t0, bucket=len(items),
                            kind=stat)

    async def _run_checks(self, batch: list) -> None:
        await self._run_fused(
            batch, "fused_checks",
            self.inner.check_bulk_permissions,
            self.inner.check_permission)

    async def _run_lookups(self, key: tuple, waiters: list) -> None:
        resource_type, permission = key
        await self._run_fused(
            waiters, "fused_lookups",
            lambda subjects: self.inner.lookup_resources_batch(
                resource_type, permission, subjects),
            lambda subject: self.inner.lookup_resources(
                resource_type, permission, subject))

    async def _start_lookups(self, key: tuple, waiters: list):
        """Phase 1 of a pipelined fused lookup: dispatch the kernel +
        async D2H.  On failure, degrade to the classic fused call with
        per-member retry; returns None so the drain loop has nothing to
        finish."""
        resource_type, permission = key
        self._stats["fused_lookups"] += 1
        self._stats["max_fused_batch"] = max(self._stats["max_fused_batch"],
                                            len(waiters))
        _mark_exec_start(waiters)
        t0 = timeline.now()
        try:
            with _activate_batch_trace(waiters):
                ctx = await self.inner.lookup_resources_batch_start(
                    resource_type, permission, [w[0] for w in waiters])
        except Exception:
            self._stats["fused_lookups"] -= 1  # _run_fused recounts
            await self._run_lookups(key, waiters)
            return None
        timeline.record("fused_start", "dispatcher", t0,
                        batch=ctx.get("batch_id") if isinstance(ctx, dict)
                        else None, bucket=len(waiters))
        return ("lr", waiters, (key, ctx))

    async def _start_checks(self, batch: list):
        """Phase 1 of a pipelined fused check: dispatch the kernel +
        async readback.  On failure, degrade to the classic fused call
        with per-member retry; returns None so the drain loop has
        nothing to finish."""
        self._stats["fused_checks"] += 1
        self._stats["max_fused_batch"] = max(self._stats["max_fused_batch"],
                                            len(batch))
        _mark_exec_start(batch)
        t0 = timeline.now()
        try:
            with _activate_batch_trace(batch):
                ctx = await self.inner.check_bulk_permissions_start(
                    [w[0] for w in batch])
        except Exception:
            self._stats["fused_checks"] -= 1  # _run_checks recounts
            await self._run_checks(batch)
            return None
        timeline.record("fused_start", "dispatcher", t0,
                        batch=ctx.get("batch_id") if isinstance(ctx, dict)
                        else None, bucket=len(batch), kind="fused_checks")
        return ("ck", batch, ctx)

    async def _finish_checks(self, waiters: list, ctx) -> None:
        """Phase 2: blocking readback + result assembly; per-member
        retry on failure (same isolation contract as _run_fused)."""
        t0 = timeline.now()
        try:
            with _activate_batch_trace(waiters):
                try:
                    results = await self.inner.check_bulk_permissions_finish(
                        ctx)
                except Exception:
                    await self._retry_individually(
                        waiters, self.inner.check_permission)
                    return
            self._resolve(waiters, results)
        finally:
            _mark_exec_end(waiters)
            timeline.record("fused_finish", "dispatcher", t0,
                            batch=ctx.get("batch_id")
                            if isinstance(ctx, dict) else None,
                            bucket=len(waiters), kind="fused_checks")

    async def _finish_lookups(self, waiters: list, started) -> None:
        """Phase 2: blocking transfer + extraction; per-member retry on
        failure (same isolation contract as _run_fused)."""
        key, ctx = started
        resource_type, permission = key
        t0 = timeline.now()
        try:
            with _activate_batch_trace(waiters):
                try:
                    results = await self.inner.lookup_resources_batch_finish(ctx)
                except Exception:
                    await self._retry_individually(
                        waiters, lambda s: self.inner.lookup_resources(
                            resource_type, permission, s))
                    return
            self._resolve(waiters, results)
        finally:
            _mark_exec_end(waiters)
            timeline.record("fused_finish", "dispatcher", t0,
                            batch=ctx.get("batch_id")
                            if isinstance(ctx, dict) else None,
                            bucket=len(waiters))

    # -- batched verbs -------------------------------------------------------

    async def check_permission(self, req: CheckRequest):
        self._admit(len(self._check_queue), 1, "check")
        tc = _trace_ctx()
        fut = asyncio.get_running_loop().create_future()
        self._check_queue.append((req, fut, tc))
        self._kick()
        try:
            return await fut
        finally:
            _record_waiter_spans(tc)

    async def check_bulk_permissions(self, reqs: list) -> list:
        if not reqs:
            return []
        # admit or reject the bulk WHOLE: partially enqueueing one
        # caller's batch and then rejecting the rest would run half its
        # checks for an answer the caller never sees
        self._admit(len(self._check_queue), len(reqs), "check")
        loop = asyncio.get_running_loop()
        tc = _trace_ctx()  # one shared ctx: the bulk is one caller
        futs = []
        for r in reqs:
            fut = loop.create_future()
            self._check_queue.append((r, fut, tc))
            futs.append(fut)
        self._kick()
        try:
            return list(await asyncio.gather(*futs))
        finally:
            _record_waiter_spans(tc)

    async def lookup_resources(self, resource_type: str, permission: str,
                               subject: SubjectRef) -> list:
        tc = _trace_ctx()
        fut = self._enqueue_lookup(resource_type, permission, subject, tc)
        self._kick()
        try:
            return await fut
        finally:
            _record_waiter_spans(tc)

    async def lookup_resources_batch(self, resource_type: str, permission: str,
                                     subjects: list) -> list:
        if not subjects:
            return []
        # whole-batch admission (conservative: duplicates that would
        # collapse into followers still count) — enqueueing half a
        # caller's batch then rejecting the rest wastes kernel lanes on
        # an answer the caller never sees
        self._admit(self._lr_depth, len(subjects), "lookup")
        tc = _trace_ctx()  # one shared ctx: the batch is one caller
        futs = [self._enqueue_lookup(resource_type, permission, s, tc,
                                     pre_admitted=True)
                for s in subjects]
        self._kick()
        try:
            return list(await asyncio.gather(*futs))
        finally:
            _record_waiter_spans(tc)

    # -- passthrough verbs ---------------------------------------------------

    def explain_check(self, resource, permission, subject):
        """Witness capture bypasses the fused queue: an explain is a
        targeted re-check on a rare debug path — co-batching it would
        make the captured iterate depend on whatever requests it fused
        with, and a queue backlog would stall the audit event it feeds."""
        self._stats["explain_bypass"] += 1
        fn = getattr(self.inner, "explain_check", None)
        if fn is not None:
            return fn(resource, permission, subject)
        from ..authz.explain import witness_for
        return witness_for(self.inner, resource, permission, subject)

    async def read_relationships(self, flt: RelationshipFilter) -> list:
        return await self.inner.read_relationships(flt)

    async def write_relationships(self, updates: Iterable[RelationshipUpdate],
                                  preconditions: Iterable[Precondition] = ()) -> int:
        return await self.inner.write_relationships(updates, preconditions)

    async def delete_relationships(self, flt: RelationshipFilter,
                                   preconditions: Iterable[Precondition] = ()) -> int:
        return await self.inner.delete_relationships(flt, preconditions)

    def watch(self, object_types=None) -> Watcher:
        return self.inner.watch(object_types)

    async def close(self) -> None:
        task = self._drain_task
        if task is not None and not task.done():
            await task
        await self.inner.close()

    def __getattr__(self, name):
        return getattr(self.inner, name)
