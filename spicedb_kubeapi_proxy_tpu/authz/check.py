"""Bulk permission-check runner (reference pkg/authz/check.go).

All Check/PostCheck templates across the matched rules resolve to
relationships and are checked concurrently per-expression; each expression's
relationships go through one CheckBulkPermissions call and every item must
be HAS_PERMISSION.
"""

from __future__ import annotations

from ..rules.engine import ResolveInput
from ..spicedb.endpoints import PermissionsEndpoint
from ..spicedb.types import CheckRequest, ObjectRef, SubjectRef


class UnauthorizedError(Exception):
    pass


def check_request_from_rel(rel) -> CheckRequest:
    return CheckRequest(
        resource=ObjectRef(rel.resource_type, rel.resource_id),
        permission=rel.resource_relation,
        subject=SubjectRef(rel.subject_type, rel.subject_id,
                           rel.subject_relation),
    )


async def check_relationships(endpoint: PermissionsEndpoint, resolved_rels: list,
                              check_type: str) -> None:
    """One bulk check; all must pass (reference check.go:18-72)."""
    if not resolved_rels:
        return
    reqs = [check_request_from_rel(rel) for rel in resolved_rels]
    results = await endpoint.check_bulk_permissions(reqs)
    for rel, result in zip(resolved_rels, results):
        if not result.allowed:
            raise UnauthorizedError(
                f"bulk {check_type} failed for {rel.rel_string()}")


async def _run_exprs(endpoint: PermissionsEndpoint, rules_list: list,
                     input: ResolveInput, attr: str, check_type: str) -> None:
    # All templates across all matched rules resolve first, then fold into
    # ONE CheckBulkPermissions call for the whole request (reference
    # check.go:23-48 collects every checkRel before the single bulk RPC).
    resolved = [rel
                for r in rules_list
                for expr in getattr(r, attr)
                for rel in expr.generate_relationships(input)]
    await check_relationships(endpoint, resolved, check_type)


async def run_all_matching_checks(endpoint: PermissionsEndpoint,
                                  matching_rules: list,
                                  input: ResolveInput) -> None:
    await _run_exprs(endpoint, matching_rules, input, "checks", "check")


async def run_all_matching_post_checks(endpoint: PermissionsEndpoint,
                                       matching_rules: list,
                                       input: ResolveInput) -> None:
    await _run_exprs(endpoint, matching_rules, input, "post_checks", "postcheck")
