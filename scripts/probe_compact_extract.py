"""A/B probe (real TPU): full packed-word transfer vs device-side
compact extraction (unpack -> per-column nonzero -> flat indices).

The fused 256-subject lookup on multitenant-1m transfers [L=200k, W=8]
uint32 = 6.4 MB through the ~20 MB/s tunnel (~320 ms).  Total set bits
are ~512k -> flat indices = 2 MB.  If the extract-jit + smaller
transfer wins, the endpoint grows a compact lookup path.

Run:  PYTHONPATH=/root/repo python scripts/probe_compact_extract.py
(no JAX_PLATFORMS override: uses the axon TPU backend)
"""

import sys
import time

sys.path.insert(0, "/root/repo")

import jax
import jax.numpy as jnp
import numpy as np

from spicedb_kubeapi_proxy_tpu.models import workloads as wl
from spicedb_kubeapi_proxy_tpu.ops.jax_endpoint import JaxEndpoint
from spicedb_kubeapi_proxy_tpu.spicedb import schema as sch
from spicedb_kubeapi_proxy_tpu.spicedb.types import SubjectRef, parse_relationship


def main():
    print("devices:", jax.devices(), flush=True)
    w = wl.multitenant_1m()
    schema = sch.parse_schema(w.schema_text)
    ep = JaxEndpoint(schema)
    t0 = time.perf_counter()
    ep.store.bulk_load([parse_relationship(r) for r in w.relationships])
    print(f"load {time.perf_counter()-t0:.1f}s", flush=True)

    subjects = [SubjectRef("user", w.subjects[i]) for i in range(256)]
    with ep._lock:
        graph = ep._current_graph()
        q_arr, cols, _ = ep._encode_subjects(graph, subjects)
        snap = graph.snapshot()
    rng = graph.prog.slot_range(w.resource_type, w.permission)
    n_words = max(1, len(q_arr) // 32)
    print(f"slot range {rng}, n_words {n_words}", flush=True)

    t0 = time.perf_counter()
    packed_dev = graph.run_lookup_packed(rng[0], rng[1], q_arr, snap=snap)
    packed_dev = jnp.asarray(packed_dev)
    packed_dev.block_until_ready()
    print(f"first kernel (compile) {time.perf_counter()-t0:.1f}s; "
          f"out {packed_dev.shape} {packed_dev.dtype}", flush=True)

    # -- A: full packed transfer -------------------------------------------
    def fetch_full():
        out = graph.run_lookup_packed(rng[0], rng[1], q_arr, snap=snap)
        return np.ascontiguousarray(out)

    fetch_full()  # warm transfer mode
    for i in range(3):
        t0 = time.perf_counter()
        full = fetch_full()
        ta = time.perf_counter() - t0
        print(f"A full packed fetch: {ta*1e3:.0f} ms "
              f"({full.nbytes/1e6:.1f} MB)", flush=True)

    L, W = full.shape
    C = W * 32

    # ground truth density
    bits = np.unpackbits(full.view(np.uint8), bitorder="little")
    total_set = int(bits.sum())
    print(f"L={L} C={C} total set bits={total_set} "
          f"({total_set/(L*C)*100:.2f}%)", flush=True)

    # -- B: device-side flat extraction ------------------------------------
    main_t, aux_t, cav_t = snap

    def K_bucket(n):
        k = 1 << 16
        while k < n:
            k <<= 1
        return k

    K = K_bucket(int(total_set * 1.25))
    print(f"K bucket = {K}", flush=True)

    @jax.jit
    def extract(sl, K=K):
        # sl [L, W] uint32 -> bools [L, C] -> [C, L] -> flat nonzero
        shifts = jnp.arange(32, dtype=jnp.uint32)
        b = ((sl[:, :, None] >> shifts[None, None, :]) & 1).astype(jnp.bool_)
        b = b.reshape(sl.shape[0], -1)          # [L, C], col = w*32+bit
        counts = b.sum(axis=0, dtype=jnp.int32)  # [C]
        flat = jnp.nonzero(b.T.reshape(-1), size=K,
                           fill_value=sl.shape[0] * b.shape[1])[0]
        return counts, flat.astype(jnp.uint32)

    def fetch_compact():
        sl = graph.run_lookup_packed(rng[0], rng[1], q_arr, snap=snap)
        counts, flat = extract(jnp.asarray(sl))
        return np.asarray(counts), np.asarray(flat)

    t0 = time.perf_counter()
    counts, flat = fetch_compact()
    print(f"B first (compile) {time.perf_counter()-t0:.1f}s", flush=True)
    for i in range(3):
        t0 = time.perf_counter()
        counts, flat = fetch_compact()
        tb = time.perf_counter() - t0
        print(f"B compact fetch: {tb*1e3:.0f} ms "
              f"({(counts.nbytes+flat.nbytes)/1e6:.1f} MB)", flush=True)

    # verify equivalence on a few columns
    total = int(counts.sum())
    assert total == total_set, (total, total_set)
    starts = np.concatenate([[0], np.cumsum(counts)])
    for c in (0, 5, 100, 255):
        got = np.sort(flat[starts[c]:starts[c+1]] % np.uint32(L))
        wcol = np.ascontiguousarray(full[:, c // 32])
        want = np.nonzero((wcol >> np.uint32(c % 32)) & np.uint32(1))[0]
        assert np.array_equal(got, np.sort(want.astype(np.uint32))), c
    print("equivalence ok", flush=True)

    # -- C: pipelining check: dispatch kernel N+1 during N's transfer -------
    t0 = time.perf_counter()
    sl1 = graph.run_lookup_packed(rng[0], rng[1], q_arr, snap=snap)
    c1 = extract(jnp.asarray(sl1))
    sl2 = graph.run_lookup_packed(rng[0], rng[1], q_arr, snap=snap)
    c2 = extract(jnp.asarray(sl2))
    r1 = (np.asarray(c1[0]), np.asarray(c1[1]))
    r2 = (np.asarray(c2[0]), np.asarray(c2[1]))
    tc = time.perf_counter() - t0
    print(f"C two pipelined compact batches: {tc*1e3:.0f} ms total "
          f"({tc/2*1e3:.0f} ms/batch amortized)", flush=True)


if __name__ == "__main__":
    main()
