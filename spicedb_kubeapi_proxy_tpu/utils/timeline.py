"""Dispatch timeline profiler (docs/observability.md "Dispatch timeline").

The existing observability layers aggregate (metrics), attribute
per-request latency (tracing), or snapshot per-window state (devtel) —
none of them can measure *concurrency in time*: whether batch N+1's
host→device transfer actually overlaps batch N's kernel, what bandwidth
a dispatch achieved against the device's HBM peak, or which wall-clock
window a graph rebuild stalled.  Those are exactly the questions the
roofline gap (ROADMAP item 1: `transfer_transpose_ms` > device time at
~1-2% of v5e HBM peak) and the rebuild p99 spikes (item 4) hang on.
This module is the dependency-free instrument:

- **Event ring**: a bounded ring of monotonic-clock `TimelineEvent`s
  emitted from every stage of the batch pipeline — host pack,
  transpose, host→device transfer / blocking sync, kernel launch,
  device→host extract, graph rebuild/compact/warm-start spans, and jit
  compiles — each carrying the recording thread id, fused-batch id,
  pow-2 lane bucket, and bytes moved.  Device-side kernel spans arrive
  through `utils/tracing.kernel_span` (lazy-bound hook, mirroring the
  devtel kernel accounting); host/dispatcher/rebuild stages record
  directly.

- **Derived telemetry** per dispatch: achieved bytes/sec per stage
  (`authz_dispatch_bandwidth_bytes_per_sec{stage=}`), the kernel-stage
  bandwidth as a fraction of the configured device HBM peak
  (`authz_roofline_fraction`; `--device-hbm-peak-gbps`, defaulting from
  the detected platform), host-stall attribution
  (`authz_dispatch_stall_seconds{cause=pack|transpose|transfer|rebuild|compile}`),
  and the transfer/compute **overlap ratio** — the fraction of
  transfer/transpose wall time during which a *different* batch's
  kernel interval was open (`authz_dispatch_overlap_ratio`).  The
  overlap ratio is the direct before/after number for double-buffered
  dispatch: serialized pipelines sit at ~0, a perfect double-buffer
  approaches 1.

- **Chrome trace export**: `chrome_trace()` renders the ring as
  trace-event JSON (Perfetto-loadable; `ph: X` complete slices on named
  tracks for host / dispatcher / device, `ph: B/E` pairs on the rebuild
  track) served at the authed `/debug/timeline`, so a p99 spike window
  in the flight recorder links to the exact stall slice.

- **Summaries**: `summary(since=)` condenses a window of the ring into
  {overlap ratio, roofline fraction, stall-cause breakdown, per-stage
  bandwidth, worst-dispatch exemplar} — embedded in `bench.py` sweep
  artifacts, per-window in `scripts/soak.py`, and per-window in the
  flight recorder.

The `Timeline` feature gate is the killswitch: with it off, `record` is
one gate check and `span()` returns a shared module-level null context
— no event objects, no ring writes, no counter updates (asserted by
tests/test_timeline.py).

Kernel-stage bytes are **measured** when KernelIntrospect is on: the
kernels thread an iteration counter through the sweep and the byte tag
becomes `iterations x one-sweep gather traffic` (utils/workload.py).
With the gate off — and on paths that cannot read telemetry back
(sharded mesh, pre-first-readback) — the tag falls back to the
*modeled lower bound* of one fixpoint sweep, so
`authz_roofline_fraction` under-reports true achieved bandwidth there.
`summary()["kernel_bytes_basis"]` says which basis
(measured/modeled/mixed) the window's roofline number rests on.

Thread-safe: events are recorded from asyncio handlers and executor
threads concurrently.
"""

from __future__ import annotations

import collections
import threading
import time
from typing import Iterable, Optional

from . import metrics as m

# host-stall attribution: stage -> stall cause.  compact/warm_start are
# rebuild-family stalls (they hold the same endpoint lock a rebuild
# does); kernel/extract/dispatcher stages are not host stalls.
_STALL_CAUSE = {
    "pack": "pack",
    "transpose": "transpose",
    "transfer": "transfer",
    "rebuild": "rebuild",
    "compact": "rebuild",
    "warm_start": "rebuild",
    "compile": "compile",
}

# overlap accounting: transfer-side stages (host-visible result
# movement: the blocking D2H sync and the host word-transpose) vs
# compute-side stages (the host window holding a kernel execution).
# Prep stages (host query encode) are not data movement — they stay out
# of the overlap RATIO — but time they spend hidden behind a different
# batch's kernel window is still subtracted from stall attribution:
# host encode while the device is busy is not a stall.
_TRANSFER_STAGES = frozenset(("transfer", "transpose"))
_PREP_STAGES = frozenset(("pack",))
_COMPUTE_STAGES = frozenset(("kernel",))

# stages whose bytes/duration is a meaningful data-movement bandwidth;
# other byte-tagged events (e.g. rebuild's registered device footprint)
# keep their bytes in the event/chrome args but never set the gauge —
# "registered bytes / rebuild seconds" is not a bandwidth
_BANDWIDTH_STAGES = frozenset(("pack", "transpose", "transfer", "kernel"))

# serving-tier (non-kernel) stages: the full proxy path a request walks
# outside the dispatch/kernel machinery — authn, rule match, the
# upstream kube round-trip, list JSON decode, filter evaluation,
# re-serialization.  They land on their own "serving" track with the
# same event/overlap accounting the kernel stages get, and export as
# authz_serving_stage_seconds{stage=} (PAPER.md §7: the serving-shim
# escalation is only justified once these spans prove proxy overhead
# dominates).
_SERVING_STAGES = ("authn", "rule_match", "kube_upstream", "decode",
                   "filter", "serialize")

# chrome-trace track layout: one synthetic tid per named track (the
# real recording thread id rides in args.thread)
_TRACK_TIDS = {"host": 1, "dispatcher": 2, "device": 3, "rebuild": 4,
               "serving": 5}

# published HBM peaks (GB/s) by detected jax platform; the CLI flag
# overrides.  v5e is the hardware this repo benches on; unknown
# platforms leave the peak unset (bandwidth still exports, the roofline
# fraction reads 0).
_PLATFORM_HBM_PEAK_GBPS = {"tpu": 819.0}

# tracing.kernel_span name -> timeline stage.  kernel.dispatch maps to
# "kernel": with the current packed-extraction path the capture-side
# call blocks until the device result lands, so its host window IS the
# kernel execution; on a truly async backend it degrades to launch-only
# (still the honest lower bound).  Spans may override per call via
# attrs["timeline_stage"] (e.g. kernel.transfer flips to "transpose"
# when the pending result is already a host array and the block is the
# word-transpose copy, not a device sync).
_KERNEL_SPAN_STAGES = {
    "kernel.device": "kernel",
    "kernel.dispatch": "kernel",
    "kernel.transfer": "transfer",
}


def enabled() -> bool:
    """Timeline gate (killswitch); unknown-gate errors fail open so
    embedded users with a stripped gate registry still get timelines."""
    try:
        from .features import GATES
        return GATES.enabled("Timeline")
    except Exception:
        return True


def now() -> float:
    return time.perf_counter()


class TimelineEvent:
    __slots__ = ("stage", "track", "start", "end", "thread", "batch",
                 "bucket", "nbytes", "attrs")

    def __init__(self, stage: str, track: str, start: float, end: float,
                 thread: int, batch: Optional[int], bucket: Optional[int],
                 nbytes: int, attrs: Optional[dict]):
        self.stage = stage
        self.track = track
        self.start = start
        self.end = end
        self.thread = thread
        self.batch = batch
        self.bucket = bucket
        self.nbytes = nbytes
        self.attrs = attrs

    @property
    def duration(self) -> float:
        return self.end - self.start


def _merged_length(segs: list) -> float:
    """Total length of a union of (lo, hi) intervals."""
    if not segs:
        return 0.0
    segs.sort()
    total = 0.0
    cur_lo, cur_hi = segs[0]
    for lo, hi in segs[1:]:
        if lo > cur_hi:
            total += cur_hi - cur_lo
            cur_lo, cur_hi = lo, hi
        else:
            cur_hi = max(cur_hi, hi)
    return total + (cur_hi - cur_lo)


def overlap_stats(events: Iterable[TimelineEvent]) -> Optional[dict]:
    """Transfer/compute overlap over a set of events: the fraction of
    transfer-stage wall time during which a compute-stage interval of a
    DIFFERENT fused batch was open.  None when no transfer time exists
    (nothing to overlap).  This is ROADMAP item 1's before/after
    number: a serialized pipeline scores ~0; batch N+1's kernel hiding
    batch N's transfer scores toward 1.

    Cost matters: this runs on the event loop (flight-recorder window
    capture, /debug/timeline).  Computes are sorted once and each
    transfer bisects to its temporal neighborhood (a compute starting
    more than the longest compute duration before the transfer cannot
    overlap it), so a full 4096-event ring stays O((T+C)·log C + local
    candidates) instead of T×C interval checks."""
    import bisect
    transfers = []
    computes = []
    preps = []
    for e in events:
        if e.end <= e.start:
            continue
        if e.stage in _TRANSFER_STAGES:
            transfers.append(e)
        elif e.stage in _PREP_STAGES:
            preps.append(e)
        elif e.stage in _COMPUTE_STAGES:
            computes.append(e)
    total = sum(e.duration for e in transfers)
    if total <= 0.0:
        return None
    computes.sort(key=lambda c: c.start)
    starts = [c.start for c in computes]
    max_dur = max((c.duration for c in computes), default=0.0)
    overlap = 0.0
    hidden: dict = {}  # transfer/prep stage -> seconds PROVABLY hidden
    for t in transfers + preps:
        is_transfer = t.stage in _TRANSFER_STAGES
        segs = []
        segs_strict = []
        lo_bound = t.start - max_dur
        i = bisect.bisect_left(starts, t.end) - 1  # last start < t.end
        while i >= 0 and computes[i].start >= lo_bound:
            c = computes[i]
            i -= 1
            if (c.batch is not None and c.batch == t.batch):
                continue  # same dispatch: that is serialization, not overlap
            lo, hi = max(t.start, c.start), min(t.end, c.end)
            if hi > lo:
                segs.append((lo, hi))
                # strict variant feeding the stall subtraction: only
                # intervals PROVABLY from a different fused batch (both
                # sides tagged) count as hiding — untagged events keep
                # raw stall semantics
                if c.batch is not None and t.batch is not None:
                    segs_strict.append((lo, hi))
        if is_transfer:
            overlap += _merged_length(segs)
        if segs_strict:
            hidden[t.stage] = (hidden.get(t.stage, 0.0)
                               + _merged_length(segs_strict))
    return {
        "transfer_s": round(total, 6),
        "overlap_s": round(overlap, 6),
        "ratio": round(overlap / total, 4),
        "transfers": len(transfers),
        "computes": len(computes),
        # per-stage seconds hidden behind a different batch's kernel
        # window (summary() subtracts these from the pack/transpose/
        # transfer stall causes: a hidden transfer or host encode is
        # not a stall — the device never went idle for it)
        "hidden_s_by_stage": {s: round(v, 6)
                              for s, v in sorted(hidden.items())},
    }


class _NullSpan:
    """Shared no-op context manager returned by span() when the gate is
    off (tests assert the span object's identity — no per-call span or
    generator allocation).  __enter__ yields a FRESH scratch dict: the
    span() contract lets callers enrich the yielded dict, and handing
    every gated-off call site one shared dict would leak enrichments
    across unrelated spans process-wide."""
    __slots__ = ()

    def __enter__(self):
        return {}

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    __slots__ = ("_tl", "stage", "track", "kw", "t0")

    def __init__(self, tl: "Timeline", stage: str, track: str, kw: dict):
        self._tl = tl
        self.stage = stage
        self.track = track
        self.kw = kw

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self.kw  # callers may enrich (nbytes discovered inside)

    def __exit__(self, *exc):
        self._tl.record(self.stage, self.track, self.t0, **self.kw)
        return False


_trace_current = None  # resolved lazily; False => tracing unavailable


def _note_trace_span(stage: str, start: float, end: float) -> None:
    """Mirror a serving-stage span into the active request trace (as a
    forensic `serving.<stage>` span, never a phase — the phases already
    tile the wall time).  This is what lets the fleet merge attribute
    serving stages per tier: the timeline ring is process-wide, but the
    trace travels with the request.  Lazy-bound, same discipline as the
    tracing->timeline hook in the other direction."""
    global _trace_current
    if _trace_current is None:
        try:
            from .tracing import current_trace
            _trace_current = current_trace  # noqa: A004(import cache, not gated state)
        except Exception:
            _trace_current = False  # noqa: A004(import cache, not gated state)
    if _trace_current:
        tr = _trace_current()
        if tr is not None:
            try:
                tr.add_span("serving." + stage, start, end)
            except Exception:  # pragma: no cover - defensive
                pass


class _ServingSpan(_Span):  # noqa: A004(built behind gate)
    """Serving-track span: records the timeline event, feeds the
    per-stage serving histogram, and mirrors into the request trace in
    one exit."""
    __slots__ = ()

    def __exit__(self, *exc):
        end = time.perf_counter()
        self._tl.record(self.stage, self.track, self.t0, end, **self.kw)
        self._tl._serving.observe(end - self.t0, stage=self.stage)
        _note_trace_span(self.stage, self.t0, end)
        return False


class Timeline:
    """Bounded event ring + derived dispatch telemetry (module singleton
    `TIMELINE`; an isolated instance is constructible for tests)."""

    def __init__(self, capacity: int = 4096,
                 registry: Optional[m.Registry] = None,
                 hbm_peak_gbps: Optional[float] = None):
        registry = registry or m.REGISTRY
        self.capacity = capacity
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        # wall/monotonic epoch pair: chrome-trace ts are µs since this
        # epoch; start_unix in summaries maps back to wall clock
        self.epoch_mono = time.perf_counter()
        self.epoch_wall = time.time()
        # lock-free batch-id source (itertools.count.__next__ is atomic
        # in CPython): next_batch() must stay cheap and contention-free
        # even with the gate off — it runs once per dispatch
        import itertools
        self._batch_seq = itertools.count(1)
        self._hbm_peak_gbps = hbm_peak_gbps  # None => detect lazily
        self._hbm_peak_detected: Optional[float] = None
        # platform auto-detection is armed only once a device-track
        # event exists: summary()/scrapes on a jax-less (embedded://)
        # server must never import jax or touch jax.devices()
        self._device_seen = False
        # cumulative counters (snapshot()/diff for bench artifacts)
        self._stall_s: dict = {}
        self._bytes_by_stage: dict = {}
        self._events_total = 0
        self._stall = registry.counter(
            "authz_dispatch_stall_seconds",
            "Host wall time stalled per dispatch-pipeline cause "
            "(pack, transpose, transfer, rebuild, compile)",
            labels=("cause",))
        self._bw = registry.gauge(
            "authz_dispatch_bandwidth_bytes_per_sec",
            "Achieved bytes/sec of the most recent dispatch-pipeline "
            "event per stage (kernel bytes are measured iterations x "
            "one-sweep traffic with KernelIntrospect on, else a "
            "modeled one-sweep lower bound)",
            labels=("stage",))
        self._roofline = registry.gauge(
            "authz_roofline_fraction",
            "Most recent kernel dispatch's achieved HBM bandwidth as a "
            "fraction of the configured device peak (byte basis: "
            "measured sweep telemetry when KernelIntrospect is on, else "
            "modeled one-sweep floor; 0 = peak unknown or no dispatch)")
        registry.gauge(
            "authz_dispatch_overlap_ratio",
            "Transfer/compute overlap ratio over the recent timeline "
            "ring (0 = fully serialized pipeline, ~1 = transfers hidden "
            "behind another batch's kernel)",
            callback=self._overlap_gauge)
        self._serving = registry.histogram(
            "authz_serving_stage_seconds",
            "Serving-tier (non-kernel) stage latency: authn, rule_match, "
            "kube_upstream, decode, filter, serialize (docs/"
            "observability.md 'Fleet tracing')",
            labels=("stage",))

    # -- configuration -------------------------------------------------------

    def set_hbm_peak(self, gbps: Optional[float]) -> None:
        """Override the device HBM peak (GB/s); None/0 restores
        platform auto-detection."""
        self._hbm_peak_gbps = gbps if gbps else None

    def hbm_peak_bytes_per_s(self) -> float:
        """Configured or platform-detected peak in bytes/s; 0.0 when
        unknown (roofline fraction then reads 0 rather than inventing a
        denominator)."""
        if self._hbm_peak_gbps:
            return self._hbm_peak_gbps * 1e9
        if not self._device_seen:
            # no device-track event has ever been recorded: summary()
            # and /debug scrapes on an embedded:// (jax-less) server
            # must not import jax / call jax.devices() — that would
            # stall the event loop on backend init and grab a TPU from
            # a process that never meant to use one
            return 0.0
        if self._hbm_peak_detected is None:
            # a device event exists, so the jax backend is already
            # initialized in this process — detection is a cheap lookup
            peak = 0.0
            try:
                import jax
                plat = jax.devices()[0].platform
                peak = _PLATFORM_HBM_PEAK_GBPS.get(plat, 0.0)
            except Exception:
                peak = 0.0
            self._hbm_peak_detected = peak
        return self._hbm_peak_detected * 1e9

    # -- recording -----------------------------------------------------------

    def next_batch(self) -> int:
        """Process-unique fused-batch id tying one dispatch's events
        together across host/dispatcher/device tracks (lock-free: runs
        once per dispatch whether or not the gate is on)."""
        return next(self._batch_seq)

    def record(self, stage: str, track: str, start: float,
               end: Optional[float] = None, batch: Optional[int] = None,
               bucket: Optional[int] = None, nbytes: int = 0,
               **attrs) -> None:
        """Record one closed interval; no-op when the gate is off."""
        if not enabled():
            return
        end = time.perf_counter() if end is None else end
        ev = TimelineEvent(stage, track, start, end,
                           threading.get_ident(), batch, bucket,
                           int(nbytes), attrs or None)
        dur = ev.duration
        cause = _STALL_CAUSE.get(stage)
        with self._lock:
            if track == "device":
                self._device_seen = True
            if stage in _COMPUTE_STAGES and self._compile_overlaps(start):
                # the first execution of a fresh jit bucket compiles
                # INSIDE the kernel span: the compile slice (already in
                # the ring — its wrapper closed before this span did)
                # names the stall, and this kernel event must not feed
                # bandwidth/roofline with a compile-inflated duration
                ev.attrs = dict(ev.attrs or {})
                ev.attrs["compile"] = True
            self._ring.append(ev)
            self._events_total += 1
            if nbytes:
                self._bytes_by_stage[stage] = (
                    self._bytes_by_stage.get(stage, 0) + int(nbytes))
            if cause is not None and dur > 0:
                self._stall_s[cause] = self._stall_s.get(cause, 0.0) + dur
        if cause is not None and dur > 0:
            self._stall.inc(dur, cause=cause)
        if (nbytes and dur > 0 and stage in _BANDWIDTH_STAGES
                and not (ev.attrs and ev.attrs.get("compile"))):
            bw = nbytes / dur
            self._bw.set(bw, stage=stage)
            if stage in _COMPUTE_STAGES:
                peak = self.hbm_peak_bytes_per_s()
                self._roofline.set(bw / peak if peak else 0.0)

    def _compile_overlaps(self, start: float) -> bool:
        """True when a recently recorded compile slice overlaps a span
        that began at `start` (bounded backward scan, under the lock)."""
        checked = 0
        for prev in reversed(self._ring):
            if prev.stage == "compile" and prev.end >= start:
                return True
            checked += 1
            if checked >= 64:
                return False
        return False

    def span(self, stage: str, track: str, **kw):
        """Context manager recording the enclosed block; yields the
        keyword dict so callers can enrich it (e.g. set nbytes once the
        transfer size is known) before the span closes.  Returns a
        shared null context when the gate is off."""
        if not enabled():
            return _NULL_SPAN
        return _Span(self, stage, track, kw)

    def serving_span(self, stage: str, **kw):
        """Span on the serving track (authn, rule_match, kube_upstream,
        decode, filter, serialize): the timeline event rides the normal
        ring/chrome-trace machinery AND the duration feeds the
        authz_serving_stage_seconds{stage=} histogram.  Same gate-off
        contract as span(): the shared null context, nothing ticks."""
        if not enabled():
            return _NULL_SPAN
        return _ServingSpan(self, stage, "serving", kw)

    def time_first_call(self, fn, bucket: Optional[int] = None,
                        stage: str = "compile", track: str = "device",
                        static_args: int = 0, shape_args: bool = False):
        """Wrap a jitted entry point so the first call PER COMPILE KEY
        records a `compile` timeline event: XLA compiles lazily inside
        the first execution, which is where recompile storms actually
        stall the pipeline.  `static_args` is the number of leading
        positional arguments that participate in the jit compile-cache
        key (jax.jit static_argnums): a lookup jitted with static
        (slot_offset, slot_length) recompiles for every new
        (type, permission) slot range, and each of those compiles must
        be attributed — not just the first ever.  `shape_args` adds the
        positional arguments' array shapes to the key: entry points
        whose traced arguments vary in shape independently of the
        bucket (the check gather) retrace per novel shape tuple, and
        those silent recompiles must be attributed too.  Steady-state
        calls pay one tuple-slice + set lookup."""
        seen: set = set()

        def wrapper(*args, **kwargs):
            key = args[:static_args] if static_args else ()
            if shape_args:
                key += tuple(getattr(a, "shape", None) for a in args)
            if key in seen:
                return fn(*args, **kwargs)
            t0 = time.perf_counter()
            try:
                return fn(*args, **kwargs)
            finally:
                seen.add(key)
                self.record(stage, track, t0, bucket=bucket)

        return wrapper

    # -- views ---------------------------------------------------------------

    def events(self, since: Optional[float] = None) -> list:
        """Events (oldest first) whose END is at/after `since`
        (monotonic); all retained events when None."""
        with self._lock:
            evs = list(self._ring)
        if since is None:
            return evs
        return [e for e in evs if e.end >= since]

    def _overlap_gauge(self) -> float:
        evs = self.events()
        st = overlap_stats(evs[-256:])  # bound scrape-time cost
        return st["ratio"] if st else 0.0

    def snapshot(self) -> dict:
        """Cumulative counters (process lifetime) — bench configs diff
        two of these; the ring-derived views live in summary()."""
        with self._lock:
            return {"events_total": self._events_total,
                    "stall_s": dict(self._stall_s),
                    "bytes_by_stage": dict(self._bytes_by_stage)}

    def summary(self, since: Optional[float] = None) -> dict:
        """Condense the (optionally window-restricted) ring: overlap
        ratio, per-stage bandwidth, modeled roofline fraction,
        stall-cause breakdown, and the worst-dispatch exemplar."""
        evs = self.events(since)
        by_stage: dict = {}   # stage -> [seconds, bytes, count]
        bw_agg: dict = {}     # bandwidth-stage -> [seconds, bytes]
        by_batch: dict = {}   # batch -> {stage: seconds}
        stalls: dict = {}
        basis_bytes = [0, 0]  # [measured, modeled] kernel bytes
        for e in evs:
            agg = by_stage.setdefault(e.stage, [0.0, 0, 0])
            agg[0] += e.duration
            agg[1] += e.nbytes
            agg[2] += 1
            # bandwidth aggregation excludes compile-contaminated kernel
            # windows (the adjacent compile slice carries that stall)
            # and non-movement byte tags like rebuild's footprint
            if (e.stage in _BANDWIDTH_STAGES and e.nbytes
                    and not (e.attrs and e.attrs.get("compile"))):
                b = bw_agg.setdefault(e.stage, [0.0, 0])
                b[0] += e.duration
                b[1] += e.nbytes
                if e.stage in _COMPUTE_STAGES:
                    if e.attrs and e.attrs.get("measured"):
                        basis_bytes[0] += e.nbytes
                    else:
                        basis_bytes[1] += e.nbytes
            cause = _STALL_CAUSE.get(e.stage)
            if cause is not None:
                stalls[cause] = stalls.get(cause, 0.0) + e.duration
            if e.batch is not None:
                by_batch.setdefault(e.batch, {})[e.stage] = (
                    by_batch.get(e.batch, {}).get(e.stage, 0.0) + e.duration)
        bandwidth = {
            stage: round(nbytes / secs, 1)
            for stage, (secs, nbytes) in sorted(bw_agg.items())
            if nbytes and secs > 0}
        k_secs = sum(bw_agg.get(s, [0.0, 0])[0] for s in _COMPUTE_STAGES)
        k_bytes = sum(bw_agg.get(s, [0.0, 0])[1] for s in _COMPUTE_STAGES)
        peak = self.hbm_peak_bytes_per_s()
        # 12-digit rounding: fractions can legitimately sit at 1e-7
        # scale (CPU backend, modeled lower bound) and must not read 0.0
        roofline = (round(k_bytes / k_secs / peak, 12)
                    if k_secs > 0 and k_bytes and peak else None)
        # roofline honesty label: "measured" when every kernel byte tag
        # came from sweep telemetry (iterations x per-sweep bytes),
        # "modeled" when all are the one-sweep lower bound (gate off,
        # sharded path, or pre-readback), "mixed" otherwise
        if basis_bytes[0] and basis_bytes[1]:
            bytes_basis = "mixed"
        elif basis_bytes[0]:
            bytes_basis = "measured"
        elif basis_bytes[1]:
            bytes_basis = "modeled"
        else:
            bytes_basis = None
        worst = None
        if by_batch:
            wid, stages = max(by_batch.items(),
                              key=lambda kv: sum(kv[1].values()))
            worst = {"batch": wid,
                     "total_ms": round(sum(stages.values()) * 1e3, 3),
                     "stages_ms": {s: round(v * 1e3, 3)
                                   for s, v in sorted(stages.items())}}
        ov = overlap_stats(evs)
        if ov:
            # overlap-aware stall attribution (device-resident
            # pipeline): transfer/transpose wall time hidden behind a
            # DIFFERENT batch's kernel window is not a stall — the
            # device never went idle for it.  The cumulative
            # authz_dispatch_stall_seconds counter stays raw wall time
            # (it is incremented at record time, before any overlap is
            # knowable); this window condensate is the judgment number.
            for stage, hid in ov["hidden_s_by_stage"].items():
                cause = _STALL_CAUSE.get(stage)
                if cause in stalls:
                    stalls[cause] = max(0.0, stalls[cause] - hid)
        return {
            "events": len(evs),
            "dispatches": len(by_batch),
            "overlap": ov,
            "overlap_ratio": ov["ratio"] if ov else None,
            "roofline_fraction": roofline,
            "kernel_bytes_basis": bytes_basis,
            "hbm_peak_gbps": round(peak / 1e9, 1) if peak else None,
            "bandwidth_bytes_per_s": bandwidth,
            "stall_s": {c: round(v, 6) for c, v in sorted(stalls.items())},
            "stage_ms": {s: round(a[0] * 1e3, 3)
                         for s, a in sorted(by_stage.items())},
            "worst_dispatch": worst,
        }

    def chrome_trace(self, since: Optional[float] = None) -> dict:
        """Chrome trace-event JSON of the ring (Perfetto-loadable):
        `M` metadata names the process and one row per track, pipeline
        stages are `X` complete slices, rebuild-track spans are `B`/`E`
        pairs (they nest warm-start inside recovery cleanly).  `ts` is
        µs since the timeline epoch; args carry the recording thread,
        fused-batch id, lane bucket, and bytes moved."""
        evs = self.events(since)
        pid = 1
        out = [{"name": "process_name", "ph": "M", "ts": 0, "pid": pid,
                "tid": 0, "args": {"name": "spicedb-kubeapi-proxy-tpu"}}]
        for track, tid in _TRACK_TIDS.items():
            out.append({"name": "thread_name", "ph": "M", "ts": 0,
                        "pid": pid, "tid": tid, "args": {"name": track}})
        for e in evs:
            tid = _TRACK_TIDS.get(e.track, 0)
            ts = (e.start - self.epoch_mono) * 1e6
            dur = max(e.duration, 0.0) * 1e6
            args = {"thread": e.thread}
            if e.batch is not None:
                args["batch"] = e.batch
            if e.bucket is not None:
                args["bucket"] = e.bucket
            if e.nbytes:
                args["bytes"] = e.nbytes
            if e.attrs:
                args.update(e.attrs)
            if e.track == "rebuild":
                out.append({"name": e.stage, "cat": e.track, "ph": "B",
                            "ts": round(ts, 3), "pid": pid, "tid": tid,
                            "args": args})
                out.append({"name": e.stage, "cat": e.track, "ph": "E",
                            "ts": round(ts + dur, 3), "pid": pid,
                            "tid": tid})
            else:
                out.append({"name": e.stage, "cat": e.track, "ph": "X",
                            "ts": round(ts, 3), "dur": round(dur, 3),
                            "pid": pid, "tid": tid, "args": args})
        return {
            "traceEvents": out,
            "displayTimeUnit": "ms",
            "otherData": {
                "epoch_unix": round(self.epoch_wall, 6),
                "capacity": self.capacity,
                "summary": self.summary(since),
            },
        }


# -- module singleton + delegates ---------------------------------------------

TIMELINE = Timeline()


def set_hbm_peak(gbps: Optional[float]) -> None:
    TIMELINE.set_hbm_peak(gbps)


def next_batch() -> int:
    return TIMELINE.next_batch()


def record(stage: str, track: str, start: float,
           end: Optional[float] = None, **kw) -> None:
    TIMELINE.record(stage, track, start, end, **kw)


def span(stage: str, track: str, **kw):
    return TIMELINE.span(stage, track, **kw)


def serving_span(stage: str, **kw):
    return TIMELINE.serving_span(stage, **kw)


def time_first_call(fn, bucket: Optional[int] = None,
                    static_args: int = 0, shape_args: bool = False):
    return TIMELINE.time_first_call(fn, bucket=bucket,
                                    static_args=static_args,
                                    shape_args=shape_args)


def summary(since: Optional[float] = None) -> dict:
    return TIMELINE.summary(since)


def snapshot() -> dict:
    return TIMELINE.snapshot()


def chrome_trace(since: Optional[float] = None) -> dict:
    return TIMELINE.chrome_trace(since)


def note_kernel_span(name: str, attrs: dict, start: float,
                     end: float) -> None:
    """Hook target for tracing.kernel_span (lazy-bound there): device
    kernel spans land on the timeline's device track without the
    endpoint emitting them twice.  Callers may override the stage per
    call via attrs['timeline_stage']."""
    stage = attrs.get("timeline_stage") or _KERNEL_SPAN_STAGES.get(name)
    if stage is None:
        return
    extra = {}
    if attrs.get("measured"):
        # byte tag upgraded from the modeled one-sweep floor to measured
        # iterations x per-sweep bytes (KernelIntrospect sweep telemetry)
        extra["measured"] = True
    TIMELINE.record(stage, "device", start, end,
                    batch=attrs.get("batch_id"),
                    bucket=attrs.get("bucket") or None,
                    nbytes=int(attrs.get("nbytes", 0) or 0), **extra)
