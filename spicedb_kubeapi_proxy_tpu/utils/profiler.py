"""On-demand sampling profiler for the serving tier
(docs/observability.md "Workload attribution & profiling").

A dependency-free wall-clock sampler: a capture thread polls
`sys._current_frames()` at ~100 Hz for a bounded window and aggregates
every thread's stack into collapsed-stack lines (`root;...;leaf count`,
the flamegraph.pl / speedscope input format) plus a Perfetto-compatible
chrome-trace event list.  Served at the authed `/debug/profile?seconds=N`
endpoint (proxy/server.py), which runs the blocking capture on a worker
thread so the event loop — usually the most interesting thread — keeps
running and gets sampled doing real work.

Deliberate properties:

- **Bounded**: requested durations are clamped to `HARD_CAP_S`; a second
  capture while one is running is refused (`ProfilerBusy`) rather than
  queued, so the surface cannot be used to pile up sampler threads.
- **Idle-free**: no background thread exists between captures; when
  nobody asks for a profile the cost is zero.
- **Killswitch**: the `Profiler` feature gate refuses captures outright
  (`ProfilerDisabled`) — the ALPHA-stage escape hatch for operators who
  do not want even on-demand sampling in a serving process.

Sampling, not tracing: frames are attributed by wall-clock presence, so
a function with N% of samples spent ~N% of wall time on-stack (including
time blocked on locks/IO — often exactly what you want to see in a
proxy).  Threads waiting in epoll show as `select`/`poll` leaves.
"""

from __future__ import annotations

import os.path
import sys
import threading
import time
from typing import Optional

from . import metrics as m

# ceiling on a single capture window; requests beyond it are clamped
HARD_CAP_S = 30.0
# default / maximum sampling rate (wall-clock Hz; prime-ish to avoid
# beating against 10ms-periodic work)
DEFAULT_HZ = 97.0
# chrome-trace event cap: long high-rate captures keep the collapsed
# aggregate exact but truncate the per-sample event list
MAX_TRACE_EVENTS = 20000


class ProfilerDisabled(RuntimeError):
    """Capture refused: the Profiler feature gate is off."""


class ProfilerBusy(RuntimeError):
    """Capture refused: another capture is already running."""


def enabled() -> bool:
    """Profiler gate (killswitch); unknown-gate errors fail open so
    embedded users with a stripped gate registry keep the surface
    (mirrors utils/devtel.enabled)."""
    try:
        from .features import GATES
        return GATES.enabled("Profiler")
    except Exception:
        return True


def _frame_label(frame) -> str:
    code = frame.f_code
    base = os.path.basename(code.co_filename)
    # ';' is the collapsed-stack separator — keep it out of labels
    return f"{code.co_name} ({base}:{code.co_firstlineno})".replace(";", ",")


def _stack_of(frame) -> list:
    """Root-to-leaf collapsed-stack labels for one thread's frame."""
    rev = []
    while frame is not None:
        rev.append(_frame_label(frame))
        frame = frame.f_back
    rev.reverse()
    return rev


class SamplingProfiler:
    """One-capture-at-a-time wall-clock stack sampler."""

    def __init__(self, registry: Optional[m.Registry] = None):
        registry = registry or m.REGISTRY
        self._busy = threading.Lock()
        self._captures = registry.counter(
            "authz_profile_captures_total",
            "Completed /debug/profile sampling captures")

    def capture(self, seconds: float, hz: float = DEFAULT_HZ) -> dict:
        """Blocking capture of `seconds` of wall-clock samples across
        all threads.  Raises ProfilerDisabled / ProfilerBusy; callers
        (the debug surface) run this on a worker thread."""
        if not enabled():
            raise ProfilerDisabled("Profiler feature gate disabled")
        seconds = min(max(float(seconds), 0.05), HARD_CAP_S)
        hz = min(max(float(hz), 1.0), DEFAULT_HZ)
        if not self._busy.acquire(blocking=False):
            raise ProfilerBusy("a profile capture is already running")
        try:
            return self._run(seconds, hz)
        finally:
            self._busy.release()

    def _run(self, seconds: float, hz: float) -> dict:
        interval = 1.0 / hz
        me = threading.get_ident()
        collapsed: dict = {}
        events: list = []
        samples = 0
        thread_ids: set = set()
        t0 = time.perf_counter()
        deadline = t0 + seconds
        next_tick = t0
        while True:
            now = time.perf_counter()
            if now >= deadline:
                break
            names = {t.ident: t.name for t in threading.enumerate()}
            for ident, frame in sys._current_frames().items():
                if ident == me:
                    continue
                stack = _stack_of(frame)
                if not stack:
                    continue
                thread_ids.add(ident)
                key = ";".join(stack)
                collapsed[key] = collapsed.get(key, 0) + 1
                if len(events) < MAX_TRACE_EVENTS:
                    events.append({
                        "name": stack[-1],
                        "cat": "sample",
                        "ph": "X",
                        "ts": int((now - t0) * 1e6),
                        "dur": int(interval * 1e6),
                        "pid": 1,
                        "tid": ident,
                        "args": {"thread": names.get(ident, str(ident)),
                                 "stack": key},
                    })
            samples += 1
            next_tick += interval
            delay = next_tick - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
        self._captures.inc()
        lines = [f"{k} {v}" for k, v in
                 sorted(collapsed.items(), key=lambda kv: -kv[1])]
        return {
            "seconds": round(time.perf_counter() - t0, 3),
            "hz": hz,
            "samples": samples,
            "threads": len(thread_ids),
            "collapsed": lines,
            "chrome_trace": {"traceEvents": events,
                             "displayTimeUnit": "ms"},
            "truncated_events": len(events) >= MAX_TRACE_EVENTS,
        }


PROFILER = SamplingProfiler()


def capture(seconds: float, hz: float = DEFAULT_HZ) -> dict:
    return PROFILER.capture(seconds, hz)
