#!/usr/bin/env python
"""Replication smoke for scripts/check.sh (ISSUE 9 + ISSUE 11 failover).

Three REAL processes over localhost HTTP:

  1. spawn ONE shared fake kube-apiserver, then a leader (embedded
     endpoint + durable data dir) and a follower (--replicate-from the
     leader) both proxying it — like production, where N proxies front
     the same cluster;
  2. create a pod THROUGH the leader (dual-write: kube object + tuple);
  3. assert the follower serves the filtered list including it within
     the lag bound — replicated, not forwarded;
  4. kill -9 the leader;
  5. assert the follower keeps serving bounded-staleness reads, reports
     degraded (still 200) /readyz, and rejects writes 503;
  6. POST /replication/promote: the follower becomes the leader (new
     incarnation), takes a dual-write LOCALLY, and the pre-kill write
     is still readable (zero lost acknowledged writes);
  7. resurrect the OLD leader over its old data dir with the new
     leader as a peer: the startup fence probe demotes it into a
     follower — it serves both writes (replicated from the new leader)
     and forwards new writes to the new leader.  Exactly one writable
     leader after the partition heals.

Then the fleet tracing section (ISSUE 16): a one-shard CLI router in
front of the rejoined follower drives a dual-write through THREE
tiers (router -> follower -> promoted leader) and asserts the merged
/debug/fleet view carries one trace spanning all three tiers whose
per-tier attribution reconciles with the client-measured end-to-end
latency (docs/observability.md "Fleet tracing").

Then the sharded write scale-out section (ISSUE 15): TWO shard-leader
proxies (pods+namespaces on shard 0, configmaps+cfgns on shard 1, each
its own data dir) behind the CLI router (`--shard-leaders`):

  8. dual-writes through the router land on the owning shard
     (X-Authz-Shard header + revision-vector ZedToken stamps);
  9. a read carrying the write's revision-vector token serves
     (read-your-writes through the router);
 10. kill -9 the shard-1 leader → pod dual-writes through the router
     KEEP LANDING on shard 0 (the satellite's core assertion), while
     configmap traffic answers 502 naming the dead shard.

No jax import on the serving path (embedded endpoint): runs in seconds.
"""

import argparse
import json
import os
import shutil
import signal
import subprocess
import sys
import tempfile
import time
import urllib.request

import yaml

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from spicedb_kubeapi_proxy_tpu.utils.topology import (  # noqa: E402
    free_port,
    http,
    wait_http_ready as wait_ready,
)

SCHEMA = """
definition user {}
definition namespace {
  relation creator: user
  permission view = creator
}
definition pod {
  relation creator: user
  permission view = creator
}
"""

RULES = """
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: list-pods}
match: [{apiVersion: v1, resource: pods, verbs: [list]}]
prefilter:
- fromObjectIDNamespaceExpr: "{{split_namespace(resourceId)}}"
  fromObjectIDNameExpr: "{{split_name(resourceId)}}"
  lookupMatchingResources: {tpl: "pod:$#view@user:{{user.name}}"}
---
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: create-pods}
match: [{apiVersion: v1, resource: pods, verbs: [create]}]
lock: Optimistic
check: [{tpl: "namespace:{{namespace}}#view@user:{{user.name}}"}]
update:
  creates:
  - tpl: "pod:{{namespacedName}}#creator@user:{{user.name}}"
"""

LAG_BOUND_S = 8.0

# sharded section: a second co-location class (cfgns + configmap) that
# can live on its own shard — the pod rules' types (namespace + pod)
# form the shard-0 class
SHARD_SCHEMA = SCHEMA + """
definition cfgns {
  relation creator: user
  permission view = creator
}
definition configmap {
  relation creator: user
  permission view = creator
}
"""

SHARD_RULES = RULES + """
---
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: list-configmaps}
match: [{apiVersion: v1, resource: configmaps, verbs: [list]}]
prefilter:
- fromObjectIDNamespaceExpr: "{{split_namespace(resourceId)}}"
  fromObjectIDNameExpr: "{{split_name(resourceId)}}"
  lookupMatchingResources: {tpl: "configmap:$#view@user:{{user.name}}"}
---
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: create-configmaps}
match: [{apiVersion: v1, resource: configmaps, verbs: [create]}]
lock: Optimistic
check: [{tpl: "cfgns:{{namespace}}#view@user:{{user.name}}"}]
update:
  creates:
  - tpl: "configmap:{{namespacedName}}#creator@user:{{user.name}}"
"""

PARTITION_MAP = "configmap=1,cfgns=1"


def serve(role: str, port: int, data_dir: str, leader_url: str,
          kube_url: str, peers: str = "", seed_rel: str = "") -> None:
    """Child process: the shared fake kube-apiserver, or one proxy
    serving plain HTTP with header authn in front of it."""
    import asyncio
    import logging

    logging.basicConfig(
        level=logging.WARNING,
        format="%(asctime)s %(levelname).1s %(name)s: %(message)s")

    from spicedb_kubeapi_proxy_tpu.proxy.authn import HeaderAuthenticator
    from spicedb_kubeapi_proxy_tpu.proxy.httpcore import (
        H11Transport,
        HttpServer,
    )
    from spicedb_kubeapi_proxy_tpu.proxy.server import Options, ProxyServer
    from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import Bootstrap
    from spicedb_kubeapi_proxy_tpu.spicedb.types import parse_relationship

    if role == "kube":
        from spicedb_kubeapi_proxy_tpu.kubefake.apiserver import (
            FakeKubeApiServer,
        )

        async def run_kube():
            kube = FakeKubeApiServer()
            kube.seed("", "v1", "namespaces",
                      {"metadata": {"name": "team-a"}})
            server = HttpServer(kube)
            await server.start("127.0.0.1", port)
            print(f"kube serving on {port}", flush=True)
            await asyncio.Event().wait()

        asyncio.run(run_kube())
        return

    opts = Options(
        spicedb_endpoint="embedded://",
        bootstrap=Bootstrap(schema_text=(SHARD_SCHEMA
                                         if role == "shardleader"
                                         else SCHEMA)),
        rules_yaml=SHARD_RULES if role == "shardleader" else RULES,
        upstream_transport=H11Transport(kube_url),
        authenticators=[HeaderAuthenticator()],
        workflow_database_path="",  # in-memory dual-write journal
    )
    if role in ("leader", "shardleader"):
        opts.data_dir = data_dir
        opts.wal_fsync = "never"
        if peers:
            # a (possibly resurrected) leader probes its peers for a
            # newer incarnation at startup and demotes itself instead
            # of split-braining (docs/replication.md "Failover runbook")
            opts.replica_peers = [p for p in peers.split(",") if p]
    else:
        opts.replicate_from = leader_url
        opts.replica_user = "system:replica"
        if data_dir:
            # the data dir this follower will own if promoted
            opts.promote_data_dir = data_dir

    async def run():
        proxy = ProxyServer(opts)
        if role == "leader" and proxy.endpoint.store.revision == 0:
            proxy.endpoint.store.bulk_load([parse_relationship(
                "namespace:team-a#creator@user:alice")])
        if role == "shardleader" and proxy.endpoint.store.revision == 0:
            proxy.endpoint.store.bulk_load(
                [parse_relationship(r)
                 for r in seed_rel.split(",") if r])
        # dual writes on every role: a follower forwards them until it
        # is promoted, then serves them locally
        proxy.enable_dual_writes()
        await proxy.start("127.0.0.1", port)
        print(f"{role} serving on {port}", flush=True)
        await asyncio.Event().wait()

    asyncio.run(run())


# -- parent-side helpers: free_port/http/wait_ready now come from the
# -- shared topology harness (utils/topology.py) ------------------------------


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--role", default="",
                    choices=["", "kube", "leader", "follower",
                             "shardleader"])
    ap.add_argument("--port", type=int, default=0)
    ap.add_argument("--data-dir", default="")
    ap.add_argument("--leader", default="")
    ap.add_argument("--kube", default="")
    ap.add_argument("--peers", default="")
    ap.add_argument("--seed-rel", default="")
    args = ap.parse_args()
    if args.role:
        serve(args.role, args.port, args.data_dir, args.leader, args.kube,
              peers=args.peers, seed_rel=args.seed_rel)
        return 0

    tmp = tempfile.mkdtemp(prefix="repl-smoke-")
    kp, lp, fp = free_port(), free_port(), free_port()
    kube_url = f"http://127.0.0.1:{kp}"
    leader_url = f"http://127.0.0.1:{lp}"
    follower_url = f"http://127.0.0.1:{fp}"
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    procs = []
    try:
        print("== spawn shared kube + leader + follower")
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--role", "kube",
             "--port", str(kp)], env=env))
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--role", "leader",
             "--port", str(lp), "--data-dir", os.path.join(tmp, "leader"),
             "--kube", kube_url], env=env))

        # the leader's schema + rules must pass the Cedar-style static
        # lint (docs/static-analysis.md SL-rules): a leader shipping a
        # statically-broken schema would replicate that brokenness to
        # every follower.  Run it overlapped with leader startup.
        boot_path = os.path.join(tmp, "lint-bootstrap.yaml")
        rules_path = os.path.join(tmp, "lint-rules.yaml")
        with open(boot_path, "w") as f:
            yaml.safe_dump({"schema": SCHEMA}, f)
        with open(rules_path, "w") as f:
            f.write(RULES)
        lint_proc = subprocess.Popen(
            [sys.executable, "-m", "spicedb_kubeapi_proxy_tpu",
             "--lint-schema", "--lint-schema-json",
             "--spicedb-bootstrap", boot_path, "--rule-config", rules_path],
            env=env, stdout=subprocess.PIPE, text=True)
        # in procs so the finally reaper gets it if wait_ready or the
        # communicate timeout below raises first (kill on an already-
        # exited child is a caught OSError)
        procs.append(lint_proc)

        wait_ready(leader_url, 30.0)

        print("== leader schema/rules pass --lint-schema")
        lint_out, _ = lint_proc.communicate(timeout=60)
        assert lint_proc.returncode == 0, (
            f"leader schema failed --lint-schema "
            f"(exit {lint_proc.returncode}):\n{lint_out}")
        lint = json.loads(lint_out)
        assert lint["summary"]["errors"] == 0, lint
        print(f"   lint clean: {lint['summary']['warnings']} warnings, "
              f"0 errors")
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--role", "follower",
             "--port", str(fp), "--leader", leader_url, "--kube", kube_url,
             "--data-dir", os.path.join(tmp, "follower-promote")],
            env=env))
        wait_ready(follower_url, 30.0)  # 503 until checkpoint adoption

        print("== write through the leader (dual-write create)")
        status, headers, body = http(
            "POST", leader_url + "/api/v1/namespaces/team-a/pods", "alice",
            body={"apiVersion": "v1", "kind": "Pod",
                  "metadata": {"name": "smoke-pod", "namespace": "team-a"}})
        assert status in (200, 201), (status, body)
        rev = int(headers.get("X-Authz-Revision", "0"))
        assert rev > 0, "leader response must carry its revision"

        print(f"== follower serves the write within {LAG_BOUND_S}s "
              f"(revision {rev})")
        t0 = time.time()
        while True:
            status, headers, body = http(
                "GET", follower_url + "/api/v1/namespaces/team-a/pods",
                "alice")
            names = [i["metadata"]["name"]
                     for i in json.loads(body).get("items", [])]
            if status == 200 and "smoke-pod" in names:
                assert headers.get("X-Authz-Forwarded-To") != "leader", \
                    "must be replicated, not forwarded"
                assert int(headers.get("X-Authz-Revision", "0")) >= rev
                break
            if time.time() - t0 > LAG_BOUND_S:
                raise AssertionError(
                    f"follower did not serve the write within "
                    f"{LAG_BOUND_S}s (status {status}, items {names})")
            time.sleep(0.1)
        lag_s = time.time() - t0
        print(f"   replicated in {lag_s:.2f}s")

        print("== kill -9 the leader")
        procs[1].send_signal(signal.SIGKILL)
        procs[1].wait(10)

        print("== follower keeps serving bounded-staleness reads")
        status, headers, body = http(
            "GET", follower_url + "/api/v1/namespaces/team-a/pods", "alice")
        assert status == 200, (status, body)
        assert "smoke-pod" in [i["metadata"]["name"]
                               for i in json.loads(body)["items"]]

        print("== follower /readyz reports degraded (still 200)")
        ready = wait_ready(follower_url, 45.0, want_degraded=True)
        print("   " + ready.decode().replace("\n", " | "))

        print("== follower rejects writes 503 with the leader down")
        status, _, body = http(
            "POST", follower_url + "/api/v1/namespaces/team-a/pods", "alice",
            body={"metadata": {"name": "p2", "namespace": "team-a"}})
        assert status == 503, (status, body)

        print("== promote the follower (POST /replication/promote)")
        # promotion is privileged: plain principals get 403
        status, _, body = http(
            "POST", follower_url + "/replication/promote", "mallory",
            body={})
        assert status == 403, (status, body)
        status, _, body = http(
            "POST", follower_url + "/replication/promote", "admin",
            body={}, groups=["system:masters"])
        assert status == 200, (status, body)
        promo = json.loads(body)
        assert promo["incarnation"] >= 3, promo  # promotion mint
        status, _, body = http(
            "GET", follower_url + "/replication/status", "admin")
        assert status == 200 and json.loads(body)["role"] == "leader", body
        print(f"   promoted: incarnation {promo['incarnation']} at "
              f"revision {promo['revision']}")

        print("== dual-write lands LOCALLY on the promoted leader")
        status, headers, body = http(
            "POST", follower_url + "/api/v1/namespaces/team-a/pods", "alice",
            body={"apiVersion": "v1", "kind": "Pod",
                  "metadata": {"name": "post-failover-pod",
                               "namespace": "team-a"}})
        assert status in (200, 201), (status, body)
        assert headers.get("X-Authz-Forwarded-To") != "leader", \
            "promoted leader must serve writes itself"
        assert int(headers.get("X-Authz-Revision", "0")) > promo["revision"]

        print("== zero lost: the pre-kill write is readable post-failover")
        status, _, body = http(
            "GET", follower_url + "/api/v1/namespaces/team-a/pods", "alice")
        names = [i["metadata"]["name"]
                 for i in json.loads(body).get("items", [])]
        assert status == 200 and "smoke-pod" in names, (status, names)
        assert "post-failover-pod" in names, names

        print("== resurrect the old leader; fence probe demotes it")
        olp = free_port()
        old_leader_url = f"http://127.0.0.1:{olp}"
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--role", "leader",
             "--port", str(olp), "--data-dir", os.path.join(tmp, "leader"),
             "--kube", kube_url, "--peers", follower_url], env=env))
        t0 = time.time()
        while True:
            try:
                status, _, body = http(
                    "GET", old_leader_url + "/replication/status", "admin",
                    timeout=2.0)
                if status == 200 and json.loads(body)["role"] == "follower":
                    break
            except OSError:
                pass
            if time.time() - t0 > 45.0:
                raise AssertionError(
                    f"old leader did not rejoin as follower (last: "
                    f"{body!r})")
            time.sleep(0.2)
        print(f"   rejoined as follower in {time.time() - t0:.2f}s")

        print("== the rejoined ex-leader serves BOTH writes (replicated)")
        t0 = time.time()
        while True:
            status, headers, body = http(
                "GET", old_leader_url + "/api/v1/namespaces/team-a/pods",
                "alice")
            names = [i["metadata"]["name"]
                     for i in json.loads(body).get("items", [])]
            if (status == 200 and "smoke-pod" in names
                    and "post-failover-pod" in names):
                assert headers.get("X-Authz-Forwarded-To") != "leader"
                break
            if time.time() - t0 > LAG_BOUND_S:
                raise AssertionError(
                    f"rejoined follower missing writes: {status} {names}")
            time.sleep(0.1)

        print("== exactly one writable leader: ex-leader forwards writes")
        status, headers, body = http(
            "POST", old_leader_url + "/api/v1/namespaces/team-a/pods",
            "alice",
            body={"apiVersion": "v1", "kind": "Pod",
                  "metadata": {"name": "healed-pod",
                               "namespace": "team-a"}})
        assert status in (200, 201), (status, body)
        assert headers.get("X-Authz-Forwarded-To") == "leader", headers
        status, _, body = http(
            "GET", follower_url + "/api/v1/namespaces/team-a/pods", "alice")
        assert "healed-pod" in [i["metadata"]["name"]
                                for i in json.loads(body)["items"]]

        # -- fleet tracing (ISSUE 16): one request through THREE tiers,
        # -- reconciled in the merged /debug/fleet view ------------------
        print("== fleet tracing: router -> follower -> leader")
        ftp = free_port()
        fleet_url = f"http://127.0.0.1:{ftp}"
        # a 1-shard CLI router fronting the rejoined ex-leader (now a
        # follower): a dual-write travels router -> follower ->
        # promoted leader — three processes, one trace id.
        # --fleet-peers adds the promoted leader to the /debug/fleet
        # fan-out so its segment lands in the merged view.
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "spicedb_kubeapi_proxy_tpu",
             "--shard-leaders", old_leader_url,
             "--rule-config", rules_path,
             "--spicedb-bootstrap", boot_path,
             "--fleet-peers", follower_url,
             "--embedded-mode", "--bind-address", "127.0.0.1",
             "--secure-port", str(ftp)], env=env))
        wait_ready(fleet_url, 30.0)
        # warm the router->follower connection so the timed write below
        # measures the request, not TCP/interpreter cold start
        status, _, _ = http(
            "GET", fleet_url + "/api/v1/namespaces/team-a/pods", "alice")
        assert status == 200, status

        t0 = time.time()
        status, headers, body = http(
            "POST", fleet_url + "/api/v1/namespaces/team-a/pods", "alice",
            body={"apiVersion": "v1", "kind": "Pod",
                  "metadata": {"name": "traced-pod",
                               "namespace": "team-a"}})
        e2e_ms = (time.time() - t0) * 1e3
        assert status in (200, 201), (status, body)
        # h11 lower-cases header names on the router's pass-through
        lower = {k.lower(): v for k, v in headers.items()}
        assert lower.get("x-authz-forwarded-to") == "leader", headers
        tid = lower.get("x-trace-id", "")
        assert tid, headers

        print("== fleet tracing: merged /debug/fleet reconciles e2e")
        status, _, body = http("GET", fleet_url + "/debug/fleet",
                               "alice", timeout=10.0)
        assert status == 200, (status, body)
        merged = json.loads(body)
        assert merged.get("enabled") is True, merged.get("reason")
        assert all(m["error"] is None for m in merged["members"]), \
            merged["members"]
        trd = next((t for t in merged["traces"]
                    if t["trace_id"] == tid), None)
        assert trd is not None, (
            f"trace {tid} absent from merged fleet view "
            f"({[t['trace_id'] for t in merged['traces']]})")
        tiers = set(trd["tiers"])
        assert {"router", "follower", "leader"} <= tiers, tiers
        assert trd["tier_count"] >= 3, trd
        # per-tier self time + network must reconcile to the root
        # (router) duration: the merged view accounts for the whole
        # request, it neither invents nor loses time
        assert abs(trd["attributed_ms"] - trd["duration_ms"]) <= (
            0.10 * trd["duration_ms"] + 5.0), trd
        # ...and the root duration must reconcile with what the CLIENT
        # measured end to end (10% + absolute slack for client-side
        # connection setup + encode/decode outside the router's trace)
        assert trd["duration_ms"] <= e2e_ms + 1.0, (
            trd["duration_ms"], e2e_ms)
        assert e2e_ms - trd["duration_ms"] <= 0.10 * e2e_ms + 75.0, (
            trd["duration_ms"], e2e_ms)
        per_tier = {k: v["self_ms"] for k, v in trd["tiers"].items()}
        print(f"   e2e {e2e_ms:.1f}ms, traced {trd['duration_ms']:.1f}ms: "
              f"{per_tier} + network {trd['network_ms']}ms")

        # -- sharded write scale-out (ISSUE 15): 2 shard leaders + the
        # -- CLI router -------------------------------------------------
        print("== sharded: boot 2 shard leaders + the CLI router")
        s0p, s1p, rp = free_port(), free_port(), free_port()
        s0_url = f"http://127.0.0.1:{s0p}"
        s1_url = f"http://127.0.0.1:{s1p}"
        router_url = f"http://127.0.0.1:{rp}"
        procs.append(subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--role",
             "shardleader", "--port", str(s0p), "--data-dir",
             os.path.join(tmp, "shard0"), "--kube", kube_url,
             "--seed-rel", "namespace:team-a#creator@user:alice"],
            env=env))
        shard1_proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--role",
             "shardleader", "--port", str(s1p), "--data-dir",
             os.path.join(tmp, "shard1"), "--kube", kube_url,
             "--seed-rel", "cfgns:team-a#creator@user:alice"], env=env)
        procs.append(shard1_proc)
        boot2 = os.path.join(tmp, "shard-bootstrap.yaml")
        rules2 = os.path.join(tmp, "shard-rules.yaml")
        with open(boot2, "w") as f:
            yaml.safe_dump({"schema": SHARD_SCHEMA}, f)
        with open(rules2, "w") as f:
            f.write(SHARD_RULES)
        wait_ready(s0_url, 30.0)
        wait_ready(s1_url, 30.0)
        # the router is the REAL CLI in --shard-leaders mode: routing
        # table derived from the rules, footprint-validated at startup
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "spicedb_kubeapi_proxy_tpu",
             "--shard-leaders", f"{s0_url},{s1_url}",
             "--partition-map", PARTITION_MAP,
             "--rule-config", rules2, "--spicedb-bootstrap", boot2,
             "--embedded-mode", "--bind-address", "127.0.0.1",
             "--secure-port", str(rp)], env=env))
        wait_ready(router_url, 30.0)

        print("== sharded: dual-writes land on their owning shards")
        status, headers, body = http(
            "POST", router_url + "/api/v1/namespaces/team-a/pods", "alice",
            body={"apiVersion": "v1", "kind": "Pod",
                  "metadata": {"name": "shard-pod",
                               "namespace": "team-a"}})
        assert status in (200, 201), (status, body)
        assert headers.get("X-Authz-Shard") == "0", headers
        pod_token = headers.get("X-Authz-Revision", "")
        assert pod_token.startswith("0:"), pod_token
        status, headers, body = http(
            "POST", router_url + "/api/v1/namespaces/team-a/configmaps",
            "alice",
            body={"apiVersion": "v1", "kind": "ConfigMap",
                  "metadata": {"name": "shard-cm", "namespace": "team-a"}})
        assert status in (200, 201), (status, body)
        assert headers.get("X-Authz-Shard") == "1", headers
        assert "1:" in headers.get("X-Authz-Revision", ""), headers
        print(f"   pod -> shard 0 (token {pod_token}); configmap -> "
              f"shard 1 (token {headers.get('X-Authz-Revision')})")

        print("== sharded: revision-vector read-your-writes via router")
        req = urllib.request.Request(
            router_url + "/api/v1/namespaces/team-a/pods",
            headers={"Accept": "application/json",
                     "X-Remote-User": "alice",
                     "X-Authz-Min-Revision": pod_token})
        with urllib.request.urlopen(req, timeout=5.0) as resp:
            assert resp.status == 200
            names = [i["metadata"]["name"]
                     for i in json.loads(resp.read()).get("items", [])]
        assert "shard-pod" in names, names

        print("== sharded: kill -9 shard 1; shard 0 keeps taking writes")
        shard1_proc.send_signal(signal.SIGKILL)
        shard1_proc.wait(10)
        status, headers, body = http(
            "POST", router_url + "/api/v1/namespaces/team-a/pods", "alice",
            body={"apiVersion": "v1", "kind": "Pod",
                  "metadata": {"name": "post-shardkill-pod",
                               "namespace": "team-a"}})
        assert status in (200, 201), (status, body)
        assert headers.get("X-Authz-Shard") == "0", headers
        status, _, body = http(
            "GET", router_url + "/api/v1/namespaces/team-a/configmaps",
            "alice")
        assert status == 502, (status, body)
        assert json.loads(body)["details"]["shard"] == 1, body
        status, _, body = http("GET", router_url + "/readyz", "alice")
        assert status == 200 and b"shard 0" in body, (status, body)
        print("   pod dual-write landed on shard 0; configmaps answer "
              "502 naming shard 1; router /readyz degraded-but-200")

        print("replication_smoke: ALL GREEN")
        return 0
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
                p.wait(5)
        shutil.rmtree(tmp, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
