"""Device-telemetry smoke: start the proxy, drive traffic, scrape
/metrics + /debug/flight, and fail loudly on any missing telemetry
family (wired into scripts/check.sh; fast, CPU-only, no TPU).

What it proves end to end:
- the server starts with the flight recorder + SLO tracker wired;
- `/metrics` carries the device-telemetry families (`authz_device_bytes`,
  `authz_batch_occupancy`, `authz_jit_cache_*`, `authz_slo_burn_rate`);
- `/debug/flight` returns >= 2 windows of snapshots after a warm-up;
- the `/debug` index enumerates every debug surface uniformly.
"""

import asyncio
import json
import os
import sys

os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

from spicedb_kubeapi_proxy_tpu.kubefake.apiserver import (  # noqa: E402
    FakeKubeApiServer)
from spicedb_kubeapi_proxy_tpu.proxy.httpcore import (  # noqa: E402
    HandlerTransport)
from spicedb_kubeapi_proxy_tpu.proxy.server import (  # noqa: E402
    Options, ProxyServer)
from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import Bootstrap  # noqa: E402
from spicedb_kubeapi_proxy_tpu.spicedb.types import (  # noqa: E402
    parse_relationship)

SCHEMA = """
definition user {}

definition namespace {
    relation creator: user
    permission view = creator
}

definition pod {
    relation creator: user
    relation namespace: namespace
    permission view = creator + namespace->view
}
"""

RULES = """
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: list-pods}
match: [{apiVersion: v1, resource: pods, verbs: [list]}]
prefilter:
- fromObjectIDNameExpr: "{{split_name(resourceId)}}"
  fromObjectIDNamespaceExpr: "{{split_namespace(resourceId)}}"
  lookupMatchingResources: {tpl: "pod:$#view@user:{{user.name}}"}
---
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: get-pods}
match: [{apiVersion: v1, resource: pods, verbs: [get]}]
check: [{tpl: "pod:{{namespacedName}}#view@user:{{user.name}}"}]
"""

REQUIRED_FAMILIES = (
    "authz_device_bytes",
    "authz_device_bytes_peak",
    "authz_batch_occupancy",
    "authz_jit_cache_hits_total",
    "authz_jit_cache_misses_total",
    "authz_jit_cache_entries",
    "authz_slo_burn_rate",
    "authz_kernel_time_seconds",
)


def fail(msg: str) -> None:
    print(f"devtel_smoke: FAIL: {msg}", file=sys.stderr)
    sys.exit(1)


async def main() -> None:
    kube = FakeKubeApiServer()
    for i in range(8):
        kube.seed("", "v1", "pods",
                  {"metadata": {"name": f"p{i}", "namespace": "team-a"}})
    server = ProxyServer(Options(
        spicedb_endpoint="jax://",
        bootstrap=Bootstrap(schema_text=SCHEMA),
        rules_yaml=RULES,
        upstream_transport=HandlerTransport(kube),
        flight_window_s=0.15,
        flight_windows=16,
        slo_check_p99_ms=250.0,
        slo_objective=0.01,
    ))
    rels = ["namespace:team-a#creator@user:alice"] + [
        f"pod:team-a/p{i}#creator@user:alice" for i in range(0, 8, 2)]
    server.endpoint.store.bulk_load([parse_relationship(r) for r in rels])

    await server.start("127.0.0.1", 0)
    try:
        alice = server.get_embedded_client(user="alice")
        for _ in range(6):
            resp = await alice.get("/api/v1/pods")
            assert resp.status == 200, resp.body
        resp = await alice.get("/api/v1/namespaces/team-a/pods/p0")
        assert resp.status == 200, resp.body
        # >= 2 flight windows after the warm-up
        await asyncio.sleep(0.5)

        resp = await alice.get("/metrics")
        if resp.status != 200:
            fail(f"/metrics -> {resp.status}")
        text = resp.body.decode()
        missing = [f for f in REQUIRED_FAMILIES
                   if f"# TYPE {f} " not in text]
        if missing:
            fail(f"/metrics missing device-telemetry families: {missing}")
        if "authz_device_bytes{" not in text:
            fail("authz_device_bytes has no kind-labeled samples "
                 "(HBM ledger never registered a buffer)")
        if 'authz_slo_burn_rate{slo="latency_p99"' not in text:
            fail("authz_slo_burn_rate has no latency_p99 samples "
                 "(SLO evaluator never ran)")

        resp = await alice.get("/debug/flight")
        if resp.status != 200:
            fail(f"/debug/flight -> {resp.status}")
        flight = json.loads(resp.body)
        if len(flight.get("windows", [])) < 2:
            fail(f"/debug/flight returned "
                 f"{len(flight.get('windows', []))} windows, want >= 2")
        newest = flight["windows"][0]
        for field in ("http", "hbm", "occupancy", "jit", "slo"):
            if field not in newest:
                fail(f"flight window missing {field!r}: {newest}")
        if newest["hbm"]["total"] <= 0:
            fail("flight window reports an empty HBM ledger after "
                 "kernel traffic")

        resp = await alice.get("/debug")
        if resp.status != 200:
            fail(f"/debug -> {resp.status}")
        surfaces = json.loads(resp.body).get("surfaces", {})
        for path in ("/debug/traces", "/debug/decisions", "/debug/flight"):
            if path not in surfaces:
                fail(f"/debug index missing {path}: {surfaces}")
        resp = await alice.get("/debug/nonesuch")
        if resp.status != 404:
            fail(f"/debug/nonesuch -> {resp.status}, want uniform 404")
        resp = await alice.get("/readyz")
        if resp.status != 200 or not resp.body.startswith(b"ok"):
            fail(f"/readyz -> {resp.status} {resp.body!r}")
    finally:
        await server.stop()
    print("devtel_smoke: OK (device-telemetry families present, "
          f"{len(flight['windows'])} flight windows)")


if __name__ == "__main__":
    asyncio.run(main())
