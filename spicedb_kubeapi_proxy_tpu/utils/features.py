"""Feature-gate registry (reference pkg/proxy/features.go:10-27).

The reference registers the component-base logging gates
(LoggingAlphaOptions/LoggingBetaOptions/ContextualLogging) into a mutable
gate map consulted at runtime.  This build keeps the same contract: named
boolean gates with a maturity stage and default, settable from the CLI
(`--feature-gates name=true,other=false`) or programmatically, consulted
via `enabled()`.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict

ALPHA = "ALPHA"
BETA = "BETA"
GA = "GA"


class FeatureGateError(ValueError):
    pass


@dataclass
class _Gate:
    name: str
    stage: str
    default: bool
    value: bool


class FeatureGates:
    def __init__(self):
        self._gates: Dict[str, _Gate] = {}

    def register(self, name: str, stage: str = ALPHA,
                 default: bool = False) -> None:
        if name in self._gates:
            raise FeatureGateError(f"feature gate {name!r} already registered")
        self._gates[name] = _Gate(name, stage, default, default)

    def enabled(self, name: str) -> bool:
        gate = self._gates.get(name)
        if gate is None:
            raise FeatureGateError(f"unknown feature gate {name!r}")
        return gate.value

    def set(self, name: str, value: bool) -> None:
        gate = self._gates.get(name)
        if gate is None:
            raise FeatureGateError(f"unknown feature gate {name!r}")
        gate.value = value

    def apply_flag(self, spec: str) -> None:
        """Parse a `name=true,name2=false` CLI value (component-base
        syntax; a bare name means true)."""
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            name, _, raw = part.partition("=")
            raw = raw.strip().lower() or "true"
            if raw not in ("true", "false"):
                raise FeatureGateError(
                    f"invalid feature gate value {part!r}: want name=true|false")
            self.set(name.strip(), raw == "true")

    def known(self) -> dict:
        return {g.name: (g.stage, g.value) for g in self._gates.values()}

    def reset(self) -> None:
        for g in self._gates.values():
            g.value = g.default


# process-wide gates, mirroring the reference's global gate map
GATES = FeatureGates()

# logging gates the reference registers (features.go:17-26)
GATES.register("ContextualLogging", stage=ALPHA, default=True)
GATES.register("LoggingAlphaOptions", stage=ALPHA, default=False)
GATES.register("LoggingBetaOptions", stage=BETA, default=True)
# build-specific gates
GATES.register("StructuredRequestLog", stage=BETA, default=True)
GATES.register("CrossRequestBatching", stage=GA, default=True)
# revision-keyed decision cache with relation-scoped invalidation
# (spicedb/decision_cache.py); also switchable per endpoint via
# `?cache=1` or the --decision-cache CLI flag
GATES.register("DecisionCache", stage=ALPHA, default=False)
# durable relationship store (spicedb/persist): WAL + checkpoints +
# crash recovery; engages when --data-dir is set, this gate is the
# killswitch (disable to run in-memory despite a configured data dir)
GATES.register("DurableStore", stage=BETA, default=True)
# device telemetry & flight recorder (utils/devtel.py): HBM ledger,
# kernel/compile accounting, batch occupancy, SLO burn rates; this gate
# is the killswitch for recording + the flight-recorder window task
GATES.register("DeviceTelemetry", stage=BETA, default=True)
# dispatch timeline profiler (utils/timeline.py): bounded event ring,
# chrome-trace export at /debug/timeline, transfer/compute overlap +
# roofline + stall attribution; this gate is the killswitch for
# recording (span() degrades to a shared no-op context)
GATES.register("Timeline", stage=BETA, default=True)
# device-resident query pipeline (ops/ell.py, ops/spmv.py,
# ops/jax_endpoint.py, spicedb/dispatch.py): on-device bitplane
# word-transpose, donated per-bucket state arenas, async D2H readback,
# and depth-N double-buffered fused dispatch (--pipeline-depth).  This
# gate is the killswitch: off reproduces the pre-pipeline serial path
# (host word-transpose, blocking device sync, single-slot lookup window)
GATES.register("DevicePipeline", stage=BETA, default=True)
# off-loop incremental rebuilds (ops/jax_endpoint.py): device-graph
# rebuilds run on a background executor against a store snapshot while
# the old generation keeps serving (queries on pairs the old graph can
# no longer answer route to the host oracle), then swap atomically
# under a short lock.  This gate is the killswitch: off reproduces the
# pre-PR synchronous rebuild-under-lock behavior exactly.
GATES.register("AsyncRebuild", stage=BETA, default=True)
# admission control (utils/admission.py, spicedb/dispatch.py,
# proxy/server.py): bounded dispatcher queues + read-only load shedding
# with 429/Retry-After.  This gate is the killswitch: off, configured
# bounds and shed thresholds are inert and overload queues unboundedly
# as before.
GATES.register("AdmissionControl", stage=BETA, default=True)
# WAL-shipping read replicas (spicedb/replication, docs/replication.md):
# leader-side replication API (/replication/*) + follower mode
# (--replicate-from).  This gate is the killswitch: off, the replication
# routes are not served and a configured --replicate-from is inert —
# exactly today's single-node behavior.
GATES.register("Replication", stage=ALPHA, default=True)
# differential fuzz-harness telemetry (fuzz/metrics.py): authz_fuzz_*
# counters recorded by the offline harness (scripts/fuzz_smoke.py,
# budgeted campaigns).  This gate is the killswitch for the recording
# helpers; off, fuzz runs tick nothing.
GATES.register("FuzzTelemetry", stage=ALPHA, default=True)
# partitioned write scale-out (spicedb/sharding, docs/replication.md
# "Sharding"): footprint-proven tuple-space sharding across N leaders
# with a thin router and revision-vector ZedTokens.  This gate is the
# killswitch: off, --shards/--partition-map are inert (single-shard
# behavior exactly), the router degrades to a pass-through to the
# default shard, and the authz_shard_* metrics tick nothing.
GATES.register("Sharding", stage=ALPHA, default=True)
# kernel introspection & workload cost attribution (ops/ell.py,
# ops/spmv.py, utils/workload.py): measured sweep-iteration counters and
# per-iteration frontier-population traces threaded through the fixpoint
# carry, read back with the existing result D2H; feeds
# authz_sweep_iterations / authz_frontier_decay and the per-(type,
# permission) /debug/workload attribution rows, and upgrades the
# timeline roofline from modeled one-sweep bytes to measured
# iterations x per-sweep bytes.  This gate is the killswitch: off, the
# kernels build exactly the pre-introspection jitted functions
# (byte-identical carry shape), no sweep metrics tick, and the roofline
# keeps its modeled lower-bound semantics.
GATES.register("KernelIntrospect", stage=BETA, default=True)
# on-demand sampling profiler (utils/profiler.py): authed
# /debug/profile?seconds=N thread sampler with collapsed-stack and
# chrome-trace output.  This gate is the killswitch: off, capture
# requests are refused and the sampler thread never starts.
GATES.register("Profiler", stage=ALPHA, default=True)
# multi-chip mesh execution (parallel/sharding.py, parallel/compat.py,
# ops/jax_endpoint.py _ShardedEllGraph): 2D (data x graph) shard_map
# kernels behind `jax://?mesh=...` — row-sharded ELL tables with
# per-iteration tiled all_gather, word-sharded batches, sharded donated
# state arenas, and per-device HBM ledger rows.  This gate is the
# killswitch: off, `mesh=auto` degrades to the single-chip kernels
# (byte-identical single-device path) and an explicit `mesh=DxG` fails
# endpoint construction loudly (an authz proxy must not silently ignore
# an explicitly configured topology).
GATES.register("MeshExecution", stage=ALPHA, default=True)
# Leopard-style materialized group index (ops/leopard.py,
# ops/jax_endpoint.py): statically-proven group-membership fragments are
# flattened into device-resident transitive-closure bitplanes consulted
# before the iterative kernel (one AND+popcount instead of one fixpoint
# iteration per nesting level), maintained incrementally from store
# deltas with delete-quarantine + background re-close.  This gate is the
# killswitch: off, no closure is planned or built and the check/lookup
# ladders are byte-identical to the pre-index build.  The gate is
# evaluated at endpoint construction (like a configured mesh): flipping
# it mid-process affects endpoints built afterwards.
GATES.register("LeopardIndex", stage=ALPHA, default=True)
# tail explainer (utils/tailexplain.py): /debug/tail report diffing the
# p99 trace population against the p50 population of the merged fleet
# view into a ranked per-(tier, serving stage) "where the tail lives"
# breakdown.  This gate is the killswitch: off, /debug/tail answers
# enabled:false and no report is computed — trace collection itself is
# governed by the existing Timeline/fleet plumbing, not this gate.
GATES.register("TailExplain", stage=BETA, default=True)


def mesh_enabled() -> bool:
    """MeshExecution gate accessor; unknown-gate errors fail open so an
    embedded user with a stripped gate registry keeps a configured
    mesh (mirrors pipeline_enabled below)."""
    try:
        return GATES.enabled("MeshExecution")
    except Exception:
        return True


def pipeline_enabled() -> bool:
    """DevicePipeline gate accessor; unknown-gate errors fail open so
    embedded users with a stripped gate registry still get the fast
    path (mirrors utils/timeline.enabled)."""
    try:
        return GATES.enabled("DevicePipeline")
    except Exception:
        return True


def leopard_enabled() -> bool:
    """LeopardIndex gate accessor; unknown-gate errors fail CLOSED —
    unlike the mesh/pipeline accessors, the safe degraded mode for a
    stripped registry is the iterative kernel (no index is always
    correct, it is only slower)."""
    try:
        return GATES.enabled("LeopardIndex")
    except Exception:
        return False
