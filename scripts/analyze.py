#!/usr/bin/env python
"""Unified static-analysis driver (docs/static-analysis.md).

ONE entry point for every static gate in the repo:

  A001-A006  concurrency & hot-path rules (scripts/analysis/rules_*)
  M-rules    the historical scripts/lint.py families (legacy_lint)
  SL-rules   schema/rule lint, bridged via
             `python -m spicedb_kubeapi_proxy_tpu --lint-schema --lint-schema-json`
             as a SUBPROCESS so this driver never imports jax

Usage:
  scripts/analyze.py                 # A-rules over the package
  scripts/analyze.py --all           # A + M + SL (the check.sh gate)
  scripts/analyze.py --rules A003    # one rule
  scripts/analyze.py --json          # machine-readable findings
  scripts/analyze.py --update-baseline   # grandfather current findings

Suppression: `# noqa: AXXX(reason)` on the finding line — reason
required (a bare code is finding A000).  Works for M-rules too when run
through this driver.  Pre-existing findings live in
scripts/analysis/baseline.json; the gate fails only on NEW findings.
Exit codes: 0 clean, 1 findings, 2 driver/config error.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import subprocess
import sys
from pathlib import Path

ROOT = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(ROOT / "scripts"))

from analysis import core  # noqa: E402
from analysis.legacy_lint import run_legacy  # noqa: E402
from analysis.rules_async import rule_a001, rule_a002  # noqa: E402
from analysis.rules_gates import rule_a004  # noqa: E402
from analysis.rules_jit import rule_a005  # noqa: E402
from analysis.rules_locks import rule_a003  # noqa: E402
from analysis.rules_trace import rule_a006  # noqa: E402

RULES = {
    "A001": rule_a001,
    "A002": rule_a002,
    "A003": rule_a003,
    "A004": rule_a004,
    "A005": rule_a005,
    "A006": rule_a006,
}
DEFAULT_PATHS = ["spicedb_kubeapi_proxy_tpu"]
BASELINE = ROOT / "scripts" / "analysis" / "baseline.json"


class _NoqaOnly:
    """Noqa directives for files outside the A-rule source set (legacy
    findings in tests/, scripts/, ...)."""

    def __init__(self, rel: str):
        self.rel = rel
        p = Path(rel)
        self.noqa = (core.parse_noqa_lines(p.read_text().splitlines())
                     if p.exists() else {})


def start_schema_lint():
    """SL-rules in a subprocess (the package import pulls jax; the
    analyzer itself must stay import-light).  Started BEFORE the A/M
    scan so the child's interpreter+jax startup overlaps it — that
    overlap is what keeps `--all` inside its <10s check.sh budget."""
    cmd = [sys.executable, "-m", "spicedb_kubeapi_proxy_tpu",
           "--lint-schema", "--lint-schema-json"]
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    return subprocess.Popen(cmd, stdout=subprocess.PIPE,
                            stderr=subprocess.PIPE, text=True, cwd=ROOT,
                            env=env)


def finish_schema_lint(proc) -> tuple:
    """-> (exit_code, findings, raw payload) from --lint-schema-json.
    On failure the child's diagnostics must surface — a gate that says
    only 'schema exit 2' sends the operator off to reproduce it by
    hand."""
    out, err = proc.communicate()
    try:
        payload = json.loads(out or "{}")
    except json.JSONDecodeError:
        payload = {"findings": [], "error": out[-2000:]}
    if proc.returncode:
        for line in (err or "").strip().splitlines()[-10:]:
            print(f"schema-lint: {line}", file=sys.stderr)
        if payload.get("error"):
            print(f"schema-lint: {payload['error']}", file=sys.stderr)
    return proc.returncode, payload.get("findings", []), payload


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="unified static analyzer (see docs/static-analysis.md)")
    ap.add_argument("paths", nargs="*",
                    help="files/dirs for the A-rules "
                         "(default: the package tree)")
    ap.add_argument("--all", action="store_true",
                    help="run A-rules + legacy M-rules + schema SL-rules "
                         "(the check.sh gate)")
    ap.add_argument("--legacy", action="store_true",
                    help="also run the legacy lint.py M-rule families")
    ap.add_argument("--schema", action="store_true",
                    help="also run the schema/rule lint (subprocess)")
    ap.add_argument("--rules", default="",
                    help="comma-separated rule subset (e.g. A001,A003)")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("--baseline", default=str(BASELINE),
                    help=f"baseline file (default {BASELINE})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (report everything)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="rewrite the baseline to the current findings "
                         "(A/M rules; A000 is never grandfathered)")
    args = ap.parse_args(argv)

    if args.update_baseline and args.rules:
        # regenerating the baseline from a rule subset would silently
        # delete every grandfathered finding of the other rules; an
        # explicit PATH scope stays allowed (tests regenerate fixture
        # baselines that way) — a bare --update-baseline is always the
        # full default-scope universe the --all gate checks against
        print("error: --update-baseline cannot be combined with a "
              "--rules subset (it would drop the other rules' "
              "grandfathered findings)", file=sys.stderr)
        return 2

    # absolute-ize user paths BEFORE pinning cwd to the repo root (the
    # M002 doc path and baseline paths are root-relative)
    paths = [str(Path(p).resolve()) for p in args.paths] or DEFAULT_PATHS
    os.chdir(ROOT)

    selected = ([r.strip().upper() for r in args.rules.split(",") if r.strip()]
                or sorted(RULES))
    unknown = [r for r in selected if r not in RULES]
    if unknown:
        print(f"error: unknown rule(s) {unknown}; known: {sorted(RULES)}",
              file=sys.stderr)
        return 2

    sl_proc = (start_schema_lint()
               if (args.all or args.schema) and not args.update_baseline
               else None)

    sources, findings = core.load_sources(paths, ROOT)
    for rule in selected:
        findings.extend(RULES[rule](sources))

    # a baseline rewrite must see the SAME finding universe the --all
    # gate checks against, or it drops the legacy entries on the floor
    run_m = args.all or args.legacy or args.update_baseline
    n_files = len(sources)
    if run_m:
        legacy_findings, n_legacy = run_legacy()
        findings.extend(legacy_findings)
        n_files = max(n_files, n_legacy)

    findings, suppressed = core.apply_noqa(
        findings,
        list(sources) + [_NoqaOnly(p) for p in
                         {f.path for f in findings}
                         - {s.rel for s in sources}])
    findings.sort(key=lambda f: (f.path, f.line, f.rule))

    baseline_path = Path(args.baseline)
    if args.update_baseline:
        keep = [f for f in findings if f.rule != "A000"]
        core.Baseline.write(baseline_path, keep)
        print(f"analyze: baseline rewritten with {len(keep)} findings "
              f"-> {baseline_path}")
        return 0

    baselined, stale = [], []
    if not args.no_baseline:
        bl = core.Baseline(baseline_path)
        findings, baselined, stale = bl.filter(findings)

    sl_exit, sl_findings = 0, []
    if sl_proc is not None:
        sl_exit, sl_findings, _payload = finish_schema_lint(sl_proc)

    if args.as_json:
        print(json.dumps({
            "version": 1,
            "findings": [f.as_dict() for f in findings],
            "suppressed": [{**s.finding.as_dict(), "reason": s.reason}
                           for s in suppressed],
            "baselined": len(baselined),
            "stale_baseline": [list(k) for k in stale],
            "schema": {"exit": sl_exit, "findings": sl_findings},
            "summary": {"files": n_files, "new": len(findings)},
        }, indent=1))
    else:
        for f in findings:
            print(f.text())
        for f in sl_findings:
            sev = f.get("severity", "warn").upper()
            print(f"schema: {sev} {f.get('code')} [{f.get('where')}] "
                  f"{f.get('message')}")
        for k in stale:
            print(f"note: stale baseline entry (fixed? run "
                  f"--update-baseline): {k[0]} {k[1]} {k[3][:60]}")
        bits = [f"{n_files} files", f"{len(findings)} new findings"]
        if baselined:
            bits.append(f"{len(baselined)} baselined")
        if suppressed:
            bits.append(f"{len(suppressed)} noqa-suppressed")
        if args.all or args.schema:
            bits.append(f"schema exit {sl_exit}")
        print(f"analyze: {', '.join(bits)}")

    if sl_exit == 2:
        return 2
    return 1 if (findings or sl_exit) else 0


if __name__ == "__main__":
    sys.exit(main())
