"""The sharded kernels behind the `jax://` endpoint (SURVEY.md §7 step 7).

Round-1 left the sharded kernels reachable only from raw tests; these
scenarios drive them through the full JaxEndpoint machinery — create_endpoint
URL parsing, the delta drain/lock path, expiration, and the phantom-subject
column — on the virtual 8-device CPU mesh (conftest.py).  Counterpart of the
reference's dispatch-distributed graph walk (pkg/spicedb/spicedb.go:31-47).
"""

import asyncio
import os
import time

import pytest

from spicedb_kubeapi_proxy_tpu.ops.jax_endpoint import (_ShardedEllGraph)
from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import (Bootstrap,
                                                         EndpointConfigError,
                                                         create_endpoint)
from spicedb_kubeapi_proxy_tpu.spicedb.evaluator import Evaluator
from spicedb_kubeapi_proxy_tpu.spicedb.types import (
    CheckRequest,
    ObjectRef,
    Relationship,
    RelationshipUpdate,
    SubjectRef,
    UpdateOp,
    parse_relationship,
)

SCHEMA = """
definition user {}
definition group {
  relation member: user | group#member
}
definition namespace {
  relation viewer: user | group#member | user:*
  relation creator: user
  permission view = viewer + creator
}
"""


def touch(*rels):
    return [RelationshipUpdate(UpdateOp.TOUCH, parse_relationship(r))
            for r in rels]


def delete(*rels):
    return [RelationshipUpdate(UpdateOp.DELETE, parse_relationship(r))
            for r in rels]


def make_sharded(rels, mesh="2x4"):
    ep = create_endpoint(f"jax://?mesh={mesh}&dispatch=direct",
                         Bootstrap(schema_text=SCHEMA))
    if rels:
        ep.store.write(touch(*rels))
    oracle = Evaluator(ep.schema, ep.store)
    return ep, oracle


def assert_agreement(ep, oracle, subjects, resource_type="namespace",
                     permission="view"):
    ids = ep.store.object_ids_of_type(resource_type)

    async def run():
        for s in subjects:
            want = sorted(oracle.lookup_resources(resource_type, permission, s))
            got = sorted(await ep.lookup_resources(resource_type, permission, s))
            assert got == want, f"LR mismatch for {s}: {got} != {want}"
            reqs = [CheckRequest(ObjectRef(resource_type, oid), permission, s)
                    for oid in ids]
            if reqs:
                results = await ep.check_bulk_permissions(reqs)
                for oid, res in zip(ids, results):
                    want_one = oracle.check(ObjectRef(resource_type, oid),
                                            permission, s)
                    assert res.allowed == want_one, (
                        f"check mismatch {oid}@{s}")
    asyncio.run(run())


def users(*names):
    return [SubjectRef("user", n) for n in names]


class TestShardedEndpoint:
    def test_mesh_url_selects_sharded_graph(self):
        ep, _ = make_sharded(["namespace:ns#viewer@user:alice"])
        asyncio.run(ep.lookup_resources("namespace", "view",
                                        SubjectRef("user", "alice")))
        assert isinstance(ep._graph, _ShardedEllGraph)
        assert ep.mesh.shape == {"data": 2, "graph": 4}

    def test_mesh_auto_uses_all_devices(self):
        ep = create_endpoint("jax://?mesh=auto&dispatch=direct",
                             Bootstrap(schema_text=SCHEMA))
        assert ep.mesh is not None and ep.mesh.size == 8

    def test_invalid_mesh_rejected(self):
        with pytest.raises(EndpointConfigError, match="mesh"):
            create_endpoint("jax://?mesh=banana", Bootstrap(schema_text=SCHEMA))
        with pytest.raises(ValueError, match="mesh"):
            create_endpoint("jax://?mesh=3x3", Bootstrap(schema_text=SCHEMA))

    def test_basic_agreement(self):
        ep, oracle = make_sharded([
            "group:eng#member@user:alice",
            "group:ops#member@group:eng#member",
            "namespace:ns1#viewer@group:ops#member",
            "namespace:ns2#creator@user:bob",
            "namespace:ns3#viewer@user:*",
        ])
        assert_agreement(ep, oracle,
                         users("alice", "bob", "stranger"))

    def test_incremental_deltas_on_sharded_tables(self):
        ep, oracle = make_sharded([
            "namespace:ns1#viewer@user:alice",
            "namespace:ns2#viewer@user:bob",
        ])
        assert_agreement(ep, oracle, users("alice", "bob"))
        rebuilds = ep.stats["rebuilds"]
        # in-universe edits ride the incremental row-update path
        ep.store.write(touch("namespace:ns1#viewer@user:bob"))
        ep.store.write(delete("namespace:ns2#viewer@user:bob"))
        assert_agreement(ep, oracle, users("alice", "bob"))
        assert ep.stats["rebuilds"] == rebuilds
        assert ep.stats["delta_batches"] > 0
        # a brand-new object id claims a spare row on the sharded graph
        # too (no rebuild — the device tables already hold its rows)
        ep.store.write(touch("namespace:brand-new#viewer@user:alice"))
        assert_agreement(ep, oracle, users("alice", "bob"))
        assert isinstance(ep._graph, _ShardedEllGraph)
        assert ep.stats["rebuilds"] == rebuilds
        assert ep.stats["spare_assignments"] >= 1

    def test_hub_tree_deltas_sharded(self):
        rels = [f"group:eng#member@user:u{i}" for i in range(120)]
        rels += ["namespace:ns#viewer@group:eng#member"]
        ep, oracle = make_sharded(rels)
        assert_agreement(ep, oracle, users("u0", "u77", "u119"))
        rebuilds = ep.stats["rebuilds"]
        ep.store.write(delete("group:eng#member@user:u77"))
        assert_agreement(ep, oracle, users("u0", "u77", "u119"))
        assert ep.stats["rebuilds"] == rebuilds

    def test_expiration_on_sharded_path(self):
        ep, oracle = make_sharded([])
        ep.store.write([RelationshipUpdate(UpdateOp.TOUCH, Relationship(
            resource=ObjectRef("namespace", "ns"), relation="viewer",
            subject=SubjectRef("user", "alice"),
            expires_at=time.time() + 0.3))])
        ep.store.write(touch("namespace:ns#viewer@user:bob"))
        assert_agreement(ep, oracle, users("alice", "bob"))
        time.sleep(0.35)
        got = asyncio.run(ep.lookup_resources("namespace", "view",
                                              SubjectRef("user", "alice")))
        assert got == []
        assert_agreement(ep, oracle, users("alice", "bob"))

    def test_phantom_subjects_sharded(self):
        ep, oracle = make_sharded([
            "namespace:open#viewer@user:*",
            "namespace:closed#viewer@user:alice",
        ])

        class _NoOracle:
            def __getattr__(self, name):
                raise AssertionError("oracle fallback on sharded path")

        ep._oracle = _NoOracle()

        async def run():
            subs = [SubjectRef("user", f"new{i}") for i in range(50)]
            out = await ep.lookup_resources_batch("namespace", "view", subs)
            assert all(x == ["open"] for x in out)
        asyncio.run(run())

    def test_large_batch_spans_data_axis(self):
        rels = [f"namespace:ns{i % 7}#viewer@user:u{i}" for i in range(300)]
        ep, oracle = make_sharded(rels)
        subs = [SubjectRef("user", f"u{i}") for i in range(300)]

        async def run():
            got = await ep.lookup_resources_batch("namespace", "view", subs)
            for s, g in zip(subs, got):
                assert sorted(g) == sorted(oracle.lookup_resources(
                    "namespace", "view", s))
        asyncio.run(run())


class TestMeshGateAndPipeline:
    """MeshExecution killswitch semantics + the pipelined sharded path."""

    def test_gate_off_explicit_mesh_fails_loud(self):
        from spicedb_kubeapi_proxy_tpu.utils.features import GATES
        GATES.set("MeshExecution", False)
        try:
            with pytest.raises(EndpointConfigError, match="MeshExecution"):
                create_endpoint("jax://?mesh=2x4&dispatch=direct",
                                Bootstrap(schema_text=SCHEMA))
        finally:
            GATES.set("MeshExecution", True)

    def test_gate_off_auto_is_single_device(self, monkeypatch):
        """Gate off + mesh=auto must reproduce the plain single-chip
        endpoint without ever touching the sharded machinery."""
        from spicedb_kubeapi_proxy_tpu.ops import jax_endpoint as je
        from spicedb_kubeapi_proxy_tpu.utils.features import GATES

        def boom(*a, **k):
            raise AssertionError("sharded path reached with gate off")

        monkeypatch.setattr(je, "_ShardedEllGraph", boom)
        GATES.set("MeshExecution", False)
        try:
            ep = create_endpoint("jax://?mesh=auto&dispatch=direct",
                                 Bootstrap(schema_text=SCHEMA))
            assert ep.mesh is None
            ep.store.write(touch("namespace:ns#viewer@user:alice"))
            got = asyncio.run(ep.lookup_resources(
                "namespace", "view", SubjectRef("user", "alice")))
            assert got == ["ns"]
        finally:
            GATES.set("MeshExecution", True)

    def test_pipelined_sharded_dispatch_and_device_ledger(self):
        from spicedb_kubeapi_proxy_tpu.utils import devtel
        ep, oracle = make_sharded([
            "group:eng#member@user:alice",
            "namespace:ns1#viewer@group:eng#member",
            "namespace:ns2#creator@user:bob",
        ])
        assert_agreement(ep, oracle, users("alice", "bob"))
        graph = ep._graph
        assert isinstance(graph, _ShardedEllGraph)
        # the pipelined device entry points are live (not the serial
        # degradation round-1 shipped with)
        assert graph.run_checks3_device is not None
        assert graph.run_lookup_packed_T_device is not None
        # per-device HBM ledger rows: one (kind, device) row per shard
        totals = devtel.LEDGER.device_totals()
        main_rows = {d: b for (k, d), b in totals.items() if k == "ell_main"}
        assert len(main_rows) == 8, totals  # conftest virtual 8-dev mesh
        assert all(b > 0 for b in main_rows.values())

    def test_sharded_arena_pool_reuses_state(self):
        ep, oracle = make_sharded(["namespace:ns#viewer@user:alice"])
        assert_agreement(ep, oracle, users("alice"))
        kern = ep._graph.kernel
        # arena keys are GLOBAL word counts, always data-axis-divisible
        # because the endpoint buckets lanes via padded_batch_words
        key = kern.padded_batch_words(32)
        a1 = kern.take_arena(key)
        kern.put_arena(key, a1)
        a2 = kern.take_arena(key)
        assert a2 is a1  # pooled, not re-allocated
        kern.put_arena(key, a2)
        kern.discard_arena(key)
        assert key not in kern._arenas


class TestDistributedGlue:
    """Multi-host jax.distributed glue (parallel/distributed.py)."""

    def test_partial_env_config_rejected(self, monkeypatch):
        from spicedb_kubeapi_proxy_tpu.parallel import distributed as dist
        monkeypatch.setattr(dist, "_runtime_initialized", lambda: False)
        monkeypatch.setenv("SPICEDB_TPU_COORDINATOR", "127.0.0.1:9999")
        monkeypatch.delenv("SPICEDB_TPU_NUM_PROCESSES", raising=False)
        monkeypatch.delenv("SPICEDB_TPU_PROCESS_ID", raising=False)
        with pytest.raises(ValueError, match="partial multi-host config"):
            dist.init_from_env()

    def test_idempotent_when_runtime_already_up(self, monkeypatch):
        from spicedb_kubeapi_proxy_tpu.parallel import distributed as dist
        monkeypatch.setattr(dist, "_runtime_initialized", lambda: True)
        assert dist.init_from_env() is True  # no runtime touch

    def test_endpoint_param_triggers_strict_init(self, monkeypatch):
        from spicedb_kubeapi_proxy_tpu.parallel import distributed as dist
        from spicedb_kubeapi_proxy_tpu.spicedb import endpoints as eps
        calls = []
        monkeypatch.setattr(dist, "init_from_env",
                            lambda *a, **k: calls.append(k) or True)
        eps.create_endpoint("jax://?distributed=1&dispatch=direct", None)
        assert calls == [{"strict": True}]
        calls.clear()
        eps.create_endpoint("jax://?distributed=auto&dispatch=direct", None)
        assert calls == [{"strict": False}]

    def test_endpoint_param_off_and_invalid(self, monkeypatch):
        from spicedb_kubeapi_proxy_tpu.parallel import distributed as dist
        from spicedb_kubeapi_proxy_tpu.spicedb import endpoints as eps
        monkeypatch.setattr(
            dist, "init_from_env",
            lambda *a, **k: (_ for _ in ()).throw(AssertionError("called")))
        eps.create_endpoint("jax://?distributed=false&dispatch=direct", None)
        with pytest.raises(eps.EndpointConfigError, match="invalid distributed"):
            eps.create_endpoint("jax://?distributed=bogus&dispatch=direct",
                                None)

    def test_strict_init_failure_is_config_error(self, monkeypatch):
        from spicedb_kubeapi_proxy_tpu.parallel import distributed as dist
        from spicedb_kubeapi_proxy_tpu.spicedb import endpoints as eps

        def boom(*a, **k):
            raise RuntimeError("no coordinator")

        monkeypatch.setattr(dist, "init_from_env", boom)
        with pytest.raises(eps.EndpointConfigError,
                           match="initialization failed"):
            eps.create_endpoint("jax://?distributed=1&dispatch=direct", None)

    def test_single_process_cluster_initializes(self):
        """num_processes=1 with an explicit coordinator really spins up
        the jax.distributed service — in a fresh subprocess, because
        initialize() must precede any XLA backend use in the process."""
        import pathlib
        import subprocess
        import sys
        repo = str(pathlib.Path(__file__).resolve().parent.parent)
        code = (
            "import socket\n"
            "from spicedb_kubeapi_proxy_tpu.parallel import distributed\n"
            "s = socket.socket(); s.bind((\"127.0.0.1\", 0))\n"
            "port = s.getsockname()[1]; s.close()\n"
            "assert distributed.init_from_env(\n"
            "    coordinator=f\"127.0.0.1:{port}\",\n"
            "    num_processes=1, process_id=0) is True\n"
            "assert distributed.is_initialized()\n"
            "print(\"DIST_OK\")\n")
        out = subprocess.run(
            [sys.executable, "-c", code], cwd=repo, capture_output=True,
            text=True, timeout=120,
            env={**os.environ, "JAX_PLATFORMS": "cpu"})
        assert "DIST_OK" in out.stdout, (out.stdout, out.stderr)
