"""Leader side of WAL-shipping replication: the ReplicationHub.

Serves the persistence data dir over the proxy's authenticated HTTP
surface (routes wired in proxy/server.py):

    GET /replication/manifest
        {"revision": N, "checkpoint": {...MANIFEST.json...} | null,
         "segments": [{"name", "seq", "size", "sealed"}...],
         "sidecars": ["snap-*.npz"...], "leader_id": "...",
         "incarnation": E, "fenced": {...} | null,
         "chain": {"path": [...], "lag_revisions": 0, "lag_seconds": 0}}
        ?wait_revision=R&timeout_ms=T long-polls until the store's
        revision EXCEEDS R (or the timeout lapses — the caller gets the
        current manifest either way and decides from `revision`).

    GET /replication/segment/<name>[?offset=N]
        Raw bytes of a WAL segment or bulk-load snapshot sidecar from
        byte N (also honors `Range: bytes=N-`).  206 on a partial
        serve, 404 when reclaimed — the follower's signal to
        re-bootstrap from the newest checkpoint.

    GET /replication/checkpoint/<name>
        Raw bytes of a columnar checkpoint file.

Names are validated against the exact artifact patterns before touching
the filesystem (no traversal).  The long-poll is fed by the store's
commit-listener hook: the hub attaches AFTER the PersistenceManager, so
by WAL-before-visibility ordering every revision a waiter is woken for
is already on disk and replayable.

Incarnation fencing (docs/replication.md "Failover runbook"): every hub
owns a monotonic integer **incarnation epoch**, persisted in the data
dir's INCARNATION file.  A restart-in-place mints `persisted + 1`; a
promotion (failover.py) mints `max(persisted, observed) + 2` so it
strictly dominates any later resurrection mint of the dead leader
(which can only reach `observed + 1`).  Followers reject manifests from
a lower epoch than the highest they have seen, and echo that highest
epoch back on every poll (`X-Replication-Incarnation`): a resurrected
ex-leader that receives a poll carrying a newer epoch marks itself
`fenced_by` — the server then rejects its update verbs 503 and (with
peers configured) demotes it into a follower of the new leader.
"""

from __future__ import annotations

import asyncio
import json
import os
import re
import threading
import time
import uuid
from typing import Optional
from urllib.parse import parse_qs, urlsplit

from ...utils import metrics as m
from ...utils.failpoints import fail_point
from ..store import TupleStore

_SAFE_NAME = re.compile(
    r"^(seg-\d{8}\.wal|snap-\d{12}\.npz|ckpt-\d{12}\.npz)$")

DEFAULT_LONGPOLL_S = 25.0
MAX_LONGPOLL_S = 60.0

# fencing exchange headers: followers echo the highest incarnation (and
# its leader id) they have ever observed on every /replication request
INCARNATION_HEADER = "X-Replication-Incarnation"
LEADER_ID_HEADER = "X-Replication-Leader-Id"

INCARNATION_FILE = "INCARNATION"


def safe_artifact_name(name: str) -> bool:
    """True when `name` is exactly one WAL segment / sidecar / checkpoint
    file name — the only paths the hub will ever read."""
    return bool(_SAFE_NAME.match(name))


# -- incarnation epoch persistence -------------------------------------------


def read_incarnation_state(data_dir: str) -> dict:
    """{"epoch": int, "fenced": {...}|None, "leader_ids": [...]} from
    the data dir's INCARNATION file; zeros when absent/damaged (a
    damaged epoch file only costs an extra re-bootstrap downstream —
    epochs restart conservatively low and fencing rejects them).
    `leader_ids` is the lineage of hub ids this data dir has served
    under — a rejoining ex-leader recognizes "the promotion superseded
    MY log" by the new leader's `fenced.leader_id` appearing here, even
    across its own restarts (each of which mints a fresh id)."""
    try:
        with open(os.path.join(data_dir, INCARNATION_FILE), "rb") as f:
            data = json.loads(f.read())
        if isinstance(data, dict) and isinstance(data.get("epoch"), int):
            return {"epoch": data["epoch"], "fenced": data.get("fenced"),
                    "leader_ids": list(data.get("leader_ids") or ())}
    except (OSError, ValueError):
        pass
    return {"epoch": 0, "fenced": None, "leader_ids": []}


def write_incarnation_state(data_dir: str, epoch: int,
                            fenced: Optional[dict],
                            leader_ids: Optional[list] = None) -> None:
    path = os.path.join(data_dir, INCARNATION_FILE)
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump({"epoch": int(epoch), "fenced": fenced,
                   # bounded lineage: old entries can only matter while
                   # a promotion that superseded them is still live
                   "leader_ids": list(leader_ids or ())[-16:]}, f)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)


def mint_restart_incarnation(data_dir: str, leader_id: str) -> tuple:
    """Restart-in-place mint: persisted + 1.  Returns (epoch, fenced)
    with any previously-recorded fenced info preserved, so a restarted
    promoted leader keeps advertising which log it superseded (rejoining
    ex-leaders bound their tail replay from it)."""
    state = read_incarnation_state(data_dir)
    epoch = state["epoch"] + 1
    write_incarnation_state(data_dir, epoch, state["fenced"],
                            state["leader_ids"] + [leader_id])
    return epoch, state["fenced"]


def mint_promotion_incarnation(data_dir: str, observed: int,
                               fenced: Optional[dict]) -> int:
    """Promotion mint: max(persisted, observed) + 2.  The +2 (vs the
    restart path's +1) makes a promotion epoch strictly dominate the
    epoch a later resurrection of the dead leader can mint (its
    persisted value is what this follower `observed`, so it resurrects
    at observed + 1 < observed + 2) — no tie, no split-brain."""
    state = read_incarnation_state(data_dir)
    epoch = max(state["epoch"], int(observed)) + 2
    write_incarnation_state(data_dir, epoch, fenced,
                            state["leader_ids"])
    return epoch


def append_leader_lineage(data_dir: str, leader_id: str) -> None:
    """Record `leader_id` in the data dir's hub-id lineage (promotion
    constructs its hub after minting the epoch)."""
    state = read_incarnation_state(data_dir)
    write_incarnation_state(data_dir, state["epoch"], state["fenced"],
                            state["leader_ids"] + [leader_id])


def leader_lineage(data_dir: str) -> list:
    return read_incarnation_state(data_dir)["leader_ids"]


# -- shared artifact byte serving --------------------------------------------


async def serve_artifact_file(req, path: str, kind: str,
                              shipped_counter, stats: dict) -> "Response":
    """Serve one artifact file's bytes with offset/Range semantics —
    shared by the leader hub and the follower fan-out hub (failover.py),
    so intermediates serve byte-identical responses to the leader's."""
    from ...proxy.httpcore import Response, json_response
    params = parse_qs(urlsplit(req.target).query)
    offset = 0
    raw_off = (params.get("offset") or ["0"])[0]
    range_hdr = req.headers.get("Range")
    try:
        offset = int(raw_off)
        if range_hdr:
            mm = re.match(r"^bytes=(\d+)-$", range_hdr.strip())
            if mm is None:
                raise ValueError(f"unsupported Range {range_hdr!r}")
            offset = int(mm.group(1))
    except ValueError as e:
        return json_response(400, {
            "kind": "Status", "apiVersion": "v1", "metadata": {},
            "status": "Failure", "code": 400, "message": str(e)})

    def _read():
        # a sealed segment is up to segment_bytes and a checkpoint
        # tens of MB — reading it synchronously would park the
        # serving event loop for the whole disk read, once per
        # follower fetch (analyzer A001 class); the read runs on an
        # executor thread
        size = os.path.getsize(path)
        with open(path, "rb") as f:
            if offset:
                f.seek(offset)
            return size, f.read()

    try:
        size, body = await asyncio.get_running_loop().run_in_executor(
            None, _read)
    except OSError:
        return json_response(404, {
            "kind": "Status", "apiVersion": "v1", "metadata": {},
            "status": "Failure", "reason": "NotFound", "code": 404,
            "message": f"artifact {os.path.basename(path)!r} is gone "
                       f"(reclaimed by a checkpoint?); re-bootstrap "
                       f"from /replication/manifest"})
    shipped_counter.inc(  # noqa: A004(only hubs built behind the gate call this)
        len(body), kind=kind)
    stats[f"{kind}_serves"] = stats.get(f"{kind}_serves", 0) + 1
    resp = Response(status=206 if offset else 200, body=body)
    resp.headers.set("Content-Type", "application/octet-stream")
    resp.headers.set("X-Replication-Offset", str(offset))
    resp.headers.set("X-Replication-Size", str(size))
    return resp


# gate-off = no hub exists (the server 503s /replication/* without
# constructing/attaching one), so nothing here can tick
class ReplicationHub:  # noqa: A004(built behind gate)
    """Publishes one PersistenceManager's data dir to followers."""

    def __init__(self, store: TupleStore, persistence,
                 leader_id: str = "",
                 incarnation: int = 0,
                 fenced: Optional[dict] = None,
                 registry: Optional[m.Registry] = None):
        self.store = store
        self.persistence = persistence
        # unique per INCARNATION, not per host: segment seqs restart
        # after a leader restart (reclaim empties the wal dir), so a
        # follower must detect "same name, different log" by the id
        # changing and re-bootstrap rather than resume its byte cursor
        self.leader_id = (leader_id
                          or f"leader-{os.getpid()}-{uuid.uuid4().hex[:8]}")
        # monotonic fencing epoch: callers (promotion) pass an explicit
        # epoch; a plain construction mints restart-in-place from the
        # data dir's INCARNATION file
        data_dir = getattr(persistence, "data_dir", None)
        if incarnation > 0:
            self.incarnation = int(incarnation)
            self.fenced = fenced
            if data_dir:
                append_leader_lineage(data_dir, self.leader_id)
        elif data_dir:
            self.incarnation, persisted_fenced = mint_restart_incarnation(
                data_dir, self.leader_id)
            self.fenced = fenced if fenced is not None else persisted_fenced
        else:  # persistence-less construction (unit tests)
            self.incarnation = 1
            self.fenced = fenced
        # set once a /replication poll (or a peer probe) proves a newer
        # incarnation exists: {"incarnation": E, "leader_id": id}.  The
        # server refuses update verbs while fenced — a resurrected
        # ex-leader must never take a write the fleet won't see.
        self.fenced_by: Optional[dict] = None
        # (loop, future) pairs parked in wait_for_revision; woken from
        # the commit listener via call_soon_threadsafe (the listener runs
        # under the store lock — it must only schedule, never block)
        self._waiters: list = []
        self._waiters_lock = threading.Lock()
        self._attached = False
        self.stats = {"manifest_serves": 0, "longpoll_waits": 0,
                      "segment_serves": 0, "checkpoint_serves": 0,
                      "fenced_polls": 0}
        registry = registry or m.REGISTRY
        self._shipped = registry.counter(
            "authz_replication_shipped_bytes_total",
            "Bytes of WAL segments / sidecars / checkpoints served to "
            "replication followers, by artifact kind",
            labels=("kind",))
        self._fenced_total = registry.counter(
            "authz_replication_fenced_total",
            "Incarnation-fencing events: stage=leader when this leader "
            "observed a newer incarnation and fenced itself, "
            "stage=follower when a follower rejected a stale leader's "
            "manifest", labels=("stage",))
        import weakref
        ref = weakref.ref(self)
        registry.gauge(
            "authz_replication_incarnation",
            "Current replication incarnation epoch (leader: own epoch; "
            "follower: highest epoch observed)",
            callback=lambda: (float(ref().incarnation)
                              if ref() is not None else 0.0))

    # -- commit hook ---------------------------------------------------------

    def attach(self) -> None:
        """Start waking long-poll waiters on commits.  Call AFTER the
        PersistenceManager attached: listener order is append order, so
        the WAL append precedes the wakeup for every commit."""
        if not self._attached:
            self.store.add_commit_listener(self._on_commit)
            self._attached = True

    def detach(self) -> None:
        if self._attached:
            self.store.remove_commit_listener(self._on_commit)
            self._attached = False

    def _on_commit(self, kind: str, revision: int, payload) -> None:
        # under the store lock — schedule only.  The waiter re-checks the
        # store revision on its own loop, which cannot run before this
        # commit completes and the new revision is reader-visible.
        with self._waiters_lock:
            waiters, self._waiters = self._waiters, []
        for loop, fut in waiters:
            try:
                loop.call_soon_threadsafe(self._resolve, fut)
            except RuntimeError:
                pass  # waiter's loop already closed

    @staticmethod
    def _resolve(fut) -> None:
        if not fut.done():
            fut.set_result(None)

    async def wait_for_revision(self, min_exclusive: int,
                                timeout_s: float) -> bool:
        """Park until store.revision > min_exclusive (True) or the
        timeout lapses (False)."""
        deadline = time.monotonic() + max(0.0, timeout_s)
        loop = asyncio.get_running_loop()
        while self.store.revision <= min_exclusive:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return False
            fut = loop.create_future()
            with self._waiters_lock:
                self._waiters.append((loop, fut))
            # re-check AFTER publishing the waiter: a commit landing
            # between the loop-condition read and the append above has
            # already drained the (then-empty) waiter list — without
            # this, that waiter sleeps the full timeout on a revision
            # that is long since visible
            if self.store.revision > min_exclusive:
                with self._waiters_lock:
                    try:
                        self._waiters.remove((loop, fut))
                    except ValueError:
                        pass
                return True
            try:
                await asyncio.wait_for(fut, remaining)
            except asyncio.TimeoutError:
                return self.store.revision > min_exclusive
            finally:
                with self._waiters_lock:
                    try:
                        self._waiters.remove((loop, fut))
                    except ValueError:
                        pass
        return True

    # -- fencing -------------------------------------------------------------

    def note_fenced(self, incarnation: int, leader_id: str) -> None:
        """Record that a strictly newer incarnation exists.  Idempotent;
        only the first observation (per newer epoch) counts a fencing
        event."""
        cur = self.fenced_by
        if cur is not None and cur["incarnation"] >= incarnation:
            return
        self.fenced_by = {"incarnation": int(incarnation),
                          "leader_id": leader_id}
        self._fenced_total.inc(stage="leader")

    def observe_poll_headers(self, req) -> None:
        """Fencing exchange: a follower's poll echoes the highest
        incarnation it has seen.  Newer than ours — or an epoch tie
        under a LARGER leader id (two sides of a partition promoting
        simultaneously mint the same epoch; the total order on
        (incarnation, leader_id) makes exactly one of them lose) =>
        we are superseded."""
        raw = req.headers.get(INCARNATION_HEADER)
        if not raw:
            return
        try:
            peer_inc = int(raw)
        except ValueError:
            return
        peer_lid = req.headers.get(LEADER_ID_HEADER)
        if peer_inc > self.incarnation or (
                peer_inc == self.incarnation
                and peer_lid and peer_lid > self.leader_id):
            self.stats["fenced_polls"] += 1
            self.note_fenced(peer_inc, peer_lid or "")

    # -- manifest ------------------------------------------------------------

    def manifest(self) -> dict:
        from ..persist import checkpoint as ckpt
        wal = self.persistence.wal
        segments = []
        for seq in wal.segment_seqs():
            path = wal._path(seq)
            try:
                size = os.path.getsize(path)
            except OSError:
                continue  # reclaimed between listdir and stat
            segments.append({
                "name": os.path.basename(path), "seq": seq, "size": size,
                # the open segment keeps growing; anything else is sealed
                "sealed": not (seq == wal._cur_seq
                               and wal._cur_file is not None),
            })
        sidecars = []
        try:
            for name in sorted(os.listdir(wal.dir)):
                if re.match(r"^snap-\d{12}\.npz$", name):
                    sidecars.append(name)
        except OSError:
            pass
        self.stats["manifest_serves"] += 1
        return {
            "leader_id": self.leader_id,
            "incarnation": self.incarnation,
            # which log this incarnation superseded at promotion (None
            # for a plain leader): a rejoining ex-leader whose id
            # matches bounds its unshipped-tail replay at `revision`
            "fenced": self.fenced,
            "revision": self.store.revision,
            "checkpoint": ckpt.read_manifest(self.persistence.data_dir),
            "segments": segments,
            "sidecars": sidecars,
            # chain provenance for fan-out trees: hop lags sum down the
            # chain (the leader is the root: zero lag by definition)
            "chain": {"path": [self.leader_id],
                      "lag_revisions": 0.0, "lag_seconds": 0.0},
            # wall-clock sample for the follower's clock-skew estimate
            # (authz_clock_skew_seconds); stamped at build time, i.e.
            # just before the response is written
            "server_time_unix": time.time(),
        }

    async def serve_manifest(self, req) -> "Response":
        from ...proxy.httpcore import json_response
        fail_point("replServeManifest")
        self.observe_poll_headers(req)
        params = parse_qs(urlsplit(req.target).query)
        wait_raw = (params.get("wait_revision") or [""])[0]
        if wait_raw:
            try:
                wait_rev = int(wait_raw)
                timeout_ms = float(
                    (params.get("timeout_ms")
                     or [str(DEFAULT_LONGPOLL_S * 1e3)])[0])
            except ValueError:
                return json_response(400, {
                    "kind": "Status", "apiVersion": "v1", "metadata": {},
                    "status": "Failure", "code": 400,
                    "message": "wait_revision/timeout_ms must be integers"})
            self.stats["longpoll_waits"] += 1
            await self.wait_for_revision(
                wait_rev, min(max(timeout_ms / 1e3, 0.0), MAX_LONGPOLL_S))
        return json_response(200, self.manifest())

    # -- artifact bytes ------------------------------------------------------

    async def serve_segment(self, req, name: str) -> "Response":
        from ...proxy.httpcore import json_response
        fail_point("replServeSegment")
        self.observe_poll_headers(req)
        if not safe_artifact_name(name) or name.startswith("ckpt-"):
            return json_response(400, {
                "kind": "Status", "apiVersion": "v1", "metadata": {},
                "status": "Failure", "code": 400,
                "message": f"invalid segment name {name!r}"})
        return await serve_artifact_file(
            req, os.path.join(self.persistence.wal.dir, name), "segment",
            self._shipped, self.stats)

    async def serve_checkpoint(self, req, name: str) -> "Response":
        from ...proxy.httpcore import json_response
        self.observe_poll_headers(req)
        if not safe_artifact_name(name) or not name.startswith("ckpt-"):
            return json_response(400, {
                "kind": "Status", "apiVersion": "v1", "metadata": {},
                "status": "Failure", "code": 400,
                "message": f"invalid checkpoint name {name!r}"})
        return await serve_artifact_file(
            req, os.path.join(self.persistence.ckpt_dir, name), "checkpoint",
            self._shipped, self.stats)

    def snapshot(self) -> dict:
        """/debug/replication payload (leader role)."""
        with self._waiters_lock:
            waiters = len(self._waiters)
        man = self.manifest()
        return {"role": "leader", "leader_id": self.leader_id,
                "incarnation": self.incarnation,
                "fenced": self.fenced,
                "fenced_by": self.fenced_by,
                "revision": man["revision"],
                "checkpoint_revision": (man["checkpoint"] or {}).get(
                    "revision"),
                "segments": man["segments"],
                "longpoll_waiters": waiters,
                **self.stats}
