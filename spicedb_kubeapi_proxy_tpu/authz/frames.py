"""Watch frame capture (reference pkg/authz/frames.go).

Kube JSON watch streams are newline-delimited; each complete line is one
frame whose raw bytes must be preserved for byte-exact replay.  This
generator re-chunks an arbitrary byte stream into raw frame lines,
buffering partial lines across chunks (the mutex-guarded capture window in
the reference becomes plain sequential buffering here).
"""

from __future__ import annotations

from typing import AsyncIterator


async def frame_lines(stream: AsyncIterator[bytes]) -> AsyncIterator[bytes]:
    buf = bytearray()
    async for chunk in stream:
        buf.extend(chunk)
        while True:
            idx = buf.find(b"\n")
            if idx < 0:
                break
            frame = bytes(buf[: idx + 1])
            del buf[: idx + 1]
            yield frame
    if buf:
        yield bytes(buf)


# largest accepted watch frame: a corrupt/desynchronized length prefix must
# fail fast, not buffer the rest of the stream (the real apiserver caps
# request/response object sizes well below this)
MAX_WATCH_FRAME = 16 << 20


async def frame_length_delimited(
        stream: AsyncIterator[bytes]) -> AsyncIterator[bytes]:
    """k8s protobuf watch framing: 4-byte big-endian length + payload
    (k8s.io/apimachinery/pkg/util/framer).  Yields raw frames INCLUDING the
    length prefix so allowed frames replay byte-exactly.  A truncated
    trailing frame (stream ended mid-frame) is dropped, never relayed; a
    length prefix beyond MAX_WATCH_FRAME terminates the stream with an
    error log (fail fast, bounded memory)."""
    buf = bytearray()
    async for chunk in stream:
        buf.extend(chunk)
        while len(buf) >= 4:
            ln = int.from_bytes(buf[:4], "big")
            if ln > MAX_WATCH_FRAME:
                import logging
                logging.getLogger(__name__).error(
                    "watch frame length %d exceeds cap %d — corrupt or "
                    "desynchronized stream; terminating watch", ln,
                    MAX_WATCH_FRAME)
                return
            if len(buf) < 4 + ln:
                break
            yield bytes(buf[: 4 + ln])
            del buf[: 4 + ln]
