"""Device telemetry & flight recorder (utils/devtel.py).

Covers the four surfaces: HBM ledger byte-exactness across rebuilds and
warm starts (the leak-detection contract), jit-cache/recompile-storm
accounting, batch-occupancy recording on the real kernel path, and the
flight recorder's window snapshots + SLO burn-rate math (asserting the
worked example documented in docs/observability.md), plus the uniform
/debug surface handling in the proxy server.
"""

import asyncio
import json
import time

import pytest

from spicedb_kubeapi_proxy_tpu.ops.jax_endpoint import JaxEndpoint
from spicedb_kubeapi_proxy_tpu.spicedb import schema as sch
from spicedb_kubeapi_proxy_tpu.spicedb.types import (
    CheckRequest,
    ObjectRef,
    RelationshipUpdate,
    SubjectRef,
    UpdateOp,
    parse_relationship,
)
from spicedb_kubeapi_proxy_tpu.utils import devtel
from spicedb_kubeapi_proxy_tpu.utils import metrics as m

SCHEMA = """
definition user {}

definition doc {
    relation viewer: user
    permission view = viewer
}
"""


def touch(*rels):
    return [RelationshipUpdate(UpdateOp.TOUCH, parse_relationship(r))
            for r in rels]


def run(coro):
    return asyncio.run(coro)


def make_endpoint(n_docs=6):
    schema = sch.parse_schema(SCHEMA)
    ep = JaxEndpoint(schema)
    ep.store.write(touch(*[f"doc:d{i}#viewer@user:u{i % 3}"
                           for i in range(n_docs)]))
    return ep


# -- HBM ledger ---------------------------------------------------------------


class TestHbmLedger:
    def test_register_unregister_accounting(self):
        led = devtel.HbmLedger(registry=m.Registry())
        led.register("tables", 1000, generation=1, name="main")
        led.register("tables", 500, generation=1, name="aux")
        led.register("id_view", 200, generation=1, name="ids:doc")
        assert led.total() == 1700
        assert led.totals() == {"id_view": 200, "tables": 1500}
        assert led.generation_bytes(1) == 1700
        # re-registration replaces (delta accounting), never double-counts
        led.register("tables", 800, generation=1, name="main")
        assert led.total() == 1500
        assert led.unregister("id_view", generation=1, name="ids:doc") == 200
        assert led.total() == 1300
        # unregistering an unknown buffer is a no-op, not an error
        assert led.unregister("id_view", generation=9, name="nope") == 0

    def test_defer_retire_reaped_by_next_operation(self):
        """Graph finalizers must not take the ledger lock (they run
        inside gc on a thread that may already hold it): defer_retire
        only queues, and the next ledger operation reaps."""
        led = devtel.HbmLedger(registry=m.Registry())
        led.register("tables", 1000, generation=1)
        led.register("tables", 500, generation=2)
        led.defer_retire(1)   # lock-free: safe from a finalizer
        assert led.total() == 500  # reaped on entry
        assert led.generation_bytes(1) == 0
        led.defer_retire(2)
        led.register("tables", 64, generation=3)
        assert led.totals() == {"tables": 64}

    def test_retire_generation_and_peak(self):
        led = devtel.HbmLedger(registry=m.Registry())
        led.register("tables", 1000, generation=1)
        led.register("tables", 2000, generation=2)
        assert led.peak == 3000
        assert led.retire_generation(1) == 1000
        assert led.total() == 2000
        assert led.peak == 3000  # high-water survives the retire
        assert led.generation_bytes(1) == 0

    def test_scratch_replaces_not_accumulates(self):
        led = devtel.HbmLedger(registry=m.Registry())
        led.note_scratch(4096)
        led.note_scratch(1024)
        assert led.totals() == {"scratch": 1024}
        assert led.peak == 4096

    def test_gate_blocks_additions_but_not_cleanup(self):
        """The DeviceTelemetry killswitch stops new recording, but
        unregister/retire always run so toggling the gate never strands
        ledger entries."""
        from spicedb_kubeapi_proxy_tpu.utils.features import GATES
        led = devtel.HbmLedger(registry=m.Registry())
        led.register("tables", 1000, generation=1)
        GATES.set("DeviceTelemetry", False)
        try:
            led.register("tables", 500, generation=2)
            led.note_scratch(4096)
            assert led.total() == 1000  # additions gated off
            assert led.retire_generation(1) == 1000  # cleanup still runs
            assert led.total() == 0
        finally:
            GATES.set("DeviceTelemetry", True)


def flush_dead_generations():
    """Endpoints are reference-cyclic (store->listener->endpoint), so
    prior tests' graphs die at an arbitrary later gc — firing the
    ledger's auto-retire finalizers mid-assertion.  Collect NOW so the
    totals captured below only move through this test's actions."""
    import gc
    gc.collect()


class TestLedgerRebuildRegression:
    """The rebuild contract: after a graph rebuild the ledger total must
    equal (old total − old generation + new generation) byte-exactly —
    i.e. a retained old-generation buffer is immediately visible."""

    def test_rebuild_returns_ledger_to_exact_total(self):
        ep = make_endpoint()
        # warm: build the graph and materialize an id view
        run(ep.lookup_resources("doc", "view", SubjectRef("user", "u0")))
        flush_dead_generations()
        gen1 = ep._devtel_gen
        assert gen1 >= 1
        g1_bytes = devtel.LEDGER.generation_bytes(gen1)
        assert g1_bytes > 0
        total_before = devtel.LEDGER.total()

        ep.force_rebuild()
        # re-materialize the id view on the new generation too
        run(ep.lookup_resources("doc", "view", SubjectRef("user", "u0")))
        gen2 = ep._devtel_gen
        assert gen2 > gen1
        g2_bytes = devtel.LEDGER.generation_bytes(gen2)
        assert g2_bytes > 0
        assert devtel.LEDGER.generation_bytes(gen1) == 0, \
            "old generation retained buffers after rebuild"
        assert devtel.LEDGER.total() == total_before - g1_bytes + g2_bytes

    def test_warm_start_registers_generation(self):
        ep = make_endpoint()
        flush_dead_generations()
        before = devtel.LEDGER.total()
        ep.warm_start()
        gen = ep._devtel_gen
        assert gen >= 1
        g = devtel.LEDGER.generation_bytes(gen)
        assert g > 0
        assert devtel.LEDGER.total() == before + g
        # warm_start is idempotent: no duplicate registration
        total = devtel.LEDGER.total()
        ep.warm_start()
        assert devtel.LEDGER.total() == total

    def test_delta_rebuild_accounts_exactly(self):
        """A rebuild forced by a delta outside the compiled universe
        (wildcard) follows the same exact-accounting contract."""
        ep = make_endpoint()
        run(ep.check_permission(CheckRequest(
            ObjectRef("doc", "d0"), "view", SubjectRef("user", "u0"))))
        flush_dead_generations()
        gen1 = ep._devtel_gen
        g1_bytes = devtel.LEDGER.generation_bytes(gen1)
        total_before = devtel.LEDGER.total()
        ep.store.write(touch("doc:d0#viewer@user:*"))
        run(ep.check_permission(CheckRequest(
            ObjectRef("doc", "d0"), "view", SubjectRef("user", "zz"))))
        # the wildcard delta quarantines its pairs and rebuilds OFF-LOOP
        # (AsyncRebuild default): the old generation keeps serving until
        # the candidate installs, so wait for the swap instead of racing
        # the background executor
        deadline = time.time() + 10.0
        while ep._devtel_gen == gen1 and time.time() < deadline:
            time.sleep(0.01)
            run(ep.check_permission(CheckRequest(
                ObjectRef("doc", "d0"), "view",
                SubjectRef("user", "zz"))))
        gen2 = ep._devtel_gen
        assert gen2 > gen1
        assert devtel.LEDGER.generation_bytes(gen1) == 0
        assert devtel.LEDGER.total() == (
            total_before - g1_bytes + devtel.LEDGER.generation_bytes(gen2))


# -- kernel & compile accounting ----------------------------------------------


class TestKernelAccounting:
    def test_hit_miss_and_storm_detection(self, caplog):
        ka = devtel.KernelAccounting(registry=m.Registry())
        t0 = 1000.0
        ka.note_compile(64, now=t0)
        ka.note_jit_hit(64)
        ka.note_jit_hit(64)
        snap = ka.snapshot()
        assert snap["hits"] == 2 and snap["misses"] == 1
        assert snap["storms"] == 0
        # recompiles of ONE bucket inside the window: the threshold+1'th
        # raises the storm counter and a slow-log line
        for i in range(devtel.STORM_THRESHOLD):
            ka.note_compile(64, now=t0 + i)
        assert ka.snapshot()["storms"] == 1
        # compiles outside the window never count toward a storm
        ka.note_compile(128, now=t0)
        ka.note_compile(128, now=t0 + devtel.STORM_WINDOW_S + 1)
        assert ka.snapshot()["storms"] == 1

    def test_entries_gauge_tracks_live_caches(self):
        ka = devtel.KernelAccounting(registry=m.Registry())

        class FakeCache:
            def __init__(self):
                self._jits = {}

        c = FakeCache()
        ka.track(c)
        assert ka.snapshot()["entries"] == 0
        c._jits[8] = object()
        c._jits[16] = object()
        assert ka.snapshot()["entries"] == 2
        del c  # dropped cache disappears from the count (weakref)
        assert ka.snapshot()["entries"] == 0

    def test_real_kernel_populates_accounting(self):
        ep = make_endpoint()
        before = devtel.KERNELS.snapshot()
        s = SubjectRef("user", "u0")
        run(ep.lookup_resources("doc", "view", s))
        run(ep.lookup_resources("doc", "view", s))  # same bucket: a hit
        after = devtel.KERNELS.snapshot()
        assert after["misses"] > before["misses"]
        assert after["hits"] > before["hits"]
        assert after["time_by_bucket_s"], \
            "kernel spans recorded no per-bucket device time"


# -- batch occupancy ----------------------------------------------------------


class TestBatchOccupancy:
    def test_record_and_mean(self):
        occ = devtel.BatchOccupancy(registry=m.Registry())
        occ.record("lookup", 3, 29)   # 3 useful lanes in a 32-wide bucket
        occ.record("lookup", 32, 0)
        occ.note_collapsed(5)
        snap = occ.snapshot()
        assert snap["batches"] == 2
        assert snap["useful"] == 35 and snap["padded"] == 29
        assert snap["collapsed"] == 5
        assert snap["mean"] == round(35 / 64, 4)

    def test_kernel_path_records_occupancy(self):
        before = devtel.OCCUPANCY.snapshot()
        ep = make_endpoint()
        run(ep.lookup_resources_batch(
            "doc", "view", [SubjectRef("user", f"u{i}") for i in range(3)]))
        run(ep.check_bulk_permissions([
            CheckRequest(ObjectRef("doc", "d0"), "view",
                         SubjectRef("user", "u0"))]))
        after = devtel.OCCUPANCY.snapshot()
        assert after["batches"] > before["batches"]
        assert after["padded"] > before["padded"], \
            "pow-2 bucketing produced no measured padding"

    def test_singleflight_collapse_counted(self):
        from spicedb_kubeapi_proxy_tpu.spicedb.dispatch import (
            BatchingEndpoint)
        before = devtel.OCCUPANCY.snapshot()["collapsed"]
        ep = BatchingEndpoint(make_endpoint())
        s = SubjectRef("user", "u0")

        async def go():
            return await asyncio.gather(*[
                ep.lookup_resources("doc", "view", s) for _ in range(4)])

        results = run(go())
        assert all(sorted(r) == sorted(results[0]) for r in results)
        # at least the duplicates queued behind the first leader collapse
        assert devtel.OCCUPANCY.snapshot()["collapsed"] > before


# -- snapshot / diff ----------------------------------------------------------


class TestSnapshotDiff:
    def test_diff_snapshot_subtracts_counters(self):
        a = {"hbm_bytes": {}, "hbm_total_bytes": 10, "hbm_peak_bytes": 20,
             "jit": {"hits": 1, "misses": 2, "storms": 0, "entries": 2,
                     "time_by_bucket_s": {"64": 1.0}},
             "occupancy": {"batches": 1, "useful": 10, "padded": 22,
                           "collapsed": 0, "mean": 0.3125}}
        b = {"hbm_bytes": {"ell_main": 100}, "hbm_total_bytes": 100,
             "hbm_peak_bytes": 120,
             "jit": {"hits": 5, "misses": 3, "storms": 1, "entries": 3,
                     "time_by_bucket_s": {"64": 1.5, "128": 0.25}},
             "occupancy": {"batches": 3, "useful": 42, "padded": 54,
                           "collapsed": 4, "mean": 0.4375}}
        d = devtel.diff_snapshot(a, b)
        assert d["jit_hits"] == 4 and d["recompiles"] == 1
        assert d["recompile_storms"] == 1
        assert d["hbm_peak_bytes"] == 120
        assert d["batches"] == 2
        assert d["mean_batch_occupancy"] == 0.5  # (42-10)/(32+32)
        assert d["collapsed_duplicates"] == 4
        assert d["kernel_time_by_bucket_s"] == {"64": 0.5, "128": 0.25}


# -- flight recorder + SLO burn rates ----------------------------------------


def make_http_registry():
    reg = m.Registry()
    lat = reg.histogram("proxy_http_request_seconds", "", labels=("verb",),
                        buckets=(0.1, 0.25, 0.5, 1.0))
    codes = reg.counter("proxy_http_requests_total", "",
                        labels=("verb", "code"))
    phases = reg.histogram("authz_request_phase_seconds", "",
                           labels=("phase",), buckets=(0.1, 0.25, 0.5, 1.0))
    return reg, lat, codes, phases


class TestFlightRecorder:
    def test_windows_and_quantiles(self):
        reg, _lat, _codes, phases = make_http_registry()
        fr = devtel.FlightRecorder(window_s=1.0, capacity=4, registry=reg)
        fr.capture(now=time.time())
        for _ in range(90):
            fr.observe_request(0.05, 200)
            phases.observe(0.05, phase="execute")
        for _ in range(10):
            fr.observe_request(0.4, 200)
            phases.observe(0.4, phase="execute")
        snap = fr.capture(now=time.time())
        assert snap["http"]["requests"] == 100
        assert snap["http"]["error_rate"] == 0.0
        # http quantiles come from the exact per-window sample
        assert snap["http"]["latency_p50_ms"] == 50.0
        assert snap["http"]["latency_p99_ms"] == 400.0
        # phase quantiles come from histogram-bucket deltas
        assert snap["phases"]["execute"]["count"] == 100
        assert 250 <= snap["phases"]["execute"]["p99_ms"] <= 500
        # ring serves newest first, internal tallies stripped
        out = fr.snapshots()
        assert len(out) == 2
        assert out[0]["ts"] >= out[1]["ts"]
        assert all(not k.startswith("_") for s in out for k in s)

    def test_first_window_does_not_inherit_process_history(self):
        """The delta baseline is primed at construction: cumulative
        metrics observed BEFORE the recorder exists must not be billed
        to window 1."""
        reg, _lat, _codes, phases = make_http_registry()
        for _ in range(500):
            phases.observe(0.05, phase="execute")
        fr = devtel.FlightRecorder(window_s=1.0, capacity=4, registry=reg)
        snap = fr.capture()
        assert snap["phases"] == {}, snap["phases"]
        assert snap["http"]["requests"] == 0
        phases.observe(0.05, phase="execute")
        snap = fr.capture()
        assert snap["phases"]["execute"]["count"] == 1

    def test_burn_rate_worked_example(self):
        """The docs/observability.md example: target p99 250ms with a 1%
        budget; a window where 5% of requests exceed 250ms burns at 5x."""
        reg, _lat, _codes, _ = make_http_registry()
        slo = devtel.Slo("latency_p99", "latency", objective=0.01,
                         threshold_s=0.25)
        err = devtel.Slo("error_rate", "error", objective=0.001)
        fr = devtel.FlightRecorder(window_s=1.0, capacity=8,
                                   slos=(slo, err), registry=reg,
                                   long_windows=4)
        fr.capture()
        for _ in range(95):
            fr.observe_request(0.05, 200)
        for _ in range(5):
            fr.observe_request(0.6, 500)
        snap = fr.capture()
        assert snap["slo"]["latency_p99"]["short"] == pytest.approx(5.0)
        assert snap["slo"]["latency_p99"]["burning"] is True
        # 5% errors against a 0.1% budget burns at 50x
        assert snap["slo"]["error_rate"]["short"] == pytest.approx(50.0)
        burning = {b["slo"] for b in fr.burning()}
        assert burning == {"latency_p99", "error_rate"}
        # burn-rate gauges exported with slo= and window= labels
        text = reg.render()
        assert 'authz_slo_burn_rate{slo="latency_p99",window="short"} 5' \
            in text
        # a clean window recovers the short horizon; the long horizon
        # still remembers the burn (multi-window evaluation)
        for _ in range(100):
            fr.observe_request(0.05, 200)
        snap = fr.capture()
        assert snap["slo"]["latency_p99"]["short"] == 0.0
        assert snap["slo"]["latency_p99"]["long"] == pytest.approx(2.5)
        assert snap["slo"]["latency_p99"]["burning"] is False

    def test_long_horizon_clamped_to_ring_capacity(self):
        """A small --flight-windows ring must not silently promise a
        12-window long horizon it cannot hold."""
        reg = m.Registry()
        fr = devtel.FlightRecorder(window_s=1.0, capacity=4,
                                   long_windows=12, registry=reg)
        assert fr.long_windows == 4

    def test_observe_request_exact_threshold(self):
        """SLO intake counts at the exact threshold (no histogram-bucket
        snapping): a request exactly AT the target is good."""
        slo = devtel.Slo("latency_p99", "latency", objective=0.5,
                         threshold_s=0.25)
        fr = devtel.FlightRecorder(window_s=1.0, capacity=4, slos=(slo,),
                                   registry=m.Registry())
        fr.observe_request(0.25, 200)   # at the target: good
        fr.observe_request(0.2501, 200)  # over: bad
        snap = fr.capture()
        assert snap["_slo_tallies"]["latency_p99"] == (1, 2)


# -- /debug surfaces + readyz -------------------------------------------------


SERVER_SCHEMA = """
definition user {}

definition pod {
    relation creator: user
    permission view = creator
}
"""

SERVER_RULES = """
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: get-pods}
match: [{apiVersion: v1, resource: pods, verbs: [get]}]
check: [{tpl: "pod:{{namespacedName}}#view@user:{{user.name}}"}]
"""


def make_server(**extra):
    from spicedb_kubeapi_proxy_tpu.kubefake.apiserver import FakeKubeApiServer
    from spicedb_kubeapi_proxy_tpu.proxy.httpcore import HandlerTransport
    from spicedb_kubeapi_proxy_tpu.proxy.server import Options, ProxyServer
    from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import Bootstrap

    kube = FakeKubeApiServer()
    kube.seed("", "v1", "pods",
              {"metadata": {"name": "p0", "namespace": "team-a"}})
    server = ProxyServer(Options(
        spicedb_endpoint="embedded://",
        bootstrap=Bootstrap(schema_text=SERVER_SCHEMA),
        rules_yaml=SERVER_RULES,
        upstream_transport=HandlerTransport(kube),
        slo_check_p99_ms=250.0,
        **extra))
    server.endpoint.store.write(touch("pod:team-a/p0#creator@user:alice"))
    return server


class TestDebugSurfaces:
    def test_index_enumerates_all_surfaces(self):
        server = make_server()
        alice = server.get_embedded_client(user="alice")

        async def go():
            resp = await alice.get("/debug")
            assert resp.status == 200
            surfaces = json.loads(resp.body)["surfaces"]
            assert set(surfaces) == {"/debug/traces", "/debug/decisions",
                                     "/debug/flight", "/debug/timeline",
                                     "/debug/replication",
                                     "/debug/sharding", "/debug/fleet",
                                     "/debug/tail", "/debug/workload",
                                     "/debug/profile"}
            for desc in surfaces.values():
                assert isinstance(desc, str) and desc
        run(go())

    def test_unknown_surface_uniform_404(self):
        server = make_server()
        alice = server.get_embedded_client(user="alice")

        async def go():
            resp = await alice.get("/debug/bogus")
            assert resp.status == 404
            body = json.loads(resp.body)
            assert body["reason"] == "NotFound"
        run(go())

    def test_surfaces_unauthenticated_401(self):
        server = make_server()
        anon = server.get_embedded_client()

        async def go():
            for path in ("/debug", "/debug/traces", "/debug/decisions",
                         "/debug/flight", "/debug/timeline",
                         "/debug/workload", "/debug/profile"):
                resp = await anon.get(path)
                assert resp.status == 401, path
        run(go())

    def test_flight_serves_windows_after_capture(self):
        server = make_server()
        alice = server.get_embedded_client(user="alice")

        async def go():
            await alice.get("/api/v1/namespaces/team-a/pods/p0")
            server.flight.capture()
            server.flight.capture()
            resp = await alice.get("/debug/flight")
            assert resp.status == 200
            flight = json.loads(resp.body)
            assert flight["enabled"] is True
            assert len(flight["windows"]) == 2
            assert flight["slos"][0]["name"] == "latency_p99"
            newest = flight["windows"][0]
            for field in ("http", "phases", "hbm", "occupancy", "jit",
                          "slo", "cache", "queues"):
                assert field in newest
        run(go())

    def test_readyz_surfaces_burning_slo(self):
        server = make_server()
        alice = server.get_embedded_client(user="alice")

        async def go():
            resp = await alice.get("/readyz")
            assert resp.status == 200 and resp.body == b"ok"
            # force a burn: every request slower than the 250ms target
            server.flight.capture()
            for _ in range(10):
                server.flight.observe_request(0.9, 200)
            server.flight.capture()
            resp = await alice.get("/readyz")
            assert resp.status == 200
            assert b"slo latency_p99 burning" in resp.body
        run(go())

    def test_health_and_introspection_do_not_dilute_slo(self):
        """Health probes and /metrics//debug scrapes are untraced and
        must not feed the SLO tallies — only proxied API requests do."""
        server = make_server()
        alice = server.get_embedded_client(user="alice")

        async def go():
            server.flight.capture()
            for _ in range(20):
                await alice.get("/readyz")
                await alice.get("/metrics")
                await alice.get("/debug/flight")
                await alice.get("/debug/")  # index via trailing slash
            resp = await alice.get("/api/v1/namespaces/team-a/pods/p0")
            assert resp.status == 200
            snap = server.flight.capture()
            _bad, total = snap["_slo_tallies"]["latency_p99"]
            assert total == 1, (
                f"probe/scrape traffic leaked into the SLO base: {total}")
            # the window's http stats are proxied-only too
            assert snap["http"]["requests"] == 1
        run(go())

    def test_flight_reports_gate_state(self):
        from spicedb_kubeapi_proxy_tpu.utils.features import GATES
        server = make_server()
        alice = server.get_embedded_client(user="alice")
        GATES.set("DeviceTelemetry", False)
        try:
            async def go():
                resp = await alice.get("/debug/flight")
                flight = json.loads(resp.body)
                assert flight["enabled"] is False
                assert "gate" in flight["reason"]
            run(go())
        finally:
            GATES.set("DeviceTelemetry", True)
