"""Random schema generator for the differential fuzzer.

Generates schema SOURCE TEXT (artifacts must be self-contained and
human-readable), constrained so that:

- it parses and validates (`spicedb.schema.parse_schema`);
- `--lint-schema` reports no ERRORS (the only schema-only error class,
  SL005, cannot be emitted: every caveat a relation names is defined);
- permission expressions stay within a bounded rewrite depth;
- arrows only target types defined EARLIER in the emission order, so
  cross-type permission references form a DAG (tuple-graph recursion —
  `group#member` self-usersets — is still generated: the kernels
  iterate it, the evaluator cycle-detects it);
- every shape the kernels special-case appears with tunable bias:
  wildcards (`user:*`), CEL caveats (decided and undecidable), expiring
  relations, caveat+expiration combos, intersections/exclusions, and
  multi-hop arrow chains.

`generate_schema` draws several candidates and keeps the one whose
permissions have the largest summed `relation_footprint` closure —
the Cedar-style analyzability metric biasing the fuzzer toward
deep/entangled closures instead of trivially-shallow schemas.
"""

from __future__ import annotations

import random

from ..ops.graph_compile import relation_footprint
from ..spicedb import schema as sch

# subject-relation pool for object definitions; names are cosmetic but
# stable so seeds stay readable
_TYPE_POOL = ("org", "folder", "doc", "proj", "ns", "pod", "board")
_REL_POOL = ("viewer", "editor", "owner", "reader", "writer", "auditor",
             "banned", "approved", "assigned", "pinned")
_PERM_POOL = ("view", "edit", "admin", "audit", "operate")

_CAVEAT_BODIES = (
    ("cur int, max int", "cur < max"),
    ("used int, quota int", "used + 1 < quota"),
    ("level int", "level > 2"),
)


class SchemaBias:
    """Knobs the scenario profiles (fuzz/scenarios.py) and the smoke
    size cap turn."""

    def __init__(self, wildcard=0.18, caveat=0.22, expiration=0.18,
                 userset=0.45, arrow=0.5, exclusion=0.35,
                 intersection=0.35, n_types=(2, 2, 3, 3, 4),
                 n_rels=(2, 2, 3, 3, 4), n_perms=(1, 2, 2),
                 expr_depth=2):
        self.wildcard = wildcard
        self.caveat = caveat
        self.expiration = expiration
        self.userset = userset
        self.arrow = arrow
        self.exclusion = exclusion
        self.intersection = intersection
        self.n_types = n_types
        self.n_rels = n_rels
        self.n_perms = n_perms
        self.expr_depth = expr_depth


DEFAULT_BIAS = SchemaBias()

# the fixed-seed smoke matrix: same shape universe (wildcards, caveats,
# expirations, usersets, arrows, exclusions) but bounded schema size so
# a cell's kernel compile stays cheap — the open-ended budgeted search
# runs DEFAULT_BIAS depth
SMOKE_BIAS = SchemaBias(n_types=(2, 2, 2), n_rels=(2, 2, 3),
                        n_perms=(1, 1, 2), expr_depth=1)


def _gen_caveats(rng: random.Random) -> list:
    n = rng.choice((0, 1, 1, 2))
    out = []
    for i in range(n):
        params, body = _CAVEAT_BODIES[rng.randrange(len(_CAVEAT_BODIES))]
        out.append((f"cav{i}", params, body))
    return out


def _gen_relation_refs(rng: random.Random, bias: SchemaBias, caveats: list,
                       has_group: bool, earlier_types: list) -> list:
    """One relation's `|`-union of TypeRef source strings."""
    refs = []
    n_refs = rng.choice((1, 1, 2, 2, 3))
    for _ in range(n_refs):
        roll = rng.random()
        if roll < bias.wildcard:
            base = "user:*"
        elif roll < bias.wildcard + bias.userset and has_group:
            base = "group#member"
        elif (roll < bias.wildcard + bias.userset + 0.2
                and earlier_types and rng.random() < 0.6):
            # object-valued relation: the raw material for arrows
            base = rng.choice(earlier_types)
        else:
            base = "user"
        traits = []
        if base == "user":
            # SpiceDB trait rules: `user with c` accepts ONLY c-caveated
            # tuples, so plain/caveated/expiring variants are separate refs
            if caveats and rng.random() < bias.caveat:
                traits.append(rng.choice(caveats)[0])
            if rng.random() < bias.expiration:
                traits.append("expiration")
        elif base == "group#member" and rng.random() < bias.expiration * 0.6:
            traits.append("expiration")
        refs.append(base + (" with " + " and ".join(traits) if traits else ""))
    # dedupe while keeping order; always keep at least one ref
    seen, out = set(), []
    for r in refs:
        if r not in seen:
            seen.add(r)
            out.append(r)
    return out


def _gen_perm_expr(rng: random.Random, bias: SchemaBias, relations: dict,
                   earlier_perms: list, arrow_targets: dict,
                   depth: int = 0) -> str:
    """Random permission expression of bounded depth.

    `relations`: name -> ref strings for THIS definition;
    `arrow_targets`: object-valued relation name -> candidate target
    names on its subject types (earlier types only: cross-type DAG)."""

    def leaf() -> str:
        choices = list(relations)
        if earlier_perms:
            choices += earlier_perms
        if arrow_targets and rng.random() < bias.arrow:
            left = rng.choice(sorted(arrow_targets))
            return f"{left}->{rng.choice(sorted(arrow_targets[left]))}"
        return rng.choice(choices)

    if depth >= bias.expr_depth or rng.random() < 0.35:
        return leaf()
    a = _gen_perm_expr(rng, bias, relations, earlier_perms, arrow_targets,
                       depth + 1)
    b = _gen_perm_expr(rng, bias, relations, earlier_perms, arrow_targets,
                       depth + 1)
    roll = rng.random()
    if roll < bias.exclusion:
        expr = f"{a} - {b}"
    elif roll < bias.exclusion + bias.intersection:
        expr = f"{a} & {b}"
    else:
        expr = f"{a} + {b}"
    return f"({expr})" if depth > 0 else expr


def _gen_once(rng: random.Random, bias: SchemaBias) -> str:
    caveats = _gen_caveats(rng)
    has_group = rng.random() < 0.85
    n_types = rng.choice(bias.n_types)
    type_names = list(_TYPE_POOL[:n_types])
    rng.shuffle(type_names)

    lines = []
    for name, params, body in caveats:
        lines.append(f"caveat {name}({params}) {{ {body} }}")
    lines.append("definition user {}")
    if has_group:
        member_refs = ["user", "group#member"]
        if caveats and rng.random() < bias.caveat:
            member_refs.append(f"user with {caveats[0][0]}")
        lines.append("definition group { relation member: "
                     + " | ".join(member_refs) + " }")

    # (type, perm-or-rel names) emitted so far, for arrow targets
    emitted: dict = {}
    if has_group:
        emitted["group"] = ["member"]
    for ti, tname in enumerate(type_names):
        earlier = [t for t in type_names[:ti]]
        n_rels = rng.choice(bias.n_rels)
        relations: dict = {}
        rel_names = list(_REL_POOL)
        rng.shuffle(rel_names)
        for rname in rel_names[:n_rels]:
            relations[rname] = _gen_relation_refs(
                rng, bias, caveats, has_group, earlier)
        # arrow raw material: relations whose refs include a direct
        # object type (subject id walkable by an arrow)
        arrow_targets: dict = {}
        for rname, refs in relations.items():
            targets: set = set()
            for ref in refs:
                base = ref.split(" with ")[0]
                if base in emitted:
                    targets.update(emitted[base])
            if targets:
                arrow_targets[rname] = targets
        body = [f"  relation {rname}: {' | '.join(refs)}"
                for rname, refs in relations.items()]
        perms = []
        n_perms = rng.choice(bias.n_perms)
        perm_names = list(_PERM_POOL)
        rng.shuffle(perm_names)
        for pname in perm_names[:n_perms]:
            expr = _gen_perm_expr(rng, bias, relations, perms, arrow_targets)
            body.append(f"  permission {pname} = {expr}")
            perms.append(pname)
        lines.append(f"definition {tname} {{\n" + "\n".join(body) + "\n}")
        emitted[tname] = list(relations) + perms
    return "\n".join(lines) + "\n"


def footprint_score(schema: sch.Schema) -> int:
    """Entanglement metric: summed footprint closure over every
    permission plus the rewrite depth — bigger = deeper/more entangled."""
    total = 0
    for tname, d in schema.definitions.items():
        for pname in d.permissions:
            total += len(relation_footprint(schema, tname, pname))
    return total + schema.max_rewrite_depth()


def generate_schema(seed: int, bias: SchemaBias = DEFAULT_BIAS,
                    candidates: int = 3):
    """-> (schema_text, parsed Schema). Draws `candidates` schemas from
    sub-seeds of `seed` and keeps the one with the largest
    `footprint_score` — the relation_footprint bias toward
    deep/entangled closures."""
    best = None
    for k in range(candidates):
        # stable cross-process sub-seed (str hash() is salted per process)
        rng = random.Random(seed * 1_000_003 + k * 7919)
        text = _gen_once(rng, bias)
        schema = sch.parse_schema(text)
        score = footprint_score(schema)
        if best is None or score > best[0]:
            best = (score, text, schema)
    return best[1], best[2]
