"""Caching REST mapper: GVR -> GVK via upstream discovery.

Mirrors the reference's serialized, TTL-memoized discovery mapper
(pkg/proxy/restmapper.go:31-107): lookups are memoized per (group, version,
resource) with a TTL, errors are never cached, and concurrent access is
serialized.
"""

from __future__ import annotations

import asyncio
import json
import time
from dataclasses import dataclass

from .httpcore import Headers, Request, Transport


class NoKindMatchError(Exception):
    def __init__(self, group: str, version: str, resource: str):
        super().__init__(f"no matches for {group}/{version}, resource={resource}")
        self.group, self.version, self.resource = group, version, resource


@dataclass(frozen=True)
class GroupVersionKind:
    group: str
    version: str
    kind: str

    @property
    def group_version(self) -> str:
        return f"{self.group}/{self.version}" if self.group else self.version


DEFAULT_TTL = 300.0


class CachingRESTMapper:
    def __init__(self, transport: Transport, ttl: float = DEFAULT_TTL,
                 clock=time.monotonic):
        self._transport = transport
        self._ttl = ttl
        self._clock = clock
        self._cache: dict[tuple, tuple] = {}  # gvr -> (gvk, expires)
        self._lock = asyncio.Lock()

    async def kind_for(self, group: str, version: str, resource: str) -> GroupVersionKind:
        key = (group, version, resource)
        async with self._lock:  # discovery client is not concurrency-safe
            cached = self._cache.get(key)
            now = self._clock()
            if cached is not None and cached[1] > now:
                return cached[0]
            gvk = await self._discover(group, version, resource)
            # never cache errors (discover raises on failure)
            self._cache[key] = (gvk, now + self._ttl)
            return gvk

    def invalidate(self) -> None:
        self._cache.clear()

    async def _discover(self, group: str, version: str, resource: str) -> GroupVersionKind:
        path = (f"/apis/{group}/{version}" if group else f"/api/{version}")
        req = Request(method="GET", target=path, headers=Headers(
            [("Accept", "application/json")]))
        resp = await self._transport.round_trip(req)  # noqa: A006(external kube discovery)
        if resp.status != 200:
            raise NoKindMatchError(group, version, resource)
        try:
            doc = json.loads(resp.body)
        except ValueError as e:
            raise NoKindMatchError(group, version, resource) from e
        for r in doc.get("resources", []):
            if r.get("name") == resource:
                return GroupVersionKind(group=group, version=version,
                                        kind=r.get("kind", ""))
        raise NoKindMatchError(group, version, resource)
