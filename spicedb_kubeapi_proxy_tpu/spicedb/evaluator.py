"""Host-side recursive check/lookup evaluator — the reference oracle.

Implements Zanzibar userset-rewrite evaluation over the tuple store: direct
relations (incl. wildcard and userset subjects), permission expressions
(union / intersection / exclusion / arrow), bounded by the same max dispatch
depth the embedded reference server uses (50, reference
pkg/spicedb/spicedb.go:34).

This evaluator backs the `embedded://` endpoint and serves as the
differential-testing oracle for the `jax://` device kernels
(SURVEY.md §4 build translation).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from . import schema as sch
from .store import TupleStore
from .types import (
    MaxDepthExceededError,
    ObjectRef,
    SchemaError,
    SubjectRef,
    WILDCARD,
)

MAX_DEPTH = 50


@dataclass
class _Ctx:
    """Per-query evaluation context.

    `memo` holds only *clean* results; a result computed while assuming an
    in-progress (cyclic) node was False is valid for the current root but not
    cacheable, so frames whose subtree hit a still-in-progress node skip
    memoization (`hits` tracks those assumption keys until their own frame
    completes)."""
    memo: dict = field(default_factory=dict)
    stack: set = field(default_factory=set)
    hits: set = field(default_factory=set)


class Evaluator:
    def __init__(self, schema: sch.Schema, store: TupleStore,
                 max_depth: int = MAX_DEPTH):
        self.schema = schema
        self.store = store
        self.max_depth = max_depth

    # -- public API ---------------------------------------------------------

    def check(self, resource: ObjectRef, permission: str,
              subject: SubjectRef) -> bool:
        """Does `subject` have `permission` on `resource`?"""
        return self._check(resource, permission, subject, 0, _Ctx())

    def lookup_resources(self, resource_type: str, permission: str,
                         subject: SubjectRef) -> list:
        """All object ids of `resource_type` on which `subject` has
        `permission`.  Candidates are objects appearing as a resource in any
        live tuple (an object with no tuples is unreachable)."""
        self.schema.definition(resource_type)  # validate type exists
        out = []
        ctx = _Ctx()  # memo shared across candidates — same store snapshot
        for rid in self.store.object_ids_of_type(resource_type):
            if self._check(ObjectRef(resource_type, rid), permission, subject,
                           0, ctx):
                out.append(rid)
        return out

    def lookup_subjects(self, resource: ObjectRef, permission: str,
                        subject_type: str) -> list:
        """All subject ids of `subject_type` holding `permission` on
        `resource` (expansion by candidate enumeration)."""
        candidates = set()
        for rel in self.store.read(None):
            if rel.subject.type == subject_type and not rel.subject.relation:
                candidates.add(rel.subject.id)
        out = []
        for sid in sorted(candidates):
            if self._check(resource, permission, SubjectRef(subject_type, sid),
                           0, _Ctx()):
                out.append(sid)
        return out

    # -- evaluation ---------------------------------------------------------

    def _check(self, resource: ObjectRef, name: str, subject: SubjectRef,
               depth: int, ctx: _Ctx) -> bool:
        if depth > self.max_depth:
            raise MaxDepthExceededError(
                f"max dispatch depth {self.max_depth} exceeded checking"
                f" {resource}#{name}")
        key = (resource.type, resource.id, name, subject)
        if key in ctx.memo:
            return ctx.memo[key]
        if key in ctx.stack:
            ctx.hits.add(key)
            return False  # cycle: revisiting the same node adds nothing new
        ctx.stack.add(key)
        try:
            d = self.schema.definition(resource.type)
            if name in d.relations:
                result = self._check_relation(resource, name, subject, depth, ctx)
            elif name in d.permissions:
                result = self._eval_expr(d, resource, d.permissions[name],
                                         subject, depth, ctx)
            else:
                raise SchemaError(
                    f"relation/permission `{name}` not found for {resource.type}")
        finally:
            ctx.stack.discard(key)
            ctx.hits.discard(key)
        if not (ctx.hits & ctx.stack):
            ctx.memo[key] = result
        return result

    def _check_relation(self, resource: ObjectRef, relation: str,
                        subject: SubjectRef, depth: int, ctx: _Ctx) -> bool:
        found = False
        for ts in self.store.subjects_for(resource, relation):
            if not ts.relation:
                # direct subject; wildcard matches any direct subject of type
                if ts.id == WILDCARD:
                    if ts.type == subject.type and not subject.relation:
                        found = True
                        break
                    continue
                if ts == subject:
                    found = True
                    break
            else:
                # userset subject: exact match, or expand recursively
                if (ts.type == subject.type and ts.id == subject.id
                        and ts.relation == subject.relation):
                    found = True
                    break
                if self._check(ObjectRef(ts.type, ts.id), ts.relation,
                               subject, depth + 1, ctx):
                    found = True
                    break
        return found

    def _eval_expr(self, d: sch.Definition, resource: ObjectRef, expr: sch.Expr,
                   subject: SubjectRef, depth: int, ctx: _Ctx) -> bool:
        if isinstance(expr, sch.Nil):
            return False
        if isinstance(expr, sch.RelRef):
            return self._check(resource, expr.name, subject, depth + 1, ctx)
        if isinstance(expr, sch.Arrow):
            # walk subject objects of the left relation; wildcard and userset
            # subjects are not traversed by arrows
            for ts in self.store.subjects_for(resource, expr.left):
                if ts.id == WILDCARD or ts.relation:
                    continue
                target_def = self.schema.definitions.get(ts.type)
                if (target_def is None
                        or not target_def.has_relation_or_permission(expr.target)):
                    continue
                if self._check(ObjectRef(ts.type, ts.id), expr.target, subject,
                               depth + 1, ctx):
                    return True
            return False
        if isinstance(expr, sch.Union):
            return any(self._eval_expr(d, resource, c, subject, depth, ctx)
                       for c in expr.children)
        if isinstance(expr, sch.Intersection):
            return all(self._eval_expr(d, resource, c, subject, depth, ctx)
                       for c in expr.children)
        if isinstance(expr, sch.Exclusion):
            if not self._eval_expr(d, resource, expr.base, subject, depth, ctx):
                return False
            return not self._eval_expr(d, resource, expr.subtract, subject,
                                       depth, ctx)
        raise SchemaError(f"unknown expression node {expr!r}")
