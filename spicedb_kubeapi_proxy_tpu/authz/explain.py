"""Per-check decision explain: relation-path witnesses for audit events.

Two witness sources, merged into one `Witness` record:

- **Oracle witness** (`oracle_witness`): a recursive mirror of the host
  evaluator (spicedb/evaluator.py) that, instead of a bare tri-state
  value, returns the admitting chain of relation hops for an allowed
  decision (`pod:a/x#view -> pod:a/x#viewer@user:alice [direct]`), the
  excluding chain for an exclusion-caused denial, and the probed
  frontier (which relations were searched and found empty) for ordinary
  denials.  Golden tests pin its decision to the oracle's on every
  schema construct (union/intersection/exclusion/arrow/userset/
  wildcard/caveat).

- **Device witness** (`device_witness`): an exact host (numpy) replay of
  the jax kernel's fixpoint step — edge OR-SpMV + wildcard terms +
  permission program — over the compiled GraphProgram, recording the
  iteration at which every state row first lit up.  For an allowed row
  this recovers *which relation hop / fixpoint iteration admitted the
  subject* from the staged iterate without any device work; the state
  chain is decoded back through the program's slot layout into the same
  hop vocabulary.  (Incremental deltas applied since the last compile
  live in the device tables, not the program's edge arrays, so the
  caller cross-checks the replay's decision against the kernel's and
  falls back to the oracle witness on disagreement.)

Witnesses attach to audit events (utils/audit.py) at Request level when
explain mode is on (`--audit-explain` or a `?explain=1` request), so a
filtered 10k-pod list can name, per hidden pod, the relation path that
excluded it.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..spicedb import schema as sch
from ..spicedb.evaluator import MAX_DEPTH, NO, MAYBE, YES
from ..spicedb.store import TupleStore
from ..spicedb.types import (
    MaxDepthExceededError,
    ObjectRef,
    SchemaError,
    SubjectRef,
    WILDCARD,
)

_DECISION = {NO: "denied", MAYBE: "conditional", YES: "allowed"}

# bound the probed-frontier payload on denials: the first hops name the
# excluding relations; an exhaustive listing would bloat audit events
MAX_PROBED_HOPS = 16


@dataclass
class Hop:
    """One relation hop in an evaluation witness."""
    resource: str        # "type:id"
    relation: str
    subject: str         # "type:id" or "type:id#rel"
    via: str             # direct|wildcard|userset|arrow|permission|device
    admitted: bool = True
    caveated: bool = False

    def rel_string(self) -> str:
        return f"{self.resource}#{self.relation}@{self.subject}"

    def to_dict(self) -> dict:
        d = {"rel": self.rel_string(), "via": self.via,
             "admitted": self.admitted}
        if self.caveated:
            d["caveated"] = True
        return d


@dataclass
class Witness:
    """The evaluation record for one (resource, permission, subject)."""
    decision: str                  # allowed|conditional|denied
    path: list = field(default_factory=list)    # admitting/excluding chain
    probed: list = field(default_factory=list)  # searched-and-empty hops
    iterations: Optional[int] = None  # fixpoint admission iteration
    backend: str = "oracle"
    note: str = ""

    def to_dict(self) -> dict:
        d = {"decision": self.decision,
             "path": [h.to_dict() for h in self.path],
             "backend": self.backend}
        if self.probed:
            d["probed"] = [h.to_dict() for h in self.probed]
        if self.iterations is not None:
            d["iterations"] = self.iterations
        if self.note:
            d["note"] = self.note
        return d


class ExplainError(Exception):
    pass


def _obj_str(type_name: str, object_id: str) -> str:
    return f"{type_name}:{object_id}"


def _subj_str(s: SubjectRef) -> str:
    base = f"{s.type}:{s.id}"
    return f"{base}#{s.relation}" if s.relation else base


# -- oracle witness ----------------------------------------------------------


class _WitnessEval:
    """Recursive witness evaluator; mirrors Evaluator._check /
    _check_relation / _eval_expr hop for hop, carrying the admitting
    chain alongside the Kleene value.  No memoization: explain is a
    per-denial debug path, not the hot path."""

    def __init__(self, schema: sch.Schema, store: TupleStore,
                 max_depth: int = MAX_DEPTH):
        self.schema = schema
        self.store = store
        self.max_depth = max_depth

    def _caveat_value(self, caveat) -> int:
        if caveat is None:
            return YES
        c = self.schema.caveats.get(caveat.name)
        if c is None:
            raise SchemaError(f"caveat `{caveat.name}` not found")
        out = c.evaluate(caveat.context())
        if out is None:
            return MAYBE
        return YES if out else NO

    def check(self, resource: ObjectRef, name: str, subject: SubjectRef,
              depth: int, stack: set) -> tuple:
        """Returns (value, path): for YES/MAYBE the admitting chain, for
        NO the excluding chain when the denial came from an exclusion
        (else empty)."""
        if depth > self.max_depth:
            raise MaxDepthExceededError(
                f"max dispatch depth {self.max_depth} exceeded explaining"
                f" {resource}#{name}")
        key = (resource.type, resource.id, name, subject)
        if key in stack:
            return NO, []  # cycle: revisiting adds nothing new
        stack.add(key)
        try:
            d = self.schema.definition(resource.type)
            if name in d.relations:
                return self._relation(resource, name, subject, depth, stack)
            if name in d.permissions:
                return self._expr(d, resource, d.permissions[name], subject,
                                  depth, stack)
            raise SchemaError(
                f"relation/permission `{name}` not found for {resource.type}")
        finally:
            stack.discard(key)

    def _relation(self, resource: ObjectRef, relation: str,
                  subject: SubjectRef, depth: int, stack: set) -> tuple:
        best, best_path = NO, []
        res = _obj_str(resource.type, resource.id)
        for ts, caveat in self.store.subject_entries_for(resource, relation):
            cv = self._caveat_value(caveat)
            if cv == NO:
                continue
            cav = cv == MAYBE
            if not ts.relation:
                if ts.id == WILDCARD:
                    if ts.type == subject.type and not subject.relation:
                        hop = Hop(res, relation, f"{ts.type}:*",
                                  via="wildcard", caveated=cav)
                        if cv > best:
                            best, best_path = cv, [hop]
                else:
                    if ts == subject:
                        hop = Hop(res, relation, _subj_str(ts), via="direct",
                                  caveated=cav)
                        if cv > best:
                            best, best_path = cv, [hop]
            else:
                if (ts.type == subject.type and ts.id == subject.id
                        and ts.relation == subject.relation):
                    hop = Hop(res, relation, _subj_str(ts), via="userset",
                              caveated=cav)
                    if cv > best:
                        best, best_path = cv, [hop]
                else:
                    sub_v, sub_path = self.check(
                        ObjectRef(ts.type, ts.id), ts.relation, subject,
                        depth + 1, stack)
                    v = min(cv, sub_v)
                    if v > best:
                        hop = Hop(res, relation, _subj_str(ts), via="userset",
                                  caveated=cav)
                        best, best_path = v, [hop] + sub_path
            if best == YES:
                break
        return best, best_path

    def _expr(self, d: sch.Definition, resource: ObjectRef, expr,
              subject: SubjectRef, depth: int, stack: set) -> tuple:
        if isinstance(expr, sch.Nil):
            return NO, []
        if isinstance(expr, sch.RelRef):
            return self.check(resource, expr.name, subject, depth + 1, stack)
        if isinstance(expr, sch.Arrow):
            best, best_path = NO, []
            res = _obj_str(resource.type, resource.id)
            for ts, caveat in self.store.subject_entries_for(resource,
                                                             expr.left):
                if ts.id == WILDCARD or ts.relation:
                    continue
                cv = self._caveat_value(caveat)
                if cv == NO:
                    continue
                target_def = self.schema.definitions.get(ts.type)
                if (target_def is None
                        or not target_def.has_relation_or_permission(
                            expr.target)):
                    continue
                sub_v, sub_path = self.check(
                    ObjectRef(ts.type, ts.id), expr.target, subject,
                    depth + 1, stack)
                v = min(cv, sub_v)
                if v > best:
                    hop = Hop(res, expr.left, _subj_str(ts), via="arrow",
                              caveated=cv == MAYBE)
                    best, best_path = v, [hop] + sub_path
                if best == YES:
                    break
            return best, best_path
        if isinstance(expr, sch.Union):
            best, best_path = NO, []
            for c in expr.children:
                v, p = self._expr(d, resource, c, subject, depth, stack)
                if v > best:
                    best, best_path = v, p
                if best == YES:
                    break
            return best, best_path
        if isinstance(expr, sch.Intersection):
            worst, paths = YES, []
            for c in expr.children:
                v, p = self._expr(d, resource, c, subject, depth, stack)
                if v < worst:
                    worst = v
                if v == NO:
                    return NO, []  # this branch denies the intersection
                paths.extend(p)
            return worst, paths
        if isinstance(expr, sch.Exclusion):
            base_v, base_path = self._expr(d, resource, expr.base, subject,
                                           depth, stack)
            if base_v == NO:
                return NO, []
            sub_v, sub_path = self._expr(d, resource, expr.subtract, subject,
                                         depth, stack)
            v = min(base_v, YES - sub_v)
            if v == NO:
                # the EXCLUDING chain is the explanation: the subject was
                # granted by `base` but banned by `subtract`
                return NO, [Hop(h.resource, h.relation, h.subject,
                                via="exclusion", admitted=False,
                                caveated=h.caveated) for h in sub_path]
            return v, base_path + sub_path
        raise SchemaError(f"unknown expression node {expr!r}")


def _probe_frontier(schema: sch.Schema, resource: ObjectRef, name: str,
                    subject: SubjectRef) -> list:
    """Depth-1 description of a plain denial: the relation leaves of the
    permission expression, each an unadmitted hop — 'these are the
    relations that were searched and held no admitting tuple'."""
    res = _obj_str(resource.type, resource.id)
    subj = _subj_str(subject)
    try:
        d = schema.definition(resource.type)
    except SchemaError:
        return []
    if name in d.relations:
        return [Hop(res, name, subj, via="direct", admitted=False)]
    expr = d.permissions.get(name)
    if expr is None:
        return []
    out: list = []

    def walk(e) -> None:
        if len(out) >= MAX_PROBED_HOPS:
            return
        if isinstance(e, sch.RelRef):
            out.append(Hop(res, e.name, subj, via="permission",
                           admitted=False))
        elif isinstance(e, sch.Arrow):
            out.append(Hop(res, e.left, f"->{e.target}", via="arrow",
                           admitted=False))
        elif isinstance(e, (sch.Union, sch.Intersection)):
            for c in e.children:
                walk(c)
        elif isinstance(e, sch.Exclusion):
            walk(e.base)

    walk(expr)
    return out


def oracle_witness(schema: sch.Schema, store: TupleStore,
                   resource: ObjectRef, permission: str,
                   subject: SubjectRef,
                   max_depth: int = MAX_DEPTH) -> Witness:
    """Explain one check against the host oracle's semantics."""
    ev = _WitnessEval(schema, store, max_depth=max_depth)
    try:
        value, path = ev.check(resource, permission, subject, 0, set())
    except (SchemaError, MaxDepthExceededError) as e:
        return Witness(decision="denied", note=f"explain error: {e}")
    w = Witness(decision=_DECISION[value], path=path)
    if value == YES or value == MAYBE:
        # relation-hop count == the fixpoint iteration bound that admits
        # this subject (each hop is one one-step-closure application)
        w.iterations = len(path)
    else:
        w.probed = (_probe_frontier(schema, resource, permission, subject)
                    if not path else [])
    return w


# -- device witness (host replay of the kernel iterate) ----------------------


def _perm_expr_np(expr, x):
    """numpy mirror of ops/spmv._apply_perm_expr over a bool [N] state."""
    import numpy as np

    from ..ops.graph_compile import PExclude, PIntersect, PRead, PUnion, PZero

    if isinstance(expr, PRead):
        return x[expr.offset: expr.offset + expr.length]
    if isinstance(expr, PZero):
        return np.zeros(expr.length, bool)
    if isinstance(expr, PUnion):
        out = _perm_expr_np(expr.children[0], x)
        for c in expr.children[1:]:
            out = out | _perm_expr_np(c, x)
        return out
    if isinstance(expr, PIntersect):
        out = _perm_expr_np(expr.children[0], x)
        for c in expr.children[1:]:
            out = out & _perm_expr_np(c, x)
        return out
    if isinstance(expr, PExclude):
        return _perm_expr_np(expr.base, x) & ~_perm_expr_np(expr.subtract, x)
    raise TypeError(f"unknown perm expr {expr!r}")


def _iterate_states(prog, subject_idx: int, max_iters: int = 50) -> tuple:
    """Replay the kernel fixpoint on host; returns (final bool [N] state,
    int [N] first-admission iteration, -1 = never admitted)."""
    import numpy as np

    n = prog.state_size
    x0 = np.zeros(n, bool)
    x0[subject_idx] = True
    x0[n - 1] = False
    admitted = np.full(n, -1, np.int64)
    admitted[x0] = 0
    x = x0.copy()
    for it in range(1, max_iters + 1):
        y = np.zeros(n, bool)
        np.logical_or.at(y, prog.edge_dst, x[prog.edge_src])
        for term in prog.wildcard_terms:
            if x[term.self_offset: term.self_offset + term.self_length].any():
                y[list(term.mask_indices)] = True
        x1 = y | x0
        for op in prog.perm_ops:
            sl = slice(op.offset, op.offset + op.length)
            x1[sl] = _perm_expr_np(op.expr, x1) | x0[sl]
        x1[n - 1] = False
        new = x1 & ~x
        admitted[new & (admitted < 0)] = it
        if not new.any():
            break
        x = x1
    return x, admitted


def _slot_table(prog) -> tuple:
    """(sorted offsets, parallel (type, slot, length) rows) decode table
    for state indices, cached on the program."""
    table = getattr(prog, "_explain_slot_table", None)
    if table is None:
        rows = sorted(
            (off, t, slot, prog.num_objects[t])
            for (t, slot), off in prog.slot_offsets.items())
        table = ([r[0] for r in rows], [(r[1], r[2], r[3]) for r in rows])
        prog._explain_slot_table = table
    return table


def decode_state(prog, idx: int) -> Optional[tuple]:
    """State index -> (type, slot, object_id), or None for dead/padding."""
    import bisect

    offsets, rows = _slot_table(prog)
    i = bisect.bisect_right(offsets, idx) - 1
    if i < 0:
        return None
    t, slot, length = rows[i]
    if idx >= offsets[i] + length:
        return None
    return t, slot, prog.object_ids[t][idx - offsets[i]]


def _predecessor(prog, state, admitted, idx: int):
    """One state that admitted `idx` at an earlier iteration: an in-edge
    whose source lit earlier, or a permission-program read leaf."""
    import numpy as np

    it = admitted[idx]
    srcs = prog.edge_src[np.nonzero(prog.edge_dst == idx)[0]]
    for s in srcs:
        s = int(s)
        if 0 <= admitted[s] < it:
            return s
    for term in prog.wildcard_terms:
        if idx in term.mask_indices:
            sl = admitted[term.self_offset:
                          term.self_offset + term.self_length]
            live = np.nonzero((sl >= 0) & (sl < it))[0]
            if live.size:
                return term.self_offset + int(live[0])
    for op in prog.perm_ops:
        if not (op.offset <= idx < op.offset + op.length):
            continue
        local = idx - op.offset

        def leaf(e):
            from ..ops.graph_compile import (PExclude, PIntersect, PRead,
                                             PUnion)
            if isinstance(e, PRead):
                s = e.offset + local
                # a leaf admitted at it-1 OR at it: the permission
                # program applies within the same iteration as the edge
                # sweep that lit the leaf (Gauss-Seidel within the step)
                if 0 <= admitted[s] <= it and s != idx and state[s]:
                    return s
                return None
            if isinstance(e, (PUnion, PIntersect)):
                for c in e.children:
                    s = leaf(c)
                    if s is not None:
                        return s
                return None
            if isinstance(e, PExclude):
                return leaf(e.base)
            return None

        s = leaf(op.expr)
        if s is not None:
            return s
    return None


def device_witness(prog, subject_idx: int, target_idx: int,
                   max_iters: int = 50) -> Witness:
    """Witness from the compiled program's staged iterate: admission
    iteration + decoded state chain for (subject column, target row)."""
    state, admitted = _iterate_states(prog, subject_idx,
                                      max_iters=max_iters)
    if admitted[target_idx] < 0:
        return Witness(decision="denied", backend="device",
                       note="target row never admitted in the replayed "
                            "iterate")
    chain: list = []
    idx = target_idx
    seen = set()
    while idx != subject_idx and idx not in seen:
        seen.add(idx)
        decoded = decode_state(prog, idx)
        pred = _predecessor(prog, state, admitted, idx)
        if decoded is not None:
            t, slot, oid = decoded
            sub = "?"
            if pred is not None:
                pd = decode_state(prog, pred)
                if pd is not None:
                    sub = _obj_str(pd[0], pd[2])
                    if pd[1] not in ("__self__",):
                        sub += f"#{pd[1]}"
            chain.append(Hop(_obj_str(t, oid), slot, sub, via="device"))
        if pred is None:
            break
        idx = pred
    return Witness(decision="allowed", path=chain, backend="device",
                   iterations=int(admitted[target_idx]))


def witness_for(endpoint, resource: ObjectRef, permission: str,
                subject: SubjectRef) -> Optional[Witness]:
    """Best witness the endpoint can produce, or None when it carries no
    host store/schema (remote gRPC).  Backends exposing `explain_check`
    (jax://) get iterate capture; anything with a schema + store gets the
    oracle witness."""
    explain = getattr(endpoint, "explain_check", None)
    if explain is not None:
        return explain(resource, permission, subject)
    schema = getattr(endpoint, "schema", None)
    store = getattr(endpoint, "store", None)
    if schema is None or store is None:
        return None
    w = oracle_witness(schema, store, resource, permission, subject)
    w.backend = "embedded"
    return w


async def witness_async(endpoint, resource: ObjectRef, permission: str,
                        subject: SubjectRef) -> Optional[Witness]:
    """witness_for off the event loop: jax iterate capture replays the
    fixpoint on host and must not stall concurrent requests."""
    import asyncio
    import contextvars

    loop = asyncio.get_running_loop()
    ctx = contextvars.copy_context()
    return await loop.run_in_executor(
        None, lambda: ctx.run(witness_for, endpoint, resource, permission,
                              subject))


async def witness_dict_for_rel(endpoint, rel,
                               object_id: Optional[str] = None
                               ) -> Optional[dict]:
    """Audit-event witness payload for a resolved rel (an audit helper:
    failures yield None, never an exception — an explain fault must not
    fail the decision it describes).  `object_id` overrides the rel's
    resource id (prefilter rels carry `$`)."""
    if rel is None:
        return None
    try:
        w = await witness_async(
            endpoint,
            ObjectRef(rel.resource_type,
                      rel.resource_id if object_id is None else object_id),
            rel.resource_relation,
            SubjectRef(rel.subject_type, rel.subject_id,
                       rel.subject_relation))
    except Exception:
        return None
    return w.to_dict() if w is not None else None
