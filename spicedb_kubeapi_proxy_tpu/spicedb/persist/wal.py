"""Segmented append-only write-ahead log for the tuple store.

Each committed store mutation (delta write, bulk load, delete-all) becomes
one CRC-framed, revision-stamped record appended synchronously under the
store lock, so the on-disk stream totally orders every revision the
in-memory store ever produced.  Records live in numbered segment files;
sealed segments are immutable and become reclaimable once a checkpoint's
revision covers them (manager.py).

Frame format (little-endian), after an 8-byte per-segment magic:

    [u32 payload_len][u32 crc32(payload)][payload bytes]

The payload is compact JSON (see manager.py for the record vocabulary).
Replay tolerates a torn FINAL record — a crash mid-append — by truncating
the tail at the last whole frame; a bad frame anywhere else is real
corruption and raises `WalCorruptionError` rather than silently dropping
committed revisions.

Fsync policy is configurable (`always` | `interval` | `never`): `always`
makes every acked write durable before the caller resumes (crash-smoke
relies on this), `interval` bounds the loss window, `never` leaves
durability to the OS cache.  Appends always flush the Python buffer, so
an in-process "crash" (abandoning the writer) loses nothing that replay
could have seen.

Single-writer: one process appends to a data dir at a time (the proxy's
deployment owns the volume; there is no lock file).
"""

from __future__ import annotations

import json
import logging
import os
import re
import struct
import threading
import time
import zlib
from typing import Iterator, Optional

from ...utils import metrics as m
from ...utils.failpoints import fail_point

logger = logging.getLogger("spicedb_kubeapi_proxy_tpu.persist")

SEGMENT_MAGIC = b"SPWAL001"
_FRAME = struct.Struct("<II")
_SEG_RE = re.compile(r"^seg-(\d{8})\.wal$")

FSYNC_ALWAYS = "always"
FSYNC_INTERVAL = "interval"
FSYNC_NEVER = "never"
FSYNC_POLICIES = (FSYNC_ALWAYS, FSYNC_INTERVAL, FSYNC_NEVER)

DEFAULT_SEGMENT_BYTES = 8 << 20

# checkpoint/fsync work spans ms..minutes; the default latency buckets
# top out at 10s
_IO_BUCKETS = (0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5,
               1.0, 5.0, 15.0, 60.0)


class WalCorruptionError(Exception):
    """A non-tail frame failed its CRC/length check, or the record stream
    has a revision gap: committed state cannot be reconstructed."""


def _fsync_dir(path: str) -> None:
    try:
        fd = os.open(path, os.O_RDONLY)
    except OSError:  # platforms without dir fds
        return
    try:
        os.fsync(fd)
    finally:
        os.close(fd)


def segment_name(seq: int) -> str:
    return f"seg-{seq:08d}.wal"


class TornFrameError(Exception):
    """A frame in the middle of a byte stream failed its CRC/length check
    (parse_frames): the stream is damaged beyond a torn tail."""


def parse_frames(data: bytes, offset: int = 0) -> tuple:
    """Parse complete CRC frames out of `data[offset:]` -> (records,
    consumed) where `consumed` is the offset just past the last WHOLE
    valid frame.  A truncated FINAL frame (torn tail / still-being-
    written segment) stops the parse cleanly; a bad frame followed by
    more bytes raises TornFrameError.  This is the ONE frame decoder:
    segment replay (SegmentedWal._replay_segment below) and replication
    followers parsing segment bytes fetched over HTTP
    (spicedb/replication/follower.py) both call it, so leader recovery
    and follower tailing can never disagree on framing."""
    records = []
    off = offset
    n = len(data)
    while off < n:
        if off + _FRAME.size > n:
            break  # torn header: wait for more bytes
        length, crc = _FRAME.unpack_from(data, off)
        start, end = off + _FRAME.size, off + _FRAME.size + length
        if end > n:
            break  # torn payload
        bad = None
        if zlib.crc32(data[start:end]) != crc:
            bad = "crc mismatch"
        else:
            try:
                rec = json.loads(data[start:end])
            except ValueError:
                rec = None
            if not isinstance(rec, dict) or "k" not in rec or "r" not in rec:
                bad = "undecodable record"
        if bad is not None:
            if end == n:
                break  # torn tail shape: retry once more bytes arrive
            raise TornFrameError(f"frame at offset {off}: {bad}")
        records.append(rec)
        off = end
    return records, off


# owned by PersistenceManager, which only exists behind the DurableStore
# gate: a gate-off process never opens a WAL, so nothing here can tick
class SegmentedWal:  # noqa: A004(built behind gate)
    """Append/replay over the `wal/` directory of a data dir.

    Thread safety is the owning store's lock: appends happen from commit
    listeners that already run under it; replay happens before any
    listener is attached.
    """

    def __init__(self, wal_dir: str,
                 fsync: str = FSYNC_INTERVAL,
                 fsync_interval: float = 1.0,
                 segment_bytes: int = DEFAULT_SEGMENT_BYTES,
                 registry: Optional[m.Registry] = None):
        if fsync not in FSYNC_POLICIES:
            raise ValueError(
                f"unknown fsync policy {fsync!r}; expected one of "
                f"{FSYNC_POLICIES}")
        self.dir = wal_dir
        self.fsync_policy = fsync
        self.fsync_interval = fsync_interval
        self.segment_bytes = segment_bytes
        os.makedirs(wal_dir, exist_ok=True)
        existing = self.segment_seqs()
        self._next_seq = (existing[-1] + 1) if existing else 1
        self._cur_seq = 0
        self._cur_file = None
        self._cur_bytes = 0
        self._last_fsync = time.monotonic()
        # appends are serialized by the store lock, but the idle-flush
        # task (manager.py) fsyncs from the event loop: seal/fsync of the
        # open segment must not race a concurrent close
        self._io_lock = threading.Lock()
        self._dirty = False
        # replay repair accounting (surfaced in recovery_info)
        self.torn_records = 0
        registry = registry or m.REGISTRY
        self._append_hist = registry.histogram(
            "authz_wal_append_seconds",
            "Write-ahead-log record append latency (excluding fsync)")
        self._fsync_hist = registry.histogram(
            "authz_wal_fsync_seconds",
            "Write-ahead-log fsync latency", buckets=_IO_BUCKETS)
        self._appends = registry.counter(
            "authz_wal_appends_total",
            "Write-ahead-log records appended, by record kind",
            labels=("kind",))

    # -- introspection -------------------------------------------------------

    def segment_seqs(self) -> list:
        out = []
        try:
            names = os.listdir(self.dir)
        except OSError:
            return out
        for n in names:
            mm = _SEG_RE.match(n)
            if mm:
                out.append(int(mm.group(1)))
        return sorted(out)

    def segment_count(self) -> int:
        return len(self.segment_seqs())

    def total_bytes(self) -> int:
        total = 0
        for seq in self.segment_seqs():
            try:
                total += os.path.getsize(self._path(seq))
            except OSError:
                pass
        return total

    def _path(self, seq: int) -> str:
        return os.path.join(self.dir, segment_name(seq))

    # -- append --------------------------------------------------------------

    def _open_segment(self) -> None:
        seq = self._next_seq
        self._next_seq += 1
        f = open(self._path(seq), "wb")
        f.write(SEGMENT_MAGIC)
        f.flush()
        if self.fsync_policy != FSYNC_NEVER:
            # make the segment's DIRECTORY ENTRY durable: without this a
            # power failure could drop the whole newest segment — and
            # with it acked fsync=always writes — with no gap to detect
            _fsync_dir(self.dir)
        self._cur_seq, self._cur_file, self._cur_bytes = \
            seq, f, len(SEGMENT_MAGIC)

    def append(self, payload: bytes, kind: str = "") -> None:
        """Append one record; called under the store lock.  An IOError or
        armed failpoint propagates to the writer — durability failures
        must fail the write, not pass silently."""
        fail_point("walBeforeAppend")
        if self._cur_file is None:
            self._open_segment()
        frame = _FRAME.pack(len(payload), zlib.crc32(payload)) + payload
        t0 = time.perf_counter()
        self._cur_file.write(frame)
        self._cur_file.flush()
        self._append_hist.observe(time.perf_counter() - t0)
        self._appends.inc(kind=kind or "delta")
        self._cur_bytes += len(frame)
        self._dirty = True
        fail_point("walAfterAppend")
        self._maybe_fsync()
        if self._cur_bytes >= self.segment_bytes:
            self._seal_current()

    def _fsync_current_locked(self) -> None:
        # clear the dirty flag BEFORE fsync: an append racing the fsync
        # re-marks it, so its (possibly not-yet-synced) frame is caught
        # by the next flush instead of being skipped forever; clearing
        # after would swallow that append's mark
        self._dirty = False
        t0 = time.perf_counter()
        try:
            os.fsync(self._cur_file.fileno())
        except Exception:
            self._dirty = True
            raise
        self._fsync_hist.observe(time.perf_counter() - t0)
        self._last_fsync = time.monotonic()

    def _maybe_fsync(self) -> None:
        if self.fsync_policy == FSYNC_NEVER:
            return
        if (self.fsync_policy == FSYNC_INTERVAL
                and time.monotonic() - self._last_fsync < self.fsync_interval):
            return
        with self._io_lock:
            self._fsync_current_locked()

    def fsync_if_dirty(self) -> bool:
        """Fsync the open segment if it holds unfsynced appends — the
        idle-flush hook (manager.py) that bounds the `interval` policy's
        loss window even when no further append arrives."""
        if self.fsync_policy == FSYNC_NEVER or not self._dirty:
            return False
        with self._io_lock:
            if self._cur_file is None or not self._dirty:
                return False
            self._fsync_current_locked()
            return True

    def _seal_current(self) -> int:
        """Close the open segment (fsynced unless policy is `never`);
        returns its seq."""
        seq = self._cur_seq
        with self._io_lock:
            f = self._cur_file
            if f is not None:
                if self.fsync_policy != FSYNC_NEVER:
                    self._fsync_current_locked()
                f.close()
            self._cur_file = None
            self._cur_bytes = 0
            self._dirty = False
        return seq

    def cut(self) -> int:
        """Seal the open segment and return the highest sealed seq — the
        checkpoint watermark: every record appended so far lives in a
        segment <= this seq.  Called under the store lock together with
        the checkpoint's revision capture, so no record <= that revision
        can land in a later segment."""
        if self._cur_file is not None:
            return self._seal_current()
        return self._next_seq - 1

    def close(self) -> None:
        if self._cur_file is not None:
            self._seal_current()

    # -- replay --------------------------------------------------------------

    def replay(self) -> Iterator[dict]:
        """Yield decoded records across all segments in order.  A torn
        final record (crash mid-append) is repaired by truncation; bad
        frames anywhere else raise WalCorruptionError."""
        seqs = self.segment_seqs()
        for i, seq in enumerate(seqs):
            yield from self._replay_segment(seq, final=(i == len(seqs) - 1))

    def _replay_segment(self, seq: int, final: bool) -> Iterator[dict]:
        path = self._path(seq)
        with open(path, "rb") as f:
            data = f.read()
        if len(data) == 0:
            # a crash between segment creation and the magic write (or a
            # prior header repair) leaves an empty file: no records, not
            # corruption — even when later segments follow it
            return
        if len(data) < len(SEGMENT_MAGIC) or not data.startswith(SEGMENT_MAGIC):
            if final:
                # torn segment creation: remove the file entirely so a
                # LATER restart (when this segment is no longer final)
                # doesn't read the remnant as corruption
                logger.warning("wal: torn segment header in %s; removing",
                               path)
                self.torn_records += 1
                os.unlink(path)
                _fsync_dir(self.dir)
                return
            raise WalCorruptionError(f"{path}: bad segment header")
        # the one shared frame decoder (parse_frames): a bad frame
        # reaching EOF stops the parse (torn-append shape), a bad frame
        # followed by more bytes raises — replay layers the repair
        # policy on top: a torn tail is repairable only at the end of
        # the LAST segment; anywhere else committed revisions are
        # damaged
        try:
            records, consumed = parse_frames(data, len(SEGMENT_MAGIC))
        except TornFrameError as e:
            raise WalCorruptionError(f"{path}: {e}") from e
        yield from records
        if consumed < len(data):
            if final:
                self._truncate(path, consumed, "torn or damaged final frame")
                return
            raise WalCorruptionError(
                f"{path}@{consumed}: torn frame in a sealed segment")

    def _truncate(self, path: str, offset: int, why: str) -> None:
        logger.warning("wal: torn final record in %s at offset %d (%s); "
                       "truncating", path, offset, why)
        self.torn_records += 1
        with open(path, "rb+") as f:
            f.truncate(offset)
            f.flush()
            os.fsync(f.fileno())

    # -- reclamation ---------------------------------------------------------

    def reclaim(self, watermark_seq: int, up_to_revision: int) -> int:
        """Delete sealed segments <= watermark_seq and snapshot sidecars
        <= up_to_revision (all covered by the durable checkpoint).  Never
        touches the open segment."""
        removed = 0
        for seq in self.segment_seqs():
            if seq > watermark_seq or seq == self._cur_seq and \
                    self._cur_file is not None:
                continue
            try:
                os.unlink(self._path(seq))
                removed += 1
            except OSError:
                pass
        for name in os.listdir(self.dir):
            mm = re.match(r"^snap-(\d{12})\.npz$", name)
            if mm and int(mm.group(1)) <= up_to_revision:
                try:
                    os.unlink(os.path.join(self.dir, name))
                    removed += 1
                except OSError:
                    pass
        if removed:
            _fsync_dir(self.dir)
        return removed
