"""Static schema/rule lint (spicedb/schema_lint.py, Cedar-inspired) —
built on the `relation_footprint` closure: unreachable relations,
statically-DENY permissions, and rule templates naming undefined
relations all surface before a single request is served."""

from spicedb_kubeapi_proxy_tpu.cli import main as cli_main
from spicedb_kubeapi_proxy_tpu.config import proxyrule
from spicedb_kubeapi_proxy_tpu.spicedb import schema as sch
from spicedb_kubeapi_proxy_tpu.spicedb.schema_lint import lint_schema

SCHEMA = """
definition user {}
definition group { relation member: user }
definition doc {
  relation viewer: user | group#member
  relation orphan: user
  relation banned: user
  permission view = viewer - banned
  permission nobody = nil
}
"""

RULES_OK = """
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: get-docs}
match: [{apiVersion: v1, resource: docs, verbs: [get]}]
check: [{tpl: "doc:{{name}}#view@user:{{user.name}}"}]
"""

RULES_BAD = """
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: bad-rel}
match: [{apiVersion: v1, resource: docs, verbs: [get]}]
check: [{tpl: "doc:{{name}}#nonexistent@user:{{user.name}}"}]
---
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: bad-type}
match: [{apiVersion: v1, resource: widgets, verbs: [get]}]
check: [{tpl: "widget:{{name}}#view@user:{{user.name}}"}]
---
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: bad-subject-rel}
match: [{apiVersion: v1, resource: docs, verbs: [list]}]
prefilter:
- fromObjectIDNameExpr: "{{resourceId}}"
  lookupMatchingResources: {tpl: "doc:$#view@group:{{name}}#nosuch"}
"""


def codes(findings):
    return sorted(f.code for f in findings)


def test_clean_schema_and_rules():
    schema = sch.parse_schema("""
definition user {}
definition doc {
  relation viewer: user
  permission view = viewer
}
""")
    findings = lint_schema(schema, proxyrule.parse(RULES_OK.replace(
        "#view@", "#view@")))
    assert findings == []


def test_empty_footprint_and_unreachable_relation():
    schema = sch.parse_schema(SCHEMA)
    findings = lint_schema(schema, proxyrule.parse(RULES_OK))
    by_code = {f.code: f for f in findings}
    # nobody = nil -> empty footprint warning
    assert by_code["SL003"].where == "doc#nobody"
    assert by_code["SL003"].severity == "warn"
    # orphan feeds no permission and no rule -> unreachable
    assert by_code["SL004"].where == "doc#orphan"
    # viewer/banned (in view's footprint) and group#member (referenced
    # by viewer's subject annotation) are NOT flagged
    flagged = {f.where for f in findings}
    assert "doc#viewer" not in flagged
    assert "doc#banned" not in flagged
    assert "group#member" not in flagged


def test_rule_template_errors():
    schema = sch.parse_schema(SCHEMA)
    findings = lint_schema(schema, proxyrule.parse(RULES_BAD))
    errors = [f for f in findings if f.severity == "error"]
    msgs = "\n".join(f.message for f in errors)
    assert any(f.code == "SL002" and "nonexistent" in f.message
               for f in errors)
    assert any(f.code == "SL001" and "widget" in f.message for f in errors)
    assert any(f.code == "SL002" and "nosuch" in f.message
               for f in errors), msgs
    # errors sort before warnings
    assert findings[0].severity == "error"


def test_rule_reference_keeps_relation_reachable():
    """A relation read directly by a rule template (not via any
    permission) is not 'unreachable'."""
    schema = sch.parse_schema("""
definition user {}
definition doc {
  relation auditor: user
  relation viewer: user
  permission view = viewer
}
""")
    rules = proxyrule.parse("""
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: audit}
match: [{apiVersion: v1, resource: docs, verbs: [get]}]
check: [{tpl: "doc:{{name}}#auditor@user:{{user.name}}"}]
""")
    assert lint_schema(schema, rules) == []
    # without the rule, auditor IS unreachable
    assert codes(lint_schema(schema, [])) == ["SL004"]


def test_internal_types_exempt():
    from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import (
        INTERNAL_SCHEMA,
        merge_internal_definitions,
    )
    schema = merge_internal_definitions(sch.parse_schema("""
definition user {}
definition doc {
  relation viewer: user
  permission view = viewer
}
"""))
    # lock#workflow / workflow#idempotency_key feed no permission but
    # belong to the dual-write engine: never flagged
    assert lint_schema(schema, []) == []
    assert "lock" in INTERNAL_SCHEMA


def test_cli_lint_schema_verb(tmp_path, capsys):
    bootstrap = tmp_path / "bootstrap.yaml"
    bootstrap.write_text("schema: |\n" + "\n".join(
        "  " + line for line in SCHEMA.splitlines()))
    rules = tmp_path / "rules.yaml"
    rules.write_text(RULES_BAD)
    # errors -> exit 1
    rc = cli_main(["--lint-schema", "--spicedb-bootstrap", str(bootstrap),
                   "--rule-config", str(rules)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "SL001" in out and "SL002" in out
    # warnings only -> exit 0 (non-strict), 1 with --lint-schema-strict
    rc = cli_main(["--lint-schema", "--spicedb-bootstrap", str(bootstrap)])
    assert rc == 0
    rc = cli_main(["--lint-schema", "--spicedb-bootstrap", str(bootstrap),
                   "--lint-schema-strict"])
    assert rc == 1
    # the built-in default schema lints clean of errors
    assert cli_main(["--lint-schema"]) == 0


# -- SL005: undefined caveat names (ISSUE 12 satellite) -----------------------

CAVEAT_SCHEMA = """
caveat within_quota(used int, quota int) { used < quota }
definition user {}
definition doc {
  relation viewer: user | user with within_quota
  permission view = viewer
}
"""

RULES_CAVEAT_BAD = """
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: grant-caveated}
match: [{apiVersion: v1, resource: docs, verbs: [create]}]
update:
  touches:
  - tpl: 'doc:{{name}}#viewer@user:{{user.name}}[caveat:no_such_caveat:{"used": 1}]'
"""

RULES_CAVEAT_OK = RULES_CAVEAT_BAD.replace("no_such_caveat", "within_quota")


def test_sl005_rule_template_undefined_caveat():
    schema = sch.parse_schema(CAVEAT_SCHEMA)
    findings = lint_schema(schema, proxyrule.parse(RULES_CAVEAT_BAD))
    sl005 = [f for f in findings if f.code == "SL005"]
    assert len(sl005) == 1
    assert sl005[0].severity == "error"
    assert "no_such_caveat" in sl005[0].message
    assert sl005[0].where == "rule grant-caveated"
    # the same template naming a DECLARED caveat is clean
    ok = lint_schema(schema, proxyrule.parse(RULES_CAVEAT_OK))
    assert not [f for f in ok if f.code == "SL005"]


def test_sl005_programmatic_schema_undefined_caveat():
    """The parser rejects `with ghost`, but a programmatically-built
    schema IR can still carry it — lint re-checks the invariant."""
    schema = sch.parse_schema("""
definition user {}
definition doc {
  relation viewer: user
  permission view = viewer
}
""")
    schema.definitions["doc"].relations["viewer"].append(
        sch.TypeRef(type="user", traits=("ghost",)))
    findings = lint_schema(schema)
    sl005 = [f for f in findings if f.code == "SL005"]
    assert len(sl005) == 1 and sl005[0].where == "doc#viewer"
    assert "ghost" in sl005[0].message


# -- SL006: relations only reachable through an expiring path -----------------


def test_sl006_expiring_only_path():
    schema = sch.parse_schema("""
definition user {}
definition group { relation member: user }
definition ns {
  relation viewer: group#member with expiration
  relation creator: user
  permission view = viewer + creator
}
""")
    findings = lint_schema(schema)
    sl006 = [f for f in findings if f.code == "SL006"]
    assert [f.where for f in sl006] == ["group#member"]
    assert sl006[0].severity == "warn"
    # the directly-read relations are NOT flagged (their own tuples may
    # expire, but the relations are reachable without crossing an
    # expiring annotation)
    flagged = {f.where for f in sl006}
    assert "ns#viewer" not in flagged and "ns#creator" not in flagged


def test_sl006_alternate_durable_path_suppresses():
    """One non-expiring route to the relation is enough: no warning."""
    schema = sch.parse_schema("""
definition user {}
definition group { relation member: user }
definition ns {
  relation viewer: group#member with expiration
  relation auditor: group#member
  permission view = viewer + auditor
}
""")
    assert not [f for f in lint_schema(schema) if f.code == "SL006"]


def test_sl006_arrow_through_expiring_left():
    """An arrow whose left relation only accepts expiring subjects
    makes the target's whole closure expiry-gated."""
    schema = sch.parse_schema("""
definition user {}
definition org {
  relation admin: user
}
definition ns {
  relation org: org with expiration
  permission view = org->admin
}
""")
    findings = lint_schema(schema)
    sl006 = {f.where for f in findings if f.code == "SL006"}
    assert "org#admin" in sl006
    assert "ns#org" not in sl006


# -- SL007/SL008: partition-map co-location (ISSUE 15 satellite) --------------

SHARDED_SCHEMA = """
definition user {}
definition namespace {
  relation viewer: user
  permission view = viewer
}
definition pod {
  relation namespace: namespace
  relation viewer: user
  permission view = viewer + namespace->view
}
definition island {
  relation owner: user
  permission own = owner
}
"""


def test_sl007_permission_closure_spanning_shards():
    """pod#view reaches namespace#viewer through the arrow: splitting
    pod and namespace across shards is an unroutable evaluation."""
    from spicedb_kubeapi_proxy_tpu.spicedb.sharding import PartitionMap
    schema = sch.parse_schema(SHARDED_SCHEMA)
    findings = lint_schema(schema, (), partition_map=PartitionMap.parse(
        "pod=1", n_shards=2))
    sl007 = [f for f in findings if f.code == "SL007"]
    assert sl007 and all(f.severity == "error" for f in sl007)
    assert any("pod#view" in f.where for f in sl007)
    # co-locating the entangled pair clears it; the independent type
    # may live anywhere
    findings = lint_schema(schema, (), partition_map=PartitionMap.parse(
        "pod=1,namespace=1", n_shards=2))
    assert not [f for f in findings if f.code == "SL007"]


def test_sl007_rule_template_spanning_shards():
    """A rule checking one type and updating another is a dual-write:
    both types must land on one shard."""
    from spicedb_kubeapi_proxy_tpu.spicedb.sharding import PartitionMap
    schema = sch.parse_schema(SHARDED_SCHEMA)
    rules = proxyrule.parse("""
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: create-islands}
match: [{apiVersion: v1, resource: islands, verbs: [create]}]
check: [{tpl: "namespace:{{namespace}}#view@user:{{user.name}}"}]
update:
  creates:
  - tpl: "island:{{name}}#owner@user:{{user.name}}"
""")
    pm = PartitionMap.parse("island=1", n_shards=2)
    findings = lint_schema(schema, rules, partition_map=pm)
    sl007 = [f for f in findings if f.code == "SL007"]
    assert any("create-islands" in f.where for f in sl007)
    pm = PartitionMap.parse("island=0", n_shards=2)
    findings = lint_schema(schema, rules, partition_map=pm)
    assert not [f for f in findings if f.code == "SL007"]


def test_sl008_unknown_partition_map_key_warns():
    from spicedb_kubeapi_proxy_tpu.spicedb.sharding import PartitionMap
    schema = sch.parse_schema(SHARDED_SCHEMA)
    findings = lint_schema(schema, (), partition_map=PartitionMap.parse(
        "podd=1", n_shards=2))
    sl008 = [f for f in findings if f.code == "SL008"]
    assert sl008 and all(f.severity == "warn" for f in sl008)
    assert "podd" in sl008[0].message


def test_cli_lint_schema_partition_map(tmp_path, capsys):
    """--lint-schema + --partition-map/--shards engages SL007/SL008
    through the CLI (the startup-validation exit contract)."""
    bootstrap = tmp_path / "bootstrap.yaml"
    bootstrap.write_text("schema: |\n" + "\n".join(
        "  " + line for line in SHARDED_SCHEMA.splitlines()))
    rc = cli_main(["--lint-schema", "--spicedb-bootstrap", str(bootstrap),
                   "--shards", "2", "--partition-map", "pod=1"])
    out = capsys.readouterr().out
    assert rc == 1 and "SL007" in out
    rc = cli_main(["--lint-schema", "--spicedb-bootstrap", str(bootstrap),
                   "--shards", "2", "--partition-map",
                   "pod=1,namespace=1"])
    assert rc == 0


def test_sl009_leopard_over_budget(monkeypatch):
    """A pure group-membership permission whose estimated closure busts
    the byte budget warns (the pair stays iterative); a comfortable
    budget clears it, and ineligible fragments never fire."""
    schema = sch.parse_schema("""
definition user {}
definition group {
  relation member: user | group#member
  permission view = member
}
definition doc {
  relation viewer: user | group#member
  relation banned: user
  permission view = viewer
  permission allowed = view - banned
}
""")
    monkeypatch.setenv("SPICEDB_TPU_LEOPARD_LINT_OBJECTS", "100000")
    monkeypatch.setenv("SPICEDB_TPU_LEOPARD_BUDGET_BYTES", "1024")
    findings = lint_schema(schema)
    sl009 = [f for f in findings if f.code == "SL009"]
    assert sl009 and all(f.severity == "warn" for f in sl009)
    wheres = {f.where for f in sl009}
    assert {"group#view", "doc#view"} <= wheres
    # `allowed` contains an exclusion: not Leopard-eligible, never warns
    assert "doc#allowed" not in wheres
    assert "SPICEDB_TPU_LEOPARD_BUDGET_BYTES" in sl009[0].message
    # a comfortable budget clears the warning
    monkeypatch.setenv("SPICEDB_TPU_LEOPARD_BUDGET_BYTES",
                       str(64 << 30))
    assert not [f for f in lint_schema(schema) if f.code == "SL009"]
