"""Randomized differential campaign: jax:// kernels vs the host oracle.

Broader than the committed randomized tier (tests/test_jax_backend.py):
random schema template x random graph x sustained churn that mixes
in-universe edits, BRAND-NEW object/subject ids (spare-pool path),
caveated tuples with random contexts, already-expired / far-future
expirations, and deletes — oracle agreement asserted after every burst.
Kernel choice (ell/segment) is randomized per seed; `--mesh` runs every
seed on the sharded endpoint (ell-only) over a virtual 8-device CPU
mesh instead.

Usage:
    python scripts/fuzz_differential.py [n_seeds] [--mesh]
Prints one line per seed; exits non-zero on the first divergence with a
reproduction recipe.
"""

import asyncio
import os
import random
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

if "--mesh" in sys.argv and os.environ.get("_FUZZ_MESH_REEXEC") != "1":
    # the sharded path needs the virtual 8-device CPU mesh, and the env
    # must be in place before the interpreter's sitecustomize initializes
    # a jax backend — re-exec with it set
    env = dict(os.environ, _FUZZ_MESH_REEXEC="1", JAX_PLATFORMS="cpu",
               XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                          + " --xla_force_host_platform_device_count=8"))
    os.execve(sys.executable, [sys.executable] + sys.argv, env)

from spicedb_kubeapi_proxy_tpu.cli import _sync_jax_platforms

# honor JAX_PLATFORMS even under the sitecustomize that pins the axon
# backend
_sync_jax_platforms()

from spicedb_kubeapi_proxy_tpu.ops.jax_endpoint import JaxEndpoint
from spicedb_kubeapi_proxy_tpu.spicedb import schema as sch
from spicedb_kubeapi_proxy_tpu.spicedb.evaluator import Evaluator
from spicedb_kubeapi_proxy_tpu.spicedb.types import (
    CheckRequest,
    ObjectRef,
    RelationshipUpdate,
    SubjectRef,
    UpdateOp,
    parse_relationship,
)

SCHEMAS = {
    "groups": """
definition user {}
definition group { relation member: user | group#member }
definition namespace {
  relation viewer: user | group#member
  relation creator: user
  permission view = viewer + creator
}
""",
    "rbac-deny": """
definition user {}
definition group { relation member: user | group#member }
definition project {
  relation assigned: user | group#member
  relation approved: user
  relation banned: user | group#member
  permission edit = assigned & approved - banned
}
""",
    "arrows": """
definition user {}
definition org {
  relation admin: user
  permission admin_perm = admin
}
definition namespace {
  relation org: org
  relation viewer: user
  permission view = viewer + org->admin_perm
}
definition pod {
  relation namespace: namespace
  relation creator: user
  permission view = creator + namespace->view
}
""",
    "caveats": """
caveat within_limit(current int, max int) { current < max }
definition user {}
definition doc {
  relation viewer: user | user with within_limit
  relation editor: user
  permission view = viewer + editor
}
""",
}

TARGET = {"groups": ("namespace", "view"), "rbac-deny": ("project", "edit"),
          "arrows": ("pod", "view"), "caveats": ("doc", "view")}


def rand_rel(rng, kind, n, new_id_rate=0.15):
    def oid(prefix, pool):
        if rng.random() < new_id_rate:
            return f"{prefix}{rng.randrange(10 * n)}x"  # mostly brand-new
        return f"{prefix}{rng.randrange(n)}"

    u = f"user:u{rng.randrange(n)}"
    if kind == "groups":
        c = rng.random()
        if c < 0.35:
            return f"group:{oid('g', n)}#member@{u}"
        if c < 0.5:
            a, b = oid("g", n), oid("g", n)
            return f"group:{a}#member@group:{b}#member"
        if c < 0.75:
            return f"namespace:{oid('ns', n)}#viewer@{u}"
        if c < 0.85:
            # deterministic expiration cases: already expired (the lazy
            # expiry-heap delete path) or far-future (plain tuple + heap
            # bookkeeping) — never near-now, which would race the oracle
            exp = (time.time() - 3600 if rng.random() < 0.5
                   else time.time() + 86400)
            return (f"namespace:{oid('ns', n)}#viewer@{u}"
                    f"[expiration:{exp}]")
        return f"namespace:{oid('ns', n)}#creator@{u}"
    if kind == "rbac-deny":
        c = rng.random()
        if c < 0.3:
            return f"group:{oid('g', 3)}#member@{u}"
        p = oid("p", n)
        if c < 0.55:
            return f"project:{p}#assigned@group:g{rng.randrange(3)}#member"
        if c < 0.75:
            return f"project:{p}#approved@{u}"
        return f"project:{p}#banned@{u}"
    if kind == "arrows":
        c = rng.random()
        if c < 0.2:
            return f"org:{oid('o', 3)}#admin@{u}"
        if c < 0.4:
            return f"namespace:{oid('ns', n)}#org@org:o{rng.randrange(3)}"
        if c < 0.6:
            return f"namespace:{oid('ns', n)}#viewer@{u}"
        if c < 0.8:
            return (f"pod:{oid('pd', n)}#namespace"
                    f"@namespace:ns{rng.randrange(n)}")
        return f"pod:{oid('pd', n)}#creator@{u}"
    # caveats
    c = rng.random()
    d = oid("d", n)
    if c < 0.4:
        cur, mx = rng.randrange(5), rng.randrange(5)
        return (f"doc:{d}#viewer@{u}"
                f"[caveat:within_limit:{{\"current\":{cur},\"max\":{mx}}}]")
    if c < 0.5:
        # undecidable: max missing -> context-dependent at check time
        cur = rng.randrange(5)
        return (f"doc:{d}#viewer@{u}"
                f"[caveat:within_limit:{{\"current\":{cur}}}]")
    if c < 0.8:
        return f"doc:{d}#viewer@{u}"
    return f"doc:{d}#editor@{u}"


def agree(jx, oracle, rt, perm, subjects, seed, step):
    async def run():
        for s in subjects:
            want = sorted(oracle.lookup_resources(rt, perm, s))
            got = sorted(await jx.lookup_resources(rt, perm, s))
            assert got == want, (
                f"LR mismatch seed={seed} step={step} subj={s}: "
                f"kernel-only={sorted(set(got)-set(want))} "
                f"oracle-only={sorted(set(want)-set(got))}")
            ids = jx.store.object_ids_of_type(rt)
            if ids:
                reqs = [CheckRequest(ObjectRef(rt, o), perm, s) for o in ids]
                res = await jx.check_bulk_permissions(reqs)
                for o, r in zip(ids, res):
                    w3 = oracle.check3(ObjectRef(rt, o), perm, s)
                    g3 = {"NO_PERMISSION": 0, "CONDITIONAL_PERMISSION": 1,
                          "HAS_PERMISSION": 2}[r.permissionship.name]
                    assert g3 == w3, (
                        f"check3 mismatch seed={seed} step={step} "
                        f"{rt}:{o}#{perm}@{s}: kernel={g3} oracle={w3}")
    asyncio.run(run())


def run_seed(seed, mesh=None):
    rng = random.Random(seed)
    kind = rng.choice(list(SCHEMAS))
    n = rng.randint(4, 16)
    schema = sch.parse_schema(SCHEMAS[kind])
    kwargs = {}
    if mesh is not None:
        kwargs["mesh"] = mesh
        kwargs["kernel"] = "ell"  # mesh sharding is ell-only
    else:
        kwargs["kernel"] = rng.choice(["ell", "ell", "segment"])
    jx = JaxEndpoint(schema, **kwargs)
    oracle = Evaluator(schema, jx.store)
    rt, perm = TARGET[kind]
    seeds = sorted({rand_rel(rng, kind, n, new_id_rate=0)
                    for _ in range(rng.randint(5, 40))})
    jx.store.write([RelationshipUpdate(UpdateOp.TOUCH, parse_relationship(r))
                    for r in seeds])
    subjects = [SubjectRef("user", f"u{i}") for i in range(n)] + \
               [SubjectRef("user", "stranger")]
    agree(jx, oracle, rt, perm, subjects, seed, -1)
    for step in range(rng.randint(3, 7)):
        ops = []
        for _ in range(rng.randint(2, 12)):
            r = rand_rel(rng, kind, n)
            op = UpdateOp.DELETE if rng.random() < 0.35 else UpdateOp.TOUCH
            # deletes key on identity only: strip any caveat/expiry suffix
            rel = parse_relationship(r.split("[")[0]
                                     if op == UpdateOp.DELETE else r)
            ops.append(RelationshipUpdate(op, rel))
        jx.store.write(ops)
        agree(jx, oracle, rt, perm, subjects, seed, step)
    return kind, jx.stats


def main():
    args = [a for a in sys.argv[1:] if a != "--mesh"]
    n_seeds = int(args[0]) if args else 40
    mesh = None
    if "--mesh" in sys.argv:
        from spicedb_kubeapi_proxy_tpu.parallel.sharding import make_mesh
        mesh = make_mesh(data=2, graph=4)
    t0 = time.time()
    for seed in range(n_seeds):
        t1 = time.time()
        kind, stats = run_seed(seed, mesh=mesh)
        print(f"seed {seed:3d} [{kind:9s}] ok in {time.time()-t1:5.1f}s  "
              f"(rebuilds={stats['rebuilds']} spares="
              f"{stats['spare_assignments']} kernel={stats['kernel_calls']})")
    print(f"ALL {n_seeds} SEEDS AGREE in {time.time()-t0:.0f}s")


if __name__ == "__main__":
    main()
