"""Command-line entry point.

Python counterpart of the reference CLI (cmd/spicedb-kubeapi-proxy/main.go:20-64
and pkg/proxy/options.go): same flag surface (word-separator normalized, so
`--rule_config` and `--rule-config` both work), the same
Complete -> Validate -> NewServer -> Run lifecycle, and the same endpoint
dispatch on `--spicedb-endpoint` URL scheme — with `jax://` selecting the TPU
execution backend.

    python -m spicedb_kubeapi_proxy_tpu \
        --backend-kubeconfig ./backend.yaml \
        --rule-config ./rules.yaml \
        --spicedb-endpoint jax:// \
        --secure-port 8443
"""

from __future__ import annotations

import argparse
import asyncio
import json
import logging
import signal
import ssl
import sys

import yaml
from dataclasses import dataclass
from typing import Optional

from . import __version__
from .config import proxyrule
from .proxy import kubeconfig as kubecfg
from .proxy.authn import (
    Authenticator,
    ClientCertAuthenticator,
    HeaderAuthenticator,
    OIDCAuthenticator,
    RequestHeaderAuthenticator,
    TokenFileAuthenticator)
from .proxy.httpcore import Transport
from .proxy.server import Options as ServerOptions, ProxyServer
from .spicedb.endpoints import Bootstrap

DEFAULT_WORKFLOW_DATABASE_PATH = "/tmp/dtx.sqlite"  # options.go:41


def resolve_workflow_db(data_dir: str, workflow_database_path: str) -> str:
    """The SQLite dual-write journal defaults into the persistence data
    dir when one is configured: the journal and the relationship store
    must share a fate for crash recovery to replay pending dual writes
    against the state they committed into."""
    if data_dir and workflow_database_path == DEFAULT_WORKFLOW_DATABASE_PATH:
        import os
        os.makedirs(data_dir, exist_ok=True)
        return os.path.join(data_dir, "dtx.sqlite")
    return workflow_database_path


def _durable_store_on() -> bool:
    from .utils.features import GATES
    return GATES.enabled("DurableStore")


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="spicedb-kubeapi-proxy-tpu",
        description="Authorizes Kube api requests against a relationship "
                    "graph (TPU-accelerated via the jax:// endpoint).",
        allow_abbrev=False,
    )
    p.add_argument("--version", action="version", version=__version__)

    # SpiceDB endpoint options (reference options.go:106-112)
    p.add_argument("--spicedb-endpoint", default="embedded://",
                   help="endpoint authorizing proxy operations: embedded:// "
                        "(in-memory host evaluator), jax:// (TPU kernel "
                        "backend), or grpc://host:port (remote SpiceDB)")
    p.add_argument("--spicedb-insecure", action="store_true",
                   help="use insecure transport for the remote gRPC endpoint")
    p.add_argument("--spicedb-skip-verify-ca", action="store_true",
                   help="do not verify the remote endpoint's certificate chain")
    p.add_argument("--spicedb-token", default="",
                   help="preshared key for the remote SpiceDB")
    p.add_argument("--spicedb-ca-path", default="",
                   help="directory or file with CAs to trust for SpiceDB")
    p.add_argument("--spicedb-bootstrap", default="",
                   help="YAML file with bootstrap schema/relationships for "
                        "embedded:// and jax:// endpoints")
    p.add_argument("--decision-cache", action="store_true",
                   help="revision-keyed decision cache with relation-scoped "
                        "invalidation in front of the endpoint: repeated "
                        "identical checks/LookupResources are served from "
                        "cache until a write touches a relation in their "
                        "compiled footprint (embedded:// and jax:// only; "
                        "see docs/performance.md)")
    p.add_argument("--decision-cache-bytes", type=int, default=0,
                   help="decision-cache LRU bound in bytes "
                        "(0 = default 128MiB)")
    # device-resident query pipeline (ops/jax_endpoint.py,
    # spicedb/dispatch.py; docs/performance.md "Device-resident
    # pipeline"; killswitch: --feature-gates DevicePipeline=false)
    p.add_argument("--pipeline-depth", type=int, default=2,
                   help="fused dispatch pipeline depth for jax://: "
                        "N-1 started batches stay in flight so batch "
                        "N+1's host encode + upload + kernel dispatch "
                        "overlap batch N's device execution and async "
                        "D2H readback (1 = fully serial)")
    p.add_argument("--prewarm-compiles", default=True,
                   action=argparse.BooleanOptionalAction,
                   help="compile the common pow-2 batch-bucket ladder of "
                        "kernel entry points during warm start, so "
                        "first-request-per-bucket jit stalls move to "
                        "startup (jax:// only; on by default)")
    # admission control (utils/admission.py, docs/performance.md
    # "Overload & rebuild behavior"; killswitch:
    # --feature-gates AdmissionControl=false)
    p.add_argument("--max-queue-depth", type=int, default=0,
                   help="bound on each dispatcher queue (checks and "
                        "LookupResources): an enqueue past the bound is "
                        "rejected with 429 + Retry-After instead of "
                        "queueing unboundedly; dual-write authorization "
                        "is exempt (0 = unbounded)")
    p.add_argument("--shed-queue-depth", type=int, default=0,
                   help="load-shed threshold: read-only requests are "
                        "rejected with 429 + Retry-After BEFORE "
                        "authorization work starts once the dispatcher "
                        "queues reach this depth (0 = disabled)")
    p.add_argument("--shed-slo-burn", action="store_true",
                   help="also shed read-only requests while an SLO "
                        "(--slo-check-p99-ms / --slo-error-rate) burns "
                        "on both horizons; update verbs are never shed")
    p.add_argument("--shed-retry-after", type=float, default=1.0,
                   help="Retry-After seconds suggested on shed "
                        "responses")

    # WAL-shipping replication (spicedb/replication, docs/replication.md;
    # killswitch: --feature-gates Replication=false)
    p.add_argument("--replicate-from", default="",
                   help="run as a read replica of the proxy at this base "
                        "URL (e.g. http://leader:8443): bootstrap from "
                        "its newest checkpoint, tail its WAL segments, "
                        "serve read-only traffic at bounded staleness, "
                        "and forward update verbs to it.  Exclusive "
                        "with --data-dir (the leader owns the log).  "
                        "The leader serves the replication API whenever "
                        "it has a --data-dir")
    p.add_argument("--replica-wait-ms", type=float, default=2000.0,
                   help="how long a replica read carrying "
                        "X-Authz-Min-Revision waits for the tail to "
                        "reach that revision before forwarding to the "
                        "leader (or 503 when forwarding is disabled)")
    p.add_argument("--replica-forward", default=True,
                   action=argparse.BooleanOptionalAction,
                   help="forward update verbs and too-stale ZedToken "
                        "reads to the leader; --no-replica-forward "
                        "rejects them 503 with a Status naming the "
                        "leader instead")
    p.add_argument("--replica-user", default="system:replica",
                   help="identity this follower presents to the leader "
                        "(header authentication; the leader must trust "
                        "the follower's transport path)")
    p.add_argument("--shed-replica-lag", type=float, default=0.0,
                   help="shed read-only requests with 429 + Retry-After "
                        "once this replica is at least this many "
                        "seconds behind its leader (0 = disabled); a "
                        "stale replica sheds before serving garbage")
    # replication fault tolerance (spicedb/replication/failover.py,
    # docs/replication.md "Failover runbook")
    p.add_argument("--serve-replication", action="store_true",
                   help="this follower also serves /replication/* from "
                        "a byte mirror of what it applies, so further "
                        "followers chain off it (fan-out trees) instead "
                        "of NIC-saturating one leader; requires "
                        "--replicate-from")
    p.add_argument("--replication-mirror-dir", default="",
                   help="directory for the --serve-replication artifact "
                        "mirror (default: a private temp dir)")
    p.add_argument("--promote-data-dir", default="",
                   help="data dir this follower will own if promoted to "
                        "leader (POST /replication/promote or "
                        "--promote-on-leader-loss); its WAL/checkpoints "
                        "are wiped at promotion — only the incarnation "
                        "epoch persists across promotions")
    p.add_argument("--promote-on-leader-loss", action="store_true",
                   help="watchdog: after --leader-loss-grace seconds "
                        "without a successful sync, poll "
                        "--replica-peers and run the election (highest "
                        "adopted revision wins, ties break on smallest "
                        "--replica-id); the winner promotes itself, "
                        "losers repoint to it.  Requires "
                        "--promote-data-dir")
    p.add_argument("--leader-loss-grace", type=float, default=5.0,
                   help="seconds without a successful sync before the "
                        "leader-loss watchdog starts an election; keep "
                        "it well under one flight window so failover "
                        "completes inside it")
    p.add_argument("--replica-peers", default="",
                   help="comma-separated base URLs of the other proxies "
                        "in the fleet: election candidates for a "
                        "follower, fence probes for a (possibly "
                        "resurrected) leader")
    p.add_argument("--replica-id", default="",
                   help="stable identity in elections and "
                        "/replication/status (default: minted per "
                        "process); the election tie-break orders on it")
    p.add_argument("--fleet-peers", default="",
                   help="comma-separated base URLs of fleet members "
                        "whose /debug/traces + /debug/flight + /metrics "
                        "this node merges at /debug/fleet (cross-process "
                        "trace assembly + per-tier attribution; docs/"
                        "observability.md \"Fleet tracing\").  On the "
                        "--shard-leaders router the shard leaders are "
                        "included implicitly")

    # partitioned write scale-out (spicedb/sharding, docs/replication.md
    # "Sharding"; killswitch: --feature-gates Sharding=false)
    p.add_argument("--shards", type=int, default=1,
                   help="split the tuple space by resource type across "
                        "this many independent in-process leaders, each "
                        "with its own WAL/checkpoint lineage under "
                        "<data-dir>/shard-<k> (embedded:// and jax:// "
                        "only; 1 = single leader).  The partition is "
                        "validated against every permission's and "
                        "rule's relation_footprint closure at startup: "
                        "a closure spanning two shards is a hard error")
    p.add_argument("--partition-map", default="",
                   help="comma-separated type=shard assignments "
                        "(e.g. pod=0,secret=1); unassigned types land "
                        "on shard 0.  Shared verbatim by the router "
                        "and every shard leader")
    p.add_argument("--shard-leaders", default="",
                   help="router mode: serve as a thin stateless router "
                        "over these comma-separated shard-leader base "
                        "URLs (one per shard, index = shard id).  Each "
                        "leader is an unmodified proxy with its own "
                        "data dir and replication tree; the router "
                        "maps each request to the shard its matched "
                        "rules' types live on, and translates "
                        "revision-vector ZedTokens to per-shard "
                        "components.  Exclusive with serving locally")

    # static schema/rule lint (spicedb/schema_lint.py, Cedar-inspired):
    # analyze instead of serve
    p.add_argument("--lint-schema", action="store_true",
                   help="lint the bootstrap schema (--spicedb-bootstrap; "
                        "the built-in default schema when omitted) and "
                        "the proxy rules (--rule-config) instead of "
                        "serving: flags unreachable relations, "
                        "permissions with empty footprints, and rule "
                        "templates referencing undefined relations.  "
                        "Exit 1 on errors; --lint-schema-strict also "
                        "fails on warnings")
    p.add_argument("--lint-schema-strict", action="store_true",
                   help="with --lint-schema, exit 1 on warnings too")
    p.add_argument("--lint-schema-json", action="store_true",
                   help="with --lint-schema, emit machine-readable JSON "
                        "(the scripts/analyze.py driver consumes this); "
                        "same exit-code contract: 0 clean, 1 findings, "
                        "2 inputs would not boot")

    # upstream cluster (options.go:203-206)
    p.add_argument("--backend-kubeconfig", default="",
                   help="path to the kubeconfig for the upstream apiserver; "
                        "should authenticate with cluster-admin permission")
    p.add_argument("--use-in-cluster-config", action="store_true",
                   help="use the ambient service-account config as upstream")
    p.add_argument("--override-upstream", action="store_true", default=True,
                   help="rewrite the kubeconfig server address from the "
                        "KUBERNETES_SERVICE_HOST/PORT environment")
    p.add_argument("--no-override-upstream", dest="override_upstream",
                   action="store_false")

    # rules + workflow (options.go:201-202,207)
    p.add_argument("--rule-config", default="",
                   help="path to the proxy rule configuration (multi-doc YAML)")
    p.add_argument("--workflow-database-path",
                   default=DEFAULT_WORKFLOW_DATABASE_PATH,
                   help="SQLite database backing the dual-write workflow "
                        "engine (defaults into --data-dir/dtx.sqlite when "
                        "a data dir is configured)")

    # durable relationship store (spicedb/persist, docs/durability.md)
    p.add_argument("--data-dir", default="",
                   help="directory for the durable relationship store "
                        "(segmented WAL + columnar checkpoints); empty = "
                        "in-memory only.  On restart the store is "
                        "recovered from the newest checkpoint plus the "
                        "WAL tail, the revision counter continues, and "
                        "the bootstrap RELATIONSHIPS are skipped "
                        "(bootstrap-once) — keep passing "
                        "--spicedb-bootstrap: its schema is not "
                        "persisted and is required every start")
    p.add_argument("--wal-fsync", default="interval",
                   choices=["always", "interval", "never"],
                   help="WAL fsync policy: always (every committed write "
                        "is durable before it is acked), interval "
                        "(~1s loss window), never (OS cache only)")
    p.add_argument("--checkpoint-interval", type=float, default=300.0,
                   help="seconds between store checkpoints; each "
                        "checkpoint lets covered WAL segments be "
                        "reclaimed and bounds restart replay time")
    p.add_argument("--lock-mode", default=proxyrule.PESSIMISTIC_LOCK_MODE,
                   choices=[proxyrule.PESSIMISTIC_LOCK_MODE,
                            proxyrule.OPTIMISTIC_LOCK_MODE],
                   help="default dual-write locking strategy")

    # serving (SecureServingOptions)
    p.add_argument("--bind-address", default="0.0.0.0")
    p.add_argument("--secure-port", type=int, default=443)
    p.add_argument("--cert-dir", default="apiserver.local.config/certificates",
                   help="directory for the serving certificate pair; a "
                        "self-signed pair is generated if none exists")
    p.add_argument("--tls-cert-file", default="")
    p.add_argument("--tls-private-key-file", default="")
    p.add_argument("--embedded-mode", action="store_true",
                   help="serve plain HTTP with header authentication "
                        "(X-Remote-User/-Group/-Extra-*); for use behind a "
                        "trusted front end or for embedding")

    # authentication (reference authn.go:17-53)
    p.add_argument("--client-ca-file", default="",
                   help="CA bundle for verifying client certificates "
                        "(CN -> user, O -> groups)")
    p.add_argument("--token-auth-file", default="",
                   help="CSV file of static bearer tokens "
                        "(token,user,uid,groups)")
    # front-proxy (request-header) authn (reference authn.go:121-153)
    p.add_argument("--requestheader-client-ca-file", default="",
                   help="CA bundle; X-Remote-* identity headers are "
                        "trusted only from clients whose certificate "
                        "verifies against it")
    p.add_argument("--requestheader-allowed-names", default="",
                   help="comma-separated CNs allowed to front-proxy; "
                        "empty = any CN under the requestheader CA")
    p.add_argument("--requestheader-username-headers",
                   default="X-Remote-User")
    p.add_argument("--requestheader-group-headers",
                   default="X-Remote-Group")
    p.add_argument("--requestheader-extra-headers-prefix",
                   default="X-Remote-Extra-")
    # OIDC bearer authn with static JWKS (no egress for discovery)
    p.add_argument("--oidc-issuer-url", default="")
    p.add_argument("--oidc-client-id", default="")
    p.add_argument("--oidc-jwks-file", default="",
                   help="static JWKS (RFC 7517) file with the issuer's "
                        "signing keys; required with --oidc-issuer-url")
    p.add_argument("--oidc-username-claim", default="sub")
    p.add_argument("--oidc-groups-claim", default="groups")
    p.add_argument("--oidc-username-prefix", default="")

    # observability (docs/observability.md)
    p.add_argument("--trace-slow-threshold", type=float, default=0.0,
                   help="seconds; a request slower than this logs its full "
                        "trace (per-phase span breakdown) as structured "
                        "JSON (0 disables the log; traces always feed "
                        "/debug/traces and the phase histograms)")
    p.add_argument("--audit-level", default="Metadata",
                   help="decision-audit level: None (off), Metadata "
                        "(identity + decision), Request (adds relationship "
                        "strings, caveat context, explain witnesses); "
                        "recent decisions serve at /debug/decisions")
    p.add_argument("--audit-sample-every", type=int, default=1,
                   help="emit 1 of every N ALLOWED decisions per "
                        "(user, verb); denials and errors always pass")
    p.add_argument("--audit-explain", action="store_true",
                   help="attach the relation-path witness to every audited "
                        "denial (otherwise only requests with ?explain=1 "
                        "are explained)")
    # device telemetry: flight recorder + SLO burn rates
    # (utils/devtel.py, docs/observability.md "Device telemetry")
    p.add_argument("--flight-window", type=float, default=10.0,
                   help="seconds per flight-recorder window; each window "
                        "snapshots phase quantiles, queue depths, the HBM "
                        "ledger, batch occupancy, and SLO burn rates, "
                        "served at /debug/flight")
    p.add_argument("--flight-windows", type=int, default=64,
                   help="flight-recorder ring capacity (windows retained)")
    p.add_argument("--slo-check-p99-ms", type=float, default=0.0,
                   help="latency SLO target in ms: requests slower than "
                        "this consume the error budget set by "
                        "--slo-objective; burn rates export as "
                        "authz_slo_burn_rate{slo=latency_p99} and surface "
                        "in /readyz when burning (0 disables)")
    p.add_argument("--slo-objective", type=float, default=0.01,
                   help="allowed fraction of requests slower than the "
                        "latency SLO target (the error budget; burn rate "
                        "1.0 = consuming it exactly at the sustainable "
                        "rate)")
    p.add_argument("--slo-error-rate", type=float, default=0.0,
                   help="error SLO: allowed fraction of 5xx responses "
                        "(0 disables)")
    # dispatch timeline profiler (utils/timeline.py,
    # docs/observability.md "Dispatch timeline")
    p.add_argument("--device-hbm-peak-gbps", type=float, default=0.0,
                   help="device HBM peak bandwidth in GB/s for the "
                        "authz_roofline_fraction export and the "
                        "/debug/timeline summary; 0 (default) "
                        "auto-detects from the jax platform "
                        "(tpu/v5e -> 819)")

    p.add_argument("-v", "--verbosity", type=int, default=3,
                   help="log verbosity (reference defaults to 3)")
    p.add_argument("--feature-gates", default="",
                   help="comma-separated name=true|false feature gate "
                        "overrides (reference features.go:10-27); known "
                        "gates: see utils/features.py")
    return p


@dataclass
class CompletedConfig:
    server_options: ServerOptions
    bind_address: str
    secure_port: int
    embedded_mode: bool


class OptionsError(ValueError):
    pass


def validate(args: argparse.Namespace) -> list:
    """Mirror of Options.Validate (reference options.go:412-427)."""
    errs = []
    if args.lint_schema:
        # analysis mode: no upstream, no serving — only the schema/rule
        # inputs matter
        return []
    from .spicedb.sharding import PartitionMap, PartitionMapError
    if args.shard_leaders:
        # router mode: no upstream, no local endpoint — the shard
        # leaders do the serving
        urls = [u.strip() for u in args.shard_leaders.split(",")
                if u.strip()]
        for u in urls:
            if not u.startswith(("http://", "https://")):
                errs.append(f"--shard-leaders entry {u!r} must be an "
                            f"http(s) base URL")
        if args.shards > 1:
            errs.append("--shards describes in-process sharding; router "
                        "mode derives the shard count from the "
                        "--shard-leaders list")
        if args.replicate_from:
            errs.append("--shard-leaders (router mode) is exclusive "
                        "with --replicate-from")
        if args.data_dir:
            errs.append("--shard-leaders (router mode) is exclusive "
                        "with --data-dir: the router is stateless; the "
                        "shard leaders own the logs")
        if urls and not errs:
            try:
                PartitionMap.parse(args.partition_map,
                                   n_shards=len(urls))
            except PartitionMapError as e:
                errs.append(f"--partition-map: {e}")
        if not args.embedded_mode and not (0 < args.secure_port < 65536):
            errs.append(f"--secure-port {args.secure_port} is not a "
                        f"valid port")
        return errs
    if args.shards < 1:
        errs.append("--shards must be >= 1")
    elif args.shards > 1:
        if not args.spicedb_endpoint.startswith(("embedded", "jax")):
            errs.append("--shards requires a store-backed endpoint "
                        "(embedded:// or jax://)")
        if args.replicate_from:
            errs.append("--shards is exclusive with --replicate-from: "
                        "a follower tails ONE leader's log; run one "
                        "follower per shard leader instead")
        try:
            PartitionMap.parse(args.partition_map, n_shards=args.shards)
        except PartitionMapError as e:
            errs.append(f"--partition-map: {e}")
    elif args.partition_map:
        try:
            PartitionMap.parse(args.partition_map)
        except PartitionMapError as e:
            errs.append(f"--partition-map: {e}")
    if not args.backend_kubeconfig and not args.use_in_cluster_config:
        errs.append("either --backend-kubeconfig or --use-in-cluster-config"
                    " must be specified")
    if not args.rule_config:
        errs.append("--rule-config is required")
    if not args.embedded_mode and not (0 < args.secure_port < 65536):
        errs.append(f"--secure-port {args.secure_port} is not a valid port")
    if args.trace_slow_threshold < 0:
        errs.append("--trace-slow-threshold must be >= 0")
    if (args.decision_cache
            and not args.spicedb_endpoint.startswith(("embedded", "jax"))):
        errs.append("--decision-cache requires a store-backed endpoint "
                    "(embedded:// or jax://)")
    if args.decision_cache_bytes < 0:
        errs.append("--decision-cache-bytes must be >= 0")
    if (args.data_dir
            and not args.spicedb_endpoint.startswith(("embedded", "jax"))):
        errs.append("--data-dir persistence requires a store-backed "
                    "endpoint (embedded:// or jax://)")
    if args.checkpoint_interval <= 0:
        errs.append("--checkpoint-interval must be > 0")
    from .utils.audit import parse_level
    try:
        parse_level(args.audit_level)
    except ValueError as e:
        errs.append(f"--audit-level: {e}")
    if args.audit_sample_every < 1:
        errs.append("--audit-sample-every must be >= 1")
    if args.flight_window <= 0:
        errs.append("--flight-window must be > 0")
    if args.flight_windows < 2:
        errs.append("--flight-windows must be >= 2 (burn rates need a "
                    "short and a long horizon)")
    if args.slo_check_p99_ms < 0:
        errs.append("--slo-check-p99-ms must be >= 0")
    if not (0 < args.slo_objective <= 1):
        errs.append("--slo-objective must be in (0, 1]")
    if not (0 <= args.slo_error_rate <= 1):
        errs.append("--slo-error-rate must be in [0, 1]")
    if args.device_hbm_peak_gbps < 0:
        errs.append("--device-hbm-peak-gbps must be >= 0 (0 = auto)")
    if args.pipeline_depth < 1:
        errs.append("--pipeline-depth must be >= 1 (1 = fully serial)")
    if args.max_queue_depth < 0:
        errs.append("--max-queue-depth must be >= 0 (0 = unbounded)")
    if args.shed_queue_depth < 0:
        errs.append("--shed-queue-depth must be >= 0 (0 = disabled)")
    if args.shed_retry_after <= 0:
        errs.append("--shed-retry-after must be > 0")
    if args.shed_slo_burn and not (args.slo_check_p99_ms > 0
                                   or args.slo_error_rate > 0):
        errs.append("--shed-slo-burn needs an SLO configured "
                    "(--slo-check-p99-ms or --slo-error-rate)")
    if args.replicate_from:
        if not args.spicedb_endpoint.startswith(("embedded", "jax")):
            errs.append("--replicate-from requires a store-backed "
                        "endpoint (embedded:// or jax://)")
        if args.data_dir:
            errs.append("--replicate-from is exclusive with --data-dir: "
                        "a follower re-bootstraps from its leader and "
                        "must not journal the leader's log as its own")
        if not args.replicate_from.startswith(("http://", "https://")):
            errs.append("--replicate-from must be an http(s) base URL")
    if args.replica_wait_ms < 0:
        errs.append("--replica-wait-ms must be >= 0")
    if args.serve_replication and not args.replicate_from:
        errs.append("--serve-replication only applies to a replica "
                    "(--replicate-from); a leader always serves "
                    "/replication/* with a --data-dir")
    if args.promote_on_leader_loss and not args.replicate_from:
        errs.append("--promote-on-leader-loss only applies to a replica "
                    "(--replicate-from)")
    if args.promote_on_leader_loss and not args.promote_data_dir:
        errs.append("--promote-on-leader-loss needs --promote-data-dir "
                    "(the data dir a promoted leader will own)")
    if args.promote_data_dir and not args.replicate_from:
        errs.append("--promote-data-dir only applies to a replica "
                    "(--replicate-from)")
    if args.leader_loss_grace <= 0:
        errs.append("--leader-loss-grace must be > 0")
    for peer in (u.strip() for u in args.replica_peers.split(",")):
        if peer and not peer.startswith(("http://", "https://")):
            errs.append(f"--replica-peers entry {peer!r} must be an "
                        f"http(s) base URL")
    for peer in (u.strip() for u in args.fleet_peers.split(",")):
        if peer and not peer.startswith(("http://", "https://")):
            errs.append(f"--fleet-peers entry {peer!r} must be an "
                        f"http(s) base URL")
    if args.shed_replica_lag < 0:
        errs.append("--shed-replica-lag must be >= 0 (0 = disabled)")
    if args.shed_replica_lag > 0 and not args.replicate_from:
        errs.append("--shed-replica-lag only applies to a replica "
                    "(--replicate-from)")
    return errs


def complete(args: argparse.Namespace,
             upstream_transport: Optional[Transport] = None) -> CompletedConfig:
    """Mirror of Options.Complete (reference options.go:213-380): logging,
    upstream transport, rules, serving certs, authenticators, endpoint."""
    level = (logging.DEBUG if args.verbosity >= 4
             else logging.INFO if args.verbosity >= 2 else logging.WARNING)
    logging.basicConfig(
        level=level,
        format="%(asctime)s %(levelname).1s %(name)s: %(message)s")

    if getattr(args, "feature_gates", ""):
        from .utils.features import GATES, FeatureGateError
        try:
            GATES.apply_flag(args.feature_gates)
        except FeatureGateError as e:
            raise OptionsError(f"invalid --feature-gates: {e}") from e

    rule_configs: list = []
    if args.rule_config:
        try:
            with open(args.rule_config, "r", encoding="utf-8") as f:
                rules_yaml = f.read()
        except OSError as e:
            raise OptionsError(f"couldn't load rule config: {e}") from e
        try:
            rule_configs = proxyrule.parse(rules_yaml)
        except Exception as e:
            raise OptionsError(f"invalid rule config: {e}") from e

    if upstream_transport is None:
        if args.use_in_cluster_config:
            ctx = kubecfg.in_cluster_context()
        elif args.backend_kubeconfig:
            try:
                ctx = kubecfg.load_kubeconfig(
                    args.backend_kubeconfig,
                    override_upstream=args.override_upstream)
            except OSError as e:
                raise OptionsError(
                    f"couldn't load kubeconfig from path: {e}") from e
        else:
            raise OptionsError("no upstream configured")
        upstream_transport = kubecfg.transport_for(ctx)

    bootstrap = None
    if args.spicedb_bootstrap:
        try:
            bootstrap = Bootstrap.from_file(args.spicedb_bootstrap)
        except (OSError, ValueError) as e:
            raise OptionsError(f"couldn't load spicedb bootstrap: {e}") from e

    ssl_context: Optional[ssl.SSLContext] = None
    authenticators: list[Authenticator] = []
    if args.embedded_mode:
        authenticators.append(HeaderAuthenticator())
    else:
        cert_file, key_file = args.tls_cert_file, args.tls_private_key_file
        if bool(cert_file) != bool(key_file):
            raise OptionsError(
                "--tls-cert-file and --tls-private-key-file must be"
                " specified together")
        if not cert_file:
            cert_file, key_file = kubecfg.generate_self_signed_cert(
                args.cert_dir, hosts=[args.bind_address])
        ssl_context = kubecfg.serving_ssl_context(
            cert_file, key_file, client_ca_file=args.client_ca_file,
            extra_client_ca_files=(args.requestheader_client_ca_file,))
        if args.requestheader_client_ca_file:
            # requestheader outranks plain client-cert authn, matching the
            # k8s union authenticator's order
            try:
                authenticators.append(RequestHeaderAuthenticator(
                    args.requestheader_client_ca_file,
                    allowed_names=tuple(
                        n for n in
                        args.requestheader_allowed_names.split(",") if n),
                    username_headers=tuple(
                        args.requestheader_username_headers.split(",")),
                    group_headers=tuple(
                        args.requestheader_group_headers.split(",")),
                    extra_prefixes=tuple(
                        args.requestheader_extra_headers_prefix.split(","))))
            except (OSError, ValueError) as e:
                raise OptionsError(
                    f"couldn't load requestheader CA: {e}") from e
        if args.client_ca_file:
            authenticators.append(ClientCertAuthenticator())
    if args.oidc_issuer_url:
        if not args.oidc_jwks_file:
            raise OptionsError(
                "--oidc-jwks-file is required with --oidc-issuer-url "
                "(no egress for issuer discovery)")
        try:
            authenticators.append(OIDCAuthenticator(
                args.oidc_issuer_url, args.oidc_client_id,
                args.oidc_jwks_file,
                username_claim=args.oidc_username_claim,
                groups_claim=args.oidc_groups_claim,
                username_prefix=args.oidc_username_prefix))
        except (OSError, ValueError) as e:
            raise OptionsError(f"couldn't load OIDC JWKS: {e}") from e
    if args.token_auth_file:
        try:
            authenticators.append(TokenFileAuthenticator(args.token_auth_file))
        except OSError as e:
            raise OptionsError(f"couldn't load token auth file: {e}") from e
    if not authenticators:
        # serving mode with no explicit authn: accept client certs if the
        # handshake produced one (self-signed default trusts none)
        authenticators.append(ClientCertAuthenticator())

    endpoint_kwargs = {}
    # fused-dispatch pipeline depth; a `jax://?pipeline_depth=N` URL
    # parameter still overrides the flag inside create_endpoint
    endpoint_kwargs["pipeline_depth"] = args.pipeline_depth
    # dispatcher queue bound (admission control); a
    # `jax://?max_queue_depth=N` URL parameter still overrides
    endpoint_kwargs["max_queue_depth"] = args.max_queue_depth
    if args.decision_cache:
        endpoint_kwargs["decision_cache"] = True
    if args.decision_cache_bytes:
        # independent of --decision-cache: the cache may also come up via
        # `?cache=1` or the DecisionCache gate, and a bound the operator
        # set must apply then too
        endpoint_kwargs["decision_cache_bytes"] = args.decision_cache_bytes
    if not args.spicedb_endpoint.startswith(("embedded", "jax")):
        # every non-local endpoint dials gRPC — including the reference's
        # scheme-less `host:port` default shape (options.go:107) — and
        # must carry the connection flags
        endpoint_kwargs = {
            "token": args.spicedb_token,
            "insecure": args.spicedb_insecure,
            "skip_verify_ca": args.spicedb_skip_verify_ca,
            "ca_path": args.spicedb_ca_path,
        }

    server_options = ServerOptions(
        spicedb_endpoint=args.spicedb_endpoint,
        bootstrap=bootstrap,
        rule_configs=rule_configs,
        upstream_transport=upstream_transport,
        authenticators=authenticators,
        # the journal relocates into the data dir only when persistence
        # will actually engage: with the DurableStore gate off the store
        # runs in-memory, and the journal must not imply a shared fate
        # that does not exist
        workflow_database_path=resolve_workflow_db(
            args.data_dir if _durable_store_on() else "",
            args.workflow_database_path),
        lock_mode_default=args.lock_mode,
        ssl_context=ssl_context,
        endpoint_kwargs=endpoint_kwargs,
        trace_slow_threshold=args.trace_slow_threshold,
        audit_level=args.audit_level,
        audit_sample_every=args.audit_sample_every,
        audit_explain=args.audit_explain,
        data_dir=args.data_dir,
        wal_fsync=args.wal_fsync,
        checkpoint_interval=args.checkpoint_interval,
        flight_window_s=args.flight_window,
        flight_windows=args.flight_windows,
        slo_check_p99_ms=args.slo_check_p99_ms,
        slo_objective=args.slo_objective,
        slo_error_rate=args.slo_error_rate,
        device_hbm_peak_gbps=args.device_hbm_peak_gbps,
        prewarm_compiles=args.prewarm_compiles,
        shed_queue_depth=args.shed_queue_depth,
        shed_slo_burn=args.shed_slo_burn,
        shed_retry_after_s=args.shed_retry_after,
        replicate_from=args.replicate_from,
        replica_wait_ms=args.replica_wait_ms,
        replica_forward=args.replica_forward,
        replica_user=args.replica_user,
        shed_replica_lag_s=args.shed_replica_lag,
        serve_replication=args.serve_replication,
        mirror_dir=args.replication_mirror_dir,
        promote_data_dir=args.promote_data_dir,
        promote_on_leader_loss=args.promote_on_leader_loss,
        leader_loss_grace_s=args.leader_loss_grace,
        replica_peers=[u.strip() for u in args.replica_peers.split(",")
                       if u.strip()],
        replica_id=args.replica_id,
        shards=args.shards,
        partition_map=args.partition_map,
        fleet_peers=[u.strip() for u in args.fleet_peers.split(",")
                     if u.strip()],
    )
    return CompletedConfig(server_options=server_options,
                           bind_address=args.bind_address,
                           secure_port=args.secure_port,
                           embedded_mode=args.embedded_mode)


async def run_server(completed: CompletedConfig) -> None:
    """Server.Run equivalent (reference server.go:170-208): serve until
    SIGINT/SIGTERM."""
    server = ProxyServer(completed.server_options)
    server.enable_dual_writes()
    port = await server.start(completed.bind_address, completed.secure_port)
    scheme = "http" if completed.embedded_mode else "https"
    logging.getLogger("spicedb_kubeapi_proxy_tpu").info(
        "serving on %s://%s:%d", scheme, completed.bind_address, port)

    stop = asyncio.Event()
    loop = asyncio.get_running_loop()
    for sig in (signal.SIGINT, signal.SIGTERM):
        try:
            loop.add_signal_handler(sig, stop.set)
        except NotImplementedError:  # non-unix
            pass
    try:
        await stop.wait()
    finally:
        await server.stop()


def _normalize_argv(argv: list) -> list:
    """pflag word-separator normalization (reference main.go:23): underscores
    in flag names are equivalent to dashes."""
    out = []
    for a in argv:
        if a.startswith("--") and "=" in a:
            name, _, val = a.partition("=")
            out.append(name.replace("_", "-") + "=" + val)
        elif a.startswith("--"):
            out.append(a.replace("_", "-"))
        else:
            out.append(a)
    return out


def _sync_jax_platforms() -> None:
    """Honor JAX_PLATFORMS even when a sitecustomize has already pinned
    jax.config.jax_platforms to a different backend (the env var alone is
    ignored once the config value is set)."""
    import os

    plat = os.environ.get("JAX_PLATFORMS")
    if not plat:
        return
    try:
        import jax

        if getattr(jax.config, "jax_platforms", None) != plat:
            jax.config.update("jax_platforms", plat)
    except Exception:
        pass


def run_schema_lint(args: argparse.Namespace) -> int:
    """`--lint-schema`: static schema/rule analysis (Cedar-inspired;
    spicedb/schema_lint.py) instead of serving.  Exit 0 = clean (or
    warnings only, unless --lint-schema-strict), 1 = findings, 2 = the
    inputs would not even boot."""
    from .spicedb import schema_lint
    from .spicedb import schema as sch
    from .spicedb.endpoints import (
        Bootstrap,
        DEFAULT_BOOTSTRAP_SCHEMA,
        merge_internal_definitions,
    )

    try:
        schema_text = DEFAULT_BOOTSTRAP_SCHEMA
        if args.spicedb_bootstrap:
            bootstrap = Bootstrap.from_file(args.spicedb_bootstrap)
            if bootstrap.schema_text:
                schema_text = bootstrap.schema_text
        schema = merge_internal_definitions(sch.parse_schema(schema_text))
        rule_configs = (proxyrule.parse_file(args.rule_config)
                        if args.rule_config else [])
        # sharding co-location lint (SL007/SL008) engages when a
        # partition is configured: --shards N and/or an explicit
        # --partition-map (router mode infers the count from the
        # leader list)
        partition_map = None
        if args.partition_map or args.shards > 1 or args.shard_leaders:
            from .spicedb.sharding import PartitionMap
            n_shards = None
            if args.shard_leaders:
                n_shards = len([u for u in args.shard_leaders.split(",")
                                if u.strip()])
            elif args.shards > 1:
                n_shards = args.shards
            partition_map = PartitionMap.parse(args.partition_map,
                                               n_shards=n_shards)
    except Exception as e:
        if args.lint_schema_json:
            print(json.dumps({"version": 1, "error": str(e),
                              "findings": []}))
        print(f"error: {e}", file=sys.stderr)
        return 2
    findings = schema_lint.lint_schema(schema, rule_configs,
                                       partition_map=partition_map)
    errors = [f for f in findings if f.severity == "error"]
    warnings = [f for f in findings if f.severity != "error"]
    failed = bool(errors or (warnings and args.lint_schema_strict))
    if args.lint_schema_json:
        # the exact shape scripts/analyze.py --all consumes; exit-code
        # contract shared with the driver (0 clean, 1 findings, 2 boot
        # failure)
        print(json.dumps({
            "version": 1,
            "findings": [{"code": f.code, "severity": f.severity,
                          "where": f.where, "message": f.message}
                         for f in findings],
            "summary": {"errors": len(errors), "warnings": len(warnings),
                        "strict": bool(args.lint_schema_strict)},
        }, indent=1))
    else:
        for f in findings:
            print(f"{f.severity.upper()} {f.code} [{f.where}] {f.message}")
        print(f"schema lint: {len(errors)} errors, "
              f"{len(warnings)} warnings")
    return 1 if failed else 0


def run_router(args: argparse.Namespace) -> int:
    """`--shard-leaders`: serve as the thin stateless shard router
    (spicedb/sharding/router.py) instead of a local proxy.  The routing
    table derives from --rule-config (+ the bootstrap schema's
    footprint closures when supplied) and is validated at startup: a
    rule whose types span shards refuses to boot."""
    level = (logging.DEBUG if args.verbosity >= 4
             else logging.INFO if args.verbosity >= 2 else logging.WARNING)
    logging.basicConfig(
        level=level,
        format="%(asctime)s %(levelname).1s %(name)s: %(message)s")
    if getattr(args, "feature_gates", ""):
        from .utils.features import GATES, FeatureGateError
        try:
            GATES.apply_flag(args.feature_gates)
        except FeatureGateError as e:
            print(f"error: invalid --feature-gates: {e}", file=sys.stderr)
            return 1
    from .spicedb import schema as sch
    from .spicedb import sharding
    from .spicedb.endpoints import merge_internal_definitions
    urls = [u.strip() for u in args.shard_leaders.split(",") if u.strip()]
    try:
        pmap = sharding.PartitionMap.parse(args.partition_map,
                                           n_shards=len(urls))
        rule_configs = (proxyrule.parse_file(args.rule_config)
                        if args.rule_config else [])
        schema = None
        if args.spicedb_bootstrap:
            bootstrap = Bootstrap.from_file(args.spicedb_bootstrap)
            if bootstrap.schema_text:
                schema = merge_internal_definitions(
                    sch.parse_schema(bootstrap.schema_text))
        ssl_context: Optional[ssl.SSLContext] = None
        if not args.embedded_mode:
            cert_file, key_file = args.tls_cert_file, args.tls_private_key_file
            if bool(cert_file) != bool(key_file):
                raise OptionsError(
                    "--tls-cert-file and --tls-private-key-file must be"
                    " specified together")
            if not cert_file:
                cert_file, key_file = kubecfg.generate_self_signed_cert(
                    args.cert_dir, hosts=[args.bind_address])
            ssl_context = kubecfg.serving_ssl_context(cert_file, key_file)
        server = sharding.RouterServer(
            pmap, urls, rule_configs=rule_configs, schema=schema,
            ssl_context=ssl_context,
            fleet_peers=[u.strip() for u in args.fleet_peers.split(",")
                         if u.strip()])
    except (OSError, ValueError, yaml.YAMLError) as e:
        # yaml.YAMLError: Bootstrap.from_file / parse_file surface
        # malformed YAML directly, and it is not a ValueError subclass
        print(f"error: {e}", file=sys.stderr)
        return 1
    log = logging.getLogger("spicedb_kubeapi_proxy_tpu")
    if not sharding.enabled():
        log.info("Sharding gate disabled: routing everything to shard "
                 "%d (pass-through)", pmap.default_shard)

    async def serve() -> None:
        port = await server.start(args.bind_address, args.secure_port)
        scheme = "http" if args.embedded_mode else "https"
        log.info("shard router serving on %s://%s:%d over %d shard "
                 "leader(s): %s", scheme, args.bind_address, port,
                 len(urls), ", ".join(urls))
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGINT, signal.SIGTERM):
            try:
                loop.add_signal_handler(sig, stop.set)
            except NotImplementedError:  # non-unix
                pass
        try:
            await stop.wait()
        finally:
            await server.stop()

    try:
        asyncio.run(serve())
    except KeyboardInterrupt:
        pass
    return 0


def main(argv: Optional[list] = None) -> int:
    _sync_jax_platforms()
    parser = build_parser()
    args = parser.parse_args(_normalize_argv(
        list(sys.argv[1:] if argv is None else argv)))
    errs = validate(args)
    if errs:
        for e in errs:
            print(f"error: {e}", file=sys.stderr)
        return 2
    if args.lint_schema:
        return run_schema_lint(args)
    if args.shard_leaders:
        return run_router(args)
    try:
        completed = complete(args)
    except OptionsError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    try:
        asyncio.run(run_server(completed))
    except KeyboardInterrupt:
        pass
    return 0


if __name__ == "__main__":
    sys.exit(main())
