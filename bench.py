#!/usr/bin/env python
"""Benchmark harness: authz checks/sec, jax:// kernel vs the python oracle.

Prints ONE JSON line on stdout, ALWAYS (a global watchdog and a top-level
exception handler both emit the line with an "error" field rather than
dying with a traceback):

  {"metric": ..., "value": N, "unit": "checks/s", "vs_baseline": N,
   "p99_list_filter_ms": N, "platform": ..., "baseline": "python-oracle", ...}

The headline config follows BASELINE.json config 5: filtering list requests
against a 1M-tuple multi-tenant depth-4 graph, 256 *concurrent list
requests* fused by the cross-request dispatcher (spicedb/dispatch.py) —
i.e. the exact path production `jax://` traffic takes.  The direct
batched-kernel number is reported alongside as `direct_batch_checks_per_s`.

Honesty note (VERDICT r2 weak-1): `vs_baseline` compares against THIS
repo's single-threaded pure-Python oracle evaluator — NOT the reference's
embedded Go SpiceDB, which cannot run in this image.  The payload carries
`baseline: "python-oracle"` and a `baseline_note` so nobody mistakes the
multiple for the BASELINE.md ">=50x vs embedded SpiceDB" target.

TPU bring-up (VERDICT r2 item 1): PJRT init in this sandbox has been
observed to hang >540s, so the old 2x150s probes could never succeed.
Now: ONE long probe (default 600s, BENCH_PROBE_TIMEOUT_S) in a subprocess
with verbose libtpu logging captured; on failure the JSON carries a
`tpu_probe` object with env vars, device-file existence, and the probe's
stderr tail so "slow init" is distinguishable from "no device".  The probe
verdict is cached on disk for 30 min so immediate re-runs don't re-pay it.

All progress/diagnostics go to stderr; stdout carries only the JSON line.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import statistics
import subprocess
import sys
import threading
import time

_T0 = time.time()
_STATE: dict = {"stage": "start", "partial": {}}
_EMITTED = threading.Event()
_PROBE_CACHE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            ".bench_probe.json")

BASELINE_NOTE = (
    "vs_baseline compares against this repo's single-threaded pure-Python "
    "oracle evaluator, NOT the reference's embedded Go SpiceDB (not runnable "
    "in this image). The BASELINE.md '>=50x vs embedded SpiceDB' target is "
    "not established by this multiple."
)


def p99(times: list) -> float:
    """Nearest-rank p99: ceil(0.99*n)-th order statistic — for n < 100
    that is the max, never silently the p90."""
    import math
    return sorted(times)[math.ceil(0.99 * len(times)) - 1]


def log(msg: str) -> None:
    print(f"[{time.time() - _T0:7.1f}s] {msg}", file=sys.stderr, flush=True)


def stage(name: str) -> None:
    _STATE["stage"] = name
    log(f"== stage: {name}")


def emit(payload: dict) -> None:
    """Print the one JSON line exactly once."""
    if _EMITTED.is_set():
        return
    _EMITTED.set()
    print(json.dumps(payload), flush=True)


def emit_error(msg: str) -> None:
    p = _STATE["partial"]
    out = {
        "metric": _STATE.get("metric", "authz checks/sec"),
        "value": p.get("value", 0.0),
        "unit": "checks/s",
        "vs_baseline": p.get("vs_baseline", 0.0),
        "p99_list_filter_ms": p.get("p99_list_filter_ms", 0.0),
        "platform": _STATE.get("platform", "unknown"),
        "baseline": "python-oracle",
        "error": f"{msg} (stage={_STATE['stage']})",
    }
    out.update({k: v for k, v in p.items() if k not in out})
    if "tpu_probe" in _STATE:
        out["tpu_probe"] = _STATE["tpu_probe"]
    emit(out)


def start_watchdog(deadline_s: float) -> None:
    def fire():
        log(f"WATCHDOG: deadline {deadline_s:.0f}s exceeded at stage "
            f"{_STATE['stage']!r}; emitting partial result")
        emit_error(f"deadline {deadline_s:.0f}s exceeded")
        sys.stdout.flush()
        os._exit(0)

    t = threading.Timer(deadline_s, fire)
    t.daemon = True
    t.start()


def collect_tpu_diagnostics(probe_stderr: str, note: str) -> dict:
    """Everything the next round needs to tell 'slow PJRT init' from
    'no TPU device in this sandbox'."""
    env = {k: v for k, v in sorted(os.environ.items())
           if k.split("_")[0] in ("TPU", "JAX", "PJRT", "LIBTPU", "XLA")
           or k.startswith("CLOUD_TPU")}
    paths = {}
    for pat in ("/dev/accel*", "/dev/vfio/*", "/dev/tpu*", "/run/tpu*",
                "/var/run/tpu*", "/tmp/libtpu_lockfile",
                "/tmp/tpu_logs", "/sys/class/accel/*"):
        paths[pat] = sorted(glob.glob(pat))
    libtpu = None
    try:
        import importlib.util
        spec = importlib.util.find_spec("libtpu")
        libtpu = getattr(spec, "origin", None) if spec else None
    except Exception as e:
        libtpu = f"find_spec failed: {e!r}"
    return {
        "note": note,
        "env": env,
        "device_paths": {k: v for k, v in paths.items()},
        "libtpu_module": libtpu,
        "probe_stderr_tail": (probe_stderr or "").strip()[-2000:],
    }


def probe_backend(timeout_s: float, attempts: int,
                  fresh: bool = False) -> str:
    """Check (in a subprocess, so a hung PJRT init can't wedge this
    process) whether the default JAX backend initializes.  Returns the
    platform string to use: "" (keep driver default) or "cpu".

    On failure, leaves a full diagnostic bundle in _STATE["tpu_probe"].
    """
    if os.environ.get("JAX_PLATFORMS", "") == "cpu":
        return "cpu"
    # 30-min disk cache: immediate re-runs (e.g. --all sweeps driven
    # externally) must not re-pay a 600s probe.  A cached FAILURE is only
    # trusted if it was probed at least as patiently as this run asks for.
    try:
        if fresh:
            raise OSError("--fresh-probe: cache bypassed")
        with open(_PROBE_CACHE) as f:
            c = json.load(f)
        if time.time() - c.get("ts", 0) < 1800 and (
                c["verdict"] == ""
                or c.get("probe_timeout", 0) >= timeout_s):
            log(f"backend probe cached ({c['verdict']!r}, "
                f"{time.time() - c['ts']:.0f}s old, probed at "
                f"{c.get('probe_timeout', 0):.0f}s timeout)")
            if c.get("diagnostics"):
                _STATE["tpu_probe"] = c["diagnostics"]
            return c["verdict"]
    except (OSError, ValueError, KeyError):
        pass

    code = ("import jax; d = jax.devices(); "
            "print(d[0].platform, len(d))")
    probe_env = dict(os.environ)
    # verbose libtpu/PJRT breadcrumbs so a hang leaves evidence in stderr
    probe_env.setdefault("TPU_STDERR_LOG_LEVEL", "0")
    probe_env.setdefault("TPU_MIN_LOG_LEVEL", "0")
    verdict, diagnostics = "cpu", None
    for i in range(attempts):
        stage(f"backend-probe attempt {i + 1}/{attempts} "
              f"(timeout {timeout_s:.0f}s)")
        t0 = time.time()
        try:
            r = subprocess.run([sys.executable, "-c", code],
                               capture_output=True, text=True,
                               timeout=timeout_s, env=probe_env)
            if r.returncode == 0 and r.stdout.strip():
                log(f"backend probe ok in {time.time() - t0:.0f}s: "
                    f"{r.stdout.strip()}")
                verdict, diagnostics = "", None
                break
            log(f"backend probe rc={r.returncode}: "
                f"{(r.stderr or '').strip()[-300:]}")
            diagnostics = collect_tpu_diagnostics(
                r.stderr, f"probe exited rc={r.returncode} "
                f"in {time.time() - t0:.0f}s")
        except subprocess.TimeoutExpired as e:
            err = e.stderr
            if isinstance(err, bytes):
                err = err.decode("utf-8", "replace")
            log(f"backend probe timed out after {timeout_s:.0f}s "
                f"(PJRT init hang)")
            diagnostics = collect_tpu_diagnostics(
                err or "", f"PJRT init did not complete within "
                f"{timeout_s:.0f}s (hang, not error)")
        time.sleep(min(10.0, 2.0 * (i + 1)))
    if verdict == "cpu":
        log("backend unavailable -> falling back to JAX_PLATFORMS=cpu")
        _STATE["tpu_probe"] = diagnostics
    try:
        with open(_PROBE_CACHE, "w") as f:
            json.dump({"ts": time.time(), "verdict": verdict,
                       "probe_timeout": timeout_s,
                       "diagnostics": diagnostics}, f)
    except OSError:
        pass
    return verdict


def devtel_snapshot():
    """Cumulative device-telemetry counters (utils/devtel.py); None when
    the package (or jax) is unavailable so the bench never dies on it."""
    try:
        from spicedb_kubeapi_proxy_tpu.utils import devtel
        return devtel.snapshot()
    except Exception:
        return None


def devtel_delta(before):
    """End-of-run device-telemetry view for one config: HBM peak/by-kind
    bytes, recompile + jit-hit counts, mean batch occupancy, per-bucket
    kernel time — the numbers later kernel PRs are judged by."""
    after = devtel_snapshot()
    if before is None or after is None:
        return None
    from spicedb_kubeapi_proxy_tpu.utils import devtel
    return devtel.diff_snapshot(before, after)


def timeline_mark():
    """Monotonic mark delimiting one config's dispatch-timeline window;
    None when the package (or jax) is unavailable."""
    try:
        from spicedb_kubeapi_proxy_tpu.utils import timeline
        return timeline.now()
    except Exception:
        return None


def timeline_summary(mark):
    """End-of-run dispatch-timeline condensate for one config (overlap
    ratio, roofline fraction, stall-cause breakdown, worst-dispatch
    exemplar — utils/timeline.py): the numbers ROADMAP item 1's
    double-buffering work is judged by, riding every BENCH artifact."""
    try:
        from spicedb_kubeapi_proxy_tpu.utils import timeline
        if not timeline.enabled():
            return None
        return timeline.summary(since=mark)
    except Exception:
        return None


def timeline_headline(tl_sum) -> dict:
    """Promote the three device-resident-pipeline judgment numbers to
    headline columns (ISSUE 7): overlap ratio (0 = serialized), modeled
    roofline fraction, and the host transfer+transpose wall time the
    pipeline exists to hide.  Riding every config entry of every sweep
    artifact, so the BENCH trajectory shows the before/after directly
    instead of burying it inside timeline_summary."""
    if not tl_sum:
        return {}
    stage_ms = tl_sum.get("stage_ms") or {}
    return {
        "overlap_ratio": tl_sum.get("overlap_ratio"),
        "roofline_fraction": tl_sum.get("roofline_fraction"),
        "transfer_transpose_ms": round(
            stage_ms.get("transfer", 0.0) + stage_ms.get("transpose", 0.0),
            3),
    }


def build_endpoint(workload, kind: str):
    from spicedb_kubeapi_proxy_tpu.ops.jax_endpoint import JaxEndpoint
    from spicedb_kubeapi_proxy_tpu.spicedb import schema as sch
    from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import EmbeddedEndpoint

    schema = sch.parse_schema(workload.schema_text)
    t0 = time.time()
    ep = (JaxEndpoint(schema) if kind == "jax" else EmbeddedEndpoint(schema))
    # columnar bulk path: native parse -> store base layer, no per-tuple
    # Python objects
    ep.store.bulk_load_text("\n".join(workload.relationships))
    log(f"loaded {len(workload.relationships)} relationship lines "
        f"in {time.time() - t0:.1f}s (columnar)")
    return ep


def warmup_tiny() -> None:
    """Compile + run the kernel on a tiny graph first: separates 'backend
    comes up / kernel compiles' from 'the 1M-tuple config is slow'."""
    import asyncio

    from spicedb_kubeapi_proxy_tpu.models import workloads as wl
    from spicedb_kubeapi_proxy_tpu.spicedb.types import SubjectRef

    stage("tiny-warmup (graph build + first XLA compile)")
    t0 = time.time()
    workload = wl.pods_depth1(n_pods=64, n_users=8, n_tuples=256)
    ep = build_endpoint(workload, "jax")
    out = asyncio.run(ep.lookup_resources_batch(
        workload.resource_type, workload.permission,
        [SubjectRef("user", s) for s in workload.subjects[:8]]))
    log(f"tiny warmup ok in {time.time() - t0:.1f}s "
        f"(allowed sizes sample {[len(x) for x in out[:4]]})")


def bench_jax(workload, batch: int, rounds: int, ep=None) -> dict:
    """Direct batched-kernel path: one lookup_resources_batch per round."""
    import asyncio

    from spicedb_kubeapi_proxy_tpu.spicedb.types import SubjectRef

    if ep is None:
        stage("jax graph build + load")
        ep = build_endpoint(workload, "jax")
    subjects = [s for s in workload.subjects]

    def batch_subjects(r):
        base = (r * batch) % max(1, len(subjects) - batch)
        return [SubjectRef("user", subjects[(base + i) % len(subjects)])
                for i in range(batch)]

    async def run():
        stage("jax warmup (real-config compile + first batch)")
        t0 = time.time()
        first = await ep.lookup_resources_batch(
            workload.resource_type, workload.permission, batch_subjects(0))
        warm = time.time() - t0
        n_obj = len(ep.store.object_ids_of_type(workload.resource_type))
        log(f"jax warmup {warm:.1f}s; {n_obj} objects of type "
            f"{workload.resource_type}; first batch allowed sizes sample "
            f"{[len(x) for x in first[:4]]}")
        stage("jax timed rounds (direct batch)")
        times = []
        for r in range(rounds):
            t0 = time.time()
            await ep.lookup_resources_batch(
                workload.resource_type, workload.permission,
                batch_subjects(r + 1))
            times.append(time.time() - t0)
            log(f"round {r + 1}/{rounds}: {times[-1] * 1000:.1f} ms")
        per_batch = statistics.median(times)
        checks = batch * n_obj
        return {
            "per_batch_s": per_batch,
            "p99_s": p99(times),
            "checks_per_s": checks / per_batch,
            "objects": n_obj,
            "warmup_s": warm,
            "endpoint": ep,
        }

    return asyncio.run(run())


def bench_concurrent(workload, batch: int, rounds: int) -> dict:
    """BASELINE config-5 shape (the HEADLINE): `batch` concurrent list
    requests, each issuing a single-subject LookupResources, fused by the
    cross-request dispatcher (spicedb/dispatch.py) into device batches —
    the exact path production `jax://` traffic takes."""
    import asyncio

    from spicedb_kubeapi_proxy_tpu.spicedb.dispatch import BatchingEndpoint
    from spicedb_kubeapi_proxy_tpu.spicedb.types import SubjectRef

    stage("jax concurrent-dispatch build + load")
    inner = build_endpoint(workload, "jax")
    ep = BatchingEndpoint(inner)
    subjects = workload.subjects

    async def one_round(r):
        async def caller(i):
            s = SubjectRef("user", subjects[(r * batch + i) % len(subjects)])
            return await ep.lookup_resources(
                workload.resource_type, workload.permission, s)
        t0 = time.time()
        await asyncio.gather(*[caller(i) for i in range(batch)])
        return time.time() - t0

    async def run():
        stage("dispatcher warmup (compile + first fused round)")
        await one_round(0)
        stage("dispatcher timed rounds (concurrent list requests)")
        times = []
        for r in range(rounds):
            times.append(await one_round(r + 1))
            log(f"round {r + 1}/{rounds}: {times[-1] * 1000:.1f} ms")
        n_obj = len(ep.store.object_ids_of_type(workload.resource_type))
        per_round = statistics.median(times)
        log(f"dispatch stats: {ep.stats}")
        return {
            "per_round_s": per_round,
            "per_batch_s": per_round,
            "p99_s": p99(times),
            "checks_per_s": batch * n_obj / per_round,
            "objects": n_obj,
            "fused_lookups": ep.stats["fused_lookups"],
            "endpoint": inner,
        }

    return asyncio.run(run())


def bench_oracle(workload, queries: int) -> dict:
    import asyncio

    from spicedb_kubeapi_proxy_tpu.spicedb.types import SubjectRef

    stage("oracle baseline build + load")
    ep = build_endpoint(workload, "embedded")

    async def run():
        n_obj = len(ep.store.object_ids_of_type(workload.resource_type))
        stage("oracle timed queries")
        times = []
        for i in range(queries):
            s = SubjectRef("user", workload.subjects[i % len(workload.subjects)])
            t0 = time.time()
            await ep.lookup_resources(workload.resource_type,
                                      workload.permission, s)
            times.append(time.time() - t0)
            log(f"oracle query {i + 1}/{queries}: {times[-1] * 1000:.0f} ms")
        per_query = statistics.median(times)
        return {
            "per_query_s": per_query,
            "checks_per_s": n_obj / per_query,
            "objects": n_obj,
        }

    return asyncio.run(run())


def roofline_probe(ep, workload, batch: int) -> dict:
    """Roofline/efficiency accounting for the ELL kernel (VERDICT r3 item
    4): measured device time + executed while_loop iterations + a bytes-
    moved MODEL per iteration -> modeled achieved HBM GB/s and fraction of
    the chip's peak.  The model counts, per iteration, each gather's
    output bytes (K reads of the packed state per table row) plus one
    state write and the gather-table reads; random-access amplification is
    NOT modeled, so the achieved number is a lower bound on true traffic.
    Also decomposes one lookup into device / transfer+unpack / id-
    materialize stages (the parts behind the reported p99)."""
    import jax.numpy as jnp
    import numpy as np

    from spicedb_kubeapi_proxy_tpu.spicedb.types import SubjectRef

    with ep._lock:
        graph = ep._current_graph()
    if not hasattr(graph, "dev_main"):
        return {"skipped": "roofline probe needs the single-chip ELL graph"}
    prog = graph.prog
    rng_slot = prog.slot_range(workload.resource_type, workload.permission)
    subjects = [SubjectRef("user", workload.subjects[i % len(workload.subjects)])
                for i in range(batch)]
    with ep._lock:
        q_arr, cols, _ = ep._encode_subjects(graph, subjects)
    n_words = max(1, len(q_arr) // 32)
    kern = graph.kernel
    _, run_lookup, intro = kern._fns(n_words)
    if intro:
        # KernelIntrospect builds return (out, sweep_telemetry); the
        # probe times the raw jitted fn, so strip telemetry here
        _rl = run_lookup
        run_lookup = lambda *a: _rl(*a)[0]  # noqa: E731
    args = [rng_slot[0], rng_slot[1], jnp.asarray(q_arr),
            graph.dev_main, graph.dev_aux]
    if kern.planes:
        args.append(graph.dev_cav)
    import jax

    out = run_lookup(*args)
    _ = int(np.asarray(out[0, 0]))  # warm/compile (forced)
    # dispatch/sync round-trip floor: a trivial jitted op timed the same
    # way — under the axon TPU tunnel this is ~70ms and dominates small
    # kernels; subtracting it separates "kernel compute" from "transport".
    # A SCALAR FETCH forces execution: block_until_ready can be a no-op
    # under the tunnel (lazy dispatch), which round 4's probe fell for.
    tiny = jax.jit(lambda v: v + 1)
    z = jnp.zeros(8, jnp.uint32)
    _ = int(np.asarray(tiny(z)[0]))
    r0 = time.perf_counter()
    _ = int(np.asarray(tiny(z)[0]))
    rtt = time.perf_counter() - r0

    # Detect-and-retime (VERDICT r4 item 7): repeat the forced-execution
    # timing until two consecutive measurements agree within tolerance;
    # record the residual disagreement as timing_confidence instead of
    # publishing a labeled guess.
    tol = 0.15
    samples = []
    for _i in range(6):
        t0 = time.perf_counter()
        o = run_lookup(*args)
        _ = int(np.asarray(o[0, 0]))  # scalar fetch: forces execution
        samples.append(time.perf_counter() - t0)
        if (len(samples) >= 2
                and abs(samples[-1] - samples[-2]) / max(samples[-1],
                                                         samples[-2]) < tol):
            break
    device_s = (samples[-1] + samples[-2]) / 2 if len(samples) >= 2 \
        else samples[-1]
    timing_confidence = (1.0 - abs(samples[-1] - samples[-2])
                         / max(samples[-1], samples[-2])
                         if len(samples) >= 2 else 0.0)
    out = run_lookup(*args)
    t1 = time.perf_counter()
    # production extraction path: packed transpose + per-column word ops
    # (ops/jax_endpoint._lookup_batch_sync)
    from spicedb_kubeapi_proxy_tpu.ops.jax_endpoint import (
        _object_ids_np, _word_col_indices)
    packed = np.ascontiguousarray(out)
    packed_T = np.ascontiguousarray(packed.T)
    t2 = time.perf_counter()
    ids_np, _mask = _object_ids_np(graph, workload.resource_type)
    _ = [ids_np[_word_col_indices(packed_T[c // 32], c % 32)].tolist()
         for c in range(min(len(cols), 8))]  # sample of id materialization
    t3 = time.perf_counter()

    iters = kern.iterations(q_arr, n_words, graph.dev_main, graph.dev_aux,
                            graph.dev_cav if kern.planes else None)
    n = prog.state_size
    a = graph.dev_aux.shape[0]
    nt = n + a
    # fanin widths from the ACTUAL tables (K layout is env-tunable)
    k_main = int(graph.dev_main.shape[1])
    k_aux = int(graph.dev_aux.shape[1])
    k_cav = int(graph.dev_cav.shape[1]) if kern.planes else 0
    w_total = 2 * n_words if kern.planes else n_words
    state_bytes = nt * w_total * 4
    # the bottom-up aux refresh sweeps the aux table aux_passes times per
    # outer iteration (Gauss-Seidel tree collapse)
    ap = getattr(kern, "aux_passes", 1)
    gather_bytes = 4 * w_total * (n * (k_main + 1) + ap * a * (k_aux + 1))
    if kern.planes:
        gather_bytes += 4 * w_total * nt * (k_cav + 1)
    table_bytes = 4 * (n * k_main + ap * a * k_aux
                       + (nt * k_cav if kern.planes else 0))
    per_iter = gather_bytes + 2 * state_bytes + table_bytes
    total_bytes = per_iter * max(iters, 1)
    peak = {"tpu": 819.0}.get(_STATE.get("platform", ""), None)
    # device_s came from converged scalar-fetch forced timing above (no
    # lazy-execution guessing path any more — VERDICT r4 item 7).  When
    # the kernel is too small to separate from the dispatch round trip
    # (device_s - rtt within jitter), the net-of-rtt rates are
    # meaningless: null them instead of publishing absurd GB/s.
    compute_s = device_s - rtt
    rtt_dominated = compute_s < max(0.1 * device_s, 1e-4)
    compute_s = max(compute_s, 1e-6)
    achieved = total_bytes / max(device_s, 1e-6) / 1e9
    achieved_net = (None if rtt_dominated
                    else total_bytes / compute_s / 1e9)

    # Measured attainable floor for THIS access pattern: XLA's TPU
    # row-gather lowering costs a per-row constant independent of index
    # locality (scripts/probe_step_breakdown.py), so chip-peak HBM GB/s
    # is not reachable by any index layout.  Time one amortized gather
    # of the state shape and scale to the kernel's per-sweep gather
    # rows; kernel_vs_gather_floor ≈ 1 means the kernel is at the
    # lowering floor and further wins must cut sweeps or rows.
    idx_probe = jnp.arange(nt, dtype=jnp.int32)

    @jax.jit
    def _gather_loop(x):
        return jax.lax.fori_loop(
            0, 20, lambda i, v: v[idx_probe] + jnp.uint32(1), x)

    xs = jnp.zeros((nt, w_total), jnp.uint32)
    _ = int(np.asarray(_gather_loop(xs)[0, 0]))
    g0 = time.perf_counter()
    _ = int(np.asarray(_gather_loop(xs)[0, 0]))
    gather_pass_s = max((time.perf_counter() - g0 - rtt) / 20, 1e-9)
    ns_per_row = gather_pass_s / nt * 1e9
    # per-sweep gather rows: K_MAIN over state + aux refreshes
    sweep_rows = n * k_main + ap * a * k_aux
    floor_s = sweep_rows * (ns_per_row / 1e9) * max(iters, 1)
    return {
        "state_rows": nt,
        "state_bytes": state_bytes,
        "packed_words_per_plane": n_words,
        "bitplanes": 2 if kern.planes else 1,
        "iterations_executed": iters,
        "iteration_cap": kern.num_iters,
        "modeled_bytes_per_iteration": per_iter,
        "device_time_ms": round(device_s * 1e3, 3),
        "dispatch_rtt_ms": round(rtt * 1e3, 3),
        "kernel_compute_ms": round(compute_s * 1e3, 3),
        "rtt_dominated": rtt_dominated,
        "timing_basis": "scalar-fetch forced execution, converged",
        "timing_confidence": round(timing_confidence, 3),
        "timing_samples_ms": [round(s * 1e3, 1) for s in samples],
        "kernel_transfer_pipeline_ms": round((t2 - t1) * 1e3, 3),
        # the pipeline window contains a full kernel execution; the
        # transfer estimate subtracts the separately-forced kernel time
        "transfer_est_ms": round(max((t2 - t1) - device_s, 0.0) * 1e3, 3),
        "id_materialize_sample_ms": round((t3 - t2) * 1e3, 3),
        "modeled_achieved_hbm_gbps": round(achieved, 2),
        "modeled_achieved_hbm_gbps_net_of_rtt": (
            round(achieved_net, 2) if achieved_net is not None else None),
        "hbm_peak_gbps_v5e": 819.0,
        "modeled_peak_fraction": (round(achieved / peak, 4)
                                  if peak else None),
        "modeled_peak_fraction_net_of_rtt": (
            round(achieved_net / peak, 4)
            if peak and achieved_net is not None else None),
        "gather_ns_per_row_measured": round(ns_per_row, 2),
        "gather_floor_ms": round(floor_s * 1e3, 3),
        "kernel_vs_gather_floor": round(compute_s / max(floor_s, 1e-9), 2),
        "model_note": ("bytes model counts gather outputs + state "
                       "read/write + table reads (lower bound). "
                       "modeled_peak_fraction vs chip HBM peak is NOT the "
                       "efficiency story: XLA's row-gather lowering costs "
                       "gather_ns_per_row regardless of locality (measured "
                       "in-situ), so gather_floor/kernel_vs_gather_floor is "
                       "the attainable-efficiency measure; dispatch_rtt (a "
                       "trivial-op round trip, ~70ms under the axon tunnel) "
                       "is subtracted for net-of-rtt numbers"),
    }


def sharded_comm_model(ep, workload, batch: int,
                       n_data: int = 2, n_graph: int = 4) -> dict:
    """Analytic per-iteration ICI traffic for the v5e-8 sharded layout
    (VERDICT r3 item 10), computed from the REAL headline graph's table
    shapes via the canonical model in parallel/sharding.py."""
    from spicedb_kubeapi_proxy_tpu.parallel.sharding import comm_model

    with ep._lock:
        graph = ep._current_graph()
    if not hasattr(graph, "dev_main"):
        return {"skipped": "needs the ELL graph"}
    out = comm_model(graph.prog.state_size, graph.dev_aux.shape[0],
                     n_data, n_graph, batch,
                     planes=bool(getattr(graph, "has_cav", False)),
                     aux_passes=getattr(graph.kernel, "aux_passes", 1))
    out["note"] = ("per-iteration tiled all_gather over ICI reassembles "
                   "row blocks; measured wall time for this layout is "
                   "recorded by dryrun_multichip (MULTICHIP artifact)")
    return out


def v5e8_projection(ep, workload, batch: int, roofline: dict) -> dict:
    """Predicted v5e-8 throughput from the measured single-chip roofline
    (VERDICT r4 item 4) — formula + inputs recorded in the artifact."""
    from spicedb_kubeapi_proxy_tpu.parallel.sharding import (
        predict_v5e8_checks_per_s)

    with ep._lock:
        graph = ep._current_graph()
    if not hasattr(graph, "dev_main") or "kernel_compute_ms" not in roofline:
        return {"skipped": "needs the ELL graph + a measured roofline"}
    iters = max(roofline.get("iterations_executed", 1), 1)
    iter_s = roofline["kernel_compute_ms"] / 1e3 / iters
    # fixed overhead: extraction + dispatch (not the tunnel transfer —
    # a deployed v5e-8 host is directly attached; model D2H at 8 GB/s
    # PCIe for the packed result instead)
    n_words = roofline.get("packed_words_per_plane", 8)
    d2h_s = workload.expected_objects * n_words * 4 / 8e9
    fixed = d2h_s + roofline.get("id_materialize_sample_ms", 0) / 1e3
    return predict_v5e8_checks_per_s(
        graph.prog.state_size, graph.dev_aux.shape[0], 2, 4, batch,
        objects=workload.expected_objects,
        single_chip_iter_s=iter_s, iters=iters,
        planes=bool(getattr(graph, "has_cav", False)),
        aux_passes=getattr(graph.kernel, "aux_passes", 1),
        fixed_overhead_s=fixed)


def _cache_chain(workload, cache_on: bool):
    """Production proxy-chain wiring for the cache benches:
    jax:// -> BatchingEndpoint -> (DecisionCacheEndpoint when on)."""
    from spicedb_kubeapi_proxy_tpu.ops.jax_endpoint import JaxEndpoint
    from spicedb_kubeapi_proxy_tpu.spicedb import schema as sch
    from spicedb_kubeapi_proxy_tpu.spicedb.decision_cache import (
        DecisionCacheEndpoint)
    from spicedb_kubeapi_proxy_tpu.spicedb.dispatch import BatchingEndpoint

    schema = sch.parse_schema(workload.schema_text)
    inner = JaxEndpoint(schema)
    inner.store.bulk_load_text("\n".join(workload.relationships))
    ep = BatchingEndpoint(inner)
    if cache_on:
        ep = DecisionCacheEndpoint(ep)
    return ep, inner


def _cache_workload():
    from spicedb_kubeapi_proxy_tpu.models import workloads as wl
    return wl.pods_depth1(n_pods=10_000, n_users=100, n_tuples=30_000)


def bench_warm_repeat_list(args) -> dict:
    """Decision-cache headline: the SAME user lists 10k pods N times
    (no interleaved writes), cache on vs off — the repeat-list is the
    production hot path the cache exists for.  Reports proxy-chain
    filter throughput both ways plus the on/off speedup (acceptance:
    >=5x) and the cache hit rate."""
    import asyncio

    from spicedb_kubeapi_proxy_tpu.spicedb.types import SubjectRef

    workload = _cache_workload()
    lists = 32
    subject = SubjectRef("user", workload.subjects[0])
    out = {}
    for cache_on in (False, True):
        label = "on" if cache_on else "off"
        stage(f"warm-repeat-list cache={label}")
        ep, inner = _cache_chain(workload, cache_on)

        async def run():
            # warmup: compile + first frontier (and the one cache fill)
            first = await ep.lookup_resources(
                workload.resource_type, workload.permission, subject)
            t0 = time.time()
            for _ in range(lists):
                got = await ep.lookup_resources(
                    workload.resource_type, workload.permission, subject)
            elapsed = time.time() - t0
            assert sorted(got) == sorted(first)
            return len(first), elapsed

        n_allowed, elapsed = asyncio.run(run())
        n_obj = workload.expected_objects
        out[f"cache_{label}_lists_per_s"] = round(lists / elapsed, 2)
        out[f"cache_{label}_checks_per_s"] = round(
            lists * n_obj / elapsed, 1)
        if cache_on:
            st = ep.cache.stats
            probes = st["hits"] + st["misses"]
            out["hit_rate"] = round(st["hits"] / max(probes, 1), 4)
        log(f"warm-repeat-list cache={label}: "
            f"{lists / elapsed:.1f} lists/s ({n_allowed} allowed ids)")
    out["speedup"] = round(out["cache_on_lists_per_s"]
                           / max(out["cache_off_lists_per_s"], 1e-9), 2)
    out["objects"] = workload.expected_objects
    log(f"warm-repeat-list speedup (on/off): {out['speedup']}x "
        f"(acceptance >=5x), hit rate {out.get('hit_rate')}")
    return out


def bench_delta_churn(args) -> dict:
    """Decision-cache correctness under interleaved writes: every round
    commits a write (touch/delete of viewer tuples), then the cache-on
    chain's lookups are refereed against the host oracle over the SAME
    store.  Divergences must be zero; the hit rate shows the
    relation-scoped invalidation keeping unrelated entries warm."""
    import asyncio

    from spicedb_kubeapi_proxy_tpu.spicedb.evaluator import Evaluator
    from spicedb_kubeapi_proxy_tpu.spicedb.types import (
        RelationshipUpdate, SubjectRef, UpdateOp, parse_relationship)

    workload = _cache_workload()
    stage("delta-churn build")
    ep, inner = _cache_chain(workload, cache_on=True)
    oracle = Evaluator(inner.schema, inner.store)
    rounds = 12
    subjects = [SubjectRef("user", workload.subjects[i % len(workload.subjects)])
                for i in range(3)]
    divergences = 0

    async def run():
        nonlocal divergences
        stage("delta-churn warmup")
        for s in subjects:
            await ep.lookup_resources(workload.resource_type,
                                      workload.permission, s)
        stage("delta-churn rounds (interleaved writes)")
        chain_s = 0.0
        n_lists = 0
        for r in range(rounds):
            op = UpdateOp.TOUCH if r % 2 == 0 else UpdateOp.DELETE
            rel = parse_relationship(
                f"pod:p{r % 7}#viewer@user:{workload.subjects[0]}")
            await ep.write_relationships([RelationshipUpdate(op=op, rel=rel)])
            for s in subjects:
                # the revision is frozen between writes: one oracle
                # frontier referees BOTH passes (pass 2 serves
                # unchanged-footprint entries from cache)
                want = sorted(oracle.lookup_resources(
                    workload.resource_type, workload.permission, s))
                for _pass in range(2):
                    t0 = time.time()
                    got = sorted(await ep.lookup_resources(
                        workload.resource_type, workload.permission, s))
                    chain_s += time.time() - t0
                    n_lists += 1
                    if got != want:
                        divergences += 1
        return n_lists, chain_s

    n_lists, elapsed = asyncio.run(run())
    st = ep.cache.stats
    probes = st["hits"] + st["misses"]
    out = {
        "divergences": divergences,
        "rounds": rounds,
        "lists_per_s": round(n_lists / elapsed, 2),
        "hit_rate": round(st["hits"] / max(probes, 1), 4),
        "invalidations": st["invalidations"],
    }
    log(f"delta-churn: {divergences} divergences over {n_lists} refereed "
        f"lists, hit rate {out['hit_rate']}, "
        f"{st['invalidations']} invalidations")
    return out


def bench_recovery(args) -> dict:
    """Durable-store restart cost (ISSUE 4): time-to-serve after a
    restart at 1M tuples — checkpoint load + WAL tail replay + warm
    graph rebuild, measured separately and summed — plus the WAL-on vs
    WAL-off write-path overhead (the price every live write pays for
    durability)."""
    import asyncio
    import shutil
    import tempfile

    from spicedb_kubeapi_proxy_tpu.models import workloads as wl
    from spicedb_kubeapi_proxy_tpu.ops.jax_endpoint import JaxEndpoint
    from spicedb_kubeapi_proxy_tpu.spicedb import schema as sch
    from spicedb_kubeapi_proxy_tpu.spicedb.persist import PersistenceManager
    from spicedb_kubeapi_proxy_tpu.spicedb.store import TupleStore
    from spicedb_kubeapi_proxy_tpu.spicedb.types import (
        CheckRequest,
        RelationshipUpdate,
        SubjectRef,
        UpdateOp,
        parse_relationship,
    )

    workload = wl.multitenant_1m()
    rel_text = "\n".join(workload.relationships)
    write_rounds, batch = 50, 50
    tail_rounds = 25

    def churn_batch(i):
        # touch/delete EXISTING workload tuples so every write is
        # schema-valid and the device graph replays them cleanly
        ups = []
        for j in range(batch):
            line = workload.relationships[(i * batch + j)
                                          % len(workload.relationships)]
            op = UpdateOp.DELETE if (i + j) % 2 else UpdateOp.TOUCH
            ups.append(RelationshipUpdate(op, parse_relationship(line)))
        return ups

    def time_writes(store, rounds, start=0):
        t0 = time.time()
        for i in range(start, start + rounds):
            store.write(churn_batch(i))
        return time.time() - t0

    tmp = tempfile.mkdtemp(prefix="persist-bench-")
    out = {"tuples": len(workload.relationships)}
    try:
        stage("recovery: seed + journal (WAL on)")
        mgr = PersistenceManager(tmp, fsync="interval")
        store = mgr.recover()
        mgr.attach(store)
        store.bulk_load_text(rel_text)
        wal_on_s = time_writes(store, write_rounds)
        stage("recovery: checkpoint + WAL tail")
        mgr.checkpoint()
        time_writes(store, tail_rounds, start=write_rounds)
        seed_revision = store.revision
        mgr.close()

        stage("recovery: WAL-off write baseline")
        bare = TupleStore()
        bare.bulk_load_text(rel_text)
        wal_off_s = time_writes(bare, write_rounds)
        del bare

        stage("recovery: restart (checkpoint + tail replay)")
        mgr2 = PersistenceManager(tmp, fsync="interval")
        recovered = mgr2.recover()
        assert recovered.revision == seed_revision
        info = mgr2.recovery_info

        stage("recovery: warm graph rebuild")
        schema = sch.parse_schema(workload.schema_text)
        ep = JaxEndpoint(schema, store=recovered)
        probe = next(parse_relationship(line)
                     for line in workload.relationships
                     if line.startswith(workload.resource_type + ":"))
        t0 = time.time()
        ep.warm_start()
        # first kernel answer = "serving": includes jit compile
        asyncio.run(ep.check_permission(CheckRequest(
            probe.resource, workload.permission,
            SubjectRef("user", workload.subjects[0]))))
        rebuild_s = time.time() - t0

        out.update({
            "checkpoint_load_s": info["checkpoint_load_s"],
            "wal_replay_s": info["wal_replay_s"],
            "wal_tail_records": info["replayed_records"],
            "graph_rebuild_s": round(rebuild_s, 3),
            "time_to_serve_s": round(
                info["total_s"] + rebuild_s, 3),
            "wal_on_batch_ms": round(wal_on_s / write_rounds * 1e3, 3),
            "wal_off_batch_ms": round(wal_off_s / write_rounds * 1e3, 3),
            "wal_overhead_pct": round(
                (wal_on_s - wal_off_s) / max(wal_off_s, 1e-9) * 100, 1),
        })
        log(f"recovery: time-to-serve {out['time_to_serve_s']}s at "
            f"{out['tuples']} tuples (ckpt {out['checkpoint_load_s']}s + "
            f"replay {out['wal_replay_s']}s [{out['wal_tail_records']} "
            f"records] + rebuild {out['graph_rebuild_s']}s); WAL write "
            f"overhead {out['wal_overhead_pct']}% "
            f"({out['wal_on_batch_ms']} vs {out['wal_off_batch_ms']} "
            f"ms/batch)")
        return out
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def bench_pipeline_depth(args) -> dict:
    """Device-resident pipeline A/B (ISSUE 7): the headline 1M-tuple
    10k-pod concurrent-list shape, run with the DevicePipeline gate OFF
    (the exact pre-PR host-pack serial path) and then gate ON at
    dispatch depths 1, 2, and 4.  Each mode records checks/s, the
    overlap ratio, and the stall{pack|transpose|transfer} attribution,
    so the BENCH artifact carries the before/after for ROADMAP item 1
    directly: `stall_reduction_x` = (host-pack pack+transpose+transfer
    stall) / (depth-2 same), `checks_per_s_gain` = depth-2 / host-pack
    throughput."""
    import asyncio

    from spicedb_kubeapi_proxy_tpu.models import workloads as wl
    from spicedb_kubeapi_proxy_tpu.spicedb.dispatch import BatchingEndpoint
    from spicedb_kubeapi_proxy_tpu.spicedb.types import SubjectRef
    from spicedb_kubeapi_proxy_tpu.utils.features import GATES

    stage("pipeline-depth sweep build + load (multitenant-1m)")
    workload = wl.multitenant_1m()
    inner = build_endpoint(workload, "jax")
    batch = args.batch
    rounds = max(3, args.rounds // 2)
    subjects = workload.subjects
    # max_batch splits each round into ~4 fused batches: with one
    # monolithic batch per round the drain has nothing to keep in
    # flight and every depth degenerates to serial
    max_batch = max(1, batch // 4)
    modes = (
        ("host-pack", False, 1),   # gate off: pre-PR serial baseline
        ("depth-1", True, 1),      # device pack, serial dispatch
        ("depth-2", True, 2),      # the --pipeline-depth default
        ("depth-4", True, 4),
    )
    out: dict = {"modes": {}, "batch": batch, "rounds": rounds,
                 "max_batch": max_batch}
    eps = {name: BatchingEndpoint(inner, max_batch=max_batch,
                                  pipeline_depth=depth)
           for name, _gate, depth in modes}
    acc = {name: {"times": [], "stall": {}, "transfer_s": 0.0,
                  "overlap_s": 0.0, "tt_ms": 0.0}
           for name, _gate, _depth in modes}

    async def one_round(ep, r):
        async def caller(i):
            s = SubjectRef(
                "user", subjects[(r * batch + i) % len(subjects)])
            return await ep.lookup_resources(
                workload.resource_type, workload.permission, s)
        t0 = time.time()
        await asyncio.gather(*[caller(i) for i in range(batch)])
        return time.time() - t0

    try:
        # interleaved A/B (the same methodology the gate-off parity
        # claim uses): mode order rotates inside every round, so
        # allocator drift / process aging lands on all modes equally
        # instead of flattering whichever ran first
        stage("pipeline-depth interleaved rounds")
        for name, gate, _depth in modes:
            GATES.set("DevicePipeline", gate)
            asyncio.run(one_round(eps[name], 0))  # warm: compiles+arenas
        for r in range(rounds):
            for name, gate, _depth in modes:
                GATES.set("DevicePipeline", gate)
                mark = timeline_mark()
                dt = asyncio.run(one_round(eps[name], r + 1))
                tl = timeline_summary(mark) or {}
                a = acc[name]
                a["times"].append(dt)
                for cause, v in (tl.get("stall_s") or {}).items():
                    a["stall"][cause] = a["stall"].get(cause, 0.0) + v
                ov = tl.get("overlap") or {}
                a["transfer_s"] += ov.get("transfer_s", 0.0)
                a["overlap_s"] += ov.get("overlap_s", 0.0)
                a["tt_ms"] += timeline_headline(tl).get(
                    "transfer_transpose_ms", 0.0)
    finally:
        GATES.set("DevicePipeline", True)

    n_obj = len(inner.store.object_ids_of_type(workload.resource_type))
    for name, _gate, _depth in modes:
        a = acc[name]
        per_round = statistics.median(a["times"])
        host_stall = (a["stall"].get("pack", 0.0)
                      + a["stall"].get("transpose", 0.0)
                      + a["stall"].get("transfer", 0.0))
        mode = {
            "checks_per_s": round(batch * n_obj / per_round, 1),
            "per_round_ms": round(per_round * 1e3, 2),
            "p99_ms": round(p99(a["times"]) * 1e3, 2),
            "stall_s": {c: round(v, 6) for c, v in sorted(
                a["stall"].items())},
            "stall_pack_transpose_transfer_s": round(host_stall, 6),
            "overlap_ratio": (round(a["overlap_s"] / a["transfer_s"], 4)
                              if a["transfer_s"] > 0 else None),
            "transfer_transpose_ms": round(a["tt_ms"], 3),
        }
        out["modes"][name] = mode
        log(f"pipeline {name}: {mode['checks_per_s']:.3g} checks/s, "
            f"overlap={mode.get('overlap_ratio')}, "
            f"host stalls={mode['stall_pack_transpose_transfer_s']}s")
    base = out["modes"].get("host-pack", {})
    d2 = out["modes"].get("depth-2", {})
    if base and d2:
        denom = max(d2.get("stall_pack_transpose_transfer_s") or 0.0, 1e-9)
        out["stall_reduction_x"] = round(
            (base.get("stall_pack_transpose_transfer_s") or 0.0) / denom, 2)
        out["checks_per_s_gain"] = round(
            d2["checks_per_s"] / max(base["checks_per_s"], 1e-9), 3)
        log(f"pipeline-depth: stall reduction "
            f"{out['stall_reduction_x']}x, checks/s gain "
            f"{out['checks_per_s_gain']}x (depth-2 vs host-pack)")
    return out


REPLICA_WORKER_SPEC = {
    "n_pods": 10_000, "n_users": 100, "n_tuples": 30_000,
    "lookup_batch": 32, "measure_s": 4.0,
}


def replica_worker(spec_json: str) -> None:
    """`bench.py --replica-worker <spec-json>` subprocess: one follower
    tailing the leader's replication API over real HTTP and serving
    batched filtered-list reads from its own device graph.  Protocol
    on stdio: print READY after warm; each `RUN` line on stdin runs one
    measured window and prints `DONE <json>`; `EXIT` quits.  A separate
    process per follower is the point — N proxy replicas behind a load
    balancer are separate processes, and the GIL would serialize
    in-process reader threads into an anti-measurement."""
    import asyncio

    spec = json.loads(spec_json)
    from spicedb_kubeapi_proxy_tpu.models import workloads as wl
    from spicedb_kubeapi_proxy_tpu.ops.jax_endpoint import JaxEndpoint
    from spicedb_kubeapi_proxy_tpu.proxy.httpcore import H11Transport
    from spicedb_kubeapi_proxy_tpu.spicedb import schema as sch
    from spicedb_kubeapi_proxy_tpu.spicedb.replication import ReplicaFollower
    from spicedb_kubeapi_proxy_tpu.spicedb.store import TupleStore
    from spicedb_kubeapi_proxy_tpu.spicedb.types import SubjectRef

    workload = wl.pods_depth1(n_pods=spec["n_pods"],
                              n_users=spec["n_users"],
                              n_tuples=spec["n_tuples"])
    schema = sch.parse_schema(workload.schema_text)
    store = TupleStore()
    repl = ReplicaFollower(store, H11Transport(spec["leader"]),
                           identity=spec["identity"])
    ep = JaxEndpoint(schema, store=store)
    lookup_batch = spec["lookup_batch"]

    def subjects(base):
        return [SubjectRef("user", workload.subjects[
            (base + k) % len(workload.subjects)])
            for k in range(lookup_batch)]

    async def measured_window(seconds: float) -> dict:
        await repl.sync_once()  # catch up the backlog untimed
        lists = 0
        lags: list = []
        base = 0
        stop = asyncio.Event()

        async def tail():
            # the tail runs CONCURRENTLY with reads, exactly like the
            # server's follower task — reads never block on leader RTT.
            # Lag is sampled just BEFORE each sync: the staleness a
            # read arriving at that moment would actually observe.
            while not stop.is_set():
                lags.append(repl.lag_revisions())
                try:
                    await repl.sync_once()
                except Exception:
                    pass  # transient leader hiccup; lag keeps counting
                await asyncio.sleep(0.05)

        tail_task = asyncio.ensure_future(tail())
        t0 = time.time()
        while time.time() - t0 < seconds:
            await ep.lookup_resources_batch(
                workload.resource_type, workload.permission,
                subjects(base))
            base += lookup_batch
            lists += lookup_batch
        elapsed = time.time() - t0
        stop.set()
        await tail_task
        lags.sort()

        def pct(p):
            return (float(lags[min(len(lags) - 1, int(p * len(lags)))])
                    if lags else 0.0)

        return {"lists": lists, "elapsed_s": round(elapsed, 3),
                "lists_per_s": round(lists / elapsed, 1),
                "lag_p50": pct(0.5), "lag_p99": pct(0.99),
                "lag_samples": len(lags),
                "applied_records": repl.stats["applied_records"]}

    async def main_loop():
        await repl.sync_once()
        await ep.lookup_resources_batch(
            workload.resource_type, workload.permission, subjects(0))
        print("READY", flush=True)
        loop = asyncio.get_running_loop()
        while True:
            line = await loop.run_in_executor(None, sys.stdin.readline)
            if not line or line.strip() == "EXIT":
                return
            if line.strip() == "RUN":
                res = await measured_window(spec["measure_s"])
                print("DONE " + json.dumps(res), flush=True)

    asyncio.run(main_loop())


def bench_replica_scale(args) -> dict:
    """WAL-shipping read-replica scaling (ISSUE 9): one leader taking
    write churn, its WAL served over real localhost HTTP by the
    replication hub, and N follower PROCESSES (replica_worker above —
    one process per replica, as deployed) each bootstrapping, tailing,
    and serving batched filtered-list reads from its own device graph.
    Reports aggregate filtered-list throughput at 1/2/4 followers plus
    per-follower lag percentiles; headline column
    `replica_read_scaling` = 2-follower aggregate over 1-follower
    (acceptance >= 1.7x on CPU — note the hardware ceiling: aggregate
    scaling cannot exceed the machine's core count)."""
    import asyncio
    import shutil
    import tempfile

    from spicedb_kubeapi_proxy_tpu.models import workloads as wl
    from spicedb_kubeapi_proxy_tpu.proxy.httpcore import (
        HttpServer,
        json_response,
    )
    from spicedb_kubeapi_proxy_tpu.utils.topology import (
        WorkerFleet,
        cpu_pair_ceiling,
    )
    from spicedb_kubeapi_proxy_tpu.spicedb.persist import PersistenceManager
    from spicedb_kubeapi_proxy_tpu.spicedb.replication import ReplicationHub
    from spicedb_kubeapi_proxy_tpu.spicedb.types import (
        RelationshipUpdate,
        UpdateOp,
        parse_relationship,
    )

    spec = dict(REPLICA_WORKER_SPEC)
    fleet_sizes = (1, 2, 4)
    workload = wl.pods_depth1(n_pods=spec["n_pods"],
                              n_users=spec["n_users"],
                              n_tuples=spec["n_tuples"])

    tmp = tempfile.mkdtemp(prefix="replica-bench-")
    stage("replica-scale: leader build + journal")
    mgr = PersistenceManager(tmp, fsync="never")
    leader_store = mgr.recover()
    mgr.attach(leader_store)
    leader_store.bulk_load_text("\n".join(workload.relationships))
    hub = ReplicationHub(leader_store, mgr)
    hub.attach()

    async def hub_handler(req):
        path = req.path
        if path == "/replication/manifest":
            return await hub.serve_manifest(req)
        if path.startswith("/replication/segment/"):
            return await hub.serve_segment(req, path.rsplit("/", 1)[1])
        if path.startswith("/replication/checkpoint/"):
            return await hub.serve_checkpoint(req, path.rsplit("/", 1)[1])
        return json_response(404, {"message": f"unknown {path}"})

    # leader HTTP serving + churn run on a dedicated thread's loop so
    # the measured follower processes see a live leader throughout
    ready = threading.Event()
    stop = threading.Event()
    port_box: dict = {}

    def leader_thread():
        async def run():
            server = HttpServer(hub_handler)
            port_box["port"] = await server.start("127.0.0.1", 0)
            ready.set()
            # ~50 writes/s of churn: enough to keep every follower's
            # tail busy without the (unpinned) leader thread eating the
            # fixed per-replica core budgets it is refereeing
            i = 0
            while not stop.is_set():
                line = workload.relationships[
                    i % len(workload.relationships)]
                op = UpdateOp.DELETE if i % 2 else UpdateOp.TOUCH
                leader_store.write([RelationshipUpdate(
                    op, parse_relationship(line))])
                i += 1
                await asyncio.sleep(0.02)
            await server.stop()

        asyncio.run(run())

    lt = threading.Thread(target=leader_thread, daemon=True)
    lt.start()
    ready.wait(10)
    leader_url = f"http://127.0.0.1:{port_box['port']}"

    out: dict = {"fleet": {}, "measure_s": spec["measure_s"],
                 "lookup_batch": spec["lookup_batch"],
                 "tuples": len(workload.relationships),
                 "cores": os.cpu_count()}
    # fixed per-replica CPU budget (1 core, single-threaded XLA) via
    # the shared harness: production replicas are separate nodes, so
    # the scaling claim is "aggregate throughput grows as replicas are
    # added at a constant per-replica budget" — without the pin, one
    # XLA intra-op pool eats every local core and the baseline is
    # already machine-saturated, measuring contention, not scaling
    fleet = WorkerFleet(name="replica-scale")
    try:
        stage(f"replica-scale: spawn + warm {max(fleet_sizes)} follower "
              f"processes")
        for i in range(max(fleet_sizes)):
            wspec = dict(spec, leader=leader_url, identity=f"replica-{i}")
            fleet.spawn(
                [sys.executable, os.path.abspath(__file__),
                 "--replica-worker", json.dumps(wspec)],
                pin=i, label=f"replica-{i}")
        fleet.wait_ready()

        def window(n):
            return fleet.run_window(n)

        # interleaved rounds, median per fleet size (same methodology
        # as the pipeline-depth A/B): this box's background load drifts
        # minute to minute, and sequential one-shot windows would hand
        # whichever fleet size ran during a quiet patch a fake win
        rounds = 3
        acc: dict = {n: [] for n in fleet_sizes}
        for r in range(rounds):
            for n in fleet_sizes:
                stage(f"replica-scale round {r + 1}/{rounds}: {n} "
                      f"follower process(es) under churn")
                acc[n].append(window(n))
        for n in fleet_sizes:
            aggs = [sum(res["lists_per_s"] for res in results)
                    for results in acc[n]]
            agg = statistics.median(aggs)
            flat = [res for results in acc[n] for res in results]
            lag_p50 = statistics.median(res["lag_p50"] for res in flat)
            lag_p99 = max(res["lag_p99"] for res in flat)
            out["fleet"][str(n)] = {
                "aggregate_lists_per_s": round(agg, 1),
                "aggregate_lists_per_s_rounds": [round(a, 1)
                                                 for a in aggs],
                "aggregate_checks_per_s": round(
                    agg * workload.expected_objects, 1),
                "per_follower_lists_per_s": round(agg / n, 1),
                "lag_revisions_p50": lag_p50,
                "lag_revisions_p99": lag_p99,
                "lag_samples": sum(res["lag_samples"] for res in flat),
            }
            log(f"replica-scale n={n}: {agg:.1f} lists/s aggregate "
                f"(median of {aggs}), lag p50/p99 = "
                f"{lag_p50}/{lag_p99} revisions")
    finally:
        fleet.shutdown()
        stop.set()
        lt.join(10)
        mgr.close()
        shutil.rmtree(tmp, ignore_errors=True)

    stage("replica-scale: CPU pair-scaling ceiling probe")
    out["cpu_pair_scaling_ceiling"] = cpu_pair_ceiling()

    # scaling is estimated from PAIRED per-round ratios (windows inside
    # one round are adjacent in time), because ambient load on a shared
    # box drifts across rounds by more than the effect being measured;
    # the n=1 round spread is recorded so a reader can judge the noise
    base_rounds = [sum(res["lists_per_s"] for res in results)
                   for results in acc[1]]
    out["noise_spread_1x"] = round(
        max(base_rounds) / max(min(base_rounds), 1e-9), 2)
    for n in fleet_sizes[1:]:
        ratios = [
            sum(res["lists_per_s"] for res in results) / max(b, 1e-9)
            for results, b in zip(acc[n], base_rounds)]
        out[f"scaling_{n}x"] = round(statistics.median(ratios), 2)
        out[f"scaling_{n}x_rounds"] = [round(r, 2) for r in ratios]
    out["replica_read_scaling"] = out.get("scaling_2x", 0.0)
    ceiling = out["cpu_pair_scaling_ceiling"]
    out["replica_read_scaling_normalized"] = round(
        out["replica_read_scaling"] / max(ceiling, 1e-9), 2)
    log(f"replica-scale: read scaling at 2 followers = "
        f"{out['replica_read_scaling']}x raw (acceptance >= 1.7x on >=2 "
        f"free cores), {out['replica_read_scaling_normalized']}x of this "
        f"box's measured pair ceiling {ceiling}x; at 4 = "
        f"{out.get('scaling_4x')}x on {out['cores']} cores "
        f"(n=1 round noise spread {out['noise_spread_1x']}x)")
    return out


# -- partitioned write scale-out (ISSUE 15) -----------------------------------

# four independent co-location classes — (kube resource, namespace-like
# parent type, tuple type) — so a 4-shard partition map can spread them
# 1:1 and a 2-shard map packs two classes per shard.  Every class is
# symmetric: the per-class dual-write cost is identical, so aggregate
# throughput differences between fleet sizes measure sharding, not
# workload skew.
SHARD_CLASSES = (
    ("pods", "podns", "pod"),
    ("configmaps", "cfgns", "configmap"),
    ("secrets", "secns", "secret"),
    ("services", "svcns", "service"),
)

SHARD_SCHEMA = "definition user {}\n" + "\n".join(
    f"definition {t} {{\n  relation creator: user\n"
    f"  permission view = creator\n}}"
    for _res, ns, typ in SHARD_CLASSES for t in (ns, typ))

_SHARD_RULE_TPL = """\
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {{name: create-{res}}}
match: [{{apiVersion: v1, resource: {res}, verbs: [create]}}]
lock: Optimistic
check: [{{tpl: "{ns}:{{{{namespace}}}}#view@user:{{{{user.name}}}}"}}]
update:
  creates:
  - tpl: "{typ}:{{{{namespacedName}}}}#creator@user:{{{{user.name}}}}"
---
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {{name: delete-{res}}}
match: [{{apiVersion: v1, resource: {res}, verbs: [delete]}}]
lock: Optimistic
update:
  deleteByFilter:
  - tpl: "{typ}:{{{{namespacedName}}}}#$resourceRelation@$subjectType:$subjectID"
"""

SHARD_RULES = "\n---\n".join(
    _SHARD_RULE_TPL.format(res=res, ns=ns, typ=typ)
    for res, ns, typ in SHARD_CLASSES)

SHARD_WORKER_SPEC = {
    "measure_s": 4.0, "inflight": 6, "wal_fsync": "always",
}


def shard_leader_worker(spec_json: str) -> None:
    """`bench.py --shard-worker <spec-json>` subprocess: ONE shard
    leader — an unmodified embedded proxy (rules engine, dual-write
    workflow engine, its own WAL under `data_dir` with the spec'd fsync
    policy) taking kube-style create/delete dual-writes through the
    in-process client, exactly the per-shard write path behind the
    router (spicedb/sharding/router.py).  Protocol on stdio: READY
    after warm; each `RUN {"tag":..,"resources":[..]}` line runs one
    measured churn window over those resources and prints
    `DONE <json>`; `EXIT` quits.  A separate pinned process per shard
    leader is the point: each has its own GIL, event loop, and WAL —
    the deployment unit the partition map scales."""
    import asyncio

    spec = json.loads(spec_json)
    from spicedb_kubeapi_proxy_tpu.kubefake.apiserver import FakeKubeApiServer
    from spicedb_kubeapi_proxy_tpu.proxy.httpcore import HandlerTransport
    from spicedb_kubeapi_proxy_tpu.proxy.server import Options, ProxyServer
    from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import Bootstrap
    from spicedb_kubeapi_proxy_tpu.spicedb.types import parse_relationship

    kube = FakeKubeApiServer()
    kube.seed("", "v1", "namespaces", {"metadata": {"name": "team-a"}})
    opts = Options(
        spicedb_endpoint="embedded://",
        bootstrap=Bootstrap(schema_text=SHARD_SCHEMA),
        rules_yaml=SHARD_RULES,
        upstream_transport=HandlerTransport(kube),
        workflow_database_path="",  # in-memory dual-write journal
    )
    opts.data_dir = spec["data_dir"]
    opts.wal_fsync = spec["wal_fsync"]
    proxy = ProxyServer(opts)
    if proxy.endpoint.store.revision == 0:
        proxy.endpoint.store.bulk_load([
            parse_relationship(f"{ns}:team-a#creator@user:alice")
            for _res, ns, _typ in SHARD_CLASSES])
    proxy.enable_dual_writes()
    client = proxy.get_embedded_client(user="alice")
    ident = spec["identity"]

    async def one_create(res: str, name: str) -> float:
        t0 = time.perf_counter()
        resp = await client.post(
            f"/api/v1/namespaces/team-a/{res}",
            {"apiVersion": "v1", "metadata": {"name": name,
                                              "namespace": "team-a"}})
        assert resp.status in (200, 201), (res, name, resp.status,
                                           resp.body)
        return time.perf_counter() - t0

    async def one_delete(res: str, name: str) -> float:
        t0 = time.perf_counter()
        resp = await client.delete(
            f"/api/v1/namespaces/team-a/{res}/{name}")
        assert resp.status in (200, 404), (res, name, resp.status,
                                           resp.body)
        return time.perf_counter() - t0

    async def window(tag: str, resources: list, seconds: float) -> dict:
        lat: list = []
        done = 0
        deadline = time.perf_counter() + seconds

        async def loop(lane: int):
            nonlocal done
            i = 0
            recent: list = []
            while time.perf_counter() < deadline:
                res = resources[i % len(resources)]
                # churn profile: 3 creates then a delete of the oldest
                # pending create — bounded store growth, both dual-write
                # verbs (create = check + precondition + create tuple;
                # delete = delete-by-filter), unique names across
                # windows via the round tag
                if len(recent) >= 3:
                    lat.append(await one_delete(*recent.pop(0)))
                else:
                    name = f"{ident}-{tag}-l{lane}-{i}"
                    lat.append(await one_create(res, name))
                    recent.append((res, name))
                done += 1
                i += 1

        t0 = time.perf_counter()
        await asyncio.gather(*(loop(k) for k in range(spec["inflight"])))
        elapsed = time.perf_counter() - t0
        lat.sort()

        def pct(p):
            return round(
                lat[min(len(lat) - 1, int(p * len(lat)))] * 1000, 3)

        return {"writes": done, "elapsed_s": round(elapsed, 3),
                "writes_per_s": round(done / elapsed, 1),
                "p50_ms": pct(0.5), "p99_ms": pct(0.99),
                "store_revision": proxy.endpoint.store.revision}

    async def main_loop():
        # warm every rule/template path before READY so compilation
        # never lands inside a measured window
        for res, _ns, _typ in SHARD_CLASSES:
            await one_create(res, f"{ident}-warm")
            await one_delete(res, f"{ident}-warm")
        print("READY", flush=True)
        loop = asyncio.get_running_loop()
        while True:
            line = await loop.run_in_executor(None, sys.stdin.readline)
            if not line or line.strip() == "EXIT":
                return
            if line.startswith("RUN "):
                cmd = json.loads(line[4:])
                res = await window(cmd["tag"], cmd["resources"],
                                   spec["measure_s"])
                print("DONE " + json.dumps(res), flush=True)

    asyncio.run(main_loop())


def bench_write_shard_scale(args) -> dict:
    """Partitioned write scale-out (ISSUE 15): aggregate dual-write
    throughput + p99 at 1/2/4 shard-leader PROCESSES (shard_leader_worker
    above — each an unmodified embedded proxy with its own WAL,
    fsync=always, pinned to a core) under the create/delete churn
    profile.  The parent plays the thin stateless router: it owns the
    PartitionMap, footprint-validates the schema against it per fleet
    size (the SL007 startup gate), and assigns each co-location class to
    its shard — routers are horizontally scalable, so routing cost rides
    the client, not a one-process bottleneck that would cap the thing
    being measured.  Headline `write_shard_scaling` = 2-shard aggregate
    over 1-shard (acceptance >= 1.5x — same hardware ceiling caveat as
    replica-scale: scaling cannot exceed the box's measured pair
    ceiling, recorded alongside)."""
    import shutil
    import tempfile

    from spicedb_kubeapi_proxy_tpu.spicedb import schema as sch
    from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import (
        merge_internal_definitions,
    )
    from spicedb_kubeapi_proxy_tpu.spicedb.sharding import PartitionMap
    from spicedb_kubeapi_proxy_tpu.utils.topology import (
        WorkerFleet,
        cpu_pair_ceiling,
    )

    spec = dict(SHARD_WORKER_SPEC)
    fleet_sizes = (1, 2, 4)
    schema = merge_internal_definitions(sch.parse_schema(SHARD_SCHEMA))

    # the partition maps the parent-as-router would serve each fleet
    # with: class c -> shard c % n.  Footprint-validate each one — the
    # same hard startup gate the real router applies (SL007): 0 errors
    # proves every class's closure is shard-local for every fleet size.
    maps: dict = {}
    for n in fleet_sizes:
        assignments = {}
        for c, (_res, ns, typ) in enumerate(SHARD_CLASSES):
            assignments[ns] = c % n
            assignments[typ] = c % n
        pmap = PartitionMap(n, assignments)
        errors, _warnings = pmap.validate_schema(schema)
        if errors:
            raise AssertionError(
                f"write-shard-scale partition map for {n} shard(s) "
                f"fails footprint validation: {errors}")
        maps[n] = pmap

    tmp = tempfile.mkdtemp(prefix="shard-bench-")
    out: dict = {"fleet": {}, "measure_s": spec["measure_s"],
                 "inflight_per_shard": spec["inflight"],
                 "wal_fsync": spec["wal_fsync"],
                 "partition_map_4": maps[4].describe(),
                 "cores": os.cpu_count()}
    # same fixed per-process budget as replica-scale, via the shared
    # harness: production shard leaders are separate nodes, so the
    # claim is "aggregate write throughput grows as shards are added
    # at a constant per-shard budget"
    fleet = WorkerFleet(name="write-shard-scale")
    try:
        stage(f"write-shard-scale: spawn + warm {max(fleet_sizes)} "
              f"shard-leader processes")
        for i in range(max(fleet_sizes)):
            wspec = dict(spec, identity=f"shard{i}",
                         data_dir=os.path.join(tmp, f"shard-{i}"))
            fleet.spawn(
                [sys.executable, os.path.abspath(__file__),
                 "--shard-worker", json.dumps(wspec)],
                pin=i, label=f"shard-{i}")
        fleet.wait_ready()

        def window(n: int, tag: str) -> list:
            # ownership split the fleet-n partition map prescribes:
            # worker i writes the kube resources of classes c%n == i
            pmap = maps[n]
            payloads = []
            for i in range(n):
                resources = [res for res, _ns, typ in SHARD_CLASSES
                             if pmap.shard_for_type(typ) == i]
                payloads.append({"tag": tag, "resources": resources})
            return fleet.run_window(n, payloads=payloads)

        # interleaved rounds, median per fleet size, paired per-round
        # scaling ratios — the replica-scale methodology (ambient load
        # on a shared box drifts by more than the effect measured)
        rounds = 3
        acc: dict = {n: [] for n in fleet_sizes}
        for r in range(rounds):
            for n in fleet_sizes:
                stage(f"write-shard-scale round {r + 1}/{rounds}: {n} "
                      f"shard leader(s) under churn")
                acc[n].append(window(n, f"r{r}n{n}"))
        for n in fleet_sizes:
            aggs = [sum(res["writes_per_s"] for res in results)
                    for results in acc[n]]
            agg = statistics.median(aggs)
            flat = [res for results in acc[n] for res in results]
            out["fleet"][str(n)] = {
                "aggregate_writes_per_s": round(agg, 1),
                "aggregate_writes_per_s_rounds": [round(a, 1)
                                                  for a in aggs],
                "per_shard_writes_per_s": round(agg / n, 1),
                "dual_write_p50_ms": statistics.median(
                    res["p50_ms"] for res in flat),
                # conservative: the slowest shard's p99 across rounds
                "dual_write_p99_ms": max(res["p99_ms"] for res in flat),
                "writes": sum(res["writes"] for res in flat),
            }
            log(f"write-shard-scale n={n}: {agg:.1f} dual-writes/s "
                f"aggregate (median of {aggs}), p99 "
                f"{out['fleet'][str(n)]['dual_write_p99_ms']}ms")
    finally:
        fleet.shutdown()
        shutil.rmtree(tmp, ignore_errors=True)

    stage("write-shard-scale: CPU pair-scaling ceiling probe")
    out["cpu_pair_scaling_ceiling"] = cpu_pair_ceiling()

    base_rounds = [sum(res["writes_per_s"] for res in results)
                   for results in acc[1]]
    out["noise_spread_1x"] = round(
        max(base_rounds) / max(min(base_rounds), 1e-9), 2)
    for n in fleet_sizes[1:]:
        ratios = [
            sum(res["writes_per_s"] for res in results) / max(b, 1e-9)
            for results, b in zip(acc[n], base_rounds)]
        out[f"scaling_{n}x"] = round(statistics.median(ratios), 2)
        out[f"scaling_{n}x_rounds"] = [round(r, 2) for r in ratios]
    out["write_shard_scaling"] = out.get("scaling_2x", 0.0)
    ceiling = out["cpu_pair_scaling_ceiling"]
    out["write_shard_scaling_normalized"] = round(
        out["write_shard_scaling"] / max(ceiling, 1e-9), 2)
    out["dual_write_p99_ms"] = out["fleet"]["2"]["dual_write_p99_ms"]
    log(f"write-shard-scale: write scaling at 2 shards = "
        f"{out['write_shard_scaling']}x raw (acceptance >= 1.5x on >=2 "
        f"free cores), {out['write_shard_scaling_normalized']}x of this "
        f"box's measured pair ceiling {ceiling}x; at 4 = "
        f"{out.get('scaling_4x')}x on {out['cores']} cores "
        f"(n=1 round noise spread {out['noise_spread_1x']}x)")
    return out


def _scenario_chain(workload, clock, cache_on: bool):
    """jax:// endpoint over a FAKE-clock store (+ DecisionCacheEndpoint
    when the scenario exercises the cache seam) and its oracle."""
    from spicedb_kubeapi_proxy_tpu.ops.jax_endpoint import JaxEndpoint
    from spicedb_kubeapi_proxy_tpu.spicedb import schema as sch
    from spicedb_kubeapi_proxy_tpu.spicedb.evaluator import Evaluator
    from spicedb_kubeapi_proxy_tpu.spicedb.store import TupleStore

    schema = sch.parse_schema(workload.schema_text)
    store = TupleStore(clock=clock.now)
    inner = JaxEndpoint(schema, store=store)
    store.bulk_load_text("\n".join(workload.relationships))
    ep = inner
    if cache_on:
        from spicedb_kubeapi_proxy_tpu.spicedb.decision_cache import (
            DecisionCacheEndpoint)
        ep = DecisionCacheEndpoint(inner)
    return ep, inner, Evaluator(schema, store)


def _scenario_bench(name, args, churn_fn, cache_on=False, rounds=None,
                    extra=None):
    """Shared scenario runner with the HOST-ORACLE PARITY REFEREE:
    every round applies scenario churn, referees N subjects' frontiers
    and a check-bulk sample against the recursive evaluator over the
    SAME store at the SAME revision, and measures device throughput.
    Churn rounds scale with --rounds (the default 10 maps to 6 rounds,
    --rounds 20 to 12, ...).  Divergence acceptance for every scenario
    config: 0."""
    if rounds is None:
        rounds = max(2, args.rounds * 6 // 10)
    import asyncio
    import random as _random

    from spicedb_kubeapi_proxy_tpu.fuzz.delta_gen import FakeClock
    from spicedb_kubeapi_proxy_tpu.fuzz.scenarios import SCENARIO_WORKLOADS
    from spicedb_kubeapi_proxy_tpu.spicedb.types import (
        CheckRequest, ObjectRef, SubjectRef)

    clock = FakeClock()
    wl_kw = {"now": clock.now()} if name == "ephemeral-grants" else {}
    workload = SCENARIO_WORKLOADS[name](**wl_kw)
    stage(f"{name} build ({len(workload.relationships)} tuples)")
    ep, inner, oracle = _scenario_chain(workload, clock, cache_on)
    rng = _random.Random(99)
    rt, perm = workload.resource_type, workload.permission
    subjects = [SubjectRef("user", workload.subjects[i * 7
                                                     % len(workload.subjects)])
                for i in range(4)]
    divergences = 0
    refereed = 0
    check_s = 0.0
    n_checks = 0
    list_s = 0.0
    n_lists = 0

    async def run():
        nonlocal divergences, refereed, check_s, n_checks, list_s, n_lists
        # warmup: pay first-use jit compiles outside the timed rounds
        await ep.lookup_resources(rt, perm, subjects[0])
        ids0 = inner.store.object_ids_of_type(rt)[:64]
        await ep.check_bulk_permissions(
            [CheckRequest(ObjectRef(rt, o), perm, subjects[0])
             for o in ids0])
        for r in range(rounds):
            churn_fn(inner.store, clock, rng, r)
            # referee: frontier parity per subject at the pinned
            # revision — twice when the cache rides the chain, so the
            # SECOND pass referees a cache-served answer too
            for s in subjects:
                want = sorted(oracle.lookup_resources(rt, perm, s))
                for _pass in range(2 if cache_on else 1):
                    t0 = time.time()
                    got = sorted(await ep.lookup_resources(rt, perm, s))
                    list_s += time.time() - t0
                    n_lists += 1
                    refereed += 1
                    if got != want:
                        divergences += 1
            # referee: tri-state check parity on a sampled id block
            ids = inner.store.object_ids_of_type(rt)
            sample = ids[:: max(1, len(ids) // 128)][:128]
            reqs = [CheckRequest(ObjectRef(rt, o), perm, s)
                    for o in sample for s in subjects[:2]]
            t0 = time.time()
            res = await ep.check_bulk_permissions(reqs)
            check_s += time.time() - t0
            n_checks += len(reqs)
            p3 = {"NO_PERMISSION": 0, "CONDITIONAL_PERMISSION": 1,
                  "HAS_PERMISSION": 2}
            for req, cr in zip(reqs, res):
                refereed += 1
                if p3[cr.permissionship.name] != oracle.check3(
                        req.resource, req.permission, req.subject):
                    divergences += 1

    asyncio.run(run())
    out = {
        "divergences": divergences,
        "refereed_answers": refereed,
        "rounds": rounds,
        "checks_per_s": round(n_checks / max(check_s, 1e-9), 1),
        "lists_per_s": round(n_lists / max(list_s, 1e-9), 2),
        "objects": workload.expected_objects,
        "tuples": len(workload.relationships),
        "kernel_calls": inner.stats["kernel_calls"],
        "oracle_residual_checks": inner.stats["oracle_residual_checks"],
        "rebuilds": inner.stats["rebuilds"],
    }
    if cache_on:
        st = ep.cache.stats
        probes = st["hits"] + st["misses"]
        out["hit_rate"] = round(st["hits"] / max(probes, 1), 4)
        out["cache_invalidations"] = st["invalidations"]
    if extra:
        out.update(extra(inner))
    log(f"{name}: {divergences} divergences over {refereed} refereed "
        f"answers, {out['checks_per_s']} checks/s, "
        f"{out['rebuilds']} rebuilds")
    return out


def bench_scenario_caveat_heavy(args) -> dict:
    """CEL-caveated tuples at scale (ROADMAP item 5): decided-true /
    decided-false / undecidable contexts churned every round; the
    artifact records WHICH side decided the caveats (`caveat_path`) —
    the tri-state device bitplanes or the host-oracle post-filter."""

    def churn(store, clock, rng, r):
        from spicedb_kubeapi_proxy_tpu.spicedb.types import (
            RelationshipUpdate, UpdateOp, parse_relationship)
        ops = []
        for _ in range(24):
            d = rng.randrange(3000)
            u = rng.randrange(400)
            roll = rng.random()
            if roll < 0.4:
                ctx = '{"used": 1, "quota": 5}' if rng.random() < 0.5 \
                    else '{"used": 1}'
                ops.append(RelationshipUpdate(
                    UpdateOp.TOUCH, parse_relationship(
                        f"doc:d{d}#assigned@user:u{u}"
                        f"[caveat:within_quota:{ctx}]")))
            elif roll < 0.7:
                lvl = rng.randrange(6)
                ops.append(RelationshipUpdate(
                    UpdateOp.TOUCH, parse_relationship(
                        f"doc:d{d}#approved@user:u{u}"
                        f'[caveat:min_level:{{"level": {lvl}}}]')))
            else:
                ops.append(RelationshipUpdate(
                    UpdateOp.DELETE, parse_relationship(
                        f"doc:d{d}#assigned@user:u{u}")))
        store.write(ops)

    def caveat_path(inner):
        graph = inner._graph
        bitplane = bool(getattr(graph, "has_cav", False))
        residual = inner.stats["oracle_residual_checks"]
        return {"caveat_path": ("device-bitplane" if bitplane and not
                                residual else
                                "device-bitplane+host-residual" if bitplane
                                else "host-postfilter"),
                "caveat_bitplanes": bitplane}

    return _scenario_bench("caveat-heavy", args, churn, extra=caveat_path)


def bench_scenario_wildcard_public(args) -> dict:
    """Wildcard-heavy public resources: `user:*` grants FLIP on and off
    every round — the delta class the device graph cannot absorb in
    place, so the rebuild path (sync or background per the AsyncRebuild
    gate) carries the churn while the referee holds parity."""

    def churn(store, clock, rng, r):
        from spicedb_kubeapi_proxy_tpu.spicedb.types import (
            RelationshipUpdate, UpdateOp, parse_relationship)
        ops = []
        for _ in range(8):
            d = rng.randrange(4000)
            op = (UpdateOp.DELETE if rng.random() < 0.5 else UpdateOp.TOUCH)
            ops.append(RelationshipUpdate(
                op, parse_relationship(f"doc:d{d}#public@user:*")))
        for _ in range(8):
            d = rng.randrange(4000)
            u = rng.randrange(400)
            ops.append(RelationshipUpdate(
                UpdateOp.TOUCH,
                parse_relationship(f"doc:d{d}#viewer@user:u{u}")))
        store.write(ops)

    return _scenario_bench("wildcard-public", args, churn)


def bench_scenario_ephemeral_grants(args) -> dict:
    """PAuth-style task-scoped ephemeral grants: short-TTL expiring
    tuples at high churn against the store's fake clock, with the
    DecisionCache ON — every round grants expire mid-stream, so the
    PR 3 expiry heap must invalidate cached frontiers exactly when the
    clock crosses each instant (the referee proves it)."""

    def churn(store, clock, rng, r):
        from spicedb_kubeapi_proxy_tpu.spicedb.types import (
            RelationshipUpdate, UpdateOp, parse_relationship)
        ops = []
        for _ in range(32):
            d = rng.randrange(3000)
            u = rng.randrange(300)
            ttl = 5.0 + 25.0 * rng.random()
            exp = clock.now() + ttl
            ops.append(RelationshipUpdate(
                UpdateOp.TOUCH, parse_relationship(
                    f"doc:d{d}#grant@user:u{u}[expiration:{exp}]")))
        store.write(ops)
        # cross a swath of TTL instants: earlier rounds' grants lapse
        clock.advance(12.0)

    return _scenario_bench("ephemeral-grants", args, churn, cache_on=True)


def bench_scenario_group_explosion(args) -> dict:
    """Leopard materialized group index A/B (ISSUE 19): 100k groups in
    disjoint depth-8 membership chains, docs shared with chain HEADS —
    the shape where every check pays `depth` HBM sweep iterations
    without the index and ONE closure-plane probe with it.  Two
    endpoints over the SAME store: LeopardIndex gate ON at construction
    (indexed) and OFF (iterative kernel sweeps), churned with tail-user
    moves (insert propagation + delete quarantine -> background
    re-close) under the host-oracle parity referee on BOTH endpoints.
    Acceptance: 0 divergences, indexed >= 5x iterative checks/s, and
    measured mean sweep depth ~1 on the indexed pairs."""
    import asyncio
    import random as _random

    from spicedb_kubeapi_proxy_tpu.fuzz.delta_gen import FakeClock
    from spicedb_kubeapi_proxy_tpu.fuzz.scenarios import SCENARIO_WORKLOADS
    from spicedb_kubeapi_proxy_tpu.ops.jax_endpoint import JaxEndpoint
    from spicedb_kubeapi_proxy_tpu.spicedb import schema as sch
    from spicedb_kubeapi_proxy_tpu.spicedb.evaluator import Evaluator
    from spicedb_kubeapi_proxy_tpu.spicedb.store import TupleStore
    from spicedb_kubeapi_proxy_tpu.spicedb.types import (
        CheckRequest, ObjectRef, RelationshipUpdate, SubjectRef, UpdateOp,
        parse_relationship)
    from spicedb_kubeapi_proxy_tpu.utils import workload as wk
    from spicedb_kubeapi_proxy_tpu.utils.features import GATES

    depth = 8
    workload = SCENARIO_WORKLOADS["group-explosion"](depth=depth)
    stage(f"group-explosion build ({len(workload.relationships)} tuples)")
    schema = sch.parse_schema(workload.schema_text)
    clock = FakeClock()
    store = TupleStore(clock=clock.now)
    store.bulk_load_text("\n".join(workload.relationships))
    # the LeopardIndex gate is captured at endpoint construction, so the
    # indexed and iterative endpoints coexist over the same store
    prev = GATES.enabled("LeopardIndex")
    try:
        GATES.set("LeopardIndex", True)
        ep_on = JaxEndpoint(schema, store=store)
        GATES.set("LeopardIndex", False)
        ep_off = JaxEndpoint(schema, store=store)
    finally:
        GATES.set("LeopardIndex", prev)
    oracle = Evaluator(schema, store)
    rng = _random.Random(199)

    # doc -> (head group, tail user) straight from the tuples, so the
    # check mix carries known depth-8 positives without generator coupling
    doc_user = {}
    for r in workload.relationships:
        if r.startswith("doc:"):
            rel = parse_relationship(r)
            head = int(rel.subject.id[1:])
            doc_user[rel.resource.id] = f"u{(head // depth) % 2000}"
    docs = sorted(doc_user)

    def check_reqs(n):
        reqs = []
        for _ in range(n):
            d = docs[rng.randrange(len(docs))]
            u = (doc_user[d] if rng.random() < 0.5
                 else f"u{rng.randrange(2000)}")
            reqs.append(CheckRequest(ObjectRef("doc", d), "view",
                                     SubjectRef("user", u)))
        return reqs

    rounds = max(2, args.rounds * 4 // 10)
    n_chains = 100_000 // depth
    divergences = 0
    refereed = 0
    p3 = {"NO_PERMISSION": 0, "CONDITIONAL_PERMISSION": 1,
          "HAS_PERMISSION": 2}

    def churn(r):
        # move a few tail users between chains: the DELETE leg drives
        # the quarantine -> background re-close path, the TOUCH leg the
        # bounded-frontier insert propagation
        ops = []
        for _ in range(4):
            c = rng.randrange(n_chains)
            tail = c * depth + depth - 1
            ops.append(RelationshipUpdate(UpdateOp.DELETE,
                       parse_relationship(f"group:g{tail}#member"
                                          f"@user:u{c % 2000}")))
            ops.append(RelationshipUpdate(UpdateOp.TOUCH,
                       parse_relationship(f"group:g{tail}#member"
                                          f"@user:u{rng.randrange(2000)}")))
        store.write(ops)

    async def referee():
        nonlocal divergences, refereed
        subjects = [SubjectRef("user", doc_user[docs[rng.randrange(
            len(docs))]]) for _ in range(2)]
        for s in subjects:
            want = sorted(oracle.lookup_resources("doc", "view", s))
            for ep in (ep_on, ep_off):
                got = sorted(await ep.lookup_resources("doc", "view", s))
                refereed += 1
                if got != want:
                    divergences += 1
        reqs = check_reqs(64)
        want3 = [oracle.check3(q.resource, q.permission, q.subject)
                 for q in reqs]
        for ep in (ep_on, ep_off):
            res = await ep.check_bulk_permissions(reqs)
            for w, cr in zip(want3, res):
                refereed += 1
                if p3[cr.permissionship.name] != w:
                    divergences += 1

    async def measure(ep):
        # depth attribution reads the sweep-telemetry singleton, so each
        # phase starts from a clean accounting slate
        wk.WORKLOAD.reset()
        reqs = check_reqs(args.batch)
        await ep.check_bulk_permissions(reqs)  # pay compiles untimed
        t0 = time.time()
        n = 0
        for _ in range(max(4, args.rounds)):
            await ep.check_bulk_permissions(reqs)
            n += len(reqs)
        check_s = time.time() - t0
        t0 = time.time()
        n_lists = 0
        for _ in range(8):
            s = SubjectRef("user", doc_user[docs[rng.randrange(len(docs))]])
            await ep.lookup_resources("doc", "view", s)
            n_lists += 1
        list_s = time.time() - t0
        mean_depth = None
        for row in wk.WORKLOAD.payload()["rows"]:
            if (row["resource_type"], row["permission"]) == ("doc", "view"):
                mean_depth = row["mean_sweep_depth"]
        return {"checks_per_s": round(n / max(check_s, 1e-9), 1),
                "lists_per_s": round(n_lists / max(list_s, 1e-9), 2),
                "mean_sweep_depth": mean_depth}

    async def run():
        for r in range(rounds):
            churn(r)
            await referee()
        # drain background re-closes so the indexed phase measures the
        # closure-plane fast path, not the quarantine kernel fallback
        ep_on.wait_rebuilds()
        ep_off.wait_rebuilds()
        await referee()
        return await measure(ep_on), await measure(ep_off)

    indexed, iterative = asyncio.run(run())
    lp = ep_on._leopard
    statuses = lp.status_map() if lp is not None else {}
    out = {
        "divergences": divergences,
        "refereed_answers": refereed,
        "rounds": rounds,
        "depth": depth,
        "tuples": len(workload.relationships),
        "checks_per_s": indexed["checks_per_s"],
        "indexed": indexed,
        "iterative": iterative,
        "indexed_speedup": round(indexed["checks_per_s"]
                                 / max(iterative["checks_per_s"], 1e-9), 2),
        "index_fragments": lp.fragment_count() if lp is not None else 0,
        "index_bytes": lp.nbytes if lp is not None else 0,
        "index_statuses": statuses,
        "leopard_checks": ep_on.stats["leopard_checks"],
        "leopard_lookups": ep_on.stats["leopard_lookups"],
        "leopard_recloses": ep_on.stats["leopard_recloses"],
    }
    log(f"group-explosion: {divergences} divergences over {refereed} "
        f"refereed answers, indexed {indexed['checks_per_s']} vs "
        f"iterative {iterative['checks_per_s']} checks/s "
        f"({out['indexed_speedup']}x), depth {indexed['mean_sweep_depth']}"
        f" vs {iterative['mean_sweep_depth']}")
    return out


# scenario matrix configs (ISSUE 12 / ROADMAP item 5): the three
# workload shapes the sweep was missing, each with a host-oracle parity
# referee (docs/performance.md "Scenario matrix")
def bench_sweep_telemetry(args) -> dict:
    """KernelIntrospect A/B (ISSUE 17): the 1M-tuple depth-4 headline
    shape run with the sweep-telemetry gate OFF (byte-identical
    pre-introspection jits) and ON (iteration counter + frontier trace
    threaded through the fixpoint carry), interleaved so allocator
    drift lands on both modes equally.  Reports the per-round overhead
    of the telemetry (acceptance: within run-to-run noise), the
    measured-basis roofline from a dedicated introspect-on window
    (`kernel_bytes_basis` must read "measured"), and the /debug/workload
    attribution payload the traffic produced."""
    import asyncio

    from spicedb_kubeapi_proxy_tpu.models import workloads as wl
    from spicedb_kubeapi_proxy_tpu.spicedb.dispatch import BatchingEndpoint
    from spicedb_kubeapi_proxy_tpu.spicedb.types import SubjectRef
    from spicedb_kubeapi_proxy_tpu.utils import workload as wk
    from spicedb_kubeapi_proxy_tpu.utils.features import GATES

    workload = wl.multitenant_1m()
    batch = args.batch
    rounds = max(3, args.rounds // 2)
    max_batch = max(1, batch // 4)
    subjects = workload.subjects
    modes = (("introspect-off", False), ("introspect-on", True))
    out: dict = {"modes": {}, "batch": batch, "rounds": rounds,
                 "max_batch": max_batch}
    eps: dict = {}
    acc = {name: [] for name, _gate in modes}

    async def one_round(ep, r):
        async def caller(i):
            s = SubjectRef(
                "user", subjects[(r * batch + i) % len(subjects)])
            return await ep.lookup_resources(
                workload.resource_type, workload.permission, s)
        t0 = time.time()
        await asyncio.gather(*[caller(i) for i in range(batch)])
        return time.time() - t0

    try:
        # `introspect` is resolved at jit BUILD time, so each mode gets
        # its own endpoint, built and warmed under its gate state — the
        # off mode runs the exact pre-introspection functions
        for name, gate in modes:
            GATES.set("KernelIntrospect", gate)
            stage(f"sweep-telemetry build + load + warm ({name})")
            inner = build_endpoint(workload, "jax")
            eps[name] = BatchingEndpoint(inner, max_batch=max_batch,
                                         pipeline_depth=2)
            asyncio.run(one_round(eps[name], 0))  # warm: compiles+arenas
        stage("sweep-telemetry interleaved rounds")
        for r in range(rounds):
            for name, gate in modes:
                GATES.set("KernelIntrospect", gate)
                acc[name].append(asyncio.run(one_round(eps[name], r + 1)))
        # dedicated introspect-on window for the measured-basis roofline:
        # only introspect-built kernels dispatch inside it, so the
        # summary's kernel byte tags are all iterations x one-sweep
        GATES.set("KernelIntrospect", True)
        mark = timeline_mark()
        asyncio.run(one_round(eps["introspect-on"], rounds + 1))
        tl = timeline_summary(mark) or {}
    finally:
        GATES.set("KernelIntrospect", True)

    n_obj = len(eps["introspect-on"].inner.store.object_ids_of_type(
        workload.resource_type))
    for name, _gate in modes:
        per_round = statistics.median(acc[name])
        out["modes"][name] = {
            "checks_per_s": round(batch * n_obj / per_round, 1),
            "per_round_ms": round(per_round * 1e3, 2),
            "p99_ms": round(p99(acc[name]) * 1e3, 2),
        }
    off_med = statistics.median(acc["introspect-off"])
    on_med = statistics.median(acc["introspect-on"])
    noise = (statistics.stdev(acc["introspect-off"])
             if len(acc["introspect-off"]) > 1 else 0.0)
    out["overhead_pct"] = round((on_med / off_med - 1) * 100, 2)
    out["noise_pct"] = round(noise / off_med * 100, 2) if off_med else None
    out["overhead_within_noise"] = bool(abs(on_med - off_med)
                                        <= max(2 * noise, 0.02 * off_med))
    out["roofline_fraction"] = tl.get("roofline_fraction")
    out["kernel_bytes_basis"] = tl.get("kernel_bytes_basis")
    out["workload_attribution"] = wk.WORKLOAD.payload()
    log(f"sweep-telemetry: overhead={out['overhead_pct']}% "
        f"(noise {out['noise_pct']}%), basis={out['kernel_bytes_basis']}, "
        f"roofline={out['roofline_fraction']}")
    return out


def bench_cpu_microbench(args) -> dict:
    """Deterministic pure-python microbench for the perf-regression
    sentinel (scripts/benchdiff.py + the check.sh gate): NO jax import,
    fixed seeds and fixed work, per-round wall times recorded so the
    comparator can derive noise-aware thresholds, and a pure-python
    calibration loop riding the artifact so two runs on
    differently-loaded machines compare ratio-normalized.  Exercises
    the dispatch drain hot loop (spicedb/dispatch.py) and the recursive
    oracle — the two CPU paths a slowdown is most likely to hide in."""
    import asyncio

    from spicedb_kubeapi_proxy_tpu.spicedb import schema as sch
    from spicedb_kubeapi_proxy_tpu.spicedb.dispatch import BatchingEndpoint
    from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import EmbeddedEndpoint
    from spicedb_kubeapi_proxy_tpu.spicedb.types import (
        CheckRequest, ObjectRef, SubjectRef)

    schema_text = """
definition user {}
definition team {
  relation member: user | team#member
  permission view = member
}
definition doc {
  relation owner: user
  relation reader: user | team#member
  permission view = owner + reader
}
"""
    n_docs, n_users, n_teams = 120, 24, 6
    rels = []
    for t in range(n_teams):
        for u in range(t, n_users, n_teams):
            rels.append(f"team:t{t}#member@user:u{u}")
        if t:
            rels.append(f"team:t{t}#member@team:t{t - 1}#member")
    for d in range(n_docs):
        rels.append(f"doc:d{d}#owner@user:u{d % n_users}")
        rels.append(f"doc:d{d}#reader@team:t{d % n_teams}#member")
    inner = EmbeddedEndpoint(sch.parse_schema(schema_text))
    inner.store.bulk_load_text("\n".join(rels))
    ep = BatchingEndpoint(inner, max_batch=8)

    def calib() -> float:
        t0 = time.perf_counter()
        x = 0
        for i in range(200_000):
            x = (x * 31 + i) % 1_000_003
        return time.perf_counter() - t0

    calibration_s = min(calib() for _ in range(3))
    rounds = max(5, args.rounds)
    batch = min(args.batch, 64)

    async def check_round(r):
        reqs = [CheckRequest(ObjectRef("doc", f"d{(r * batch + i) % n_docs}"),
                             "view", SubjectRef("user", f"u{i % n_users}"))
                for i in range(batch)]
        await asyncio.gather(*[ep.check_permission(q) for q in reqs])

    async def lookup_round(r):
        await asyncio.gather(*[
            ep.lookup_resources("doc", "view",
                                SubjectRef("user", f"u{(r + i) % n_users}"))
            for i in range(batch)])

    async def oracle_round(r):
        reqs = [CheckRequest(ObjectRef("doc", f"d{(r * batch + i) % n_docs}"),
                             "view", SubjectRef("user", f"u{i % n_users}"))
                for i in range(batch)]
        await inner.check_bulk_permissions(reqs)

    configs: dict = {}
    for name, fn in (("dispatch-check", check_round),
                     ("dispatch-lookup", lookup_round),
                     ("oracle-eval", oracle_round)):
        asyncio.run(fn(0))  # warm
        times = []
        for r in range(rounds):
            t0 = time.perf_counter()
            asyncio.run(fn(r + 1))
            times.append(time.perf_counter() - t0)
        configs[name] = {
            "per_round_s": [round(t, 6) for t in times],
            "median_s": round(statistics.median(times), 6),
        }
        log(f"cpu-microbench {name}: median "
            f"{configs[name]['median_s'] * 1e3:.2f} ms/round")
    return {"calibration_s": round(calibration_s, 6), "rounds": rounds,
            "batch": batch, "tuples": len(rels), "configs": configs}


def bench_mesh_scale(args) -> dict:
    """Multi-chip mesh scaling, MEASURED (not projected) on the local
    device set: the same depth-4 workload served by the single-chip
    kernels and by sharded 1x1 / 1x2 / 1x4 (data x graph) meshes, with
    rounds interleaved across all modes so allocator and load drift
    land on every mode equally.  Reports per-mode lookup-round medians
    and paired scaling ratios vs the single-chip baseline, the
    per-device HBM ledger rows each mesh registered, and the pipelined
    dispatch overlap measured on the largest sharded graph.  On a CPU
    host (forced virtual devices) the numbers measure STRUCTURAL
    scaling — partition, collective, and dispatch overheads are real,
    FLOPS scaling is not; on a TPU slice the same config measures the
    physical thing.  Needs >= 2 devices; single-device hosts record a
    skip marker instead of projecting."""
    import asyncio

    import jax

    from spicedb_kubeapi_proxy_tpu.models import workloads as wl
    from spicedb_kubeapi_proxy_tpu.ops.jax_endpoint import JaxEndpoint
    from spicedb_kubeapi_proxy_tpu.parallel.sharding import make_mesh
    from spicedb_kubeapi_proxy_tpu.spicedb import schema as sch
    from spicedb_kubeapi_proxy_tpu.spicedb.dispatch import BatchingEndpoint
    from spicedb_kubeapi_proxy_tpu.spicedb.types import SubjectRef
    from spicedb_kubeapi_proxy_tpu.utils import devtel

    devices = jax.devices()
    sizes = [n for n in (1, 2, 4) if n <= len(devices)]
    if len(devices) < 2:
        return {"skipped": True,
                "reason": f"mesh-scale needs >= 2 devices, have "
                          f"{len(devices)} (force a virtual mesh with "
                          f"XLA_FLAGS=--xla_force_host_platform_device_"
                          f"count=N)"}
    workload = wl.nested_groups(n_pods=4_000, n_users=1_000, n_groups=120,
                                n_teams=24, n_namespaces=60)
    schema = sch.parse_schema(workload.schema_text)
    batch = args.batch
    rounds = max(3, args.rounds // 2)
    subjects = workload.subjects
    modes = [("single", None)] + [(f"mesh-1x{n}", n) for n in sizes]

    def batch_subjects(r):
        return [SubjectRef("user", subjects[(r * batch + i) % len(subjects)])
                for i in range(batch)]

    async def one_round(ep, r):
        t0 = time.perf_counter()
        await ep.lookup_resources_batch(
            workload.resource_type, workload.permission, batch_subjects(r))
        return time.perf_counter() - t0

    eps: dict = {}
    shard_rows: dict = {}
    acc = {name: [] for name, _n in modes}
    for name, n in modes:
        stage(f"mesh-scale build + load + warm ({name})")
        before = dict(devtel.LEDGER.device_totals())
        mesh = (make_mesh(devices[:n], data=1, graph=n)
                if n is not None else None)
        ep = JaxEndpoint(schema, kernel="ell", mesh=mesh)
        ep.store.bulk_load_text("\n".join(workload.relationships))
        asyncio.run(one_round(ep, 0))  # warm: build + compiles + arenas
        after = devtel.LEDGER.device_totals()
        shard_rows[name] = {
            f"{kind}:d{dev}": b - before.get((kind, dev), 0)
            for (kind, dev), b in sorted(after.items())
            if b - before.get((kind, dev), 0) > 0}
        eps[name] = ep

    stage("mesh-scale interleaved rounds")
    for r in range(rounds):
        for name, _n in modes:
            acc[name].append(asyncio.run(one_round(eps[name], r + 1)))

    n_obj = len(eps["single"].store.object_ids_of_type(
        workload.resource_type))
    out: dict = {"devices": len(devices), "batch": batch, "rounds": rounds,
                 "basis": "measured", "modes": {}, "scaling": {}}
    single_med = statistics.median(acc["single"])
    for name, _n in modes:
        med = statistics.median(acc[name])
        out["modes"][name] = {
            "per_round_ms": round(med * 1e3, 2),
            "p99_ms": round(p99(acc[name]) * 1e3, 2),
            "checks_per_s": round(batch * n_obj / med, 1),
            "device_shard_bytes": shard_rows[name],
        }
        if name != "single":
            out["scaling"][name] = round(single_med / med, 3)

    # pipelined dispatch on the largest sharded graph: concurrent
    # per-subject lists must fan into overlapping fused batches (the
    # acceptance is overlap > 0 — the sharded kernels keep the PR 7
    # pipelined drain instead of degrading to serial dispatch)
    big = f"mesh-1x{sizes[-1]}"
    bep = BatchingEndpoint(eps[big], max_batch=max(8, batch // 8),
                           pipeline_depth=2)

    async def wave(r):
        await asyncio.gather(*[
            bep.lookup_resources(
                workload.resource_type, workload.permission,
                SubjectRef("user", subjects[(r + i) % len(subjects)]))
            for i in range(batch)])

    overlap = None
    for attempt in range(6):
        mark = timeline_mark()
        asyncio.run(wave(attempt))
        tl = timeline_summary(mark) or {}
        overlap = tl.get("overlap_ratio")
        if overlap:
            break
    out["pipelined_overlap_ratio"] = overlap
    out["mesh_scaling"] = out["scaling"].get(big, 0.0)
    log(f"mesh-scale: {out['scaling']} vs single-chip "
        f"(basis=measured, overlap={overlap})")
    return out


SCENARIO_CONFIGS = {
    "caveat-heavy": bench_scenario_caveat_heavy,
    "wildcard-public": bench_scenario_wildcard_public,
    "ephemeral-grants": bench_scenario_ephemeral_grants,
    "group-explosion": bench_scenario_group_explosion,
}

# device-resident pipeline A/B (ISSUE 7): same contract as CACHE_CONFIGS
PIPELINE_CONFIGS = {
    "pipeline-depth": bench_pipeline_depth,
}

# kernel introspection & regression sentinel (ISSUE 17): sweep-telemetry
# needs jax; cpu-microbench deliberately does NOT (it short-circuits in
# main() before the backend probe so the check.sh benchdiff gate stays
# fast and deterministic)
OBS_CONFIGS = {
    "sweep-telemetry": bench_sweep_telemetry,
    "cpu-microbench": bench_cpu_microbench,
}

# WAL-shipping replication scale-out (ISSUE 9): same contract
REPLICATION_CONFIGS = {
    "replica-scale": bench_replica_scale,
}

# partitioned write scale-out (ISSUE 15): same contract
SHARDING_CONFIGS = {
    "write-shard-scale": bench_write_shard_scale,
}

# multi-chip mesh execution (ISSUE 18): measured shard_map scaling on
# the local device set (virtual CPU mesh in CI, physical on TPU)
MESH_CONFIGS = {
    "mesh-scale": bench_mesh_scale,
}

# composed fleet topology (ISSUE 20): real multi-process fleets (shard
# leaders x follower fan-out trees x the CLI router) under open-loop
# load, via the shared harness (utils/topology.py) + scripts/
# fleet_bench.py.  The parent never imports jax (members run embedded
# endpoints), so these dispatch BEFORE the backend probe like
# cpu-microbench.  Excluded from --all like OBS_CONFIGS: a fleet boot
# is minutes of wall clock and its artifact is FLEET_rNN.json, not
# BENCH.  Values are fleet_bench.py section names.
FLEET_CONFIGS = {
    "fleet-read-scale": "read_scale",
    "fleet-write-scale": "write_scale",
    "fleet-chaos": "chaos",
    "fleet-topology": "full",
}

# decision-cache bench configs (ISSUE 3): run standalone via --config or
# appended to the --all sweep artifact
CACHE_CONFIGS = {
    "warm-repeat-list": bench_warm_repeat_list,
    "delta-churn": bench_delta_churn,
}

# durable-store bench configs (ISSUE 4): same contract as CACHE_CONFIGS
PERSIST_CONFIGS = {
    "recovery": bench_recovery,
}

CONFIGS = {
    "namespace-baseline": ("namespace_baseline", {}),
    "pods-depth1": ("pods_depth1", {}),
    "nested-groups-depth4": ("nested_groups", {}),
    "rbac-deny": ("rbac_deny", {}),
    "multitenant-1m": ("multitenant_1m", {}),
    # VERDICT r1 item 7: half the querying subjects have zero tuples; the
    # phantom-column path must show no cliff vs multitenant-1m
    "multitenant-1m-cold-users": ("multitenant_1m", {"cold_subjects": 0.5}),
    # VERDICT r3 item 5: caveat-heavy RBAC — tri-state bitplane path; must
    # be within ~10x of the definite rbac-deny throughput
    "caveats-rbac": ("caveated_rbac", {}),
}


def _config_registry() -> dict:
    """Every runnable --config, grouped; the source of truth for both
    validation and the unknown-config listing."""
    return {
        "workload sweep (CONFIGS)": list(CONFIGS),
        "decision cache": list(CACHE_CONFIGS),
        "durable store": list(PERSIST_CONFIGS),
        "device pipeline": list(PIPELINE_CONFIGS),
        "replication": list(REPLICATION_CONFIGS),
        "write sharding": list(SHARDING_CONFIGS),
        "multi-chip mesh": list(MESH_CONFIGS),
        "fleet topology": list(FLEET_CONFIGS),
        "scenario matrix": list(SCENARIO_CONFIGS),
        "observability": list(OBS_CONFIGS),
    }


def _reject_unknown_config(name: str) -> None:
    """Unknown --config: print the grouped registry and exit 2 (never a
    traceback — ISSUE 12 satellite)."""
    groups = _config_registry()
    if any(name in names for names in groups.values()):
        return
    print(f"bench.py: unknown --config {name!r}; registered configs:",
          file=sys.stderr)
    for group, names in groups.items():
        print(f"  {group}:", file=sys.stderr)
        for n in names:
            print(f"    {n}", file=sys.stderr)
    sys.exit(2)


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="multitenant-1m",
                    metavar="NAME",
                    help="one of the registered configs (an unknown "
                         "name prints the grouped registry and exits 2)")
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--rounds", type=int, default=10)
    ap.add_argument("--oracle-queries", type=int, default=2)
    ap.add_argument("--deadline", type=float,
                    default=float(os.environ.get("BENCH_DEADLINE_S", "2100")),
                    help="hard wall-clock cap; the JSON line is emitted "
                         "with partial results when it expires")
    ap.add_argument("--probe-timeout", type=float,
                    default=float(os.environ.get("BENCH_PROBE_TIMEOUT_S", "600")),
                    help="ONE long probe: PJRT init has been observed to "
                         "need >540s here; short retries are wasted time")
    ap.add_argument("--probe-attempts", type=int, default=1)
    ap.add_argument("--fresh-probe", action="store_true",
                    default=os.environ.get("BENCH_FRESH_PROBE", "") == "1",
                    help="ignore the cached probe verdict (env "
                         "BENCH_FRESH_PROBE=1); use after fixing the TPU "
                         "relay within the 30-min cache window")
    ap.add_argument("--no-fallback", action="store_true",
                    help="fail instead of falling back to CPU")
    ap.add_argument("--all", action="store_true", default=True,
                    help="run every config (the default since round 4: the "
                         "BENCH artifact must carry the whole BASELINE "
                         "sweep); headline metric stays the default config")
    ap.add_argument("--single", dest="all", action="store_false",
                    help="headline config only (smoke runs)")
    ap.add_argument("--no-cold-users", action="store_true",
                    help="skip the cold-users side-measurement")
    ap.add_argument("--direct-only", action="store_true",
                    help="headline = direct batched call instead of the "
                         "concurrent dispatcher path")
    ap.add_argument("--baseline", default="", metavar="ARTIFACT",
                    help="compare this run's artifact against a prior "
                         "bench JSON via scripts/benchdiff.py and exit "
                         "with its verdict (0 ok, 1 regression); "
                         "currently honored by --config cpu-microbench")
    ap.add_argument("--replica-worker", default="", help=argparse.SUPPRESS)
    ap.add_argument("--shard-worker", default="", help=argparse.SUPPRESS)
    args = ap.parse_args()
    _reject_unknown_config(args.config)

    if args.replica_worker:
        # replica-scale follower subprocess: no probe, no watchdog —
        # the parent bench owns the lifecycle (see replica_worker)
        replica_worker(args.replica_worker)
        return
    if args.shard_worker:
        # write-shard-scale shard-leader subprocess: same contract
        shard_leader_worker(args.shard_worker)
        return

    start_watchdog(args.deadline)

    if args.config == "cpu-microbench":
        # perf-regression sentinel config: pure python, runs BEFORE the
        # backend probe / jax import so the check.sh benchdiff gate is
        # fast, deterministic, and immune to device bring-up weather
        stage("cpu-microbench (no jax)")
        _STATE["metric"] = "cpu-microbench"
        res = bench_cpu_microbench(args)
        payload = {
            "metric": "cpu-microbench",
            "value": res["configs"]["dispatch-check"]["median_s"],
            "unit": "s/round", "platform": "cpu-python",
            "baseline": "committed benchdiff baseline artifact "
                        "(scripts/benchdiff_baseline.json)",
            **res}
        emit(payload)
        if args.baseline:
            import importlib.util
            spec = importlib.util.spec_from_file_location(
                "benchdiff",
                os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "scripts", "benchdiff.py"))
            bd = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(bd)
            with open(args.baseline) as f:
                base = json.load(f)
            verdict = bd.compare(base, payload)
            bd.print_report(verdict, file=sys.stderr)
            sys.exit(1 if verdict["regressions"] else 0)
        return

    if args.config in FLEET_CONFIGS:
        # composed-fleet config: multi-process members, no jax in the
        # parent — dispatch before the backend probe (cpu-microbench
        # precedent) and delegate to the fleet_bench section runner
        stage(f"fleet config {args.config} (multi-process, no jax in "
              f"parent)")
        import importlib.util
        spec = importlib.util.spec_from_file_location(
            "fleet_bench",
            os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "scripts", "fleet_bench.py"))
        fb = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(fb)
        _STATE["metric"] = f"fleet {args.config}"
        res = fb.run_section(FLEET_CONFIGS[args.config])
        emit({"metric": _STATE["metric"],
              "value": res.get("headline", 0.0),
              "unit": res.get("headline_unit", "x"),
              "platform": "cpu-multiprocess",
              "baseline": "smallest fleet of the same shape under the "
                          "same open-loop schedule (paired rounds)",
              **res})
        return

    path_desc = (f"{args.batch}-subject direct batched call"
                 if args.direct_only else
                 f"{args.batch} concurrent list requests, batched dispatch")
    _STATE["metric"] = f"authz checks/sec ({args.config}, {path_desc})"

    # -- backend selection, BEFORE importing jax in this process ------------
    cpu_requested = os.environ.get("JAX_PLATFORMS", "") == "cpu"
    platform = probe_backend(args.probe_timeout, args.probe_attempts,
                             fresh=args.fresh_probe)
    if platform == "cpu":
        if args.no_fallback and not cpu_requested:
            emit_error("TPU backend unavailable and --no-fallback set")
            return
        os.environ["JAX_PLATFORMS"] = "cpu"
        _STATE["platform"] = "cpu" if cpu_requested else "cpu-fallback"
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

    stage("jax import + device init")
    import jax
    if platform == "cpu":
        jax.config.update("jax_platforms", "cpu")
    devs = jax.devices()
    _STATE.setdefault("platform", devs[0].platform)
    log(f"devices: {devs}")

    warmup_tiny()

    if args.config in CACHE_CONFIGS:
        # standalone decision-cache config: its own headline metric
        stage(f"cache config {args.config}")
        tel_before = devtel_snapshot()
        tl_mark = timeline_mark()
        res = CACHE_CONFIGS[args.config](args)
        tel = devtel_delta(tel_before)
        if tel:
            res["device_telemetry"] = tel
        tl_sum = timeline_summary(tl_mark)
        if tl_sum:
            res["timeline_summary"] = tl_sum
        res.update(timeline_headline(tl_sum))
        value = (res.get("cache_on_checks_per_s")
                 or res.get("lists_per_s", 0.0))
        _STATE["metric"] = f"decision-cache {args.config}"
        emit({"metric": _STATE["metric"], "value": value,
              "unit": ("checks/s" if "cache_on_checks_per_s" in res
                       else "lists/s"),
              "platform": _STATE["platform"],
              "baseline": "cache-off proxy chain", **res})
        return

    if args.config in PIPELINE_CONFIGS:
        # standalone pipeline A/B: depth-2 checks/s is the headline
        # value, the gate-off host-pack serial path is the baseline
        stage(f"pipeline config {args.config}")
        tel_before = devtel_snapshot()
        res = PIPELINE_CONFIGS[args.config](args)
        tel = devtel_delta(tel_before)
        if tel:
            res["device_telemetry"] = tel
        _STATE["metric"] = f"device-pipeline {args.config}"
        d2 = res.get("modes", {}).get("depth-2", {})
        emit({"metric": _STATE["metric"],
              "value": d2.get("checks_per_s", 0.0), "unit": "checks/s",
              "platform": _STATE["platform"],
              "baseline": "DevicePipeline gate off (host-pack serial "
                          "dispatch, the pre-PR path)",
              **res})
        return

    if args.config in OBS_CONFIGS:
        # kernel-introspection A/B: the headline value is the telemetry
        # overhead (acceptance: within noise), the gate-off byte-
        # identical jits are the baseline
        stage(f"observability config {args.config}")
        tel_before = devtel_snapshot()
        res = OBS_CONFIGS[args.config](args)
        tel = devtel_delta(tel_before)
        if tel:
            res["device_telemetry"] = tel
        _STATE["metric"] = f"kernel-introspection {args.config}"
        emit({"metric": _STATE["metric"],
              "value": res.get("overhead_pct", 0.0), "unit": "%",
              "platform": _STATE["platform"],
              "baseline": "KernelIntrospect gate off (byte-identical "
                          "pre-introspection jits, interleaved rounds)",
              **res})
        return

    if args.config in REPLICATION_CONFIGS:
        # standalone replication config: 2-follower read scaling is the
        # headline, single-follower aggregate is the baseline
        stage(f"replication config {args.config}")
        tel_before = devtel_snapshot()
        res = REPLICATION_CONFIGS[args.config](args)
        tel = devtel_delta(tel_before)
        if tel:
            res["device_telemetry"] = tel
        _STATE["metric"] = f"replication {args.config}"
        emit({"metric": _STATE["metric"],
              "value": res.get("replica_read_scaling", 0.0), "unit": "x",
              "platform": _STATE["platform"],
              "baseline": "single follower aggregate filtered-list "
                          "throughput (same churn, same graph)",
              **res})
        return

    if args.config in SHARDING_CONFIGS:
        # standalone write-sharding config: 2-shard write scaling is the
        # headline, single shard-leader aggregate is the baseline
        stage(f"sharding config {args.config}")
        tel_before = devtel_snapshot()
        res = SHARDING_CONFIGS[args.config](args)
        tel = devtel_delta(tel_before)
        if tel:
            res["device_telemetry"] = tel
        _STATE["metric"] = f"write-sharding {args.config}"
        emit({"metric": _STATE["metric"],
              "value": res.get("write_shard_scaling", 0.0), "unit": "x",
              "platform": _STATE["platform"],
              "baseline": "single shard-leader aggregate dual-write "
                          "throughput (same churn profile, same "
                          "per-process core budget)",
              **res})
        return

    if args.config in MESH_CONFIGS:
        # standalone mesh config: largest-mesh paired scaling vs the
        # single-chip kernels is the headline (measured, not projected)
        stage(f"mesh config {args.config}")
        tel_before = devtel_snapshot()
        res = MESH_CONFIGS[args.config](args)
        tel = devtel_delta(tel_before)
        if tel:
            res["device_telemetry"] = tel
        _STATE["metric"] = f"mesh {args.config}"
        emit({"metric": _STATE["metric"],
              "value": res.get("mesh_scaling", 0.0), "unit": "x",
              "platform": _STATE["platform"],
              "baseline": "single-chip ell kernels over the same store "
                          "(interleaved rounds, same batches)",
              **res})
        return

    if args.config in SCENARIO_CONFIGS:
        # standalone scenario config: refereed divergences must be 0;
        # the headline value is the device check throughput under churn
        stage(f"scenario config {args.config}")
        tel_before = devtel_snapshot()
        tl_mark = timeline_mark()
        res = SCENARIO_CONFIGS[args.config](args)
        tel = devtel_delta(tel_before)
        if tel:
            res["device_telemetry"] = tel
        tl_sum = timeline_summary(tl_mark)
        if tl_sum:
            res["timeline_summary"] = tl_sum
        res.update(timeline_headline(tl_sum))
        _STATE["metric"] = f"scenario {args.config}"
        emit({"metric": _STATE["metric"],
              "value": res.get("checks_per_s", 0.0), "unit": "checks/s",
              "platform": _STATE["platform"],
              "baseline": "host-oracle referee over the same store "
                          "(parity acceptance: divergences == 0)",
              **res})
        return

    if args.config in PERSIST_CONFIGS:
        # standalone durable-store config: time-to-serve after restart
        stage(f"persist config {args.config}")
        tel_before = devtel_snapshot()
        tl_mark = timeline_mark()
        res = PERSIST_CONFIGS[args.config](args)
        tel = devtel_delta(tel_before)
        if tel:
            res["device_telemetry"] = tel
        tl_sum = timeline_summary(tl_mark)
        if tl_sum:
            res["timeline_summary"] = tl_sum
        res.update(timeline_headline(tl_sum))
        _STATE["metric"] = f"durable-store {args.config}"
        emit({"metric": _STATE["metric"],
              "value": res.get("time_to_serve_s", 0.0), "unit": "s",
              "platform": _STATE["platform"],
              "baseline": "in-memory proxy (full bootstrap re-ingest "
                          "on every restart, post-bootstrap writes lost)",
              **res})
        return

    from spicedb_kubeapi_proxy_tpu.models import workloads as wl

    def load_workload(name):
        fn_name, kw = CONFIGS[name]
        workload = getattr(wl, fn_name)(**kw)
        log(f"== config {name}: {len(workload.relationships)} tuples, "
            f"{len(workload.subjects)} subjects ==")
        return workload

    def run_one(name, with_oracle=True, rounds=None):
        workload = load_workload(name)
        tel_before = devtel_snapshot()
        tl_mark = timeline_mark()
        r = rounds if rounds is not None else args.rounds
        if args.direct_only:
            head = bench_jax(workload, args.batch, r)
            direct = head
        else:
            head = bench_concurrent(workload, args.batch, r)
            # re-use the already-built+compiled endpoint for the direct run
            direct = bench_jax(workload, args.batch, max(3, r // 2),
                               ep=head["endpoint"])
        log(f"{name} (dispatcher): {head['checks_per_s']:.3g} checks/s "
            f"({head['per_batch_s'] * 1000:.1f} ms / {args.batch} requests, "
            f"p99 {head['p99_s'] * 1000:.1f} ms)")
        log(f"{name} direct batch: {direct['checks_per_s']:.3g} checks/s "
            f"({direct['per_batch_s'] * 1000:.1f} ms, "
            f"p99 {direct['p99_s'] * 1000:.1f} ms)")
        # end-of-run device-telemetry snapshot rides the artifact for
        # EVERY config (HBM peak, recompiles, occupancy, per-bucket
        # kernel time), so BENCH_r*.json carries device numbers
        # alongside throughput
        tel = devtel_delta(tel_before)
        tl_sum = timeline_summary(tl_mark)
        if tl_sum:
            log(f"{name} timeline: overlap={tl_sum.get('overlap_ratio')} "
                f"roofline={tl_sum.get('roofline_fraction')} "
                f"stalls_s={tl_sum.get('stall_s')}")
        if name == args.config:
            # watchdog partials must only ever carry the headline config's
            # numbers — a sweep config's value under the headline metric
            # label would misattribute the workload
            _STATE["partial"].update({
                "value": round(head["checks_per_s"], 1),
                "p99_list_filter_ms": round(head["p99_s"] * 1000, 2),
                "direct_batch_checks_per_s": round(direct["checks_per_s"], 1),
                **({"device_telemetry": tel} if tel else {}),
                **({"timeline_summary": tl_sum} if tl_sum else {}),
                **timeline_headline(tl_sum),
            })
        else:
            # sweep numbers land in the artifact too (VERDICT r3 item 3)
            _STATE["partial"].setdefault("configs", {})[name] = {
                "checks_per_s": round(head["checks_per_s"], 1),
                "p99_ms": round(head["p99_s"] * 1000, 2),
                "direct_checks_per_s": round(direct["checks_per_s"], 1),
                "objects": head["objects"],
                **({"device_telemetry": tel} if tel else {}),
                **({"timeline_summary": tl_sum} if tl_sum else {}),
                **timeline_headline(tl_sum),
            }
        oracle_res = None
        if with_oracle:
            oracle_res = bench_oracle(workload, args.oracle_queries)
            log(f"oracle: {oracle_res['checks_per_s']:.3g} checks/s"
                f" ({oracle_res['per_query_s'] * 1000:.1f} ms / query)")
        return workload, head, direct, oracle_res

    cold_users_planned = (args.config == "multitenant-1m"
                          and not args.no_cold_users)

    # headline FIRST: if the watchdog fires mid-sweep, the partial payload
    # already carries the headline numbers (VERDICT r3 item 3 reordering)
    workload, head, direct, oracle_res = run_one(args.config)
    speedup = head["checks_per_s"] / max(oracle_res["checks_per_s"], 1e-9)
    payload = {
        "metric": _STATE["metric"],
        "value": round(head["checks_per_s"], 1),
        "unit": "checks/s",
        "vs_baseline": round(speedup, 2),
        "p99_list_filter_ms": round(head["p99_s"] * 1000, 2),
        "platform": _STATE["platform"],
        "objects": head["objects"],
        "batch": args.batch,
        "fused_lookups": head.get("fused_lookups"),
        "direct_batch_checks_per_s": round(direct["checks_per_s"], 1),
        "direct_batch_p99_ms": round(direct["p99_s"] * 1000, 2),
        "oracle_checks_per_s": round(oracle_res["checks_per_s"], 1),
        "baseline": "python-oracle",
        "baseline_note": BASELINE_NOTE,
    }
    if _STATE["partial"].get("device_telemetry"):
        payload["device_telemetry"] = _STATE["partial"]["device_telemetry"]
    if _STATE["partial"].get("timeline_summary"):
        # headline dispatch-timeline condensate: overlap fraction,
        # modeled roofline fraction, stall breakdown, worst dispatch
        payload["timeline_summary"] = _STATE["partial"]["timeline_summary"]
        payload.update(timeline_headline(payload["timeline_summary"]))
    # dispatcher overhead = headline round time minus the bare device batch
    payload["latency_breakdown_ms"] = {
        "dispatcher_round": round(head["per_batch_s"] * 1e3, 2),
        "direct_batch": round(direct["per_batch_s"] * 1e3, 2),
        "dispatcher_overhead": round(
            (head["per_batch_s"] - direct["per_batch_s"]) * 1e3, 2),
    }

    # roofline accounting on the headline endpoint (VERDICT r3 item 4).
    # The probe's extra device round-trips can WEDGE the TPU tunnel for
    # minutes (documented tunnel behavior); run it on a daemon thread
    # with a hard join so a wedge costs bounded time and the sweep still
    # happens.
    def bounded_roofline(ep, wl, batch, timeout_s=240.0):
        box: dict = {}

        def run():
            try:
                box["out"] = roofline_probe(ep, wl, batch)
            except Exception as e:  # noqa: BLE001 — recorded, not raised
                box["out"] = {"error": repr(e)}

        t = threading.Thread(target=run, daemon=True)
        t.start()
        t.join(timeout_s)
        if t.is_alive():
            # the abandoned thread may still be blocked on the device;
            # everything measured after this point contends with it —
            # flag it so the artifact's sweep numbers carry the caveat
            _STATE["partial"]["roofline_probe_abandoned"] = True
            return {"error": f"probe exceeded {timeout_s:.0f}s "
                             f"(tunnel wedge?); skipped — an abandoned "
                             f"probe thread may contend with subsequent "
                             f"sweep measurements"}
        return box.get("out", {"error": "probe produced no result"})

    ep_head = head.get("endpoint") or direct.get("endpoint")
    if ep_head is not None:
        try:
            stage("roofline probe")
            payload["roofline"] = bounded_roofline(ep_head, workload,
                                                   args.batch)
            payload["latency_breakdown_ms"].update({
                k: payload["roofline"][k]
                for k in ("device_time_ms", "transfer_est_ms",
                          "id_materialize_sample_ms")
                if k in payload["roofline"]})
            log(f"roofline: {payload['roofline']}")
        except Exception as e:
            log(f"roofline probe failed: {e!r}")
            payload["roofline"] = {"error": repr(e)}
        try:
            payload["sharded_comm_model"] = sharded_comm_model(
                ep_head, workload, args.batch)
        except Exception as e:
            payload["sharded_comm_model"] = {"error": repr(e)}
        try:
            payload["v5e8_projection"] = v5e8_projection(
                ep_head, workload, args.batch,
                payload.get("roofline", {}))
        except Exception as e:
            payload["v5e8_projection"] = {"error": repr(e)}
        if _STATE["partial"].get("roofline_probe_abandoned"):
            payload["roofline_probe_abandoned"] = True
        ep_head = None  # release: the pops below are no-ops while this lives

    # -- sweep: every other config, fewer rounds, no oracle ------------------
    if args.all:
        # drop the headline endpoint before the sweep so its (possibly
        # 1M-tuple) graph doesn't stay live while sweep graphs build;
        # each sweep run's endpoint is scoped to its run_one call
        head.pop("endpoint", None)
        direct.pop("endpoint", None)
        for name in CONFIGS:
            if name == args.config:
                continue
            if name == "multitenant-1m-cold-users" and cold_users_planned:
                continue  # measured once, as the side-measurement below
            try:
                run_one(name, with_oracle=False,
                        rounds=max(3, args.rounds // 2))
            except Exception as e:  # keep the headline alive
                log(f"config {name} failed: {e!r}")
                _STATE["partial"].setdefault("configs", {})[name] = {
                    "error": repr(e)}
        # decision-cache + durable-store configs ride the sweep artifact
        # too (hit rate, on/off speedup, churn divergences, and the
        # restart time-to-serve + WAL write-overhead columns)
        for name, fn in {**CACHE_CONFIGS, **PERSIST_CONFIGS,
                         **PIPELINE_CONFIGS, **REPLICATION_CONFIGS,
                         **SHARDING_CONFIGS, **MESH_CONFIGS,
                         **SCENARIO_CONFIGS}.items():
            try:
                tel_before = devtel_snapshot()
                tl_mark = timeline_mark()
                res = fn(args)
                tel = devtel_delta(tel_before)
                if tel:
                    res["device_telemetry"] = tel
                tl_sum = timeline_summary(tl_mark)
                if tl_sum:
                    res["timeline_summary"] = tl_sum
                res.update(timeline_headline(tl_sum))
                _STATE["partial"].setdefault("configs", {})[name] = res
            except Exception as e:
                log(f"config {name} failed: {e!r}")
                _STATE["partial"].setdefault("configs", {})[name] = {
                    "error": repr(e)}
        payload["configs"] = _STATE["partial"].get("configs", {})
        # caveat-path health: within ~10x of the definite rbac path
        # (the headline config's number lives in payload["value"], not
        # the sweep table — read whichever slot holds each config)
        cfgs = payload["configs"]

        def value_of(name):
            if name == args.config:
                return payload["value"]
            return cfgs.get(name, {}).get("checks_per_s")

        definite, caveated = value_of("rbac-deny"), value_of("caveats-rbac")
        if definite and caveated:
            ratio = definite / max(caveated, 1e-9)
            payload["definite_over_caveated_ratio"] = round(ratio, 2)
            log(f"definite/caveated throughput ratio: {ratio:.2f} "
                f"(target <~10)")

    # VERDICT r2 item 9: measure the cold-users config (50% of querying
    # subjects have zero tuples) and record the warm/cold ratio — the
    # phantom-column path must show no cliff.
    if cold_users_planned:
        try:
            # free the warm 1M graph before building the cold one — holding
            # both doubles peak memory for nothing
            head.pop("endpoint", None)
            direct.pop("endpoint", None)
            cold_wl = load_workload("multitenant-1m-cold-users")
            cold = bench_jax(cold_wl, args.batch, max(3, args.rounds // 2))
            cold.pop("endpoint", None)
            ratio = direct["per_batch_s"] / max(cold["per_batch_s"], 1e-9)
            log(f"cold-users: {cold['checks_per_s']:.3g} checks/s "
                f"(warm/cold per-batch ratio {ratio:.2f}; "
                f"1.0 = no cliff)")
            payload["cold_users_checks_per_s"] = round(cold["checks_per_s"], 1)
            payload["cold_users_p99_ms"] = round(cold["p99_s"] * 1000, 2)
            payload["warm_over_cold_batch_time"] = round(ratio, 3)
        except Exception as e:
            log(f"cold-users run failed: {e!r}")
            payload["cold_users_error"] = repr(e)

    if "tpu_probe" in _STATE:
        payload["tpu_probe"] = _STATE["tpu_probe"]
    emit(payload)


if __name__ == "__main__":
    try:
        main()
    except SystemExit:
        raise
    except BaseException as e:  # never die without the JSON line
        import traceback
        traceback.print_exc(file=sys.stderr)
        emit_error(f"{type(e).__name__}: {e}")
