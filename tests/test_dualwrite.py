"""Dual-write workflow engine tests: e2e writes through the proxy, the
failpoint crash matrix under both lock modes, rollback completeness,
idempotent retry, lock mutual exclusion, and journal-based crash recovery
(reference e2e/proxy_test.go:459-1290 dual-write scenarios and
distributedtx/workflow_test.go)."""

import asyncio
import json
import os
import tempfile

import pytest

from spicedb_kubeapi_proxy_tpu.kubefake.apiserver import FakeKubeApiServer
from spicedb_kubeapi_proxy_tpu.proxy.httpcore import HandlerTransport
from spicedb_kubeapi_proxy_tpu.proxy.server import Options, ProxyServer
from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import Bootstrap
from spicedb_kubeapi_proxy_tpu.spicedb.types import (
    RelationshipFilter,
    parse_relationship,
)
from spicedb_kubeapi_proxy_tpu.utils import failpoints

SCHEMA = """
definition user {}
definition cluster {}
definition namespace {
  relation cluster: cluster
  relation creator: user
  relation viewer: user
  permission view = viewer + creator
}
definition pod {
  relation namespace: namespace
  relation creator: user
  permission view = creator
}
definition lock { relation workflow: workflow }
definition workflow { relation idempotency_key: activity with expiration }
definition activity {}
"""

RULES_TEMPLATE = """
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {{name: create-namespaces}}
lock: {lock_mode}
match: [{{apiVersion: v1, resource: namespaces, verbs: [create]}}]
update:
  preconditionDoesNotExist:
  - tpl: "namespace:{{{{name}}}}#cluster@cluster:cluster"
  creates:
  - tpl: "namespace:{{{{name}}}}#creator@user:{{{{user.name}}}}"
  - tpl: "namespace:{{{{name}}}}#cluster@cluster:cluster"
---
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {{name: delete-namespaces}}
lock: {lock_mode}
match: [{{apiVersion: v1, resource: namespaces, verbs: [delete]}}]
update:
  deletes:
  - tpl: "namespace:{{{{name}}}}#creator@user:{{{{user.name}}}}"
  - tpl: "namespace:{{{{name}}}}#cluster@cluster:cluster"
---
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {{name: create-pods}}
lock: {lock_mode}
match: [{{apiVersion: v1, resource: pods, verbs: [create]}}]
update:
  creates:
  - tpl: "pod:{{{{namespacedName}}}}#creator@user:{{{{user.name}}}}"
---
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {{name: delete-pods-by-filter}}
lock: {lock_mode}
match: [{{apiVersion: v1, resource: pods, verbs: [delete]}}]
update:
  deleteByFilter:
  - tpl: "pod:{{{{namespacedName}}}}#$resourceRelation@$subjectType:$subjectID"
---
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {{name: get-namespaces}}
match: [{{apiVersion: v1, resource: namespaces, verbs: [get]}}]
check: [{{tpl: "namespace:{{{{name}}}}#view@user:{{{{user.name}}}}"}}]
"""


def make_proxy(lock_mode="Pessimistic", db_path=""):
    kube = FakeKubeApiServer()
    proxy = ProxyServer(Options(
        spicedb_endpoint="embedded://",
        bootstrap=Bootstrap(schema_text=SCHEMA),
        rules_yaml=RULES_TEMPLATE.format(lock_mode=lock_mode),
        upstream_transport=HandlerTransport(kube),
        workflow_database_path=db_path,
    ))
    proxy.enable_dual_writes()
    return proxy, kube


def run(coro):
    return asyncio.run(coro)


@pytest.fixture(autouse=True)
def reset_failpoints():
    failpoints.disable_all()
    yield
    failpoints.disable_all()


def store_rels(proxy, resource_type=""):
    flt = RelationshipFilter(resource_type=resource_type) if resource_type else None
    return {r.rel_string() for r in proxy.endpoint.store.read(flt)}


class TestDualWriteHappyPath:
    @pytest.mark.parametrize("lock_mode", ["Pessimistic", "Optimistic"])
    def test_create_namespace(self, lock_mode):
        proxy, kube = make_proxy(lock_mode)
        alice = proxy.get_embedded_client(user="alice")

        async def go():
            resp = await alice.post("/api/v1/namespaces",
                                    {"metadata": {"name": "team-x"}})
            assert resp.status == 201, resp.body
            assert "team-x" in kube.objects[("", "v1", "namespaces")][""]
            assert "namespace:team-x#creator@user:alice" in store_rels(proxy, "namespace")
            assert "namespace:team-x#cluster@cluster:cluster" in store_rels(proxy, "namespace")
            # lock removed, no stray workflow state
            assert store_rels(proxy, "lock") == set()
            # the creator can now read it back through the proxy
            assert (await alice.get("/api/v1/namespaces/team-x")).status == 200
        run(go())

    @pytest.mark.parametrize("lock_mode", ["Pessimistic", "Optimistic"])
    def test_delete_namespace(self, lock_mode):
        proxy, kube = make_proxy(lock_mode)
        alice = proxy.get_embedded_client(user="alice")

        async def go():
            assert (await alice.post("/api/v1/namespaces",
                                     {"metadata": {"name": "doomed"}})).status == 201
            resp = await alice.delete("/api/v1/namespaces/doomed")
            assert resp.status == 200, resp.body
            assert "doomed" not in kube.objects.get(("", "v1", "namespaces"), {}).get("", {})
            assert store_rels(proxy, "namespace") == set()
        run(go())

    def test_precondition_conflict(self):
        proxy, kube = make_proxy()
        alice = proxy.get_embedded_client(user="alice")

        async def go():
            assert (await alice.post("/api/v1/namespaces",
                                     {"metadata": {"name": "dup"}})).status == 201
            # second create: preconditionDoesNotExist now fails -> kube 409
            resp = await alice.post("/api/v1/namespaces",
                                    {"metadata": {"name": "dup"}})
            assert resp.status == 409, resp.body
            body = json.loads(resp.body)
            assert body["reason"] == "Conflict"
        run(go())

    def test_delete_by_filter(self):
        proxy, kube = make_proxy()
        alice = proxy.get_embedded_client(user="alice")

        async def go():
            assert (await alice.post("/api/v1/namespaces/ns/pods",
                                     {"metadata": {"name": "p1", "namespace": "ns"}})).status == 201
            assert "pod:ns/p1#creator@user:alice" in store_rels(proxy, "pod")
            resp = await alice.delete("/api/v1/namespaces/ns/pods/p1")
            assert resp.status == 200, resp.body
            assert store_rels(proxy, "pod") == set()
        run(go())


FAILPOINT_MATRIX = [
    "panicWriteSpiceDB",
    "panicSpiceDBWriteResp",
    "panicKubeWrite",
    "panicKubeReadResp",
]


class TestCrashMatrix:
    @pytest.mark.parametrize("lock_mode", ["Pessimistic", "Optimistic"])
    @pytest.mark.parametrize("failpoint", FAILPOINT_MATRIX)
    def test_create_survives_crash(self, lock_mode, failpoint):
        """A crash at any activity site must not lose the dual write: after
        journal replay both SpiceDB and kube converge (reference
        proxy_test.go crash-recovery matrix)."""
        proxy, kube = make_proxy(lock_mode)
        alice = proxy.get_embedded_client(user="alice")

        async def go():
            failpoints.enable_failpoint(failpoint, 1)
            resp = await alice.post("/api/v1/namespaces",
                                    {"metadata": {"name": "crashy"}})
            # a crash after the kube write landed (panicKubeReadResp) loses
            # the original 201; the replayed POST gets 409 AlreadyExists,
            # which the workflow treats as converged (reference
            # workflow.go:274-276) — state must be consistent either way
            assert resp.status in (201, 409), (failpoint, lock_mode,
                                               resp.status, resp.body)
            assert "crashy" in kube.objects[("", "v1", "namespaces")][""]
            rels = store_rels(proxy, "namespace")
            assert "namespace:crashy#creator@user:alice" in rels, (failpoint, rels)
            assert store_rels(proxy, "lock") == set()
        run(go())

    @pytest.mark.parametrize("failpoint", ["panicReadSpiceDB",
                                           "panicSpiceDBReadResp"])
    def test_delete_by_filter_survives_crash(self, failpoint):
        proxy, kube = make_proxy()
        alice = proxy.get_embedded_client(user="alice")

        async def go():
            assert (await alice.post("/api/v1/namespaces/ns/pods",
                                     {"metadata": {"name": "p1", "namespace": "ns"}})).status == 201
            failpoints.enable_failpoint(failpoint, 1)
            resp = await alice.delete("/api/v1/namespaces/ns/pods/p1")
            assert resp.status == 200, resp.body
            assert store_rels(proxy, "pod") == set()
        run(go())

    def test_repeated_crashes_converge(self):
        proxy, kube = make_proxy()
        alice = proxy.get_embedded_client(user="alice")

        async def go():
            failpoints.enable_failpoint("panicKubeWrite", 3)
            resp = await alice.post("/api/v1/namespaces",
                                    {"metadata": {"name": "stubborn"}})
            assert resp.status == 201, resp.body
            assert "stubborn" in kube.objects[("", "v1", "namespaces")][""]
        run(go())


class TestLocking:
    def test_lock_mutual_exclusion(self):
        """A held lock for the same (path, name, verb) forces a 409
        (ownership-stealing prevention)."""
        from spicedb_kubeapi_proxy_tpu.authz.distributedtx.workflow import (
            resource_lock_rel,
        )
        proxy, kube = make_proxy()
        alice = proxy.get_embedded_client(user="alice")

        async def go():
            lock_tmpl = resource_lock_rel({
                "request_path": "/api/v1/namespaces",
                "object_name": "contested", "verb": "create"})
            held = lock_tmpl["rel"].replace("{workflow_id}", "other-workflow")
            proxy.endpoint.store.bulk_load([parse_relationship(held)])
            resp = await alice.post("/api/v1/namespaces",
                                    {"metadata": {"name": "contested"}})
            assert resp.status == 409, resp.body
            # rollback: no partial tuples
            assert "namespace:contested#creator@user:alice" not in store_rels(proxy)
            assert "contested" not in kube.objects.get(("", "v1", "namespaces"), {}).get("", {})
        run(go())

    def test_rollback_on_kube_rejection(self):
        """A definitively-failed kube write rolls the SpiceDB writes back."""
        proxy, kube = make_proxy()
        alice = proxy.get_embedded_client(user="alice")

        async def go():
            # invalid object: fake apiserver 422s (metadata.name required)
            resp = await alice.post("/api/v1/namespaces", {"metadata": {}})
            assert resp.status == 403  # middleware: template resolution fails
        run(go())


class TestJournalRecovery:
    def test_resume_from_sqlite_after_restart(self):
        """A pending instance in the SQLite journal resumes on a fresh
        engine: already-journaled activities are replayed, the rest run."""
        from spicedb_kubeapi_proxy_tpu.authz.distributedtx.client import (
            setup_workflow_engine,
        )
        from spicedb_kubeapi_proxy_tpu.authz.distributedtx.workflow import (
            STRATEGY_PESSIMISTIC,
        )
        with tempfile.TemporaryDirectory() as tmp:
            db = os.path.join(tmp, "dtx.sqlite")
            proxy, kube = make_proxy(db_path=db)

            write_input = {
                "verb": "create", "request_uri": "/api/v1/namespaces",
                "request_path": "/api/v1/namespaces", "request_name": "",
                "api_group": "", "resource": "namespaces", "headers": {},
                "user_name": "alice", "object_name": "revived",
                "body": json.dumps({"metadata": {"name": "revived"}}),
                "probe_uri": "/api/v1/namespaces/revived",
                "creates": ["namespace:revived#creator@user:alice"],
                "touches": [], "deletes": [], "preconditions": [],
                "delete_by_filter": [],
            }

            async def crashed_process():
                # "crash before the worker ran": instance persisted, nothing
                # executed
                proxy.workflow_client.journal.create_instance(
                    "inst-1", STRATEGY_PESSIMISTIC, write_input)
            run(crashed_process())

            async def restarted_process():
                engine, worker = setup_workflow_engine(
                    proxy.endpoint, HandlerTransport(kube), db)
                count = await engine.run_pending_once()
                assert count == 1
                rec = engine.journal.get_instance("inst-1")
                assert rec.status == "completed", rec.error
                assert rec.result["status_code"] == 201
                assert "revived" in kube.objects[("", "v1", "namespaces")][""]
                assert ("namespace:revived#creator@user:alice"
                        in store_rels(proxy, "namespace"))
            run(restarted_process())

    def test_replay_does_not_duplicate_side_effects(self):
        """Journaled activities are not re-executed on replay."""
        from spicedb_kubeapi_proxy_tpu.authz.distributedtx.client import (
            setup_workflow_engine,
        )
        from spicedb_kubeapi_proxy_tpu.authz.distributedtx.workflow import (
            STRATEGY_PESSIMISTIC,
        )
        proxy, kube = make_proxy()
        engine = proxy.workflow_client
        calls = {"spicedb": 0, "kube": 0}
        orig_spicedb = engine._activities["write_to_spicedb"]
        orig_kube = engine._activities["write_to_kube"]

        async def counting_spicedb(*a):
            calls["spicedb"] += 1
            return await orig_spicedb(*a)

        async def counting_kube(*a):
            calls["kube"] += 1
            return await orig_kube(*a)

        engine.register_activity("write_to_spicedb", counting_spicedb)
        engine.register_activity("write_to_kube", counting_kube)

        async def go():
            failpoints.enable_failpoint("panicKubeReadResp", 1)
            alice = proxy.get_embedded_client(user="alice")
            resp = await alice.post("/api/v1/namespaces",
                                    {"metadata": {"name": "once"}})
            # crash after the kube write landed: replayed POST sees 409
            assert resp.status in (201, 409), resp.body
            assert "once" in kube.objects[("", "v1", "namespaces")][""]
            # the journaled spicedb write ran exactly once (replayed from the
            # journal, not re-executed); the kube write re-ran because the
            # crash hit mid-activity (at-least-once)
            assert calls["spicedb"] == 1 + 1  # initial write + lock cleanup
            assert calls["kube"] == 2  # crashed attempt + replay
        run(go())


class TestIdempotencyKeys:
    def test_duplicate_spicedb_write_treated_as_success(self):
        """After a crash post-write, the CREATE retry hits AlreadyExists but
        the idempotency key proves the write landed (activity.go:62-74)."""
        proxy, kube = make_proxy()
        alice = proxy.get_embedded_client(user="alice")

        async def go():
            failpoints.enable_failpoint("panicSpiceDBWriteResp", 1)
            resp = await alice.post("/api/v1/namespaces",
                                    {"metadata": {"name": "idem"}})
            assert resp.status == 201, resp.body
            rels = store_rels(proxy, "namespace")
            assert "namespace:idem#creator@user:alice" in rels
        run(go())
