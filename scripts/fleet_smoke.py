#!/usr/bin/env python
"""Fleet topology smoke: the CI-sized proof that the ISSUE 20 stack
works end to end (docs/performance.md "Fleet topology bench").

Boots the smallest interesting fleet — fake kube apiserver, one shard
leader, one follower, and the CLI router fronting the follower (so a
write travels router -> follower -> leader: three processes per trace)
— entirely through the shared `ProcessFleet` harness, then:

1. drives ~10s of OPEN-LOOP mixed load (filtered lists + checks +
   dual-write creates) through the router with `OpenLoopRunner`, so the
   serving path records every `_SERVING_STAGES` stage across the fleet;
2. takes timed client samples (e2e wall time + `x-trace-id`) and
   reconciles the merged `/debug/fleet` view's per-tier attribution
   against them with the same bounds scripts/replication_smoke.py pins
   (attributed-vs-duration within 10% + 5ms; trace inside client e2e;
   client e2e within 10% + 75ms of the trace);
3. asserts `/debug/tail` serves a non-empty ranked tail report whose
   stage set is exactly `_SERVING_STAGES` — the p99 explainer is wired
   into CI, not just the bench artifact.

Runs under check.sh in BOTH modes (--fast included): the fleet is tiny
and the load window short, so this is the cheapest end-to-end guard on
the harness + loadgen + tailexplain composition.
"""

import argparse
import asyncio
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from spicedb_kubeapi_proxy_tpu.proxy.httpcore import (  # noqa: E402
    H11Transport,
    Headers,
    Request,
)
from spicedb_kubeapi_proxy_tpu.utils import loadgen  # noqa: E402
from spicedb_kubeapi_proxy_tpu.utils.timeline import _SERVING_STAGES  # noqa: E402
from spicedb_kubeapi_proxy_tpu.utils.topology import (  # noqa: E402
    FleetSpec,
    ProcessFleet,
    http,
)

SCHEMA = """
definition user {}
definition namespace {
  relation creator: user
  permission view = creator
}
definition pod {
  relation creator: user
  permission view = creator
}
"""

RULES = """
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: list-pods}
match: [{apiVersion: v1, resource: pods, verbs: [list]}]
prefilter:
- fromObjectIDNamespaceExpr: "{{split_namespace(resourceId)}}"
  fromObjectIDNameExpr: "{{split_name(resourceId)}}"
  lookupMatchingResources: {tpl: "pod:$#view@user:{{user.name}}"}
---
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: create-pods}
match: [{apiVersion: v1, resource: pods, verbs: [create]}]
lock: Optimistic
check: [{tpl: "namespace:{{namespace}}#view@user:{{user.name}}"}]
update:
  creates:
  - tpl: "pod:{{namespacedName}}#creator@user:{{user.name}}"
"""

LIST_PATH = "/api/v1/namespaces/team-a/pods"

# same reconcile contract replication_smoke pins for the two-process
# fleet view; a third tier must not loosen it
ATTR_REL_TOL = 0.10
ATTR_ABS_TOL_MS = 5.0
E2E_ABS_TOL_MS = 75.0


def stage_msg(msg: str) -> None:
    print(f"[fleet-smoke] {msg}", file=sys.stderr, flush=True)


def pod_body(name: str) -> dict:
    return {"apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": name, "namespace": "team-a"}}


async def drive_open_loop(router_url: str, spec: loadgen.WorkloadSpec):
    """Open-loop mixed load through the router: filter/check -> filtered
    LIST, update -> dual-write create.  Latencies are charged to the
    INTENDED send time by OpenLoopRunner (coordinated-omission-free).

    Every response's `x-trace-id` is recorded against the client-side
    send->completion wall time, so EVERY request is also a timed
    attribution sample — the fleet's trace recorders retain the slowest
    traces, and whichever survive can be reconciled against what this
    client actually experienced."""
    transport = H11Transport(router_url)
    client_e2e: dict = {}   # trace_id -> e2e ms (send -> completion)

    async def issue(ev: dict) -> None:
        h = Headers()
        h.set("Accept", "application/json")
        h.set("X-Remote-User", "alice")
        if ev["verb"] == "update":
            body = json.dumps(pod_body(f"lg-{ev['seq']}")).encode()
            h.set("Content-Type", "application/json")
            req = Request(method="POST", target=LIST_PATH,
                          headers=h, body=body)
        else:
            req = Request(method="GET", target=LIST_PATH, headers=h)
        t_send = time.perf_counter()
        # open-loop load driver: latency is charged to the intended
        # schedule; per-hop spans are the serving fleet's job, asserted
        # below via /debug/fleet
        resp = await transport.round_trip(req)  # noqa: A006(open-loop client)
        if resp.status >= 400:
            raise AssertionError(
                f"{ev['verb']} -> HTTP {resp.status}: {resp.body[:200]!r}")
        tid = resp.headers.get("x-trace-id")
        if tid:
            client_e2e[tid] = (time.perf_counter() - t_send) * 1e3

    runner = loadgen.OpenLoopRunner(issue, max_inflight=64)
    report = await runner.run(spec.schedule())
    return report, client_e2e


def reconcile(merged: dict, client_e2e: dict) -> tuple:
    """Per-tier attribution must reconcile with the client's measured
    e2e wall time for every retained trace this client issued."""
    matched = 0
    max_tiers = 0
    for tr in merged.get("traces", ()):
        e2e = client_e2e.get(tr.get("trace_id"))
        if e2e is None:
            continue
        # reconcile only fully-retained chains: if any member's
        # slowest-N recorder evicted its segment (flagged by the merge
        # as wall alignment / orphan fallbacks), the root duration is
        # no longer the client-facing e2e and the tier sums cannot
        # telescope to it
        if tr.get("aligned_by_wall") or tr.get("wall_fallbacks", 0):
            continue
        matched += 1
        max_tiers = max(max_tiers, tr.get("tier_count", 0))
        dur, attr = tr["duration_ms"], tr["attributed_ms"]
        assert abs(attr - dur) <= ATTR_REL_TOL * dur + ATTR_ABS_TOL_MS, (
            f"attribution gap: attributed {attr:.2f}ms vs trace "
            f"{dur:.2f}ms (trace {tr['trace_id']})")
        assert dur <= e2e + 1.0, (
            f"trace {dur:.2f}ms exceeds client e2e {e2e:.2f}ms "
            f"(trace {tr['trace_id']})")
        assert e2e - dur <= ATTR_REL_TOL * e2e + E2E_ABS_TOL_MS, (
            f"client e2e {e2e:.2f}ms unexplained by trace {dur:.2f}ms "
            f"(trace {tr['trace_id']})")
    assert matched >= 5, (
        f"only {matched} retained fleet traces matched a client sample "
        f"— ring eviction or trace-id propagation loss")
    assert max_tiers >= 2, (
        f"retained traces span at most {max_tiers} tier(s), want the "
        f"multi-process path")
    return matched, max_tiers


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__.split("\n")[0])
    ap.add_argument("--fast", action="store_true",
                    help="shorter load window (check.sh --fast lane)")
    ap.add_argument("--duration", type=float, default=0.0,
                    help="override the open-loop window length (s)")
    args = ap.parse_args()

    duration = args.duration or (6.0 if args.fast else 10.0)
    spec = loadgen.WorkloadSpec(
        seed=20, duration_s=duration, rate_per_s=12.0,
        users=50_000,  # smoke-sized id space; the bench uses 1e6
        verb_mix=(("filter", 0.5), ("check", 0.2), ("update", 0.3)))

    fleet_spec = FleetSpec(
        schema_text=SCHEMA, rules_yaml=RULES,
        shard_leaders=1, follower_levels=(1,),
        router=True, route_via="followers",
        seed_rels=("namespace:team-a#creator@user:alice",))

    stage_msg("booting router + 1 leader + 1 follower fleet ...")
    with ProcessFleet(fleet_spec) as fleet:
        fleet.boot()
        router = fleet.router_url
        stage_msg(f"fleet ready (router {router}); warming ...")
        status, _, body = http("GET", router + LIST_PATH, user="alice")
        assert status == 200, f"warm list -> HTTP {status}: {body[:200]!r}"
        status, _, body = http("POST", router + LIST_PATH, user="alice",
                               body=pod_body("warm-0"))
        assert status in (200, 201), \
            f"warm create -> HTTP {status}: {body[:200]!r}"

        stage_msg(f"open-loop load: {duration:.0f}s @ 12 req/s "
                  f"(filter/check/update mix) ...")
        report, client_e2e = asyncio.run(drive_open_loop(router, spec))
        stage_msg(
            f"load done: offered {report['offered']} achieved "
            f"{report['achieved']} errors {report['errors']} "
            f"p50 {report['p50_ms']}ms p99 {report['p99_ms']}ms "
            f"max-sched-lag {report['max_sched_lag_ms']}ms")
        assert report["errors"] == 0, \
            f"{report['errors']} open-loop requests failed"
        assert report["achieved"] == report["offered"] > 0

        status, _, body = http("GET", router + "/debug/fleet",
                               user="alice", timeout=15.0)
        assert status == 200, f"/debug/fleet -> HTTP {status}"
        merged = json.loads(body)
        found, max_tiers = reconcile(merged, client_e2e)
        stage_msg(f"attribution reconciles with client e2e on {found} "
                  f"retained traces (deepest spans {max_tiers} tiers)")

        status, _, body = http("GET", router + "/debug/tail",
                               user="alice", timeout=15.0)
        assert status == 200, f"/debug/tail -> HTTP {status}"
        tail = json.loads(body)
        assert tail.get("enabled") is True, f"/debug/tail: {tail!r}"
        assert tail.get("requests", 0) >= 2, \
            f"tail report over {tail.get('requests')} traces, want >= 2"
        ranked = tail.get("ranked") or []
        assert ranked, "/debug/tail ranked report is empty"
        got_stages = set(tail.get("stages") or ())
        assert got_stages == set(_SERVING_STAGES), (
            f"/debug/tail stage set {sorted(got_stages)} != "
            f"_SERVING_STAGES {sorted(_SERVING_STAGES)}")
        top = ranked[0]
        stage_msg(
            f"/debug/tail: p50 {tail['p50_ms']}ms p99 {tail['p99_ms']}ms "
            f"gap {tail['gap_ms']}ms; top contributor "
            f"{top['tier']}/{top['stage']} (+{top['delta_ms']}ms, "
            f"{top['share_of_gap']:.0%} of gap)")

    print(json.dumps({
        "fleet_smoke": "ok", "open_loop": report,
        "traces_reconciled": found, "deepest_tier_count": max_tiers,
        "tail_top": ranked[0], "tail_gap_ms": tail["gap_ms"],
    }, sort_keys=True))
    stage_msg("ALL GREEN")
    return 0


if __name__ == "__main__":
    sys.exit(main())
