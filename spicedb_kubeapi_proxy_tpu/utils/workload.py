"""Workload cost attribution & measured sweep telemetry (docs/observability.md
"Workload attribution & profiling").

The device-telemetry layer (utils/devtel.py) answers "what is the device
doing"; this module answers "for WHOM" — which (resource type, permission)
pairs actually burn device time, how deep their userset rewrites converge,
how much of their traffic the decision cache absorbs, and how much routes
to the host oracle.  It is fed from three places:

1. **Measured sweep telemetry** (`note_sweep`): the kernels (ops/ell.py,
   ops/spmv.py) thread an iteration counter plus per-iteration
   frontier-population deltas through the fixpoint carry and return them
   alongside the result, so the trace rides the existing D2H readback —
   no extra device sync.  Exported as
   `authz_sweep_iterations{kernel,verb}` and
   `authz_frontier_decay{kernel,verb}` (successive-iteration frontier
   ratios: mass near 0 = fast convergence, mass near 1 = deep nesting).

2. **Device-time attribution** (`note_device_time`): the devtel
   kernel-span hook forwards the SAME seconds that feed
   `authz_kernel_time_seconds{phase=kernel.device|kernel.dispatch}`,
   along with the batch's (type, permission, rows) composition stamped
   on the span attrs — so the per-pair rows sum-reconcile with the
   cumulative histogram by construction.

3. **Routing & cache hooks** (`note_batch` / `note_oracle` /
   `note_cache`): batch occupancy and measured sweep depth per pair,
   oracle-routed row counts, and decision-cache hit/miss counts.

The rolled-up view is served at the authed `/debug/workload` endpoint
and merged into `/debug/fleet`; `leopard_candidates()` flags pairs whose
measured sweep depth AND recursive `relation_footprint` structure make
them materialization (Leopard-index) candidates — the decision input
ROADMAP item 3 needs.

The `KernelIntrospect` feature gate is the killswitch: off, the kernels
build exactly the pre-introspection jitted functions and nothing here
records.  Thread-safe; recording happens from executor and readback
threads concurrently.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Iterable, Optional, Sequence

from . import metrics as m

# measured mean sweep depth at or above this flags a pair as a
# Leopard-index candidate (staged Gauss-Seidel converges flat schemas in
# 2 sweeps — propagate + confirm — so sustained depth >= 3 means real
# nested propagation is happening)
LEOPARD_DEPTH = float(os.environ.get("SPICEDB_TPU_LEOPARD_DEPTH", "3"))

_ITER_BUCKETS = (1, 2, 3, 4, 5, 6, 8, 10, 12, 16, 24, 32)
_DECAY_BUCKETS = (0.05, 0.1, 0.2, 0.3, 0.4, 0.5, 0.6, 0.7, 0.8, 0.9,
                  1.0, 2.0)

# kernel-span phases that represent the device window — the phases whose
# authz_kernel_time_seconds observations the per-pair rows reconcile with
DEVICE_PHASES = frozenset(("kernel.device", "kernel.dispatch"))


def enabled() -> bool:
    """KernelIntrospect gate (killswitch); unknown-gate errors fail open
    so embedded users with a stripped gate registry still get numbers
    (mirrors utils/devtel.enabled)."""
    try:
        from .features import GATES
        return GATES.enabled("KernelIntrospect")
    except Exception:
        return True


@dataclass
class SweepRecord:
    """One kernel sweep's measured telemetry, decoded from the int32
    trace the jitted fixpoint returns: tel[0] = executed iterations,
    tel[1:1+iterations] = per-iteration frontier-population deltas."""
    kernel: str
    verb: str
    iterations: int
    deltas: tuple


class WorkloadAccounting:
    """Rolling per-(resource type, permission) cost attribution."""

    def __init__(self, registry: Optional[m.Registry] = None):
        registry = registry or m.REGISTRY
        self._lock = threading.Lock()
        self._rows: dict = {}          # (type, perm) -> mutable row dict
        self._total_device_s = 0.0     # all DEVICE_PHASES seconds seen
        self._attributed_s = 0.0       # seconds split onto pairs
        self._schema = None            # most recent endpoint schema
        self._footprints: dict = {}    # (type, perm) -> frozenset
        self._leopard_status: dict = {}  # "type#perm" -> index status
        self._tls = threading.local()  # per-thread last SweepRecord
        self._sweep_iters = registry.histogram(
            "authz_sweep_iterations",
            "Measured fixpoint sweep iterations per kernel call, read "
            "back with the result D2H",
            labels=("kernel", "verb"), buckets=_ITER_BUCKETS)
        self._decay = registry.histogram(
            "authz_frontier_decay",
            "Frontier-population ratio between successive sweep "
            "iterations (near 0 = fast convergence, near 1 = deep "
            "nested propagation)",
            labels=("kernel", "verb"), buckets=_DECAY_BUCKETS)

    # -- measured sweep telemetry -------------------------------------------

    def note_sweep(self, kernel: str, verb: str,
                   tel) -> Optional[SweepRecord]:
        """Record one sweep's readback telemetry; returns the decoded
        record (also stashed thread-locally for `take_last_sweep`) or
        None when gated off / the trace is malformed."""
        if not enabled() or tel is None:
            return None
        try:
            iters = int(tel[0])
            if iters < 0:
                return None
            deltas = tuple(int(x) for x in tel[1:1 + iters])
        except (TypeError, ValueError, IndexError):
            return None
        rec = SweepRecord(kernel=kernel, verb=verb, iterations=iters,
                          deltas=deltas)
        self._sweep_iters.observe(iters, kernel=kernel, verb=verb)
        for prev, cur in zip(deltas, deltas[1:]):
            if prev > 0:
                self._decay.observe(min(cur / prev, 2.0),
                                    kernel=kernel, verb=verb)
        self._tls.last = rec
        return rec

    def take_last_sweep(self) -> Optional[SweepRecord]:
        """Pop the calling thread's most recent SweepRecord (the serial
        kernel wrappers run synchronously on the caller's thread, so the
        endpoint can patch measured bytes onto its open kernel span)."""
        rec = getattr(self._tls, "last", None)
        self._tls.last = None
        return rec

    # -- per-pair attribution -----------------------------------------------

    def _row_locked(self, pair: tuple) -> dict:
        row = self._rows.get(pair)
        if row is None:
            row = {"device_s": 0.0, "device_calls": 0, "kernel_rows": 0,
                   "oracle_rows": 0, "sweep_iter_rows": 0.0,
                   "sweep_rows": 0, "occ_sum": 0.0, "occ_batches": 0,
                   "cache_hits": 0, "cache_misses": 0}
            self._rows[pair] = row
        return row

    def note_device_time(self, comp: Optional[Iterable], phase: str,
                         seconds: float) -> None:
        """One device-window span's seconds, with the batch composition
        `comp` = iterable of (resource_type, permission, rows).  The
        seconds are split across pairs by row share; spans with no
        composition still count toward the reconciliation total."""
        if not enabled() or phase not in DEVICE_PHASES or seconds < 0:
            return
        comp = list(comp or ())
        total_rows = sum(max(0, int(r)) for _, _, r in comp)
        with self._lock:
            self._total_device_s += seconds
            if total_rows <= 0:
                return
            self._attributed_s += seconds
            for rtype, perm, rows in comp:
                rows = max(0, int(rows))
                if not rows:
                    continue
                row = self._row_locked((str(rtype), str(perm)))
                row["device_s"] += seconds * rows / total_rows
                row["device_calls"] += 1

    def note_batch(self, comp: Optional[Iterable], verb: str,
                   iterations: Optional[int] = None,
                   occupancy: Optional[float] = None) -> None:
        """Per-batch routing stats: kernel-served rows, batch occupancy,
        and (serial path, where the sweep record is available
        synchronously) measured depth.  The pipelined path calls this at
        capture time without iterations and feeds depth separately via
        `note_depth` when the async readback decodes the trace."""
        if not enabled():
            return
        with self._lock:
            for rtype, perm, rows in comp or ():
                rows = max(0, int(rows))
                if not rows:
                    continue
                row = self._row_locked((str(rtype), str(perm)))
                row["kernel_rows"] += rows
                if iterations is not None:
                    row["sweep_iter_rows"] += iterations * rows
                    row["sweep_rows"] += rows
                if occupancy is not None:
                    row["occ_sum"] += occupancy
                    row["occ_batches"] += 1

    def note_depth(self, comp: Optional[Iterable],
                   iterations: int) -> None:
        """Row-weighted measured sweep depth only (async-readback path —
        the batch's rows/occupancy were already counted at capture)."""
        if not enabled():
            return
        with self._lock:
            for rtype, perm, rows in comp or ():
                rows = max(0, int(rows))
                if not rows:
                    continue
                row = self._row_locked((str(rtype), str(perm)))
                row["sweep_iter_rows"] += iterations * rows
                row["sweep_rows"] += rows

    def note_oracle(self, comp: Optional[Iterable]) -> None:
        """Rows answered by the host oracle instead of the kernel."""
        if not enabled():
            return
        with self._lock:
            for rtype, perm, rows in comp or ():
                rows = max(0, int(rows))
                if rows:
                    self._row_locked(
                        (str(rtype), str(perm)))["oracle_rows"] += rows

    def note_cache(self, rtype: str, perm: str, hits: int,
                   misses: int) -> None:
        """Decision-cache probe outcome for one pair."""
        if not enabled():
            return
        with self._lock:
            row = self._row_locked((str(rtype), str(perm)))
            row["cache_hits"] += int(hits)
            row["cache_misses"] += int(misses)

    def note_schema(self, schema) -> None:
        """Remember the serving schema for the nesting detector (the
        most recent endpoint construction wins)."""
        with self._lock:
            self._schema = schema
            self._footprints.clear()

    def note_leopard_status(self, statuses: Optional[dict]) -> None:
        """Per-pair Leopard index status ("type#perm" ->
        `indexed | indexed(quarantined) | ineligible(reason)`), fed by
        the endpoint at every index install (ops/leopard.py
        `status_map`); surfaces in the /debug/workload rows."""
        with self._lock:
            self._leopard_status = dict(statuses or {})

    # -- Leopard-candidate detection ----------------------------------------

    def _footprint_locked(self, pair: tuple) -> frozenset:
        fp = self._footprints.get(pair)
        if fp is None:
            fp = frozenset()
            if self._schema is not None:
                try:
                    from ..ops.graph_compile import relation_footprint
                    fp = relation_footprint(self._schema, pair[0], pair[1])
                except Exception:
                    fp = frozenset()
            self._footprints[pair] = fp
        return fp

    def _nested_locked(self, pair: tuple) -> bool:
        """True when the pair's relation footprint contains a userset
        cycle — a relation reachable from itself through >= 1 declared
        userset reference (`member: user | group#member`, or a mutual
        a -> b -> a chain).  Flat schemas have only terminal subject
        types, so this never fires for them."""
        schema = self._schema
        if schema is None:
            return False
        edges: dict = {}

        def succ(node: tuple) -> list:
            out = edges.get(node)
            if out is None:
                d = schema.definitions.get(node[0])
                refs = d.relations.get(node[1], ()) if d is not None else ()
                out = [(ref.type, ref.relation) for ref in refs
                       if getattr(ref, "relation", None)]
                edges[node] = out
            return out

        for start in self._footprint_locked(pair):
            stack = list(succ(start))
            seen: set = set()
            while stack:
                node = stack.pop()
                if node == start:
                    return True
                if node in seen:
                    continue
                seen.add(node)
                stack.extend(succ(node))
        return False

    def leopard_candidates(self) -> list:
        """Pairs whose measured mean sweep depth is at or above
        LEOPARD_DEPTH and whose footprint is structurally recursive —
        the permissions a Leopard-style materialized group index would
        pay off for."""
        with self._lock:
            pairs = [(pair, row) for pair, row in self._rows.items()
                     if row["sweep_rows"] > 0]
            out = []
            for pair, row in pairs:
                depth = row["sweep_iter_rows"] / row["sweep_rows"]
                if depth >= LEOPARD_DEPTH and self._nested_locked(pair):
                    out.append({"resource_type": pair[0],
                                "permission": pair[1],
                                "mean_sweep_depth": round(depth, 2),
                                "kernel_rows": row["kernel_rows"]})
            out.sort(key=lambda c: -c["mean_sweep_depth"])
            return out

    # -- rolled-up view ------------------------------------------------------

    def payload(self) -> dict:
        """The /debug/workload body: per-pair rows (device-time-sorted),
        totals, and the attribution/σ(kernel histogram) reconciliation."""
        candidates = self.leopard_candidates()
        cand_pairs = {(c["resource_type"], c["permission"])
                      for c in candidates}
        with self._lock:
            rows = []
            for (rtype, perm), r in self._rows.items():
                routed = r["kernel_rows"] + r["oracle_rows"]
                probes = r["cache_hits"] + r["cache_misses"]
                # actionable Leopard status: installed-index verdicts win
                # (indexed / ineligible(reason)); with no verdict — gate
                # off, or no install yet — a detector-flagged pair shows
                # `candidate` so operators see what an index would buy
                leopard = self._leopard_status.get(f"{rtype}#{perm}")
                if leopard is None:
                    leopard = ("candidate" if (rtype, perm) in cand_pairs
                               else "ineligible(unplanned)")
                rows.append({
                    "leopard": leopard,
                    "resource_type": rtype,
                    "permission": perm,
                    "device_s": round(r["device_s"], 6),
                    "device_calls": r["device_calls"],
                    "kernel_rows": r["kernel_rows"],
                    "oracle_rows": r["oracle_rows"],
                    "oracle_fraction": (round(r["oracle_rows"] / routed, 4)
                                        if routed else None),
                    "mean_sweep_depth": (
                        round(r["sweep_iter_rows"] / r["sweep_rows"], 2)
                        if r["sweep_rows"] else None),
                    "mean_occupancy": (round(r["occ_sum"] / r["occ_batches"],
                                             4) if r["occ_batches"] else None),
                    "cache_hits": r["cache_hits"],
                    "cache_misses": r["cache_misses"],
                    "cache_hit_rate": (round(r["cache_hits"] / probes, 4)
                                       if probes else None),
                })
            total = self._total_device_s
            attributed = self._attributed_s
        rows.sort(key=lambda r: -r["device_s"])
        return {
            "rows": rows,
            "attributed_device_s": round(attributed, 6),
            "total_device_s": round(total, 6),
            "attribution_ratio": (round(attributed / total, 4)
                                  if total > 0 else None),
            "leopard_depth_threshold": LEOPARD_DEPTH,
            "leopard_candidates": candidates,
        }

    def reset(self) -> None:
        with self._lock:
            self._rows.clear()
            self._total_device_s = 0.0
            self._attributed_s = 0.0
            self._leopard_status.clear()


WORKLOAD = WorkloadAccounting()


def note_sweep(kernel: str, verb: str, tel) -> Optional[SweepRecord]:
    return WORKLOAD.note_sweep(kernel, verb, tel)


def take_last_sweep() -> Optional[SweepRecord]:
    return WORKLOAD.take_last_sweep()


def note_device_time(comp, phase: str, seconds: float) -> None:
    WORKLOAD.note_device_time(comp, phase, seconds)


def comp_rows(reqs: Sequence) -> list:
    """Collapse a CheckRequest sequence into the (type, permission, rows)
    composition stamped on kernel spans."""
    agg: dict = {}
    for r in reqs:
        pair = (r.resource.type, r.permission)
        agg[pair] = agg.get(pair, 0) + 1
    return [(t, p, n) for (t, p), n in agg.items()]
