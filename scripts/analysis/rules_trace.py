"""A006 (internal HTTP hop without trace propagation).

Fleet tracing (docs/observability.md, "Fleet tracing") only works when
EVERY internal hop carries the `X-Authz-Trace-Id` /
`X-Authz-Parent-Span` headers — one un-instrumented `round_trip` call
and the merged `/debug/fleet` trace silently loses a tier.  The rule is
lexical, matching the failure mode: someone adds a new outbound call
and forgets the headers.

A function that calls `*.round_trip(...)` must reference `hop_span` or
`propagation_headers` (the two sanctioned ways to attach the headers)
somewhere in the same function.  Exemptions:

  * functions themselves named `round_trip` — transport wrappers
    (retry/auth shims) delegate to a base transport and must pass the
    caller's headers through untouched, not mint their own;
  * `# noqa: A006(reason)` — for genuinely external hops (the upstream
    kube apiserver does not speak our header contract) and client entry
    points that originate rather than forward requests.
"""

from __future__ import annotations

import ast

_PROPAGATORS = frozenset(("hop_span", "propagation_headers"))


def _references_propagator(func_node: ast.AST) -> bool:
    for node in ast.walk(func_node):
        if isinstance(node, ast.Name) and node.id in _PROPAGATORS:
            return True
        if isinstance(node, ast.Attribute) and node.attr in _PROPAGATORS:
            return True
    return False


def _enclosing_function(src, node):
    cur = src.parents.get(node)
    while cur is not None:
        if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return cur
        cur = src.parents.get(cur)
    return None


def rule_a006(sources) -> list:
    findings: list = []
    for src in sources:
        # cache the propagator check per function — fan-out helpers can
        # hold several round_trip call sites
        checked: dict = {}
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "round_trip"):
                continue
            fn = _enclosing_function(src, node)
            if fn is not None and fn.name == "round_trip":
                continue  # transport wrapper: pass-through by contract
            scope = fn if fn is not None else src.tree
            ok = checked.get(id(scope))
            if ok is None:
                ok = _references_propagator(scope)
                checked[id(scope)] = ok
            if ok:
                continue
            findings.append(src.finding(
                "A006", node,
                "outbound HTTP hop without trace propagation — attach "
                "headers via hop_span()/propagation_headers(), or mark "
                "external hops `# noqa: A006(reason)`"))
    return findings
