// Native data-loader: bulk relationship-text parsing into columnar form.
//
// The TPU-native equivalent of the reference's bootstrap/datastore loading
// (embedded SpiceDB seeds bootstrap data straight into the datastore,
// reference pkg/spicedb/spicedb.go:63-67).  Python-level parsing of a
// 1M-tuple bootstrap costs ~20s (regex + per-tuple object churn); this
// extension parses the same text in well under a second into an interned
// string pool plus int32 index columns, which the columnar store/compiler
// consume without ever materializing per-tuple Python objects.
//
// Grammar (must match rules/relstring.py _REL_RE, the reference's
// non-greedy relRegex, pkg/rules/rules.go:1053-1076):
//   resourceType ':' resourceID '#' relation '@' subjectType ':' subjectID
//   ('#' subjectRel)?  ('[expiration:' float ']')?
// with every split at the FIRST occurrence of its delimiter.  subjectRel
// "..." normalizes to "" (types.py ELLIPSIS); empty fields are errors
// (types.parse_relationship).  Lines: skip blank and '#'-prefixed
// (endpoints.Bootstrap.relationships()).
//
// Exposed API (wrapped by native/__init__.py):
//   parse_rels(text: str) ->
//     (pool: list[str],                    # interned strings
//      six bytearrays of int32 ordinals,   # rtype, rid, rel, stype, sid, srel
//      bytearray of float64 expirations)   # NaN = no expiration

#define PY_SSIZE_T_CLEAN
#include <Python.h>

#include <cmath>
#include <cstdint>
#include <cstring>
#include <stdexcept>
#include <string>
#include <string_view>
#include <unordered_map>
#include <vector>

namespace {

struct Interner {
  std::unordered_map<std::string_view, int32_t> map;
  std::vector<std::string_view> order;

  int32_t intern(std::string_view s) {
    auto it = map.find(s);
    if (it != map.end()) return it->second;
    int32_t id = static_cast<int32_t>(order.size());
    map.emplace(s, id);
    order.push_back(s);
    return id;
  }
};

bool find_char(std::string_view s, char c, size_t from, size_t* pos) {
  size_t p = s.find(c, from);
  if (p == std::string_view::npos) return false;
  *pos = p;
  return true;
}

PyObject* parse_error(size_t lineno, std::string_view line, const char* why) {
  PyErr_Format(PyExc_ValueError, "line %zu: %s: %.200s", lineno, why,
               std::string(line).c_str());
  return nullptr;
}

PyObject* parse_rels(PyObject*, PyObject* args) {
  const char* buf;
  Py_ssize_t len;
  if (!PyArg_ParseTuple(args, "s#", &buf, &len)) return nullptr;
  std::string_view text(buf, static_cast<size_t>(len));

  Interner interner;
  std::vector<int32_t> rtype, rid, rel, stype, sid, srel;
  std::vector<double> expiry;
  const double kNaN = std::nan("");

  size_t lineno = 0;
  size_t start = 0;
  while (start <= text.size()) {
    size_t nl = text.find('\n', start);
    std::string_view line = (nl == std::string_view::npos)
                                ? text.substr(start)
                                : text.substr(start, nl - start);
    start = (nl == std::string_view::npos) ? text.size() + 1 : nl + 1;
    ++lineno;
    // strip (ASCII whitespace, mirroring str.strip on this grammar)
    size_t b = 0, e = line.size();
    while (b < e && isspace(static_cast<unsigned char>(line[b]))) ++b;
    while (e > b && isspace(static_cast<unsigned char>(line[e - 1]))) --e;
    line = line.substr(b, e - b);
    if (line.empty() || line[0] == '#') continue;

    // optional [expiration:...] suffix; number parsing mirrors Python's
    // float(): surrounding whitespace tolerated, hex forms rejected
    double exp = kNaN;
    if (!line.empty() && line.back() == ']') {
      size_t lb = line.rfind("[expiration:");
      if (lb != std::string_view::npos) {
        std::string num(line.substr(lb + 12, line.size() - lb - 13));
        size_t nb = 0, ne = num.size();
        while (nb < ne && isspace(static_cast<unsigned char>(num[nb]))) ++nb;
        while (ne > nb && isspace(static_cast<unsigned char>(num[ne - 1]))) --ne;
        num = num.substr(nb, ne - nb);
        bool ok = !num.empty()
                  && num.find('x') == std::string::npos
                  && num.find('X') == std::string::npos;
        if (ok) {
          try {
            size_t used = 0;
            exp = std::stod(num, &used);
            ok = used == num.size();
          } catch (...) {
            ok = false;
          }
        }
        if (!ok) return parse_error(lineno, line, "bad expiration");
        line = line.substr(0, lb);
      }
    }

    size_t c1, h1, at, c2;
    if (!find_char(line, ':', 0, &c1))
      return parse_error(lineno, line, "missing ':'");
    if (!find_char(line, '#', c1 + 1, &h1))
      return parse_error(lineno, line, "missing '#'");
    if (!find_char(line, '@', h1 + 1, &at))
      return parse_error(lineno, line, "missing '@'");
    if (!find_char(line, ':', at + 1, &c2))
      return parse_error(lineno, line, "missing subject ':'");
    std::string_view v_rtype = line.substr(0, c1);
    std::string_view v_rid = line.substr(c1 + 1, h1 - c1 - 1);
    std::string_view v_rel = line.substr(h1 + 1, at - h1 - 1);
    std::string_view v_stype = line.substr(at + 1, c2 - at - 1);
    std::string_view rest = line.substr(c2 + 1);
    std::string_view v_sid = rest, v_srel = std::string_view();
    size_t h2 = rest.find('#');
    if (h2 != std::string_view::npos) {
      v_sid = rest.substr(0, h2);
      v_srel = rest.substr(h2 + 1);
    }
    if (v_srel == "...") v_srel = std::string_view();
    if (v_rtype.empty() || v_rid.empty() || v_rel.empty() ||
        v_stype.empty() || v_sid.empty())
      return parse_error(lineno, line, "empty field");
    if (line.find("{{") != std::string_view::npos)
      return parse_error(lineno, line, "not a concrete relationship");

    rtype.push_back(interner.intern(v_rtype));
    rid.push_back(interner.intern(v_rid));
    rel.push_back(interner.intern(v_rel));
    stype.push_back(interner.intern(v_stype));
    sid.push_back(interner.intern(v_sid));
    srel.push_back(interner.intern(v_srel));
    expiry.push_back(exp);
  }

  PyObject* pool = PyList_New(static_cast<Py_ssize_t>(interner.order.size()));
  if (!pool) return nullptr;
  for (size_t i = 0; i < interner.order.size(); ++i) {
    std::string_view s = interner.order[i];
    PyObject* o = PyUnicode_FromStringAndSize(s.data(),
                                              static_cast<Py_ssize_t>(s.size()));
    if (!o) { Py_DECREF(pool); return nullptr; }
    PyList_SET_ITEM(pool, static_cast<Py_ssize_t>(i), o);
  }

  auto col_bytes = [](const void* data, size_t nbytes) {
    return PyByteArray_FromStringAndSize(static_cast<const char*>(data),
                                         static_cast<Py_ssize_t>(nbytes));
  };
  PyObject* out = Py_BuildValue(
      "(NNNNNNNN)", pool,
      col_bytes(rtype.data(), rtype.size() * 4),
      col_bytes(rid.data(), rid.size() * 4),
      col_bytes(rel.data(), rel.size() * 4),
      col_bytes(stype.data(), stype.size() * 4),
      col_bytes(sid.data(), sid.size() * 4),
      col_bytes(srel.data(), srel.size() * 4),
      col_bytes(expiry.data(), expiry.size() * 8));
  return out;
}

PyMethodDef methods[] = {
    {"parse_rels", parse_rels, METH_VARARGS,
     "Parse relationship text into (pool, 6 int32 columns, float64 expiry)."},
    {nullptr, nullptr, 0, nullptr}};

PyModuleDef moduledef = {PyModuleDef_HEAD_INIT, "_fastparse",
                         "Native bulk relationship parser.", -1, methods,
                         nullptr, nullptr, nullptr, nullptr};

}  // namespace

PyMODINIT_FUNC PyInit__fastparse(void) { return PyModule_Create(&moduledef); }
