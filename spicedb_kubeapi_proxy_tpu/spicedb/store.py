"""In-memory relationship (tuple) store.

The host-side source of truth replacing embedded SpiceDB's memory datastore
(reference pkg/spicedb/spicedb.go:18-71): versioned writes with
create/touch/delete semantics, filter deletes with `$`-wildcards,
preconditions, relationship expiration (`use expiration` /
`with expiration`, used by the dual-write engine's idempotency keys,
reference activity.go:47-102), read filters, and watch subscriptions.

The device CSR used by the jax:// backend is a cache rebuilt/delta-updated
from this store (SURVEY.md §5 checkpoint/resume note).
"""

from __future__ import annotations

import threading
import time

import numpy as np
from dataclasses import dataclass
from typing import Callable, Iterable, Optional

from .columnar import BaseLayer, ColumnarSnapshot
from .types import (
    AlreadyExistsError,
    ObjectRef,
    Precondition,
    PreconditionFailedError,
    PreconditionOp,
    Relationship,
    RelationshipFilter,
    RelationshipUpdate,
    SubjectRef,
    UpdateOp,
    WatchUpdate,
)

# Max mutations / preconditions per write call, mirroring the embedded
# server's limits (reference spicedb.go:35-36).
MAX_UPDATES_PER_WRITE = 1000
MAX_PRECONDITIONS = 1000


class WriteLimitExceededError(Exception):
    pass


class WatchQueue:
    """Thread-safe event drain with BOTH a blocking poll() and an
    asyncio-native next() (no polling thread, no added latency — the
    publisher wakes async consumers through call_soon_threadsafe).
    Publishers may run on any thread; multiple async consumers on
    multiple loops are supported."""

    def __init__(self):
        self._events: list = []
        self._cond = threading.Condition()
        self.closed = False
        self._waiters: list = []  # (loop, future) pairs

    def _push(self, item) -> None:
        with self._cond:
            self._events.append(item)
            self._cond.notify_all()
            self._wake_waiters_locked()

    def _mark_closed(self) -> None:
        with self._cond:
            self.closed = True
            self._cond.notify_all()
            self._wake_waiters_locked()

    def _wake_waiters_locked(self) -> None:
        waiters, self._waiters = self._waiters, []
        for loop, fut in waiters:
            try:
                loop.call_soon_threadsafe(self._resolve, fut)
            except RuntimeError:
                pass  # consumer's loop already closed

    @staticmethod
    def _resolve(fut) -> None:
        if not fut.done():
            fut.set_result(None)

    def poll(self, timeout: Optional[float] = None):
        """Block until the next batch (or timeout/close); None on timeout."""
        with self._cond:
            if not self._events and not self.closed:
                self._cond.wait(timeout)
            if self._events:
                return self._events.pop(0)
            return None

    async def next(self, timeout: Optional[float] = None):
        """Await the next batch without blocking the event loop; None on
        timeout or when the watcher is closed and drained."""
        import asyncio

        loop = asyncio.get_running_loop()
        while True:
            with self._cond:
                if self._events:
                    return self._events.pop(0)
                if self.closed:
                    return None
                fut = loop.create_future()
                self._waiters.append((loop, fut))
            try:
                if timeout is None:
                    await fut
                else:
                    try:
                        await asyncio.wait_for(fut, timeout)
                    except asyncio.TimeoutError:
                        return None
            finally:
                with self._cond:
                    try:
                        self._waiters.remove((loop, fut))
                    except ValueError:
                        pass


class Watcher(WatchQueue):
    """A subscription to relationship updates; drained via poll()/next()."""

    def __init__(self, store: "TupleStore", object_types: Optional[set]):
        super().__init__()
        self._store = store
        self._object_types = object_types

    def _publish(self, update: WatchUpdate) -> None:
        if self._object_types:
            updates = tuple(u for u in update.updates
                            if u.rel.resource.type in self._object_types)
            if not updates:
                return
            update = WatchUpdate(updates=updates, revision=update.revision)
        self._push(update)

    def close(self) -> None:
        self._mark_closed()
        self._store._unsubscribe(self)


@dataclass
class _Entry:
    rel: Relationship
    revision: int


class TupleStore:
    """Thread-safe in-memory tuple store with monotonic revisions."""

    def now(self) -> float:
        """The store's time source — consumers enforcing expiration (the
        device-graph expiry heap) must read THIS clock so tests can drive
        expiry deterministically."""
        return self._clock()

    def __init__(self, clock: Callable[[], float] = time.time):
        self._lock = threading.RLock()
        self._clock = clock
        # (resource_type, relation) -> {resource_id -> {subject_key -> _Entry}}
        self._by_relation: dict = {}
        # optional immutable columnar bootstrap layer (bulk_load_text);
        # overlay writes shadow base rows via its dead mask
        self._base: Optional[BaseLayer] = None
        self._revision = 0
        self._watchers: list[Watcher] = []
        # delta listeners get every committed batch synchronously under the
        # store lock — used by the jax:// backend for incremental CSR updates.
        self._delta_listeners: list[Callable[[WatchUpdate], None]] = []
        # reset listeners fire on non-delta mass changes (bulk_load,
        # delete_all) that require a full cache rebuild.
        self._reset_listeners: list[Callable[[], None]] = []
        # commit listeners receive EVERY revision-advancing commit with
        # its payload — (kind, revision, payload) where kind is "delta"
        # (payload: applied RelationshipUpdate tuple, possibly empty),
        # "snapshot" (ColumnarSnapshot), "bulk" (Relationship list), or
        # "clear" (None).  They run synchronously under the store lock
        # BEFORE the mutation applies: the WAL (spicedb/persist) must
        # observe a revision before any reader can act on it, and a
        # listener exception (durability failure) aborts the commit —
        # the store stays untouched, the revision is not consumed, and
        # the error propagates to the writer.
        self._commit_listeners: list[Callable] = []

    # -- revision -----------------------------------------------------------

    @property
    def revision(self) -> int:
        with self._lock:
            return self._revision

    @property
    def lock(self):
        """The store's reentrant lock — for callers that must combine
        several reads (e.g. revision + a snapshot) atomically."""
        return self._lock

    # -- reads --------------------------------------------------------------

    def read(self, flt: Optional[RelationshipFilter] = None) -> list:
        """All live (unexpired) relationships matching the filter."""
        now = self._clock()
        out = []
        with self._lock:
            if self._base is not None:
                snap = self._base.snap
                out.extend(snap.relationship(int(i))
                           for i in self._base.matching_rows(flt, now))
            for (rtype, relation), by_id in self._by_relation.items():
                if flt is not None and flt.resource_type and rtype != flt.resource_type:
                    continue
                if flt is not None and flt.relation and relation != flt.relation:
                    continue
                for rid, subjects in by_id.items():
                    if flt is not None and flt.resource_id and rid != flt.resource_id:
                        continue
                    for entry in subjects.values():
                        if entry.rel.expired(now):
                            continue
                        if flt is None or flt.matches(entry.rel):
                            out.append(entry.rel)
        return out

    def subjects_for(self, resource: ObjectRef, relation: str) -> list:
        """Live subjects of (resource, relation) — evaluator hot path."""
        now = self._clock()
        out = []
        with self._lock:
            base = self._base
            if base is not None:
                snap = base.snap
                pool = snap.pool
                for row in base.rows_for_resource(resource.type, relation,
                                                  resource.id):
                    if base.row_live(int(row), now):
                        out.append(SubjectRef(pool[snap.stype[row]],
                                              pool[snap.sid[row]],
                                              pool[snap.srel[row]]))
            by_id = self._by_relation.get((resource.type, relation))
            subjects = by_id.get(resource.id) if by_id else None
            if subjects:
                out.extend(e.rel.subject for e in subjects.values()
                           if not e.rel.expired(now))
        return out

    def subject_entries_for(self, resource: ObjectRef, relation: str) -> list:
        """Live (subject, caveat) pairs of (resource, relation).  The
        columnar base layer never carries caveats (caveated tuples always
        take the object path, see bulk_load_text), so base rows pair with
        None."""
        now = self._clock()
        out = []
        with self._lock:
            base = self._base
            if base is not None:
                snap = base.snap
                pool = snap.pool
                for row in base.rows_for_resource(resource.type, relation,
                                                  resource.id):
                    if base.row_live(int(row), now):
                        out.append((SubjectRef(pool[snap.stype[row]],
                                               pool[snap.sid[row]],
                                               pool[snap.srel[row]]), None))
            by_id = self._by_relation.get((resource.type, relation))
            subjects = by_id.get(resource.id) if by_id else None
            if subjects:
                out.extend((e.rel.subject, e.rel.caveat)
                           for e in subjects.values()
                           if not e.rel.expired(now))
        return out

    def caveated_relation_pairs(self) -> set:
        """(resource_type, relation) pairs currently holding >=1 live
        caveated tuple (jax:// uses this to route affected permissions to
        the host evaluator)."""
        now = self._clock()
        out = set()
        with self._lock:
            for (rtype, relation), by_id in self._by_relation.items():
                if (rtype, relation) in out:
                    continue
                for subjects in by_id.values():
                    if any(e.rel.caveat is not None and not e.rel.expired(now)
                           for e in subjects.values()):
                        out.add((rtype, relation))
                        break
        return out

    def caveated_keys(self) -> set:
        """Identity keys of live caveated tuples (jax:// excludes these from
        the device graph and tracks them across deltas)."""
        now = self._clock()
        out = set()
        with self._lock:
            for by_id in self._by_relation.values():
                for subjects in by_id.values():
                    for e in subjects.values():
                        if e.rel.caveat is not None and not e.rel.expired(now):
                            out.add(e.rel.key())
        return out

    def resources_with_relation(self, resource_type: str, relation: str) -> list:
        """Live resource ids having any tuple for (type, relation)."""
        now = self._clock()
        out = []
        seen = set()
        with self._lock:
            base = self._base
            if base is not None:
                snap = base.snap
                rows = base.rows_for(resource_type, relation)
                if len(rows):
                    live = rows[base.live_mask(now)[rows]]
                    for o in np.unique(snap.rid[live]):
                        rid = snap.pool[o]
                        seen.add(rid)
                        out.append(rid)
            by_id = self._by_relation.get((resource_type, relation))
            if by_id:
                for rid, subjects in by_id.items():
                    if rid not in seen and any(
                            not e.rel.expired(now) for e in subjects.values()):
                        out.append(rid)
        return out

    def object_ids_of_type(self, resource_type: str) -> list:
        """All ids appearing as a resource of the given type (live tuples)."""
        now = self._clock()
        ids = set()
        with self._lock:
            base = self._base
            if base is not None:
                snap = base.snap
                t = snap.ordinal(resource_type)
                if t >= 0:
                    live = base.live_mask(now) & (snap.rtype == t)
                    ids.update(snap.pool[o]
                               for o in np.unique(snap.rid[live]))
            for (rtype, _), by_id in self._by_relation.items():
                if rtype != resource_type:
                    continue
                for rid, subjects in by_id.items():
                    if any(not e.rel.expired(now) for e in subjects.values()):
                        ids.add(rid)
        return sorted(ids)

    def expiry_schedule(self) -> list:
        """(expires_at, (resource_type, relation)) for every LIVE tuple
        carrying an expiration — vectorized over the columnar base, object
        scan over the overlay.  Consumers that cache decisions keyed on
        relation state (spicedb/decision_cache.py) seed their expiry heap
        from this so a tuple expiring without a delta event still
        invalidates the relations it touches."""
        now = self._clock()
        out = []
        with self._lock:
            base = self._base
            if base is not None:
                snap = base.snap
                exp = snap.expiry
                rows = np.nonzero(~np.isnan(exp) & ~base.dead
                                  & (exp > now))[0]
                pool = snap.pool
                for i in rows:
                    out.append((float(exp[i]),
                                (pool[snap.rtype[i]], pool[snap.rel[i]])))
            for (rtype, relation), by_id in self._by_relation.items():
                for subjects in by_id.values():
                    for e in subjects.values():
                        if (e.rel.expires_at is not None
                                and not e.rel.expired(now)):
                            out.append((e.rel.expires_at, (rtype, relation)))
        return out

    def relationships_since(self, revision: int) -> list:
        """Live relationships whose last write landed AFTER `revision`.
        Overlay entries carry exact per-tuple revisions; base-layer rows
        all carry the base's adoption revision, so a base adopted above
        `revision` exports wholesale — conservative, and safe for the
        TOUCH-idempotent rejoin replay this serves
        (spicedb/replication/failover.py collect_unshipped_tail: the
        WAL record stream for a window reclaimed by a pre-crash
        checkpoint is gone, but the surviving EFFECTS are still here)."""
        now = self._clock()
        out = []
        with self._lock:
            if self._base is not None and self._base.revision > revision:
                snap = self._base.snap
                out.extend(snap.relationship(int(i))
                           for i in self._base.matching_rows(None, now))
            for by_id in self._by_relation.values():
                for subjects in by_id.values():
                    for entry in subjects.values():
                        if (entry.revision > revision
                                and not entry.rel.expired(now)):
                            out.append(entry.rel)
        return out

    def has_exact(self, rel: Relationship) -> bool:
        now = self._clock()
        with self._lock:
            return self._live_entry(rel, now) is not None

    def count(self) -> int:
        return len(self.read())

    # -- writes -------------------------------------------------------------

    def write(self, updates: Iterable[RelationshipUpdate],
              preconditions: Iterable[Precondition] = ()) -> int:
        """Atomically apply updates after checking preconditions; returns the
        new revision (the zedtoken equivalent)."""
        updates = list(updates)
        preconditions = list(preconditions)
        if len(updates) > MAX_UPDATES_PER_WRITE:
            raise WriteLimitExceededError(
                f"{len(updates)} updates exceeds limit {MAX_UPDATES_PER_WRITE}")
        if len(preconditions) > MAX_PRECONDITIONS:
            raise WriteLimitExceededError(
                f"{len(preconditions)} preconditions exceeds limit {MAX_PRECONDITIONS}")
        with self._lock:
            self._check_preconditions(preconditions)
            # validate CREATEs before mutating (atomicity); duplicates
            # within the batch are also conflicts
            now = self._clock()
            created_in_batch: set = set()
            for u in updates:
                if u.op != UpdateOp.CREATE:
                    continue
                key = u.rel.key()
                if (self._live_entry(u.rel, now) is not None
                        or key in created_in_batch):
                    raise AlreadyExistsError(
                        f"relationship already exists: {u.rel.rel_string()}")
                created_in_batch.add(key)
            # compute the applied set WITHOUT mutating: commit listeners
            # (the WAL) journal the batch before any reader-visible
            # change, so a durability failure aborts the write with the
            # store untouched.  `present` tracks intra-batch ordering
            # (touch-then-delete deletes; double-delete applies once).
            applied = []
            present: dict = {}
            for u in updates:
                key = u.rel.key()
                if u.op in (UpdateOp.CREATE, UpdateOp.TOUCH):
                    applied.append(RelationshipUpdate(UpdateOp.TOUCH, u.rel))
                    present[key] = True
                elif u.op == UpdateOp.DELETE:
                    if present.get(key, self._present(u.rel)):
                        applied.append(
                            RelationshipUpdate(UpdateOp.DELETE, u.rel))
                    present[key] = False
            # journal even effect-free commits: the revision advances,
            # and recovery must reproduce the exact counter
            rev = self._revision + 1
            self._commit("delta", rev, tuple(applied))
            self._revision = rev
            for u in applied:
                if u.op == UpdateOp.TOUCH:
                    self._put(u.rel, rev)
                else:
                    self._remove(u.rel)
            if applied:
                self._broadcast(WatchUpdate(updates=tuple(applied), revision=rev))
            return rev

    def bulk_load(self, rels: Iterable[Relationship]) -> int:
        """Bootstrap/benchmark path: load relationships without the per-call
        API update limit (the reference seeds bootstrap data straight into
        the datastore, not through WriteRelationships — spicedb.go:63-67).
        One revision, no watch events."""
        with self._lock:
            if self._commit_listeners:
                rels = list(rels)  # journaled payload; iterated twice
            rev = self._revision + 1
            self._commit("bulk", rev, rels if isinstance(rels, list) else ())
            self._revision = rev
            for rel in rels:
                self._put(rel, rev)
            for fn in list(self._reset_listeners):
                fn()
            return rev

    def delete_by_filter(self, flt: RelationshipFilter,
                         preconditions: Iterable[Precondition] = ()) -> tuple:
        """Delete all relationships matching the filter; returns
        (revision, deleted relationships)."""
        with self._lock:
            self._check_preconditions(list(preconditions))
            victims = self.read(flt)
            if not victims:
                return self._revision, []
            applied = tuple(RelationshipUpdate(UpdateOp.DELETE, rel)
                            for rel in victims)
            rev = self._revision + 1
            self._commit("delta", rev, applied)
            self._revision = rev
            for rel in victims:
                self._remove(rel)
            self._broadcast(WatchUpdate(updates=applied, revision=rev))
            return rev, victims

    def delete_all(self) -> None:
        """Test helper (mirrors the reference e2e DeleteAllTuples util)."""
        with self._lock:
            rev = self._revision + 1
            self._commit("clear", rev, None)
            self._revision = rev
            self._by_relation.clear()
            self._base = None
            for fn in list(self._reset_listeners):
                fn()

    # -- columnar bulk path -------------------------------------------------

    def bulk_load_snapshot(self, snap: ColumnarSnapshot) -> int:
        """Adopt a columnar snapshot as the store's base layer without
        materializing per-tuple objects (the fast bootstrap path; reference
        seeds bootstrap data straight into the datastore, spicedb.go:63-67).
        Requires an empty store; otherwise falls back to object inserts.
        One revision, no watch events (like bulk_load)."""
        with self._lock:
            if self._by_relation or self._base is not None:
                return self.bulk_load(snap.relationship(i)
                                      for i in range(len(snap)))
            rev = self._revision + 1
            self._commit("snapshot", rev, snap)
            self._revision = rev
            self._base = BaseLayer(snap, rev)
            for fn in list(self._reset_listeners):
                fn()
            return rev

    def bulk_load_text(self, text: str) -> int:
        """Parse + adopt relationship text via the native loader.  Caveated
        lines (`[caveat:...]` suffix) are split out and loaded through the
        object path — the columnar base layer stays caveat-free by
        construction (see subject_entries_for)."""
        if "[caveat:" in text:
            from .types import parse_relationship as _parse
            plain_lines = []
            caveat_rels = []
            for line in text.splitlines():
                stripped = line.strip()
                if "[caveat:" in stripped:
                    caveat_rels.append(_parse(stripped))
                else:
                    plain_lines.append(line)
            rev = self.bulk_load_snapshot(
                ColumnarSnapshot.from_text("\n".join(plain_lines)))
            if caveat_rels:
                rev = self.bulk_load(caveat_rels)
            return rev
        return self.bulk_load_snapshot(ColumnarSnapshot.from_text(text))

    def columnar_view(self) -> Optional[tuple]:
        """(snapshot, live base row indices, overlay relationships) for the
        vectorized graph compiler, or None when no base layer exists.  Call
        under no lock; takes the store lock itself."""
        now = self._clock()
        with self._lock:
            if self._base is None:
                return None
            rows = self._base.live_rows(now)
            overlay = []
            for by_id in self._by_relation.values():
                for subjects in by_id.values():
                    overlay.extend(e.rel for e in subjects.values()
                                   if not e.rel.expired(now))
            return self._base.snap, rows, overlay

    # -- watch --------------------------------------------------------------

    def subscribe(self, object_types: Optional[Iterable[str]] = None) -> Watcher:
        w = Watcher(self, set(object_types) if object_types else None)
        with self._lock:
            self._watchers.append(w)
        return w

    def _unsubscribe(self, w: Watcher) -> None:
        with self._lock:
            if w in self._watchers:
                self._watchers.remove(w)

    def add_delta_listener(self, fn: Callable[[WatchUpdate], None]) -> None:
        with self._lock:
            self._delta_listeners.append(fn)

    def add_reset_listener(self, fn: Callable[[], None]) -> None:
        with self._lock:
            self._reset_listeners.append(fn)

    def remove_delta_listener(self, fn: Callable[[WatchUpdate], None]) -> None:
        with self._lock:
            if fn in self._delta_listeners:
                self._delta_listeners.remove(fn)

    def remove_reset_listener(self, fn: Callable[[], None]) -> None:
        with self._lock:
            if fn in self._reset_listeners:
                self._reset_listeners.remove(fn)

    def add_commit_listener(self, fn: Callable) -> None:
        """fn(kind, revision, payload) on every revision-advancing
        commit, synchronously under the store lock (see __init__)."""
        with self._lock:
            self._commit_listeners.append(fn)

    def remove_commit_listener(self, fn: Callable) -> None:
        with self._lock:
            if fn in self._commit_listeners:
                self._commit_listeners.remove(fn)

    # -- recovery (spicedb/persist) -----------------------------------------

    def adopt_recovery_state(self, snap: Optional[ColumnarSnapshot],
                             overlay: Iterable[Relationship],
                             revision: int) -> None:
        """Recovery-only: adopt a checkpointed state wholesale at
        EXACTLY `revision` — columnar base plus object overlay
        (caveated tuples), with no intermediate revision bumps (a
        checkpoint taken at revision 1 must not land at 2 because its
        overlay loaded as a second step).  Requires a store with no
        history; fires no listeners (recovery precedes attach)."""
        if revision < 1:
            raise ValueError(f"invalid recovery revision {revision}")
        with self._lock:
            if self._revision != 0 or self._by_relation or self._base is not None:
                raise ValueError(
                    "adopt_recovery_state requires an empty store")
            if snap is not None and len(snap):
                self._base = BaseLayer(snap, revision)
            for rel in overlay:
                self._put(rel, revision)
            self._revision = revision

    def apply_recovery_batch(self, updates: Iterable[RelationshipUpdate]) -> int:
        """Re-apply one journaled committed batch exactly as recorded:
        no limits, preconditions, CREATE validation, or listener
        broadcast (recovery runs before any listener attaches) — the
        batch already committed once, so it re-applies verbatim.  One
        revision bump even for an effect-free batch, mirroring write()."""
        with self._lock:
            self._revision += 1
            rev = self._revision
            for u in updates:
                if u.op == UpdateOp.DELETE:
                    self._remove(u.rel)
                else:
                    self._put(u.rel, rev)
            return rev

    # -- replication (spicedb/replication) ----------------------------------

    def apply_replica_batch(self, updates: Iterable[RelationshipUpdate]) -> int:
        """Replica-apply one journaled committed batch: the exact-replay
        semantics of apply_recovery_batch (no limits / preconditions /
        CREATE validation — the batch already committed on the leader),
        but applied to a LIVE store: watchers and delta listeners fire,
        so the device graph, decision-cache epochs, and watch streams
        follow the leader through the normal delta pipeline.  Commit
        listeners do NOT fire — a follower must never re-journal the
        leader's log."""
        updates = tuple(updates)
        with self._lock:
            self._revision += 1
            rev = self._revision
            for u in updates:
                if u.op == UpdateOp.DELETE:
                    self._remove(u.rel)
                else:
                    self._put(u.rel, rev)
            if updates:
                self._broadcast(WatchUpdate(updates=updates, revision=rev))
            return rev

    def replica_reset(self, snap: Optional[ColumnarSnapshot],
                      overlay: Iterable[Relationship],
                      revision: int) -> None:
        """Replica (re-)bootstrap: discard ALL current state and adopt a
        leader checkpoint wholesale at EXACTLY `revision`.  Unlike
        adopt_recovery_state this works on a non-empty store (a follower
        re-bootstraps after losing the segment tail it was tailing) and
        fires the reset listeners so live consumers rebuild their caches
        from the adopted state.  The revision may move backwards — after
        a leader crash that lost an unsynced WAL tail, the checkpoint is
        the only truthful state left."""
        if revision < 1:
            raise ValueError(f"invalid replica reset revision {revision}")
        with self._lock:
            self._by_relation.clear()
            self._base = None
            if snap is not None and len(snap):
                self._base = BaseLayer(snap, revision)
            for rel in overlay:
                self._put(rel, revision)
            self._revision = revision
            for fn in list(self._reset_listeners):
                fn()

    # -- internals ----------------------------------------------------------

    def _present(self, rel: Relationship) -> bool:
        """Identity-present regardless of expiry — mirrors what
        _remove() can reach, so a pre-commit applied-set computation
        agrees with the mutation it precedes."""
        by_id = self._by_relation.get((rel.resource.type, rel.relation))
        subjects = by_id.get(rel.resource.id) if by_id else None
        if subjects and rel.subject in subjects:
            return True
        base = self._base
        return base is not None and base.find_row(rel.key()) >= 0

    def _live_entry(self, rel: Relationship, now: float) -> Optional[_Entry]:
        by_id = self._by_relation.get((rel.resource.type, rel.relation), {})
        entry = by_id.get(rel.resource.id, {}).get(rel.subject)
        if entry is not None:
            return None if entry.rel.expired(now) else entry
        base = self._base
        if base is not None:
            row = base.find_row(rel.key())
            if row >= 0 and base.row_live(row, now):
                return _Entry(rel=base.snap.relationship(row),
                              revision=base.revision)
        return None

    def _put(self, rel: Relationship, rev: int) -> None:
        base = self._base
        if base is not None:
            # overlay shadows the base copy (keeps iteration duplicate-free)
            row = base.find_row(rel.key())
            if row >= 0:
                base.dead[row] = True
        key = (rel.resource.type, rel.relation)
        by_id = self._by_relation.setdefault(key, {})
        subjects = by_id.setdefault(rel.resource.id, {})
        subjects[rel.subject] = _Entry(rel=rel, revision=rev)

    def _remove(self, rel: Relationship) -> bool:
        key = (rel.resource.type, rel.relation)
        by_id = self._by_relation.get(key)
        subjects = by_id.get(rel.resource.id) if by_id else None
        if subjects and rel.subject in subjects:
            del subjects[rel.subject]
            if not subjects:
                del by_id[rel.resource.id]
            if not by_id:
                del self._by_relation[key]
            return True
        base = self._base
        if base is not None:
            row = base.find_row(rel.key())
            if row >= 0 and not base.dead[row]:
                base.dead[row] = True
                return True
        return False

    def _check_preconditions(self, preconditions: list) -> None:
        for p in preconditions:
            matched = bool(self.read(p.filter))
            if p.op == PreconditionOp.MUST_MATCH and not matched:
                raise PreconditionFailedError(p)
            if p.op == PreconditionOp.MUST_NOT_MATCH and matched:
                raise PreconditionFailedError(p)

    def _broadcast(self, update: WatchUpdate) -> None:
        for fn in list(self._delta_listeners):
            fn(update)
        for w in list(self._watchers):
            w._publish(update)

    def _commit(self, kind: str, revision: int, payload) -> None:
        """Notify commit listeners (under the store lock, before any
        watcher/delta listener — WAL-before-visibility ordering)."""
        for fn in list(self._commit_listeners):
            fn(kind, revision, payload)
