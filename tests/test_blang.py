"""Template expression language tests.

Covers the expression shapes exercised by the reference's Bloblang corpus
(pkg/rules/rules_test.go, tupleset_test.go, env_test.go)."""

import pytest

from spicedb_kubeapi_proxy_tpu.rules import blang
from spicedb_kubeapi_proxy_tpu.rules.engine import default_environment

ENV = default_environment()


def q(expr, data=None):
    return ENV.parse(expr).query(data if data is not None else {})


class TestLiterals:
    def test_string(self):
        assert q('"hello"') == "hello"

    def test_single_quoted(self):
        assert q("'hello'") == "hello"

    def test_numbers(self):
        assert q("42") == 42
        assert q("4.5") == 4.5

    def test_bool_null(self):
        assert q("true") is True
        assert q("false") is False
        assert q("null") is None

    def test_array(self):
        assert q('[1, "a", true]') == [1, "a", True]

    def test_object(self):
        assert q('{"a": 1, "b": "x"}') == {"a": 1, "b": "x"}

    def test_escapes(self):
        assert q(r'"a\"b\n"') == 'a"b\n'


class TestFieldAccess:
    DATA = {"user": {"name": "alice", "groups": ["dev", "ops"]},
            "resourceId": "default/pod1"}

    def test_this_field(self):
        assert q("this.user.name", self.DATA) == "alice"

    def test_bare_ident_is_this_field(self):
        assert q("user.name", self.DATA) == "alice"
        assert q("resourceId", self.DATA) == "default/pod1"

    def test_missing_field_is_null(self):
        assert q("this.nope", self.DATA) is None
        assert q("this.nope.deeper", self.DATA) is None

    def test_index(self):
        assert q("user.groups[0]", self.DATA) == "dev"
        assert q('this["resourceId"]', self.DATA) == "default/pod1"

    def test_index_out_of_bounds_errors(self):
        with pytest.raises(blang.BlangEvalError):
            q("user.groups[5]", self.DATA)


class TestOperators:
    def test_concat(self):
        assert q('"a" + "b"') == "ab"

    def test_concat_non_string_errors(self):
        with pytest.raises(blang.BlangEvalError):
            q('"a" + 1')

    def test_arith(self):
        assert q("1 + 2 * 3") == 7
        assert q("(1 + 2) * 3") == 9
        assert q("7 % 3") == 1

    def test_compare(self):
        assert q("1 < 2") is True
        assert q('"a" != "b"') is True
        assert q("2 == 2.0") is True

    def test_logic(self):
        assert q("true && false") is False
        assert q("true || false") is True
        assert q("!false") is True

    def test_catch_pipe_on_null(self):
        assert q("this.missing | []", {"a": 1}) == []

    def test_catch_pipe_on_error(self):
        assert q('this.num.map_each(this) | "fallback"', {"num": 5}) == "fallback"

    def test_catch_pipe_passthrough(self):
        assert q("this.a | 9", {"a": 1}) == 1

    def test_catch_method(self):
        assert q('this.num.map_each(this).catch("fb")', {"num": 5}) == "fb"


class TestLambdasAndMethods:
    DATA = {
        "namespacedName": "default/dep1",
        "name": "dep1",
        "user": {"name": "alice"},
        "object": {
            "spec": {
                "template": {"spec": {"containers": [
                    {"name": "app"}, {"name": "proxy-sidecar"}]}},
                "ports": [{"name": "http", "port": 80}, {"port": 8080}],
            },
        },
    }

    def test_map_each_with_capture(self):
        # The canonical tupleSet shape from the reference corpus.
        expr = ('this.namespacedName.(nsName -> this.object.spec.template.spec'
                '.containers.map_each("deployment:" + nsName +'
                ' "#has-container@container:" + this.name))')
        assert q(expr, self.DATA) == [
            "deployment:default/dep1#has-container@container:app",
            "deployment:default/dep1#has-container@container:proxy-sidecar",
        ]

    def test_filter(self):
        expr = ('this.object.spec.template.spec.containers'
                '.filter(this.name != "proxy-sidecar").map_each(this.name)')
        assert q(expr, self.DATA) == ["app"]

    def test_if_else_and_string_conversion(self):
        expr = ('this.object.spec.ports.map_each('
                'if this.name != null { this.name } else { this.port.string() })')
        assert q(expr, self.DATA) == ["http", "8080"]

    def test_missing_list_with_fallback(self):
        expr = ('(this.object.spec.template.spec.initContainers | [])'
                '.map_each(this.name)')
        assert q(expr, self.DATA) == []

    def test_let_variables(self):
        expr = ('let nsName = this.namespacedName\n'
                'this.object.spec.template.spec.containers.map_each('
                '"deployment:" + $nsName + "#c@container:" + this.name)')
        assert q(expr, self.DATA) == [
            "deployment:default/dep1#c@container:app",
            "deployment:default/dep1#c@container:proxy-sidecar",
        ]

    def test_map_each_on_non_array_errors(self):
        with pytest.raises(blang.BlangEvalError):
            q("this.name.map_each(this)", self.DATA)

    def test_nested_capture_sees_outer(self):
        expr = ('this.name.(n -> this.user.name.(u -> n + ":" + u))')
        assert q(expr, self.DATA) == "dep1:alice"


class TestMethods:
    def test_string_methods(self):
        assert q('"AbC".uppercase()') == "ABC"
        assert q('"AbC".lowercase()') == "abc"
        assert q('" x ".trim()') == "x"
        assert q('"abc".contains("b")') is True
        assert q('"abc".has_prefix("ab")') is True
        assert q('"abc".has_suffix("bc")') is True
        assert q('"a/b/c".split("/")') == ["a", "b", "c"]
        assert q('["a","b"].join("-")') == "a-b"

    def test_conversions(self):
        assert q('8080.string()') == "8080"
        assert q('"12".number()') == 12
        assert q('true.string()') == "true"
        assert q('"abc".length()') == 3

    def test_collections(self):
        assert q('[3,1,2].sort()') == [1, 2, 3]
        assert q('[1,1,2].unique()') == [1, 2]
        assert q('{"b":1,"a":2}.keys()') == ["a", "b"]
        assert q('[1,2,3].contains(2)') is True


class TestFunctions:
    def test_split_name(self):
        assert q('split_name("ns/podname")') == "podname"
        assert q('split_name("noslash")') == "noslash"

    def test_split_namespace(self):
        assert q('split_namespace("ns/podname")') == "ns"
        assert q('split_namespace("noslash")') == ""

    def test_split_on_resource_id(self):
        data = {"resourceId": "default/pod1"}
        assert q("split_name(resourceId)", data) == "pod1"
        assert q("split_namespace(resourceId)", data) == "default"

    def test_unknown_function(self):
        with pytest.raises(blang.BlangEvalError):
            q("nope(1)")


class TestParseErrors:
    @pytest.mark.parametrize("src", ["", "1 +", '"unterminated', "a..b", "((1)"])
    def test_bad_input(self, src):
        with pytest.raises(blang.BlangParseError):
            ENV.parse(src)


class TestReviewRegressions:
    def test_split_empty_separator_splits_chars(self):
        assert q('"abc".split("")') == ["a", "b", "c"]

    def test_or_lazy_on_error(self):
        assert q('"a".number().or(0)') == 0
        assert q('this.missing.or("fb")', {"a": 1}) == "fb"
        assert q('this.a.or(9)', {"a": 1}) == 1

    def test_let_terminated_by_newline(self):
        assert q('let a = this.name\n["x", $a]', {"name": "n"}) == ["x", "n"]
        # the newline ends the let RHS; the next line is the result expression
        assert q('let a = this.n\n-1', {"n": 5}) == -1

    def test_let_rhs_can_span_brackets(self):
        assert q('let a = [1,\n2]\n$a', {}) == [1, 2]

    def test_wrong_arity_is_blang_error(self):
        with pytest.raises(blang.BlangEvalError):
            q('"a/b".split()')
        with pytest.raises(blang.BlangEvalError):
            q('"abc".contains()')
