"""CLI / options tests (reference pkg/proxy/options_test.go and
cmd/spicedb-kubeapi-proxy/main.go): flag parsing + normalization,
Validate invariants, Complete wiring (rules, kubeconfig transport,
self-signed serving certs, authenticators), and an end-to-end serve/request
round trip over real TLS."""

import asyncio
import base64
import json
import ssl

import pytest

from spicedb_kubeapi_proxy_tpu import cli
from spicedb_kubeapi_proxy_tpu.config import proxyrule
from spicedb_kubeapi_proxy_tpu.proxy import kubeconfig as kubecfg
from spicedb_kubeapi_proxy_tpu.proxy.authn import (
    HeaderAuthenticator,
    TokenFileAuthenticator)
from spicedb_kubeapi_proxy_tpu.proxy.httpcore import (
    Headers,
    Request,
    Response,
    Transport,
)

RULES = """
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: get-namespaces}
match: [{apiVersion: v1, resource: namespaces, verbs: [get]}]
check: [{tpl: "namespace:{{name}}#view@user:{{user.name}}"}]
"""


def parse(argv):
    return cli.build_parser().parse_args(cli._normalize_argv(argv))


# -- flag parsing ------------------------------------------------------------

def test_defaults():
    args = parse([])
    assert args.spicedb_endpoint == "embedded://"
    assert args.workflow_database_path == cli.DEFAULT_WORKFLOW_DATABASE_PATH
    assert args.lock_mode == proxyrule.PESSIMISTIC_LOCK_MODE
    assert args.override_upstream is True
    assert args.secure_port == 443
    assert args.verbosity == 3


def test_word_separator_normalization():
    # pflag WordSepNormalizeFunc equivalence (reference main.go:23)
    args = parse(["--rule_config", "/tmp/r.yaml",
                  "--spicedb_endpoint=jax://"])
    assert args.rule_config == "/tmp/r.yaml"
    assert args.spicedb_endpoint == "jax://"


def test_lock_mode_choices():
    with pytest.raises(SystemExit):
        parse(["--lock-mode", "Bogus"])


# -- Validate (reference options.go:412-427) ---------------------------------

def test_validate_requires_upstream_and_rules():
    errs = cli.validate(parse([]))
    assert any("--backend-kubeconfig" in e for e in errs)
    assert any("--rule-config" in e for e in errs)


def test_validate_ok_with_in_cluster_and_rules():
    errs = cli.validate(parse(["--use-in-cluster-config",
                               "--rule-config", "r.yaml"]))
    assert errs == []


def test_validate_rejects_bad_port():
    errs = cli.validate(parse(["--use-in-cluster-config",
                               "--rule-config", "r.yaml",
                               "--secure-port", "0"]))
    assert any("secure-port" in e for e in errs)


# -- kubeconfig loading (reference options.go:382-449) -----------------------

def write_kubeconfig(tmp_path, server="https://kube.example:6443",
                     token="", insecure=False):
    cfg = {
        "apiVersion": "v1", "kind": "Config",
        "current-context": "ctx",
        "contexts": [{"name": "ctx",
                      "context": {"cluster": "c", "user": "u"}}],
        "clusters": [{"name": "c", "cluster": {
            "server": server,
            "insecure-skip-tls-verify": insecure,
        }}],
        "users": [{"name": "u", "user": {"token": token} if token else {}}],
    }
    path = tmp_path / "kubeconfig.yaml"
    path.write_text(json.dumps(cfg))
    return str(path)


def test_load_kubeconfig_current_context(tmp_path):
    path = write_kubeconfig(tmp_path, token="sekrit")
    ctx = kubecfg.load_kubeconfig(path)
    assert ctx.server == "https://kube.example:6443"
    assert ctx.token == "sekrit"


def test_load_kubeconfig_override_upstream(tmp_path, monkeypatch):
    # reference options.go:396-407: env rewrites every cluster server
    monkeypatch.setenv("KUBERNETES_SERVICE_HOST", "10.0.0.1")
    monkeypatch.setenv("KUBERNETES_SERVICE_PORT", "443")
    ctx = kubecfg.load_kubeconfig(write_kubeconfig(tmp_path),
                                  override_upstream=True)
    assert ctx.server == "https://10.0.0.1:443"


def test_load_kubeconfig_cert_data(tmp_path):
    ca = base64.b64encode(b"CERTDATA").decode()
    cfg = {
        "current-context": "ctx",
        "contexts": [{"name": "ctx",
                      "context": {"cluster": "c", "user": "u"}}],
        "clusters": [{"name": "c", "cluster": {
            "server": "https://k:6443",
            "certificate-authority-data": ca}}],
        "users": [{"name": "u", "user": {}}],
    }
    path = tmp_path / "k.yaml"
    path.write_text(json.dumps(cfg))
    assert kubecfg.load_kubeconfig(str(path)).ca_data == b"CERTDATA"


def test_bearer_token_transport_injects():
    seen = {}

    class Rec(Transport):
        async def round_trip(self, req):
            seen["auth"] = req.headers.get("Authorization")
            return Response(status=200)

    t = kubecfg.BearerTokenTransport(Rec(), "tok")
    asyncio.run(t.round_trip(Request(method="GET", target="/x")))
    assert seen["auth"] == "Bearer tok"


# -- Complete (reference options.go:213-380) ---------------------------------

class NullTransport(Transport):
    async def round_trip(self, req):
        return Response(status=200, body=b"{}")


def test_complete_loads_and_validates_rules(tmp_path):
    rules = tmp_path / "rules.yaml"
    rules.write_text(RULES)
    args = parse(["--rule-config", str(rules), "--use-in-cluster-config",
                  "--embedded-mode"])
    completed = cli.complete(args, upstream_transport=NullTransport())
    assert len(completed.server_options.rule_configs) == 1
    assert completed.embedded_mode


def test_scheme_less_endpoint_carries_connection_flags():
    """The reference's default endpoint shape is scheme-less host:port
    (options.go:107); token/insecure/CA flags must flow to it exactly as
    they do for grpc:// URLs."""
    args = parse(["--spicedb-endpoint", "spicedb.example.com:50051",
                  "--spicedb-token", "tok", "--spicedb-insecure",
                  "--use-in-cluster-config", "--embedded-mode"])
    completed = cli.complete(args, upstream_transport=NullTransport())
    kw = completed.server_options.endpoint_kwargs
    assert kw["token"] == "tok"
    assert kw["insecure"] is True


def test_complete_rejects_invalid_rules(tmp_path):
    rules = tmp_path / "rules.yaml"
    rules.write_text("apiVersion: authzed.com/v1alpha1\nkind: Nope\n")
    args = parse(["--rule-config", str(rules), "--embedded-mode"])
    with pytest.raises(cli.OptionsError, match="invalid rule config"):
        cli.complete(args, upstream_transport=NullTransport())


def test_complete_missing_kubeconfig_errors(tmp_path):
    rules = tmp_path / "rules.yaml"
    rules.write_text(RULES)
    args = parse(["--rule-config", str(rules),
                  "--backend-kubeconfig", str(tmp_path / "absent.yaml")])
    with pytest.raises(cli.OptionsError, match="kubeconfig"):
        cli.complete(args)


def test_complete_embedded_mode_uses_header_auth(tmp_path):
    rules = tmp_path / "rules.yaml"
    rules.write_text(RULES)
    args = parse(["--rule-config", str(rules), "--embedded-mode"])
    completed = cli.complete(args, upstream_transport=NullTransport())
    kinds = [type(a) for a in completed.server_options.authenticators]
    assert kinds == [HeaderAuthenticator]
    assert completed.server_options.ssl_context is None


def test_complete_serving_mode_generates_self_signed_certs(tmp_path):
    # self-signed pair generation needs the optional cryptography
    # package (requirements-dev.txt); degrade to a skip like test_authn
    pytest.importorskip("cryptography")
    rules = tmp_path / "rules.yaml"
    rules.write_text(RULES)
    args = parse(["--rule-config", str(rules),
                  "--cert-dir", str(tmp_path / "certs")])
    completed = cli.complete(args, upstream_transport=NullTransport())
    assert completed.server_options.ssl_context is not None
    assert (tmp_path / "certs" / "tls.crt").exists()
    assert (tmp_path / "certs" / "tls.key").exists()
    # idempotent: second Complete reuses the pair
    before = (tmp_path / "certs" / "tls.crt").read_bytes()
    cli.complete(args, upstream_transport=NullTransport())
    assert (tmp_path / "certs" / "tls.crt").read_bytes() == before


def test_complete_rejects_half_specified_tls_pair(tmp_path):
    rules = tmp_path / "rules.yaml"
    rules.write_text(RULES)
    args = parse(["--rule-config", str(rules),
                  "--tls-cert-file", str(tmp_path / "tls.crt")])
    with pytest.raises(cli.OptionsError, match="together"):
        cli.complete(args, upstream_transport=NullTransport())


def test_complete_missing_token_auth_file_errors(tmp_path):
    rules = tmp_path / "rules.yaml"
    rules.write_text(RULES)
    args = parse(["--rule-config", str(rules), "--embedded-mode",
                  "--token-auth-file", str(tmp_path / "absent.csv")])
    with pytest.raises(cli.OptionsError, match="token auth file"):
        cli.complete(args, upstream_transport=NullTransport())


def test_complete_token_auth_file(tmp_path):
    rules = tmp_path / "rules.yaml"
    rules.write_text(RULES)
    tokens = tmp_path / "tokens.csv"
    tokens.write_text('tok1,alice,uid1,"dev,ops"\ntok2,bob,uid2\n')
    args = parse(["--rule-config", str(rules), "--embedded-mode",
                  "--token-auth-file", str(tokens)])
    completed = cli.complete(args, upstream_transport=NullTransport())
    tf = [a for a in completed.server_options.authenticators
          if isinstance(a, TokenFileAuthenticator)]
    assert len(tf) == 1
    req = Request(method="GET", target="/",
                  headers=Headers([("Authorization", "Bearer tok1")]))
    user = tf[0].authenticate(req)
    assert user.name == "alice" and user.groups == ["dev", "ops"]
    assert tf[0].authenticate(Request(
        method="GET", target="/",
        headers=Headers([("Authorization", "Bearer nope")]))) is None


# -- end-to-end: serve over TLS and round-trip a request ---------------------

def test_serve_tls_end_to_end(tmp_path):
    """complete() -> ProxyServer over real TLS -> authenticated request is
    authorized and proxied (upstream faked)."""
    pytest.importorskip("cryptography")  # self-signed serving pair
    from spicedb_kubeapi_proxy_tpu.proxy.server import ProxyServer

    rules = tmp_path / "rules.yaml"
    rules.write_text(RULES)
    tokens = tmp_path / "tokens.csv"
    tokens.write_text("tok1,alice,uid1\n")

    class Upstream(Transport):
        async def round_trip(self, req):
            return Response(status=200, body=json.dumps({
                "kind": "Namespace", "apiVersion": "v1",
                "metadata": {"name": "ns1"}}).encode())

    args = parse(["--rule-config", str(rules),
                  "--cert-dir", str(tmp_path / "certs"),
                  "--token-auth-file", str(tokens),
                  "--use-in-cluster-config"])
    completed = cli.complete(args, upstream_transport=Upstream())

    async def run():
        server = ProxyServer(completed.server_options)
        # seed the permission the check rule requires
        from spicedb_kubeapi_proxy_tpu.spicedb.types import (
            RelationshipUpdate, UpdateOp, parse_relationship)
        await server.endpoint.write_relationships([RelationshipUpdate(
            op=UpdateOp.TOUCH,
            rel=parse_relationship("namespace:ns1#viewer@user:alice"))])
        port = await server.start("127.0.0.1", 0)
        try:
            ssl_ctx = ssl.create_default_context()
            ssl_ctx.check_hostname = False
            ssl_ctx.verify_mode = ssl.CERT_NONE
            from spicedb_kubeapi_proxy_tpu.proxy.httpcore import H11Transport
            client = H11Transport(f"https://127.0.0.1:{port}",
                                  ssl_context=ssl_ctx)
            ok = await client.round_trip(Request(
                method="GET", target="/api/v1/namespaces/ns1",
                headers=Headers([("Authorization", "Bearer tok1"),
                                 ("Accept", "application/json")])))
            anon = await client.round_trip(Request(
                method="GET", target="/api/v1/namespaces/ns1",
                headers=Headers([("Accept", "application/json")])))
            return ok, anon
        finally:
            await server.stop()

    ok, anon = asyncio.run(run())
    assert ok.status == 200
    assert json.loads(ok.body)["metadata"]["name"] == "ns1"
    assert anon.status == 401


def test_trace_slow_threshold_flag_wires_through(tmp_path):
    rules = tmp_path / "rules.yaml"
    rules.write_text(RULES)
    args = parse(["--rule-config", str(rules), "--use-in-cluster-config",
                  "--embedded-mode", "--trace-slow-threshold", "1.5"])
    assert cli.validate(args) == []
    completed = cli.complete(args, upstream_transport=NullTransport())
    assert completed.server_options.trace_slow_threshold == 1.5
    # default off; negative rejected at validate time
    assert parse([]).trace_slow_threshold == 0.0
    bad = parse(["--rule-config", str(rules), "--use-in-cluster-config",
                 "--embedded-mode", "--trace_slow_threshold", "-1"])
    assert any("trace-slow-threshold" in e for e in cli.validate(bad))
