"""A001 (event-loop-blocking call in `async def`) and A002 (dropped
asyncio task).

A001 — every shipped event-loop stall in this repo was a synchronous
call that looked innocent at the call site: `fsync` on the WAL, a
checkpoint `np.load`, `block_until_ready` on a device result.  The rule
flags calls from a known blocking table that are LEXICALLY inside an
`async def` body — code inside a nested sync def/lambda is excluded,
because that is exactly the `run_in_executor`/`to_thread` hop that makes
the call legal.

A002 — the PR 2 GC-hang class: `asyncio.create_task`/`ensure_future`
whose result is dropped on the floor.  The event loop holds tasks only
weakly; a gen-2 collection mid-flight destroys the pending task and the
awaiting caller hangs.  Only a bare expression statement is a drop —
assigning, awaiting, returning, or passing the task to any call keeps a
reference (and shows intent).
"""

from __future__ import annotations

import ast

from .core import attr_chain

# (dotted-prefix) calls that block the calling thread.  Matching is on
# the trailing components of the attribute chain, so `self.wal.fsync`,
# `os.fsync`, and `wal.fsync` all hit the `fsync` entry.
_BLOCKING_TAILS = {
    ("time", "sleep"): "time.sleep() blocks the loop — use asyncio.sleep",
    ("os", "fsync"): "os.fsync() is a disk barrier on the event loop",
    ("os", "fdatasync"): "os.fdatasync() is a disk barrier on the event loop",
    ("os", "fdopen"): "sync file I/O on the event loop "
                      "(hop via run_in_executor)",
    ("subprocess", "run"): "subprocess.run() blocks until the child exits",
    ("subprocess", "call"): "subprocess.call() blocks until the child exits",
    ("subprocess", "check_call"): "subprocess.check_call() blocks",
    ("subprocess", "check_output"): "subprocess.check_output() blocks",
    ("np", "asarray"):
        "np.asarray() on the loop materializes (blocking D2H when the "
        "operand is a device array)",
    ("np", "array"):
        "np.array() on the loop materializes (blocking D2H when the "
        "operand is a device array)",
}
# single-name method tails that block regardless of the receiver
_BLOCKING_METHODS = {
    "fsync": "fsync is a disk barrier on the event loop",
    "fdatasync": "fdatasync is a disk barrier on the event loop",
    "block_until_ready":
        "block_until_ready() parks the loop for the whole device window",
    "fsync_if_dirty": "WAL fsync is a disk barrier on the event loop",
}
# builtins that are sync file I/O when called in an async body
_BLOCKING_BUILTINS = {
    "open": "sync file open() on the event loop (hop via run_in_executor)",
}

_SPAWN_METHODS = ("create_task", "ensure_future")


def _blocking_reason(call: ast.Call):
    chain = attr_chain(call.func)
    if not chain:
        return None
    if len(chain) == 1:
        return _BLOCKING_BUILTINS.get(chain[0])
    tail2 = chain[-2:]
    if tail2 in _BLOCKING_TAILS:
        return _BLOCKING_TAILS[tail2]
    return _BLOCKING_METHODS.get(chain[-1])


def _is_task_spawn(call: ast.Call) -> bool:
    # attr-based so `asyncio.get_running_loop().create_task(...)` —
    # whose receiver is a Call, not a name chain — still matches
    if (isinstance(call.func, ast.Attribute)
            and call.func.attr in _SPAWN_METHODS):
        return True
    # bare `create_task(...)` / `ensure_future(...)` via from-import
    return (isinstance(call.func, ast.Name)
            and call.func.id in _SPAWN_METHODS)


class _AsyncBodyWalker(ast.NodeVisitor):
    """Visit one async def's body without descending into nested
    function scopes (a nested sync def/lambda runs elsewhere — usually
    on an executor — so its calls are not loop-blocking here)."""

    def __init__(self, src, func, findings):
        self.src = src
        self.func = func
        self.findings = findings

    def visit_FunctionDef(self, node):   # do not descend
        pass

    def visit_AsyncFunctionDef(self, node):
        pass

    def visit_Lambda(self, node):
        pass

    def visit_Call(self, node):
        reason = _blocking_reason(node)
        if reason is not None:
            self.findings.append(self.src.finding(
                "A001", node,
                f"blocking call in async def `{self.func.name}`: {reason}"))
        self.generic_visit(node)


def rule_a001(sources) -> list:
    findings: list = []
    for src in sources:
        for node in ast.walk(src.tree):
            if not isinstance(node, ast.AsyncFunctionDef):
                continue
            walker = _AsyncBodyWalker(src, node, findings)
            for stmt in node.body:
                walker.visit(stmt)
    return findings


def rule_a002(sources) -> list:
    findings: list = []
    for src in sources:
        for node in ast.walk(src.tree):
            if not (isinstance(node, ast.Expr)
                    and isinstance(node.value, ast.Call)
                    and _is_task_spawn(node.value)):
                continue
            fn = ".".join(attr_chain(node.value.func)) or (
                node.value.func.attr
                if isinstance(node.value.func, ast.Attribute)
                else node.value.func.id)
            findings.append(src.finding(
                "A002", node.value,
                f"task from `{fn}(...)` is dropped — the loop holds "
                f"tasks weakly, so gc can destroy it mid-flight "
                f"(store it and await/cancel on shutdown, or chain a "
                f"done-callback)"))
    return findings
