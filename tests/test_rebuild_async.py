"""Off-loop incremental rebuilds (ops/jax_endpoint.py, AsyncRebuild
gate; docs/performance.md "Overload & rebuild behavior").

Contract under test: a delta the live device graph cannot absorb no
longer stalls every request behind a synchronous rebuild-under-lock.
Instead its affected (type, permission) closure is quarantined (routed
to the host oracle — answers stay exact), the replacement generation
builds on a background executor against a store snapshot while the old
generation keeps serving, deltas accumulated during the build replay
onto the candidate, and the swap happens atomically under a short lock.
The spare-pool low watermark additionally rebuilds preemptively so
new-object churn rarely forces a quarantine at all.
"""

import asyncio
import time

from spicedb_kubeapi_proxy_tpu.ops.jax_endpoint import JaxEndpoint
from spicedb_kubeapi_proxy_tpu.spicedb import schema as sch
from spicedb_kubeapi_proxy_tpu.spicedb.evaluator import Evaluator
from spicedb_kubeapi_proxy_tpu.spicedb.store import TupleStore
from spicedb_kubeapi_proxy_tpu.spicedb.types import (
    RelationshipUpdate,
    SubjectRef,
    UpdateOp,
    parse_relationship,
)
from spicedb_kubeapi_proxy_tpu.utils import devtel
from spicedb_kubeapi_proxy_tpu.utils.features import GATES

SCHEMA = """
definition user {}
definition doc {
  relation viewer: user
  relation editor: user
  permission view = viewer + editor
  permission edit = editor
}
"""


def touch(*rels):
    return [RelationshipUpdate(UpdateOp.TOUCH, parse_relationship(r))
            for r in rels]


def make_pair(rels, schema_text=SCHEMA):
    schema = sch.parse_schema(schema_text)
    jx = JaxEndpoint(schema, store=TupleStore())
    if rels:
        jx.store.write(touch(*rels))
    return jx, Evaluator(schema, jx.store)


def lr(jx, subject, perm="view"):
    return sorted(asyncio.run(jx.lookup_resources(
        "doc", perm, SubjectRef("user", subject))))


def agree(jx, oracle, subjects, perm="view"):
    for s in subjects:
        want = sorted(oracle.lookup_resources("doc", perm,
                                              SubjectRef("user", s)))
        assert lr(jx, s, perm) == want, (s, perm)


class TestOffLoopRebuild:
    def test_wildcard_write_quarantines_then_swaps(self):
        jx, oracle = make_pair(["doc:d0#viewer@user:a",
                                "doc:d1#editor@user:b"])
        agree(jx, oracle, ["a", "b"])
        rebuilds = jx.stats["rebuilds"]
        # wildcard tuples are baked into the compiled masks: the live
        # graph cannot absorb this delta
        jx.store.write(touch("doc:d2#viewer@user:*"))
        # answers are exact IMMEDIATELY (quarantined pairs -> oracle),
        # no multi-second stall, regardless of rebuild timing
        agree(jx, oracle, ["a", "b", "zed"])
        assert jx.stats["stale_pair_marks"] >= 1
        # quiesce: the background swap lands, quarantine clears
        assert jx.wait_rebuilds()
        assert jx.stats["rebuilds"] == rebuilds + 1
        assert not jx._stale_pairs
        assert jx.stats["bg_rebuilds"] >= 1
        # post-swap the kernel serves the wildcard natively
        routed = jx.stats["stale_routed"]
        agree(jx, oracle, ["a", "b", "zed"])
        assert jx.stats["stale_routed"] == routed

    def test_unaffected_pairs_stay_on_kernel_during_quarantine(self):
        jx, oracle = make_pair(["doc:d0#viewer@user:a",
                                "doc:d1#editor@user:b"])
        agree(jx, oracle, ["a", "b"])
        # block the background executor so the quarantine window is
        # observable deterministically
        from spicedb_kubeapi_proxy_tpu.ops import jax_endpoint as je
        import threading
        gate = threading.Event()
        orig = jx._build_candidate

        def slow_build():
            gate.wait(timeout=10)
            return orig()

        jx._build_candidate = slow_build
        try:
            jx.store.write(touch("doc:d2#viewer@user:*"))
            routed = jx.stats["stale_routed"]
            # `view`'s closure includes viewer -> quarantined (oracle)
            agree(jx, oracle, ["a", "zed"])
            assert jx.stats["stale_routed"] > routed
            assert ("doc", "view") in jx._stale_pairs
            # `edit` never traverses viewer: stays on the kernel
            assert ("doc", "edit") not in jx._stale_pairs
            routed = jx.stats["stale_routed"]
            agree(jx, oracle, ["b"], perm="edit")
            assert jx.stats["stale_routed"] == routed
        finally:
            gate.set()
            jx._build_candidate = orig
        assert jx.wait_rebuilds()
        assert not jx._stale_pairs

    def test_hbm_ledger_invariant_across_background_swap(self):
        jx, oracle = make_pair(["doc:d0#viewer@user:a"])
        agree(jx, oracle, ["a"])
        old_gen = jx._devtel_gen
        old_bytes = devtel.LEDGER.generation_bytes(old_gen)
        assert old_bytes > 0
        jx.store.write(touch("doc:d1#viewer@user:*"))
        lr(jx, "a")
        assert jx.wait_rebuilds()
        new_gen = jx._devtel_gen
        assert new_gen != old_gen
        # the outgoing generation retired wholesale; the new one owns
        # all registered graph bytes
        assert devtel.LEDGER.generation_bytes(old_gen) == 0
        assert devtel.LEDGER.generation_bytes(new_gen) > 0

    def test_preemptive_rebuild_refreshes_pool_before_dry(self, monkeypatch):
        from spicedb_kubeapi_proxy_tpu.ops import jax_endpoint as je
        monkeypatch.setattr(je, "_SPARE_FLOOR", 16)
        jx, oracle = make_pair(["doc:d0#viewer@user:a"])
        agree(jx, oracle, ["a"])
        # 13 brand-new ids: pool 16 -> 3 free, under the 25% watermark
        for k in range(13):
            jx.store.write(touch(f"doc:new{k}#viewer@user:a"))
        agree(jx, oracle, ["a"])
        assert jx.wait_rebuilds()
        assert jx.stats["preemptive_rebuilds"] >= 1
        # the refreshed pool covers continued churn without quarantine
        marks = jx.stats["stale_pair_marks"]
        for k in range(13, 20):
            jx.store.write(touch(f"doc:new{k}#viewer@user:a"))
        agree(jx, oracle, ["a"])
        assert jx.wait_rebuilds()
        assert lr(jx, "a") == sorted(["d0"] + [f"new{k}" for k in range(20)])

    def test_concurrent_traffic_across_rebuilds_pinned_consistency(
            self, monkeypatch):
        """Oracle referee under churn: monotone appends mean every LR
        answer must equal {d0..dK} for some K inside the [before, after]
        revision window of the call — stale or torn reads fail this.
        The tiny spare pool forces repeated background rebuilds while
        the queries run."""
        from spicedb_kubeapi_proxy_tpu.ops import jax_endpoint as je
        monkeypatch.setattr(je, "_SPARE_FLOOR", 4)
        jx, _ = make_pair(["doc:d0#viewer@user:a"])
        lr(jx, "a")

        async def run():
            written = [0]   # highest dK committed
            errors = []

            async def writer():
                for k in range(1, 60):
                    await jx.write_relationships(
                        touch(f"doc:d{k}#viewer@user:a"))
                    written[0] = k
                    await asyncio.sleep(0.002)

            async def reader(i):
                sub = SubjectRef("user", "a")
                while written[0] < 59:
                    lo = written[0]
                    ids = await jx.lookup_resources("doc", "view", sub)
                    hi = written[0]
                    got = sorted(int(x[1:]) for x in ids)
                    k = len(got) - 1
                    if got != list(range(k + 1)):
                        errors.append(("torn", got))
                    if not (lo <= k <= hi):
                        errors.append(("window", lo, k, hi))
                    await asyncio.sleep(0.001)

            await asyncio.gather(writer(), reader(0), reader(1))
            return errors

        errors = asyncio.run(run())
        assert not errors, errors[:5]
        assert jx.wait_rebuilds()
        assert jx.stats["bg_rebuilds"] + jx.stats["preemptive_rebuilds"] >= 1
        assert lr(jx, "a") == sorted(f"d{k}" for k in range(60))

    def test_sync_killswitch_reproduces_blocking_rebuild(self, monkeypatch):
        monkeypatch.setattr(GATES._gates["AsyncRebuild"], "value", False)
        jx, oracle = make_pair(["doc:d0#viewer@user:a"])
        agree(jx, oracle, ["a"])
        rebuilds = jx.stats["rebuilds"]
        jx.store.write(touch("doc:d1#viewer@user:*"))
        agree(jx, oracle, ["a", "zed"])
        # gate off: the rebuild happened synchronously inside the query
        assert jx.stats["rebuilds"] == rebuilds + 1
        assert jx.stats["bg_rebuilds"] == 0
        assert not jx._stale_pairs

    def test_force_rebuild_supersedes_background_candidate(self):
        jx, oracle = make_pair(["doc:d0#viewer@user:a"])
        agree(jx, oracle, ["a"])
        import threading
        gate = threading.Event()
        orig = jx._build_candidate
        builds = []

        def slow_build():
            builds.append(1)
            st = orig()
            if len(builds) == 1:
                gate.wait(timeout=10)
            return st

        jx._build_candidate = slow_build
        try:
            jx.store.write(touch("doc:d1#viewer@user:*"))
            lr(jx, "a")  # kicks the background rebuild
            assert jx.rebuild_inflight
            # a sync rebuild lands first: the background candidate must
            # abandon itself instead of clobbering the newer generation
            jx._build_candidate = orig
            jx.force_rebuild()
            gen_after_force = jx._devtel_gen
            gate.set()
            assert jx.wait_rebuilds()
            assert jx._devtel_gen == gen_after_force, \
                "stale background candidate overwrote a newer generation"
        finally:
            gate.set()
            jx._build_candidate = orig
        agree(jx, oracle, ["a", "zed"])

    def test_event_loop_tick_jitter_bounded_during_rebuild(self):
        """The rebuild runs on its own executor: the event loop must
        keep ticking while a sizable graph compiles in the background.
        Ambient-calibrated bound (same idiom as the concurrency-stress
        suite) so loaded CI boxes don't flake."""
        jx, _ = make_pair([])
        jx.store.bulk_load([
            parse_relationship(f"doc:d{i}#viewer@user:u{i % 97}")
            for i in range(12_000)])
        lr(jx, "u0")

        async def measure(during_rebuild):
            if during_rebuild:
                jx.store.write(touch("doc:w#viewer@user:*"))
                await jx.lookup_resources("doc", "view",
                                          SubjectRef("user", "u0"))
            ticks = []
            t_prev = time.perf_counter()
            deadline = t_prev + (1.5 if during_rebuild else 0.3)
            while time.perf_counter() < deadline:
                await asyncio.sleep(0.005)
                now = time.perf_counter()
                ticks.append(now - t_prev)
                t_prev = now
                if during_rebuild and not jx.rebuild_inflight and ticks:
                    break
            return max(ticks)

        base = asyncio.run(measure(False))
        worst = asyncio.run(measure(True))
        assert jx.wait_rebuilds()
        bound = max(0.35, 8 * base)
        assert worst < bound, (
            f"event loop froze {worst * 1e3:.0f}ms during a background "
            f"rebuild (ambient bound {bound * 1e3:.0f}ms)")
