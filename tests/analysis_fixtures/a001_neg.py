"""A001 near-misses: the same calls, correctly hopped or out of scope."""
import asyncio
import time


async def hops_via_executor(loop, wal):
    # the blocking call sits in a NESTED sync scope handed to the
    # executor — exactly the legal pattern
    def _flush():
        time.sleep(0.1)
        wal.fsync()

    await loop.run_in_executor(None, _flush)


async def hops_via_lambda(loop, fd):
    import os
    await loop.run_in_executor(None, lambda: os.fsync(fd))


async def passes_reference(loop, wal):
    # a bare reference is not a call
    await loop.run_in_executor(None, wal.fsync_if_dirty)


def sync_helper_can_block(path):
    # not an async def: blocking here is the executor's business
    time.sleep(0.1)
    with open(path) as f:
        return f.read()


async def to_thread_hop(wal):
    await asyncio.to_thread(wal.fsync)
