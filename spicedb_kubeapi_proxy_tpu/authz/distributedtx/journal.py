"""Durable workflow journal: SQLite file or in-memory.

The event-sourced store behind the dual-write engine (reference uses
go-workflows with a SQLite backend, pkg/authz/distributedtx/client.go:18-30).
Every activity completion is journaled; on crash the instance replays and
completed activities return their recorded results instead of re-executing.
The journal file is the proxy's only durable state (SURVEY.md §5
checkpoint/resume).
"""

from __future__ import annotations

import json
import sqlite3
import threading
import time
from dataclasses import dataclass
from typing import Any, Optional

STATUS_PENDING = "pending"
STATUS_COMPLETED = "completed"
STATUS_FAILED = "failed"


@dataclass
class InstanceRecord:
    instance_id: str
    workflow: str
    input: dict
    status: str
    result: Optional[dict] = None
    error: str = ""
    attempts: int = 0


class Journal:
    """Interface; see SQLiteJournal / MemoryJournal."""

    def create_instance(self, instance_id: str, workflow: str, input: dict) -> None:
        raise NotImplementedError

    def get_instance(self, instance_id: str) -> Optional[InstanceRecord]:
        raise NotImplementedError

    def pending_instances(self) -> list:
        raise NotImplementedError

    def record_event(self, instance_id: str, seq: int, activity: str,
                     result: Any, error: str = "") -> None:
        raise NotImplementedError

    def events(self, instance_id: str) -> list:
        """[(seq, activity, result, error)] ordered by seq."""
        raise NotImplementedError

    def complete_instance(self, instance_id: str, result: Optional[dict],
                          error: str = "") -> None:
        raise NotImplementedError

    def bump_attempts(self, instance_id: str) -> int:
        raise NotImplementedError

    def prune_completed(self, keep_last: int = 1000) -> None:
        """Drop all but the most recent `keep_last` finished instances so
        the journal (the proxy's only durable state) doesn't grow without
        bound with total request count."""
        raise NotImplementedError

    def close(self) -> None:
        pass


class SQLiteJournal(Journal):
    def __init__(self, path: str):
        self.path = path
        self._lock = threading.Lock()
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute("""
            CREATE TABLE IF NOT EXISTS instances (
                instance_id TEXT PRIMARY KEY,
                workflow TEXT NOT NULL,
                input TEXT NOT NULL,
                status TEXT NOT NULL,
                result TEXT,
                error TEXT DEFAULT '',
                attempts INTEGER DEFAULT 0,
                created REAL
            )""")
        self._conn.execute("""
            CREATE TABLE IF NOT EXISTS events (
                instance_id TEXT NOT NULL,
                seq INTEGER NOT NULL,
                activity TEXT NOT NULL,
                result TEXT,
                error TEXT DEFAULT '',
                PRIMARY KEY (instance_id, seq)
            )""")
        self._conn.commit()

    def create_instance(self, instance_id: str, workflow: str, input: dict) -> None:
        with self._lock:
            self._conn.execute(
                "INSERT INTO instances (instance_id, workflow, input, status,"
                " created) VALUES (?, ?, ?, ?, ?)",
                (instance_id, workflow, json.dumps(input), STATUS_PENDING,
                 time.time()))
            self._conn.commit()

    def get_instance(self, instance_id: str) -> Optional[InstanceRecord]:
        with self._lock:
            row = self._conn.execute(
                "SELECT instance_id, workflow, input, status, result, error,"
                " attempts FROM instances WHERE instance_id = ?",
                (instance_id,)).fetchone()
        if row is None:
            return None
        return InstanceRecord(
            instance_id=row[0], workflow=row[1], input=json.loads(row[2]),
            status=row[3], result=json.loads(row[4]) if row[4] else None,
            error=row[5] or "", attempts=row[6])

    def pending_instances(self) -> list:
        with self._lock:
            rows = self._conn.execute(
                "SELECT instance_id FROM instances WHERE status = ?"
                " ORDER BY created", (STATUS_PENDING,)).fetchall()
        return [r[0] for r in rows]

    def record_event(self, instance_id: str, seq: int, activity: str,
                     result: Any, error: str = "") -> None:
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO events (instance_id, seq, activity,"
                " result, error) VALUES (?, ?, ?, ?, ?)",
                (instance_id, seq, activity, json.dumps(result), error))
            self._conn.commit()

    def events(self, instance_id: str) -> list:
        with self._lock:
            rows = self._conn.execute(
                "SELECT seq, activity, result, error FROM events WHERE"
                " instance_id = ? ORDER BY seq", (instance_id,)).fetchall()
        return [(r[0], r[1], json.loads(r[2]) if r[2] else None, r[3] or "")
                for r in rows]

    def complete_instance(self, instance_id: str, result: Optional[dict],
                          error: str = "") -> None:
        with self._lock:
            self._conn.execute(
                "UPDATE instances SET status = ?, result = ?, error = ?"
                " WHERE instance_id = ?",
                (STATUS_FAILED if error else STATUS_COMPLETED,
                 json.dumps(result) if result is not None else None,
                 error, instance_id))
            self._conn.commit()

    def bump_attempts(self, instance_id: str) -> int:
        with self._lock:
            self._conn.execute(
                "UPDATE instances SET attempts = attempts + 1 WHERE"
                " instance_id = ?", (instance_id,))
            self._conn.commit()
            row = self._conn.execute(
                "SELECT attempts FROM instances WHERE instance_id = ?",
                (instance_id,)).fetchone()
        return row[0] if row else 0

    def prune_completed(self, keep_last: int = 1000) -> None:
        with self._lock:
            rows = self._conn.execute(
                "SELECT instance_id FROM instances WHERE status != ?"
                " ORDER BY created DESC", (STATUS_PENDING,)).fetchall()
            victims = [r[0] for r in rows[keep_last:]]
            for instance_id in victims:
                self._conn.execute("DELETE FROM events WHERE instance_id = ?",
                                   (instance_id,))
                self._conn.execute(
                    "DELETE FROM instances WHERE instance_id = ?",
                    (instance_id,))
            if victims:
                self._conn.commit()

    def close(self) -> None:
        with self._lock:
            self._conn.close()


class MemoryJournal(Journal):
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._instances: dict[str, InstanceRecord] = {}
        self._events: dict[str, list] = {}
        self._order: list = []

    def create_instance(self, instance_id: str, workflow: str, input: dict) -> None:
        with self._lock:
            self._instances[instance_id] = InstanceRecord(
                instance_id=instance_id, workflow=workflow, input=input,
                status=STATUS_PENDING)
            self._order.append(instance_id)

    def get_instance(self, instance_id: str) -> Optional[InstanceRecord]:
        with self._lock:
            rec = self._instances.get(instance_id)
            if rec is None:
                return None
            return InstanceRecord(**vars(rec))

    def pending_instances(self) -> list:
        with self._lock:
            return [i for i in self._order
                    if self._instances[i].status == STATUS_PENDING]

    def record_event(self, instance_id: str, seq: int, activity: str,
                     result: Any, error: str = "") -> None:
        with self._lock:
            events = self._events.setdefault(instance_id, [])
            events[:] = [e for e in events if e[0] != seq]
            events.append((seq, activity, json.loads(json.dumps(result)), error))
            events.sort(key=lambda e: e[0])

    def events(self, instance_id: str) -> list:
        with self._lock:
            return list(self._events.get(instance_id, []))

    def complete_instance(self, instance_id: str, result: Optional[dict],
                          error: str = "") -> None:
        with self._lock:
            rec = self._instances[instance_id]
            rec.status = STATUS_FAILED if error else STATUS_COMPLETED
            rec.result = result
            rec.error = error

    def bump_attempts(self, instance_id: str) -> int:
        with self._lock:
            rec = self._instances[instance_id]
            rec.attempts += 1
            return rec.attempts

    def prune_completed(self, keep_last: int = 1000) -> None:
        with self._lock:
            finished = [i for i in self._order
                        if self._instances[i].status != STATUS_PENDING]
            for instance_id in finished[:-keep_last] if keep_last else finished:
                self._instances.pop(instance_id, None)
                self._events.pop(instance_id, None)
                self._order.remove(instance_id)
