"""Zero-dependency lint gate (reference runs golangci-lint in CI,
/root/reference/.github/workflows/build-test.yaml:56-92 and
magefiles/lint.go; this sandbox has no ruff/flake8 baked in, so the
local gate is an AST pass over the same high-signal rule families —
CI additionally runs real ruff, see .github/workflows/build-test.yaml).

Checks:
  F401  unused import (module scope; `__future__` exempt)
  E722  bare `except:`
  B006  mutable default argument
  E711  comparison to None with ==/!=
  F811  redefinition of a top-level def/class in the same scope
  W291  trailing whitespace
  E501  line longer than 100 characters
  TAB   hard tab in indentation

(E712 `== True` is deliberately NOT enforced: the codebase compares
numpy bools where `is True` would silently change semantics.)

Exit 1 on any finding.  Usage: python scripts/lint.py [paths...]
"""

import ast
import sys
from pathlib import Path

DEFAULT_PATHS = ["spicedb_kubeapi_proxy_tpu", "tests", "scripts",
                 "bench.py", "__graft_entry__.py"]
MAX_LINE = 100


def iter_py(paths):
    for p in paths:
        p = Path(p)
        if p.is_dir():
            yield from sorted(p.rglob("*.py"))
        elif p.suffix == ".py":
            yield p


class Visitor(ast.NodeVisitor):
    def __init__(self, findings, path):
        self.findings = findings
        self.path = path
        self.imports: dict = {}   # name -> (lineno, import stmt text)
        self.used: set = set()
        self.toplevel_defs: dict = {}

    def visit_Import(self, node):
        for a in node.names:
            name = (a.asname or a.name).split(".")[0]
            self.imports[name] = node.lineno
        self.generic_visit(node)

    def visit_ImportFrom(self, node):
        if node.module == "__future__":
            return
        for a in node.names:
            if a.name == "*":
                continue
            self.imports[a.asname or a.name] = node.lineno
        self.generic_visit(node)

    def visit_Name(self, node):
        if isinstance(node.ctx, ast.Load):
            self.used.add(node.id)
        self.generic_visit(node)

    def visit_Attribute(self, node):
        self.generic_visit(node)

    def visit_ExceptHandler(self, node):
        if node.type is None:
            self.findings.append(
                (self.path, node.lineno, "E722", "bare `except:`"))
        self.generic_visit(node)

    def _check_defaults(self, node):
        for d in list(node.args.defaults) + [
                d for d in node.args.kw_defaults if d is not None]:
            if isinstance(d, (ast.List, ast.Dict, ast.Set)):
                self.findings.append(
                    (self.path, d.lineno, "B006",
                     "mutable default argument"))

    def visit_FunctionDef(self, node):
        self._check_defaults(node)
        self.generic_visit(node)

    def visit_AsyncFunctionDef(self, node):
        self._check_defaults(node)
        self.generic_visit(node)

    def visit_Compare(self, node):
        for op, cmp in zip(node.ops, node.comparators):
            if isinstance(op, (ast.Eq, ast.NotEq)):
                if isinstance(cmp, ast.Constant) and cmp.value is None:
                    self.findings.append(
                        (self.path, node.lineno, "E711",
                         "comparison to None with ==/!= (use is/is not)"))
        self.generic_visit(node)


def lint_file(path, findings):
    text = path.read_text()
    try:
        tree = ast.parse(text, filename=str(path))
    except SyntaxError as e:
        findings.append((path, e.lineno or 0, "E999", f"syntax error: {e}"))
        return
    v = Visitor(findings, path)
    v.visit(tree)

    # unused imports: names imported at module scope and never loaded
    # anywhere in the file (conservative: attribute/string uses of the
    # name are caught by the Load-name scan; __all__ and re-exports in
    # __init__.py are exempt)
    src_names = v.used
    exempt = path.name == "__init__.py" or "__all__" in text
    if not exempt:
        for name, lineno in v.imports.items():
            if name not in src_names and f"{name}." not in text:
                findings.append((path, lineno, "F401",
                                 f"unused import `{name}`"))

    # top-level redefinitions
    seen: dict = {}
    for node in tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            if node.name in seen:
                findings.append((path, node.lineno, "F811",
                                 f"redefinition of `{node.name}` "
                                 f"(first at line {seen[node.name]})"))
            seen[node.name] = node.lineno

    for i, line in enumerate(text.splitlines(), 1):
        if line != line.rstrip():
            findings.append((path, i, "W291", "trailing whitespace"))
        if len(line) > MAX_LINE:
            findings.append((path, i, "E501",
                             f"line too long ({len(line)} > {MAX_LINE})"))
        stripped = line.lstrip(" ")
        if stripped.startswith("\t"):
            findings.append((path, i, "TAB", "hard tab in indentation"))


def main():
    paths = sys.argv[1:] or DEFAULT_PATHS
    findings: list = []
    n = 0
    for f in iter_py(paths):
        n += 1
        lint_file(f, findings)
    for path, lineno, code, msg in sorted(findings,
                                          key=lambda x: (str(x[0]), x[1])):
        print(f"{path}:{lineno}: {code} {msg}")
    print(f"lint: {n} files, {len(findings)} findings")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
