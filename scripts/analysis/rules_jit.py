"""A005 — jit purity by call-graph reach (supersedes M003's comment
fences).

M003 could only see host work inside `# hotpath:` fenced regions; the
first-use compile stalls PR 8 chased lived in UNfenced helpers reached
from jitted entry points.  This rule finds every `jax.jit(...)` site in
`ops/`, resolves the jitted function, and walks the lexical call graph
from it — including the factory idiom this codebase uses everywhere
(`evaluate = make_ell_evaluate(...)` where `make_ell_evaluate` is a
module-level factory returning a locally-defined closure): calls
through the bound name reach the factory's returned defs.  Inside any
reached ("traced") function it flags:

  * host `np.` array construction (anything that MAKES an array; dtype
    descriptors stay legal — same whitelist as M003) — a silent
    device->host->device round trip on every call;
  * `time.*` / `random.*` / `datetime.now` — trace-time constants
    frozen into the compiled kernel, a classic silent-staleness bug;
  * `.item()` / `np.asarray` — forced materialization that blocks on
    the device inside the traced region;
  * Python `for`/`while` whose trip condition reads a traced PARAMETER
    — either a TracerConversionError at first call or a per-shape
    retrace storm (the PR 8 compile-stall class); loops over closure
    constants (static unroll, e.g. staged sweeps) are legal and not
    flagged.
"""

from __future__ import annotations

import ast

from .core import attr_chain

_NP_DTYPE_WHITELIST = frozenset((
    "ndarray", "dtype", "int32", "int64", "uint32", "uint8", "float32",
    "float64", "bool_", "uint64", "int8", "int16", "uint16", "integer",
    "floating", "generic",
))
# trace-time clock/randomness calls: any `time.*` / `random.*` call,
# plus `.now()` through a datetime chain (`datetime.now`,
# `datetime.datetime.now`)
_CLOCK_ROOTS = ("time", "random")


class _Scope:
    """One module's lexical function index: qualname -> def node, plus
    factory returns and jit roots."""

    def __init__(self, src):
        self.src = src
        self.defs: dict = {}        # qualname -> node
        self.children: dict = {}    # qualname -> {bare name -> qualname}
        for node in ast.walk(src.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = src.qualnames[id(node)]
                self.defs[qual] = node
        for qual in self.defs:
            parent = qual.rsplit(".", 1)[0] if "." in qual else ""
            self.children.setdefault(parent, {})[
                qual.rsplit(".", 1)[-1]] = qual
        # module-level factories: def F(): ... return <local def name>
        self.factory_returns: dict = {}   # func qualname -> [qualnames]
        for qual, node in self.defs.items():
            returned = []
            local = self.children.get(qual, {})
            for sub in ast.walk(node):
                if (isinstance(sub, ast.Return)
                        and isinstance(sub.value, ast.Name)
                        and sub.value.id in local):
                    returned.append(local[sub.value.id])
            if returned:
                self.factory_returns[qual] = returned

    def resolve(self, name: str, from_qual: str):
        """Bare callee name -> qualname, walking enclosing scopes."""
        scope = from_qual
        while True:
            hit = self.children.get(scope, {}).get(name)
            if hit is not None:
                return hit
            if not scope:
                return None
            scope = scope.rsplit(".", 1)[0] if "." in scope else ""


def _is_jit_ref(node) -> bool:
    chain = attr_chain(node)
    return chain[-2:] == ("jax", "jit") or chain[-1:] == ("jit",)


def _jit_roots(scope) -> list:
    """Function qualnames jitted anywhere in the file: the call form
    `jax.jit(fn, ...)`, the decorator forms `@jax.jit` /
    `@jax.jit(...)`, and `@partial(jax.jit, ...)`."""
    roots = []
    for node in ast.walk(scope.src.tree):
        if isinstance(node, ast.Call) and _is_jit_ref(node.func):
            if node.args and isinstance(node.args[0], ast.Name):
                enclosing = scope.src.symbol_at(node)
                target = scope.resolve(node.args[0].id, enclosing)
                if target is not None:
                    roots.append(target)
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            for dec in node.decorator_list:
                jitted = (
                    _is_jit_ref(dec)                       # @jax.jit
                    or (isinstance(dec, ast.Call)
                        and (_is_jit_ref(dec.func)         # @jax.jit(...)
                             or (attr_chain(dec.func)[-1:]
                                 == ("partial",)           # @partial(jax.jit)
                                 and any(_is_jit_ref(a)
                                         for a in dec.args)))))
                if jitted:
                    roots.append(scope.src.qualnames[id(node)])
                    break
    return roots


def _reach(scope, roots) -> set:
    """Traced set: closure over bare-name calls, factory-bound names
    (`x = factory(...)` -> factory's returned defs), and factory
    returns themselves."""
    # per-function: names bound from factory calls
    traced: set = set()
    work = list(roots)
    while work:
        qual = work.pop()
        if qual in traced or qual not in scope.defs:
            continue
        traced.add(qual)
        node = scope.defs[qual]
        bound: dict = {}   # local name -> [callee qualnames]
        # include bindings made in ENCLOSING defs (closures see them)
        for enc_qual, enc_node in scope.defs.items():
            if not (qual == enc_qual or qual.startswith(enc_qual + ".")):
                continue
            for sub in ast.walk(enc_node):
                if (isinstance(sub, ast.Assign)
                        and isinstance(sub.value, ast.Call)
                        and isinstance(sub.value.func, ast.Name)):
                    factory = scope.resolve(sub.value.func.id, enc_qual)
                    rets = scope.factory_returns.get(factory or "", ())
                    if not rets:
                        continue
                    for tgt in sub.targets:
                        if isinstance(tgt, ast.Name):
                            bound.setdefault(tgt.id, []).extend(rets)
        for sub in ast.walk(node):
            if isinstance(sub, ast.Call) and isinstance(sub.func, ast.Name):
                callee = scope.resolve(sub.func.id, qual)
                if callee is not None:
                    work.append(callee)
                work.extend(bound.get(sub.func.id, ()))
    return traced


def _check_traced(src, qual, node, findings) -> None:
    params = {a.arg for a in (node.args.args + node.args.posonlyargs
                              + node.args.kwonlyargs)}
    nested = {id(n) for n in ast.walk(node)
              if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
              and n is not node}

    def in_nested(n):
        cur = src.parents.get(n)
        while cur is not None and cur is not node:
            if id(cur) in nested:
                return True
            cur = src.parents.get(cur)
        return False

    for sub in ast.walk(node):
        if in_nested(sub):
            continue   # nested defs are traced separately if reached
        if isinstance(sub, ast.Call):
            chain = attr_chain(sub.func)
            if (isinstance(sub.func, ast.Attribute)
                    and sub.func.attr == "item"):
                # attr-based, not chain-based: `x.sum().item()` has no
                # resolvable name chain but blocks all the same
                findings.append(src.finding(
                    "A005", sub,
                    f"`.item()` inside jit-reached `{qual}` forces a "
                    f"blocking device materialization at trace time"))
            elif (len(chain) >= 2 and chain[-2] == "np"
                    and chain[-1] not in _NP_DTYPE_WHITELIST):
                findings.append(src.finding(
                    "A005", sub,
                    f"host `np.{chain[-1]}(...)` inside jit-reached "
                    f"`{qual}` — host work in a traced function runs "
                    f"per call on the device round trip"))
            elif (len(chain) >= 2 and chain[0] in _CLOCK_ROOTS) or (
                    len(chain) >= 2 and chain[-1] == "now"
                    and chain[0] == "datetime"):
                findings.append(src.finding(
                    "A005", sub,
                    f"`{'.'.join(chain)}(...)` inside jit-reached "
                    f"`{qual}` is frozen at trace time — the compiled "
                    f"kernel replays the first call's value forever"))
        elif isinstance(sub, (ast.For, ast.While)):
            test = sub.iter if isinstance(sub, ast.For) else sub.test
            # a param used through an attribute access is static at
            # trace time (`range(1, idx.shape[1])` unrolls over a shape
            # constant, `expr.children` is static pytree structure);
            # only a DIRECT use of the param drives the loop by a
            # traced value
            hot = set()
            for n in ast.walk(test):
                if (isinstance(n, ast.Name) and n.id in params
                        and not isinstance(src.parents.get(n),
                                           ast.Attribute)):
                    hot.add(n.id)
            if hot:
                kind = "for" if isinstance(sub, ast.For) else "while"
                findings.append(src.finding(
                    "A005", sub,
                    f"Python `{kind}` over traced parameter(s) "
                    f"{sorted(hot)} in jit-reached `{qual}` — use "
                    f"lax.scan/while_loop (a Python loop either fails "
                    f"tracing or retraces per shape)"))


def rule_a005(sources) -> list:
    findings: list = []
    for src in sources:
        if "/ops/" not in "/" + src.rel.replace("\\", "/"):
            continue
        scope = _Scope(src)
        roots = _jit_roots(scope)
        if not roots:
            continue
        for qual in sorted(_reach(scope, roots)):
            _check_traced(src, qual, scope.defs[qual], findings)
    return findings
