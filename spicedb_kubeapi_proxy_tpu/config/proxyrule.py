"""ProxyRule config schema (`authzed.com/v1alpha1`, kind ProxyRule).

Typed dataclasses + multi-doc YAML parsing + validation, mirroring the
reference schema and its validator semantics (reference:
pkg/config/proxyrule/rule.go:12-272):

- `match` required, each entry needs apiVersion/resource/verbs with verbs in
  the fixed kube verb set
- `StringOrTemplate` is exactly one of `tpl` | structured template | `tupleSet`
- prefilter / postfilter / update substructures.
"""

from __future__ import annotations

import io
from dataclasses import dataclass, field
from typing import Any, Optional, Union

import yaml

API_VERSION = "authzed.com/v1alpha1"
KIND = "ProxyRule"

# LookupResources requests use this resourceID value to indicate "match the
# object being processed" (reference rule.go:19-22).
MATCHING_ID_FIELD_VALUE = "$"

PESSIMISTIC_LOCK_MODE = "Pessimistic"
OPTIMISTIC_LOCK_MODE = "Optimistic"

ALLOWED_VERBS = ("get", "list", "watch", "create", "update", "patch", "delete")


class RuleValidationError(ValueError):
    pass


@dataclass
class ObjectTemplate:
    type: str = ""
    id: str = ""
    relation: str = ""


@dataclass
class RelationshipTemplate:
    resource: ObjectTemplate = field(default_factory=ObjectTemplate)
    subject: ObjectTemplate = field(default_factory=ObjectTemplate)


@dataclass
class StringOrTemplate:
    """Exactly one of template / tuple_set / relationship_template is set."""
    template: str = ""
    tuple_set: str = ""
    relationship_template: Optional[RelationshipTemplate] = None

    def validate(self, path: str) -> None:
        count = sum([bool(self.template), bool(self.tuple_set),
                     self.relationship_template is not None])
        if count == 0:
            raise RuleValidationError(
                f"{path}: one of tpl, tupleSet, or a relationship template is required")
        if count > 1:
            raise RuleValidationError(
                f"{path}: tpl, tupleSet, and relationship template are mutually exclusive")


@dataclass
class Match:
    group_version: str = ""
    resource: str = ""
    verbs: list = field(default_factory=list)

    def validate(self, path: str) -> None:
        if not self.group_version:
            raise RuleValidationError(f"{path}.apiVersion is required")
        if not self.resource:
            raise RuleValidationError(f"{path}.resource is required")
        if not self.verbs:
            raise RuleValidationError(f"{path}.verbs must be non-empty")
        for v in self.verbs:
            if v not in ALLOWED_VERBS:
                raise RuleValidationError(
                    f"{path}.verbs: {v!r} is not one of {ALLOWED_VERBS}")


@dataclass
class PreFilter:
    from_object_id_name_expr: str = ""
    from_object_id_namespace_expr: str = ""
    lookup_matching_resources: Optional[StringOrTemplate] = None


@dataclass
class PostFilter:
    check_permission_template: Optional[StringOrTemplate] = None

    def validate(self, path: str) -> None:
        if self.check_permission_template is None:
            raise RuleValidationError(
                f"{path}.checkPermissionTemplate is required")
        self.check_permission_template.validate(path + ".checkPermissionTemplate")


@dataclass
class Update:
    precondition_exists: list = field(default_factory=list)
    precondition_does_not_exist: list = field(default_factory=list)
    creates: list = field(default_factory=list)
    touches: list = field(default_factory=list)
    deletes: list = field(default_factory=list)
    delete_by_filter: list = field(default_factory=list)

    def empty(self) -> bool:
        return not (self.precondition_exists or self.precondition_does_not_exist
                    or self.creates or self.touches or self.deletes
                    or self.delete_by_filter)


@dataclass
class Spec:
    locking: str = ""
    matches: list = field(default_factory=list)
    if_conditions: list = field(default_factory=list)
    checks: list = field(default_factory=list)
    post_checks: list = field(default_factory=list)
    pre_filters: list = field(default_factory=list)
    post_filters: list = field(default_factory=list)
    update: Update = field(default_factory=Update)


@dataclass
class Config:
    """A parsed ProxyRule document (TypeMeta + ObjectMeta + Spec inline)."""
    api_version: str = API_VERSION
    kind: str = KIND
    name: str = ""
    spec: Spec = field(default_factory=Spec)


def _string_or_template(raw: Any, path: str) -> StringOrTemplate:
    if not isinstance(raw, dict):
        raise RuleValidationError(f"{path}: expected a mapping, got {type(raw).__name__}")
    out = StringOrTemplate(
        template=raw.get("tpl", "") or "",
        tuple_set=raw.get("tupleSet", "") or "",
    )
    if "resource" in raw or "subject" in raw:
        res = raw.get("resource") or {}
        sub = raw.get("subject") or {}
        out.relationship_template = RelationshipTemplate(
            resource=ObjectTemplate(
                type=res.get("type", ""), id=res.get("id", ""),
                relation=res.get("relation", "")),
            subject=ObjectTemplate(
                type=sub.get("type", ""), id=sub.get("id", ""),
                relation=sub.get("relation", "")),
        )
    out.validate(path)
    return out


def _string_or_template_list(raw: Any, path: str) -> list:
    if raw is None:
        return []
    if not isinstance(raw, list):
        raise RuleValidationError(f"{path}: expected a list")
    return [_string_or_template(item, f"{path}[{i}]") for i, item in enumerate(raw)]


def parse_doc(doc: dict) -> Config:
    """Parse and validate a single ProxyRule YAML document."""
    if not isinstance(doc, dict):
        raise RuleValidationError(f"rule document must be a mapping, got {type(doc).__name__}")
    cfg = Config()
    cfg.api_version = doc.get("apiVersion", "")
    cfg.kind = doc.get("kind", "")
    meta = doc.get("metadata") or {}
    cfg.name = meta.get("name", "")

    spec = cfg.spec
    spec.locking = doc.get("lock", "") or ""
    if spec.locking and spec.locking not in (PESSIMISTIC_LOCK_MODE, OPTIMISTIC_LOCK_MODE):
        raise RuleValidationError(
            f"lock must be one of {OPTIMISTIC_LOCK_MODE!r}, {PESSIMISTIC_LOCK_MODE!r};"
            f" got {spec.locking!r}")

    raw_matches = doc.get("match")
    if not raw_matches or not isinstance(raw_matches, list):
        raise RuleValidationError("match is required and must be a non-empty list")
    for i, m in enumerate(raw_matches):
        if not isinstance(m, dict):
            raise RuleValidationError(f"match[{i}]: expected a mapping, got {type(m).__name__}")
        match = Match(
            group_version=m.get("apiVersion", ""),
            resource=m.get("resource", ""),
            verbs=list(m.get("verbs") or []),
        )
        match.validate(f"match[{i}]")
        spec.matches.append(match)

    raw_if = doc.get("if") or []
    if not isinstance(raw_if, list):
        raise RuleValidationError("if must be a list of CEL expressions")
    spec.if_conditions = [str(x) for x in raw_if]

    spec.checks = _string_or_template_list(doc.get("check"), "check")
    spec.post_checks = _string_or_template_list(doc.get("postcheck"), "postcheck")

    raw_pre = doc.get("prefilter") or []
    if not isinstance(raw_pre, list):
        raise RuleValidationError("prefilter must be a list")
    for i, p in enumerate(raw_pre):
        if not isinstance(p, dict):
            raise RuleValidationError(f"prefilter[{i}]: expected a mapping, got {type(p).__name__}")
        pf = PreFilter(
            from_object_id_name_expr=p.get("fromObjectIDNameExpr", "") or "",
            from_object_id_namespace_expr=p.get("fromObjectIDNamespaceExpr", "") or "",
        )
        if p.get("lookupMatchingResources") is not None:
            pf.lookup_matching_resources = _string_or_template(
                p["lookupMatchingResources"], f"prefilter[{i}].lookupMatchingResources")
        spec.pre_filters.append(pf)

    raw_post = doc.get("postfilter") or []
    if not isinstance(raw_post, list):
        raise RuleValidationError("postfilter must be a list")
    for i, p in enumerate(raw_post):
        if not isinstance(p, dict):
            raise RuleValidationError(
                f"postfilter[{i}]: expected a mapping, "
                f"got {type(p).__name__}")
        pf = PostFilter()
        if p.get("checkPermissionTemplate") is not None:
            pf.check_permission_template = _string_or_template(
                p["checkPermissionTemplate"], f"postfilter[{i}].checkPermissionTemplate")
        pf.validate(f"postfilter[{i}]")
        spec.post_filters.append(pf)

    raw_update = doc.get("update") or {}
    if not isinstance(raw_update, dict):
        raise RuleValidationError("update must be a mapping")
    if raw_update:
        u = spec.update
        u.precondition_exists = _string_or_template_list(
            raw_update.get("preconditionExists"), "update.preconditionExists")
        u.precondition_does_not_exist = _string_or_template_list(
            raw_update.get("preconditionDoesNotExist"), "update.preconditionDoesNotExist")
        u.creates = _string_or_template_list(raw_update.get("creates"), "update.creates")
        u.touches = _string_or_template_list(raw_update.get("touches"), "update.touches")
        u.deletes = _string_or_template_list(raw_update.get("deletes"), "update.deletes")
        u.delete_by_filter = _string_or_template_list(
            raw_update.get("deleteByFilter"), "update.deleteByFilter")
    return cfg


def parse(source: Union[str, bytes, io.IOBase]) -> list:
    """Parse multi-document YAML into a list of validated Configs
    (reference rule.go:215-239)."""
    if isinstance(source, io.IOBase):
        source = source.read()
    if isinstance(source, bytes):
        source = source.decode("utf-8")
    configs: list[Config] = []
    for doc in yaml.safe_load_all(source):
        if doc is None:
            continue
        configs.append(parse_doc(doc))
    return configs


def parse_file(path: str) -> list:
    with open(path, "r", encoding="utf-8") as f:
        return parse(f.read())
