#!/usr/bin/env python
"""Benchmark harness: authz checks/sec, jax:// kernel vs embedded oracle.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The headline config follows BASELINE.json: filtering list requests against a
1M-tuple multi-tenant depth-4 graph, 256 concurrent list subjects, on one
TPU chip.  `value` is effective authz checks/sec through LookupResources
(each batched LR answers <permission> for every object of the listed type,
i.e. batch_size x num_objects checks per kernel invocation); `vs_baseline`
is the speedup over the embedded (host oracle) backend on the same workload.

All progress/diagnostics go to stderr; stdout carries only the JSON line.
"""

from __future__ import annotations

import argparse
import json
import statistics
import sys
import time

# NOTE: do not touch JAX_PLATFORMS/PYTHONPATH here — the driver environment
# routes jax to the real TPU chip.


def log(msg: str) -> None:
    print(msg, file=sys.stderr, flush=True)


def build_endpoint(workload, kind: str):
    from spicedb_kubeapi_proxy_tpu.spicedb import schema as sch
    from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import EmbeddedEndpoint
    from spicedb_kubeapi_proxy_tpu.ops.jax_endpoint import JaxEndpoint

    schema = sch.parse_schema(workload.schema_text)
    t0 = time.time()
    ep = (JaxEndpoint(schema) if kind == "jax" else EmbeddedEndpoint(schema))
    # columnar bulk path: native parse -> store base layer, no per-tuple
    # Python objects
    ep.store.bulk_load_text("\n".join(workload.relationships))
    log(f"loaded {len(workload.relationships)} relationship lines "
        f"in {time.time() - t0:.1f}s (columnar)")
    return ep


def bench_jax(workload, batch: int, rounds: int) -> dict:
    import asyncio

    ep = build_endpoint(workload, "jax")
    subjects = [s for s in workload.subjects]

    from spicedb_kubeapi_proxy_tpu.spicedb.types import SubjectRef

    def batch_subjects(r):
        base = (r * batch) % max(1, len(subjects) - batch)
        return [SubjectRef("user", subjects[(base + i) % len(subjects)])
                for i in range(batch)]

    async def run():
        # warmup: builds device graph + compiles the kernel
        t0 = time.time()
        first = await ep.lookup_resources_batch(
            workload.resource_type, workload.permission, batch_subjects(0))
        warm = time.time() - t0
        n_obj = len(ep.store.object_ids_of_type(workload.resource_type))
        log(f"jax warmup {warm:.1f}s (graph build + XLA compile);"
            f" {n_obj} objects of type {workload.resource_type};"
            f" first batch allowed sizes sample"
            f" {[len(x) for x in first[:4]]}")
        times = []
        for r in range(rounds):
            t0 = time.time()
            await ep.lookup_resources_batch(
                workload.resource_type, workload.permission,
                batch_subjects(r + 1))
            times.append(time.time() - t0)
        per_batch = statistics.median(times)
        checks = batch * n_obj
        return {
            "per_batch_s": per_batch,
            "p99_s": sorted(times)[max(0, int(len(times) * 0.99) - 1)],
            "checks_per_s": checks / per_batch,
            "objects": n_obj,
            "warmup_s": warm,
        }

    return asyncio.run(run())


def bench_concurrent(workload, batch: int, rounds: int) -> dict:
    """BASELINE config-5 shape: `batch` concurrent list requests, each
    issuing a single-subject LookupResources, fused by the cross-request
    dispatcher (spicedb/dispatch.py) into device batches."""
    import asyncio

    from spicedb_kubeapi_proxy_tpu.spicedb.dispatch import BatchingEndpoint
    from spicedb_kubeapi_proxy_tpu.spicedb.types import SubjectRef

    ep = BatchingEndpoint(build_endpoint(workload, "jax"))
    subjects = workload.subjects

    async def one_round(r):
        async def caller(i):
            s = SubjectRef("user", subjects[(r * batch + i) % len(subjects)])
            return await ep.lookup_resources(
                workload.resource_type, workload.permission, s)
        t0 = time.time()
        await asyncio.gather(*[caller(i) for i in range(batch)])
        return time.time() - t0

    async def run():
        await one_round(0)  # warmup compile
        times = [await one_round(r + 1) for r in range(rounds)]
        n_obj = len(ep.store.object_ids_of_type(workload.resource_type))
        per_round = statistics.median(times)
        log(f"dispatch stats: {ep.stats}")
        return {
            "per_round_s": per_round,
            "checks_per_s": batch * n_obj / per_round,
            "objects": n_obj,
            "fused_lookups": ep.stats["fused_lookups"],
        }

    return asyncio.run(run())


def bench_oracle(workload, queries: int) -> dict:
    import asyncio

    ep = build_endpoint(workload, "embedded")
    from spicedb_kubeapi_proxy_tpu.spicedb.types import SubjectRef

    async def run():
        n_obj = len(ep.store.object_ids_of_type(workload.resource_type))
        times = []
        for i in range(queries):
            s = SubjectRef("user", workload.subjects[i % len(workload.subjects)])
            t0 = time.time()
            await ep.lookup_resources(workload.resource_type,
                                      workload.permission, s)
            times.append(time.time() - t0)
        per_query = statistics.median(times)
        return {
            "per_query_s": per_query,
            "checks_per_s": n_obj / per_query,
            "objects": n_obj,
        }

    return asyncio.run(run())


CONFIGS = {
    "namespace-baseline": ("namespace_baseline", {}),
    "pods-depth1": ("pods_depth1", {}),
    "nested-groups-depth4": ("nested_groups", {}),
    "rbac-deny": ("rbac_deny", {}),
    "multitenant-1m": ("multitenant_1m", {}),
}


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--config", default="multitenant-1m", choices=CONFIGS)
    ap.add_argument("--batch", type=int, default=256)
    ap.add_argument("--rounds", type=int, default=10)
    ap.add_argument("--oracle-queries", type=int, default=2)
    ap.add_argument("--all", action="store_true",
                    help="run every config; headline metric stays the default config")
    ap.add_argument("--concurrent", action="store_true",
                    help="drive the batch as N concurrent single-subject "
                         "callers through the cross-request dispatcher "
                         "instead of one explicit batched call")
    args = ap.parse_args()

    sys.path.insert(0, ".")
    from spicedb_kubeapi_proxy_tpu.models import workloads as wl

    def run_one(name):
        fn_name, kw = CONFIGS[name]
        workload = getattr(wl, fn_name)(**kw)
        log(f"== config {name}: {len(workload.relationships)} tuples ==")
        if args.concurrent:
            jax_res = bench_concurrent(workload, args.batch, args.rounds)
            jax_res.setdefault("per_batch_s", jax_res["per_round_s"])
        else:
            jax_res = bench_jax(workload, args.batch, args.rounds)
        log(f"jax: {jax_res['checks_per_s']:.3g} checks/s"
            f" ({jax_res['per_batch_s'] * 1000:.1f} ms / {args.batch}-batch)")
        oracle_res = bench_oracle(workload, args.oracle_queries)
        log(f"oracle: {oracle_res['checks_per_s']:.3g} checks/s"
            f" ({oracle_res['per_query_s'] * 1000:.1f} ms / query)")
        return jax_res, oracle_res

    if args.all:
        for name in CONFIGS:
            if name == args.config:
                continue
            try:
                run_one(name)
            except Exception as e:  # keep the headline alive
                log(f"config {name} failed: {e!r}")

    jax_res, oracle_res = run_one(args.config)
    speedup = jax_res["checks_per_s"] / max(oracle_res["checks_per_s"], 1e-9)
    print(json.dumps({
        "metric": f"authz checks/sec ({args.config}, {args.batch} concurrent list subjects)",
        "value": round(jax_res["checks_per_s"], 1),
        "unit": "checks/s",
        "vs_baseline": round(speedup, 2),
    }))


if __name__ == "__main__":
    main()
