"""Systematic concurrency tier (SURVEY §5 race-detection note; reference
keeps goroutine-safety via xsync.Map/mutexed readers and a dedicated
RESTMapper race test).  Here: mixed concurrent traffic — writers, bulk
checkers, lookups, watch consumers, dispatcher-fused callers — hammering
one endpoint, with invariants checked throughout:

- no deadlock (everything completes under a timeout);
- revisions are monotone non-decreasing per caller;
- a check result is always consistent with SOME store state, never a
  torn mix (the graph lock snapshots revision before evaluating);
- the final store state equals the deterministic replay of all writes;
- watch consumers observe every write exactly once (no loss, no dupes).
"""

import asyncio

import pytest

from spicedb_kubeapi_proxy_tpu.spicedb.dispatch import BatchingEndpoint
from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import (
    Bootstrap,
    create_endpoint,
)
from spicedb_kubeapi_proxy_tpu.spicedb.types import (
    CheckRequest,
    ObjectRef,
    RelationshipUpdate,
    SubjectRef,
    UpdateOp,
    parse_relationship,
)

SCHEMA = """
definition user {}
definition group { relation member: user | group#member }
definition doc {
  relation viewer: user | group#member
  relation banned: user
  permission view = viewer - banned
}
"""

N_DOCS = 24
N_USERS = 12


def seed_rels():
    out = []
    for d in range(N_DOCS):
        out.append(f"doc:d{d}#viewer@user:u{d % N_USERS}")
        out.append(f"doc:d{d}#viewer@group:g{d % 3}#member")
    for u in range(N_USERS):
        out.append(f"group:g{u % 3}#member@user:u{u}")
    return out


@pytest.mark.parametrize("endpoint_url", ["embedded://", "jax://"])
def test_mixed_concurrent_traffic(endpoint_url):
    ep = create_endpoint(endpoint_url, Bootstrap(schema_text=SCHEMA))
    ep.store.bulk_load([parse_relationship(r) for r in seed_rels()])
    batching = BatchingEndpoint(ep)
    writes_done: list = []

    async def writer(i):
        for j in range(10):
            rel = f"doc:d{(i * 7 + j) % N_DOCS}#viewer@user:w{i}"
            await ep.write_relationships([RelationshipUpdate(
                UpdateOp.TOUCH, parse_relationship(rel))])
            writes_done.append(rel)
            await asyncio.sleep(0)

    async def checker(i):
        last_rev = -1
        for j in range(15):
            res = await ep.check_bulk_permissions([
                CheckRequest(ObjectRef("doc", f"d{(i + k) % N_DOCS}"),
                             "view", SubjectRef("user", f"u{k % N_USERS}"))
                for k in range(8)])
            revs = {r.checked_at for r in res}
            assert len(revs) == 1, "torn bulk check across revisions"
            rev = revs.pop()
            assert rev >= last_rev, "revision went backwards"
            last_rev = rev
            await asyncio.sleep(0)

    async def fused_looker(i):
        for j in range(10):
            ids = await batching.lookup_resources(
                "doc", "view", SubjectRef("user", f"u{(i + j) % N_USERS}"))
            assert isinstance(ids, list)
            await asyncio.sleep(0)

    async def go():
        watcher = ep.watch(["doc"])
        seen: list = []

        async def consume():
            while True:
                upd = await watcher.next(timeout=2.0)
                if upd is None:
                    # next() returns None on timeout AND close — only a
                    # real close ends the stream (a slow box / cold JIT
                    # can stall >2s mid-run without losing events)
                    if watcher.closed:
                        return
                    continue
                for u in upd.updates:
                    seen.append(u.rel.rel_string())

        consumer = asyncio.ensure_future(consume())
        tasks = ([writer(i) for i in range(4)]
                 + [checker(i) for i in range(4)]
                 + [fused_looker(i) for i in range(4)])
        await asyncio.wait_for(asyncio.gather(*tasks), 60)
        # drain the watch tail, then close
        await asyncio.sleep(0.3)
        watcher.close()
        await asyncio.wait_for(consumer, 10)

        # every write observed exactly once (TOUCH of distinct rels)
        assert sorted(seen) == sorted(writes_done)

        # final checks agree with the deterministic end state
        for rel in writes_done:
            user = rel.split("@user:")[1]
            doc = rel.split("#")[0].split(":")[1]
            res = await ep.check_permission(CheckRequest(
                ObjectRef("doc", doc), "view", SubjectRef("user", user)))
            assert res.allowed, (doc, user)

    asyncio.run(go())


@pytest.mark.parametrize("endpoint_url", ["embedded://", "jax://"])
def test_checked_at_tracks_evaluated_snapshot(endpoint_url):
    """checked_at must name the revision the evaluated graph reflects —
    after a write drains, checks carry that write's revision."""
    ep = create_endpoint(endpoint_url, Bootstrap(schema_text=SCHEMA))
    ep.store.bulk_load([parse_relationship(r) for r in seed_rels()])

    async def go():
        req = CheckRequest(ObjectRef("doc", "d0"), "view",
                           SubjectRef("user", "u0"))
        res = await ep.check_permission(req)
        assert res.checked_at == ep.store.revision
        await ep.write_relationships([RelationshipUpdate(
            UpdateOp.TOUCH,
            parse_relationship("doc:d0#viewer@user:fresh"))])
        r1 = ep.store.revision
        res = await ep.check_permission(CheckRequest(
            ObjectRef("doc", "d0"), "view", SubjectRef("user", "fresh")))
        assert res.allowed
        assert res.checked_at == r1
    asyncio.run(go())


_DEVICE_BATCH_CHILD = r"""
import asyncio
import json
import sys
import time as _time

from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import (
    Bootstrap,
    create_endpoint,
)
from spicedb_kubeapi_proxy_tpu.spicedb.types import (
    CheckRequest,
    CheckResult,
    ObjectRef,
    Permissionship,
    SubjectRef,
    parse_relationship,
)

SCHEMA, SEED = json.loads(sys.argv[1]), json.loads(sys.argv[2])

ep = create_endpoint("jax://", Bootstrap(schema_text=SCHEMA))
ep.store.bulk_load([parse_relationship(r) for r in SEED])


def slow_batch(reqs):
    _time.sleep(0.5)  # stand-in for a long kernel+transfer window
    return [CheckResult(permissionship=Permissionship.NO_PERMISSION,
                        checked_at=0) for _ in reqs]


ep._check_batch_sync = slow_batch


def max_gap(ticks):
    return max((b - a for a, b in zip(ticks, ticks[1:])), default=1.0)


async def go():
    async def ticker(out):
        while True:
            out.append(asyncio.get_running_loop().time())
            await asyncio.sleep(0.02)

    ticks = []
    t = asyncio.ensure_future(ticker(ticks))
    await ep.check_bulk_permissions([CheckRequest(
        ObjectRef("doc", "d0"), "view", SubjectRef("user", "u0"))])
    t.cancel()
    return ticks


ticks = asyncio.run(go())
print(json.dumps({"ticks": len(ticks), "stall": max_gap(ticks)}))
"""


def test_device_batches_do_not_block_event_loop():
    """A fused device batch (kernel + transfer + unpack) can take hundreds
    of ms on big graphs; it must run OFF the event loop so concurrent
    requests, watch frames, and health probes keep flowing.

    De-flaked for real (tripping in-suite since PR 8): the stall was
    never the dispatch — it was ambient pressure from PRECEDING test
    files (first diagnosed as gen-2 gc; a gc.collect+gc.disable
    preamble still measured 0.44s in-suite stalls on the 2-vCPU box
    while standalone runs always passed, so leftover threads/scheduler
    pressure are part of it too).  The environment is now ISOLATED
    instead of retried around: the measurement runs in a FRESH
    interpreter (subprocess) — no inherited threads, no foreign gc
    debt, no shared executor — exactly the standalone configuration
    that never flaked.  The retry crutch is gone: one attempt, and a
    genuinely blocked loop (the 0.5s device window landing ON the
    loop) fails it deterministically while the 0.45s bound stays below
    the 0.5s device window, so no amount of environmental luck can
    mask the very signal this test exists to detect."""
    import json
    import subprocess
    import sys
    from pathlib import Path

    out = subprocess.run(
        [sys.executable, "-c", _DEVICE_BATCH_CHILD,
         json.dumps(SCHEMA), json.dumps(seed_rels())],
        capture_output=True, text=True, timeout=180,
        cwd=Path(__file__).resolve().parent.parent,
        env=_child_env())
    assert out.returncode == 0, out.stderr[-2000:]
    res = json.loads(out.stdout.strip().splitlines()[-1])
    assert res["ticks"] >= 10, (
        f"event loop starved: only {res['ticks']} ticks during the batch")
    assert res["stall"] < 0.45, (
        f"loop stalled {res['stall']:.3f}s during the 0.5s device window "
        f"— the batch ran ON the event loop")


def _child_env():
    import os

    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env.pop("PYTEST_CURRENT_TEST", None)
    return env


@pytest.mark.parametrize("endpoint_url", ["jax://"])
def test_concurrent_writes_during_rebuild(endpoint_url):
    """Writes racing graph rebuilds (bulk_load invalidation) must never
    deadlock or lose updates."""
    ep = create_endpoint(endpoint_url, Bootstrap(schema_text=SCHEMA))
    ep.store.bulk_load([parse_relationship(r) for r in seed_rels()])

    async def rebuilder():
        for _ in range(3):
            ep.store.bulk_load(
                [parse_relationship(r) for r in seed_rels()])
            await asyncio.sleep(0.01)

    async def writer_checker():
        for j in range(12):
            rel = f"doc:d{j % N_DOCS}#viewer@user:rw"
            await ep.write_relationships([RelationshipUpdate(
                UpdateOp.TOUCH, parse_relationship(rel))])
            res = await ep.check_permission(CheckRequest(
                ObjectRef("doc", f"d{j % N_DOCS}"), "view",
                SubjectRef("user", "rw")))
            assert res.allowed  # read-your-writes through rebuilds
            await asyncio.sleep(0)

    async def go():
        await asyncio.wait_for(
            asyncio.gather(rebuilder(), writer_checker(), writer_checker()),
            60)

    asyncio.run(go())


@pytest.mark.parametrize("endpoint_url", ["jax://", "jax://?mesh=2x4"])
def test_lookups_race_spare_assigning_writes(endpoint_url):
    """Round-4 regression net: lookups (kernel + id materialization run
    OUTSIDE the endpoint lock on a snapshot) race writes that create
    brand-new object ids (in-place renames of the program's id maps via
    the spare pool).  Invariants: no placeholder id (NUL-prefixed) ever
    leaks into results; every id returned was a doc id the store has
    seen; once a create's write returns, subsequent lookups must include
    it (read-your-writes through the drain)."""
    if "mesh" in endpoint_url:
        import jax
        if len(jax.devices()) < 8:
            pytest.skip("needs the 8-device virtual mesh")
    ep = create_endpoint(endpoint_url + ("&" if "?" in endpoint_url
                                         else "?") + "dispatch=direct",
                         Bootstrap(schema_text=SCHEMA))
    ep.store.bulk_load([parse_relationship(r) for r in seed_rels()])

    async def go():
        errors = []
        created = []  # ids whose write has returned
        stop = asyncio.Event()

        async def writer():
            for k in range(60):
                rel = f"doc:new-{k}#viewer@user:u0"
                await ep.write_relationships([RelationshipUpdate(
                    UpdateOp.TOUCH, parse_relationship(rel))])
                created.append(f"new-{k}")
                await asyncio.sleep(0)
            stop.set()

        def diag():
            inner_ep = getattr(ep, "inner", ep)
            try:  # best-effort: races rebuilds repopulating these dicts
                st = dict(getattr(inner_ep, "stats", {}))
                pool = {t: len(v) for t, v in
                        list(getattr(inner_ep, "_spare_pool", {}).items())}
            except RuntimeError:
                st, pool = "racing-rebuild", {}
            return f"stats={st} pool={pool} created={len(created)}"

        async def reader():
            while not stop.is_set():
                mark = len(created)
                ids = await ep.lookup_resources(
                    "doc", "view", SubjectRef("user", "u0"))
                got = set(ids)
                if any("\x00" in i for i in got):
                    bad = [i for i in got if chr(0) in i]
                    inner_ep = getattr(ep, "inner", ep)
                    with inner_ep._lock:
                        # leak family: placeholder still unassigned in the
                        # CURRENT index => the kernel lit a dead row;
                        # renamed away => a stale id view was used
                        try:
                            fam = {n: inner_ep._graph.prog
                                   .object_index["doc"]
                                   .get(n, "renamed-away")
                                   for n in bad[:6]}
                        except AttributeError:  # mid-rebuild window
                            fam = "graph-rebuilding"
                    errors.append(
                        f"placeholder leak: {bad[:6]} families={fam} "
                        f"[{diag()}]")
                    return
                # read-your-writes: ids created before the call started
                missing = [c for c in created[:mark] if c not in got]
                if missing:
                    errors.append(f"missing created ids: {missing} "
                                  f"(got {len(got)}) [{diag()}]")
                    return
                await asyncio.sleep(0)

        await asyncio.wait_for(
            asyncio.gather(writer(), *[reader() for _ in range(4)]), 120)
        assert not errors, errors[:3]
        final = set(await ep.lookup_resources(
            "doc", "view", SubjectRef("user", "u0")))
        assert all(f"new-{k}" in final for k in range(60)), \
            f"final lookup incomplete [{diag()}]"
        # suppression events are HANDLED (the endpoint re-captures and
        # returns the correct result; see _lookup_sync) — strict result
        # invariants above are the real tripwire, the counter is the
        # observability signal for how often the race fires
        inner_ep = getattr(ep, "inner", ep)
        suppressed = inner_ep.stats.get("placeholder_suppressed", 0)
        if suppressed:
            print(f"\nNOTE: id-view race fired and was self-healed "
                  f"(suppressed={suppressed}) [{diag()}]", flush=True)

    asyncio.run(go())
