"""Leopard materialized group index (ops/leopard.py, LeopardIndex gate;
docs/performance.md "Leopard index").

Contract under test: membership-only (type, permission) fragments —
pure union/userset/arrow closures with no caveats, wildcards,
intersections, exclusions, or traits — materialize as device-resident
transitive-closure bitplanes consulted BEFORE the sweep kernels, so a
depth-N nested-group check costs one plane probe instead of N sweep
iterations.  Maintenance is incremental: inserts propagate through a
bounded frontier pass, unprovable deletes quarantine the fragment (the
kernel keeps answering exactly) and a background re-close restores it,
and caveated tuples on fragment relations retire the fragment
permanently.  Gate off must mean inert, and the planes must ride the
HBM ledger and the mesh-sharded path like any other graph buffer.
"""

import asyncio

import pytest

from spicedb_kubeapi_proxy_tpu.ops.jax_endpoint import JaxEndpoint
from spicedb_kubeapi_proxy_tpu.spicedb import schema as sch
from spicedb_kubeapi_proxy_tpu.spicedb.evaluator import Evaluator
from spicedb_kubeapi_proxy_tpu.spicedb.store import TupleStore
from spicedb_kubeapi_proxy_tpu.spicedb.types import (
    CheckRequest,
    ObjectRef,
    RelationshipUpdate,
    SubjectRef,
    UpdateOp,
    parse_relationship,
)
from spicedb_kubeapi_proxy_tpu.utils import devtel
from spicedb_kubeapi_proxy_tpu.utils.features import GATES

NESTED_SCHEMA = """
definition user {}
definition group {
  relation member: user | group#member
  permission view = member
}
definition doc {
  relation viewer: user | group#member
  permission view = viewer
}
"""

# a depth-4 membership chain: members of g3 reach g0 (and d0) through
# three userset hops — g0#member <- g1#member <- g2#member <- g3#member
CHAIN = [
    "group:g0#member@group:g1#member",
    "group:g1#member@group:g2#member",
    "group:g2#member@group:g3#member",
    "group:g3#member@user:alice",
    "doc:d0#viewer@group:g0#member",
    "doc:d1#viewer@user:bob",
]


def touch(*rels):
    return [RelationshipUpdate(UpdateOp.TOUCH, parse_relationship(r))
            for r in rels]


def delete(*rels):
    return [RelationshipUpdate(UpdateOp.DELETE, parse_relationship(r))
            for r in rels]


def make_pair(rels=CHAIN, leopard=True, mesh=None):
    schema = sch.parse_schema(NESTED_SCHEMA)
    prev = GATES.enabled("LeopardIndex")
    GATES.set("LeopardIndex", leopard)
    try:
        jx = JaxEndpoint(schema, store=TupleStore(), mesh=mesh)
    finally:
        GATES.set("LeopardIndex", prev)
    if rels:
        jx.store.write(touch(*rels))
    return jx, Evaluator(schema, jx.store)


def check3(jx, doc, subject):
    res = asyncio.run(jx.check_bulk_permissions(
        [CheckRequest(ObjectRef("doc", doc), "view",
                      SubjectRef("user", subject))]))
    return {"NO_PERMISSION": 0, "CONDITIONAL_PERMISSION": 1,
            "HAS_PERMISSION": 2}[res[0].permissionship.name]


def lr(jx, subject):
    return sorted(asyncio.run(jx.lookup_resources(
        "doc", "view", SubjectRef("user", subject))))


def agree(jx, oracle, subjects, docs=("d0", "d1")):
    for s in subjects:
        want = sorted(oracle.lookup_resources(
            "doc", "view", SubjectRef("user", s)))
        assert lr(jx, s) == want, s
        for d in docs:
            assert check3(jx, d, s) == oracle.check3(
                ObjectRef("doc", d), "view", SubjectRef("user", s)), (d, s)


class TestGateTripwire:
    def test_gate_off_means_inert(self, monkeypatch):
        """With the LeopardIndex killswitch off at construction, the
        endpoint must never touch the leopard module: no index object,
        no plane consults, exact answers from the kernels alone."""
        from spicedb_kubeapi_proxy_tpu.ops import leopard

        def boom(*a, **kw):
            raise AssertionError(
                "LeopardIndex.build called with the gate off")

        monkeypatch.setattr(leopard.LeopardIndex, "build",
                            classmethod(boom))
        jx, oracle = make_pair(leopard=False)
        assert jx._leopard is None
        agree(jx, oracle, ["alice", "bob", "zed"])
        # the delta paths must not consult the index either
        jx.store.write(touch("group:g3#member@user:zed"))
        jx.store.write(delete(*CHAIN[3:4]))
        agree(jx, oracle, ["alice", "bob", "zed"])
        assert jx.stats["leopard_checks"] == 0
        assert jx.stats["leopard_lookups"] == 0

    def test_gate_on_serves_from_plane(self):
        jx, oracle = make_pair()
        # the index rides the graph build, which is lazy: first query
        lr(jx, "alice")
        assert jx._leopard is not None
        statuses = jx._leopard.status_map()
        assert statuses.get("doc#view") == "indexed", statuses
        agree(jx, oracle, ["alice", "bob", "zed"])
        # depth-4 membership resolved without a single kernel sweep
        assert jx.stats["leopard_checks"] > 0
        assert jx.stats["leopard_lookups"] > 0
        assert jx.stats["kernel_calls"] == 0


class TestLedgerInvariant:
    def test_planes_follow_the_generation_across_rebuild(self):
        jx, oracle = make_pair()
        agree(jx, oracle, ["alice"])
        old_gen = jx._devtel_gen
        assert devtel.LEDGER.generation_bytes(
            old_gen, kind="leopard_plane") > 0
        # wildcard writes are unabsorbable: full background rebuild,
        # new graph generation, new index
        jx.store.write(touch("doc:d2#viewer@user:*"))
        agree(jx, oracle, ["alice", "zed"])
        assert jx.wait_rebuilds()
        new_gen = jx._devtel_gen
        assert new_gen != old_gen
        # the outgoing generation retired wholesale — planes included
        assert devtel.LEDGER.generation_bytes(
            old_gen, kind="leopard_plane") == 0
        assert devtel.LEDGER.generation_bytes(
            new_gen, kind="leopard_plane") > 0

    def test_caveat_tuple_retires_fragment(self):
        schema_text = NESTED_SCHEMA.replace(
            "relation viewer: user | group#member",
            "relation viewer: user | group#member | user with recently")
        schema_text = ("caveat recently(age int) { age < 5 }\n"
                       + schema_text)
        schema = sch.parse_schema(schema_text)
        jx = JaxEndpoint(schema, store=TupleStore())
        jx.store.write(touch(*CHAIN))
        oracle = Evaluator(schema, jx.store)
        agree(jx, oracle, ["alice", "bob"])
        # the first caveated tuple on a fragment relation permanently
        # retires the fragment: closure bits cannot carry tri-state
        jx.store.write(touch(
            'doc:d1#viewer@user:zed[caveat:recently:{"age": 1}]'))
        agree(jx, oracle, ["alice", "bob", "zed"])
        assert jx.wait_rebuilds()
        lp = jx._leopard
        if lp is not None:
            status = lp.status_map().get("doc#view", "")
            assert status.startswith("ineligible("), status
        agree(jx, oracle, ["alice", "bob", "zed"])


class TestIncrementalMaintenance:
    def test_insert_propagates_without_rebuild(self):
        jx, oracle = make_pair()
        agree(jx, oracle, ["alice", "zed"])
        rebuilds = jx.stats["rebuilds"]
        jx.store.write(touch("group:g2#member@user:zed"))
        # the insert is absorbed into the closure in place: the new
        # member reaches d0 through the remaining two hops, exactly as
        # the oracle says, and still from the plane
        agree(jx, oracle, ["alice", "zed"])
        assert jx.stats["rebuilds"] == rebuilds
        assert jx._leopard.status_map().get("doc#view") == "indexed"
        assert jx.stats["kernel_calls"] == 0

    def test_delete_quarantines_then_recloses_to_parity(self):
        jx, oracle = make_pair()
        agree(jx, oracle, ["alice"])
        # removing alice's membership MUST revoke instantly: the bit
        # cannot be proven removable (other paths might set it), so the
        # fragment quarantines and the kernel carries the pair
        jx.store.write(delete("group:g3#member@user:alice"))
        agree(jx, oracle, ["alice", "bob"])
        assert jx.stats["leopard_recloses"] >= 1
        # quiesce: the background re-close reinstates the plane
        assert jx.wait_rebuilds()
        assert jx._leopard.status_map().get("doc#view") == "indexed"
        checks = jx.stats["leopard_checks"]
        agree(jx, oracle, ["alice", "bob"])
        assert jx.stats["leopard_checks"] > checks

    def test_churn_parity_vs_oracle(self):
        """Bursts of inserts and unprovable deletes, refereed against
        the oracle at every step (quarantined windows included)."""
        import random
        jx, oracle = make_pair()
        rng = random.Random(7)
        users = ["alice", "bob", "carol", "dave"]
        live = set()
        for step in range(10):
            g = rng.randrange(4)
            u = rng.choice(users)
            if (g, u) in live and rng.random() < 0.5:
                jx.store.write(delete(f"group:g{g}#member@user:{u}"))
                live.discard((g, u))
            else:
                jx.store.write(touch(f"group:g{g}#member@user:{u}"))
                live.add((g, u))
            agree(jx, oracle, users + ["zed"])
        assert jx.wait_rebuilds()
        agree(jx, oracle, users + ["zed"])
        assert jx._leopard.status_map().get("doc#view") == "indexed"


class TestMeshComposition:
    def test_plane_parity_on_virtual_mesh(self):
        """The closure planes shard on the graph axis of a 1x2 virtual
        mesh (conftest forces 8 CPU devices) and answer exactly like the
        single-device path and the oracle."""
        import jax
        from spicedb_kubeapi_proxy_tpu.parallel.sharding import make_mesh
        if len(jax.devices()) < 2:
            pytest.skip("needs >= 2 virtual devices")
        mesh = make_mesh(jax.devices()[:2], data=1, graph=2)
        jx, oracle = make_pair(mesh=mesh)
        single, _ = make_pair()
        lr(jx, "alice")
        assert jx._leopard is not None
        assert jx._leopard.status_map().get("doc#view") == "indexed"
        agree(jx, oracle, ["alice", "bob", "zed"])
        for s in ("alice", "bob", "zed"):
            assert lr(jx, s) == lr(single, s), s
        assert jx.stats["leopard_checks"] > 0
        # maintenance composes too: insert + unprovable delete on the
        # sharded planes hold parity through the re-close
        jx.store.write(touch("group:g1#member@user:zed"))
        agree(jx, oracle, ["alice", "zed"])
        jx.store.write(delete("group:g1#member@user:zed"))
        agree(jx, oracle, ["alice", "zed"])
        assert jx.wait_rebuilds()
        agree(jx, oracle, ["alice", "zed"])
