"""Tracing subsystem tests: span recording and context propagation, the
dispatch queue-wait/execute attribution, slow-trace retention and the
/debug/traces endpoint, phase histograms in /metrics, and the end-to-end
coverage criterion — a request through the in-memory transport against
the jax:// endpoint yields a trace whose phase spans tile wall time."""

import asyncio
import json
import logging
import time

from spicedb_kubeapi_proxy_tpu.kubefake.apiserver import FakeKubeApiServer
from spicedb_kubeapi_proxy_tpu.proxy.httpcore import HandlerTransport
from spicedb_kubeapi_proxy_tpu.proxy.server import Options, ProxyServer
from spicedb_kubeapi_proxy_tpu.spicedb import schema as sch
from spicedb_kubeapi_proxy_tpu.spicedb.dispatch import BatchingEndpoint
from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import (
    Bootstrap,
    EmbeddedEndpoint,
)
from spicedb_kubeapi_proxy_tpu.spicedb.types import (
    CheckRequest,
    ObjectRef,
    RelationshipUpdate,
    SubjectRef,
    UpdateOp,
    parse_relationship,
)
from spicedb_kubeapi_proxy_tpu.utils import tracing

SCHEMA = """
definition user {}
definition namespace {
  relation creator: user
  relation viewer: user
  permission view = viewer + creator
}
definition pod {
  relation creator: user
  relation viewer: user
  permission view = viewer + creator
}
"""

RULES = """
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: get-pods}
match: [{apiVersion: v1, resource: pods, verbs: [get]}]
check: [{tpl: "pod:{{namespacedName}}#view@user:{{user.name}}"}]
---
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: list-pods}
match: [{apiVersion: v1, resource: pods, verbs: [list]}]
prefilter:
- fromObjectIDNamespaceExpr: "{{split_namespace(resourceId)}}"
  fromObjectIDNameExpr: "{{split_name(resourceId)}}"
  lookupMatchingResources: {tpl: "pod:$#view@user:{{user.name}}"}
"""

UPSTREAM_SLEEP = 0.025


class SlowUpstream(HandlerTransport):
    """In-memory upstream with a real (attributable) latency, so phase
    spans dominate wall time and the tiling assertion is robust."""

    async def round_trip(self, req):
        await asyncio.sleep(UPSTREAM_SLEEP)
        return await super().round_trip(req)


def make_proxy(**opt_kw):
    kube = FakeKubeApiServer()
    for i in range(4):
        kube.seed("", "v1", "pods",
                  {"metadata": {"name": f"p{i}", "namespace": "team-a"}})
    proxy = ProxyServer(Options(
        spicedb_endpoint="jax://",
        bootstrap=Bootstrap(schema_text=SCHEMA),
        rules_yaml=RULES,
        upstream_transport=SlowUpstream(kube),
        **opt_kw,
    ))
    rels = [f"pod:team-a/p{i}#creator@user:alice" for i in range(3)]
    proxy.endpoint.store.bulk_load([parse_relationship(r) for r in rels])
    return proxy, kube


# -- core primitives ---------------------------------------------------------

def test_span_is_noop_without_active_trace():
    assert tracing.current_trace() is None
    with tracing.span("anything", phase=True):
        pass
    assert tracing.current_trace() is None


def test_request_trace_records_spans_and_phases():
    with tracing.request_trace(method="GET") as tr:
        assert tracing.current_trace() is tr
        with tracing.span("a", phase=True):
            time.sleep(0.005)
        with tracing.span("b", detail=1):
            pass
        with tracing.span("a", phase=True):
            pass
    assert tracing.current_trace() is None
    assert tr.duration is not None and tr.duration >= 0.005
    names = [s.name for s in tr.spans]
    assert names == ["a", "b", "a"]
    phases = tr.phase_durations()
    assert set(phases) == {"a"}  # 'b' is informational, not a phase
    assert phases["a"] >= 0.005
    d = tr.to_dict()
    assert d["trace_id"] == tr.trace_id
    assert [s["name"] for s in d["spans"]] == names
    assert d["spans"][1]["attrs"] == {"detail": 1}
    json.dumps(d)  # must be JSON-serializable for logs + /debug/traces


def test_span_attrs_enrichable_before_close():
    with tracing.request_trace() as tr:
        with tracing.span("x") as attrs:
            attrs["picked"] = "late"
    assert tr.spans[0].attrs == {"picked": "late"}


def test_fanout_trace_records_into_all_members():
    t1, t2 = tracing.Trace(), tracing.Trace()
    fan = tracing.FanoutTrace([t1, t2])
    token = tracing.activate(fan)
    try:
        with tracing.span("kernel.device", phase=False, rows=7):
            pass
    finally:
        tracing.deactivate(token)
    for t in (t1, t2):
        assert [s.name for s in t.spans] == ["kernel.device"]
        assert t.spans[0].attrs == {"rows": 7}


def test_clean_trace_id():
    assert tracing.clean_trace_id("abc-123") == "abc-123"
    assert tracing.clean_trace_id("") is None
    assert tracing.clean_trace_id("x" * 65) is None
    assert tracing.clean_trace_id("has space") is None
    assert tracing.clean_trace_id('quo"te') is None
    assert tracing.clean_trace_id("new\nline") is None


def test_recorder_keeps_n_slowest_and_drains():
    rec = tracing.SlowTraceRecorder(capacity=3)
    for ms in (5, 1, 9, 3, 7):
        tr = tracing.Trace(trace_id=f"t{ms}")
        tr.duration = ms / 1e3
        rec.record(tr)
    snap = rec.snapshot()
    assert [t["trace_id"] for t in snap] == ["t9", "t7", "t5"]
    assert rec.snapshot() == snap  # non-destructive
    assert [t["trace_id"] for t in rec.drain()] == ["t9", "t7", "t5"]
    assert rec.snapshot() == []  # drained per window


# -- dispatch attribution ----------------------------------------------------

def _check(user, pod="team-a/p0"):
    return CheckRequest(resource=ObjectRef("pod", pod), permission="view",
                        subject=SubjectRef("user", user))


def _embedded_batching():
    inner = EmbeddedEndpoint(sch.parse_schema(SCHEMA))
    inner.store.write([RelationshipUpdate(UpdateOp.TOUCH, parse_relationship(
        "pod:team-a/p0#creator@user:alice"))])
    return BatchingEndpoint(inner)


def test_dispatch_records_queue_wait_and_execute_phase_spans():
    ep = _embedded_batching()

    async def run():
        with tracing.request_trace() as tr:
            res = await ep.check_permission(_check("alice"))
        assert res.allowed
        return tr

    tr = asyncio.run(run())
    by_name = {s.name: s for s in tr.spans}
    assert by_name["queue_wait"].phase and by_name["execute"].phase
    # queue wait ends where execution starts: the two phases partition
    # the caller's dispatch wall time
    assert abs(by_name["queue_wait"].end - by_name["execute"].start) < 1e-6


def test_cobatched_callers_each_get_their_own_spans():
    ep = _embedded_batching()

    async def one(user):
        with tracing.request_trace() as tr:
            await ep.check_permission(_check(user))
        return tr

    async def run():
        return await asyncio.gather(*[one(u) for u in ("alice", "bob", "eve")])

    for tr in asyncio.run(run()):
        names = [s.name for s in tr.spans]
        assert "queue_wait" in names and "execute" in names


def test_untraced_dispatch_has_zero_span_overhead_path():
    ep = _embedded_batching()

    async def run():
        # no active trace: waiter trace ctx must stay None end to end
        assert tracing.current_trace() is None
        res = await ep.check_permission(_check("alice"))
        assert res.allowed

    asyncio.run(run())


# -- proxy end-to-end (jax://) ----------------------------------------------

def test_e2e_jax_trace_covers_all_phases_and_tiles_wall_time():
    """The ISSUE acceptance criterion: an in-memory-transport request
    against jax:// produces a trace covering authn, rule match, dispatch
    queue-wait, kernel execution, and response filtering, with phase
    span sums within ~10% of wall time; /debug/traces serves it; the
    phase histograms are scrapeable."""
    proxy, _ = make_proxy()

    async def run():
        client = proxy.get_embedded_client(user="alice")
        warm = await client.get("/api/v1/namespaces/team-a/pods/p0")
        assert warm.status == 200, warm.body

        ratios = []
        for _ in range(4):
            tracing.RECORDER.drain()  # deterministic retention
            resp = await client.get("/api/v1/namespaces/team-a/pods/p0")
            assert resp.status == 200
            trace_id = resp.headers.get(tracing.TRACE_ID_HEADER)
            assert trace_id

            dbg = await client.get("/debug/traces")
            assert dbg.status == 200
            retained = json.loads(dbg.body)["traces"]
            matches = [t for t in retained if t["trace_id"] == trace_id]
            assert matches, f"trace {trace_id} not retained in {len(retained)}"
            tr = matches[0]

            names = {s["name"] for s in tr["spans"]}
            assert {"authn", "match", "queue_wait", "execute",
                    "respfilter"} <= names
            assert any(n.startswith("kernel.") for n in names), names

            wall = tr["duration_ms"]
            phase_sum = sum(s["duration_ms"] for s in tr["spans"]
                            if s.get("phase"))
            # phases never double-count: the sum can only undershoot
            # wall (by untraced scheduler gaps), never overshoot
            assert phase_sum <= 1.1 * wall, (phase_sum, wall, tr["spans"])
            assert phase_sum >= 0.7 * wall, (phase_sum, wall, tr["spans"])
            ratios.append(phase_sum / wall)
            if phase_sum >= 0.9 * wall:
                break
        else:
            # every attempt left >10% unattributed: systematic hole in
            # the phase coverage, not scheduler noise
            raise AssertionError(f"phase tiling ratios {ratios}")

        metrics = (await client.get("/metrics")).body.decode()
        for phase in ("authn", "match", "queue_wait", "execute",
                      "respfilter", "upstream"):
            assert (f'authz_request_phase_seconds_count{{phase="{phase}"}}'
                    in metrics), phase

    asyncio.run(run())


def test_e2e_list_request_attributes_prefilter_kernel_time():
    proxy, _ = make_proxy()

    async def run():
        client = proxy.get_embedded_client(user="alice")
        warm = await client.get("/api/v1/pods")
        assert warm.status == 200
        tracing.RECORDER.drain()
        resp = await client.get("/api/v1/pods")
        assert resp.status == 200
        items = json.loads(resp.body)["items"]
        assert {i["metadata"]["name"] for i in items} == {"p0", "p1", "p2"}
        tid = resp.headers.get(tracing.TRACE_ID_HEADER)
        retained = json.loads((await client.get("/debug/traces")).body)
        tr = [t for t in retained["traces"] if t["trace_id"] == tid][0]
        names = {s["name"] for s in tr["spans"]}
        # the concurrent LR lands in the request trace (prefilter), and
        # the wait is separated from the actual body filtering
        assert {"prefilter", "upstream", "respfilter.wait",
                "respfilter"} <= names

    asyncio.run(run())


def test_trace_id_header_is_honored_and_echoed():
    proxy, _ = make_proxy()

    async def run():
        client = proxy.get_embedded_client(user="alice")
        resp = await client.get("/api/v1/namespaces/team-a/pods/p0",
                                headers=[(tracing.TRACE_ID_HEADER,
                                          "caller-supplied-id")])
        assert resp.headers.get(tracing.TRACE_ID_HEADER) == "caller-supplied-id"
        # malformed inbound ids are replaced, never echoed verbatim
        resp = await client.get("/api/v1/namespaces/team-a/pods/p0",
                                headers=[(tracing.TRACE_ID_HEADER,
                                          'bad"id with spaces')])
        got = resp.headers.get(tracing.TRACE_ID_HEADER)
        assert got and got != 'bad"id with spaces'

    asyncio.run(run())


def test_debug_traces_requires_authentication():
    proxy, _ = make_proxy()

    async def run():
        anon = proxy.get_embedded_client()  # no identity headers
        resp = await anon.get("/debug/traces")
        assert resp.status == 401

    asyncio.run(run())


def test_slow_trace_threshold_emits_structured_json_log(caplog):
    proxy, _ = make_proxy(trace_slow_threshold=0.001)

    async def run():
        client = proxy.get_embedded_client(user="alice")
        resp = await client.get("/api/v1/namespaces/team-a/pods/p0")
        assert resp.status == 200
        return resp.headers.get(tracing.TRACE_ID_HEADER)

    with caplog.at_level(logging.WARNING,
                         logger="spicedb_kubeapi_proxy_tpu.proxy"):
        trace_id = asyncio.run(run())
    slow = [r for r in caplog.records
            if "slow request trace" in r.getMessage()]
    assert slow, "threshold exceeded but no slow-trace log emitted"
    payload = json.loads(
        slow[-1].getMessage().split("slow request trace: ", 1)[1])
    assert payload["trace_id"] == trace_id
    assert any(s.get("phase") for s in payload["spans"])


def test_health_and_introspection_paths_are_not_traced():
    proxy, _ = make_proxy()

    async def run():
        client = proxy.get_embedded_client(user="alice")
        tracing.RECORDER.drain()
        for path in ("/readyz", "/livez", "/metrics", "/debug/traces"):
            resp = await client.get(path)
            assert resp.status == 200
            assert not resp.headers.get(tracing.TRACE_ID_HEADER)
        assert tracing.RECORDER.snapshot() == []

    asyncio.run(run())


def test_untraced_batch_does_not_record_into_kicking_request_trace():
    """The drain task inherits the context of whichever caller kicked it
    alive; a later all-untraced batch processed by that same task must
    NOT resolve current_trace() to the kicker's trace (its kernel spans
    would pollute an unrelated request)."""
    seen = []

    class SpyEndpoint(EmbeddedEndpoint):
        async def check_bulk_permissions(self, reqs):
            seen.append(tracing.current_trace())
            await asyncio.sleep(0.01)  # keep the drain task alive
            return await super().check_bulk_permissions(reqs)

    inner = SpyEndpoint(sch.parse_schema(SCHEMA))
    ep = BatchingEndpoint(inner)

    async def run():
        async def traced():
            with tracing.request_trace() as tr:
                await ep.check_permission(_check("alice"))
            return tr

        task = asyncio.create_task(traced())
        await asyncio.sleep(0.002)  # drain born inside the traced context
        await ep.check_permission(_check("bob"))  # untraced co-batcher
        tr = await task
        return tr

    tr = asyncio.run(run())
    assert len(seen) == 2
    assert seen[0] is tr, "traced batch must see the caller's trace"
    assert seen[1] is None, \
        "untraced batch leaked the kicking request's trace into the drain"
