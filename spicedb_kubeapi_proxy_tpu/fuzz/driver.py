"""Differential replay driver: jax:// vs the host oracle, swept across
the gate matrix and the replication-role matrix.

One `FuzzCase` (fully derived from its seed) replays against a device
endpoint built over the store a REPLICATION ROLE produces:

- `leader`      deltas land via `store.write` / `delete_by_filter` /
                `bulk_load` — the single-node path;
- `follower2`   a plain leader store fans every committed batch through
                TWO `apply_replica_batch` hops (leader -> mid -> leaf,
                the PR 9/11 chain shape); leader bulk loads / resets
                re-bootstrap each hop via `replica_reset` — the device
                graph, decision-cache epochs, and expiry heaps on the
                LEAF must follow through the replica delta pipeline;
- `promoted`    a 1-hop follower consumes the first half of the stream,
                then promotes: the remaining bursts are written
                DIRECTLY to the promoted store (the post-
                `/replication/promote` serving shape);
- `sharded2`    the stream routes through a schema-derived (and
                footprint-revalidated) partition map into TWO
                partition-leader stores behind a ShardedEndpoint
                (spicedb/sharding) while the oracle reads a single
                mirror store of the full stream — the per-shard device
                graphs must answer exactly like the whole-store oracle
                (the footprint co-location proof, exercised end to
                end);
- `mesh`       a single leader store served by a THREE-way differential:
                a multi-chip mesh endpoint (2x2 virtual-device
                shard_map kernels, parallel/sharding.py) and a plain
                single-device endpoint answer every query over the same
                store — a mesh-vs-single disagreement fails the replay
                loudly, and the mesh answer is then compared against
                the host oracle like any other cell;
- `leopard`    a single leader store served by another THREE-way
                differential: a Leopard-indexed endpoint (the
                LeopardIndex gate forced ON at construction, so
                membership-only fragments answer from the materialized
                closure planes — ops/leopard.py) and a gate-OFF
                endpoint (pure kernel sweeps) answer every query over
                the same store; an indexed-vs-plain disagreement fails
                the replay loudly, and the indexed answer is then
                compared against the host oracle like any other cell.

After every burst, every query in the case's query stream is answered
by the device endpoint (optionally behind a DecisionCacheEndpoint) and
by a fresh `Evaluator` over the SAME store — both at the same pinned
revision (the driver is single-threaded; no concurrent writers).  Any
mismatch is a `Divergence`.

Gate combos (the killswitch matrix of PRs 3/7/8):

- `off`    DecisionCache / DevicePipeline / AsyncRebuild all OFF
           (the bare serial kernel path);
- `cache`  DecisionCache ON (wrapper constructed), pipeline OFF,
           AsyncRebuild OFF — cache coherence against the oracle;
- `full`   all three ON — the production chain.
"""

from __future__ import annotations

import asyncio
import contextlib
from dataclasses import dataclass, field

from ..spicedb import schema as sch
from ..spicedb.evaluator import Evaluator
from ..spicedb.store import TupleStore
from ..spicedb.types import (
    CheckRequest,
    ObjectRef,
    RelationshipFilter,
    RelationshipUpdate,
    SubjectRef,
    UpdateOp,
    parse_relationship,
)
from ..utils.features import GATES
from . import metrics as fuzz_metrics
from .delta_gen import (
    DEFAULT_DELTA_BIAS,
    FakeClock,
    generate_bursts,
    id_universe,
    initial_rels,
)
from .schema_gen import DEFAULT_BIAS, generate_schema

import random

GATE_COMBOS = {
    "off": {"DecisionCache": False, "DevicePipeline": False,
            "AsyncRebuild": False},
    "cache": {"DecisionCache": True, "DevicePipeline": False,
              "AsyncRebuild": False},
    "full": {"DecisionCache": True, "DevicePipeline": True,
             "AsyncRebuild": True},
}

ROLES = ("leader", "follower2", "promoted")

# partitioned write scale-out (spicedb/sharding): the case replays
# through a ShardedEndpoint routing over TWO partition leaders, with a
# schema-derived co-location-valid partition map; the oracle reads a
# single mirror store receiving the same stream
SHARDED_ROLE = "sharded2"

# multi-chip mesh execution (parallel/sharding.py): the case replays
# through a 2x2 virtual-device mesh endpoint differentially checked
# against a single-device endpoint over the same store, and the mesh
# answers are compared against the host oracle like any other cell
MESH_ROLE = "mesh"

# Leopard materialized group index (ops/leopard.py): the case replays
# through a gate-ON endpoint (closure-plane fast path + incremental
# maintenance under the delta stream) differentially checked against a
# gate-OFF endpoint over the same store, and the indexed answers are
# compared against the host oracle like any other cell
LEOPARD_ROLE = "leopard"
ALL_ROLES = ROLES + (SHARDED_ROLE, MESH_ROLE, LEOPARD_ROLE)

SMOKE_KERNELS = ("segment", "ell")

# the gate combos the appended sharded smoke cells run under (the
# bare path and the full production chain)
SMOKE_SHARDED_GATES = ("off", "full")


def smoke_cell_for(seed: int) -> tuple:
    """The fixed (gates, role, kernel) cell a smoke seed lands in:
    seeds 0..24 walk the classic 3x3 gate x role matrix (every cell
    covered >= 2x) with the kernel alternating on top; seeds 25..26 are
    the appended `sharded2` cells (router over 2 partition leaders,
    off/full gates, kernels alternating); seeds 27..28 are the `mesh`
    cells (2x2 virtual-device mesh vs single-device vs oracle, off/full
    gates, ell kernel only — the mesh path requires it); seeds >= 29
    are the `leopard` cells (Leopard-indexed vs gate-off vs oracle,
    off/full gates, kernels alternating, nested-groups schema bias).
    Shared by scripts/fuzz_smoke.py and the mutation-check tests so
    'the fixed seed set' means one thing."""
    if seed >= 29:
        return (SMOKE_SHARDED_GATES[(seed - 29) % 2], LEOPARD_ROLE,
                SMOKE_KERNELS[seed % 2])
    if seed >= 27:
        return (SMOKE_SHARDED_GATES[(seed - 27) % 2], MESH_ROLE, "ell")
    if seed >= 25:
        return (SMOKE_SHARDED_GATES[(seed - 25) % 2], SHARDED_ROLE,
                SMOKE_KERNELS[seed % 2])
    return (tuple(GATE_COMBOS)[seed % 3], ROLES[(seed // 3) % 3],
            SMOKE_KERNELS[seed % 2])


_P3 = {"NO_PERMISSION": 0, "CONDITIONAL_PERMISSION": 1, "HAS_PERMISSION": 2}


@dataclass
class FuzzCase:
    """Everything a replay needs; serializes to the repro artifact."""
    seed: int
    schema_text: str
    init_rels: list           # rel strings, bulk-loaded at revision 1
    bursts: list              # serialized delta stream (delta_gen format)
    targets: list             # [(resource_type, permission), ...]
    subjects: list            # subject id strings ("user:u1")
    kernel: str = "ell"
    schema: sch.Schema = field(default=None, repr=False, compare=False)

    def parsed_schema(self) -> sch.Schema:
        if self.schema is None:
            self.schema = sch.parse_schema(self.schema_text)
        return self.schema


@dataclass
class Divergence:
    seed: int
    gates: str
    role: str
    kernel: str
    step: int                 # burst index the divergence was seen after
    query: dict               # {"kind": "check"|"lookup", ...}
    got: object               # device-side answer
    want: object              # oracle answer
    revision: int

    def line(self) -> str:
        return (f"DIVERGENCE seed={self.seed} gates={self.gates} "
                f"role={self.role} kernel={self.kernel} step={self.step} "
                f"rev={self.revision} query={self.query} "
                f"jax={self.got!r} oracle={self.want!r}")


def build_case(seed: int, schema_bias=DEFAULT_BIAS,
               delta_bias=DEFAULT_DELTA_BIAS, kernel: str = "ell",
               n_bursts: int = None, smoke: bool = False) -> FuzzCase:
    """Derive the full (schema, deltas, queries) triple from `seed`.

    `smoke=True` is the check.sh profile: the same generator universe
    but trimmed replay cost (shorter stream, ONE deepest-footprint
    target, 2 subjects + the stranger) so
    the fixed-seed matrix fits the smoke time box; the open-ended
    budgeted search runs the full-size profile."""
    rng = random.Random(seed * 2_654_435_761 % (2 ** 31))
    if smoke and schema_bias is DEFAULT_BIAS:
        from .schema_gen import SMOKE_BIAS
        schema_bias = SMOKE_BIAS
    text, schema = generate_schema(seed, bias=schema_bias)
    clock = FakeClock()
    ids = id_universe(schema, rng)
    init = initial_rels(schema, rng, clock, ids, delta_bias,
                        rng.randint(6, 18))
    if n_bursts is None:
        n_bursts = rng.randint(2, 3) if smoke else rng.randint(3, 6)
    bursts = generate_bursts(schema, rng, clock, ids, delta_bias, n_bursts)
    # query targets: every (type, permission) pair, deepest closures
    # first (relation_footprint bias), capped for replay cost
    from ..ops.graph_compile import relation_footprint
    pairs = [(tname, pname)
             for tname, d in schema.definitions.items()
             for pname in d.permissions]
    pairs.sort(key=lambda p: (-len(relation_footprint(schema, *p)), p))
    targets = pairs[:1 if smoke else 3]
    user_ids = ids.get("user", [])
    subjects = [f"user:{u}" for u in
                rng.sample(user_ids, min(2 if smoke else 3, len(user_ids)))]
    subjects.append("user:stranger")
    return FuzzCase(seed=seed, schema_text=text, init_rels=init,
                    bursts=bursts, targets=targets, subjects=subjects,
                    kernel=kernel, schema=schema)


@contextlib.contextmanager
def gates_set(combo: str):
    flags = GATE_COMBOS[combo]
    prev = {k: GATES.enabled(k) for k in flags}
    for k, v in flags.items():
        GATES.set(k, v)
    try:
        yield
    finally:
        for k, v in prev.items():
            GATES.set(k, v)


# -- role plumbing ------------------------------------------------------------


class _RoleHarness:
    """Owns the store topology of one replay and routes bursts into it.

    `query_store` is the store the device endpoint and the oracle both
    read — the leaf of whatever replication chain the role builds."""

    def __init__(self, role: str, clock: FakeClock, n_bursts: int,
                 schema: sch.Schema = None):
        self.role = role
        self.clock = clock
        self.leader = TupleStore(clock=clock.now)
        self._recorded: list = []      # captured committed delta batches
        self._leader_reset = False
        self._promote_at = n_bursts // 2 if role == "promoted" else None
        self._promoted = False
        self.pmap = None               # sharded2: the partition map
        self.shard_stores: list = []   # sharded2: per-shard stores
        if role in ("leader", MESH_ROLE, LEOPARD_ROLE):
            # mesh/leopard: same single-store topology as leader; the
            # differential endpoint pair is built later
            self.query_store = self.leader
            self.hops = []
        elif role == "follower2":
            self.hops = [TupleStore(clock=clock.now),
                         TupleStore(clock=clock.now)]
            self.query_store = self.hops[-1]
        elif role == "promoted":
            self.hops = [TupleStore(clock=clock.now)]
            self.query_store = self.hops[-1]
        elif role == SHARDED_ROLE:
            # two partition leaders behind a ShardedEndpoint; the oracle
            # reads `self.leader` as a single mirror of the full stream.
            # schema_gen emits cross-type DAGs, so the map is DERIVED
            # per schema (co-location classes from the footprint
            # closures) and then re-validated: the footprint validator
            # must accept it or the harness fails loudly.
            from ..spicedb.sharding import partition_map_for_schema
            if schema is None:
                raise ValueError("sharded2 role needs the case schema")
            self.hops = []
            self.query_store = self.leader
            self.pmap = partition_map_for_schema(schema, 2)
            errors, _ = self.pmap.validate_schema(schema)
            if errors:
                raise AssertionError(
                    f"derived partition map failed footprint "
                    f"validation: {errors}")
            self.shard_stores = [TupleStore(clock=clock.now)
                                 for _ in range(2)]
        else:
            raise ValueError(f"unknown role {role!r}")
        if self.hops:
            # delta listeners run under the leader store lock; recording
            # is append-only and the driver drains OUTSIDE the lock
            self.leader.add_delta_listener(self._record_delta)
            self.leader.add_reset_listener(self._record_reset)

    def _record_delta(self, update) -> None:
        self._recorded.append(update.updates)

    def _record_reset(self) -> None:
        self._leader_reset = True

    def _drain_into_hops(self) -> None:
        if self._leader_reset:
            # leader bulk-load/clear: each hop re-bootstraps from its
            # upstream exactly like a follower losing its tail does
            self._leader_reset = False
            self._recorded.clear()
            upstream = self.leader
            for hop in self.hops:
                hop.replica_reset(None, upstream.read(None),
                                  upstream.revision)
                upstream = hop
            return
        batches, self._recorded = self._recorded, []
        for updates in batches:
            for hop in self.hops:
                hop.apply_replica_batch(updates)

    def seed_initial(self, rels: list) -> None:
        parsed = [parse_relationship(r) for r in rels]
        self.leader.bulk_load(parsed)
        if self.hops:
            self._drain_into_hops()
        if self.shard_stores:
            self._route_bulk(parsed)

    # -- sharded2 plumbing ---------------------------------------------------

    def _route_bulk(self, rels: list) -> None:
        groups: dict = {}
        for rel in rels:
            k = self.pmap.shard_of(rel.resource.type, rel.resource.id)
            groups.setdefault(k, []).append(rel)
        for k, subset in sorted(groups.items()):
            self.shard_stores[k].bulk_load(subset)

    def _route_burst(self, burst: dict) -> None:
        """Mirror one burst into the partition leaders, routed by the
        partition map — the stream a real router would deliver."""
        kind = burst["kind"]
        if kind == "advance":
            return  # the FakeClock is shared by every store
        if kind == "write":
            groups: dict = {}
            for op in burst["ops"]:
                rel = parse_relationship(op["rel"])
                k = self.pmap.shard_of(rel.resource.type, rel.resource.id)
                groups.setdefault(k, []).append(RelationshipUpdate(
                    UpdateOp.DELETE if op["op"] == "delete"
                    else UpdateOp.TOUCH, rel))
            for k, ups in sorted(groups.items()):
                self.shard_stores[k].write(ups)
        elif kind == "dbf":
            flt = RelationshipFilter(
                resource_type=burst["resource_type"],
                relation=burst["relation"],
                resource_id=burst["resource_id"])
            for k in self.pmap.shards_for_filter(flt):
                self.shard_stores[k].delete_by_filter(flt)
        elif kind == "bulk":
            self._route_bulk([parse_relationship(r)
                              for r in burst["rels"]])

    def _writable_store(self) -> TupleStore:
        if self._promoted:
            return self.hops[-1]
        return self.leader

    def apply_burst(self, i: int, burst: dict) -> None:
        if self._promote_at is not None and i >= self._promote_at:
            if not self._promoted:
                # promotion: stop consuming the old leader; the adopted
                # state keeps serving and the remaining stream lands as
                # DIRECT writes on the promoted store
                self.leader.remove_delta_listener(self._record_delta)
                self._recorded.clear()
                self._promoted = True
        store = self._writable_store()
        kind = burst["kind"]
        if kind == "advance":
            self.clock.advance(burst["dt"])
        elif kind == "write":
            store.write([
                RelationshipUpdate(
                    UpdateOp.DELETE if op["op"] == "delete"
                    else UpdateOp.TOUCH,
                    parse_relationship(op["rel"]))
                for op in burst["ops"]])
        elif kind == "dbf":
            store.delete_by_filter(RelationshipFilter(
                resource_type=burst["resource_type"],
                relation=burst["relation"],
                resource_id=burst["resource_id"]))
        elif kind == "bulk":
            store.bulk_load([parse_relationship(r)
                             for r in burst["rels"]])
        else:
            raise ValueError(f"unknown burst kind {kind!r}")
        if self.hops and not self._promoted:
            self._drain_into_hops()
        if self.shard_stores:
            self._route_burst(burst)

    def build_endpoint(self, schema: sch.Schema, kernel: str,
                       cache_on: bool):
        """The device endpoint under test for this role: a plain
        JaxEndpoint over the query store, or (sharded2) a
        ShardedEndpoint routing over per-shard JaxEndpoints — with the
        decision cache wrapped per shard, exactly as a sharded
        deployment runs it (caches are shard-local)."""
        from ..ops.jax_endpoint import JaxEndpoint
        if self.role == SHARDED_ROLE:
            from ..spicedb.sharding import ShardedEndpoint
            inners: list = [JaxEndpoint(schema, store=s, kernel=kernel)
                            for s in self.shard_stores]
            if cache_on:
                from ..spicedb.decision_cache import DecisionCacheEndpoint
                inners = [DecisionCacheEndpoint(i) for i in inners]
            return ShardedEndpoint(self.pmap, inners, schema=schema)
        if self.role == MESH_ROLE:
            import jax
            from ..parallel.sharding import make_mesh
            mesh = make_mesh(jax.devices()[:4], data=2, graph=2)
            mesh_ep = JaxEndpoint(schema, store=self.query_store,
                                  kernel=kernel, mesh=mesh)
            if cache_on:
                from ..spicedb.decision_cache import DecisionCacheEndpoint
                mesh_ep = DecisionCacheEndpoint(mesh_ep)
            # the single-device reference is always bare: an independent
            # checker, not a second copy of the cell's gate combo
            return _MeshDifferentialEndpoint(
                mesh_ep, JaxEndpoint(schema, store=self.query_store,
                                     kernel=kernel))
        if self.role == LEOPARD_ROLE:
            # the LeopardIndex gate is captured at endpoint
            # construction, so an ON endpoint and an OFF endpoint can
            # coexist over the same store — the on-vs-off differential
            prev = GATES.enabled("LeopardIndex")
            try:
                GATES.set("LeopardIndex", True)
                leo_ep = JaxEndpoint(schema, store=self.query_store,
                                     kernel=kernel)
                GATES.set("LeopardIndex", False)
                plain_ep = JaxEndpoint(schema, store=self.query_store,
                                       kernel=kernel)
            finally:
                GATES.set("LeopardIndex", prev)
            if cache_on:
                from ..spicedb.decision_cache import DecisionCacheEndpoint
                leo_ep = DecisionCacheEndpoint(leo_ep)
            # the gate-off reference stays bare: an independent checker,
            # not a second copy of the cell's gate combo
            return _LeopardDifferentialEndpoint(leo_ep, plain_ep)
        ep = JaxEndpoint(schema, store=self.query_store, kernel=kernel)
        if cache_on:
            from ..spicedb.decision_cache import DecisionCacheEndpoint
            ep = DecisionCacheEndpoint(ep)
        return ep


class _MeshDifferentialEndpoint:
    """Three-way differential shim for the `mesh` role: every query runs
    on the sharded mesh endpoint AND a plain single-device endpoint over
    the same store.  A mesh-vs-single disagreement fails the replay
    loudly (same contract as the sharded2 partition-map validation);
    the mesh answer is what the driver then compares against the host
    oracle, so all three pairwise comparisons are covered."""

    def __init__(self, mesh_ep, single_ep):
        self._mesh = mesh_ep
        self._single = single_ep

    def warm_start(self) -> None:
        self._mesh.warm_start()
        self._single.warm_start()

    def wait_rebuilds(self) -> None:
        for ep in (self._mesh, self._single):
            wait = getattr(ep, "wait_rebuilds", None)
            if wait is not None:
                wait()

    async def lookup_resources(self, rtype, perm, subject):
        got = await self._mesh.lookup_resources(rtype, perm, subject)
        ref = await self._single.lookup_resources(rtype, perm, subject)
        if sorted(got) != sorted(ref):
            raise AssertionError(
                f"mesh vs single-device lookup divergence for "
                f"{rtype}#{perm}@{subject}: mesh={sorted(got)} "
                f"single={sorted(ref)}")
        return got

    async def check_bulk_permissions(self, reqs):
        got = await self._mesh.check_bulk_permissions(reqs)
        ref = await self._single.check_bulk_permissions(reqs)
        for req, g, s in zip(reqs, got, ref):
            if g.permissionship != s.permissionship:
                raise AssertionError(
                    f"mesh vs single-device check divergence for "
                    f"{req}: mesh={g.permissionship.name} "
                    f"single={s.permissionship.name}")
        return got


class _LeopardDifferentialEndpoint:
    """Three-way differential shim for the `leopard` role: every query
    runs on the Leopard-indexed endpoint AND a gate-off endpoint over
    the same store.  An indexed-vs-plain disagreement fails the replay
    loudly (same contract as the mesh differential); the indexed answer
    is what the driver then compares against the host oracle, so all
    three pairwise comparisons are covered."""

    def __init__(self, leo_ep, plain_ep):
        self._leo = leo_ep
        self._plain = plain_ep

    def warm_start(self) -> None:
        self._leo.warm_start()
        self._plain.warm_start()

    def wait_rebuilds(self) -> None:
        for ep in (self._leo, self._plain):
            wait = getattr(ep, "wait_rebuilds", None)
            if wait is not None:
                wait()

    async def lookup_resources(self, rtype, perm, subject):
        got = await self._leo.lookup_resources(rtype, perm, subject)
        ref = await self._plain.lookup_resources(rtype, perm, subject)
        if sorted(got) != sorted(ref):
            raise AssertionError(
                f"leopard-indexed vs gate-off lookup divergence for "
                f"{rtype}#{perm}@{subject}: indexed={sorted(got)} "
                f"plain={sorted(ref)}")
        return got

    async def check_bulk_permissions(self, reqs):
        got = await self._leo.check_bulk_permissions(reqs)
        ref = await self._plain.check_bulk_permissions(reqs)
        for req, g, p in zip(reqs, got, ref):
            if g.permissionship != p.permissionship:
                raise AssertionError(
                    f"leopard-indexed vs gate-off check divergence for "
                    f"{req}: indexed={g.permissionship.name} "
                    f"plain={p.permissionship.name}")
        return got


# -- the replay ---------------------------------------------------------------


def _parse_subject(s: str) -> SubjectRef:
    stype, _, rest = s.partition(":")
    sid, _, srel = rest.partition("#")
    return SubjectRef(stype, sid, srel)


async def _compare_queries(case: FuzzCase, ep, oracle, step: int,
                           gates: str, role: str,
                           check_only: dict = None) -> list:
    """Run the query stream; return Divergences.  `check_only` restricts
    to one serialized query (the shrinker's single-query probe)."""
    out = []
    store = oracle.store
    rev = store.revision
    if check_only is not None:
        # single-query probe (the shrinker): evaluate exactly this query
        # against the end state, independent of id enumeration
        q = check_only
        subject = _parse_subject(q["subject"])
        if q["kind"] == "lookup":
            want = sorted(oracle.lookup_resources(q["type"], q["perm"],
                                                  subject))
            got = sorted(await ep.lookup_resources(q["type"], q["perm"],
                                                   subject))
            if got != want:
                out.append(Divergence(case.seed, gates, role, case.kernel,
                                      step, q, got, want, rev))
        else:
            rt, _, oid = q["resource"].partition(":")
            res = await ep.check_bulk_permissions(
                [CheckRequest(ObjectRef(rt, oid), q["perm"], subject)])
            got3 = _P3[res[0].permissionship.name]
            want3 = oracle.check3(ObjectRef(rt, oid), q["perm"], subject)
            if got3 != want3:
                out.append(Divergence(case.seed, gates, role, case.kernel,
                                      step, q, got3, want3, rev))
        return out
    for rtype, perm in case.targets:
        for s in case.subjects:
            subject = _parse_subject(s)
            q = {"kind": "lookup", "type": rtype, "perm": perm,
                 "subject": s}
            want = sorted(oracle.lookup_resources(rtype, perm, subject))
            got = sorted(await ep.lookup_resources(rtype, perm, subject))
            if got != want:
                out.append(Divergence(case.seed, gates, role,
                                      case.kernel, step, q, got, want,
                                      rev))
        ids = store.object_ids_of_type(rtype)[:12]
        if not ids:
            continue
        subjects = [_parse_subject(s) for s in case.subjects]
        wanted_queries = []
        reqs = []
        for oid in ids:
            for s, subject in zip(case.subjects, subjects):
                q = {"kind": "check", "resource": f"{rtype}:{oid}",
                     "perm": perm, "subject": s}
                wanted_queries.append((q, subject))
                reqs.append(CheckRequest(ObjectRef(rtype, oid), perm,
                                         subject))
        res = await ep.check_bulk_permissions(reqs)
        for (q, subject), r in zip(wanted_queries, res):
            got3 = _P3[r.permissionship.name]
            rt, _, oid = q["resource"].partition(":")
            want3 = oracle.check3(ObjectRef(rt, oid), q["perm"], subject)
            if got3 != want3:
                out.append(Divergence(case.seed, gates, role, case.kernel,
                                      step, q, got3, want3, rev))
    return out


def run_case(case: FuzzCase, gates: str = "off", role: str = "leader",
             stop_on_first: bool = False, check_only: dict = None,
             final_only: bool = False, checkpoints: str = "every",
             record_metrics: bool = True) -> list:
    """Replay one (case, gate-combo, role) cell; returns Divergences.

    `checkpoints` picks where the query stream runs: "every" compares
    after the initial load and every burst (the budgeted search);
    "ends" compares after the initial load and the final burst only;
    "final" warm-starts the device graph over the initial state (so the
    stream still flows through the live intake/absorb machinery) and
    compares once after the last burst — ONE kernel-compile set per
    cell, which is what lets the fixed-seed smoke matrix fit its time
    box.

    `final_only` + `check_only` are the shrinker's probe mode: apply the
    whole stream, then evaluate one query once at the end state."""
    schema = case.parsed_schema()
    clock = FakeClock()
    harness = _RoleHarness(role, clock, len(case.bursts), schema=schema)
    divergences: list = []

    with gates_set(gates):
        harness.seed_initial(case.init_rels)
        ep = harness.build_endpoint(
            schema, case.kernel,
            cache_on=GATE_COMBOS[gates]["DecisionCache"])
        oracle = Evaluator(schema, harness.query_store)

        async def replay():
            last = len(case.bursts) - 1
            if final_only or checkpoints == "final":
                # build the device graph over the initial state WITHOUT
                # compiling query kernels: the delta stream must flow
                # through a live graph's intake/absorb machinery, not be
                # absorbed into a fresh build at the final query
                ep.warm_start()
            else:
                divergences.extend(await _compare_queries(
                    case, ep, oracle, -1, gates, role,
                    check_only=check_only))
                if divergences and stop_on_first:
                    return
            for i, burst in enumerate(case.bursts):
                harness.apply_burst(i, burst)
                if i < last and (final_only
                                 or checkpoints in ("ends", "final")):
                    continue
                divergences.extend(await _compare_queries(
                    case, ep, oracle, i, gates, role,
                    check_only=check_only))
                if divergences and stop_on_first:
                    return
            if not case.bursts and (final_only or checkpoints == "final"):
                # degenerate empty-stream case (a shrunk repro can be
                # init-rels-only): the end state IS the initial state —
                # compare it rather than vacuously passing
                divergences.extend(await _compare_queries(
                    case, ep, oracle, -1, gates, role,
                    check_only=check_only))

        asyncio.run(replay())
        wait = getattr(ep, "wait_rebuilds", None)
        if wait is not None:
            wait()
    if record_metrics:
        # shrink probes pass record_metrics=False: a single failing case
        # must count ONE divergence, not one per still-reproducing probe
        fuzz_metrics.note_case(diverged=bool(divergences))
    return divergences


def run_seed(seed: int, gates: str = "off", role: str = "leader",
             kernel: str = "ell") -> list:
    """Convenience: build + replay one cell for a bare seed."""
    case = build_case(seed, kernel=kernel)
    return run_case(case, gates=gates, role=role)
