"""E2E proxy tests: fake kube-apiserver + embedded client, read/list/watch
paths (reference e2e/proxy_test.go scenarios, minus dual writes)."""

import asyncio
import json

import pytest

from spicedb_kubeapi_proxy_tpu.kubefake.apiserver import FakeKubeApiServer
from spicedb_kubeapi_proxy_tpu.proxy.httpcore import HandlerTransport
from spicedb_kubeapi_proxy_tpu.proxy.server import Options, ProxyServer
from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import Bootstrap
from spicedb_kubeapi_proxy_tpu.spicedb.types import (
    RelationshipUpdate,
    UpdateOp,
    parse_relationship,
)

SCHEMA = """
definition user {}
definition namespace {
  relation creator: user
  relation viewer: user
  permission view = viewer + creator
}
definition pod {
  relation creator: user
  relation viewer: user
  permission view = viewer + creator
}
"""

RULES = """
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: get-namespaces}
match: [{apiVersion: v1, resource: namespaces, verbs: [get]}]
check: [{tpl: "namespace:{{name}}#view@user:{{user.name}}"}]
---
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: list-watch-namespaces}
match: [{apiVersion: v1, resource: namespaces, verbs: [list, watch]}]
prefilter:
- fromObjectIDNameExpr: "{{resourceId}}"
  lookupMatchingResources: {tpl: "namespace:$#view@user:{{user.name}}"}
---
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: get-pods}
match: [{apiVersion: v1, resource: pods, verbs: [get]}]
check: [{tpl: "pod:{{namespacedName}}#view@user:{{user.name}}"}]
---
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: list-watch-pods}
match: [{apiVersion: v1, resource: pods, verbs: [list, watch]}]
prefilter:
- fromObjectIDNamespaceExpr: "{{split_namespace(resourceId)}}"
  fromObjectIDNameExpr: "{{split_name(resourceId)}}"
  lookupMatchingResources: {tpl: "pod:$#view@user:{{user.name}}"}
---
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: create-pods}
match: [{apiVersion: v1, resource: pods, verbs: [create]}]
lock: Optimistic
check: [{tpl: "namespace:{{namespace}}#view@user:{{user.name}}"}]
update:
  creates:
  - tpl: "pod:{{namespacedName}}#creator@user:{{user.name}}"
---
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: admin-configmaps}
match: [{apiVersion: v1, resource: configmaps, verbs: [get]}]
if: ["'admins' in user.groups"]
check: []
---
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: postfilter-secrets}
match: [{apiVersion: v1, resource: secrets, verbs: [list]}]
postfilter:
- checkPermissionTemplate: {tpl: "pod:{{namespacedName}}#view@user:{{user.name}}"}
"""


def make_proxy(endpoint_url="embedded://"):
    kube = FakeKubeApiServer()
    # seed kube objects
    for ns in ("team-a", "team-b"):
        kube.seed("", "v1", "namespaces", {"metadata": {"name": ns}})
    for i in range(4):
        ns = "team-a" if i % 2 == 0 else "team-b"
        kube.seed("", "v1", "pods", {"metadata": {"name": f"p{i}", "namespace": ns}})
        kube.seed("", "v1", "secrets", {"metadata": {"name": f"p{i}", "namespace": ns}})
    kube.seed("", "v1", "configmaps", {"metadata": {"name": "cm", "namespace": "team-a"}})

    proxy = ProxyServer(Options(
        spicedb_endpoint=endpoint_url,
        bootstrap=Bootstrap(schema_text=SCHEMA),
        rules_yaml=RULES,
        upstream_transport=HandlerTransport(kube),
    ))
    # seed tuples: alice owns team-a + its pods; bob owns team-b
    rels = ["namespace:team-a#creator@user:alice",
            "namespace:team-b#creator@user:bob",
            "pod:team-a/p0#creator@user:alice",
            "pod:team-a/p2#creator@user:alice",
            "pod:team-b/p1#creator@user:bob",
            "pod:team-b/p3#creator@user:bob"]
    proxy.endpoint.store.bulk_load([parse_relationship(r) for r in rels])
    return proxy, kube


@pytest.fixture(params=["embedded://", "jax://"])
def proxy_kube(request):
    return make_proxy(request.param)


def run(coro):
    return asyncio.run(coro)


class TestGet:
    def test_allowed_get(self, proxy_kube):
        proxy, _ = proxy_kube
        alice = proxy.get_embedded_client(user="alice")

        async def go():
            resp = await alice.get("/api/v1/namespaces/team-a/pods/p0")
            assert resp.status == 200, resp.body
            assert json.loads(resp.body)["metadata"]["name"] == "p0"
        run(go())

    def test_denied_get(self, proxy_kube):
        proxy, _ = proxy_kube
        alice = proxy.get_embedded_client(user="alice")

        async def go():
            resp = await alice.get("/api/v1/namespaces/team-b/pods/p1")
            assert resp.status == 403
        run(go())

    def test_namespace_get(self, proxy_kube):
        proxy, _ = proxy_kube
        alice = proxy.get_embedded_client(user="alice")

        async def go():
            assert (await alice.get("/api/v1/namespaces/team-a")).status == 200
            assert (await alice.get("/api/v1/namespaces/team-b")).status == 403
        run(go())

    def test_unauthenticated(self, proxy_kube):
        proxy, _ = proxy_kube
        anon = proxy.get_embedded_client()  # no user header

        async def go():
            resp = await anon.get("/api/v1/namespaces/team-a/pods/p0")
            assert resp.status == 401
        run(go())

    def test_unmatched_resource_forbidden(self, proxy_kube):
        proxy, _ = proxy_kube
        alice = proxy.get_embedded_client(user="alice")

        async def go():
            resp = await alice.get("/api/v1/nodes/n1")
            assert resp.status == 403
        run(go())


class TestListFiltering:
    def test_pods_filtered_per_user(self, proxy_kube):
        proxy, _ = proxy_kube

        async def go():
            for user, expect in (("alice", {"p0", "p2"}), ("bob", {"p1", "p3"}),
                                 ("mallory", set())):
                client = proxy.get_embedded_client(user=user)
                resp = await client.get("/api/v1/pods")
                assert resp.status == 200, (user, resp.status, resp.body)
                names = {i["metadata"]["name"]
                         for i in json.loads(resp.body)["items"]}
                assert names == expect, (user, names)
        run(go())

    def test_namespaces_filtered(self, proxy_kube):
        proxy, _ = proxy_kube

        async def go():
            client = proxy.get_embedded_client(user="alice")
            resp = await client.get("/api/v1/namespaces")
            names = {i["metadata"]["name"] for i in json.loads(resp.body)["items"]}
            assert names == {"team-a"}
        run(go())

    def test_table_list_filtered(self, proxy_kube):
        proxy, _ = proxy_kube

        async def go():
            client = proxy.get_embedded_client(user="alice")
            resp = await client.get(
                "/api/v1/pods",
                headers=[("Accept",
                          "application/json;as=Table;v=v1;g=meta.k8s.io")])
            assert resp.status == 200
            table = json.loads(resp.body)
            assert table["kind"] == "Table"
            names = {r["object"]["metadata"]["name"] for r in table["rows"]}
            assert names == {"p0", "p2"}
        run(go())

    def test_postfilter_list(self, proxy_kube):
        proxy, _ = proxy_kube

        async def go():
            client = proxy.get_embedded_client(user="alice")
            resp = await client.get("/api/v1/secrets")
            assert resp.status == 200, resp.body
            names = {i["metadata"]["name"] for i in json.loads(resp.body)["items"]}
            # secrets named like alice's pods pass the postfilter template
            assert names == {"p0", "p2"}
        run(go())


class TestProtobufNegotiation:
    """Proto-negotiated bodies filtered at the wire level (reference
    responsefilterer.go:241-301).  The fake apiserver serves
    application/vnd.kubernetes.protobuf; assertions decode the proxied
    bytes with the k8sproto codec."""

    PROTO = "application/vnd.kubernetes.protobuf"

    def test_proto_list_filtered_per_user(self, proxy_kube):
        from spicedb_kubeapi_proxy_tpu.proxy import k8sproto
        proxy, _ = proxy_kube

        async def go():
            for user, expect in (("alice", {("team-a", "p0"), ("team-a", "p2")}),
                                 ("bob", {("team-b", "p1"), ("team-b", "p3")}),
                                 ("mallory", set())):
                client = proxy.get_embedded_client(user=user)
                resp = await client.get("/api/v1/pods",
                                        headers=[("Accept", self.PROTO)])
                assert resp.status == 200, (user, resp.status)
                assert k8sproto.is_k8s_proto(resp.body)
                av, kind, raw, _ = k8sproto.decode_unknown(resp.body)
                assert kind == "PodList"
                got = {k8sproto.object_meta(i)
                       for i in k8sproto.iter_list_items(raw)}
                assert got == expect, (user, got)
        run(go())

    def test_proto_get_allowed_and_denied(self, proxy_kube):
        from spicedb_kubeapi_proxy_tpu.proxy import k8sproto
        proxy, _ = proxy_kube
        alice = proxy.get_embedded_client(user="alice")

        async def go():
            resp = await alice.get("/api/v1/namespaces/team-a/pods/p0",
                                   headers=[("Accept", self.PROTO)])
            assert resp.status == 200
            _, kind, raw, _ = k8sproto.decode_unknown(resp.body)
            assert kind == "Pod"
            assert k8sproto.object_meta(raw) == ("team-a", "p0")
            # denied single object -> 403 from the check rule before the
            # upstream is even consulted
            resp = await alice.get("/api/v1/namespaces/team-b/pods/p1",
                                   headers=[("Accept", self.PROTO)])
            assert resp.status == 403
        run(go())

    def test_proto_table_filtered(self, proxy_kube):
        from spicedb_kubeapi_proxy_tpu.proxy import k8sproto
        proxy, _ = proxy_kube

        async def go():
            client = proxy.get_embedded_client(user="alice")
            resp = await client.get(
                "/api/v1/pods",
                headers=[("Accept",
                          f"{self.PROTO};as=Table;v=v1;g=meta.k8s.io")])
            assert resp.status == 200
            assert k8sproto.is_k8s_proto(resp.body)
            av, kind, raw, _ = k8sproto.decode_unknown(resp.body)
            assert kind == "Table"
            names = set()
            for f, wt, _, _, row in k8sproto.records(raw):
                if f == 3 and wt == 2:
                    names.add(k8sproto._table_row_meta(row))
            assert names == {("team-a", "p0"), ("team-a", "p2")}
        run(go())

    def test_garbage_proto_body_rejected(self, proxy_kube):
        """An upstream serving a corrupt proto body must fail closed (502
        via FilterError), never pass unfiltered (reference rejects
        unparseable proto at responsefilterer.go:278-280)."""
        proxy, kube = proxy_kube

        orig = kube._list

        async def corrupt_list(req, t, key, ns, query):
            resp = await orig(req, t, key, ns, query)
            if kube._wants_proto(req):
                resp.body = resp.body[:-4]  # truncate mid-record
                resp.headers.set("Content-Length", str(len(resp.body)))
            return resp

        kube._list = corrupt_list

        async def go():
            client = proxy.get_embedded_client(user="alice")
            resp = await client.get("/api/v1/pods",
                                    headers=[("Accept", self.PROTO)])
            assert resp.status == 502
        run(go())


class TestCEL:
    def test_group_gated_rule(self, proxy_kube):
        proxy, _ = proxy_kube

        async def go():
            admin = proxy.get_embedded_client(user="root", groups=["admins"])
            pleb = proxy.get_embedded_client(user="root", groups=["devs"])
            assert (await admin.get(
                "/api/v1/namespaces/team-a/configmaps/cm")).status == 200
            assert (await pleb.get(
                "/api/v1/namespaces/team-a/configmaps/cm")).status == 403
        run(go())


class TestAlwaysAllow:
    def test_api_metadata(self, proxy_kube):
        proxy, _ = proxy_kube

        async def go():
            client = proxy.get_embedded_client(user="nobody")
            for path in ("/api", "/apis", "/openapi/v2"):
                resp = await client.get(path)
                assert resp.status == 200, path
        run(go())

    def test_health(self, proxy_kube):
        proxy, _ = proxy_kube

        async def go():
            client = proxy.get_embedded_client()
            assert (await client.get("/readyz")).status == 200
            assert (await client.get("/livez")).status == 200
        run(go())


class TestWatch:
    def test_watch_allow_buffer_revoke(self, proxy_kube):
        proxy, kube = proxy_kube

        async def go():
            client = proxy.get_embedded_client(user="alice")
            resp = await client.get("/api/v1/pods?watch=true")
            assert resp.status == 200
            assert resp.stream is not None
            frames: asyncio.Queue = asyncio.Queue()

            async def consume():
                async for frame in resp.stream:
                    await frames.put(json.loads(frame))

            task = asyncio.ensure_future(consume())
            try:
                # grant first, then the kube event arrives -> replayed
                await proxy.endpoint.write_relationships([RelationshipUpdate(
                    UpdateOp.TOUCH,
                    parse_relationship("pod:team-a/pnew#creator@user:alice"))])
                await asyncio.sleep(0.6)  # let the spicedb watch propagate
                kube.seed("", "v1", "pods", {
                    "metadata": {"name": "pnew", "namespace": "team-a"}})
                await kube._notify(("", "v1", "pods"), "ADDED",
                                   kube.objects[("", "v1", "pods")]["team-a"]["pnew"])
                ev = await asyncio.wait_for(frames.get(), 5)
                assert ev["type"] == "ADDED"
                assert ev["object"]["metadata"]["name"] == "pnew"

                # unauthorized object -> buffered (no frame)
                kube.seed("", "v1", "pods", {
                    "metadata": {"name": "phidden", "namespace": "team-b"}})
                await kube._notify(("", "v1", "pods"), "ADDED",
                                   kube.objects[("", "v1", "pods")]["team-b"]["phidden"])
                with pytest.raises(asyncio.TimeoutError):
                    await asyncio.wait_for(frames.get(), 0.7)

                # late grant -> buffered frame flushed
                await proxy.endpoint.write_relationships([RelationshipUpdate(
                    UpdateOp.TOUCH,
                    parse_relationship("pod:team-b/phidden#viewer@user:alice"))])
                ev = await asyncio.wait_for(frames.get(), 5)
                assert ev["object"]["metadata"]["name"] == "phidden"
            finally:
                task.cancel()
        run(go())


class TestMatcherSwap:
    def test_runtime_matcher_swap(self, proxy_kube):
        """e2e pattern: tests swap rule sets at runtime (reference
        server.go:145-146, proxy_test.go:967-981)."""
        from spicedb_kubeapi_proxy_tpu.config import proxyrule
        from spicedb_kubeapi_proxy_tpu.rules.engine import MapMatcher
        proxy, _ = proxy_kube

        async def go():
            alice = proxy.get_embedded_client(user="alice")
            assert (await alice.get("/api/v1/namespaces/team-a/pods/p0")).status == 200
            proxy.matcher = MapMatcher(proxyrule.parse("""
apiVersion: authzed.com/v1alpha1
kind: ProxyRule
metadata: {name: deny-all-gets}
match: [{apiVersion: v1, resource: pods, verbs: [get]}]
check: [{tpl: "pod:{{namespacedName}}#view@user:nobody-has-this"}]
"""))
            assert (await alice.get("/api/v1/namespaces/team-a/pods/p0")).status == 403
        run(go())


class TestSustainedCreates:
    def test_many_dual_write_creates_stay_incremental(self, proxy_kube):
        """25 consecutive pod creations through the full proxy chain
        (rules -> workflow dual-write -> store -> device graph -> prefilter
        LR): each new pod is immediately visible to its creator, and on
        the jax:// backend the spare-row path keeps the device graph from
        rebuilding per creation."""
        proxy, _ = proxy_kube
        proxy.enable_dual_writes()
        alice = proxy.get_embedded_client(user="alice")

        inner = getattr(proxy.endpoint, "inner", proxy.endpoint)

        async def warmup():
            # first query builds the device graph (counted as a rebuild);
            # the incremental-creates invariant starts after that
            assert (await alice.get("/api/v1/namespaces/team-a/pods")
                    ).status == 200
        run(warmup())
        rebuilds_before = (inner.stats.get("rebuilds")
                          if hasattr(inner, "stats") else None)

        async def go():
            for k in range(25):
                resp = await alice.post(
                    "/api/v1/namespaces/team-a/pods",
                    {"kind": "Pod", "apiVersion": "v1",
                     "metadata": {"name": f"web-{k}",
                                  "namespace": "team-a"}})
                assert resp.status in (200, 201), (k, resp.status, resp.body)
                got = await alice.get("/api/v1/namespaces/team-a/pods")
                assert got.status == 200
                names = {i["metadata"]["name"]
                         for i in json.loads(got.body)["items"]}
                assert {f"web-{j}" for j in range(k + 1)} <= names, (k, names)
        run(go())

        if rebuilds_before is not None and hasattr(inner, "_spare_pool"):
            assert inner.stats["rebuilds"] == rebuilds_before, \
                "dual-write creates must ride the spare-row path"
            assert inner.stats["spare_assignments"] >= 25
