"""Feature-gate registry (reference pkg/proxy/features.go:10-27)."""

import pytest

from spicedb_kubeapi_proxy_tpu.utils.features import (
    ALPHA,
    GATES,
    FeatureGateError,
    FeatureGates,
)


@pytest.fixture(autouse=True)
def reset_global():
    yield
    GATES.reset()


class TestFeatureGates:
    def test_register_and_defaults(self):
        g = FeatureGates()
        g.register("X", stage=ALPHA, default=False)
        assert g.enabled("X") is False
        g.set("X", True)
        assert g.enabled("X") is True

    def test_duplicate_registration_rejected(self):
        g = FeatureGates()
        g.register("X")
        with pytest.raises(FeatureGateError, match="already"):
            g.register("X")

    def test_unknown_gate_rejected(self):
        g = FeatureGates()
        with pytest.raises(FeatureGateError, match="unknown"):
            g.enabled("nope")

    def test_apply_flag_syntax(self):
        g = FeatureGates()
        g.register("A")
        g.register("B", default=True)
        g.apply_flag("A=true, B=false")
        assert g.enabled("A") and not g.enabled("B")
        with pytest.raises(FeatureGateError, match="invalid"):
            g.apply_flag("A=maybe")
        with pytest.raises(FeatureGateError, match="unknown"):
            g.apply_flag("C=true")

    def test_reference_gates_registered(self):
        known = GATES.known()
        for name in ("ContextualLogging", "LoggingAlphaOptions",
                     "LoggingBetaOptions"):
            assert name in known

    def test_cli_flag_applies(self):
        from spicedb_kubeapi_proxy_tpu import cli
        args = cli.build_parser().parse_args(
            ["--feature-gates", "LoggingAlphaOptions=true",
             "--use-in-cluster-config"])
        assert args.feature_gates == "LoggingAlphaOptions=true"
