"""A004 — feature-gate hygiene for killswitch-gated subsystems.

Every gated subsystem in this repo ships with the same hand-tested
invariant: "gate off must mean inert" (tripwire tests monkeypatch the
gated entry points to raise).  The mechanical version: inside a gated
module, a function that MUTATES subsystem state — bumps a metric
(`.inc()`/`.observe()`/`.dec()`), rebinds a module global (`global x`
then `x = ...`), or appends/records into a module-level registry — must
be dominated by a gate check: a call or flag read whose name says
"enabled" appearing before the mutation in the same function, or (for
private helpers) in every same-module caller.  A public mutator with no
dominating check is exactly how an "inert" killswitch quietly keeps
counting, queueing, or journaling.

The module -> gate map below is the subsystem registry; extend it when
a new gated subsystem lands (the gate name is printed in the finding so
the fix is obvious either way).
"""

from __future__ import annotations

import ast

from .core import attr_chain

# package-relative path fragment -> gate name (utils/features.py)
GATED_MODULES = (
    # the directory fragment covers the whole replication subsystem:
    # leader.py, follower.py, AND the failover layer (failover.py —
    # promotion/fencing/fan-out) all ride the `Replication` gate
    ("spicedb/replication/", "Replication"),
    ("utils/admission.py", "AdmissionControl"),
    ("utils/timeline.py", "Timeline"),
    ("utils/devtel.py", "DeviceTelemetry"),
    ("spicedb/decision_cache.py", "DecisionCache"),
    ("spicedb/persist/", "DurableStore"),
    # the differential fuzz harness's authz_fuzz_* recording helpers
    # (the generators/driver/shrinker themselves are offline tooling
    # with no subsystem state to gate)
    ("fuzz/metrics.py", "FuzzTelemetry"),
    # partitioned write scale-out: the directory fragment covers the
    # whole sharding subsystem (partition map, revision vectors, the
    # router/endpoint compositions, and the authz_shard_* recording
    # helpers) under the `Sharding` killswitch
    ("spicedb/sharding/", "Sharding"),
    # kernel introspection & cost attribution: the sweep-telemetry
    # accounting plane (authz_sweep_* metrics + /debug/workload) rides
    # the KernelIntrospect gate; the sampling profiler has its own
    # killswitch because a blocking capture is a heavier hammer
    ("utils/workload.py", "KernelIntrospect"),
    ("utils/profiler.py", "Profiler"),
    # multi-chip mesh execution: the sharded kernel module rides the
    # MeshExecution killswitch (compat.py/distributed.py are pure
    # resolution/runtime glue with no subsystem state to gate; the
    # endpoint checks the gate at mesh construction)
    ("parallel/sharding.py", "MeshExecution"),
    # Leopard materialized group index: the closure planner/builder and
    # its authz_leopard_* recording helpers ride the LeopardIndex
    # killswitch (the endpoint only constructs the index when the gate
    # was on at build time)
    ("ops/leopard.py", "LeopardIndex"),
    # tail explainer: pure report computation over the merged fleet
    # view; the explain() entry point checks the gate itself, and the
    # module keeps no state and ticks no metrics
    ("utils/tailexplain.py", "TailExplain"),
)

_MUTATOR_METHODS = ("inc", "observe", "dec")


def _gate_for(rel: str):
    if "spicedb_kubeapi_proxy_tpu" not in rel:
        return None
    for frag, gate in GATED_MODULES:
        if frag in rel:
            return gate
    return None


def _is_gate_check(node) -> bool:
    """A call or flag read whose terminal name says 'enabled'."""
    if isinstance(node, ast.Call):
        chain = attr_chain(node.func)
        return bool(chain) and "enabled" in chain[-1].lower()
    chain = attr_chain(node)
    return bool(chain) and "enabled" in chain[-1].lower()


def _has_gate_check(func: ast.AST, before_line=None) -> bool:
    for node in ast.walk(func):
        if _is_gate_check(node):
            if before_line is None or node.lineno <= before_line:
                return True
    return False


def _mutations(func, module_globals) -> list:
    """(line, description) mutation sites in one function body."""
    out = []
    declared_global: set = set()
    for node in ast.walk(func):
        if isinstance(node, ast.Global):
            declared_global.update(node.names)
    for node in ast.walk(func):
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func)
            if chain and chain[-1] in _MUTATOR_METHODS:
                out.append((node.lineno,
                            f"metric mutation `{'.'.join(chain)}(...)`"))
        elif isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for tgt in targets:
                if (isinstance(tgt, ast.Name)
                        and tgt.id in declared_global):
                    out.append((node.lineno,
                                f"module global `{tgt.id}` rebound"))
    for node in ast.walk(func):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("append", "add", "appendleft")
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id in module_globals):
            out.append((node.lineno,
                        f"module registry `{node.func.value.id}."
                        f"{node.func.attr}(...)`"))
    return out


def _class_exempt(src, cls) -> bool:
    """True when the `class Foo:` line carries `# noqa: A004(reason)` —
    the class-level declaration that its instances only exist when the
    gate is on (reason required, same contract as line suppressions)."""
    for code, reason in src.noqa.get(cls.lineno, ()):
        if code == "A004" and (reason or "").strip():
            return True
    return False


def rule_a004(sources) -> list:
    findings: list = []
    for src in sources:
        gate = _gate_for(src.rel)
        if gate is None:
            continue
        module_globals = {
            t.id for n in src.tree.body if isinstance(n, ast.Assign)
            for t in n.targets if isinstance(t, ast.Name)}
        funcs = {src.qualnames[id(n)]: n for n in ast.walk(src.tree)
                 if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        # same-module caller map (by bare name and self-method name)
        callers: dict = {}
        for qual, fn in funcs.items():
            for node in ast.walk(fn):
                if isinstance(node, ast.Call):
                    chain = attr_chain(node.func)
                    if not chain:
                        continue
                    name = chain[-1]
                    callers.setdefault(name, []).append(
                        (qual, node.lineno))
        for qual, fn in funcs.items():
            muts = _mutations(fn, module_globals)
            if not muts:
                continue
            name = qual.rsplit(".", 1)[-1]
            if name in ("__init__", "__post_init__"):
                continue  # construction wires state; gates act at use
            cls = src.enclosing_class(fn)
            if cls is not None and _class_exempt(src, cls):
                # constructed-behind-gate wrapper: the gate decides
                # whether the object EXISTS (create_endpoint / server
                # startup checks it), so call sites need no re-check —
                # declared by `# noqa: A004(reason)` on the class line
                continue
            for line, what in muts:
                if _has_gate_check(fn, before_line=line):
                    continue
                if name.startswith("_"):
                    # private helper: pass when every same-module caller
                    # is gate-checked before the call site
                    calls = callers.get(name, [])
                    if calls and all(
                            _has_gate_check(funcs[cq], before_line=cl)
                            for cq, cl in calls if cq in funcs):
                        continue
                findings.append(src.finding(
                    "A004", line,
                    f"{what} in `{qual}` ({gate}-gated module) has no "
                    f"dominating gate check — with the {gate} "
                    f"killswitch off this path must be inert"))
    return findings
