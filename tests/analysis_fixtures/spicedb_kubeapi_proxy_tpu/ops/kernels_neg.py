"""A005 near-misses: static unrolls, dtype descriptors, device work,
and host helpers NOT reachable from any jit site."""
import jax
import jax.numpy as jnp
import numpy as np


def host_staging(batch):
    # never reached from a jit root: host numpy here is the normal
    # encode path, not a traced-function regression
    return np.zeros((len(batch),), np.int32)


def build():
    def run(q, idx, expr):
        acc = q
        for k in range(1, idx.shape[1]):  # shape: trace-time constant
            acc = acc | idx[:, k]
        for child in expr.children:       # static pytree structure
            acc = acc | child
        seed = jnp.uint32(0)
        mask = np.uint32(7)               # dtype scalar: whitelisted
        return acc + seed + mask

    return jax.jit(run)


def plain_helper(batch):
    # undecorated and unreached: free to loop on the host
    while len(batch):
        batch = batch[1:]
    return batch
