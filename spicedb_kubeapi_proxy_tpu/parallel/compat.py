"""Version-spanning `shard_map` resolution.

`shard_map` has moved twice across the jax versions this package must
span: 0.4.x ships it at `jax.experimental.shard_map.shard_map` with a
`check_rep` kwarg, newer releases promote it to `jax.shard_map` and
rename the replication-check kwarg to `check_vma`.  Every sharded
program in this repo (parallel/sharding.py, parallel/distributed.py's
multi-host variant, tests) goes through this one shim so a jax upgrade
is a one-file event instead of a grep across kernels.

The shim keeps the MODERN calling convention (`check_vma=`) at call
sites and translates down for 0.4.x, because the modern name is where
the API is heading — the compat direction should age out, not in.
"""

from __future__ import annotations

import inspect

import jax

__all__ = ["shard_map"]


def _resolve():
    """-> (callable, replication-check kwarg name or None)."""
    fn = getattr(jax, "shard_map", None)
    if fn is None:
        from jax.experimental.shard_map import shard_map as fn
    try:
        params = inspect.signature(fn).parameters
    except (TypeError, ValueError):  # C-accelerated / exotic wrapper
        params = {}
    for name in ("check_vma", "check_rep"):
        if name in params:
            return fn, name
    return fn, None


_SHARD_MAP, _CHECK_KWARG = _resolve()


def shard_map(f, mesh, in_specs, out_specs, check_vma=None):
    """`jax.shard_map` with the modern signature on every supported jax.

    `check_vma=None` means "library default"; an explicit bool is passed
    through under whatever name (`check_vma`/`check_rep`) the resolved
    implementation accepts, and silently dropped if it accepts neither
    (the check is an assertion aid, never a semantics change).
    """
    kwargs = {}
    if check_vma is not None and _CHECK_KWARG is not None:
        kwargs[_CHECK_KWARG] = check_vma
    return _SHARD_MAP(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, **kwargs)
