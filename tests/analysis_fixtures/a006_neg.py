"""A006 near-misses: hops that DO carry the propagation headers (via
hop_span / propagation_headers), transport wrappers that pass the
caller's headers through by contract, and non-call references."""

from contextlib import contextmanager


@contextmanager
def hop_span(name, tier=""):
    yield type("H", (), {"headers": {}})()


def propagation_headers(default_tier=""):
    return {}


class tracing:
    hop_span = hop_span
    propagation_headers = propagation_headers


async def forward_with_hop_span(transport, req):
    with hop_span("hop.forward", tier="leader") as hop:
        for k, v in hop.headers.items():
            req.headers.set(k, v)
        return await transport.round_trip(req)        # covered: hop_span


async def forward_with_headers(transport, req):
    for k, v in propagation_headers(default_tier="follower").items():
        req.headers.set(k, v)
    return await transport.round_trip(req)            # covered: headers


async def forward_via_module_attr(transport, req):
    with tracing.hop_span("hop.forward") as hop:
        req.headers.update(hop.headers)
        return await transport.round_trip(req)        # covered: attr ref


class RetryTransport:
    def __init__(self, base):
        self.base = base

    async def round_trip(self, req):
        # wrapper contract: the CALLER attached the headers; this layer
        # must forward them untouched, not mint its own
        return await self.base.round_trip(req)


def reference_only(transport):
    # passing the bound method around is not a hop
    return transport.round_trip


async def external_hop(kube, req):
    return await kube.round_trip(req)  # noqa: A006(external kube hop)
