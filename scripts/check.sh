#!/usr/bin/env bash
# Pre-snapshot check gate (the reference gates merges on unit + e2e suites,
# magefiles/test.go:19-56 and .github/workflows/build-test.yaml:56-92).
# Run this before every commit/snapshot:
#
#   scripts/check.sh            # full gate
#   scripts/check.sh --fast     # skip the bench smoke
#
# Everything runs on the virtual CPU mesh — no TPU required.
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== syntax gate (compileall)"
python -m compileall -q spicedb_kubeapi_proxy_tpu tests bench.py __graft_entry__.py

echo "== static analysis gate (scripts/analyze.py --all)"
# ONE driver for every static gate (docs/static-analysis.md):
#   A001-A005  concurrency & hot-path rules — event-loop-blocking calls
#              in async defs, dropped asyncio tasks (the PR 2 GC-hang
#              class), lock-order cycles / await-under-sync-lock (the
#              PR 5 finalizer-deadlock class), feature-gate hygiene
#              ("killswitch off must mean inert"), and jit purity by
#              call-graph reach (supersedes the M003 fence for
#              unfenced helpers)
#   M-rules    the historical lint.py families (F401/... + M001 metric
#              cardinality, M002 docs-vs-registry drift, M003 hotpath
#              fences) — scripts/lint.py still works standalone
#   SL-rules   schema/rule lint via --lint-schema --lint-schema-json in
#              a subprocess (overlapped with the scan; errors fail)
# Fails on any NEW finding (not noqa'd with a reason, not in
# scripts/analysis/baseline.json).  Runs even with --fast; no jax
# import in the driver itself.
JAX_PLATFORMS=cpu python scripts/analyze.py --all

if [[ "${1:-}" != "--fast" ]]; then
  echo "== unit + e2e suites with enforced-minimum line coverage"
  # COV_MIN overrides the floor; the default sits safely under the
  # current measured total so the gate catches regressions, not noise
  python scripts/cov.py --min-pct "${COV_MIN:-70}" tests/ -q
else
  echo "== unit + e2e suites (pytest)"
  python -m pytest tests/ -q
fi

echo "== decision-cache coherence smoke (deterministic, CPU, small sizes)"
# relation-scoped invalidation bugs fail HERE, in seconds, without the
# slow bench: random delta streams with the host oracle as referee plus
# the footprint unit tests (tests/test_decision_cache.py)
JAX_PLATFORMS=cpu python -m pytest tests/test_decision_cache.py -q \
    -p no:cacheprovider -k "coherence or Footprint or Invalidation"

echo "== differential fuzz smoke (25 fixed seeds x 3 gate combos x 3"
echo "   replication roles + 2 sharded2 router cells + 2 mesh cells,"
echo "   jax:// vs oracle)"
# seeded, deterministic, time-boxed (docs/fuzzing.md): random schemas +
# random delta streams replayed against the device kernels AND the
# recursive oracle at pinned revisions, as leader / 2-hop follower
# chain / promoted leader, across the DecisionCache x DevicePipeline x
# AsyncRebuild killswitch matrix.  Any divergence anywhere in that
# matrix fails HERE with a shrunken repro artifact + one-line seed.
# Runs even with --fast.  (~12s with a warm /tmp XLA cache, ~20s cold;
# an injected-bug tripwire for the harness itself lives in
# tests/test_fuzz.py::TestMutationCheck.)
python scripts/fuzz_smoke.py

echo "== crash-recovery smoke (kill -9 mid write-churn, restart, parity)"
# the durable store must never lose an acked write: fsync=always child,
# SIGKILL mid-churn, recover on the same data dir, compare against an
# uninterrupted host-oracle replay (fast, deterministic, no jax import)
python scripts/crash_smoke.py

echo "== replication smoke (leader + follower over localhost, kill -9,"
echo "   promote, rejoin; fleet trace through 3 tiers; sharded router)"
# WAL-shipping read replicas + failover (docs/replication.md): write
# through the leader, assert the follower serves the filtered list
# within the lag bound, kill -9 the leader, assert bounded-staleness
# reads keep flowing with a degraded-but-200 /readyz; then promote the
# follower (new incarnation), land a write locally with the pre-kill
# write still readable (zero lost), resurrect the old leader and
# assert the startup fence probe demotes it into a forwarding follower
# (fast, embedded endpoint, no jax on the serving path).  Then fleet
# tracing (docs/observability.md "Fleet tracing"): one dual-write
# through router -> follower -> leader, asserting the merged
# /debug/fleet trace spans all three tiers and reconciles with the
# client-measured e2e latency; then the sharded write scale-out.
JAX_PLATFORMS=cpu python scripts/replication_smoke.py

echo "== fleet topology smoke (router + leader + follower, open-loop"
echo "   load, /debug/tail p99 explainer, attribution vs client e2e)"
# the ISSUE 20 stack end to end (docs/performance.md "Fleet topology
# bench"): the shared ProcessFleet harness boots the smallest real
# fleet (fake kube + shard leader + follower + CLI router fronting the
# follower), the open-loop generator drives ~10s of mixed
# filter/check/update load through it (coordinated-omission-free:
# latencies charged to intended send times, scheduler lag exported as
# authz_loadgen_lag_seconds), and the gate asserts (a) per-tier
# /debug/fleet attribution reconciles with the client's own e2e wall
# times (10% + slack, same bounds as the replication smoke) and (b)
# /debug/tail serves a non-empty ranked tail report covering exactly
# the _SERVING_STAGES stage set.  Runs even with --fast.
JAX_PLATFORMS=cpu python scripts/fleet_smoke.py --fast

echo "== device-telemetry smoke (/metrics + /debug/flight + /debug/timeline)"
# the device-telemetry metric families (HBM ledger, jit-cache counters,
# batch occupancy, SLO burn rates, dispatch-timeline stall/roofline/
# overlap) must be present and populated after real proxied traffic,
# /debug/timeline must serve valid chrome-trace JSON with >= 1
# dispatch slice, and with the device-resident pipeline enabled the
# concurrent-wave section must drive authz_dispatch_overlap_ratio > 0
# with stall{cause=pack|transpose} ~ 0; fast, CPU-only, runs even
# with --fast
JAX_PLATFORMS=cpu python scripts/devtel_smoke.py

echo "== perf-regression sentinel (cpu-microbench vs committed baseline)"
# noise-aware benchdiff gate (docs/performance.md "Regression
# sentinel"): a deterministic pure-python microbench (no jax import,
# ~3s) over the dispatch drain + recursive oracle, compared against
# scripts/benchdiff_baseline.json calibration-normalized with
# variance-derived ratio thresholds — an injected slowdown in the
# drain hot loop (SPICEDB_TPU_BENCHDIFF_INJECT_MS) fails HERE, exit 1,
# with the offending config named (the tripwire proving the gate can
# fire lives in tests/test_workload.py::TestBenchdiffGate).  Runs even
# with --fast.
python bench.py --config cpu-microbench \
    --baseline scripts/benchdiff_baseline.json > /tmp/benchdiff_current.json

echo "== churn soak gate (deterministic CPU, small graph, SLO-asserted)"
# tail-latency hardening acceptance (docs/performance.md "Overload &
# rebuild behavior"): sustained create/delete churn + list-heavy reads
# for 4 windows; per-window p99 must hold max(2 x p50, 250ms) and never
# exceed 1s — a rebuild- or compile-coincident spike fails HERE, in
# under a minute, instead of in the 30-min soak.  The 250ms floor is
# noise headroom for a small shared CI box (measured: ambient
# contention on a 2-core host inflates clean-run p99 from ~30ms to
# ~200ms); the failure classes this gate exists for — flush-scatter
# compiles (~400ms), off-diagonal check compiles (~3.5s), sync rebuild
# stalls (multi-second) — sit cleanly above it.
JAX_PLATFORMS=cpu python scripts/soak.py 24 --churn --graph small \
    --window 6 --assert-slo --p99-floor-ms 250 \
    --out /tmp/soak_churn_gate.json

echo "== multi-chip dryrun (8-device virtual mesh + single-chip entry)"
JAX_PLATFORMS=cpu python __graft_entry__.py 8

echo "== multi-chip mesh smoke (proxy on jax://?mesh=1x2, oracle parity)"
# the sharded shard_map path end to end (docs/performance.md
# "Multi-chip mesh"): the server boots a 1x2 (data x graph) mesh over
# forced virtual CPU devices, a filtered LIST through the full proxy
# stack matches the embedded host oracle before and after write churn
# (no full rebuild), and /metrics carries one
# authz_device_shard_bytes{kind,device} ledger row per mesh device.
# Runs even with --fast.
python scripts/mesh_smoke.py

if [[ "${1:-}" != "--fast" ]]; then
  echo "== bench smoke (pods-depth1, CPU)"
  JAX_PLATFORMS=cpu python bench.py --config pods-depth1 --single --batch 64 \
      --rounds 2 --oracle-queries 1
fi

echo "check.sh: ALL GREEN"
