"""Rule-selection invariants (reference pkg/authz/rules.go): at most one
update rule and at most one prefilter rule may match a request."""

from __future__ import annotations


class MultipleRulesError(Exception):
    pass


def single_update_rule(matching_rules: list):
    with_updates = [r for r in matching_rules if r.update is not None]
    if not with_updates:
        return None
    if len(with_updates) > 1:
        raise MultipleRulesError(
            f"multiple write rules matched: {[r.name for r in with_updates]}")
    return with_updates[0]


def single_pre_filter_rule(matching_rules: list):
    with_pre = [r for r in matching_rules if r.pre_filter]
    if not with_pre:
        return None
    if len(with_pre) > 1:
        raise MultipleRulesError(
            f"multiple pre-filter rules matched: {[r.name for r in with_pre]}")
    return with_pre[0]


def post_filter_rules(matching_rules: list) -> list:
    return [r for r in matching_rules if r.post_filter]
