"""Behavior parity for the deploy/ demo (rules.yaml + bootstrap.yaml).

The demo is original to this repo (multi-tenant tenant/namespace/pod
domain of __graft_entry__.py); these tests pin its behavior end-to-end
through the real proxy for every verb the rules cover: check-gated get,
prefiltered list, CEL-gated + precondition-guarded dual-write create,
tupleSet fan-out, deleteByFilter teardown, postfilter, and the
banned-user exclusion walking the depth-4 graph.
"""

import asyncio
import json
import pathlib

import pytest

from spicedb_kubeapi_proxy_tpu.kubefake.apiserver import FakeKubeApiServer
from spicedb_kubeapi_proxy_tpu.proxy.httpcore import HandlerTransport
from spicedb_kubeapi_proxy_tpu.proxy.server import Options, ProxyServer
from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import Bootstrap

DEPLOY = pathlib.Path(__file__).resolve().parent.parent / "deploy"


def make_proxy(endpoint_url="embedded://"):
    kube = FakeKubeApiServer()
    kube.seed("", "v1", "namespaces", {"metadata": {"name": "acme-prod"}})
    kube.seed("", "v1", "namespaces", {"metadata": {"name": "initech-dev"}})
    for pod in ("api-0", "api-1"):
        kube.seed("", "v1", "pods",
                  {"metadata": {"name": pod, "namespace": "acme-prod"}})
    kube.seed("", "v1", "pods",
              {"metadata": {"name": "tps-report", "namespace": "initech-dev"}})
    kube.seed("", "v1", "events",
              {"metadata": {"name": "ev-a", "namespace": "acme-prod"}})
    kube.seed("", "v1", "events",
              {"metadata": {"name": "ev-i", "namespace": "initech-dev"}})
    kube.seed("", "v1", "configmaps",
              {"metadata": {"name": "cm", "namespace": "acme-prod"}})

    proxy = ProxyServer(Options(
        spicedb_endpoint=endpoint_url,
        bootstrap=Bootstrap.from_file(str(DEPLOY / "bootstrap.yaml")),
        rules_yaml=(DEPLOY / "rules.yaml").read_text(),
        upstream_transport=HandlerTransport(kube),
    ))
    proxy.enable_dual_writes()
    return proxy, kube


@pytest.fixture(params=["embedded://", "jax://"])
def proxy_kube(request):
    return make_proxy(request.param)


def run(coro):
    return asyncio.run(coro)


def names(list_body):
    return sorted(i["metadata"]["name"] for i in json.loads(list_body)["items"])


class TestReadPaths:
    def test_admin_reaches_pods_through_tenant_arrow(self, proxy_kube):
        proxy, _ = proxy_kube
        ada = proxy.get_embedded_client(user="ada")

        async def go():
            resp = await ada.get("/api/v1/pods")
            assert resp.status == 200
            assert names(resp.body) == ["api-0", "api-1"]
            assert (await ada.get(
                "/api/v1/namespaces/acme-prod/pods/api-0")).status == 200
            assert (await ada.get(
                "/api/v1/namespaces/initech-dev/pods/tps-report")).status == 403
        run(go())

    def test_nested_group_member_reaches_pods_depth4(self, proxy_kube):
        """grace: eng -> platform -> tenant acme member -> namespace arrow."""
        proxy, _ = proxy_kube
        grace = proxy.get_embedded_client(user="grace")

        async def go():
            resp = await grace.get("/api/v1/pods")
            assert names(resp.body) == ["api-0", "api-1"]
            resp = await grace.get("/api/v1/namespaces")
            assert names(resp.body) == ["acme-prod"]
        run(go())

    def test_banned_user_excluded_from_one_pod(self, proxy_kube):
        """mallory views acme-prod but api-1 subtracts her via `banned`."""
        proxy, _ = proxy_kube
        mallory = proxy.get_embedded_client(user="mallory")

        async def go():
            resp = await mallory.get("/api/v1/pods")
            assert names(resp.body) == ["api-0"]
            assert (await mallory.get(
                "/api/v1/namespaces/acme-prod/pods/api-1")).status == 403
        run(go())

    def test_direct_viewer_scoped_to_own_namespace(self, proxy_kube):
        proxy, _ = proxy_kube
        peek = proxy.get_embedded_client(user="peek")

        async def go():
            resp = await peek.get("/api/v1/pods")
            assert names(resp.body) == ["tps-report"]
        run(go())

    def test_event_postfilter_by_namespace(self, proxy_kube):
        proxy, _ = proxy_kube
        ada = proxy.get_embedded_client(user="ada")

        async def go():
            resp = await ada.get("/api/v1/events")
            assert resp.status == 200
            assert names(resp.body) == ["ev-a"]
        run(go())

    def test_operator_cel_gate(self, proxy_kube):
        proxy, _ = proxy_kube

        async def go():
            op = proxy.get_embedded_client(
                user="ops", groups=["system:operators"])
            assert (await op.get(
                "/api/v1/namespaces/acme-prod/configmaps/cm")).status == 200
            outsider = proxy.get_embedded_client(user="ada")
            assert (await outsider.get(
                "/api/v1/namespaces/acme-prod/configmaps/cm")).status == 403
        run(go())


class TestWritePaths:
    def test_namespace_create_binds_tenant_from_label(self, proxy_kube):
        proxy, _ = proxy_kube
        ada = proxy.get_embedded_client(user="ada")
        body = json.dumps({
            "apiVersion": "v1", "kind": "Namespace",
            "metadata": {"name": "acme-stage",
                         "labels": {"tenant": "acme"}}}).encode()

        async def go():
            resp = await ada.request("POST", "/api/v1/namespaces", body=body)
            assert resp.status in (200, 201), resp.body
            rels = [r.rel_string() for r in proxy.endpoint.store.read(None)]
            assert "namespace:acme-stage#tenant@tenant:acme" in rels
            # ada now reaches it via the tenant arrow
            assert (await ada.get(
                "/api/v1/namespaces/acme-stage")).status == 200
        run(go())

    def test_namespace_create_denied_without_tenant_access(self, proxy_kube):
        proxy, _ = proxy_kube
        bill = proxy.get_embedded_client(user="bill")  # initech, not acme
        body = json.dumps({
            "apiVersion": "v1", "kind": "Namespace",
            "metadata": {"name": "sneaky",
                         "labels": {"tenant": "acme"}}}).encode()

        async def go():
            resp = await bill.request("POST", "/api/v1/namespaces", body=body)
            assert resp.status == 403
        run(go())

    def test_namespace_create_without_label_unmatched(self, proxy_kube):
        """No `tenant` label -> the CEL `if` rejects the rule -> no rule
        matches -> request denied (fail closed)."""
        proxy, _ = proxy_kube
        ada = proxy.get_embedded_client(user="ada")
        body = json.dumps({
            "apiVersion": "v1", "kind": "Namespace",
            "metadata": {"name": "unlabeled"}}).encode()

        async def go():
            resp = await ada.request("POST", "/api/v1/namespaces", body=body)
            assert resp.status == 403
        run(go())

    def test_rebind_precondition_blocks_second_tenant(self, proxy_kube):
        proxy, _ = proxy_kube
        # bill is initech admin; acme-prod is already bound to acme
        bill = proxy.get_embedded_client(user="bill")
        body = json.dumps({
            "apiVersion": "v1", "kind": "Namespace",
            "metadata": {"name": "acme-prod",
                         "labels": {"tenant": "initech"}}}).encode()

        async def go():
            resp = await bill.request("POST", "/api/v1/namespaces", body=body)
            assert resp.status == 409  # precondition failed -> conflict
        run(go())

    def test_pod_launch_with_sharewith_fanout(self, proxy_kube):
        proxy, kube = proxy_kube
        ada = proxy.get_embedded_client(user="ada")
        body = json.dumps({
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "api-2", "namespace": "acme-prod"},
            "spec": {"shareWith": ["guest1", "guest2"]}}).encode()

        async def go():
            resp = await ada.request(
                "POST", "/api/v1/namespaces/acme-prod/pods", body=body)
            assert resp.status in (200, 201), resp.body
            rels = {r.rel_string() for r in proxy.endpoint.store.read(None)}
            assert "pod:acme-prod/api-2#creator@user:ada" in rels
            assert "pod:acme-prod/api-2#namespace@namespace:acme-prod" in rels
            assert "pod:acme-prod/api-2#viewer@user:guest1" in rels
            assert "pod:acme-prod/api-2#viewer@user:guest2" in rels
            # guest1 sees exactly the shared pod
            guest = proxy.get_embedded_client(user="guest1")
            resp = await guest.get("/api/v1/pods")
            assert names(resp.body) == ["api-2"]
        run(go())

    def test_pod_retire_deletes_all_rels_by_filter(self, proxy_kube):
        proxy, _ = proxy_kube
        ada = proxy.get_embedded_client(user="ada")
        body = json.dumps({
            "apiVersion": "v1", "kind": "Pod",
            "metadata": {"name": "api-3", "namespace": "acme-prod"},
            "spec": {"shareWith": ["guest9"]}}).encode()

        async def go():
            resp = await ada.request(
                "POST", "/api/v1/namespaces/acme-prod/pods", body=body)
            assert resp.status in (200, 201), resp.body
            resp = await ada.request(
                "DELETE", "/api/v1/namespaces/acme-prod/pods/api-3")
            assert resp.status in (200, 202), resp.body
            rels = {r.rel_string() for r in proxy.endpoint.store.read(None)}
            assert not any("pod:acme-prod/api-3#" in r for r in rels), rels
        run(go())

    def test_namespace_teardown_sweeps_viewers(self, proxy_kube):
        proxy, _ = proxy_kube
        peek = proxy.get_embedded_client(user="peek")

        async def go():
            resp = await peek.request("DELETE", "/api/v1/namespaces/initech-dev")
            assert resp.status in (200, 202), resp.body
            rels = {r.rel_string() for r in proxy.endpoint.store.read(None)}
            assert not any(r.startswith("namespace:initech-dev#")
                           for r in rels), rels
        run(go())
