"""TPU-native kube-apiserver authorizing proxy.

A from-scratch framework with the capabilities of
authzed/spicedb-kubeapi-proxy; the authorization hot path executes as
batched boolean-SpMV reachability kernels on TPU via the `jax://` endpoint
backend (see SURVEY.md).
"""

__version__ = "0.1.0"
