"""Dual-write workflows (reference pkg/authz/distributedtx/workflow.go).

Pessimistic: acquire a lock relationship (hash of path+name+verb) together
with the SpiceDB writes and preconditions, then write to kube with bounded
retries; on failure roll back with inverted operations; always remove the
lock.  SpiceDB write failures surface as kube 409 Conflict.

Optimistic: SpiceDB write -> kube write; on a kube activity failure, probe
object existence and roll back iff the object is absent.

deleteByFilter reads matching relationships first so retries delete a
deterministic set (workflow.go:353-388).
"""

from __future__ import annotations

import hashlib
import json
from typing import Optional

from .engine import ActivityError, DEFAULT_WORKFLOW_TIMEOUT, WorkflowContext

LOCK_RESOURCE_TYPE = "lock"
LOCK_RELATION_NAME = "workflow"
WORKFLOW_RESOURCE_TYPE = "workflow"
MAX_KUBE_ATTEMPTS = 5
STRATEGY_OPTIMISTIC = "Optimistic"
STRATEGY_PESSIMISTIC = "Pessimistic"

__all__ = ["DEFAULT_WORKFLOW_TIMEOUT"]

KUBE_BACKOFF_BASE = 0.1
KUBE_BACKOFF_FACTOR = 2.0


def _invert(update: dict) -> dict:
    op = update["op"]
    inverted = "delete" if op in ("create", "touch") else "touch"
    return {"op": inverted, "rel": update["rel"]}


async def _cleanup(ctx: WorkflowContext, rollback_updates: list,
                   reason: str) -> None:
    """Inverted-op rollback, retried until success (workflow.go:86-129).
    Like the reference, this loops until the write lands (the journal keeps
    the instance durable across crashes; the client's 30s result timeout
    does not stop the workflow) and bails only on unrecoverable
    invalid-argument errors."""
    if reason.startswith("rollback"):
        # note the outcome for the engine's dual-write audit event (the
        # post-success lock cleanup is not a rollback and is not noted)
        notes = getattr(ctx, "notes", None)
        if notes is not None:
            notes.setdefault("rollbacks", []).append(reason)
    updates = [_invert(u) for u in rollback_updates]
    while True:
        try:
            await ctx.execute_activity(
                "write_to_spicedb", {"updates": updates, "preconditions": []},
                ctx.instance_id)
            return
        except ActivityError as e:
            if "invalid" in str(e).lower():
                return  # unrecoverable, matches codes.InvalidArgument bail
            await ctx.sleep(0.05)


def resource_lock_rel(input: dict) -> dict:
    """lock:{hash(path/name/verb)}#workflow@workflow:{id}
    (workflow.go:392-418; xxhash becomes blake2b)."""
    name = input.get("request_name", "")
    if input.get("object_name"):
        name = input["object_name"]
    lock_key = f"{input.get('request_path', '')}/{name}/{input.get('verb', '')}"
    lock_hash = hashlib.blake2b(lock_key.encode(), digest_size=8).hexdigest()
    return {
        "op": "create",
        "rel": (f"{LOCK_RESOURCE_TYPE}:{lock_hash}#{LOCK_RELATION_NAME}"
                f"@{WORKFLOW_RESOURCE_TYPE}:{{workflow_id}}"),
        "lock_hash": lock_hash,
    }


def _lock_update(input: dict, workflow_id: str) -> tuple:
    tmpl = resource_lock_rel(input)
    rel = tmpl["rel"].replace("{workflow_id}", workflow_id)
    precondition = {
        "op": "must_not_match",
        "filter": {
            "resource_type": LOCK_RESOURCE_TYPE,
            "resource_id": tmpl["lock_hash"],
            "relation": LOCK_RELATION_NAME,
            "subject": {"type": WORKFLOW_RESOURCE_TYPE, "id": "",
                        "relation": None},
        },
    }
    return {"op": "create", "rel": rel}, precondition


def _collect_updates(input: dict) -> list:
    updates = []
    for r in input.get("creates", []):
        updates.append({"op": "create", "rel": r})
    for r in input.get("touches", []):
        updates.append({"op": "touch", "rel": r})
    for r in input.get("deletes", []):
        updates.append({"op": "delete", "rel": r})
    return updates


async def _append_deletes_from_filters(ctx: WorkflowContext, input: dict,
                                       updates: list) -> None:
    """Read-then-delete for deterministic retry (workflow.go:353-388)."""
    for f in input.get("delete_by_filter", []):
        rels = await ctx.execute_activity("read_relationships", f)
        for rel_string in rels:
            updates.append({"op": "delete", "rel": rel_string})


def kube_conflict(error: str, input: dict) -> dict:
    """SpiceDB failure -> kube 409 Conflict (workflow.go:422-450)."""
    status = {
        "kind": "Status", "apiVersion": "v1", "metadata": {},
        "status": "Failure",
        "message": (f"Operation cannot be fulfilled on"
                    f" {input.get('resource', '')} \"{input.get('object_name', '')}\":"
                    f" {error}"),
        "reason": "Conflict",
        "details": {"group": input.get('api_group', ''),
                    "kind": input.get('resource', ''),
                    "name": input.get('object_name', '')},
        "code": 409,
    }
    return {"status_code": 409, "content_type": "application/json",
            "body": json.dumps(status)}


def _kube_req(input: dict) -> dict:
    return {
        "verb": input.get("verb", ""),
        "request_uri": input.get("request_uri", ""),
        "headers": input.get("headers", {}),
        "body": input.get("body", ""),
    }


def _is_successful_kube_operation(input: dict, out: dict) -> Optional[bool]:
    """None => unsupported verb (workflow.go:249-276)."""
    verb = input.get("verb", "")
    code = out.get("status_code", 0)
    if verb == "delete":
        return code in (404, 200)
    if verb in ("create", "update", "patch"):
        return code in (409, 201, 200)
    return None


async def pessimistic_write(ctx: WorkflowContext, input: dict) -> dict:
    """workflow.go:134-247."""
    if not input.get("user_name"):
        raise ValueError("missing user info in CreateObjectInput")

    lock_rel, lock_precondition = _lock_update(input, ctx.instance_id)
    rollback = [lock_rel]

    preconditions = [lock_precondition] + list(input.get("preconditions", []))
    updates = _collect_updates(input)
    await _append_deletes_from_filters(ctx, input, updates)

    try:
        await ctx.execute_activity(
            "write_to_spicedb",
            {"updates": updates + [lock_rel], "preconditions": preconditions},
            ctx.instance_id)
    except ActivityError as e:
        await _cleanup(ctx, rollback + updates, "rollback due to failed SpiceDB write")
        return kube_conflict(str(e), input)

    backoff = KUBE_BACKOFF_BASE
    for attempt in range(MAX_KUBE_ATTEMPTS + 1):
        try:
            out = await ctx.execute_activity("write_to_kube", _kube_req(input))
        except ActivityError:
            await ctx.sleep(backoff)
            backoff *= KUBE_BACKOFF_FACTOR
            continue

        # kube throttling: honor RetryAfterSeconds (workflow.go:225-229)
        retry_after = out.get("retry_after_seconds") or 0
        if retry_after > 0:
            await ctx.sleep(min(float(retry_after), 5.0))
            continue

        ok = _is_successful_kube_operation(input, out)
        if ok is None:
            await _cleanup(ctx, rollback + updates,
                           "rollback due to unsupported kube verb")
            raise ValueError(f"unsupported kube verb: {input.get('verb')}")
        if ok:
            await _cleanup(ctx, rollback,
                           "cleanup after successful kube operation")
            return out
        await _cleanup(ctx, rollback + updates,
                       "rollback due to unsuccessful kube operation")
        return out

    await _cleanup(ctx, rollback + updates,
                   "rollback due to failed kube operation after max attempts")
    raise RuntimeError(
        f"failed to communicate with kubernetes after {MAX_KUBE_ATTEMPTS} attempts")


async def optimistic_write(ctx: WorkflowContext, input: dict) -> dict:
    """workflow.go:279-351."""
    if not input.get("user_name"):
        raise ValueError("missing user info in CreateObjectInput")

    updates = _collect_updates(input)
    await _append_deletes_from_filters(ctx, input, updates)

    try:
        await ctx.execute_activity(
            "write_to_spicedb",
            {"updates": updates,
             "preconditions": list(input.get("preconditions", []))},
            ctx.instance_id)
    except ActivityError as e:
        await _cleanup(ctx, updates, "rollback due to failed SpiceDB write")
        return kube_conflict(str(e), input)

    try:
        out = await ctx.execute_activity("write_to_kube", _kube_req(input))
    except ActivityError:
        # the activity may have failed after the kube write landed: probe
        exists = await ctx.execute_activity(
            "check_kube_resource", input.get("probe_uri", ""))
        if not exists:
            await _cleanup(ctx, updates, "rollback due to failed Kube write")
        # when the object exists the state has converged, but like the
        # reference (workflow.go:334-350 returns a nil response) the client
        # still sees an error and must re-inspect
        raise
    return out


WORKFLOWS = {
    STRATEGY_PESSIMISTIC: pessimistic_write,
    STRATEGY_OPTIMISTIC: optimistic_write,
}


def workflow_for_lock_mode(lock_mode: str) -> str:
    return (STRATEGY_OPTIMISTIC if lock_mode == STRATEGY_OPTIMISTIC
            else STRATEGY_PESSIMISTIC)
