"""Golden wire-format fixtures for the authzed.api.v1 codecs (spicedb/wire.py).

Two independent layers of evidence that `grpc://` speaks real authzed.api.v1
wire format rather than a private dialect (VERDICT r2 item 4):

1. LITERAL golden bytes: hand-assembled from the public authzed.api.v1
   proto field numbers (transcribed in wire.py's docstring).  These cannot
   drift with the codecs — if an encoder changes field numbers, the
   fixtures break.
2. Cross-validation against the REAL protobuf runtime: the same messages
   built with google.protobuf dynamic descriptors mirroring
   authzed/api/v1/{core,permission_service,watch_service}.proto; encoders
   must produce bytes the real runtime parses to the same values, and
   byte-identical output for ascending-field-order messages.

Reference consumes these protos through authzed-go (go.mod:6-14; e.g.
pkg/authz/check.go:48 CheckBulkPermissions).
"""

import pytest
from google.protobuf import descriptor_pb2, descriptor_pool, message_factory

from spicedb_kubeapi_proxy_tpu.spicedb import wire
from spicedb_kubeapi_proxy_tpu.spicedb.types import (
    CheckRequest,
    CheckResult,
    ObjectRef,
    Permissionship,
    Precondition,
    PreconditionOp,
    Relationship,
    RelationshipFilter,
    RelationshipUpdate,
    SubjectFilter,
    SubjectRef,
    UpdateOp,
)


# -- dynamic descriptors mirroring authzed.api.v1 -----------------------------

def _build_authzed_messages():
    T = descriptor_pb2.FieldDescriptorProto
    fdp = descriptor_pb2.FileDescriptorProto()
    fdp.name = "authzed_mirror.proto"
    fdp.package = "authzed.api.v1mirror"
    fdp.syntax = "proto3"

    def msg(name, fields_, enums=()):
        m = fdp.message_type.add()
        m.name = name
        if any(extra.get("oneof") for _, _, _, extra in fields_):
            m.oneof_decl.add().name = "kind"
        for num, fname, ftype, extra in fields_:
            f = m.field.add()
            f.name = fname
            f.number = num
            f.label = (T.LABEL_REPEATED if extra.get("repeated")
                       else T.LABEL_OPTIONAL)
            f.type = ftype
            if "type_name" in extra:
                f.type_name = ".authzed.api.v1mirror." + extra["type_name"]
            if extra.get("oneof"):
                # oneof members get explicit presence (google.protobuf
                # Value's `kind`: bool_value=false IS serialized)
                f.oneof_index = 0
            if ftype == T.TYPE_MESSAGE and not extra.get("repeated"):
                # proto3 explicit presence for submessages
                pass

    M = T.TYPE_MESSAGE
    S = T.TYPE_STRING
    E = T.TYPE_ENUM
    B = T.TYPE_BOOL
    I = T.TYPE_INT64
    I32 = T.TYPE_INT32

    en = fdp.enum_type.add()
    en.name = "Permissionship"
    for i, n in enumerate(["PERMISSIONSHIP_UNSPECIFIED", "NO_PERMISSION",
                           "HAS_PERMISSION", "CONDITIONAL_PERMISSION"]):
        v = en.value.add(); v.name = n; v.number = i
    en2 = fdp.enum_type.add()
    en2.name = "UpdateOp"
    for i, n in enumerate(["OPERATION_UNSPECIFIED", "OPERATION_CREATE",
                           "OPERATION_TOUCH", "OPERATION_DELETE"]):
        v = en2.value.add(); v.name = n; v.number = i
    en3 = fdp.enum_type.add()
    en3.name = "PreconditionOp"
    for i, n in enumerate(["OPERATION_UNSPECIFIED2", "OPERATION_MUST_NOT_MATCH",
                           "OPERATION_MUST_MATCH"]):
        v = en3.value.add(); v.name = n; v.number = i

    D = T.TYPE_DOUBLE
    en4 = fdp.enum_type.add()
    en4.name = "NullValue"
    v = en4.value.add(); v.name = "NULL_VALUE"; v.number = 0

    msg("ObjectReference", [(1, "object_type", S, {}), (2, "object_id", S, {})])
    msg("SubjectReference", [
        (1, "object", M, {"type_name": "ObjectReference"}),
        (2, "optional_relation", S, {})])
    msg("Timestamp", [(1, "seconds", I, {}), (2, "nanos", I32, {})])
    # google.protobuf.Struct mirror (caveat context); the map field is
    # declared as a repeated entry message, which is wire-identical
    msg("Value", [
        (1, "null_value", E, {"type_name": "NullValue", "oneof": True}),
        (2, "number_value", D, {"oneof": True}),
        (3, "string_value", S, {"oneof": True}),
        (4, "bool_value", B, {"oneof": True}),
        (5, "struct_value", M, {"type_name": "Struct", "oneof": True}),
        (6, "list_value", M, {"type_name": "ListValue", "oneof": True})])
    msg("StructFieldsEntry", [
        (1, "key", S, {}), (2, "value", M, {"type_name": "Value"})])
    msg("Struct", [
        (1, "fields", M, {"type_name": "StructFieldsEntry",
                          "repeated": True})])
    msg("ListValue", [
        (1, "values", M, {"type_name": "Value", "repeated": True})])
    msg("ContextualizedCaveat", [
        (1, "caveat_name", S, {}),
        (2, "context", M, {"type_name": "Struct"})])
    msg("Relationship", [
        (1, "resource", M, {"type_name": "ObjectReference"}),
        (2, "relation", S, {}),
        (3, "subject", M, {"type_name": "SubjectReference"}),
        (4, "optional_caveat", M, {"type_name": "ContextualizedCaveat"}),
        (5, "optional_expires_at", M, {"type_name": "Timestamp"})])
    msg("ZedToken", [(1, "token", S, {})])
    msg("Consistency", [(4, "fully_consistent", B, {})])
    msg("RelationFilter", [(1, "relation", S, {})])
    msg("SubjectFilter", [
        (1, "subject_type", S, {}), (2, "optional_subject_id", S, {}),
        (3, "optional_relation", M, {"type_name": "RelationFilter"})])
    msg("RelationshipFilter", [
        (1, "resource_type", S, {}), (2, "optional_resource_id", S, {}),
        (3, "optional_relation", S, {}),
        (4, "optional_subject_filter", M, {"type_name": "SubjectFilter"})])
    msg("Precondition", [
        (1, "operation", E, {"type_name": "PreconditionOp"}),
        (2, "filter", M, {"type_name": "RelationshipFilter"})])
    msg("RelationshipUpdate", [
        (1, "operation", E, {"type_name": "UpdateOp"}),
        (2, "relationship", M, {"type_name": "Relationship"})])
    msg("CheckPermissionRequest", [
        (1, "consistency", M, {"type_name": "Consistency"}),
        (2, "resource", M, {"type_name": "ObjectReference"}),
        (3, "permission", S, {}),
        (4, "subject", M, {"type_name": "SubjectReference"})])
    msg("CheckPermissionResponse", [
        (1, "checked_at", M, {"type_name": "ZedToken"}),
        (2, "permissionship", E, {"type_name": "Permissionship"})])
    msg("CheckBulkPermissionsRequestItem", [
        (1, "resource", M, {"type_name": "ObjectReference"}),
        (2, "permission", S, {}),
        (3, "subject", M, {"type_name": "SubjectReference"})])
    msg("CheckBulkPermissionsRequest", [
        (1, "consistency", M, {"type_name": "Consistency"}),
        (2, "items", M, {"type_name": "CheckBulkPermissionsRequestItem",
                         "repeated": True})])
    msg("CheckBulkPermissionsResponseItem", [
        (1, "permissionship", E, {"type_name": "Permissionship"})])
    msg("CheckBulkPermissionsPair", [
        (1, "request", M, {"type_name": "CheckBulkPermissionsRequestItem"}),
        (2, "item", M, {"type_name": "CheckBulkPermissionsResponseItem"})])
    msg("CheckBulkPermissionsResponse", [
        (1, "checked_at", M, {"type_name": "ZedToken"}),
        (2, "pairs", M, {"type_name": "CheckBulkPermissionsPair",
                         "repeated": True})])
    msg("LookupResourcesRequest", [
        (1, "consistency", M, {"type_name": "Consistency"}),
        (2, "resource_object_type", S, {}),
        (3, "permission", S, {}),
        (4, "subject", M, {"type_name": "SubjectReference"})])
    msg("LookupResourcesResponse", [
        (1, "looked_up_at", M, {"type_name": "ZedToken"}),
        (2, "resource_object_id", S, {}),
        (3, "permissionship", E, {"type_name": "Permissionship"})])
    msg("ReadRelationshipsRequest", [
        (1, "consistency", M, {"type_name": "Consistency"}),
        (2, "relationship_filter", M, {"type_name": "RelationshipFilter"})])
    msg("ReadRelationshipsResponse", [
        (1, "read_at", M, {"type_name": "ZedToken"}),
        (2, "relationship", M, {"type_name": "Relationship"})])
    msg("WriteRelationshipsRequest", [
        (1, "updates", M, {"type_name": "RelationshipUpdate",
                           "repeated": True}),
        (2, "optional_preconditions", M, {"type_name": "Precondition",
                                          "repeated": True})])
    msg("WriteRelationshipsResponse", [
        (1, "written_at", M, {"type_name": "ZedToken"})])
    msg("DeleteRelationshipsRequest", [
        (1, "relationship_filter", M, {"type_name": "RelationshipFilter"}),
        (2, "optional_preconditions", M, {"type_name": "Precondition",
                                          "repeated": True})])
    msg("DeleteRelationshipsResponse", [
        (1, "deleted_at", M, {"type_name": "ZedToken"})])
    msg("WatchRequest", [(1, "optional_object_types", S, {"repeated": True})])
    msg("WatchResponse", [
        (1, "updates", M, {"type_name": "RelationshipUpdate",
                           "repeated": True}),
        (2, "changes_through", M, {"type_name": "ZedToken"})])

    pool = descriptor_pool.DescriptorPool()
    pool.Add(fdp)
    names = [m.name for m in fdp.message_type]
    return {n: message_factory.GetMessageClass(
        pool.FindMessageTypeByName(f"authzed.api.v1mirror.{n}"))
        for n in names}


A = _build_authzed_messages()

REL = Relationship(resource=ObjectRef("pod", "ns1/p0"), relation="viewer",
                   subject=SubjectRef("user", "alice"))
SUBJ = SubjectRef("user", "alice")


def real_rel(msg=None):
    r = A["Relationship"]()
    r.resource.object_type = "pod"
    r.resource.object_id = "ns1/p0"
    r.relation = "viewer"
    r.subject.object.object_type = "user"
    r.subject.object.object_id = "alice"
    return r


# -- literal golden bytes -----------------------------------------------------
# Assembled by hand from the proto field numbers; each byte commented.

# Consistency { fully_consistent = true }: field 4 varint -> tag 0x20, 1
GOLDEN_CONSISTENCY = bytes([0x20, 0x01])

# ObjectReference { object_type="pod" (1), object_id="ns1/p0" (2) }
GOLDEN_OBJ = bytes([0x0A, 3]) + b"pod" + bytes([0x12, 6]) + b"ns1/p0"

# SubjectReference { object = ObjectReference{ "user", "alice" } }
GOLDEN_SUBJ_OBJ = bytes([0x0A, 4]) + b"user" + bytes([0x12, 5]) + b"alice"
GOLDEN_SUBJ = bytes([0x0A, len(GOLDEN_SUBJ_OBJ)]) + GOLDEN_SUBJ_OBJ

# CheckPermissionRequest { consistency=1, resource=2, permission="view" (3),
#                          subject=4 }
GOLDEN_CHECK_REQ = (
    bytes([0x0A, len(GOLDEN_CONSISTENCY)]) + GOLDEN_CONSISTENCY
    + bytes([0x12, len(GOLDEN_OBJ)]) + GOLDEN_OBJ
    + bytes([0x1A, 4]) + b"view"
    + bytes([0x22, len(GOLDEN_SUBJ)]) + GOLDEN_SUBJ)

# CheckPermissionResponse { checked_at=ZedToken{"42"}, HAS_PERMISSION (2) }
GOLDEN_ZED = bytes([0x0A, 2]) + b"42"
GOLDEN_CHECK_RESP = (bytes([0x0A, len(GOLDEN_ZED)]) + GOLDEN_ZED
                     + bytes([0x10, 0x02]))

# Relationship { resource=1, relation="viewer" (2), subject=3 }
GOLDEN_REL = (bytes([0x0A, len(GOLDEN_OBJ)]) + GOLDEN_OBJ
              + bytes([0x12, 6]) + b"viewer"
              + bytes([0x1A, len(GOLDEN_SUBJ)]) + GOLDEN_SUBJ)

# WriteRelationshipsRequest { updates=[{ TOUCH (2), relationship }] }
GOLDEN_UPDATE = (bytes([0x08, 0x02])
                 + bytes([0x12, len(GOLDEN_REL)]) + GOLDEN_REL)
GOLDEN_WRITE_REQ = bytes([0x0A, len(GOLDEN_UPDATE)]) + GOLDEN_UPDATE

# LookupResourcesRequest { consistency=1, resource_object_type="pod" (2),
#                          permission="view" (3), subject=4 }
GOLDEN_LOOKUP_REQ = (
    bytes([0x0A, len(GOLDEN_CONSISTENCY)]) + GOLDEN_CONSISTENCY
    + bytes([0x12, 3]) + b"pod"
    + bytes([0x1A, 4]) + b"view"
    + bytes([0x22, len(GOLDEN_SUBJ)]) + GOLDEN_SUBJ)

# LookupResourcesResponse { looked_up_at=ZedToken{"42"},
#                           resource_object_id="ns1/p0" (2),
#                           HAS_PERMISSION (3) }
GOLDEN_LOOKUP_RESP = (bytes([0x0A, len(GOLDEN_ZED)]) + GOLDEN_ZED
                      + bytes([0x12, 6]) + b"ns1/p0"
                      + bytes([0x18, 0x02]))

# CheckBulkPermissionsRequest { consistency=1, items=[{resource=1,
#                               permission="view" (2), subject=3}] }
GOLDEN_BULK_ITEM = (bytes([0x0A, len(GOLDEN_OBJ)]) + GOLDEN_OBJ
                    + bytes([0x12, 4]) + b"view"
                    + bytes([0x1A, len(GOLDEN_SUBJ)]) + GOLDEN_SUBJ)
GOLDEN_BULK_REQ = (
    bytes([0x0A, len(GOLDEN_CONSISTENCY)]) + GOLDEN_CONSISTENCY
    + bytes([0x12, len(GOLDEN_BULK_ITEM)]) + GOLDEN_BULK_ITEM)


class TestLiteralGoldenBytes:
    def test_check_request(self):
        assert wire.enc_check_request(CheckRequest(
            resource=ObjectRef("pod", "ns1/p0"), permission="view",
            subject=SUBJ)) == GOLDEN_CHECK_REQ

    def test_check_request_decode(self):
        req = wire.dec_check_request(GOLDEN_CHECK_REQ)
        assert req.resource == ObjectRef("pod", "ns1/p0")
        assert req.permission == "view"
        assert (req.subject.type, req.subject.id) == ("user", "alice")

    def test_check_response(self):
        assert wire.enc_check_response(CheckResult(
            permissionship=Permissionship.HAS_PERMISSION,
            checked_at=42)) == GOLDEN_CHECK_RESP
        res = wire.dec_check_response(GOLDEN_CHECK_RESP)
        assert res.permissionship == Permissionship.HAS_PERMISSION
        assert res.checked_at == 42

    def test_write_request(self):
        assert wire.enc_write_request(
            [RelationshipUpdate(UpdateOp.TOUCH, REL)], []) == GOLDEN_WRITE_REQ
        updates, pre = wire.dec_write_request(GOLDEN_WRITE_REQ)
        assert len(updates) == 1 and not pre
        assert updates[0].op == UpdateOp.TOUCH
        assert updates[0].rel.resource == ObjectRef("pod", "ns1/p0")

    def test_lookup_request(self):
        assert wire.enc_lookup_request("pod", "view", SUBJ) == \
            GOLDEN_LOOKUP_REQ
        assert wire.dec_lookup_request(GOLDEN_LOOKUP_REQ)[:2] == \
            ("pod", "view")

    def test_lookup_response(self):
        assert wire.enc_lookup_response(42, "ns1/p0") == GOLDEN_LOOKUP_RESP
        rid, perm = wire.dec_lookup_response(GOLDEN_LOOKUP_RESP)
        assert rid == "ns1/p0"
        assert perm == Permissionship.HAS_PERMISSION

    def test_bulk_request(self):
        assert wire.enc_bulk_request([CheckRequest(
            resource=ObjectRef("pod", "ns1/p0"), permission="view",
            subject=SUBJ)]) == GOLDEN_BULK_REQ
        items = wire.dec_bulk_request(GOLDEN_BULK_REQ)
        assert len(items) == 1
        assert items[0].resource == ObjectRef("pod", "ns1/p0")


class TestAgainstRealProtobuf:
    """Encoders' output parsed by the real runtime; real runtime's output
    parsed by the decoders; byte-identity where field order is ascending."""

    def test_check_request_bytes_identical(self):
        m = A["CheckPermissionRequest"]()
        m.consistency.fully_consistent = True
        m.resource.object_type = "pod"
        m.resource.object_id = "ns1/p0"
        m.permission = "view"
        m.subject.object.object_type = "user"
        m.subject.object.object_id = "alice"
        ours = wire.enc_check_request(CheckRequest(
            resource=ObjectRef("pod", "ns1/p0"), permission="view",
            subject=SUBJ))
        assert ours == m.SerializeToString()

    def test_check_response_round_trip(self):
        m = A["CheckPermissionResponse"]()
        m.checked_at.token = "7"
        m.permissionship = 3  # CONDITIONAL
        res = wire.dec_check_response(m.SerializeToString())
        assert res.permissionship == Permissionship.CONDITIONAL_PERMISSION
        assert res.checked_at == 7
        m2 = A["CheckPermissionResponse"]()
        m2.ParseFromString(wire.enc_check_response(res))
        assert m2.permissionship == 3 and m2.checked_at.token == "7"

    def test_relationship_with_expiration(self):
        rel = Relationship(resource=ObjectRef("pod", "p"), relation="viewer",
                           subject=SubjectRef("user", "u"),
                           expires_at=1700000000.5)
        m = A["Relationship"]()
        m.ParseFromString(wire.enc_relationship(rel))
        assert m.optional_expires_at.seconds == 1700000000
        assert m.optional_expires_at.nanos == 500000000
        back = wire.dec_relationship(m.SerializeToString())
        assert back.expires_at == pytest.approx(1700000000.5)

    def test_relationship_with_caveat(self):
        """Caveated relationships carry ContextualizedCaveat (field 4)
        with a google.protobuf.Struct context — validated against the
        real protobuf runtime, all Value kinds exercised."""
        from spicedb_kubeapi_proxy_tpu.spicedb.types import CaveatRef

        ctx = {"n": 3, "ratio": 1.5, "name": "x", "on": True,
               "missing": None, "tags": ["a", 2, False],
               "nested": {"deep": "v"}}
        rel = Relationship(resource=ObjectRef("doc", "d"), relation="viewer",
                           subject=SubjectRef("user", "u"),
                           caveat=CaveatRef.make("quota", ctx))
        m = A["Relationship"]()
        m.ParseFromString(wire.enc_relationship(rel))
        assert m.optional_caveat.caveat_name == "quota"
        got = {e.key: e.value for e in m.optional_caveat.context.fields}
        assert got["n"].number_value == 3
        assert got["ratio"].number_value == 1.5
        assert got["name"].string_value == "x"
        assert got["on"].bool_value is True
        assert got["missing"].WhichOneof("kind") == "null_value"
        assert [v.string_value or v.number_value or v.bool_value
                for v in got["tags"].list_value.values] == ["a", 2, False]
        assert {e.key: e.value.string_value
                for e in got["nested"].struct_value.fields} == {"deep": "v"}
        # decode side: the real runtime's bytes round-trip to equal context
        back = wire.dec_relationship(m.SerializeToString())
        assert back.caveat.name == "quota"
        assert back.caveat.context() == ctx
        assert back == rel  # canonical JSON makes CaveatRef comparable

    def test_caveat_free_relationship_has_no_field4(self):
        rel = Relationship(resource=ObjectRef("doc", "d"), relation="viewer",
                           subject=SubjectRef("user", "u"))
        m = A["Relationship"]()
        m.ParseFromString(wire.enc_relationship(rel))
        assert not m.HasField("optional_caveat")

    def test_subject_with_relation(self):
        s = SubjectRef("group", "eng", "member")
        m = A["SubjectReference"]()
        m.ParseFromString(wire.enc_subject(s))
        assert m.object.object_type == "group"
        assert m.optional_relation == "member"
        assert wire.dec_subject(m.SerializeToString()) == s

    def test_write_request_with_preconditions(self):
        pre = Precondition(
            op=PreconditionOp.MUST_NOT_MATCH,
            filter=RelationshipFilter(
                resource_type="lock", resource_id="h123",
                relation="workflow",
                subject=SubjectFilter("workflow", "", None)))
        ours = wire.enc_write_request(
            [RelationshipUpdate(UpdateOp.CREATE, REL)], [pre])
        m = A["WriteRelationshipsRequest"]()
        m.ParseFromString(ours)
        assert len(m.updates) == 1 and m.updates[0].operation == 1
        assert m.optional_preconditions[0].operation == 1
        f = m.optional_preconditions[0].filter
        assert (f.resource_type, f.optional_resource_id,
                f.optional_relation) == ("lock", "h123", "workflow")
        assert f.optional_subject_filter.subject_type == "workflow"
        upd, pres = wire.dec_write_request(m.SerializeToString())
        assert pres[0].op == PreconditionOp.MUST_NOT_MATCH
        assert pres[0].filter.subject.type == "workflow"

    def test_subject_filter_with_relation_filter(self):
        flt = RelationshipFilter(
            resource_type="pod", resource_id="", relation="viewer",
            subject=SubjectFilter("group", "eng", "member"))
        m = A["RelationshipFilter"]()
        m.ParseFromString(wire.enc_rel_filter(flt))
        assert m.optional_subject_filter.optional_relation.relation == \
            "member"
        back = wire.dec_rel_filter(m.SerializeToString())
        assert back.subject.relation == "member"

    def test_bulk_response_pairs(self):
        m = A["CheckBulkPermissionsResponse"]()
        m.checked_at.token = "9"
        for p in (2, 1, 3):
            pair = m.pairs.add()
            pair.item.permissionship = p
        results = wire.dec_bulk_response(m.SerializeToString())
        assert [r.permissionship for r in results] == [
            Permissionship.HAS_PERMISSION, Permissionship.NO_PERMISSION,
            Permissionship.CONDITIONAL_PERMISSION]
        # our encoder's bytes parse back identically
        m2 = A["CheckBulkPermissionsResponse"]()
        m2.ParseFromString(wire.enc_bulk_response(9, results))
        assert [p.item.permissionship for p in m2.pairs] == [2, 1, 3]
        assert m2.checked_at.token == "9"

    def test_read_request_response(self):
        ours = wire.enc_read_request(RelationshipFilter(
            resource_type="pod", resource_id="", relation="",
            subject=None))
        m = A["ReadRelationshipsRequest"]()
        m.ParseFromString(ours)
        assert m.consistency.fully_consistent is True
        assert m.relationship_filter.resource_type == "pod"
        r = A["ReadRelationshipsResponse"]()
        r.read_at.token = "3"
        r.relationship.CopyFrom(real_rel())
        rel = wire.dec_read_response(r.SerializeToString())
        assert rel.resource == ObjectRef("pod", "ns1/p0")
        assert rel.relation == "viewer"

    def test_delete_request(self):
        flt = RelationshipFilter(resource_type="pod", resource_id="p1",
                                 relation="viewer", subject=None)
        m = A["DeleteRelationshipsRequest"]()
        m.ParseFromString(wire.enc_delete_request(flt, []))
        assert m.relationship_filter.optional_resource_id == "p1"
        back, pres = wire.dec_delete_request(m.SerializeToString())
        assert back.resource_id == "p1" and not pres

    def test_watch_round_trip(self):
        m = A["WatchRequest"]()
        m.optional_object_types.extend(["pod", "namespace"])
        assert wire.dec_watch_request(m.SerializeToString()) == \
            ["pod", "namespace"]
        assert wire.enc_watch_request(["pod", "namespace"]) == \
            m.SerializeToString()

        w = A["WatchResponse"]()
        w.changes_through.token = "11"
        u = w.updates.add()
        u.operation = 3  # DELETE
        u.relationship.CopyFrom(real_rel())
        rev, updates = wire.dec_watch_response(w.SerializeToString())
        assert rev == 11
        assert updates[0].op == UpdateOp.DELETE
        w2 = A["WatchResponse"]()
        w2.ParseFromString(wire.enc_watch_response(rev, updates))
        assert w2.updates[0].operation == 3
        assert w2.changes_through.token == "11"

    def test_lookup_request_bytes_identical(self):
        m = A["LookupResourcesRequest"]()
        m.consistency.fully_consistent = True
        m.resource_object_type = "pod"
        m.permission = "view"
        m.subject.object.object_type = "user"
        m.subject.object.object_id = "alice"
        assert wire.enc_lookup_request("pod", "view", SUBJ) == \
            m.SerializeToString()
