"""Partitioned write scale-out (ROADMAP item 3c, docs/replication.md
"Sharding").

The tuple space splits BY RESOURCE TYPE across N independent leaders —
each with its own WAL, checkpoint lineage, incarnation epochs, and
replication tree (the unmodified PR 9/11 machinery, per shard) — behind
a thin stateless router.  The `relation_footprint` closure proves the
partitioning safe per-schema: a permission whose closure stays on one
shard evaluates identically over that shard's tuple subset, and a
closure spanning two shards is a hard startup error (SL007).  Client
ZedTokens become revision VECTORS (`{shard: revision}`); each shard
leader enforces only its own component through the existing
`X-Authz-Min-Revision` gate, byte-identical to a single-leader
deployment.

- `partition.py`  PartitionMap: `type=shard` assignments + default
                  shard, footprint validation, write-batch routing
                  (internal bookkeeping tuples ride their batch's
                  shard; retries land on the SAME shard).
- `revvec.py`     revision-vector ZedToken encode/decode/merge.
- `router.py`     ShardedEndpoint (N leaders in one process,
                  per-shard device graphs, cross-shard fan-out for
                  untyped reads / delete_by_filter / bulk / watch
                  merge) and ShardRouter/RouterServer (the
                  multi-process thin HTTP router).
- `metrics.py`    gated `authz_shard_*` telemetry.

Killswitch: the `Sharding` feature gate — off, nothing here is
constructed and the proxy is exactly single-shard.
"""

from .metrics import enabled
from .partition import (
    CrossShardWriteError,
    INTERNAL_TYPES,
    PartitionMap,
    PartitionMapError,
    partition_map_for_schema,
)
from .revvec import RevisionVector, RevisionVectorError
from .router import (
    MergedWatcher,
    RouterConfigError,
    RouterServer,
    ShardRouter,
    ShardedEndpoint,
    build_routing_table,
    build_sharded_endpoint,
)

__all__ = [
    "CrossShardWriteError",
    "INTERNAL_TYPES",
    "MergedWatcher",
    "PartitionMap",
    "PartitionMapError",
    "RevisionVector",
    "RevisionVectorError",
    "RouterConfigError",
    "RouterServer",
    "ShardRouter",
    "ShardedEndpoint",
    "build_routing_table",
    "build_sharded_endpoint",
    "enabled",
    "partition_map_for_schema",
]
