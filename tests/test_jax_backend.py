"""Differential tests: jax:// kernel vs the host oracle evaluator.

The embedded evaluator is the reference oracle (SURVEY.md §4: "the
embedded:// evaluator doubles as the reference oracle for differential-
testing the jax:// kernel"); every scenario asserts exact agreement on
checks and LookupResources, including after incremental writes/deletes
(the unsorted-delta device path) and expirations.
"""

import asyncio
import random

import pytest

from spicedb_kubeapi_proxy_tpu.ops.jax_endpoint import JaxEndpoint
from spicedb_kubeapi_proxy_tpu.spicedb import schema as sch
from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import Bootstrap
from spicedb_kubeapi_proxy_tpu.spicedb.evaluator import Evaluator
from spicedb_kubeapi_proxy_tpu.spicedb.store import TupleStore
from spicedb_kubeapi_proxy_tpu.spicedb.types import (
    CheckRequest,
    ObjectRef,
    RelationshipUpdate,
    SubjectRef,
    UpdateOp,
    parse_relationship)


@pytest.fixture(autouse=True, params=["ell", "segment"])
def kernel_kind(request, monkeypatch):
    """Run every differential scenario against BOTH device kernels: the
    bit-packed fixed-fanin default and the segment_sum fallback."""
    monkeypatch.setenv("SPICEDB_TPU_KERNEL", request.param)
    return request.param


def touch(*rels):
    return [RelationshipUpdate(UpdateOp.TOUCH, parse_relationship(r)) for r in rels]


def delete(*rels):
    return [RelationshipUpdate(UpdateOp.DELETE, parse_relationship(r)) for r in rels]


def make_pair(schema_text, rels, clock=None):
    """(jax endpoint, oracle) over the same tuples.  Pass `clock` for
    deterministic expiry tests (the endpoint's expiry heap and the
    store's read-time filtering share it)."""
    schema = sch.parse_schema(schema_text)
    jx = JaxEndpoint(schema, store=TupleStore(clock=clock)
                     if clock is not None else None)
    if rels:
        jx.store.write(touch(*rels))
    oracle = Evaluator(schema, jx.store)
    return jx, oracle


def make_clocked_pair(schema_text, rels):
    """(jx, oracle, clk): a pair on a manual clock — set clk[0] to move
    time for deterministic expiry tests."""
    import time
    clk = [time.time()]
    jx, oracle = make_pair(schema_text, rels, clock=lambda: clk[0])
    return jx, oracle, clk


def assert_agreement(jx, oracle, resource_type, permission, subjects,
                     object_ids=None):
    """Exhaustive check+LR agreement for the given subjects."""
    ids = object_ids if object_ids is not None else \
        jx.store.object_ids_of_type(resource_type)

    async def run():
        for s in subjects:
            want = sorted(oracle.lookup_resources(resource_type, permission, s))
            got = sorted(await jx.lookup_resources(resource_type, permission, s))
            assert got == want, (
                f"LR mismatch for {s}: kernel={got} oracle={want}")
            reqs = [CheckRequest(ObjectRef(resource_type, oid), permission, s)
                    for oid in ids]
            if not reqs:
                continue
            results = await jx.check_bulk_permissions(reqs)
            for oid, res in zip(ids, results):
                want_one = oracle.check(ObjectRef(resource_type, oid),
                                        permission, s)
                assert res.allowed == want_one, (
                    f"check mismatch {resource_type}:{oid}#{permission}@{s}:"
                    f" kernel={res.allowed} oracle={want_one}")
    asyncio.run(run())


GROUPS_SCHEMA = """
definition user {}
definition group {
  relation member: user | group#member
}
definition team {
  relation member: user | group#member
}
definition namespace {
  relation viewer: user | group#member | team#member
  relation creator: user
  permission view = viewer + creator
}
"""

RBAC_DENY_SCHEMA = """
definition user {}
definition group {
  relation member: user | group#member
}
definition project {
  relation assigned: user | group#member
  relation approved: user
  relation banned: user | group#member
  permission edit = assigned & approved - banned
}
"""

ARROW_SCHEMA = """
definition user {}
definition org {
  relation admin: user
  permission admin_perm = admin
}
definition namespace {
  relation org: org
  relation viewer: user
  permission view = viewer + org->admin_perm
}
definition pod {
  relation namespace: namespace
  relation creator: user
  permission view = creator + namespace->view
}
"""

WILDCARD_SCHEMA = """
definition user {}
definition bot {}
definition doc {
  relation viewer: user | user:* | bot
  relation editor: user
  permission view = viewer + editor
}
"""


def users(*names):
    return [SubjectRef("user", n) for n in names]


class TestDifferentialFixed:
    def test_depth1_direct(self):
        jx, oracle = make_pair(GROUPS_SCHEMA, [
            "namespace:ns1#viewer@user:alice",
            "namespace:ns2#creator@user:alice",
            "namespace:ns3#viewer@user:bob",
        ])
        assert_agreement(jx, oracle, "namespace", "view",
                         users("alice", "bob", "nobody"))

    def test_depth4_nested_groups(self):
        jx, oracle = make_pair(GROUPS_SCHEMA, [
            "group:inner#member@user:alice",
            "group:mid#member@group:inner#member",
            "group:outer#member@group:mid#member",
            "team:t#member@group:outer#member",
            "namespace:ns#viewer@team:t#member",
            "namespace:ns2#viewer@group:mid#member",
            "group:other#member@user:bob",
        ])
        assert_agreement(jx, oracle, "namespace", "view",
                         users("alice", "bob", "carol"))

    def test_intersection_exclusion(self):
        jx, oracle = make_pair(RBAC_DENY_SCHEMA, [
            "group:devs#member@user:alice",
            "group:devs#member@user:bob",
            "group:banned-folks#member@user:bob",
            "project:p1#assigned@group:devs#member",
            "project:p1#approved@user:alice",
            "project:p1#approved@user:bob",
            "project:p1#banned@group:banned-folks#member",
            "project:p2#assigned@user:carol",
        ])
        assert_agreement(jx, oracle, "project", "edit",
                         users("alice", "bob", "carol"))

    def test_arrows(self):
        jx, oracle = make_pair(ARROW_SCHEMA, [
            "org:acme#admin@user:boss",
            "namespace:ns#org@org:acme",
            "namespace:ns#viewer@user:watcher",
            "pod:ns/p1#namespace@namespace:ns",
            "pod:ns/p1#creator@user:dev",
            "pod:ns/p2#namespace@namespace:ns",
        ])
        assert_agreement(jx, oracle, "pod", "view",
                         users("boss", "watcher", "dev", "rando"))
        assert_agreement(jx, oracle, "namespace", "view",
                         users("boss", "watcher", "dev"))

    def test_wildcard(self):
        jx, oracle = make_pair(WILDCARD_SCHEMA, [
            "doc:d1#viewer@user:*",
            "doc:d2#editor@user:eve",
            "doc:d3#viewer@user:frank",
        ])
        assert_agreement(jx, oracle, "doc", "view", users("eve", "frank", "zed"))
        # userset subjects must NOT match the wildcard
        assert_agreement(jx, oracle, "doc", "view",
                         [SubjectRef("group", "g", "member")])

    def test_userset_subject_queries(self):
        jx, oracle = make_pair(GROUPS_SCHEMA, [
            "group:eng#member@user:alice",
            "namespace:ns#viewer@group:eng#member",
        ])
        assert_agreement(jx, oracle, "namespace", "view",
                         [SubjectRef("group", "eng", "member"),
                          SubjectRef("group", "other", "member")])

    def test_cyclic_groups(self):
        jx, oracle = make_pair(GROUPS_SCHEMA, [
            "group:a#member@group:b#member",
            "group:b#member@group:a#member",
            "group:a#member@user:alice",
            "namespace:ns#viewer@group:b#member",
        ])
        assert_agreement(jx, oracle, "namespace", "view", users("alice", "bob"))


class TestIncrementalDeltas:
    def test_write_then_delete(self):
        jx, oracle = make_pair(GROUPS_SCHEMA, [
            "namespace:ns1#viewer@user:alice",
        ])
        assert_agreement(jx, oracle, "namespace", "view", users("alice", "bob"))
        rebuilds_before = jx.stats["rebuilds"]
        # incremental adds (all ids already in universe? bob is known — alice
        # and ns1 are; bob came from queries... bob is NOT in the store, so
        # adding a tuple for bob forces a rebuild; alice->ns1 viewer delete
        # then re-add exercises the delta path)
        jx.store.write(delete("namespace:ns1#viewer@user:alice"))
        assert_agreement(jx, oracle, "namespace", "view", users("alice"))
        jx.store.write(touch("namespace:ns1#viewer@user:alice"))
        assert_agreement(jx, oracle, "namespace", "view", users("alice"))
        assert jx.stats["rebuilds"] == rebuilds_before, \
            "delete+readd of known ids must not rebuild"

    def test_full_row_insert_grows_aux_without_rebuild(self, kernel_kind):
        """K_MAIN=2 layout: the 3rd..Nth viewer on one namespace overflows
        the main row; add_rel must grow an OR-tree level from the spare
        aux pool instead of rebuilding (ell kernel only — the segment
        kernel has positional slack instead).  A hub seeds the aux table
        so the spare pool exists (hub-free graphs rebuild instead)."""
        rels = ["namespace:ns#viewer@user:u0"]
        # every id must be in the compiled universe, so pre-seed the users
        # on a throwaway namespace — enough of them that the seed row is a
        # hub (aux table + spare pool present)
        rels += [f"namespace:seed#viewer@user:u{i}" for i in range(1, 40)]
        jx, oracle = make_pair(GROUPS_SCHEMA, rels)
        assert_agreement(jx, oracle, "namespace", "view",
                         users(*[f"u{i}" for i in range(12)]))
        rebuilds_before = jx.stats["rebuilds"]
        for i in range(1, 12):
            jx.store.write(touch(f"namespace:ns#viewer@user:u{i}"))
        assert_agreement(jx, oracle, "namespace", "view",
                         users(*[f"u{i}" for i in range(12)]))
        if kernel_kind == "ell":
            assert jx.stats["rebuilds"] == rebuilds_before, \
                "full-row inserts must grow aux nodes, not rebuild"
            # removal after growth still works through the grown tree
            jx.store.write(delete("namespace:ns#viewer@user:u3"))
            assert_agreement(jx, oracle, "namespace", "view",
                             users(*[f"u{i}" for i in range(12)]))

    def test_new_object_id_assigns_spare_without_rebuild(self):
        """A tuple naming a brand-new object/subject id claims spare rows
        (renamed in the program's id maps) instead of forcing a full
        rebuild — the dual-write create path at 1M scale must not stall
        seconds per new pod."""
        jx, oracle = make_pair(GROUPS_SCHEMA, ["namespace:ns1#viewer@user:alice"])
        assert_agreement(jx, oracle, "namespace", "view", users("alice"))
        rebuilds = jx.stats["rebuilds"]
        jx.store.write(touch("namespace:brand-new#viewer@user:newbie"))
        assert_agreement(jx, oracle, "namespace", "view", users("alice", "newbie"))
        assert jx.stats["rebuilds"] == rebuilds, \
            "new ids must claim spare rows, not rebuild"
        assert jx.stats["spare_assignments"] >= 2  # object + subject
        # placeholder ids never leak from lookups
        ids = asyncio.run(jx.lookup_resources(
            "namespace", "view", SubjectRef("user", "newbie")))
        assert ids == ["brand-new"]

    def test_spare_pool_exhaustion_rebuilds_and_resizes(self, monkeypatch):
        """Draining the spare pool falls back to a rebuild whose new pool
        is sized from the (now larger) universe; correctness holds across
        the boundary.  The sizing divisor is patched to 1 so the resize
        is observable at unit-test scale."""
        from spicedb_kubeapi_proxy_tpu.ops import jax_endpoint as je
        from spicedb_kubeapi_proxy_tpu.utils.features import GATES
        monkeypatch.setattr(je, "_SPARE_DIVISOR", 1)
        # this test probes the SYNCHRONOUS exhaustion->rebuild fallback
        # (the AsyncRebuild killswitch path); the off-loop flavor is
        # covered by tests/test_rebuild_async.py
        monkeypatch.setattr(GATES._gates["AsyncRebuild"], "value", False)
        jx, oracle = make_pair(GROUPS_SCHEMA, ["namespace:ns1#viewer@user:alice"])
        assert_agreement(jx, oracle, "namespace", "view", users("alice"))
        floor_pool = len(jx._spare_pool["namespace"])
        for k in range(70):  # exceeds the 64-row floor pool
            jx.store.write(touch(f"namespace:n{k}#viewer@user:alice"))
        assert_agreement(jx, oracle, "namespace", "view", users("alice"))
        assert jx.stats["rebuilds"] >= 2
        # the exhaustion rebuild sized the new pool from the grown
        # universe (divisor 1: one spare per existing object > the floor)
        assert len(jx._spare_pool["namespace"]) > floor_pool
        got = sorted(asyncio.run(jx.lookup_resources(
            "namespace", "view", SubjectRef("user", "alice"))))
        assert got == sorted(["ns1"] + [f"n{k}" for k in range(70)])

    def test_unique_name_churn_reclaims_spares(self):
        """The kubernetes pod lifecycle: objects with unique generated
        names created and deleted in cycles.  Each delete that removes an
        assigned id's last tuple returns its spare row to the pool, so
        200 create+delete cycles (>> the 64-row floor pool) never force a
        rebuild."""
        jx, oracle = make_pair(GROUPS_SCHEMA, ["namespace:ns1#viewer@user:alice"])
        assert_agreement(jx, oracle, "namespace", "view", users("alice"))
        rebuilds = jx.stats["rebuilds"]
        for k in range(200):
            jx.store.write(touch(f"namespace:job-{k}#viewer@user:alice"))
            # visible while alive
            if k % 50 == 0:
                got = asyncio.run(jx.lookup_resources(
                    "namespace", "view", SubjectRef("user", "alice")))
                assert f"job-{k}" in got
            jx.store.write(delete(f"namespace:job-{k}#viewer@user:alice"))
        assert_agreement(jx, oracle, "namespace", "view", users("alice"))
        assert jx.stats["rebuilds"] == rebuilds, \
            "unique-name churn must recycle spare rows, not rebuild"
        assert jx.stats["spare_reclaims"] >= 190
        got = asyncio.run(jx.lookup_resources(
            "namespace", "view", SubjectRef("user", "alice")))
        assert got == ["ns1"]

    def test_unmodeled_relation_burns_no_spares(self):
        """Edgeless tuples (relations absent from the schema) must not
        consume spare rows — a stream of them used to be a no-op and must
        stay one."""
        jx, oracle = make_pair(GROUPS_SCHEMA, ["namespace:ns1#viewer@user:alice"])
        assert_agreement(jx, oracle, "namespace", "view", users("alice"))
        before = jx.stats["spare_assignments"]
        rebuilds = jx.stats["rebuilds"]
        for k in range(10):
            jx.store.write(touch(f"namespace:brand-{k}#unmodeled@user:nobody"))
        assert_agreement(jx, oracle, "namespace", "view", users("alice"))
        assert jx.stats["spare_assignments"] == before
        assert jx.stats["rebuilds"] == rebuilds

    def test_group_membership_revocation(self):
        jx, oracle = make_pair(GROUPS_SCHEMA, [
            "group:eng#member@user:alice",
            "namespace:ns#viewer@group:eng#member",
            "namespace:ns2#viewer@user:alice",
        ])
        assert_agreement(jx, oracle, "namespace", "view", users("alice"))
        jx.store.write(delete("group:eng#member@user:alice"))
        assert_agreement(jx, oracle, "namespace", "view", users("alice"))

    def test_expiration_respected(self):
        jx, oracle, clk = make_clocked_pair(
            GROUPS_SCHEMA, ["namespace:ns#viewer@user:alice"])
        jx.store.write([RelationshipUpdate(UpdateOp.TOUCH, parse_relationship(
            f"namespace:ns#viewer@user:bob[expiration:{clk[0] + 100}]"))])
        assert_agreement(jx, oracle, "namespace", "view", users("alice", "bob"))
        clk[0] += 200
        assert_agreement(jx, oracle, "namespace", "view", users("alice", "bob"))


class TestRandomizedDifferential:
    @pytest.mark.parametrize("seed", range(6))
    def test_random_graphs(self, seed):
        rng = random.Random(seed)
        n_users = rng.randint(3, 10)
        n_groups = rng.randint(2, 6)
        n_ns = rng.randint(3, 12)
        rels = []
        for g in range(n_groups):
            for u in rng.sample(range(n_users), rng.randint(0, min(3, n_users))):
                rels.append(f"group:g{g}#member@user:u{u}")
            if g > 0 and rng.random() < 0.5:
                parent = rng.randrange(g)
                rels.append(f"group:g{g}#member@group:g{parent}#member")
        for ns in range(n_ns):
            for _ in range(rng.randint(0, 4)):
                if rng.random() < 0.6:
                    rels.append(f"namespace:ns{ns}#viewer@user:u{rng.randrange(n_users)}")
                else:
                    rels.append(f"namespace:ns{ns}#viewer@group:g{rng.randrange(n_groups)}#member")
            if rng.random() < 0.3:
                rels.append(f"namespace:ns{ns}#creator@user:u{rng.randrange(n_users)}")
        rels = sorted(set(rels))
        jx, oracle = make_pair(GROUPS_SCHEMA, rels)
        subjects = users(*[f"u{i}" for i in range(n_users)])
        assert_agreement(jx, oracle, "namespace", "view", subjects)
        # mutate: random deletes + adds, re-verify (delta path)
        existing = jx.store.read(None)
        for rel in rng.sample(existing, min(3, len(existing))):
            jx.store.write([RelationshipUpdate(UpdateOp.DELETE, rel)])
        assert_agreement(jx, oracle, "namespace", "view", subjects)

    @pytest.mark.parametrize("seed", range(4))
    def test_random_delta_churn(self, seed):
        """Sustained add/delete/re-add churn over a FIXED id universe: every
        mutation stays incremental (no new ids), so this hammers the slot
        edits, spare-aux growth, and tree-walk removal paths — agreement
        with the oracle is re-asserted after every burst."""
        rng = random.Random(7000 + seed)
        n_users, n_groups, n_ns = 8, 4, 6
        # seed graph mentions every id once so the compiled universe is
        # closed under later churn
        rels = [f"group:g{g}#member@user:u{u}"
                for g in range(n_groups) for u in range(n_users)]
        rels += [f"namespace:ns{i}#viewer@user:u0" for i in range(n_ns)]
        rels += [f"namespace:ns{i}#viewer@group:g0#member"
                 for i in range(n_ns)]
        jx, oracle = make_pair(GROUPS_SCHEMA, rels)
        subjects = users(*[f"u{i}" for i in range(n_users)])
        assert_agreement(jx, oracle, "namespace", "view", subjects)

        def any_rel():
            kind = rng.random()
            if kind < 0.4:
                return (f"group:g{rng.randrange(n_groups)}#member"
                        f"@user:u{rng.randrange(n_users)}")
            if kind < 0.6:
                a, b = rng.sample(range(n_groups), 2)
                return f"group:g{a}#member@group:g{b}#member"
            if kind < 0.85:
                return (f"namespace:ns{rng.randrange(n_ns)}#viewer"
                        f"@user:u{rng.randrange(n_users)}")
            return (f"namespace:ns{rng.randrange(n_ns)}#viewer"
                    f"@group:g{rng.randrange(n_groups)}#member")

        for _ in range(5):  # bursts
            ops = []
            for _ in range(rng.randint(3, 10)):
                rel = any_rel()
                op = (UpdateOp.DELETE if rng.random() < 0.4
                      else UpdateOp.TOUCH)
                ops.append(RelationshipUpdate(op, parse_relationship(rel)))
            jx.store.write(ops)
            assert_agreement(jx, oracle, "namespace", "view", subjects)

    @pytest.mark.parametrize("seed", range(3))
    def test_random_rbac_deny(self, seed):
        rng = random.Random(1000 + seed)
        rels = []
        n_users, n_proj = 6, 5
        for g in ("devs", "ops", "blocked"):
            for u in rng.sample(range(n_users), rng.randint(1, 4)):
                rels.append(f"group:{g}#member@user:u{u}")
        for p in range(n_proj):
            rels.append(f"project:p{p}#assigned@group:devs#member")
            for u in rng.sample(range(n_users), rng.randint(0, 4)):
                rels.append(f"project:p{p}#approved@user:u{u}")
            if rng.random() < 0.6:
                rels.append(f"project:p{p}#banned@group:blocked#member")
        jx, oracle = make_pair(RBAC_DENY_SCHEMA, sorted(set(rels)))
        assert_agreement(jx, oracle, "project", "edit",
                         users(*[f"u{i}" for i in range(n_users)]))


class TestJaxEndpointBehavior:
    def test_bootstrap_dispatch(self):
        from spicedb_kubeapi_proxy_tpu.spicedb.endpoints import create_endpoint
        ep = create_endpoint(
            "jax://",
            bootstrap=Bootstrap(
                schema_text=GROUPS_SCHEMA,
                relationships_text="namespace:ns#viewer@user:alice\n"))
        # jax:// wraps the device endpoint in the cross-request dispatcher
        # by default (spicedb/dispatch.py)
        from spicedb_kubeapi_proxy_tpu.spicedb.dispatch import BatchingEndpoint
        assert isinstance(ep, BatchingEndpoint)
        assert isinstance(ep.inner, JaxEndpoint)

        async def run():
            r = await ep.check_permission(CheckRequest(
                ObjectRef("namespace", "ns"), "view", SubjectRef("user", "alice")))
            assert r.allowed
            assert await ep.lookup_resources(
                "namespace", "view", SubjectRef("user", "alice")) == ["ns"]
        asyncio.run(run())

    def test_unknown_resource_type_raises(self):
        jx, _ = make_pair(GROUPS_SCHEMA, ["namespace:ns#viewer@user:alice"])

        async def run():
            with pytest.raises(Exception):
                await jx.lookup_resources("ghost", "view", SubjectRef("user", "a"))
        asyncio.run(run())

    def test_stats_track_kernel_usage(self):
        # this test asserts the fixpoint kernels' own accounting; keep the
        # Leopard index out so the nested chain actually hits a kernel
        from spicedb_kubeapi_proxy_tpu.utils.features import GATES

        prev = GATES.enabled("LeopardIndex")
        GATES.set("LeopardIndex", False)
        try:
            jx, oracle = make_pair(
                GROUPS_SCHEMA, ["namespace:ns#viewer@user:alice"])
        finally:
            GATES.set("LeopardIndex", prev)
        assert_agreement(jx, oracle, "namespace", "view", users("alice"))
        assert jx.stats["kernel_calls"] > 0
        assert jx.stats["rebuilds"] >= 1


class TestReviewRegressions:
    def test_wildcard_revocation_rebuilds(self):
        jx, oracle = make_pair(WILDCARD_SCHEMA, [
            "doc:d1#viewer@user:*",
            "doc:d1#editor@user:eve",
        ])
        assert_agreement(jx, oracle, "doc", "view", users("zed", "eve"))
        jx.store.write(delete("doc:d1#viewer@user:*"))
        # after revoking the wildcard, arbitrary users must lose access
        assert_agreement(jx, oracle, "doc", "view", users("zed", "eve"))

    def test_touch_adds_expiry_to_existing_tuple(self):
        jx, oracle, clk = make_clocked_pair(
            GROUPS_SCHEMA, ["namespace:ns#viewer@user:alice"])
        assert_agreement(jx, oracle, "namespace", "view", users("alice"))
        # re-touch the same tuple, now with an expiration
        jx.store.write([RelationshipUpdate(UpdateOp.TOUCH, parse_relationship(
            f"namespace:ns#viewer@user:alice[expiration:{clk[0] + 100}]"))])
        clk[0] += 200
        assert_agreement(jx, oracle, "namespace", "view", users("alice"))

    def test_delete_then_readd_clears_stale_expiry(self):
        """Deterministic via the store's injectable clock (the endpoint's
        expiry heap reads store.now()): no wall-clock races, no sleeps."""
        jx, oracle, clk = make_clocked_pair(
            GROUPS_SCHEMA, ["namespace:ns0#viewer@user:z"])
        jx.store.write([RelationshipUpdate(UpdateOp.TOUCH, parse_relationship(
            f"namespace:ns#viewer@user:alice[expiration:{clk[0] + 100}]"))])
        assert_agreement(jx, oracle, "namespace", "view", users("alice"))
        jx.store.write(delete("namespace:ns#viewer@user:alice"))
        jx.store.write(touch("namespace:ns#viewer@user:alice"))  # no expiry
        clk[0] += 200  # stale heap entry fires; must be ignored
        assert_agreement(jx, oracle, "namespace", "view", users("alice"))

    def test_deep_membership_chain(self):
        # 15-deep nested groups: beyond the old rewrite-depth-derived cap
        rels = [f"group:g{i+1}#member@group:g{i}#member" for i in range(15)]
        rels.append("group:g0#member@user:deep")
        rels.append("namespace:ns#viewer@group:g15#member")
        jx, oracle = make_pair(GROUPS_SCHEMA, rels)
        assert_agreement(jx, oracle, "namespace", "view", users("deep", "shallow"))

    def test_concurrent_writes_and_checks_no_deadlock(self):
        import threading
        jx, oracle = make_pair(GROUPS_SCHEMA, ["namespace:ns#viewer@user:alice"])
        assert_agreement(jx, oracle, "namespace", "view", users("alice"))
        errors = []

        def writer():
            try:
                for i in range(30):
                    jx.store.write(touch("namespace:ns#viewer@user:alice"))
                    jx.store.write(delete("namespace:ns#viewer@user:alice"))
                    jx.store.write(touch("namespace:ns#viewer@user:alice"))
            except Exception as e:
                errors.append(e)

        def checker():
            import asyncio
            try:
                for _ in range(15):
                    asyncio.run(jx.lookup_resources(
                        "namespace", "view", SubjectRef("user", "alice")))
            except Exception as e:
                errors.append(e)

        threads = [threading.Thread(target=writer),
                   threading.Thread(target=checker)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
            assert not t.is_alive(), "deadlock: thread did not finish"
        assert not errors, errors
        # converge: final state must agree
        assert_agreement(jx, oracle, "namespace", "view", users("alice"))


class TestHubTreeSplit:
    """Destinations whose in-degree exceeds the ELL main-row fanin are split
    into OR-tree aux nodes (ops/ell.py); these scenarios force that path and
    keep exercising it through incremental writes/deletes into the hub."""

    def test_large_group_membership(self):
        rels = [f"group:eng#member@user:u{i}" for i in range(300)]
        rels += ["namespace:ns#viewer@group:eng#member"]
        jx, oracle = make_pair(GROUPS_SCHEMA, rels)
        assert_agreement(jx, oracle, "namespace", "view",
                         users("u0", "u7", "u123", "u299", "outsider"))

    def test_delta_insert_and_remove_in_hub(self):
        rels = [f"group:eng#member@user:u{i}" for i in range(300)]
        rels += ["namespace:ns#viewer@group:eng#member"]
        jx, oracle = make_pair(GROUPS_SCHEMA, rels)
        assert_agreement(jx, oracle, "namespace", "view", users("u5"))
        # insert into the full hub (aux tree absorbs the new child or the
        # endpoint rebuilds; either way results must match the oracle)
        jx.store.write(touch("group:eng#member@user:newcomer"))
        assert_agreement(jx, oracle, "namespace", "view",
                         users("newcomer", "u5"))
        # remove a member buried in the tree
        jx.store.write(delete("group:eng#member@user:u123"))
        assert_agreement(jx, oracle, "namespace", "view",
                         users("u123", "u5", "newcomer"))

    def test_nested_hubs(self):
        rels = [f"group:g0#member@user:u{i}" for i in range(60)]
        rels += [f"group:g1#member@group:g0#member"]
        rels += [f"group:g1#member@user:v{i}" for i in range(60)]
        rels += ["namespace:ns#viewer@group:g1#member"]
        jx, oracle = make_pair(GROUPS_SCHEMA, rels)
        assert_agreement(jx, oracle, "namespace", "view",
                         users("u3", "v59", "nobody"))


class TestPhantomSubjects:
    """Subjects outside the compiled universe map onto their type's phantom
    column (zero tuples ⇒ only wildcard terms can grant), so first-contact
    users never fall back to the recursive host oracle — the round-1 cliff."""

    class _NoOracle:
        def check(self, *a, **k):
            raise AssertionError("oracle fallback used for in-schema subject")

        def lookup_resources(self, *a, **k):
            raise AssertionError("oracle fallback used for in-schema subject")

    def test_unknown_subjects_stay_on_kernel(self):
        jx, oracle = make_pair(WILDCARD_SCHEMA, [
            "doc:readme#viewer@user:*",
            "doc:secret#editor@user:alice",
        ])
        # answers must match the oracle...
        assert_agreement(jx, oracle, "doc", "view",
                         users("stranger1", "stranger2"))
        # ...and must come from the kernel, not the recursive fallback
        jx._oracle = self._NoOracle()
        assert_agreement(jx, oracle, "doc", "view",
                         users("stranger3", "stranger4"))

    def test_unknown_userset_subjects(self):
        jx, oracle = make_pair(GROUPS_SCHEMA, [
            "namespace:ns#viewer@group:eng#member",
            "group:eng#member@user:alice",
        ])
        jx._oracle = self._NoOracle()
        async def run():
            # unknown group userset: no members, wildcards don't apply
            got = await jx.lookup_resources(
                "namespace", "view", SubjectRef("group", "ghosts", "member"))
            assert got == []
            res = await jx.check_permission(CheckRequest(
                ObjectRef("namespace", "ns"), "view",
                SubjectRef("group", "ghosts", "member")))
            assert not res.allowed
        asyncio.run(run())

    def test_phantom_never_leaks_from_lookup(self):
        # subject relation on the SAME type as the listed resource: the
        # phantom's own relation slot goes live, but the phantom id must
        # never appear in LookupResources output
        jx, oracle = make_pair(GROUPS_SCHEMA, [
            "namespace:ns#viewer@user:alice",
        ])
        async def run():
            got = await jx.lookup_resources(
                "namespace", "view", SubjectRef("namespace", "nope", "viewer"))
            assert got == []
            batch = await jx.lookup_resources_batch(
                "namespace", "view",
                [SubjectRef("namespace", "nope", "viewer"),
                 SubjectRef("user", "alice")])
            assert batch[0] == []
            assert batch[1] == ["ns"]
        asyncio.run(run())

    def test_batch_shares_phantom_column(self):
        jx, oracle = make_pair(WILDCARD_SCHEMA, ["doc:d#viewer@user:*"])
        jx._oracle = self._NoOracle()
        async def run():
            subs = [SubjectRef("user", f"stranger{i}") for i in range(40)]
            out = await jx.lookup_resources_batch("doc", "view", subs)
            assert all(x == ["d"] for x in out)
        asyncio.run(run())


class TestLockFreeKernelExecution:
    @pytest.fixture(autouse=True, params=["ell"])
    def kernel_kind(self, request, monkeypatch):
        """Timing test is ell-only: override the module fixture's params
        instead of skipping, so the default suite runs with zero skips."""
        monkeypatch.setenv("SPICEDB_TPU_KERNEL", request.param)
        return request.param

    def test_check_not_serialized_behind_slow_lookup(self, kernel_kind,
                                                     monkeypatch):
        """Device execution happens OUTSIDE the endpoint lock: a check
        issued while a (artificially slow) lookup kernel is in flight
        completes immediately instead of queueing behind it."""
        import threading
        import time as _time
        jx, _ = make_pair(GROUPS_SCHEMA, [
            "namespace:ns1#viewer@user:alice",
            "namespace:ns2#viewer@user:bob",
        ])
        # warm both paths (build graph + compile)
        jx._lookup_batch_sync("namespace", "view", users("alice"))
        jx._check_batch_sync([CheckRequest(
            resource=ObjectRef("namespace", "ns1"), permission="view",
            subject=SubjectRef("user", "alice"))])
        graph = jx._graph
        real = graph.run_lookup_packed

        def slow(*a, **k):
            _time.sleep(0.6)
            return real(*a, **k)

        monkeypatch.setattr(graph, "run_lookup_packed", slow)
        t = threading.Thread(
            target=jx._lookup_batch_sync,
            args=("namespace", "view", users("alice", "bob")))
        t.start()
        _time.sleep(0.1)  # lookup now inside the slow kernel call
        t0 = _time.perf_counter()
        out = jx._check_batch_sync([CheckRequest(
            resource=ObjectRef("namespace", "ns1"), permission="view",
            subject=SubjectRef("user", "alice"))])
        elapsed = _time.perf_counter() - t0
        t.join()
        assert out[0].permissionship.name == "HAS_PERMISSION"
        assert elapsed < 0.4, \
            f"check blocked {elapsed:.2f}s behind the lookup kernel"


class TestStaleIdViewSelfHeal:
    """Regression net for the id-view/bitmap inconsistency (VERDICT r4
    item 1): results must be complete and correct even when the captured
    id view disagrees with the kernel bitmap.  The inconsistency is
    INJECTED deterministically here (corrupted cache entry) so the
    suppress -> purge -> retry path and the double-suppression ->
    host-oracle tail are both proven, independent of whether the
    underlying race fires."""

    def _corrupt(self, jx, resource_type, victim_id):
        """Make the published cache entry show a spare placeholder at a
        LIVE object's index — exactly the stale-view shape the race
        produces."""
        with jx._lock:
            graph = jx._current_graph()
            from spicedb_kubeapi_proxy_tpu.ops import jax_endpoint as je
            arr, mask = je._object_ids_np(graph, resource_type)
            local = graph.prog.object_index[resource_type][victim_id]
            arr[local] = "\x00__spare__injected"
            mask[local] = True
        return local

    def test_injected_stale_view_self_heals(self, kernel_kind):
        jx, oracle = make_pair(GROUPS_SCHEMA, [
            "namespace:ns1#viewer@user:alice",
            "namespace:ns2#viewer@user:alice",
            "namespace:ns3#viewer@user:bob",
        ])
        want = sorted(oracle.lookup_resources(
            "namespace", "view", SubjectRef("user", "alice")))

        async def run():
            # prime + publish the cache entry
            await jx.lookup_resources("namespace", "view",
                                      SubjectRef("user", "alice"))
            self._corrupt(jx, "namespace", "ns1")
            got = sorted(await jx.lookup_resources(
                "namespace", "view", SubjectRef("user", "alice")))
            assert got == want, f"self-heal returned truncated {got}"
            assert jx.stats.get("placeholder_suppressed", 0) >= 1
            assert jx.stats.get("suppression_oracle_fallbacks", 0) == 0
            # batch path: corrupt again (the retry purged the entry)
            await jx.lookup_resources_batch(
                "namespace", "view", users("alice"))
            self._corrupt(jx, "namespace", "ns2")
            out = await jx.lookup_resources_batch(
                "namespace", "view", users("alice", "bob"))
            assert sorted(out[0]) == want
            assert sorted(out[1]) == ["ns3"]
        asyncio.run(run())

    def test_persistent_stale_view_falls_back_to_oracle(self, kernel_kind,
                                                        monkeypatch):
        """If the re-captured view is ALSO inconsistent, the endpoint
        must return the host oracle's complete answer — never a silently
        truncated list."""
        from spicedb_kubeapi_proxy_tpu.ops import jax_endpoint as je
        jx, oracle = make_pair(GROUPS_SCHEMA, [
            "namespace:ns1#viewer@user:alice",
            "namespace:ns2#viewer@user:alice",
        ])
        want = sorted(oracle.lookup_resources(
            "namespace", "view", SubjectRef("user", "alice")))
        real = je._object_ids_np

        def always_stale(graph, resource_type):
            arr, mask = real(graph, resource_type)
            arr = arr.copy()
            mask = mask.copy()
            local = graph.prog.object_index[resource_type].get("ns1")
            if local is not None:
                arr[local] = "\x00__spare__persistent"
                mask[local] = True
            return arr, mask

        monkeypatch.setattr(je, "_object_ids_np", always_stale)

        async def run():
            got = sorted(await jx.lookup_resources(
                "namespace", "view", SubjectRef("user", "alice")))
            assert got == want, f"oracle fallback returned {got}"
            assert jx.stats.get("suppression_oracle_fallbacks", 0) == 1
            out = await jx.lookup_resources_batch(
                "namespace", "view", users("alice"))
            assert sorted(out[0]) == want
            assert jx.stats.get("suppression_oracle_fallbacks", 0) == 2
        asyncio.run(run())


class TestSuppressionRetryCounting:
    """The self-heal retry must not double-count placeholder_suppressed
    (or re-emit the forensic warning) for one underlying inconsistency:
    retry-attributed suppressions land in a separate counter."""

    def test_persistent_staleness_counts_first_detection_once(
            self, kernel_kind, monkeypatch):
        from spicedb_kubeapi_proxy_tpu.ops import jax_endpoint as je
        jx, oracle = make_pair(GROUPS_SCHEMA, [
            "namespace:ns1#viewer@user:alice",
            "namespace:ns2#viewer@user:alice",
        ])
        want = sorted(oracle.lookup_resources(
            "namespace", "view", SubjectRef("user", "alice")))
        real = je._object_ids_np

        def always_stale(graph, resource_type):
            arr, mask = real(graph, resource_type)
            arr = arr.copy()
            mask = mask.copy()
            local = graph.prog.object_index[resource_type].get("ns1")
            if local is not None:
                arr[local] = "\x00__spare__persistent"
                mask[local] = True
            return arr, mask

        monkeypatch.setattr(je, "_object_ids_np", always_stale)

        async def run():
            # single-subject path: suppress -> purge -> retry (also
            # stale) -> oracle.  ONE event: first-detection counter 1,
            # retry counter 1 — not first-detection 2.
            got = sorted(await jx.lookup_resources(
                "namespace", "view", SubjectRef("user", "alice")))
            assert got == want
            assert jx.stats.get("placeholder_suppressed", 0) == 1
            assert jx.stats.get("placeholder_suppressed_retry", 0) == 1
            assert jx.stats.get("suppression_oracle_fallbacks", 0) == 1
            # fused-batch path: same discipline through the batch tail
            out = await jx.lookup_resources_batch(
                "namespace", "view", users("alice"))
            assert sorted(out[0]) == want
            assert jx.stats.get("placeholder_suppressed", 0) == 2
            assert jx.stats.get("placeholder_suppressed_retry", 0) == 2
            assert jx.stats.get("suppression_oracle_fallbacks", 0) == 2

        asyncio.run(run())

    def test_clean_retry_counts_nothing_extra(self, kernel_kind):
        """A transient inconsistency (retry succeeds) counts exactly one
        suppression and zero retry suppressions."""
        jx, oracle = make_pair(GROUPS_SCHEMA, [
            "namespace:ns1#viewer@user:alice",
            "namespace:ns2#viewer@user:alice",
        ])
        want = sorted(oracle.lookup_resources(
            "namespace", "view", SubjectRef("user", "alice")))

        async def run():
            await jx.lookup_resources("namespace", "view",
                                      SubjectRef("user", "alice"))
            # corrupt the PUBLISHED cache entry once; the purge+retry
            # rebuilds it clean
            with jx._lock:
                graph = jx._current_graph()
                from spicedb_kubeapi_proxy_tpu.ops import jax_endpoint as je
                arr, mask = je._object_ids_np(graph, "namespace")
                local = graph.prog.object_index["namespace"]["ns1"]
                arr[local] = "\x00__spare__transient"
                mask[local] = True
            got = sorted(await jx.lookup_resources(
                "namespace", "view", SubjectRef("user", "alice")))
            assert got == want
            assert jx.stats.get("placeholder_suppressed", 0) == 1
            assert jx.stats.get("placeholder_suppressed_retry", 0) == 0
            assert jx.stats.get("suppression_oracle_fallbacks", 0) == 0

        asyncio.run(run())


class TestStageAuxFlip:
    def test_delta_growth_flips_aux_free_stage_annotation(self, kernel_kind):
        """A hub grown by deltas into a stage annotated aux-free at
        build time must flip the stage's wants_aux flag (so the staged
        kernel refreshes OR-trees before that stage's gather) and bump
        the visible stage_aux_flips stat — the degradation was silent
        before (ADVICE round 5)."""
        if kernel_kind != "ell":
            pytest.skip("stage annotations are an ell-kernel feature")
        # hub on `group` seeds the aux table + spare pool; namespaces
        # start with one viewer each, so the namespace stage has no aux
        # references at build time (wants_aux=False)
        rels = [f"group:hub#member@user:h{i}" for i in range(40)]
        rels += ["namespace:ns#viewer@user:u0"]
        rels += [f"namespace:seed{i}#viewer@user:u{i}" for i in range(1, 12)]
        jx, oracle = make_pair(GROUPS_SCHEMA, rels)
        subjects = users(*[f"u{i}" for i in range(12)])
        assert_agreement(jx, oracle, "namespace", "view", subjects)

        graph = jx._graph
        stages = graph.kernel.stages
        assert stages, "staged step expected on the ell kernel"
        ns_rows = {graph.prog.state_index("namespace", "viewer", "ns")}
        assert None not in ns_rows
        flags_before = {
            ranges: wants for ranges, _, wants in stages
            for (lo, hi) in ranges if any(lo <= r < hi for r in ns_rows)}
        assert set(flags_before.values()) == {False}, \
            "precondition: the namespace stage must start aux-free"

        rebuilds = jx.stats["rebuilds"]
        for i in range(1, 12):
            jx.store.write(touch(f"namespace:ns#viewer@user:u{i}"))
        assert_agreement(jx, oracle, "namespace", "view", subjects)
        assert jx.stats["rebuilds"] == rebuilds, \
            "growth must ride the spare aux pool, not rebuild"
        assert jx.stats.get("stage_aux_flips", 0) >= 1
        row = graph.prog.state_index("namespace", "viewer", "ns")
        flipped = [wants for ranges, _, wants in graph.kernel.stages
                   if any(lo <= row < hi for lo, hi in ranges)]
        assert flipped and all(flipped), "stage flag must now want aux"


class TestRebuildIdViewEviction:
    """Graph rebuilds must evict the outgoing graph's cached numpy id
    views (`_ids_np_cache`): a post-rebuild lookup must never see
    pre-rebuild ids through a stale (arr, mask) pair."""

    def test_post_rebuild_lookup_never_sees_pre_rebuild_ids(self):
        jx, oracle = make_pair(GROUPS_SCHEMA, [
            "namespace:old1#viewer@user:alice",
            "namespace:old2#viewer@user:alice",
        ])
        alice = SubjectRef("user", "alice")

        async def run():
            out = await jx.lookup_resources("namespace", "view", alice)
            assert sorted(out) == ["old1", "old2"]
            old_graph = jx._graph
            # the lookup populated the old graph's cached id view
            assert getattr(old_graph, "_ids_np_cache", None)
            # a reset-class change (bulk_load) with a DISJOINT id universe
            # forces a full rebuild
            jx.store.delete_all()
            jx.store.bulk_load([parse_relationship(
                "namespace:new1#viewer@user:alice")])
            out = await jx.lookup_resources("namespace", "view", alice)
            assert sorted(out) == ["new1"], (
                "post-rebuild lookup leaked pre-rebuild ids")
            # the outgoing graph's id view was evicted, not carried
            assert not old_graph._ids_np_cache
            assert jx._graph is not old_graph

        asyncio.run(run())
        assert_agreement(jx, oracle, "namespace", "view", [alice])

    def test_forced_rebuild_evicts_and_refreshes_id_view(self):
        jx, oracle = make_pair(GROUPS_SCHEMA, [
            "namespace:ns1#viewer@user:alice",
        ])
        alice = SubjectRef("user", "alice")

        async def run():
            assert sorted(await jx.lookup_resources(
                "namespace", "view", alice)) == ["ns1"]
            old_graph = jx._graph
            assert old_graph._ids_np_cache
            jx.force_rebuild()
            assert not old_graph._ids_np_cache
            assert not old_graph._ids_np_published
            assert sorted(await jx.lookup_resources(
                "namespace", "view", alice)) == ["ns1"]

        asyncio.run(run())
