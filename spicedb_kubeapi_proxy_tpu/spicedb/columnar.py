"""Columnar relationship snapshots: interned string pool + int32 columns.

The bulk-data representation shared by the native loader (native/fastparse),
the tuple store's base layer, and the vectorized graph compiler.  A
1M-tuple bootstrap never materializes per-tuple Python objects on the hot
path: text -> (pool, columns) -> store base / device graph, with
Relationship objects created lazily only for small result sets.

Mirrors types.parse_relationship semantics exactly (grammar
rules/relstring.py:20-23, first-occurrence splits; "..." subject relation
normalizes to ""; blank and '#' lines skipped like
endpoints.Bootstrap.relationships()).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, Optional

import numpy as np

from .types import ObjectRef, Relationship, SubjectRef

_COLS = ("rtype", "rid", "rel", "stype", "sid", "srel")


@dataclass
class ColumnarSnapshot:
    pool: list                      # interned strings; ordinals index this
    rtype: np.ndarray               # int32 [n]
    rid: np.ndarray
    rel: np.ndarray
    stype: np.ndarray
    sid: np.ndarray
    srel: np.ndarray
    expiry: np.ndarray              # float64 [n]; NaN = no expiration
    _pool_index: Optional[dict] = field(default=None, repr=False)

    def __len__(self) -> int:
        return len(self.rtype)

    @property
    def pool_index(self) -> dict:
        if self._pool_index is None:
            self._pool_index = {s: i for i, s in enumerate(self.pool)}
        return self._pool_index

    def ordinal(self, s: str) -> int:
        """Pool ordinal of `s`, or -1 (matches nothing)."""
        return self.pool_index.get(s, -1)

    def relationship(self, i: int) -> Relationship:
        pool = self.pool
        exp = float(self.expiry[i])
        return Relationship(
            resource=ObjectRef(pool[self.rtype[i]], pool[self.rid[i]]),
            relation=pool[self.rel[i]],
            subject=SubjectRef(pool[self.stype[i]], pool[self.sid[i]],
                               pool[self.srel[i]]),
            expires_at=None if np.isnan(exp) else exp,
        )

    def key_of(self, i: int) -> tuple:
        pool = self.pool
        return (pool[self.rtype[i]], pool[self.rid[i]], pool[self.rel[i]],
                pool[self.stype[i]], pool[self.sid[i]], pool[self.srel[i]])

    # -- constructors -------------------------------------------------------

    @classmethod
    def from_text(cls, text: str) -> "ColumnarSnapshot":
        """Parse relationship lines (native extension when available)."""
        from .. import native

        mod = native.load()
        if mod is not None:
            pool, *cols = mod.parse_rels(text)
            arrays = [np.frombuffer(bytes(c), np.int32) for c in cols[:6]]
            expiry = np.frombuffer(bytes(cols[6]), np.float64)
            return cls(pool, *arrays, expiry=expiry)
        return cls._from_text_py(text)

    @classmethod
    def _from_text_py(cls, text: str) -> "ColumnarSnapshot":
        """Pure-Python mirror of native/fastparse.cpp.

        The bulk-text grammar is deliberately ASCII-strict so both
        implementations agree bit-for-bit: lines split on '\\n' only,
        surrounding whitespace is ASCII whitespace, and expiration floats
        reject Python-only forms (underscores) and C-only forms (hex).
        """
        pool: list = []
        index: dict = {}

        def intern(s: str) -> int:
            i = index.get(s)
            if i is None:
                i = len(pool)
                index[s] = i
                pool.append(s)
            return i

        ascii_ws = " \t\r\v\f\n"
        cols: list[list[int]] = [[] for _ in range(6)]
        expiry: list[float] = []
        for lineno, raw in enumerate(text.split("\n"), 1):
            line = raw.strip(ascii_ws)
            if not line or line.startswith("#"):
                continue
            exp = float("nan")
            if line.endswith("]"):
                lb = line.rfind("[expiration:")
                if lb != -1:
                    num = line[lb + 12: -1].strip(ascii_ws)
                    try:
                        if "_" in num:
                            raise ValueError(num)
                        exp = float(num)
                    except ValueError:
                        raise ValueError(f"line {lineno}: bad expiration: {line!r}")
                    line = line[:lb]
            try:
                c1 = line.index(":")
                h1 = line.index("#", c1 + 1)
                at = line.index("@", h1 + 1)
                c2 = line.index(":", at + 1)
            except ValueError:
                raise ValueError(f"line {lineno}: malformed: {line!r}")
            rest = line[c2 + 1:]
            h2 = rest.find("#")
            sid_s, srel_s = (rest, "") if h2 == -1 else (rest[:h2], rest[h2 + 1:])
            if srel_s == "...":
                srel_s = ""
            fields = (line[:c1], line[c1 + 1: h1], line[h1 + 1: at],
                      line[at + 1: c2], sid_s)
            if any(not f for f in fields) or "{{" in line:
                raise ValueError(f"line {lineno}: malformed: {line!r}")
            for col, val in zip(cols, (*fields, srel_s)):
                col.append(intern(val))
            expiry.append(exp)
        arrays = [np.asarray(c, np.int32) for c in cols]
        return cls(pool, *arrays, expiry=np.asarray(expiry, np.float64))

    @classmethod
    def from_relationships(cls, rels: Iterable[Relationship]) -> "ColumnarSnapshot":
        pool: list = []
        index: dict = {}

        def intern(s: str) -> int:
            i = index.get(s)
            if i is None:
                i = len(pool)
                index[s] = i
                pool.append(s)
            return i

        cols: list[list[int]] = [[] for _ in range(6)]
        expiry: list[float] = []
        for r in rels:
            vals = (r.resource.type, r.resource.id, r.relation,
                    r.subject.type, r.subject.id, r.subject.relation)
            for col, val in zip(cols, vals):
                col.append(intern(val))
            expiry.append(float("nan") if r.expires_at is None
                          else float(r.expires_at))
        arrays = [np.asarray(c, np.int32).reshape(-1) for c in cols]
        return cls(pool, *arrays, expiry=np.asarray(expiry, np.float64))


class BaseLayer:
    """A columnar snapshot acting as the tuple store's immutable base, with
    a dead-row mask for deletions/shadowing by overlay writes.

    All lookups are ordinal-based; group indexes are built lazily on first
    query.  Thread safety is provided by the owning store's lock.
    """

    def __init__(self, snap: ColumnarSnapshot, revision: int):
        self.snap = snap
        self.revision = revision
        self.dead = np.zeros(len(snap), bool)
        self._groups: Optional[dict] = None   # (rtype_ord, rel_ord) -> rows
        # duplicate identities in the source text: keep only the LAST copy
        # (matching bulk_load's dict-upsert semantics); earlier copies are
        # dead from the start so find_row-based shadowing stays sound
        if len(snap):
            order = np.lexsort((np.arange(len(snap)), snap.srel, snap.sid,
                                snap.stype, snap.rel, snap.rid, snap.rtype))
            cols = (snap.rtype, snap.rid, snap.rel,
                    snap.stype, snap.sid, snap.srel)
            same = np.ones(len(snap) - 1, bool)
            for c in cols:
                v = c[order]
                same &= v[1:] == v[:-1]
            # `order` puts equal identities adjacent, ascending by row index;
            # a row followed by an equal identity is an earlier duplicate
            self.dead[order[:-1][same]] = True

    def __len__(self) -> int:
        return len(self.snap)

    # -- indexes ------------------------------------------------------------

    def _ensure_groups(self) -> dict:
        if self._groups is None:
            s = self.snap
            order = np.lexsort((s.rid, s.rel, s.rtype))
            rt, rl = s.rtype[order], s.rel[order]
            change = np.nonzero((np.diff(rt) != 0) | (np.diff(rl) != 0))[0] + 1
            bounds = np.concatenate([[0], change, [len(order)]])
            groups = {}
            for i in range(len(bounds) - 1):
                lo, hi = bounds[i], bounds[i + 1]
                if lo == hi:
                    continue
                rows = order[lo:hi]  # sorted by rid ordinal within the group
                groups[(int(rt[lo]), int(rl[lo]))] = rows
            self._groups = groups
        return self._groups

    def rows_for(self, rtype: str, relation: str) -> np.ndarray:
        s = self.snap
        t, r = s.ordinal(rtype), s.ordinal(relation)
        if t < 0 or r < 0:
            return np.zeros(0, np.int64)
        return self._ensure_groups().get((t, r), np.zeros(0, np.int64))

    def rows_for_resource(self, rtype: str, relation: str,
                          rid: str) -> np.ndarray:
        rows = self.rows_for(rtype, relation)
        if not len(rows):
            return rows
        i = self.snap.ordinal(rid)
        if i < 0:
            return np.zeros(0, np.int64)
        vals = self.snap.rid[rows]
        lo = np.searchsorted(vals, i, "left")
        hi = np.searchsorted(vals, i, "right")
        return rows[lo:hi]

    def find_row(self, key: tuple) -> int:
        """Row index of the live-identity tuple with this key, or -1
        (dead rows — deleted, shadowed, or pre-deduplicated — are
        invisible)."""
        (rtype, rid, relation, stype, sid, srel) = key
        s = self.snap
        st, si, sr = s.ordinal(stype), s.ordinal(sid), s.ordinal(srel)
        if st < 0 or si < 0 or sr < 0:
            return -1
        for row in self.rows_for_resource(rtype, relation, rid):
            if (not self.dead[row] and s.stype[row] == st
                    and s.sid[row] == si and s.srel[row] == sr):
                return int(row)
        return -1

    # -- liveness -----------------------------------------------------------

    def live_mask(self, now: float) -> np.ndarray:
        exp = self.snap.expiry
        return ~self.dead & (np.isnan(exp) | (now < exp))

    def row_live(self, row: int, now: float) -> bool:
        if self.dead[row]:
            return False
        e = self.snap.expiry[row]
        return bool(np.isnan(e) or now < e)

    def live_rows(self, now: float) -> np.ndarray:
        return np.nonzero(self.live_mask(now))[0]

    # -- filtered scan ------------------------------------------------------

    def matching_rows(self, flt, now: float) -> np.ndarray:
        """Vectorized RelationshipFilter scan -> live matching row indices."""
        s = self.snap
        mask = self.live_mask(now)

        def narrow(col: np.ndarray, value: str) -> bool:
            o = s.ordinal(value)
            if o < 0:
                return False
            np.logical_and(mask, col == o, out=mask)
            return True

        if flt is not None:
            if flt.resource_type and not narrow(s.rtype, flt.resource_type):
                return np.zeros(0, np.int64)
            if flt.resource_id and not narrow(s.rid, flt.resource_id):
                return np.zeros(0, np.int64)
            if flt.relation and not narrow(s.rel, flt.relation):
                return np.zeros(0, np.int64)
            sub = flt.subject
            if sub is not None:
                if sub.type and not narrow(s.stype, sub.type):
                    return np.zeros(0, np.int64)
                if sub.id and not narrow(s.sid, sub.id):
                    return np.zeros(0, np.int64)
                if sub.relation is not None and not narrow(s.srel, sub.relation):
                    return np.zeros(0, np.int64)
        return np.nonzero(mask)[0]
