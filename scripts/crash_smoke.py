#!/usr/bin/env python
"""Crash-recovery smoke for the durable store (scripts/check.sh).

kill -9 the proxy's store process mid write-churn, restart on the same
data dir, and assert:

  1. revision continuity — every write the child ACKED (fsync=always:
     the WAL record was durable before the ack) is recovered, and a
     post-recovery write lands at recovered_revision + 1;
  2. store/oracle parity — the recovered read-set is byte-identical to
     an uninterrupted host replay of the same deterministic update
     stream prefix.

Fast and deterministic: the stream is a pure function of the batch
index, so parent and child agree without any channel beyond the ACKed
revision numbers.  No jax import — runs in a couple of seconds.
"""

import argparse
import os
import shutil
import signal
import subprocess
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

ACKS_BEFORE_KILL = 30
CHECKPOINT_AT_BATCH = 10

BOOTSTRAP = "\n".join(f"doc:d{i}#viewer@user:u{i % 7}" for i in range(2000))


def stream_batch(i):
    """Deterministic churn: batch i is a pure function of i."""
    from spicedb_kubeapi_proxy_tpu.spicedb.types import (
        RelationshipUpdate,
        UpdateOp,
        parse_relationship,
    )
    ups = []
    for j in range(10):
        n = (i * 37 + j * 11) % 2500
        rel = parse_relationship(f"doc:d{n}#viewer@user:u{(i + j) % 7}")
        op = UpdateOp.DELETE if (i + j) % 4 == 0 else UpdateOp.TOUCH
        ups.append(RelationshipUpdate(op, rel))
    return ups


def child(data_dir):
    """Write-churn process: ACK each durable revision until killed."""
    from spicedb_kubeapi_proxy_tpu.spicedb.persist import PersistenceManager
    mgr = PersistenceManager(data_dir, fsync="always",
                             segment_bytes=64 * 1024)
    store = mgr.recover()
    mgr.attach(store)
    store.bulk_load_text(BOOTSTRAP)
    print(f"ACK {store.revision}", flush=True)
    i = 0
    while True:
        i += 1
        rev = store.write(stream_batch(i))
        if i == CHECKPOINT_AT_BATCH:
            mgr.checkpoint()
        print(f"ACK {rev}", flush=True)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--child", metavar="DATA_DIR", default="")
    args = ap.parse_args()
    if args.child:
        child(args.child)
        return 0

    from spicedb_kubeapi_proxy_tpu.spicedb.persist import PersistenceManager
    from spicedb_kubeapi_proxy_tpu.spicedb.store import TupleStore

    data_dir = tempfile.mkdtemp(prefix="crash-smoke-")
    proc = subprocess.Popen(
        [sys.executable, os.path.abspath(__file__), "--child", data_dir],
        stdout=subprocess.PIPE, text=True)
    acks = []
    try:
        for line in proc.stdout:
            if line.startswith("ACK "):
                acks.append(int(line.split()[1]))
            if len(acks) >= ACKS_BEFORE_KILL:
                break
        os.kill(proc.pid, signal.SIGKILL)
        proc.wait()
        proc.stdout.close()
        assert len(acks) >= ACKS_BEFORE_KILL, f"child died early: {acks}"
        last_ack = acks[-1]

        mgr = PersistenceManager(data_dir, fsync="always")
        store = mgr.recover()
        recovered = store.revision
        info = mgr.recovery_info

        # 1. revision continuity: nothing acked may be lost (the kill
        # can land mid-write, so the WAL may hold MORE than was acked)
        assert recovered >= last_ack, \
            f"lost acked writes: recovered {recovered} < acked {last_ack}"

        # 2. parity vs an uninterrupted host-oracle replay of the same
        # prefix (bootstrap commits revision 1; batch i commits i + 1)
        oracle = TupleStore()
        oracle.bulk_load_text(BOOTSTRAP)
        for i in range(1, recovered):
            oracle.write(stream_batch(i))
        assert oracle.revision == recovered
        got = sorted(r.rel_string() for r in store.read(None))
        want = sorted(r.rel_string() for r in oracle.read(None))
        assert got == want, (
            f"read-set divergence at revision {recovered}: "
            f"{len(got)} vs {len(want)} tuples; first diff: "
            f"{next(iter(set(got) ^ set(want)))}")

        # 1b. the recovered store keeps counting where it left off
        mgr.attach(store)
        assert store.write(stream_batch(recovered)) == recovered + 1
        mgr.close()
        print(f"crash-recovery smoke: OK (acked {last_ack}, recovered "
              f"revision {recovered}, {len(got)} tuples, checkpoint rev "
              f"{info['checkpoint_revision']}, "
              f"{info['replayed_records']} WAL records replayed, "
              f"{info['torn_records']} torn)")
        return 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
        shutil.rmtree(data_dir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
